"""Cancellation (soft + force) + the learned-runtime backlog signal, live.

One process hosts the whole stack (store thread + gateway thread + a
tpu-push dispatcher thread with the runtime estimator on), a saturated
1-process push worker keeps a slow task RUNNING, and the script then:

1. cancels tasks stuck QUEUED behind it — handle.cancel() returns True,
   their records go terminal CANCELLED, result() raises
   TaskCancelledError, and the dispatcher never runs them;
2. shows that cancelling the RUNNING blocker is refused (False) — a
   cancel never yanks a worker;
3. FORCE-cancels a RUNNING task: the worker interrupts it mid-run the
   way a `timeout` hint would, the slot frees in place, and the record
   converges to CANCELLED in about a second;
4. reads the dispatcher's /stats-style backlog estimate
   (``backlog_est_s``): after a few completions teach the estimator this
   workload's runtime, the pending queue is priced in SECONDS — the same
   signal `tpu-faas-deploy --stats-url ... --drain-target N` uses to size
   scale-up jumps.

Run:  python examples/cancel_and_backlog.py
"""

try:
    import _bootstrap  # noqa: F401  (repo-root path shim, script mode)
except ModuleNotFoundError:
    pass  # module mode (python -m examples.x): cwd already on sys.path

# This demo exercises the PROTOCOL (cancel + backlog pricing), not kernel
# speed: pin the scheduler to CPU so a dev box with a remote/tunneled
# accelerator isn't stalled by transport. On a production TPU host delete
# these two lines. (Env-var JAX_PLATFORMS can be rewritten by platform
# plugins; the config update after import is authoritative — see
# tests/conftest.py.)
import jax

jax.config.update("jax_platforms", "cpu")

import threading
import time

from tpu_faas.client import FaaSClient, TaskCancelledError
from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store.launch import make_store, start_store_thread
from tpu_faas.workloads import sleep_task


def main() -> None:
    store = start_store_thread()
    gw = start_gateway_thread(make_store(store.url))
    disp = TpuPushDispatcher(
        ip="127.0.0.1", port=0, max_workers=16, max_pending=128,
        max_inflight=128, tick_period=0.02, store=make_store(store.url),
    )
    threading.Thread(target=disp.start, daemon=True).start()

    import os
    import subprocess
    import sys

    from tpu_faas.bench.harness import cpu_worker_env

    # cpu_worker_env is the shared child-env recipe (repo on PYTHONPATH for
    # script mode, JAX pinned to CPU like the parent). The spawn itself
    # stays inline with INHERITED stdio — unlike the bench harness's
    # spawner, a demo must show the worker's own traceback if it dies
    worker = subprocess.Popen(
        [
            sys.executable, "-m", "tpu_faas.worker.push_worker",
            "1", f"tcp://127.0.0.1:{disp.port}", "--hb",
        ],
        env=cpu_worker_env(),
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    client = FaaSClient(gw.url)
    try:
        fid = client.register(sleep_task)

        # teach the estimator what this function costs (~0.3 s each)
        for h in [client.submit(fid, 0.3) for _ in range(4)]:
            h.result(timeout=60.0)
        print("estimator taught: 4 observations of a ~0.3 s function")

        # saturate the single slot, then queue work behind it
        blocker = client.submit(fid, 4.0)
        deadline = time.time() + 60
        while blocker.status() == "QUEUED":
            if worker.poll() is not None:
                raise RuntimeError("worker process died during startup")
            if time.time() > deadline:
                raise RuntimeError("blocker never started")
            time.sleep(0.05)
        assert blocker.status() == "RUNNING", blocker.status()
        queued = [client.submit(fid, 0.3) for _ in range(8)]
        time.sleep(0.5)  # let the dispatcher drain the announces

        stats = disp.stats()
        print(
            f"backlog: {stats['pending']} tasks pending ~= "
            f"{stats['backlog_est_s']} s of learned work "
            f"(the autoscaler's --drain-target signal)"
        )

        # cancel half the queue; the blocker itself refuses
        for h in queued[:4]:
            assert h.cancel() is True
        assert blocker.cancel() is False
        print("cancelled 4 queued tasks; RUNNING blocker refused (409)")

        survivors = [h.result(timeout=60.0) for h in queued[4:]]
        print(f"surviving queued tasks completed: {survivors}")
        for h in queued[:4]:
            assert h.status() == "CANCELLED"
            try:
                h.result(timeout=2.0)
            except TaskCancelledError:
                pass  # the advertised behavior
            else:
                raise AssertionError("result() should raise for a cancel")
        print(
            f"cancelled tasks stayed CANCELLED; dispatcher dropped "
            f"{disp.stats()['cancelled_dropped']} before dispatch"
        )
        print(f"blocker finished untouched: {blocker.result(timeout=60.0)}")

        # FORCE cancel: a RUNNING task is interrupted mid-run — the pool
        # signals the child like a `timeout` would, the slot frees in
        # place, and the record converges to CANCELLED in ~a second
        # instead of the task's natural 60
        runaway = client.submit(fid, 60.0)
        while runaway.status() != "RUNNING":
            time.sleep(0.05)
        t0 = time.time()
        runaway.cancel(force=True)
        try:
            runaway.result(timeout=30.0)
        except TaskCancelledError:
            print(
                f"force-cancel interrupted a 60 s task in "
                f"{time.time() - t0:.1f} s; status "
                f"{runaway.status()}"
            )
    finally:
        worker.kill()
        worker.wait()
        disp.stop()
        gw.stop()
        store.stop()


if __name__ == "__main__":
    main()
