"""Single-process quickstart: the whole stack in one Python process.

Store server (thread) + REST gateway (thread) + local dispatcher (thread),
then the client SDK registering and invoking functions over real HTTP.
This is the smallest end-to-end tpu-faas program; for a real deployment the
three services run as separate processes (see examples/push_cluster.sh).

Run:  python examples/quickstart.py
"""

try:
    import _bootstrap  # noqa: F401  (repo-root path shim, script mode)
except ModuleNotFoundError:
    pass  # module mode (python -m examples.x): cwd already on sys.path

import threading

from tpu_faas.client import FaaSClient, TaskFailedError
from tpu_faas.dispatch.local import LocalDispatcher
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store.launch import make_store, start_store_thread


def fib(n: int) -> int:
    a, b = 0, 1
    for _ in range(n):
        a, b = b, a + b
    return a


def main() -> None:
    store = start_store_thread()
    gateway = start_gateway_thread(make_store(store.url))
    dispatcher = LocalDispatcher(num_workers=4, store=make_store(store.url))
    threading.Thread(target=dispatcher.start, daemon=True).start()

    client = FaaSClient(gateway.url)

    # one-shot: register + submit + wait
    print("fib(30) =", client.run(fib, 30))

    # explicit handles: submit many, collect later
    fid = client.register(fib)
    handles = [client.submit(fid, n) for n in range(10, 20)]
    print("batch   =", [h.result() for h in handles])

    # or Pool.map-style, in input order
    print("map     =", client.map(fib, range(20, 26)))

    # failures come back as exceptions, not hung polls
    try:
        client.run(lambda: 1 / 0)
    except TaskFailedError as e:
        print("failure =", repr(e.cause))

    # scheduling hints: priority (admission order under overload), cost
    # (task<->worker pairing), timeout (execution budget — a runaway task
    # FAILs with TaskTimeout instead of eating a process slot forever)
    def stall(seconds):
        import time
        time.sleep(seconds)
        return "finished"

    sid = client.register(stall)
    print("hinted  =", client.submit_with(sid, args=(0.01,), priority=5).result())
    try:
        client.submit_with(sid, args=(60,), timeout=0.5).result()
    except TaskFailedError as e:
        print("timeout =", repr(e.cause))

    dispatcher.stop()
    gateway.stop()
    store.stop()


if __name__ == "__main__":
    main()
