#!/bin/sh
# A real multi-process push-mode cluster on one machine: native C++ store,
# REST gateway, TPU-scheduled push dispatcher, and two 4-process worker
# nodes. The same commands spread across machines by changing the URLs.
#
# Run from the repo root:  sh examples/push_cluster.sh
set -e

STORE=""; GW=""; DISP=""; W1=""; W2=""
cleanup() { kill $W1 $W2 $DISP $GW $STORE 2>/dev/null || true; }
trap cleanup EXIT  # a failing step must not orphan the background services

make -C native >/dev/null
mkdir -p /tmp/tpu-faas-demo

native/build/tpu-faas-store --port 6380 --snapshot /tmp/tpu-faas-demo/store.snap &
STORE=$!
sleep 1

python -m tpu_faas.gateway --port 8000 --store resp://127.0.0.1:6380 &
GW=$!
python -m tpu_faas.dispatch -m tpu-push -p 5555 --store resp://127.0.0.1:6380 &
DISP=$!
sleep 2

python -m tpu_faas.worker.push_worker 4 tcp://127.0.0.1:5555 --hb &
W1=$!
python -m tpu_faas.worker.push_worker 4 tcp://127.0.0.1:5555 --hb &
W2=$!
sleep 2

python - <<'PY'
from tpu_faas.client import FaaSClient

client = FaaSClient("http://127.0.0.1:8000")
fid = client.register(lambda n: sum(i * i for i in range(n)))
handles = [client.submit(fid, 10_000 + i) for i in range(32)]
print("32 tasks across 2 workers:", [h.result(timeout=120) for h in handles][:4], "...")
PY

echo "done"
