"""Path shim shared by the example scripts: running one directly from a
source checkout puts `examples/` (this directory) on sys.path, not the repo
root, so `tpu_faas` only resolves if the package is installed. Importing
this module from an example adds the repo root as a fallback."""

import os
import sys

try:  # installed package, or repo root already on the path
    import tpu_faas  # noqa: F401
except ModuleNotFoundError:  # source checkout without install
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
