"""Mixed-fleet migration demo: REFERENCE workers serving the tpu-faas stack.

docs/MIGRATION.md step 2, runnable: our store + gateway + push dispatcher,
with one of the reference's OWN push workers (unmodified, from a reference
checkout) executing beside one of ours. The reference worker needs only
dill + zmq; its missing protocol extensions (``elapsed``, ``token``)
degrade gracefully, and work flows across both.

Run:  python examples/migrate_from_reference.py [path-to-reference-checkout]
      (default /root/reference; exits politely when no checkout exists)
"""

try:
    import _bootstrap  # noqa: F401  (repo-root path shim, script mode)
except ModuleNotFoundError:
    pass

import os
import signal
import subprocess
import sys
import threading
import time

from tpu_faas.client import FaaSClient
from tpu_faas.dispatch.push import PushDispatcher
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store.launch import make_store, start_store_thread

REFERENCE_DIR = sys.argv[1] if len(sys.argv) > 1 else "/root/reference"


def main() -> None:
    if not os.path.isfile(os.path.join(REFERENCE_DIR, "push_worker.py")):
        print(
            f"no reference checkout at {REFERENCE_DIR} "
            "(pass its path as argv[1]); nothing to demo"
        )
        return

    store = start_store_thread()
    gw = start_gateway_thread(make_store(store.url))
    disp = PushDispatcher(
        ip="127.0.0.1", port=0, store=make_store(store.url), heartbeat=True
    )
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"

    # plain-CPU worker env: strips sitecustomize dirs that import jax (and
    # possibly touch an accelerator) into every spawned interpreter — a
    # worker process needs none of that, and on dev boxes the import can
    # stall the whole pool (see cpu_worker_env's docstring)
    from tpu_faas.bench.harness import cpu_worker_env

    env = cpu_worker_env()
    reference_worker = subprocess.Popen(
        [sys.executable, "push_worker.py", "2", url, "--hb"],
        cwd=REFERENCE_DIR,
        env=env,
        start_new_session=True,
    )
    our_worker = subprocess.Popen(
        [sys.executable, "-m", "tpu_faas.worker.push_worker", "2", url, "--hb"],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env,
        start_new_session=True,
    )
    print(f"mixed fleet on {url}: reference worker + tpu-faas worker")

    client = FaaSClient(gw.url)
    try:
        fid = client.register(lambda x: x * x, name="square")
        t0 = time.time()
        handles = [client.submit(fid, i) for i in range(20)]
        results = [h.result(timeout=60.0) for h in handles]
        assert results == [i * i for i in range(20)]
        print(
            f"20 tasks completed across the mixed fleet "
            f"in {time.time() - t0:.2f}s — results verified"
        )
        print(
            "the reference worker never sent an `elapsed` or `token` "
            "field; the dispatcher served it regardless"
        )
    finally:
        for p in (reference_worker, our_worker):
            if p.poll() is None:
                # kill the GROUP: each worker owns multiprocessing pool
                # children that a leader-only SIGKILL would orphan to
                # pid 1 (the start_new_session above exists for this)
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    p.kill()
                p.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store.stop()


if __name__ == "__main__":
    main()
