#!/usr/bin/env bash
# One dispatcher fleet across TWO OS processes sharing a global device mesh
# (the --multihost mode; parallel/multihost_tick.py). Here the "pod" is
# simulated on CPUs (--cpu-pod-devices 4 per process, gloo collectives) so
# the demo runs on any dev box; on Cloud TPU pod slices drop the
# --coordinator/--process-id/--num-processes/--cpu-pod-devices flags — the
# runtime auto-discovers them — and start one process per host.
#
# Process 0 (the lead) serves the real stack; process 1 contributes its
# devices and follows the tick collectives. SIGTERM to the lead releases
# the follower via the stop broadcast.
#
# Add --resident to BOTH processes for the unified fast path: the per-tick
# broadcast becomes the resident delta packet (O(churn) DCN bytes) and the
# scheduler state shards over the global mesh (parallel/multihost_resident).
set -euo pipefail
cd "$(dirname "$0")/.."

PIDS=()
cleanup() {
    # kill everything on ANY exit: a follower left behind blocks forever
    # inside a collective and the ports stay held, breaking re-runs
    kill -TERM "${PIDS[@]}" 2>/dev/null || true
    sleep 1
    kill -KILL "${PIDS[@]}" 2>/dev/null || true
}
trap cleanup EXIT

python -m tpu_faas.store.server --port 6380 &
STORE=$!; PIDS+=("$STORE")
sleep 1
python -m tpu_faas.gateway --port 8000 --store resp://127.0.0.1:6380 &
GW=$!; PIDS+=("$GW")

COMMON=(-m tpu-push --multihost --coordinator 127.0.0.1:7733
        --num-processes 2 --cpu-pod-devices 4
        --max-pending 64 --max-fleet 16 --tick-period 0.05
        -p 5555 --store resp://127.0.0.1:6380)

python -m tpu_faas.dispatch "${COMMON[@]}" --process-id 1 &
FOLLOWER=$!; PIDS+=("$FOLLOWER")
python -m tpu_faas.dispatch "${COMMON[@]}" --process-id 0 &
LEAD=$!; PIDS+=("$LEAD")
sleep 8

python -m tpu_faas.worker.push_worker 4 tcp://127.0.0.1:5555 --hb &
W1=$!; PIDS+=("$W1")
sleep 2

python - <<'PY'
from tpu_faas.client import FaaSClient

client = FaaSClient("http://127.0.0.1:8000")
fid = client.register(lambda n: n * n)
handles = [client.submit(fid, i) for i in range(16)]
print("16 tasks over the 2-process global mesh:",
      [h.result(timeout=120) for h in handles][:5], "...")
PY

kill -TERM "$LEAD"          # stop broadcast releases the follower
wait "$LEAD" "$FOLLOWER" || true
echo "done"                 # trap cleans up the rest
