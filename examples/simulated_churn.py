"""Scheduler under churn, at a scale sockets can't reach on one box.

A simulated 1,024-worker fleet executes 10,000 sized tasks while 5% of
workers fail (taking their in-flight tasks with them) and rejoin every tick.
The object under test is the production scheduler state — the same fused
device tick the TpuPushDispatcher runs — so `lost == 0` demonstrates the
on-device failure detection + work-redistribution actually works.

Run:  python examples/simulated_churn.py
"""

try:
    import _bootstrap  # noqa: F401  (repo-root path shim, script mode)
except ModuleNotFoundError:
    pass  # module mode (python -m examples.x): cwd already on sys.path

import numpy as np

from tpu_faas.sim import SimFleet


def main() -> None:
    rng = np.random.default_rng(7)
    fleet = SimFleet(
        n_workers=1_024,
        max_pending=4_096,
        rng=rng,
        hetero=True,
        time_to_expire=2.0,
    )
    sizes = rng.uniform(0.5, 4.0, 10_000).astype(np.float32)
    res = fleet.run(sizes, dt=1.0, churn=0.05, max_ticks=2_000)
    print(
        f"completed {res.completed}/{len(sizes)}  lost {res.lost}  "
        f"ticks {res.ticks}  sim-makespan {res.makespan:.0f}  "
        f"median tick {res.median_tick_ms:.2f} ms"
    )
    assert res.lost == 0, "redistribution must not lose tasks"


if __name__ == "__main__":
    main()
