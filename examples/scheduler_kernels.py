"""Driving the placement kernels directly (no sockets, no store).

Builds one synthetic placement problem — heterogeneous fleet, log-normal
task sizes — and solves it with all three device kernels, comparing their
makespan against the LP lower bound and the reference-style host greedy walk.

Run:  python examples/scheduler_kernels.py
(CPU works; on a TPU host the kernels run on device.)
"""

try:
    import _bootstrap  # noqa: F401  (repo-root path shim, script mode)
except ModuleNotFoundError:
    pass  # module mode (python -m examples.x): cwd already on sys.path

import numpy as np

from tpu_faas.sched.auction import auction_placement
from tpu_faas.sched.greedy import (
    host_greedy_reference,
    makespan,
    rank_match_placement,
)
from tpu_faas.sched.oracle import makespan_lower_bound
from tpu_faas.sched.problem import PlacementProblem
from tpu_faas.sched.sinkhorn import sinkhorn_placement

MAX_SLOTS = 4


def main() -> None:
    rng = np.random.default_rng(0)
    n_tasks, n_workers = 2_000, 256
    sizes = rng.lognormal(0.0, 1.0, n_tasks).astype(np.float32)
    speeds = rng.uniform(0.5, 4.0, n_workers).astype(np.float32)
    free = rng.integers(1, MAX_SLOTS + 1, n_workers).astype(np.int32)
    live = np.ones(n_workers, dtype=bool)

    p = PlacementProblem.build(sizes, speeds, free, live, T=2_048, W=256)

    placements = {
        "rank-match": np.asarray(
            rank_match_placement(
                p.task_size, p.task_valid, p.worker_speed, p.worker_free,
                p.worker_live, max_slots=MAX_SLOTS,
            )
        )[:n_tasks],
        "auction": np.asarray(
            auction_placement(
                p.task_size, p.task_valid, p.worker_speed, p.worker_free,
                p.worker_live, max_slots=MAX_SLOTS,
            ).assignment
        )[:n_tasks],
        "sinkhorn": np.asarray(
            sinkhorn_placement(
                p.task_size, p.task_valid, p.worker_speed, p.worker_free,
                p.worker_live, tau=0.05, n_iters=60, max_slots=MAX_SLOTS,
            ).assignment
        )[:n_tasks],
        "host-greedy": np.asarray(
            host_greedy_reference(
                sizes, speeds, np.minimum(free, MAX_SLOTS), live
            )
        ),
    }

    for name, assign in placements.items():
        placed = assign >= 0
        ms = makespan(assign, sizes, speeds, MAX_SLOTS)
        lb = makespan_lower_bound(sizes[placed], speeds, free, live, MAX_SLOTS)
        print(
            f"{name:>11}: placed {placed.sum():4d}/{n_tasks}  "
            f"makespan {ms:8.2f}  vs LP bound x{ms / lb:.3f}"
        )


if __name__ == "__main__":
    main()
