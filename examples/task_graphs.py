"""Task graphs: a map-reduce diamond and failure propagation, end to end.

Whole stack in one process (store + gateway + local dispatcher threads),
then two graphs through ``client.graph()``:

1. a fan-out/fan-in diamond — shards processed in parallel AFTER the
   producer finishes, merged by a sink that runs only when every shard is
   done (the store's promotion plane flips each WAITING node dispatchable
   the instant its last parent completes);
2. a pipeline with a failing stage — the failure poisons every dependent
   node WITHOUT running it (zero worker time wasted), and ``result()``
   raises ``TaskDependencyError`` naming the parent that doomed it.

Run:  python examples/task_graphs.py
"""

try:
    import _bootstrap  # noqa: F401  (repo-root path shim, script mode)
except ModuleNotFoundError:
    pass  # module mode (python -m examples.x): cwd already on sys.path

import threading

from tpu_faas.client import FaaSClient, TaskDependencyError
from tpu_faas.dispatch.local import LocalDispatcher
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store.launch import make_store, start_store_thread


def produce(n: int) -> list[int]:
    return list(range(n))


def square_sum(xs: list[int], lo: int, hi: int) -> int:
    return sum(x * x for x in xs[lo:hi])


def add(a: int, b: int) -> int:
    return a + b


def explode(msg: str) -> None:
    raise ValueError(msg)


def main() -> None:
    store = start_store_thread()
    gateway = start_gateway_thread(make_store(store.url))
    dispatcher = LocalDispatcher(num_workers=4, store=make_store(store.url))
    disp_thread = threading.Thread(target=dispatcher.start, daemon=True)
    disp_thread.start()
    client = FaaSClient(gateway.url)

    # -- 1. fan-out/fan-in diamond ----------------------------------------
    # NOTE: graph nodes don't pass values to each other (the payload plane
    # is still explicit-arguments); the DAG orders EXECUTION — each shard
    # here recomputes its input cheaply, a real pipeline would pass keys
    # into a shared datastore.
    g = client.graph()
    producer = g.call(produce, 1000)
    shards = [
        g.call(square_sum, list(range(1000)), lo, lo + 250, after=[producer])
        for lo in range(0, 1000, 250)
    ]
    # fan-in: runs only after every shard COMPLETED
    total = g.call(square_sum, list(range(1000)), 0, 1000, after=shards)
    g.submit()
    print("diamond sink:", total.result(timeout=60.0))
    print("   (statuses:", [s.status() for s in shards], ")")

    # -- 2. failure propagation -------------------------------------------
    g2 = client.graph()
    ok = g2.call(add, 1, 2)
    bad = g2.call(explode, "stage two blew up", after=[ok])
    doomed = g2.call(add, 3, 4, after=[bad])
    also_doomed = g2.call(add, 5, 6, after=[doomed])
    g2.submit()
    print("stage one:", ok.result(timeout=60.0))
    for node, name in ((doomed, "doomed"), (also_doomed, "also_doomed")):
        try:
            node.result(timeout=30.0)
        except TaskDependencyError as exc:
            print(
                f"{name}: never ran — poisoned by parent "
                f"{exc.parent_id[:8]}... ({exc.cause!r})"
            )

    dispatcher.stop()
    disp_thread.join(timeout=10)  # let the pool tear down before exit
    gateway.stop()
    store.stop()


if __name__ == "__main__":
    main()
