"""Property-based tests (hypothesis) for the invariants that seeded tests
can only spot-check: serialization totality, RESP wire framing, placement
feasibility under arbitrary fleet states, and the race monitor's soundness
on legal histories (SURVEY §4: the reference has no property layer at all).

JIT discipline: placement properties use ONE fixed padded shape and vary
only array contents, so the kernel compiles once per process, not once per
hypothesis example.
"""

from __future__ import annotations

import numpy as np
import pytest

pytest.importorskip("hypothesis")

from hypothesis import HealthCheck, given, settings
from hypothesis import strategies as st

from tpu_faas.core.executor import execute_fn, pack_params
from tpu_faas.core.serialize import deserialize, serialize
from tpu_faas.store import resp
from tpu_faas.store.memory import MemoryStore
from tpu_faas.store.racecheck import RaceMonitor

SET = settings(
    max_examples=50,
    deadline=None,
    suppress_health_check=[HealthCheck.too_slow],
)

# -- serialization: total on picklable values, exact roundtrip ---------------

VALUES = st.recursive(
    st.none()
    | st.booleans()
    | st.integers(-(2**63), 2**63)
    | st.floats(allow_nan=False)
    | st.text(max_size=50)
    | st.binary(max_size=50),
    lambda children: st.lists(children, max_size=4)
    | st.tuples(children, children)
    | st.dictionaries(st.text(max_size=8), children, max_size=4),
    max_leaves=10,
)


@SET
@given(VALUES)
def test_serialize_roundtrip(value):
    payload = serialize(value)
    assert isinstance(payload, str)
    assert deserialize(payload) == value


@SET
@given(st.lists(st.integers(-1000, 1000), max_size=20))
def test_executor_roundtrip_through_wire_format(xs):
    tid, status, result = execute_fn("t", serialize(sorted), pack_params(xs))[:3]
    assert (tid, status) == ("t", "COMPLETED")
    assert deserialize(result) == sorted(xs)


# -- RESP framing: any strings survive encode -> parse -----------------------

WIRE_TEXT = st.text(max_size=64)  # includes \r\n, unicode, empty


@SET
@given(st.lists(WIRE_TEXT, min_size=1, max_size=6))
def test_resp_command_framing_roundtrip(parts):
    parser = resp.RespParser()
    parser.feed(resp.encode_command(*parts))
    got = parser.pop()
    assert got == parts
    assert parser.pop() is resp.NEED_MORE


@SET
@given(
    st.dictionaries(WIRE_TEXT, WIRE_TEXT, min_size=0, max_size=6),
    st.dictionaries(WIRE_TEXT, WIRE_TEXT, min_size=0, max_size=3),
)
def test_memory_store_hash_semantics(first, second):
    """HSET merge + HGETALL echo for arbitrary field names/values."""
    store = MemoryStore()
    if first:
        store.hset("k", first)
    if second:
        store.hset("k", second)
    assert store.hgetall("k") == {**first, **second}
    store.close()


# -- placement feasibility under arbitrary fleet state -----------------------

T_PAD, W_PAD, MAX_SLOTS = 64, 16, 4

FLEETS = st.tuples(
    st.lists(
        st.floats(0.01, 100.0, allow_nan=False), min_size=T_PAD, max_size=T_PAD
    ),
    st.lists(st.booleans(), min_size=T_PAD, max_size=T_PAD),
    st.lists(
        st.floats(0.1, 10.0, allow_nan=False), min_size=W_PAD, max_size=W_PAD
    ),
    st.lists(st.integers(0, MAX_SLOTS + 2), min_size=W_PAD, max_size=W_PAD),
    st.lists(st.booleans(), min_size=W_PAD, max_size=W_PAD),
)


@SET
@given(FLEETS)
def test_rank_match_feasible_on_arbitrary_fleets(fleet):
    from tpu_faas.sched.greedy import rank_match_placement

    sizes, valid, speeds, free, live = (np.asarray(x) for x in fleet)
    a = np.asarray(
        rank_match_placement(
            sizes.astype(np.float32),
            valid,
            speeds.astype(np.float32),
            free.astype(np.int32),
            live,
            max_slots=MAX_SLOTS,
        )
    )
    # invalid tasks never placed
    assert (a[~valid] == -1).all()
    # placements target live workers only
    placed_workers = a[a >= 0]
    assert live[placed_workers].all() if placed_workers.size else True
    # per-worker load never exceeds its effective capacity
    cap = np.where(live, np.minimum(free, MAX_SLOTS), 0)
    load = np.bincount(placed_workers, minlength=W_PAD)
    assert (load <= cap).all()
    # work-conserving: placed count == min(valid tasks, total capacity)
    assert (a >= 0).sum() == min(int(valid.sum()), int(cap.sum()))


# -- race monitor: legal histories are clean ---------------------------------


@SET
@given(
    st.lists(
        st.tuples(
            st.integers(0, 7),  # task index
            st.sampled_from(["advance", "redispatch", "cancel"]),
        ),
        max_size=40,
    )
)
def test_race_monitor_accepts_all_legal_histories(script):
    """Drive tasks through arbitrary interleavings of legal transitions
    (QUEUED -> RUNNING -> terminal, declared re-dispatches, queued-only
    cancels): the monitor must stay silent — no false positives."""
    m = RaceMonitor()
    stage: dict[str, int] = {}
    for idx, op in script:
        tid = f"t{idx}"
        s = stage.get(tid, 0)
        if op == "redispatch":
            if s == 2:  # RUNNING: a declared re-mark is legal
                m.expect_redispatch(tid)
                m.observe("d", "status", tid, {"status": "RUNNING"})
            continue
        if op == "cancel":
            if s == 1:  # QUEUED: queued-only cancel is legal and silent
                m.observe("gw", "status", tid, {"status": "CANCELLED"})
                stage[tid] = 3
            continue
        if s == 0:
            m.observe("gw", "create", tid, {"status": "QUEUED", "result": "None"})
            stage[tid] = 1
        elif s == 1:
            m.observe("d", "status", tid, {"status": "RUNNING"})
            stage[tid] = 2
        elif s == 2:
            m.observe("d", "finish", tid, {"status": "COMPLETED", "result": "r"})
            stage[tid] = 3
    m.assert_clean()


def test_first_k_indices_matches_numpy_reference():
    """sched.resident._first_k_indices == np.flatnonzero(mask)[:K] (with
    -1 padding), across random masks, K sizes, and edge cases."""
    import jax.numpy as jnp

    from tpu_faas.sched.resident import _first_k_indices

    rng = np.random.default_rng(41)
    cases = [
        (np.zeros(16, bool), 4),
        (np.ones(16, bool), 4),
        (np.ones(16, bool), 16),
        (np.zeros(1, bool), 1),
    ] + [
        (rng.random(int(rng.integers(1, 300))) < p, int(rng.integers(1, 64)))
        for p in (0.01, 0.2, 0.5, 0.9)
        for _ in range(4)
    ]
    for mask, K in cases:
        K = min(K, len(mask))
        got = np.asarray(_first_k_indices(jnp.asarray(mask), K))
        want = np.full(K, -1, dtype=np.int32)
        idx = np.flatnonzero(mask)[:K]
        want[: len(idx)] = idx
        np.testing.assert_array_equal(got, want, err_msg=f"K={K} n={len(mask)}")


# -- consistent-hash ring (store/sharding.py) --------------------------------

RING_KEYS = st.lists(
    st.text(
        alphabet=st.characters(min_codepoint=33, max_codepoint=126),
        min_size=1,
        max_size=40,
    ),
    min_size=1,
    max_size=200,
    unique=True,
)


@SET
@given(RING_KEYS, st.integers(1, 8))
def test_ring_routing_is_deterministic_and_in_range(keys, n_shards):
    from tpu_faas.store.sharding import HashRing

    a, b = HashRing(n_shards), HashRing(n_shards)
    for key in keys:
        shard = a.shard_of(key)
        assert 0 <= shard < n_shards
        # a fresh ring with the same membership places every key
        # identically — the property every fleet process depends on
        assert b.shard_of(key) == shard


@SET
@given(st.integers(2, 8))
def test_ring_add_remove_moves_bounded_fraction(n_shards):
    """Consistent hashing's defining property: growing (or shrinking)
    the ring by one shard re-homes ~1/(N+1) of keys, never the ~N/(N+1)
    a modulo partition would. Bounded at 2.5x the ideal fraction to
    absorb virtual-node variance at small N."""
    from tpu_faas.store.sharding import HashRing

    keys = [f"task-{i}" for i in range(3000)]
    small, big = HashRing(n_shards), HashRing(n_shards + 1)
    moved = sum(
        1 for k in keys if small.shard_of(k) != big.shard_of(k)
    )
    ideal = 1.0 / (n_shards + 1)
    assert moved / len(keys) <= 2.5 * ideal
    # and the keys that DID move all landed on the new shard — a grow
    # must never shuffle keys between surviving shards
    for k in keys:
        if small.shard_of(k) != big.shard_of(k):
            assert big.shard_of(k) == n_shards
