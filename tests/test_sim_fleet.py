"""Simulated-fleet scheduler tests: scaled-down versions of BASELINE
configs 3-5 (full-size versions live in the benchmark harness)."""

import numpy as np
import pytest

from tpu_faas.sched.oracle import makespan_lower_bound
from tpu_faas.sim import SimFleet


def test_sim_drains_all_tasks_uniform():
    """Config-3 shape (scaled): uniform cost, homogeneous fleet."""
    rng = np.random.default_rng(0)
    fleet = SimFleet(n_workers=64, max_pending=512, rng=rng, hetero=False)
    sizes = np.ones(1000, dtype=np.float32)
    res = fleet.run(sizes, dt=0.5)
    assert res.completed == 1000
    assert res.lost == 0


def test_sim_heterogeneous_makespan_near_bound():
    """Config-4 shape (scaled): heterogeneous speeds; end-to-end makespan
    within a modest factor of the offline bound."""
    rng = np.random.default_rng(1)
    fleet = SimFleet(n_workers=32, max_pending=1024, rng=rng, hetero=True)
    sizes = rng.uniform(0.5, 5.0, 600).astype(np.float32)
    res = fleet.run(sizes, dt=0.25)
    assert res.completed == 600
    lb = makespan_lower_bound(
        sizes,
        fleet.speeds,
        np.full(32, 4, dtype=np.int32),
        np.ones(32, dtype=bool),
        max_slots=8,
    )
    # dt quantization + waves make exact LP parity impossible; the bound
    # check guards against gross scheduling regressions
    assert res.makespan <= lb * 2.0 + 2.0


@pytest.mark.parametrize("churn", [0.01, 0.05])
def test_sim_churn_no_lost_tasks(churn):
    """Config-5 shape (scaled): workers crash and rejoin every tick; the
    device-computed redistribution must still complete every task."""
    rng = np.random.default_rng(2)
    fleet = SimFleet(
        n_workers=48,
        max_pending=512,
        rng=rng,
        hetero=True,
        time_to_expire=1.0,  # purge quickly relative to dt
    )
    sizes = rng.uniform(0.5, 3.0, 400).astype(np.float32)
    res = fleet.run(sizes, dt=0.5, churn=churn, max_ticks=4000)
    assert res.lost == 0
    assert res.completed == 400
