"""Per-task execution timeouts: a runaway task FAILs and frees its slot.

No reference analog: in the reference a task that never returns occupies a
pool process forever, silently shrinking the fleet (and its dispatcher-side
poison guard only covers worker DEATH, not worker wedging). The budget is
client-supplied (the ``timeout`` hint), rides the store hash and the TASK
wire message, and is enforced inside the pool child with SIGALRM.
"""

from __future__ import annotations

import threading
import time

from tpu_faas.client import FaaSClient, TaskFailedError
from tpu_faas.core.executor import TaskTimeout, execute_fn, pack_params
from tpu_faas.core.serialize import deserialize, serialize
from tpu_faas.core.task import TaskStatus
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store.launch import make_store, start_store_thread
from tpu_faas.worker.pool import TaskPool
from tpu_faas.workloads import arithmetic, sleep_task
from tests.test_tpu_push_e2e import _make_dispatcher
from tests.test_workers_e2e import _spawn_worker


def test_execute_fn_enforces_budget():
    res = execute_fn(
        "t1", serialize(sleep_task), pack_params(30.0), timeout=0.3
    )
    assert res.status == str(TaskStatus.FAILED)
    exc = deserialize(res.result)
    assert isinstance(exc, TaskTimeout)
    # the itimer is disarmed: nothing fires afterwards
    time.sleep(0.4)


def test_execute_fn_fast_task_unaffected_by_budget():
    res = execute_fn(
        "t2", serialize(arithmetic), pack_params(100), timeout=30.0
    )
    assert res.status == str(TaskStatus.COMPLETED)
    assert deserialize(res.result) == arithmetic(100)
    time.sleep(0.05)  # no stray alarm


def test_pool_slot_freed_after_timeout():
    """The point of the feature: after a task times out, the SAME slot runs
    the next task (without enforcement the pool would be wedged forever)."""
    pool = TaskPool(1)
    pool.warmup()
    try:
        pool.submit("slow", serialize(sleep_task), pack_params(60.0), timeout=0.5)
        deadline = time.monotonic() + 15
        results = []
        while not results and time.monotonic() < deadline:
            results = pool.drain()
            time.sleep(0.02)
        assert results and results[0].status == str(TaskStatus.FAILED)
        assert isinstance(deserialize(results[0].result), TaskTimeout)
        assert pool.free == 1  # slot reclaimed

        pool.submit("ok", serialize(arithmetic), pack_params(50))
        results = []
        deadline = time.monotonic() + 15
        while not results and time.monotonic() < deadline:
            results = pool.drain()
            time.sleep(0.02)
        assert results and results[0].status == str(TaskStatus.COMPLETED)
        assert deserialize(results[0].result) == arithmetic(50)
    finally:
        pool.close()


def test_timeout_hint_end_to_end_push():
    """timeout hint over the full stack: gateway -> store -> tpu-push
    dispatcher -> unmodified push worker -> SIGALRM in the pool child. The
    single-process worker then completes a normal task, proving the slot
    came back."""
    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    disp = _make_dispatcher(store_handle.url)
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    worker = _spawn_worker("push_worker", 1, url, "--hb", "--hb-period", "0.3")
    client = FaaSClient(gw.url)
    try:
        fid = client.register(sleep_task)
        h = client.submit_with(fid, args=(60.0,), timeout=0.5)
        try:
            h.result(timeout=60)
            raise AssertionError("expected TaskFailedError")
        except TaskFailedError as exc:
            assert isinstance(exc.cause, TaskTimeout)
        fid2 = client.register(arithmetic)
        assert client.submit(fid2, 7).result(timeout=60) == arithmetic(7)
    finally:
        worker.kill()
        worker.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def _stubborn(horizon: float = 60.0) -> str:
    """The classic runaway shape: a retry loop that swallows Exceptions."""
    import time as t

    t0 = t.monotonic()
    while t.monotonic() - t0 < horizon:
        try:
            t.sleep(0.02)
        except Exception:
            continue  # an Exception-derived timeout would be eaten here
    return "survived"


def test_timeout_survives_user_catch_all():
    """TaskTimeout derives from BaseException precisely so the ubiquitous
    'except Exception: continue' retry loop cannot swallow the one-shot
    alarm and wedge the slot anyway."""
    res = execute_fn(
        "t-stubborn", serialize(_stubborn), pack_params(60.0), timeout=0.4
    )
    assert res.status == str(TaskStatus.FAILED)
    assert isinstance(deserialize(res.result), TaskTimeout)


def test_absurd_timeout_values_never_escape():
    """never-raises contract under hostile budgets: setitimer overflow
    values are clamped, microscopic budgets whose alarm fires before user
    code starts still produce a clean FAILED."""
    res = execute_fn(
        "t-huge", serialize(arithmetic), pack_params(10), timeout=1e12
    )
    assert res.status == str(TaskStatus.COMPLETED)  # clamp, then run
    res = execute_fn(
        "t-tiny", serialize(sleep_task), pack_params(5.0), timeout=1e-6
    )
    assert res.status == str(TaskStatus.FAILED)
    assert isinstance(deserialize(res.result), TaskTimeout)
