"""Federated control plane (tpu_faas/store/sharding.py): consistent-hash
ring determinism, ShardedStore routing/fan-out/merge semantics, shard-slice
ownership scoping, cross-shard graph promotion, per-shard failover re-arm,
and a gateway + per-shard-dispatcher end-to-end leg."""

from __future__ import annotations

import threading
import time
from collections import Counter

import pytest

from tpu_faas.admission.signal import (
    FLEET_HEALTH_KEY,
    CapacitySnapshot,
    publish_snapshot,
    read_fleet_health,
)
from tpu_faas.core.task import (
    FIELD_CHILDREN,
    FIELD_DEPS,
    FIELD_PENDING_DEPS,
    FIELD_STATUS,
    TaskStatus,
)
from tpu_faas.store.base import (
    CANCEL_ANNOUNCE_PREFIX,
    DISPATCHERS_KEY,
    LEASE_CONF_KEY,
    LIVE_INDEX_KEY,
    RESULTS_CHANNEL,
    TASKS_CHANNEL,
)
from tpu_faas.store.launch import make_store, start_store_thread
from tpu_faas.store.memory import MemoryStore
from tpu_faas.store.sharding import HashRing, ShardedStore


def sharded(n: int = 3, owned=None) -> ShardedStore:
    return ShardedStore(
        [MemoryStore() for _ in range(n)], owned_shards=owned
    )


def other_shard_key(store: ShardedStore, key: str, prefix: str = "k") -> str:
    """A key the ring places on a DIFFERENT shard than ``key``."""
    target = store.shard_of(key)
    for i in range(10_000):
        cand = f"{prefix}-{i}"
        if store.shard_of(cand) != target:
            return cand
    raise AssertionError("ring degenerated to one shard")


# -- ring --------------------------------------------------------------------


def test_ring_is_deterministic_across_instances():
    a, b = HashRing(4), HashRing(4)
    keys = [f"task-{i}" for i in range(500)]
    assert [a.shard_of(k) for k in keys] == [b.shard_of(k) for k in keys]


def test_ring_uses_every_shard_and_stays_roughly_balanced():
    ring = HashRing(4)
    counts = Counter(ring.shard_of(f"t{i}") for i in range(4000))
    assert set(counts) == {0, 1, 2, 3}
    # virtual nodes keep the imbalance bounded (loose bar: no shard may
    # carry more than 2x its fair share or less than a third of it)
    for shard in range(4):
        assert 4000 / 12 < counts[shard] < 4000 / 2


def test_ring_membership_change_moves_bounded_fraction():
    keys = [f"task-{i}" for i in range(4000)]
    before = HashRing(4)
    after = HashRing(5)
    moved = sum(
        1 for k in keys if before.shard_of(k) != after.shard_of(k)
    )
    # consistent hashing: ~1/5 of keys re-home when a 5th shard joins
    # (vs ~4/5 under modulo hashing); generous bound for vnode variance
    assert moved / len(keys) < 0.40


def test_ring_rejects_empty():
    with pytest.raises(ValueError):
        HashRing(0)


# -- routing -----------------------------------------------------------------


def test_single_key_ops_route_to_the_ring_shard():
    s = sharded(3)
    s.hset("t-route", {"a": "1"})
    owner = s.shard_of("t-route")
    for i in range(3):
        raw = s.shard_store(i).hgetall("t-route")
        assert raw == ({"a": "1"} if i == owner else {})
    assert s.hget("t-route", "a") == "1"
    s.hdel("t-route", "a")
    assert s.hget("t-route", "a") is None


def test_live_index_partitions_by_task_id_field():
    s = sharded(3)
    a = "idx-a"
    b = other_shard_key(s, a, "idx")
    s.hset(LIVE_INDEX_KEY, {a: "1", b: "1"})
    assert s.shard_store(s.shard_of(a)).hgetall(LIVE_INDEX_KEY) == {a: "1"}
    assert s.shard_store(s.shard_of(b)).hgetall(LIVE_INDEX_KEY) == {b: "1"}
    assert s.hgetall(LIVE_INDEX_KEY) == {a: "1", b: "1"}
    s.hdel(LIVE_INDEX_KEY, a)
    assert s.hgetall(LIVE_INDEX_KEY) == {b: "1"}


def test_fleet_keys_broadcast_writes_and_merge_reads():
    s = sharded(3)
    s.hset(FLEET_HEALTH_KEY, {"d1": "v1:1:2:3:0.5:100.0"})
    # broadcast: every shard carries the copy (any surviving shard can
    # answer the aggregation)
    for i in range(3):
        assert "d1" in s.shard_store(i).hgetall(FLEET_HEALTH_KEY)
    # merge keeps the FRESHEST copy per field (max trailing stamp)
    s.shard_store(0).hset(FLEET_HEALTH_KEY, {"d1": "v1:9:9:9:0.5:50.0"})
    assert s.hgetall(FLEET_HEALTH_KEY)["d1"].endswith("100.0")
    # lease conf merges the EARLIEST (first publication pins the grace
    # window)
    s.shard_store(0).hset(LEASE_CONF_KEY, {"t:30.0": "200.0"})
    s.shard_store(1).hset(LEASE_CONF_KEY, {"t:30.0": "100.0"})
    assert s.hgetall(LEASE_CONF_KEY)["t:30.0"] == "100.0"
    # broadcast hdel reaches shards the writer never owned
    s.hdel(FLEET_HEALTH_KEY, "d1")
    for i in range(3):
        assert "d1" not in s.shard_store(i).hgetall(FLEET_HEALTH_KEY)


def test_batch_ops_preserve_input_order_across_shards():
    s = sharded(4)
    ids = [f"b-{i}" for i in range(40)]
    assert len({s.shard_of(i) for i in ids}) > 1  # genuinely spread
    s.create_tasks([(i, "F", f"P{i}") for i in ids])
    records = s.hgetall_many(ids)
    assert [r["param_payload"] for r in records] == [f"P{i}" for i in ids]
    statuses = s.hget_many(ids, FIELD_STATUS)
    assert statuses == ["QUEUED"] * len(ids)
    created = s.create_tasks_if_absent([(i, "F", "P") for i in ids])
    assert created == [False] * len(ids)  # all already exist
    counts = s.hincrby_many([(i, "n", 2) for i in ids])
    assert counts == [2] * len(ids)


def test_create_finish_cancel_route_announces_by_task_shard():
    s = sharded(3)
    a = "ann-a"
    b = other_shard_key(s, a, "ann")
    sub_a = s.shard_store(s.shard_of(a)).subscribe(TASKS_CHANNEL)
    sub_all = s.subscribe(TASKS_CHANNEL)
    s.create_task(a, "F", "P")
    s.create_task(b, "F", "P")
    assert sub_a.get_message() == a
    assert sub_a.get_message() is None  # b went to the other shard
    got = {sub_all.get_message(), sub_all.get_message()}
    assert got == {a, b}
    res_sub = s.subscribe(RESULTS_CHANNEL)
    s.finish_task(a, TaskStatus.COMPLETED, "R")
    assert s.get_result(a) == ("COMPLETED", "R")
    assert res_sub.get_message() == a
    # live-index entry dropped on a's own shard
    assert a not in s.shard_store(s.shard_of(a)).hgetall(LIVE_INDEX_KEY)
    # cancel publishes the control message on b's shard bus
    assert s.cancel_task(b) == str(TaskStatus.CANCELLED)
    msgs = []
    while True:
        m = sub_all.get_message()
        if m is None:
            break
        msgs.append(m)
    assert CANCEL_ANNOUNCE_PREFIX + b in msgs
    sub_a.close(), sub_all.close(), res_sub.close()
    s.close()


def test_owned_shards_scope_subscription_index_and_keys():
    mems = [MemoryStore() for _ in range(3)]
    full = ShardedStore(mems)
    a = "own-a"
    b = other_shard_key(full, a, "own")
    owned = ShardedStore(mems, owned_shards=[full.shard_of(a)])
    sub = owned.subscribe(TASKS_CHANNEL)
    full.create_task(a, "F", "P")
    full.create_task(b, "F", "P")
    assert sub.get_message() == a
    assert sub.get_message() is None  # b's shard is not owned
    # rescan surface scopes too: keys + live index
    assert b not in owned.keys()
    assert a in owned.keys()
    assert set(owned.hgetall(LIVE_INDEX_KEY)) == {a}
    # but the unowned shard stays reachable for writes/reads by key
    assert owned.get_status(b) == "QUEUED"
    owned.finish_task(b, TaskStatus.COMPLETED, "R")
    assert full.get_result(b) == ("COMPLETED", "R")
    with pytest.raises(ValueError):
        ShardedStore(mems, owned_shards=[7])
    sub.close()


def test_cross_shard_graph_promotion_and_poison():
    s = sharded(3)
    parent = "gp-parent"
    child = other_shard_key(s, parent, "gp-child")
    grandchild = other_shard_key(s, child, "gp-grand")
    s.create_task(parent, "F", "P")
    for node, deps in ((child, parent), (grandchild, child)):
        s.create_task(
            node,
            "F",
            "P",
            extra_fields={FIELD_DEPS: deps, FIELD_PENDING_DEPS: "1"},
            status=TaskStatus.WAITING,
        )
    s.hset(parent, {FIELD_CHILDREN: child})
    s.hset(child, {FIELD_CHILDREN: grandchild})
    promoted, poisoned = s.complete_dep_many(
        [(parent, str(TaskStatus.COMPLETED))]
    )
    assert (promoted, poisoned) == ([child], [])
    assert s.get_status(child) == "QUEUED"
    # a failed mid-graph parent poisons its transitive frontier across
    # shard boundaries
    promoted, poisoned = s.complete_dep_many(
        [(child, str(TaskStatus.FAILED))]
    )
    assert (promoted, poisoned) == ([], [grandchild])
    assert s.get_status(grandchild) == "FAILED"


def test_fleet_health_aggregation_reads_all_shards():
    mems = [MemoryStore() for _ in range(2)]
    full = ShardedStore(mems)
    # two dispatchers publishing through shard-scoped handles: the
    # broadcast lands their snapshots on their reachable shards; a
    # gateway over the full ring aggregates both exactly once
    now = time.time()
    publish_snapshot(
        ShardedStore(mems, owned_shards=[0]),
        "disp-0",
        CapacitySnapshot(2, 3, 8, 1.5, now),
    )
    publish_snapshot(
        ShardedStore(mems, owned_shards=[1]),
        "disp-1",
        CapacitySnapshot(4, 5, 8, 2.5, now),
    )
    health = read_fleet_health(full, now=now)
    assert health is not None
    assert health.dispatchers == 2
    assert (health.pending, health.inflight) == (6, 8)
    assert health.capacity == 16
    assert abs(health.drain_rate - 4.0) < 1e-9


def test_replay_cursor_handles_cover_the_window_since_priming():
    s = sharded(2)
    handle, entries = s.replay_announces(-1)
    assert entries == []
    ids = [f"rp-{i}" for i in range(8)]
    for tid in ids:
        s.create_task(tid, "F", "P")
    handle2, entries2 = s.replay_announces(handle)
    replayed = [p for c, p in entries2 if c == TASKS_CHANNEL]
    assert sorted(replayed) == sorted(ids)
    # nothing new: the fresh handle covers everything
    _h3, entries3 = s.replay_announces(handle2)
    assert entries3 == []
    # an unknown handle (the dispatcher's post-outage 0 fallback)
    # replays each shard's whole bounded ring
    _h4, entries4 = s.replay_announces(0)
    assert sorted(p for c, p in entries4 if c == TASKS_CHANNEL) == sorted(ids)


def test_owned_replay_scopes_to_owned_shards():
    mems = [MemoryStore() for _ in range(2)]
    full = ShardedStore(mems)
    a = "rpo-a"
    b = other_shard_key(full, a, "rpo")
    owned = ShardedStore(mems, owned_shards=[full.shard_of(a)])
    handle, _ = owned.replay_announces(-1)
    full.create_task(a, "F", "P")
    full.create_task(b, "F", "P")
    _h, entries = owned.replay_announces(handle)
    assert [p for _c, p in entries] == [a]


def test_make_store_sharded_urls():
    s = make_store("memory://fresh;fresh")
    assert isinstance(s, ShardedStore) and s.shard_count == 2
    o = make_store("memory://fresh;fresh;fresh", owned_shards=[1, 2])
    assert o.owned_shards == [1, 2]
    with pytest.raises(ValueError):
        make_store("memory://", owned_shards=[0])
    with pytest.raises(ValueError):
        make_store("memory://fresh;fresh", owned_shards=[5])
    with pytest.raises(ValueError):
        make_store("resp://;")


def test_round_trip_and_failover_accounting_sums_shards():
    s = sharded(2)
    assert s.n_round_trips == 0  # memory shards never pay wire trips
    assert s.failover_generation == 0
    assert s.shard_failover_generations() == [0, 0]
    info = s.info()
    assert info["role"] == "primary" and info["shards"] == "2"


# -- per-shard failover over real RESP servers -------------------------------


def _wait_until(cond, timeout=10.0, step=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(step)
    return False


def test_one_shard_failover_bumps_generation_and_rearms():
    """Shard 0 is a primary+replica pair; killing its primary and
    promoting the replica must (1) settle shard 0's client on the
    promoted endpoint, (2) bump the SHARDED handle's generation, and
    (3) let a dispatcher-style replay re-discover shard 0's announces —
    while shard 1 never notices."""
    from tpu_faas.store.client import RespStore

    p0 = start_store_thread()
    r0 = start_store_thread(replica_of=("127.0.0.1", p0.port))
    s1 = start_store_thread()
    url = (
        f"resp://127.0.0.1:{p0.port},127.0.0.1:{r0.port}"
        f";127.0.0.1:{s1.port}"
    )
    store = make_store(url)
    rc = RespStore(port=r0.port)
    try:
        assert store.shard_count == 2
        assert _wait_until(
            lambda: rc.info().get("repl_link_up") == "1"
        ), "replica never synced"
        handle, _ = store.replay_announces(-1)
        # a task whose id lands on shard 0 (the HA pair)
        tid = "fo-0"
        for i in range(10_000):
            if store.shard_of(f"fo-{i}") == 0:
                tid = f"fo-{i}"
                break
        store.create_task(tid, "F", "P")
        assert _wait_until(
            lambda: rc.hget(tid, FIELD_STATUS) == "QUEUED"
        ), "create never replicated"
        gen0 = store.failover_generation
        p0.stop()
        rc.promote()
        # next command through shard 0 walks its ring and settles on the
        # promoted replica
        assert _wait_until(
            lambda: _safe_status(store, tid) == "QUEUED", timeout=20
        ), "shard 0 never failed over to the promoted replica"
        assert store.failover_generation == gen0 + 1
        assert store.shard_failover_generations()[1] == 0
        # dispatcher-style re-arm replay: the promoted replica's ring
        # still carries the announce
        _h, entries = store.replay_announces(handle)
        assert (TASKS_CHANNEL, tid) in entries
    finally:
        rc.close()
        store.close()
        for h in (r0, s1, p0):
            h.stop()


def _safe_status(store, tid):
    try:
        return store.get_status(tid)
    except (ConnectionError, OSError):
        return None


# -- gateway + per-shard dispatchers end to end ------------------------------


def test_gateway_over_sharded_store_end_to_end():
    """2 memory shards, one LocalDispatcher owning each, one stateless
    gateway over the full ring: every submit completes, /result //status
    route by shard, and the gateway's shard topology is visible."""
    import requests

    from tpu_faas.client.sdk import FaaSClient
    from tpu_faas.dispatch.local import LocalDispatcher
    from tpu_faas.gateway.app import start_gateway_thread

    mems = [MemoryStore() for _ in range(2)]
    gw_store = ShardedStore(mems)
    gw = start_gateway_thread(gw_store)
    disps = [
        LocalDispatcher(
            num_workers=2, store=ShardedStore(mems, owned_shards=[i])
        )
        for i in range(2)
    ]
    threads = [
        threading.Thread(target=d.start, daemon=True) for d in disps
    ]
    for t in threads:
        t.start()
    client = FaaSClient(gw.url)
    try:
        fid = client.register(len)
        handles = [client.submit(fid, [0] * n) for n in range(12)]
        assert [h.result(timeout=60) for h in handles] == list(range(12))
        # the keyspace genuinely spread over both shards
        by_shard = Counter(gw_store.shard_of(h.task_id) for h in handles)
        assert set(by_shard) == {0, 1}, by_shard
        # every task's terminal record landed on ITS ring shard — and
        # since dispatcher i is the only consumer of shard i's bus, each
        # shard's completions were served by its owning dispatcher
        for i, mem in enumerate(mems):
            done = [
                h.task_id
                for h in handles
                if mem.hget(h.task_id, FIELD_STATUS) == "COMPLETED"
            ]
            assert len(done) == by_shard[i], (i, done, by_shard)
            assert all(gw_store.shard_of(t) == i for t in done)
        stats = requests.get(f"{gw.url}/stats", timeout=10).json()
        assert stats["store_shards"] == 2
        # the shard-routing counter saw the /result traffic
        metrics = requests.get(f"{gw.url}/metrics", timeout=10).text
        assert "tpu_faas_gateway_shard_routed_total" in metrics
        assert 'shard="0"' in metrics and 'shard="1"' in metrics
    finally:
        for d in disps:
            d.stop()
        for t in threads:
            t.join(timeout=10)
        gw.stop()
