"""Fused Pallas resident tick: interpret-mode parity vs the XLA oracle.

The contract mirrors tests/test_sched_pallas.py's for the bid kernel: CPU
CI runs the fused kernel under the Pallas interpreter against the jitted
XLA resident tick. Integer outputs (placements, slots, liveness) must be
EXACTLY equal — the kernel body traces through the same ``_impl`` core as
the oracle, so any difference is a plumbing bug (ref packing, aliasing,
dtype round trips, the lifted-constant path). Float state (auction
prices) is compared within 1e-5, the bid kernel's tolerance, because the
auction path swaps the matrix bid for the streamed O(T+S) form.

Also here: the resident-delta replay equivalence (a tick driven by an
accumulated delta history must equal a tick driven by fresh full state),
the one-device-dispatch-per-tick regression pinned via the scheduler's
dispatch counters AND ``jax.transfer_guard_device_to_host`` (zero
intra-tick host syncs), and the streamed bid's global-hash sharding
contract.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_faas.sched.pallas_kernels import (
    bid_top2_stream,
    bid_top2_xla,
)
from tpu_faas.sched.resident import ResidentScheduler


class _Clock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


def _mk(backend, placement="rank", clock=None, **kw):
    kw.setdefault("max_workers", 32)
    kw.setdefault("max_pending", 64)
    kw.setdefault("max_inflight", 128)
    kw.setdefault("max_slots", 4)
    kw.setdefault("KA", 8)
    kw.setdefault("KP", 16)
    kw.setdefault("KR", 8)
    return ResidentScheduler(
        placement=placement,
        clock=clock or _Clock(),
        tick_backend=backend,
        **kw,
    )


def _drive(rs, script):
    """Apply a scripted event history, returning per-tick resolved views.

    script: list of ticks; each tick is a dict with optional keys
    arrivals=[(tid, size)], results=[tid], hb=[worker_ids], register=
    [(wid, procs, speed)], dt=seconds to advance the clock.
    """
    views = []
    for ev in script:
        rs.clock.t += ev.get("dt", 0.1)
        for wid, procs, speed in ev.get("register", ()):
            rs.register(wid, procs, speed=speed)
        for wid in ev.get("hb", ()):
            rs.heartbeat(wid)
        for tid, size in ev.get("arrivals", ()):
            rs.pending_add(tid, size)
        for tid in ev.get("results", ()):
            row = rs.inflight_done(tid)
            if row is not None:
                rs.release_slot(row)
        rs.tick_resident()
        resolved = []
        while True:
            r = rs.resolve_next()
            if r is None:
                break
            resolved.append(r)
            # mirror the dispatcher: placed tasks enter the in-flight table
            for tid, row in r.placed:
                rs.inflight_add(tid, row)
        views.append(resolved)
    return views


_SCRIPT = [
    {
        "register": [(b"w0", 4, 1.0), (b"w1", 4, 2.0), (b"w2", 2, 3.0)],
        "arrivals": [(f"t{i}", 0.5 + 0.25 * i) for i in range(6)],
    },
    # results free capacity; new arrivals reuse freed slots
    {
        "hb": [b"w0", b"w1", b"w2"],
        "results": ["t0", "t3"],
        "arrivals": [("t6", 2.0), ("t7", 0.1)],
    },
    # w2 goes silent past time_to_expire: purge + redispatch
    {"hb": [b"w0", b"w1"], "dt": 11.0, "arrivals": [("t8", 1.3)]},
    # it reconnects, more traffic
    {
        "register": [(b"w2", 2, 3.0)],
        "hb": [b"w0", b"w1"],
        "arrivals": [("t9", 0.9), ("t10", 4.0)],
    },
]


def _flatten(views):
    out = []
    for resolved in views:
        for r in resolved:
            out.append(
                (
                    sorted(r.placed),
                    sorted(r.redispatch_slots),
                    sorted(int(x) for x in r.purged_rows),
                    r.rejected,
                    r.n_pending,
                )
            )
    return out


@pytest.mark.parametrize("placement", ["rank", "auction", "sinkhorn"])
def test_fused_tick_matches_xla_oracle(placement):
    """The same scripted multi-tick history — arrivals, results, heartbeat
    churn, a purge + reconnect — must resolve identically through the
    fused kernel and the XLA oracle, and leave identical device state."""
    a = _mk("xla", placement)
    b = _mk("fused_interpret", placement)
    va = _drive(a, _SCRIPT)
    vb = _drive(b, _SCRIPT)
    assert _flatten(va) == _flatten(vb)
    sa, sb = a._r_state, b._r_state
    for field in ("valid", "prio", "free", "inflight", "prev_live",
                  "active"):
        np.testing.assert_array_equal(
            np.asarray(getattr(sa, field)),
            np.asarray(getattr(sb, field)),
            err_msg=field,
        )
    np.testing.assert_allclose(
        np.asarray(sa.sizes), np.asarray(sb.sizes), atol=1e-6
    )
    # auction prices ride the streamed bid on the fused path: the bid
    # kernel's 1e-5 value tolerance applies
    np.testing.assert_allclose(
        np.asarray(sa.price), np.asarray(sb.price), atol=1e-5
    )


@pytest.mark.parametrize("backend", ["xla", "fused_interpret"])
def test_resident_delta_replay_equivalence(backend):
    """A tick driven by an accumulated DELTA history must equal a tick
    driven by full state: replay scheduler A's host mirrors into a fresh
    scheduler B (bulk load of the surviving pending set in device slot
    order), tick both with the same clock, and require identical
    placements."""
    a = _mk(backend)
    _drive(a, _SCRIPT)
    # A now carries several ticks of delta history on device. Rebuild the
    # equivalent full state in B.
    b = _mk(backend, clock=a.clock)
    b.worker_speed[:] = a.worker_speed
    b.worker_free[:] = a.worker_free
    b.worker_active[:] = a.worker_active
    b.worker_procs[:] = a.worker_procs
    b.last_heartbeat[:] = a.last_heartbeat
    b.prev_live = np.asarray(a.prev_live).copy()
    b.inflight_worker[:] = a.inflight_worker
    b.worker_ids = dict(a.worker_ids)
    b.row_ids = dict(a.row_ids)
    # surviving pending set, in device slot order (= the order the device
    # admits FCFS within a tick)
    slots = sorted(a.slot_task)
    ids = [a.slot_task[s] for s in slots]
    sizes = np.asarray([a._slot_meta[s].size for s in slots], np.float32)
    b.pending_bulk_load(ids, sizes)

    # one more burst applied to BOTH, then one tick each
    for rs in (a, b):
        rs.pending_add("fresh1", 0.77)
        rs.pending_add("fresh2", 1.9)
    a.clock.t += 0.05
    out_a = a.tick_resident()
    out_b = b.tick_resident()
    ra = [a.resolve_next() for _ in range(len(a._unresolved))]
    rb = [b.resolve_next() for _ in range(len(b._unresolved))]
    placed_a = sorted(p for r in ra for p in r.placed)
    placed_b = sorted(p for r in rb for p in r.placed)
    assert placed_a == placed_b
    assert int(out_a.n_pending) == int(out_b.n_pending)
    np.testing.assert_array_equal(
        np.asarray(out_a.live), np.asarray(out_b.live)
    )


def test_fused_one_dispatch_per_tick_and_zero_host_syncs():
    """THE counter-pinned contract: a steady-state fused tick issues
    exactly ONE compiled-callable dispatch and performs zero
    device->host transfers (``jax.transfer_guard_device_to_host``
    raises on any sync inside the guarded region)."""
    rs = _mk("fused_interpret")
    for i in range(4):
        rs.register(f"w{i}".encode(), 4, speed=1.0 + i)
    rs.tick_resident()  # warmup compile outside the guard
    assert rs.device_dispatches_last_tick == 1
    for i in range(6):
        rs.pending_add(f"t{i}", float(i + 1))
    rs.clock.t += 0.1
    with jax.transfer_guard_device_to_host("disallow"):
        rs.tick_resident()
    assert rs.device_dispatches_last_tick == 1
    assert rs.device_dispatches_total == 2
    # resolution AFTER the tick is where the (deferred) sync belongs
    while rs.resolve_next() is not None:
        pass


def test_fused_overflow_flush_counts_extra_dispatches():
    """An over-KA arrival burst drains through flush packets: dispatch
    count = 1 fused tick + one per overflow flush, all counted."""
    rs = _mk("fused_interpret")
    rs.register(b"w0", 4, speed=1.0)
    for i in range(20):  # KA = 8 -> 2 flushes + the tick
        rs.pending_add(f"t{i}", 1.0)
    rs.tick_resident()
    assert rs.device_dispatches_last_tick == 3


def test_profiler_exports_dispatch_families():
    """The one-dispatch contract is scrapeable: TickProfiler's gauge and
    counter land in a strict-parsed exposition."""
    from tpu_faas.obs.expofmt import parse_exposition
    from tpu_faas.obs.metrics import MetricsRegistry, render
    from tpu_faas.obs.profile import TickProfiler

    reg = MetricsRegistry()
    prof = TickProfiler(reg)
    sig = ("resident", 64, 32, 4, "rank", "fused_interpret")
    assert prof.observe_shape(tasks=64, workers=32, slots=4, signature=sig)
    prof.note_device_dispatches(1)
    # steady state: same signature -> no recompile, dispatches stay 1/tick
    assert not prof.observe_shape(
        tasks=64, workers=32, slots=4, signature=sig
    )
    prof.note_device_dispatches(1)
    fams = parse_exposition(render([reg]))
    assert (
        fams["tpu_faas_tick_device_dispatches_last"].samples[0].value == 1
    )
    assert (
        fams["tpu_faas_tick_device_dispatches_total"].samples[0].value == 2
    )
    assert fams["tpu_faas_jit_recompiles_total"].samples[0].value == 1


def test_fused_rejects_mesh_combination():
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    with pytest.raises(ValueError, match="single-device"):
        _mk("fused_interpret", mesh_devices=2)


def test_fused_vmem_budget_headline_shape():
    """The ROADMAP 500k x 32k capacity shape fits a v5e core's 16 MB VMEM
    with the default packet capacities on the RANK path — the sizing
    claim OPERATIONS.md documents, kept honest here. The auction path
    adds ~8 MB of streamed-bid tile scratch: it fits at the bench
    auction-dryrun shape but NOT at 500k x 32k (also documented —
    estimator honesty cuts both ways)."""
    from tpu_faas.sched.pallas_fused import fused_state_bytes

    kw = dict(I=65_536, max_slots=8, KA=512, KP=2048, KR=512,
              packet_len=8_000)
    n = fused_state_bytes(T=500_000, W=32_768, **kw)
    assert n < 14 * 2**20, f"{n} bytes exceeds the fused VMEM guidance"
    a_small = fused_state_bytes(T=50_000, W=4_096, placement="auction", **kw)
    assert a_small < 14 * 2**20, f"{a_small} bytes: auction 50k x 4k"
    a_big = fused_state_bytes(
        T=500_000, W=32_768, placement="auction", **kw
    )
    assert a_big >= n + 8 * 2**20, (
        "auction scratch accounting lost its streamed-tile term"
    )
    assert a_big >= 14 * 2**20, (
        "auction at the 500k shape should sit AT the stay-on-xla ceiling"
    )


def test_stream_bid_sharded_offsets_match_global():
    """bid_top2_stream's row_offset/n_slots_total args keep the tie-break
    hash GLOBAL: two half-shards with offsets concatenate to exactly the
    full problem's output (the property the mesh permute path rests on)."""
    rng = np.random.default_rng(7)
    T, S = 256, 1024
    ts = jnp.asarray(rng.uniform(0.1, 5.0, T).astype(np.float32))
    inv = jnp.asarray((1.0 / rng.uniform(0.5, 4.0, S)).astype(np.float32))
    val = jnp.asarray((rng.random(S) < 0.8).astype(np.float32))
    pr = jnp.asarray(rng.uniform(0.0, 3.0, S).astype(np.float32))
    sc = jnp.float32(2.5e-4)
    v1, b, v2 = bid_top2_xla(ts, inv, val, pr, sc)
    h = T // 2
    lo = bid_top2_stream(ts[:h], inv, val, pr, sc, 0, S)
    hi = bid_top2_stream(ts[h:], inv, val, pr, sc, h, S)
    np.testing.assert_array_equal(
        np.asarray(v1), np.concatenate([lo[0], hi[0]])
    )
    np.testing.assert_array_equal(
        np.asarray(b), np.concatenate([lo[1], hi[1]])
    )
    np.testing.assert_array_equal(
        np.asarray(v2), np.concatenate([lo[2], hi[2]])
    )
