"""Test env: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding tests run over
``xla_force_host_platform_device_count=8`` as the driver's dryrun does.

NOTE: a pytest plugin imports jax before this conftest runs, so setting
JAX_PLATFORMS in os.environ here is too late — jax snapshots env config at
import. ``jax.config.update`` works post-import; XLA_FLAGS is read lazily at
first backend init, which hasn't happened yet at collection time.
"""

import os
import sys

_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _repo)

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# TPU_FAAS_TEST_PLATFORM overrides (e.g. =tpu to run the suite on real
# hardware); default is the 8-device virtual CPU mesh. JAX_PLATFORMS itself
# can't express the default here because platform plugins rewrite it.
_platform = os.environ.get("TPU_FAAS_TEST_PLATFORM", "cpu")
jax.config.update("jax_platforms", _platform)
# persistent XLA compile cache: the sched kernels cost ~1 min to compile cold
jax.config.update(
    "jax_compilation_cache_dir", os.path.join(_repo, ".jax_cache")
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

if _platform == "cpu":
    assert jax.default_backend() == "cpu", (
        f"backend is {jax.default_backend()!r}, wanted 'cpu' — "
        "a plugin initialized JAX before conftest could configure it"
    )
    assert len(jax.devices()) >= 8, (
        f"expected >= 8 virtual CPU devices, got {jax.devices()}"
    )
else:
    # hardware platform plugins may register under a different backend name
    # than their platform string (e.g. a tunneled-TPU plugin selected as
    # 'axon' reports default_backend() == 'tpu') — only rule out a silent
    # fallback to CPU
    assert jax.default_backend() != "cpu", (
        f"requested platform {_platform!r} but fell back to CPU"
    )
# on real hardware the mesh tests skip themselves if devices are scarce
