"""Test env: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding tests run over
``xla_force_host_platform_device_count=8`` as the driver's dryrun does.

NOTE: a pytest plugin imports jax before this conftest runs, so setting
JAX_PLATFORMS in os.environ here is too late — jax snapshots env config at
import. ``jax.config.update`` works post-import; XLA_FLAGS is read lazily at
first backend init, which hasn't happened yet at collection time.
"""

import os
import sys

_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _repo)

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# TPU_FAAS_TEST_PLATFORM overrides (e.g. =tpu to run the suite on real
# hardware); default is the 8-device virtual CPU mesh. JAX_PLATFORMS itself
# can't express the default here because platform plugins rewrite it.
_platform = os.environ.get("TPU_FAAS_TEST_PLATFORM", "cpu")
jax.config.update("jax_platforms", _platform)
# persistent XLA compile cache: the sched kernels cost ~1 min to compile cold
jax.config.update(
    "jax_compilation_cache_dir", os.path.join(_repo, ".jax_cache")
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

if _platform == "cpu":
    assert jax.default_backend() == "cpu", (
        f"backend is {jax.default_backend()!r}, wanted 'cpu' — "
        "a plugin initialized JAX before conftest could configure it"
    )
    assert len(jax.devices()) >= 8, (
        f"expected >= 8 virtual CPU devices, got {jax.devices()}"
    )
else:
    # hardware platform plugins may register under a different backend name
    # than their platform string (e.g. a tunneled-TPU plugin selected as
    # 'axon' reports default_backend() == 'tpu') — only rule out a silent
    # fallback to CPU
    assert jax.default_backend() != "cpu", (
        f"requested platform {_platform!r} but fell back to CPU"
    )
# on real hardware the mesh tests skip themselves if devices are scarce


# -- suite-level orphan detection ------------------------------------------
# The e2e tests SIGKILL workers/dispatchers constantly; a teardown bug that
# orphans their multiprocessing helpers (resource_tracker, forkserver, pool
# children) to pid 1 poisons the BOX, not just the run — each orphan burns
# ~2.4% CPU forever and accumulated orphans once drove load past 19 and
# flaked the scale tests. The leak was fixed at the source (process-group
# spawns + group kills); this fixture keeps it fixed.


# Unique per-session marker, inherited (and therefore visible in
# /proc/<pid>/environ, which snapshots the EXEC-time environment) by every
# child this suite spawns. Scopes the orphan check to processes this
# session actually owns — a concurrent pytest session's helpers or a
# developer's daemonized tpu_faas service on the same box must be neither
# counted nor killed.
_SESSION_MARKER = f"TPU_FAAS_TEST_SESSION={os.getpid()}-{os.urandom(4).hex()}"
_mk, _, _mv = _SESSION_MARKER.partition("=")
os.environ[_mk] = _mv


def _orphan_pids() -> dict[int, str]:
    """PID-1-parented processes carrying this session's env marker."""
    marker = _SESSION_MARKER.encode()
    orphans: dict[int, str] = {}
    for entry in os.listdir("/proc"):
        if not entry.isdigit():
            continue
        pid = int(entry)
        try:
            with open(f"/proc/{pid}/stat", "rb") as f:
                stat = f.read().decode("ascii", "replace")
            # ppid is the 2nd field after the parenthesized comm (which may
            # itself contain spaces/parens — split on the LAST ')')
            ppid = int(stat.rsplit(")", 1)[1].split()[1])
            if ppid != 1:
                continue
            with open(f"/proc/{pid}/environ", "rb") as f:
                env = f.read()
            if marker not in env.split(b"\x00"):
                continue
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\x00", b" ").decode(
                    "utf-8", "replace"
                ).strip()
            orphans[pid] = cmd
        except (OSError, ValueError, IndexError):
            continue  # process vanished mid-scan, or unreadable
    return orphans


import time as _time

import pytest


@pytest.fixture(scope="session", autouse=True)
def _no_orphaned_children():
    before = set(_orphan_pids())
    yield
    # grace for children still winding down at session end
    deadline = _time.monotonic() + 10
    while True:
        leaked = {
            p: c for p, c in _orphan_pids().items() if p not in before
        }
        if not leaked:
            return
        if _time.monotonic() > deadline:
            break
        _time.sleep(0.5)
    # sweep so one bad run doesn't poison the next, then fail loudly
    for pid in leaked:
        try:
            os.kill(pid, 9)
        except OSError:
            pass
    raise AssertionError(
        f"suite leaked {len(leaked)} orphaned child processes "
        f"(killed them just now):\n"
        + "\n".join(f"  {p}: {c}" for p, c in leaked.items())
    )
