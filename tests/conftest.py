"""Test env: force JAX onto a virtual 8-device CPU mesh before jax imports.

Multi-chip hardware is not available in CI; sharding tests run over
``--xla_force_host_platform_device_count=8`` as the driver's dryrun does.
Must run before anything imports jax, hence module-level in conftest.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
