"""Chaos plane unit tests (tpu_faas/chaos): spec grammar, seeded
determinism, window semantics, per-seam injection behavior, and the
chaos-off byte-identity guarantee.

Determinism is the plane's contract: the SAME seed + rule string must
replay the SAME injection sequence, run to run and process to process —
that is what makes a chaos scenario a regression test instead of a
flake. The tests drive the seams with stubbed clocks/sleeps so every
decision stream is observed event by event."""

from __future__ import annotations

import os
import subprocess
import sys

import pytest

from tpu_faas import chaos
from tpu_faas.chaos import (
    ChaosConfigError,
    parse_chaos,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _plan(spec: str, t: list[float] | None = None):
    """An armed plan with a controllable clock (t is a 1-cell box)."""
    p = parse_chaos(spec)
    box = t if t is not None else [0.0]
    p.clock = lambda: box[0]
    p.armed_at = 0.0
    return p


# -- grammar -----------------------------------------------------------------


@pytest.mark.parametrize(
    "spec",
    [
        "seed=1;bogus.kind:p=1",          # unknown site.kind
        "seed=1;wire",                     # no dot
        "seed=1;exec.slow:p=1",            # missing required ms
        "seed=1;wire.drop:p=0.5:nth=3",    # p and nth exclusive
        "seed=1;wire.drop:p=1.5",          # p out of range
        "seed=1;wire.drop:nth=0",          # nth is 1-based
        "seed=1;wire.drop:frobnicate=1",   # unknown param
        "seed=1;wire.drop:p=abc",          # non-numeric
        "seed=1;seed=2;wire.drop:p=1",     # seed twice
        "seed=1",                          # zero rules
        "",                                # empty
    ],
)
def test_parse_rejects_malformed(spec):
    with pytest.raises(ChaosConfigError):
        parse_chaos(spec)


def test_parse_accepts_full_grammar():
    p = parse_chaos(
        "seed=42;store.latency:ms=5:p=0.1,store.outage:dur=2:after=1,"
        "store.torn:nth=3,wire.drop:p=0.2,wire.dup:p=0.1,"
        "wire.delay:ms=10:until=30,exec.slow:ms=100:p=1,"
        "exec.crash_before:nth=7,exec.crash_after:p=0.01"
    )
    assert p.seed == 42
    assert len(p.rules) == 9
    # each rule's RNG stream key includes its index: two rules of the
    # same site.kind get distinct streams
    assert p.rules[0].index == 0 and p.rules[8].index == 8


# -- determinism (satellite: same seed+rules => identical sequence) ----------


def _wire_sequence(spec: str, n: int = 300) -> list[str]:
    """Drive the wire seam n times and label what happened per event."""
    p = _plan(spec)
    w = p.wire()
    w.sleep = lambda s: None
    seq: list[str] = []
    for i in range(n):
        before = dict(p.counts)
        sent: list[object] = []
        w.send(i, sent.append)
        fired = [
            f"{s}.{k}"
            for (s, k), v in p.counts.items()
            if v != before.get((s, k), 0)
        ]
        seq.append(fired[0] if fired else f"clean:{len(sent)}")
    return seq


def test_same_seed_same_rules_identical_injection_sequence():
    spec = "seed=11;wire.drop:p=0.2,wire.dup:p=0.2,wire.delay:ms=1:p=0.2"
    a = _wire_sequence(spec)
    b = _wire_sequence(spec)
    assert a == b
    # and the spec actually injected (a vacuously-equal clean run would
    # prove nothing)
    assert any(not s.startswith("clean") for s in a)


def test_different_seed_diverges():
    spec = "seed=11;wire.drop:p=0.5"
    a = _wire_sequence(spec)
    b = _wire_sequence(spec.replace("seed=11", "seed=12"))
    assert a != b


def test_rule_index_isolates_streams():
    # two rules with identical params get DIFFERENT streams (index is in
    # the seed key), so reordering-insensitive specs can't alias
    p = _plan("seed=5;wire.drop:p=0.5,wire.drop:p=0.5")
    r0, r1 = p.rules
    a = [r0.decide(0.0) for _ in range(200)]
    b = [r1.decide(0.0) for _ in range(200)]
    assert a != b


def test_window_edges_do_not_desynchronize_stream():
    # decisions OUTSIDE the window must not advance the RNG stream:
    # runs that differ by microseconds at a window edge replay the same
    # in-window sequence
    spec = "seed=3;exec.slow:ms=1:p=0.5:until=10"
    ra = _plan(spec).rules[0]
    rb = _plan(spec).rules[0]
    seq_a = [ra.decide(1.0) for _ in range(100)]
    seq_b = []
    for _ in range(100):
        assert rb.decide(20.0) is False  # outside: no stream advance
        seq_b.append(rb.decide(1.0))
    assert seq_a == seq_b


def test_nth_fires_exactly_once():
    p = _plan("seed=1;wire.drop:nth=3")
    r = p.rules[0]
    assert [r.decide(0.0) for _ in range(6)] == [
        False, False, True, False, False, False
    ]
    assert r.fired == 1


# -- per-seam semantics ------------------------------------------------------


def test_store_outage_window_and_latency():
    t = [0.0]
    p = _plan("seed=1;store.outage:dur=5:after=2,store.latency:ms=7:p=1", t)
    s = p.store()
    naps: list[float] = []
    s.sleep = naps.append
    s.before("get")  # t=0: outage not open yet; latency always fires
    t[0] = 3.0
    with pytest.raises(ConnectionError):
        s.before("get")
    t[0] = 8.0
    s.before("get")  # window closed
    assert p.counts[("store", "outage")] == 1
    assert p.counts[("store", "latency")] == 2
    assert naps == [0.007, 0.007]


def test_store_torn_counts():
    p = _plan("seed=1;store.torn:nth=2")
    s = p.store()
    assert s.torn() is False
    assert s.torn() is True
    assert p.counts[("store", "torn")] == 1


def test_wire_drop_never_sends_and_dup_sends_twice():
    p = _plan("seed=1;wire.drop:nth=1,wire.dup:nth=1")
    w = p.wire()
    sent: list[int] = []
    w.send(1, sent.append)  # dropped
    w.send(2, sent.append)  # dup (drop's nth already spent)
    w.send(3, sent.append)  # clean
    assert sent == [2, 2, 3]
    assert p.counts == {("wire", "drop"): 1, ("wire", "dup"): 1}


def test_wire_delay_defers_until_flush():
    t = [0.0]
    p = _plan("seed=1;wire.delay:ms=50:p=1", t)
    w = p.wire()
    sent: list[int] = []
    w.send(1, sent.append)
    assert sent == []  # held, not sent
    assert w.flush(sent.append) == 0  # hold not expired
    t[0] = 0.06
    assert w.flush(sent.append) == 1
    assert sent == [1]


def test_wire_lockstep_guards():
    # REQ/REP call sites pass drop_ok/dup_ok/defer_ok=False: drop and
    # dup rules FALL THROUGH to a clean send; delay degrades to a
    # blocking sleep + send (the only injection a lockstep socket
    # can express)
    p = _plan("seed=1;wire.drop:p=1,wire.dup:p=1,wire.delay:ms=5:p=1")
    w = p.wire()
    naps: list[float] = []
    w.sleep = naps.append
    sent: list[int] = []
    for i in range(4):
        w.send(i, sent.append, dup_ok=False, defer_ok=False, drop_ok=False)
    assert sent == [0, 1, 2, 3]  # nothing lost, nothing duplicated
    assert naps == [0.005] * 4  # delay degraded to a blocking sleep
    assert w.held == []


def test_exec_crash_uses_exit_fn_and_slow_sleeps():
    p = _plan("seed=1;exec.crash_before:nth=2,exec.slow:ms=30:p=1")
    e = p.execution()
    naps: list[float] = []
    exits: list[int] = []
    e.sleep = naps.append
    e.exit_fn = exits.append
    e.before_task("t1")
    assert exits == [] and naps == [0.03]
    e.before_task("t2")
    assert exits == [e.EXIT_CODE]
    e.after_result("t2")  # no crash_after rule: clean
    assert p.counts[("exec", "crash_before")] == 1
    assert p.counts[("exec", "slow")] == 1


def test_injections_reach_flight_recorder():
    from tpu_faas.obs.flightrec import FlightRecorder

    p = _plan("seed=1;wire.drop:nth=1")
    rec = FlightRecorder(capacity=16)
    p.bind_flightrec(rec)
    p.wire().send(b"x", lambda f: None)
    events = rec.snapshot()["events"]
    assert len(events) == 1
    ev = events[0]
    # the event's kind is the EVENT kind; the rule kind rides as "fault"
    assert ev["kind"] == "chaos_injected"
    assert ev["site"] == "wire" and ev["fault"] == "drop"


# -- env arming --------------------------------------------------------------


def test_from_env_unset_is_none_and_cached_per_spec(monkeypatch):
    monkeypatch.delenv(chaos.ENV_VAR, raising=False)
    chaos._reset_for_tests()
    assert chaos.from_env() is None
    monkeypatch.setenv(chaos.ENV_VAR, "seed=1;wire.drop:p=0.5")
    p1 = chaos.from_env()
    p2 = chaos.from_env()
    assert p1 is p2  # one process, one plan: streams keep advancing
    monkeypatch.setenv(chaos.ENV_VAR, "seed=2;wire.drop:p=0.5")
    assert chaos.from_env() is not p1  # changed spec re-arms
    chaos._reset_for_tests()


def test_malformed_env_raises_at_arm_time(monkeypatch):
    monkeypatch.setenv(chaos.ENV_VAR, "seed=1;wire.bogus:p=1")
    chaos._reset_for_tests()
    with pytest.raises(ChaosConfigError):
        chaos.from_env()
    chaos._reset_for_tests()


# -- chaos-off byte-identity (satellite) -------------------------------------


def test_chaos_off_exposition_byte_identical():
    """With TPU_FAAS_CHAOS unset, a process that imports and consults
    the chaos plane renders a byte-identical process-global exposition
    to one that never heard of it: the injection counter family is
    registered lazily, only when a plan is armed."""
    env = {
        k: v for k, v in os.environ.items() if k != chaos.ENV_VAR
    }
    env["JAX_PLATFORMS"] = "cpu"
    with_plane = (
        "from tpu_faas import chaos\n"
        "assert chaos.from_env() is None\n"
        "from tpu_faas.obs.metrics import REGISTRY, render\n"
        "import sys; sys.stdout.write(render([REGISTRY]))\n"
    )
    without_plane = (
        "from tpu_faas.obs.metrics import REGISTRY, render\n"
        "import sys; sys.stdout.write(render([REGISTRY]))\n"
    )
    outs = []
    for code in (with_plane, without_plane):
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, env=env, cwd=REPO, timeout=120,
        )
        assert r.returncode == 0, r.stderr.decode()
        outs.append(r.stdout)
    assert outs[0] == outs[1]
    assert b"tpu_faas_chaos" not in outs[0]


def test_chaos_off_seams_are_none(monkeypatch):
    # the per-component gate: every seam holds None when the env is
    # unset, so the hot paths pay one identity check and nothing else
    monkeypatch.delenv(chaos.ENV_VAR, raising=False)
    chaos._reset_for_tests()
    assert chaos.from_env() is None
    # and an armed plan only builds handlers for sites its rules name
    p = parse_chaos("seed=1;wire.drop:p=0.5")
    assert p.store() is None
    assert p.execution() is None
    assert p.wire() is not None
