"""Operator-reachable multi-host: the `--multihost` dispatcher CLI, end to
end.

tests/test_multihost.py proves the bare sharded kernels over a two-process
gloo pod; THIS test proves the product: two `python -m tpu_faas.dispatch
-m tpu-push --multihost` processes form the global 8-device mesh (2 OS
processes x 4 virtual CPU devices), process 0 serves a REAL stack — store,
gateway, ZMQ push worker — and places real tasks with every tick running
collectively over the global mesh (broadcast + sharded tick + allgather,
parallel/multihost_tick.py). Shutdown is part of the contract: SIGTERM to
the lead must release the follower from its blocking collective via the
stop broadcast — both processes exit cleanly.
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import time

from tpu_faas.client import FaaSClient
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store.launch import make_store, start_store_thread
from tpu_faas.workloads import sleep_task
from tests.test_workers_e2e import _spawn_worker

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

import pytest

from tests.test_multihost import cpu_pod_supported

if not cpu_pod_supported():
    pytest.skip(
        "this JAX cannot simulate a multi-process CPU pod "
        "(jax_num_cpu_devices / jax.shard_map missing)",
        allow_module_level=True,
    )



def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_dispatcher(
    rank: int, coord: int, zmq_port: int, store_url: str, *extra: str
):
    from tpu_faas.bench.harness import cpu_worker_env

    env = cpu_worker_env()
    # the processes form their OWN CPU pod (jax_num_cpu_devices + gloo);
    # the parent suite's virtual-device flags would fight that config
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    args = [
        sys.executable, "-m", "tpu_faas.dispatch",
        "-m", "tpu-push",
        "-i", "127.0.0.1",
        "-p", str(zmq_port),
        "--multihost",
        "--coordinator", f"127.0.0.1:{coord}",
        "--process-id", str(rank),
        "--num-processes", "2",
        "--cpu-pod-devices", "4",
        "--max-pending", "64",
        "--max-fleet", "16",
        "--tick-period", "0.05",
        "--tte", "2.0",  # fast purge so the crash leg stays snappy
        "--store", store_url,
        *extra,
    ]
    return subprocess.Popen(
        args, env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        start_new_session=True,
    )


def _crash_worker_and_expect_redispatch(client, workers):
    """SIGKILL workers[0] while it provably holds in-flight tasks; all
    submissions must still complete on the survivor via the fleet's
    purge + reclaim machinery. The kill waits until >= 4 tasks report
    RUNNING: that is both 2-slot workers completely full, so the killed
    worker's slots really were occupied
    (a fixed pre-kill sleep could fire before anything dispatched on a
    loaded box and make the reclaim vacuous) — and 2.5 s tasks cannot
    have completed inside the poll's exit window. The caller additionally
    pins the lead's "purged worker row" / "reclaimed ... in-flight" log
    lines at shutdown."""
    fid = client.register(sleep_task)
    slow = [client.submit(fid, 2.5) for _ in range(6)]
    deadline = time.time() + 60
    while time.time() < deadline:
        if sum(1 for h in slow if h.status() == "RUNNING") >= 4:
            break
        time.sleep(0.1)
    else:
        raise AssertionError("tasks never reached RUNNING on both workers")
    workers[0].send_signal(signal.SIGKILL)
    workers[0].wait()
    assert [h.result(timeout=120.0) for h in slow] == [2.5] * 6


def test_multihost_dispatcher_serves_and_stops():
    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    coord, zmq_port = _free_port(), _free_port()
    follower = _spawn_dispatcher(1, coord, zmq_port, store_handle.url)
    lead = _spawn_dispatcher(0, coord, zmq_port, store_handle.url)
    workers = []
    try:
        workers = [
            _spawn_worker(
                "push_worker", 2, f"tcp://127.0.0.1:{zmq_port}",
                "--hb", "--hb-period", "0.3",
            )
            for _ in range(2)
        ]
        client = FaaSClient(gw.url)
        fid = client.register(lambda x: x + 100, name="add100")
        handles = [client.submit(fid, i) for i in range(12)]
        deadline = time.time() + 180  # two cold jax compiles in children
        done = {}
        while len(done) < 12 and time.time() < deadline:
            for i, h in enumerate(handles):
                if i in done:
                    continue
                st = h.status()
                if st in ("COMPLETED", "FAILED"):
                    assert st == "COMPLETED", (i, st)
                    done[i] = h.result(timeout=5.0)
            time.sleep(0.2)
        assert len(done) == 12, f"only {len(done)}/12 completed"
        assert all(done[i] == i + 100 for i in range(12))

        # -- worker crash under multihost: redispatch is computed by the
        # LEAD host-side (the table no longer rides the broadcast)
        _crash_worker_and_expect_redispatch(client, workers)

        # -- shutdown contract: SIGTERM the lead; the stop broadcast must
        # release the follower from its blocking collective
        os.kill(lead.pid, signal.SIGTERM)
        lead_out, _ = lead.communicate(timeout=60)
        assert lead.returncode == 0, lead_out[-2000:]
        assert "purged worker row" in lead_out, lead_out[-2000:]
        assert "reclaimed" in lead_out, lead_out[-2000:]
        follower_out, _ = follower.communicate(timeout=60)
        assert follower.returncode == 0, follower_out[-2000:]
        assert "stop after" in follower_out
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.wait()
        for p in (lead, follower):
            if p.poll() is None:
                p.kill()
                p.wait()
        gw.stop()
        store_handle.stop()


def test_lead_failure_before_serving_releases_followers():
    """The lead crashing BEFORE its serve loop (here: ZMQ bind on an
    already-occupied port) must still broadcast the follower stop — a
    stranded follower blocks forever inside a collective."""
    store_handle = start_store_thread()
    coord = _free_port()
    blocker = socket.socket()
    blocker.bind(("127.0.0.1", 0))
    taken_port = blocker.getsockname()[1]
    blocker.listen(1)  # keep the port occupied for the lead's bind
    follower = _spawn_dispatcher(1, coord, taken_port, store_handle.url)
    lead = _spawn_dispatcher(0, coord, taken_port, store_handle.url)
    try:
        lead_out, _ = lead.communicate(timeout=120)
        assert lead.returncode != 0  # it crashed, as arranged
        assert "released multihost followers" in lead_out, lead_out[-2000:]
        follower_out, _ = follower.communicate(timeout=60)
        assert follower.returncode == 0, follower_out[-2000:]
        assert "stop after" in follower_out
    finally:
        blocker.close()
        for p in (lead, follower):
            if p.poll() is None:
                p.kill()
                p.wait()
        store_handle.stop()


def test_multihost_resident_dispatcher_serves_and_stops():
    """The UNIFIED path (`--resident --multihost`): per-tick DCN traffic is
    the resident delta packet, resident state shards over the global
    2-process mesh — and the full real stack still serves, and the stop
    broadcast still releases the follower (round-4; round 3 made resident
    and multihost mutually exclusive)."""
    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    coord, zmq_port, stats_port = _free_port(), _free_port(), _free_port()
    follower = _spawn_dispatcher(
        1, coord, zmq_port, store_handle.url, "--resident"
    )
    lead = _spawn_dispatcher(
        0, coord, zmq_port, store_handle.url, "--resident",
        "--stats-port", str(stats_port),
    )
    workers = []
    try:
        workers = [
            _spawn_worker(
                "push_worker", 2, f"tcp://127.0.0.1:{zmq_port}",
                "--hb", "--hb-period", "0.3",
            )
            for _ in range(2)
        ]
        client = FaaSClient(gw.url)
        fid = client.register(lambda x: x * 11, name="mul11")
        handles = [client.submit(fid, i) for i in range(12)]
        deadline = time.time() + 180
        done = {}
        while len(done) < 12 and time.time() < deadline:
            for i, h in enumerate(handles):
                if i in done:
                    continue
                st = h.status()
                if st in ("COMPLETED", "FAILED"):
                    assert st == "COMPLETED", (i, st)
                    done[i] = h.result(timeout=5.0)
            time.sleep(0.2)
        assert len(done) == 12, f"only {len(done)}/12 completed"
        assert all(done[i] == i * 11 for i in range(12))

        # -- worker crash on the UNIFIED path: purge + in-flight
        # redistribution must ride the delta packet (heartbeat section ages
        # the dead row out on-device; the redispatch slots come back in the
        # compacted output)
        _crash_worker_and_expect_redispatch(client, workers)

        # -- cancellation on the UNIFIED path: a queued task cancelled
        # while device-resident must be dropped at placement resolve (the
        # capacity correction rides the next delta packet) — saturate the
        # surviving 2-slot worker, cancel the tasks queued behind the
        # blockers, and everything else still completes
        fid3 = client.register(sleep_task, name="blocker")
        blockers = [client.submit(fid3, 2.0) for _ in range(2)]
        deadline = time.time() + 60
        while time.time() < deadline:
            if sum(1 for h in blockers if h.status() == "RUNNING") >= 2:
                break
            time.sleep(0.1)
        else:
            raise AssertionError(
                "blockers never saturated the surviving worker"
            )
        victims = [client.submit(fid3, 0.5) for _ in range(2)]
        # cancel only once the lead provably HOLDS the victims (drained
        # off the bus into its resident state): a cancel landing before
        # intake is honored by the announce skip, which never emits the
        # "dropped cancelled task" line asserted at shutdown
        from tests.test_workers_e2e import poll_stats

        deadline = time.time() + 60
        while time.time() < deadline:
            if poll_stats(stats_port, timeout=5).get("pending", 0) >= 2:
                break
            time.sleep(0.1)
        else:
            raise AssertionError("victims never reached the lead's state")
        assert all(h.cancel() for h in victims)
        assert [h.result(timeout=60.0) for h in blockers] == [2.0, 2.0]
        time.sleep(1.0)  # let cancelled placements resolve + drop
        assert [h.status() for h in victims] == ["CANCELLED"] * 2

        # -- FORCE cancel on the UNIFIED path (round-5, VERDICT r4 next
        # #6): a task RUNNING on a worker placed by the 2-process resident
        # mesh is interrupted mid-run — the kill note rides the lead's
        # serve loop (drain_control_messages + _relay_kills between delta
        # ticks), the worker's pool interrupt frees the slot in place, and
        # the record converges to terminal CANCELLED in seconds, not the
        # task's natural 30
        from tpu_faas.client import TaskCancelledError

        fid4 = client.register(sleep_task, name="long-victim")
        long_h = client.submit(fid4, 30.0)
        deadline = time.time() + 60
        while long_h.status() != "RUNNING" and time.time() < deadline:
            time.sleep(0.1)
        assert long_h.status() == "RUNNING"
        t0 = time.time()
        assert long_h.cancel(force=True) is False  # async: not yet terminal
        try:
            long_h.result(timeout=30.0)
            raise AssertionError("force-cancelled task returned a result")
        except TaskCancelledError:
            pass
        assert time.time() - t0 < 25.0  # interrupted, not waited out
        assert long_h.status() == "CANCELLED"
        # the interrupted slot is free again on the resident mesh: a
        # follow-up task completes promptly
        follow = client.submit(fid4, 0.2)
        assert follow.result(timeout=60.0) == 0.2

        # shutdown contract: SIGTERM the lead right after activity (the
        # timing that once collided a mismatched stop broadcast); the
        # resident stop packet must release the follower cleanly
        os.kill(lead.pid, signal.SIGTERM)
        lead_out, _ = lead.communicate(timeout=60)
        assert lead.returncode == 0, lead_out[-2000:]
        assert "purged worker row" in lead_out, lead_out[-2000:]
        assert "reclaimed" in lead_out, lead_out[-2000:]
        assert "dropped cancelled task" in lead_out, lead_out[-2000:]
        assert "relayed force-cancel" in lead_out, lead_out[-2000:]
        assert "stop broadcast sent" in lead_out, lead_out[-2000:]
        follower_out, _ = follower.communicate(timeout=60)
        assert follower.returncode == 0, follower_out[-2000:]
        assert "stop after" in follower_out, follower_out[-1500:]
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.wait()
        for p in (lead, follower):
            if p.poll() is None:
                p.kill()
                p.wait()
        gw.stop()
        store_handle.stop()
