"""Graceful worker drain: SIGTERM finishes in-flight tasks and deregisters,
instead of dropping them for heartbeat-timeout purge + re-dispatch to
recover. time_to_expire is set high in these tests, so if drain were broken
the killed worker's tasks could not complete within the poll timeout — the
crash-recovery path cannot silently stand in for the drain path.

(The reference has no graceful shutdown at all: its workers die mid-task and
its dispatcher loses the work, SURVEY §5.3.)
"""

from __future__ import annotations

import signal
import threading
import time

from tpu_faas.client import FaaSClient
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store.launch import make_store, start_store_thread
from tpu_faas.workloads import sleep_task
from tests.test_tpu_push_e2e import _make_dispatcher
from tests.test_workers_e2e import _spawn_worker, stack


def _drain_scenario(client: FaaSClient, workers: list) -> None:
    """Submit slow tasks, SIGTERM worker[0] once tasks are RUNNING (i.e. the
    workers are fully up — a signal during interpreter startup is the crash
    path, not the drain path), require every result AND a clean worker exit
    well before any timeout-based recovery."""
    fid = client.register(sleep_task)
    handles = [client.submit(fid, 2.0) for _ in range(8)]
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        running = sum(h.status() == "RUNNING" for h in handles)
        if running >= 3:  # both 2-proc workers necessarily hold tasks
            break
        time.sleep(0.05)
    else:
        raise AssertionError("tasks never started RUNNING")
    workers[0].send_signal(signal.SIGTERM)
    for h in handles:
        assert h.result(timeout=40.0) == 2.0
    assert workers[0].wait(timeout=10.0) == 0


def test_push_hb_graceful_drain():
    with stack(
        "push", n_workers=2, n_procs=2, heartbeat=True, time_to_expire=60.0
    ) as (client, workers, disp):
        _drain_scenario(client, workers)
        # drained worker's record is gone without any purge
        assert len(disp.workers) == 1


def test_tpu_push_graceful_drain():
    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    disp = _make_dispatcher(store_handle.url, time_to_expire=60.0)
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
        for _ in range(2)
    ]
    try:
        _drain_scenario(FaaSClient(gw.url), workers)

        # The result handler writes the store record (which is what
        # unblocks _drain_scenario's client polls) BEFORE popping the
        # in-flight entry, and the drained worker's DEREGISTER may still
        # sit in the recv queue — so these table states trail the client's
        # view by one handler invocation. Poll briefly instead of racing.
        def settled():
            rows = list(disp.arrays.worker_ids.values())
            procs = sorted(int(disp.arrays.worker_procs[r]) for r in rows)
            return disp.arrays.n_inflight == 0 and procs == [0, 2]

        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not settled():
            time.sleep(0.02)
        assert disp.arrays.n_inflight == 0
        # exactly one row (the drained worker's) had its capacity zeroed by
        # the DEREGISTER handler; the survivor keeps its 2 processes
        rows = list(disp.arrays.worker_ids.values())
        procs = [int(disp.arrays.worker_procs[r]) for r in rows]
        assert sorted(procs) == [0, 2], procs
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def test_pull_graceful_drain():
    with stack("pull", n_workers=2, n_procs=2) as (client, workers, _disp):
        _drain_scenario(client, workers)


def test_push_hb_drain_longer_than_time_to_expire_does_not_purge():
    """A drain outlasting time_to_expire must NOT be purged: the draining
    worker keeps heartbeating while tasks are in flight (silence would mean
    false purge + duplicate execution — the churn drain exists to avoid)."""
    with stack(
        "push", n_workers=2, n_procs=2, heartbeat=True, time_to_expire=1.5
    ) as (client, workers, disp):
        fid = client.register(sleep_task)
        handles = [client.submit(fid, 4.0) for _ in range(8)]
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if sum(h.status() == "RUNNING" for h in handles) >= 3:
                break
            time.sleep(0.05)
        workers[0].send_signal(signal.SIGTERM)
        for h in handles:
            assert h.result(timeout=40.0) == 4.0
        assert workers[0].wait(timeout=10.0) == 0
        assert disp.n_purged == 0, "draining worker was falsely purged"
