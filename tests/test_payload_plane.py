"""Content-addressed payload plane: codec core, blob store protocol,
gateway dedup, dispatcher resolution, worker codec cache + MISS/FILL,
binary framing negotiation, SDK memoization — unit through full-stack e2e.
"""

from __future__ import annotations

import threading
import time

import pytest

from tests.test_workers_e2e import _spawn_worker
from tpu_faas.client import FaaSClient
from tpu_faas.core.payload import PayloadLRU, payload_digest
from tpu_faas.core.serialize import serialize
from tpu_faas.core.task import (
    FIELD_FN,
    FIELD_FN_DIGEST,
    FIELD_PARAMS,
    FIELD_STATUS,
    TaskStatus,
)
from tpu_faas.dispatch.base import PendingTask
from tpu_faas.dispatch.local import LocalDispatcher
from tpu_faas.dispatch.pull import PullDispatcher
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store.base import BLOB_AT_FIELD, BLOB_DATA_FIELD, blob_key
from tpu_faas.store.launch import make_store, start_store_thread
from tpu_faas.store.memory import MemoryStore
from tpu_faas.store.racecheck import RaceCheckStore, RaceMonitor
from tpu_faas.worker import messages as m
from tpu_faas.worker.pull_worker import PullWorker
from tpu_faas.worker.push_worker import PushWorker
from tpu_faas.workloads import arithmetic


# -- codec core --------------------------------------------------------------


def test_payload_digest_is_sha256_hex():
    d = payload_digest("hello")
    assert len(d) == 64 and int(d, 16) >= 0
    assert d == payload_digest("hello")
    assert d != payload_digest("hello2")


def test_payload_lru_byte_bound_and_order():
    lru = PayloadLRU(max_bytes=10)
    lru.put("a", "12345")
    lru.put("b", "12345")
    assert lru.n_bytes == 10 and len(lru) == 2
    assert lru.get("a") == "12345"  # refresh a: b is now LRU
    lru.put("c", "123")
    assert "b" not in lru and "a" in lru and "c" in lru
    # an oversized payload is still admitted, alone
    lru.put("big", "x" * 100)
    assert lru.get("big") == "x" * 100 and len(lru) == 1


def test_payload_lru_counts_hits_and_misses():
    lru = PayloadLRU()
    assert lru.get("nope") is None
    lru.put("d", "data")
    assert lru.get("d") == "data"
    assert lru.hits == 1 and lru.misses == 1


# -- store blob namespace ----------------------------------------------------


def test_put_blob_is_create_once_and_stamps_ttl():
    store = MemoryStore()
    d = payload_digest("BODY")
    assert store.put_blob(d, "BODY") is True
    stamp1 = store.hget(blob_key(d), BLOB_AT_FIELD)
    assert store.get_blob(d) == "BODY"
    # second put: loses the data claim, refreshes the stamp
    time.sleep(0.01)
    assert store.put_blob(d, "BODY") is False
    assert store.get_blob(d) == "BODY"
    assert store.hget(blob_key(d), BLOB_AT_FIELD) != stamp1


def test_get_blobs_multi_and_missing():
    store = MemoryStore()
    d1, d2 = payload_digest("one"), payload_digest("two")
    store.put_blob(d1, "one")
    assert store.get_blobs([d1, d2, d1]) == ["one", None, "one"]


def test_resp_store_blob_roundtrip():
    handle = start_store_thread()
    store = make_store(handle.url)
    try:
        d = payload_digest("RESP-BODY")
        assert store.put_blob(d, "RESP-BODY") is True
        assert store.put_blob(d, "RESP-BODY") is False
        assert store.get_blob(d) == "RESP-BODY"
        assert store.get_blobs([d, payload_digest("x")]) == ["RESP-BODY", None]
        assert store.n_bytes_sent > 0  # the bench lane's bytes counter
    finally:
        store.close()
        handle.stop()


# -- race monitor: blob create-once ------------------------------------------


def test_race_monitor_put_blob_clean():
    monitor = RaceMonitor()
    store = RaceCheckStore(MemoryStore(), monitor, actor="gw")
    d = payload_digest("CONTENT")
    store.put_blob(d, "CONTENT")
    store.put_blob(d, "CONTENT")  # dedup repeat: no second data write
    monitor.assert_clean()


def test_race_monitor_flags_blob_digest_mismatch():
    monitor = RaceMonitor()
    store = RaceCheckStore(MemoryStore(), monitor, actor="rogue")
    store.hset(blob_key(payload_digest("real")), {BLOB_DATA_FIELD: "fake"})
    kinds = [v.kind for v in monitor.errors]
    assert "blob-digest-mismatch" in kinds


def test_race_monitor_flags_blob_overwrite():
    monitor = RaceMonitor()
    store = RaceCheckStore(MemoryStore(), monitor, actor="rogue")
    d = payload_digest("v1")
    store.hset(blob_key(d), {BLOB_DATA_FIELD: "v1"})
    monitor.assert_clean()  # honest first write
    store.hset(blob_key(d), {BLOB_DATA_FIELD: "v2"})  # bypassed setnx
    kinds = [v.kind for v in monitor.errors]
    assert "blob-overwrite" in kinds


def test_race_monitor_blob_stamp_refresh_is_not_a_task_write():
    monitor = RaceMonitor()
    store = RaceCheckStore(MemoryStore(), monitor, actor="gw")
    store.hset(blob_key(payload_digest("b")), {BLOB_AT_FIELD: "123.0"})
    monitor.assert_clean()
    assert monitor.unfinished() == []  # never mistaken for a task record


# -- wire framing ------------------------------------------------------------


def test_binary_frame_roundtrip_and_sniffing():
    ascii_raw = m.encode(m.TASK, task_id="t", fn_payload="F", param_payload="P")
    bin_raw = m.encode_bin(m.TASK, task_id="t", fn_digest="d" * 64,
                           param_payload="P")
    assert not m.is_binary(ascii_raw) and m.is_binary(bin_raw)
    assert m.decode(ascii_raw)[1]["fn_payload"] == "F"
    assert m.decode(bin_raw)[1]["fn_digest"] == "d" * 64
    # encode_for routes by negotiation state
    assert m.is_binary(m.encode_for(True, m.WAIT))
    assert not m.is_binary(m.encode_for(False, m.WAIT))


def test_binary_frame_smaller_than_ascii_for_payloads():
    kw = dict(task_id="t", fn_payload="A" * 4096, param_payload="P" * 512)
    assert len(m.encode_bin(m.TASK, **kw)) < 0.8 * len(m.encode(m.TASK, **kw))


def test_caps_of_tolerates_garbage():
    assert m.caps_of({}) == frozenset()
    assert m.caps_of({"caps": "blob"}) == frozenset()
    assert m.caps_of({"caps": ["blob", 7, "bin"]}) == {"blob", "bin"}


# -- executor child cache ----------------------------------------------------


def test_executor_fn_cache_skips_repeat_decode():
    from tpu_faas.core import executor

    payload = serialize(lambda x: x * 3)
    digest = payload_digest(payload)
    executor._FN_CACHE.clear()
    fn1 = executor._cached_fn(payload, digest)
    fn2 = executor._cached_fn("GARBAGE-NEVER-DECODED", digest)
    assert fn1 is fn2 and fn2(7) == 21  # second call never touched dill
    # digest-less callers bypass the cache entirely
    assert executor._cached_fn(payload, None)(2) == 6
    executor._FN_CACHE.clear()


def test_executor_fn_cache_bounded():
    from tpu_faas.core import executor

    executor._FN_CACHE.clear()
    payload = serialize(lambda: None)
    for i in range(executor._FN_CACHE_CAP + 10):
        executor._cached_fn(payload, f"digest-{i}")
    assert len(executor._FN_CACHE) == executor._FN_CACHE_CAP
    executor._FN_CACHE.clear()


# -- gateway: payload-plane mode ---------------------------------------------


def _submit_and_read(store, gw_url, payload="PARAMS"):
    client = FaaSClient(gw_url, auto_idempotency=False)
    fid = client.register_payload("fn", "FNBODY-" + "x" * 64)
    tid = client.execute_payload(fid, payload)
    return fid, tid, store.hgetall(tid)


def test_gateway_plane_off_keeps_inline_contract():
    store = MemoryStore()
    gw = start_gateway_thread(store)  # default: plane off
    try:
        _fid, _tid, fields = _submit_and_read(store, gw.url)
        assert fields[FIELD_FN].startswith("FNBODY-")
        assert FIELD_FN_DIGEST not in fields
    finally:
        gw.stop()


def test_gateway_plane_writes_digest_records_and_blob_once():
    store = MemoryStore()
    gw = start_gateway_thread(store, payload_plane=True)
    try:
        fid, tid, fields = _submit_and_read(store, gw.url)
        body = "FNBODY-" + "x" * 64
        digest = payload_digest(body)
        assert fields[FIELD_FN] == ""
        assert fields[FIELD_FN_DIGEST] == digest
        assert fields[FIELD_PARAMS] == "PARAMS"
        assert store.get_blob(digest) == body
        # batch submits carry the digest too
        client = FaaSClient(gw.url, auto_idempotency=False)
        handles = client.submit_many(fid, [((i,), {}) for i in range(5)])
        for h in handles:
            rec = store.hgetall(h.task_id)
            assert rec[FIELD_FN_DIGEST] == digest and rec[FIELD_FN] == ""
    finally:
        gw.stop()


def test_gateway_register_once_dedups_by_content():
    store = MemoryStore()
    gw = start_gateway_thread(store, payload_plane=True)
    try:
        client = FaaSClient(gw.url)
        fid1 = client.register_payload("a", "SAME-BODY")
        fid2 = client.register_payload("b", "SAME-BODY")
        assert fid1 == fid2  # content dedup, names notwithstanding
        fid3 = client.register_payload("a", "OTHER-BODY")
        assert fid3 != fid1
    finally:
        gw.stop()


def test_gateway_dedup_repairs_missing_registry_record():
    """A claim winner that died between its digest-index setnx and its
    registry hset must not poison the digest forever: the next
    registration of the same bytes adopts the claimed id AND repairs the
    missing function-registry record, so submits of it resolve."""
    from tpu_faas.gateway.app import _FN_INDEX_PREFIX, _FUNCTION_PREFIX

    store = MemoryStore()
    gw = start_gateway_thread(store, payload_plane=True)
    try:
        # simulate the dead winner: index claimed, registry never written
        digest = payload_digest("ORPHAN-BODY")
        store.setnx_field(
            _FN_INDEX_PREFIX + digest, "function_id", "orphan-fid"
        )
        client = FaaSClient(gw.url)
        fid = client.register_payload("repaired", "ORPHAN-BODY")
        assert fid == "orphan-fid"  # adopted the winner's claim...
        rec = store.hgetall(_FUNCTION_PREFIX + "orphan-fid")
        # ...and wrote the record the winner never did
        assert rec["payload"] == "ORPHAN-BODY"
        assert rec["payload_digest"] == digest
        assert store.get_blob(digest) == "ORPHAN-BODY"
        # a submit of the repaired function now resolves
        h = client.submit(fid)
        assert store.hgetall(h.task_id)[FIELD_FN_DIGEST] == digest
    finally:
        gw.stop()


def test_blob_gc_spares_referenced_blobs():
    from tpu_faas.gateway.app import _sweep_expired_results

    store = MemoryStore()
    now = time.time()
    old = repr(now - 10_000.0)
    # referenced by the function registry: kept however stale
    d_fn = payload_digest("REGISTERED")
    store.put_blob(d_fn, "REGISTERED")
    store.hset(blob_key(d_fn), {BLOB_AT_FIELD: old})
    store.hset("function:f1", {"payload": "REGISTERED", "payload_digest": d_fn})
    # referenced by a LIVE task: kept
    d_live = payload_digest("LIVEREF")
    store.put_blob(d_live, "LIVEREF")
    store.hset(blob_key(d_live), {BLOB_AT_FIELD: old})
    store.create_task("t-live", "", "P", extra_fields={FIELD_FN_DIGEST: d_live})
    # unreferenced + stale: collected
    d_orphan = payload_digest("ORPHAN")
    store.put_blob(d_orphan, "ORPHAN")
    store.hset(blob_key(d_orphan), {BLOB_AT_FIELD: old})
    # unreferenced but FRESH: kept (TTL half of the policy)
    d_fresh = payload_digest("FRESH")
    store.put_blob(d_fresh, "FRESH")
    _sweep_expired_results(store, ttl=60.0, now=now)
    assert store.get_blob(d_fn) == "REGISTERED"
    assert store.get_blob(d_live) == "LIVEREF"
    assert store.get_blob(d_orphan) is None
    assert store.get_blob(d_fresh) == "FRESH"


# -- dispatcher resolution ---------------------------------------------------


def _digest_task(store, task_id, body="DIGEST-BODY", params="P"):
    digest = payload_digest(body)
    store.put_blob(digest, body)
    store.create_task(
        task_id, "", params, extra_fields={FIELD_FN_DIGEST: digest}
    )
    return digest


def test_intake_accepts_digest_records():
    store = MemoryStore()
    disp = LocalDispatcher(store=store)
    try:
        _digest_task(store, "t1")
        task = disp.poll_next_task()
        assert task is not None and task.task_id == "t1"
        assert task.fn_digest == payload_digest("DIGEST-BODY")
        assert task.fn_payload == ""
    finally:
        disp.close()


def test_ensure_inline_payload_caches_blob():
    store = MemoryStore()
    disp = LocalDispatcher(store=store)
    try:
        d = _digest_task(store, "t1")
        _digest_task(store, "t2")
        t1 = PendingTask("t1", "", "P", fn_digest=d)
        t2 = PendingTask("t2", "", "P", fn_digest=d)
        assert disp.ensure_inline_payload(t1) and t1.fn_payload == "DIGEST-BODY"
        assert disp.ensure_inline_payload(t2) and t2.fn_payload == "DIGEST-BODY"
        assert disp.blob_cache.misses == 1 and disp.blob_cache.hits == 1
    finally:
        disp.close()


def test_missing_blob_fails_task_instead_of_wedging():
    store = MemoryStore()
    disp = LocalDispatcher(store=store)
    try:
        ghost = payload_digest("never-written")
        store.create_task(
            "t1", "", "P", extra_fields={FIELD_FN_DIGEST: ghost}
        )
        t = PendingTask("t1", "", "P", fn_digest=ghost)
        assert disp.ensure_inline_payload(t) is False
        assert store.get_status("t1") == str(TaskStatus.FAILED)
    finally:
        disp.close()


def test_local_dispatcher_executes_digest_tasks():
    store = MemoryStore()
    disp = LocalDispatcher(num_workers=2, store=store)
    try:
        body = serialize(arithmetic)
        digest = payload_digest(body)
        store.put_blob(digest, body)
        from tpu_faas.core.executor import pack_params

        for i in range(4):
            store.create_task(
                f"t{i}", "", pack_params(50), extra_fields={
                    FIELD_FN_DIGEST: digest
                },
            )
        done = disp.start(max_tasks=4)
        assert done == 4
        for i in range(4):
            status, _result = store.get_result(f"t{i}")
            assert status == str(TaskStatus.COMPLETED)
    finally:
        disp.close()


# -- SDK memoization ---------------------------------------------------------


def test_sdk_register_memoizes_serialize_and_registration():
    store = MemoryStore()
    gw = start_gateway_thread(store, payload_plane=True)
    try:
        client = FaaSClient(gw.url)

        def fn(x):
            return x + 1

        fid1 = client.register(fn)
        fid2 = client.register(fn)  # no HTTP round trip at all
        assert fid1 == fid2
        # exactly one function registered gateway-side
        fn_keys = [k for k in store.keys() if k.startswith("function:")]
        assert len(fn_keys) == 1
    finally:
        gw.stop()


def test_fn_memo_id_recycling_is_safe():
    from tpu_faas.client.sdk import _FnMemo

    memo = _FnMemo()

    def a(x):
        return x

    p1 = memo.serialize_fn(a)
    assert memo.serialize_fn(a) == p1  # hit
    # a DIFFERENT callable must never be served a's bytes, whatever id()
    def b(x):
        return x * 2

    assert memo.serialize_fn(b) != p1 or serialize(b) == p1


# -- push path e2e: digest shipping, MISS/FILL, binary framing ---------------


def test_push_worker_in_process_blob_flow():
    """In-process PushWorker against a PushDispatcher: REGISTER advertises
    caps, the dispatcher ships digests, the worker's payload cache misses
    once (BLOB_MISS/BLOB_FILL round), then hits; frames go binary after
    negotiation; results land correctly."""
    from tpu_faas.dispatch.push import PushDispatcher
    from tpu_faas.core.executor import pack_params

    store = MemoryStore()
    disp = PushDispatcher(ip="127.0.0.1", port=0, store=store)
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    body = serialize(arithmetic)
    digest = payload_digest(body)
    store.put_blob(digest, body)
    for i in range(6):
        store.create_task(
            f"t{i}", "", pack_params(40), extra_fields={
                FIELD_FN_DIGEST: digest
            },
        )
    worker = PushWorker(2, f"tcp://127.0.0.1:{disp.port}", heartbeat=True,
                        heartbeat_period=0.2)
    try:
        shipped = worker.run(max_tasks=6)
        assert shipped == 6
        deadline = time.monotonic() + 15.0
        while disp.n_results < 6 and time.monotonic() < deadline:
            time.sleep(0.02)  # dispatcher drains the last RESULTs async
        for i in range(6):
            status, _ = store.get_result(f"t{i}")
            assert status == str(TaskStatus.COMPLETED)
        # the payload plane engaged end to end (several tasks can arrive
        # before the first FILL lands — each counts a miss; only ONE
        # MISS/FILL round happens per digest, which m_blob_fills pins)
        assert worker.fn_cache.misses >= 1
        assert worker.fn_cache.hits >= 1
        assert worker._peer_bin  # binary framing negotiated
        assert disp.m_blob_fills.value >= 1
        # digests shipped: wire payload bytes exclude the body after fill
        assert disp.m_payload_bytes.value < 6 * len(body)
    finally:
        worker.stop()
        disp.stop()
        t.join(timeout=10)
        disp.close()


def test_pull_worker_in_process_blob_flow():
    """Pull mode: digest-only TASK replies, synchronous BLOB_MISS
    transaction on the first miss, cached afterwards."""
    from tpu_faas.core.executor import pack_params

    store = MemoryStore()
    disp = PullDispatcher(ip="127.0.0.1", port=0, store=store)
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    body = serialize(arithmetic)
    digest = payload_digest(body)
    store.put_blob(digest, body)
    for i in range(4):
        store.create_task(
            f"t{i}", "", pack_params(30), extra_fields={
                FIELD_FN_DIGEST: digest
            },
        )
    worker = PullWorker(2, f"tcp://127.0.0.1:{disp.port}", delay=0.005)
    try:
        shipped = worker.run(max_tasks=4)
        assert shipped == 4
        for i in range(4):
            status, _ = store.get_result(f"t{i}")
            assert status == str(TaskStatus.COMPLETED)
        assert worker.fn_cache.misses == 1 and worker.fn_cache.hits >= 1
    finally:
        worker.stop()
        disp.stop()
        t.join(timeout=10)
        disp.close()


def test_legacy_worker_gets_inline_payloads():
    """A worker WITHOUT caps (reference contract) served digest tasks:
    the dispatcher materializes the body inline — same results, no
    payload-plane message ever reaches the worker."""
    from tpu_faas.dispatch.push import PushDispatcher
    from tpu_faas.core.executor import pack_params

    store = MemoryStore()
    disp = PushDispatcher(ip="127.0.0.1", port=0, store=store)
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    body = serialize(arithmetic)
    digest = payload_digest(body)
    store.put_blob(digest, body)
    for i in range(4):
        store.create_task(
            f"t{i}", "", pack_params(25), extra_fields={
                FIELD_FN_DIGEST: digest
            },
        )
    worker = PushWorker(2, f"tcp://127.0.0.1:{disp.port}", heartbeat=True,
                        heartbeat_period=0.2, caps=())
    try:
        shipped = worker.run(max_tasks=4)
        assert shipped == 4
        deadline = time.monotonic() + 15.0
        while disp.n_results < 4 and time.monotonic() < deadline:
            time.sleep(0.02)
        for i in range(4):
            status, _ = store.get_result(f"t{i}")
            assert status == str(TaskStatus.COMPLETED)
        # nothing payload-plane-shaped touched the worker
        assert worker.fn_cache.hits == 0 and worker.fn_cache.misses == 0
        assert not worker._peer_bin
        # dispatcher resolved the body once, served it inline per task
        assert disp.blob_cache.misses == 1
    finally:
        worker.stop()
        disp.stop()
        t.join(timeout=10)
        disp.close()


def test_full_stack_payload_plane_e2e():
    """Gateway (payload_plane=True) -> store server -> tpu-push dispatcher
    -> real push-worker subprocesses: one function, a burst of tasks, all
    results correct, fn body written to the store ONCE, dispatch shipping
    digests (race-monitored clean)."""
    from tests.test_tpu_push_e2e import _make_dispatcher

    monitor = RaceMonitor()
    store_handle = start_store_thread()
    gw_store = RaceCheckStore(
        make_store(store_handle.url), monitor, actor="gateway"
    )
    gw = start_gateway_thread(gw_store, payload_plane=True)
    disp = _make_dispatcher(
        store_handle.url,
        store=RaceCheckStore(
            make_store(store_handle.url), monitor, actor="dispatcher"
        ),
    )
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
        for _ in range(2)
    ]
    client = FaaSClient(gw.url)
    try:
        fid = client.register(arithmetic)
        handles = client.submit_many(fid, [((100 + i,), {}) for i in range(24)])
        values = [h.result(timeout=90.0) for h in handles]
        assert values == [arithmetic(100 + i) for i in range(24)]
        # every record carried the digest, not the body
        probe = make_store(store_handle.url)
        try:
            rec = probe.hgetall(handles[0].task_id)
            assert rec[FIELD_FN] == "" and rec[FIELD_FN_DIGEST]
            assert probe.get_blob(rec[FIELD_FN_DIGEST]) is not None
        finally:
            probe.close()
        monitor.assert_clean(allow_warnings=True)
        assert not monitor.errors
    finally:
        for w in workers:
            w.kill()
            w.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def test_reclaim_preserves_digest():
    """A reclaimed digest task rebuilds with its digest (RECLAIM_FIELDS),
    so re-dispatch keeps riding the payload plane."""
    store = MemoryStore()
    disp = LocalDispatcher(store=store)
    try:
        d = _digest_task(store, "t1")
        store.set_status("t1", TaskStatus.RUNNING)
        pt = disp.fetch_reclaim("t1", retries=1)
        assert pt is not None and pt.fn_digest == d and pt.retries == 1
    finally:
        disp.close()
