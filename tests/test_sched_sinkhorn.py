"""Sinkhorn placement kernel: invariants, marginals, heterogeneity behavior."""

import numpy as np
import pytest

from tpu_faas.sched.oracle import optimal_assignment
from tpu_faas.sched.problem import PlacementProblem, check_assignment
from tpu_faas.sched.sinkhorn import sinkhorn_placement


def _run(sizes, speeds, free, live, **kw):
    p = PlacementProblem.build(sizes, speeds, free, live)
    res = sinkhorn_placement(
        p.task_size, p.task_valid, p.worker_speed, p.worker_free,
        p.worker_live, **kw,
    )
    return p, np.asarray(res.assignment), res


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_sinkhorn_invariants_random(seed):
    rng = np.random.default_rng(seed)
    sizes = rng.uniform(0.5, 5.0, 80).astype(np.float32)
    speeds = rng.uniform(0.5, 4.0, 24).astype(np.float32)
    free = rng.integers(0, 6, 24).astype(np.int32)
    live = rng.random(24) > 0.25
    p, a, res = _run(sizes, speeds, free, live)
    check_assignment(
        a, np.asarray(p.task_valid), np.asarray(p.worker_free),
        np.asarray(p.worker_live),
    )
    assert float(res.marginal_err) < 0.05


def test_sinkhorn_full_placement_when_capacity_ample():
    rng = np.random.default_rng(3)
    sizes = rng.uniform(0.5, 5.0, 30).astype(np.float32)
    speeds = rng.uniform(1.0, 2.0, 10).astype(np.float32)
    free = np.full(10, 8, dtype=np.int32)
    live = np.ones(10, dtype=bool)
    _, a, _ = _run(sizes, speeds, free, live)
    assert (a[:30] >= 0).all()


def test_sinkhorn_overflow_stays_queued():
    # 3 slots total, 10 tasks: exactly 3 placed
    sizes = np.ones(10, dtype=np.float32)
    _, a, _ = _run(sizes, [1.0, 1.0], [2, 1], [True, True])
    assert (a[:10] >= 0).sum() == 3


def test_sinkhorn_prefers_fast_workers():
    # equal-size tasks, worker 0 4x faster, capacity not binding:
    # the fast worker should receive more tasks
    sizes = np.ones(12, dtype=np.float32)
    _, a, _ = _run(sizes, [4.0, 1.0], [8, 8], [True, True], tau=0.05)
    placed = a[:12]
    assert (placed >= 0).all()
    assert (placed == 0).sum() > (placed == 1).sum()


def test_sinkhorn_near_oracle_cost():
    """Total cost within a modest factor of the exact assignment (entropic
    smoothing trades a little cost for spreading)."""
    rng = np.random.default_rng(9)
    n = 40
    sizes = rng.uniform(0.5, 6.0, n).astype(np.float32)
    speeds = rng.uniform(0.5, 4.0, 12).astype(np.float32)
    free = np.full(12, 4, dtype=np.int32)
    live = np.ones(12, dtype=bool)
    _, a, _ = _run(sizes, speeds, free, live, tau=0.01, n_iters=200, max_slots=4)
    placed = a[:n] >= 0
    assert placed.all()
    cost = float(np.sum(sizes[placed] / speeds[a[:n][placed]]))
    _, cost_opt = optimal_assignment(sizes, speeds, free, live, max_slots=4)
    assert cost <= cost_opt * 1.10


def test_sinkhorn_dead_fleet():
    _, a, _ = _run([1.0, 2.0], [1.0, 1.0], [4, 4], [False, False])
    assert (a == -1).all()
