"""Sinkhorn placement kernel: invariants, marginals, heterogeneity behavior."""

import numpy as np
import pytest

from tpu_faas.sched.oracle import optimal_assignment
from tpu_faas.sched.problem import PlacementProblem, check_assignment
from tpu_faas.sched.sinkhorn import sinkhorn_placement


def _run(sizes, speeds, free, live, **kw):
    p = PlacementProblem.build(sizes, speeds, free, live)
    res = sinkhorn_placement(
        p.task_size, p.task_valid, p.worker_speed, p.worker_free,
        p.worker_live, **kw,
    )
    return p, np.asarray(res.assignment), res


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_sinkhorn_invariants_random(seed):
    rng = np.random.default_rng(seed)
    sizes = rng.uniform(0.5, 5.0, 80).astype(np.float32)
    speeds = rng.uniform(0.5, 4.0, 24).astype(np.float32)
    free = rng.integers(0, 6, 24).astype(np.int32)
    live = rng.random(24) > 0.25
    p, a, res = _run(sizes, speeds, free, live)
    check_assignment(
        a, np.asarray(p.task_valid), np.asarray(p.worker_free),
        np.asarray(p.worker_live),
    )
    assert float(res.marginal_err) < 0.05


def test_sinkhorn_full_placement_when_capacity_ample():
    rng = np.random.default_rng(3)
    sizes = rng.uniform(0.5, 5.0, 30).astype(np.float32)
    speeds = rng.uniform(1.0, 2.0, 10).astype(np.float32)
    free = np.full(10, 8, dtype=np.int32)
    live = np.ones(10, dtype=bool)
    _, a, _ = _run(sizes, speeds, free, live)
    assert (a[:30] >= 0).all()


def test_sinkhorn_overflow_stays_queued():
    # 3 slots total, 10 tasks: exactly 3 placed
    sizes = np.ones(10, dtype=np.float32)
    _, a, _ = _run(sizes, [1.0, 1.0], [2, 1], [True, True])
    assert (a[:10] >= 0).sum() == 3


def test_sinkhorn_prefers_fast_workers():
    # equal-size tasks, worker 0 4x faster, capacity not binding:
    # the fast worker should receive more tasks
    sizes = np.ones(12, dtype=np.float32)
    _, a, _ = _run(sizes, [4.0, 1.0], [8, 8], [True, True], tau=0.05)
    placed = a[:12]
    assert (placed >= 0).all()
    assert (placed == 0).sum() > (placed == 1).sum()


def test_sinkhorn_near_oracle_cost():
    """Total cost within a modest factor of the exact assignment (entropic
    smoothing trades a little cost for spreading)."""
    rng = np.random.default_rng(9)
    n = 40
    sizes = rng.uniform(0.5, 6.0, n).astype(np.float32)
    speeds = rng.uniform(0.5, 4.0, 12).astype(np.float32)
    free = np.full(12, 4, dtype=np.int32)
    live = np.ones(12, dtype=bool)
    _, a, _ = _run(sizes, speeds, free, live, tau=0.01, n_iters=200, max_slots=4)
    placed = a[:n] >= 0
    assert placed.all()
    cost = float(np.sum(sizes[placed] / speeds[a[:n][placed]]))
    _, cost_opt = optimal_assignment(sizes, speeds, free, live, max_slots=4)
    assert cost <= cost_opt * 1.10


def test_sinkhorn_dead_fleet():
    _, a, _ = _run([1.0, 2.0], [1.0, 1.0], [4, 4], [False, False])
    assert (a == -1).all()


@pytest.mark.parametrize(
    "dist",
    ["uniform", "lognormal", "bytes"],
    ids=["uniform", "lognormal", "payload-bytes-5-decades"],
)
@pytest.mark.parametrize("kernel", ["bucketed", "streamed"])
def test_memory_bounded_kernels_match_dense(dist, kernel):
    """The two kernels that avoid the [T, W] plan — bucketed (task-axis
    compression via the rank-one cost) and streamed (chunked online
    logsumexp) — place the same COUNT at within 1% of the dense kernel's
    total cost, across size distributions spanning five decades (the
    scale-free tau makes all three kernels unit-agnostic)."""
    from tpu_faas.sched.sinkhorn import (
        sinkhorn_placement_bucketed,
        sinkhorn_placement_streamed,
    )

    rng = np.random.default_rng(17)
    T, W = 768, 64
    sizes = {
        "uniform": rng.uniform(0.3, 6.0, T),
        "lognormal": rng.lognormal(0.0, 1.5, T),
        "bytes": 10 ** rng.uniform(1, 6, T),
    }[dist].astype(np.float32)
    speeds = rng.uniform(0.5, 4.0, W).astype(np.float32)
    free = rng.integers(0, 6, W).astype(np.int32)
    live = rng.random(W) > 0.2
    p = PlacementProblem.build(sizes, speeds, free, live, T=T, W=W)
    args = (
        p.task_size, p.task_valid, p.worker_speed, p.worker_free,
        p.worker_live,
    )
    dense = sinkhorn_placement(*args, max_slots=4)
    if kernel == "bucketed":
        other = sinkhorn_placement_bucketed(*args, max_slots=4, chunk=256)
    else:
        other = sinkhorn_placement_streamed(*args, max_slots=4, chunk=256)
    a_d = np.asarray(dense.assignment)
    a_o = np.asarray(other.assignment)
    check_assignment(
        a_o, np.asarray(p.task_valid), np.asarray(p.worker_free),
        np.asarray(p.worker_live),
    )
    assert (a_o >= 0).sum() == (a_d >= 0).sum()

    def cost(a):
        placed = a >= 0
        return float(np.sum(sizes[placed[:T]] / speeds[a[:T][placed[:T]]]))

    assert cost(a_o) <= 1.01 * cost(a_d)
    assert float(other.marginal_err) < 0.05


def test_bucketed_col_err_meaningful_with_excess_capacity():
    """With total capacity far above the task count the slack ROW carries
    the leftover column mass; the convergence metric must fold it in —
    before the fix a perfectly converged run read marginal_err ~1.0 here
    (advisor r2), making the metric useless for alarming."""
    from tpu_faas.sched.sinkhorn import sinkhorn_placement_bucketed

    rng = np.random.default_rng(11)
    T, W = 64, 128  # 64 tasks on 512 slots
    res = sinkhorn_placement_bucketed(
        np.asarray(rng.uniform(0.1, 5.0, T), dtype=np.float32),
        np.ones(T, dtype=bool),
        np.asarray(rng.uniform(0.5, 4.0, W), dtype=np.float32),
        np.full(W, 4, dtype=np.int32),
        np.ones(W, dtype=bool),
        max_slots=8,
    )
    assert (np.asarray(res.assignment) >= 0).sum() == T
    assert float(res.marginal_err) < 0.05


def test_scheduler_tick_uses_bucketed_at_headline_scale():
    """placement='sinkhorn' must stay runnable at shapes where the dense
    plan would not fit one chip: the tick's branch on T*W routes to the
    bucketed kernel (verified small here; the real 50k x 4k shape runs in
    bench config 4)."""
    import jax.numpy as jnp

    from tpu_faas.sched.state import scheduler_tick

    T, W = 8192, 2049  # T*W just over the 2**24 routing threshold
    rng = np.random.default_rng(5)
    free = rng.integers(0, 4, W).astype(np.int32)
    out = scheduler_tick(
        jnp.asarray(rng.uniform(0.5, 5.0, T).astype(np.float32)),
        jnp.ones(T, dtype=bool),
        jnp.asarray(rng.uniform(0.5, 4.0, W).astype(np.float32)),
        jnp.asarray(free),
        jnp.ones(W, dtype=bool),
        jnp.zeros(W, dtype=np.float32),
        jnp.ones(W, dtype=bool),
        jnp.full(16, -1, dtype=np.int32),
        jnp.float32(10.0),
        max_slots=4,
        placement="sinkhorn",
    )
    a = np.asarray(out.assignment)
    live = np.asarray(out.live)
    check_assignment(a, np.ones(T, dtype=bool), free, live)
    cap = int(np.minimum(free, 4)[live].sum())
    assert (a >= 0).sum() == min(T, cap)


@pytest.mark.parametrize("seed", [0, 1])
def test_bucket_rounding_matches_exact_quality(seed):
    """rounding="bucket" (the live-tick path at headline scale) never
    materializes a T x W pass; its placement must match the exact-rounded
    bucketed kernel on legality, work conservation, and makespan to within
    the bucket quantization (<1.5%)."""
    from tpu_faas.sched.greedy import makespan
    from tpu_faas.sched.problem import check_assignment

    import jax.numpy as jnp

    from tpu_faas.sched.sinkhorn import sinkhorn_placement_bucketed

    rng = np.random.default_rng(seed)
    n_tasks, n_workers, max_slots = 5_000, 256, 4
    sizes = rng.lognormal(0.0, 1.0, n_tasks).astype(np.float32)
    speeds = rng.uniform(0.5, 4.0, n_workers).astype(np.float32)
    free = rng.integers(0, max_slots + 1, n_workers).astype(np.int32)
    live = rng.random(n_workers) > 0.1
    valid = np.ones(n_tasks, dtype=bool)

    outs = {}
    for mode in ("exact", "bucket"):
        res = sinkhorn_placement_bucketed(
            jnp.asarray(sizes), jnp.asarray(valid), jnp.asarray(speeds),
            jnp.asarray(free), jnp.asarray(live),
            # n_iters=20 matches the LIVE headline tick's configuration
            # (sched/state.py scheduler_tick at T*W > 2^24) so the quality
            # pin covers what actually ships, not a better-converged cousin
            tau=0.05, n_iters=20, max_slots=max_slots, rounding=mode,
        )
        a = np.asarray(res.assignment)
        check_assignment(a, valid, free, live)
        outs[mode] = a
    placed_exact = (outs["exact"] >= 0).sum()
    placed_bucket = (outs["bucket"] >= 0).sum()
    assert placed_bucket == placed_exact  # work conservation identical
    ms_exact = makespan(outs["exact"], sizes, speeds, max_slots)
    ms_bucket = makespan(outs["bucket"], sizes, speeds, max_slots)
    assert ms_bucket <= ms_exact * 1.015, (ms_bucket, ms_exact)
