"""Queued-only task cancellation, across every layer that honors it.

Beyond the reference surface (a submitted task there can only run): the
gateway's POST /cancel/{task_id} transitions QUEUED -> CANCELLED (terminal),
dispatchers evict the task from any pending structure via the announce-bus
control message (store/base.py cancel_task, dispatch/base.py
note_cancelled), and a RUNNING task is refused — cancellation never yanks a
worker. Covered here: the store protocol, the race-monitor lifecycle
extension, the gateway HTTP contract + SDK surface, and both tpu-push
dispatch paths end-to-end (classic batch and device-resident), including
capacity restoration for placements resolved against cancelled tasks.
"""

from __future__ import annotations

import threading
import time

import pytest

from tpu_faas.client import FaaSClient, TaskCancelledError
from tpu_faas.core.task import TaskStatus
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store.base import CANCEL_ANNOUNCE_PREFIX
from tpu_faas.store.launch import make_store, start_store_thread
from tpu_faas.store.memory import MemoryStore
from tpu_faas.store.racecheck import RaceCheckStore, RaceMonitor
from tpu_faas.workloads import sleep_task
from tests.test_tpu_push_e2e import _make_dispatcher
from tests.test_workers_e2e import _spawn_worker


# -- store protocol ---------------------------------------------------------
def test_store_cancel_semantics():
    s = MemoryStore()
    sub = s.subscribe("tasks")
    assert s.cancel_task("nope") is None  # unknown task

    s.create_task("t1", "fn", "p", "tasks")
    assert sub.get_message() == "t1"
    assert s.cancel_task("t1") == "CANCELLED"
    assert s.get_status("t1") == "CANCELLED"
    # the control message follows the create announce on the same channel
    assert sub.get_message() == CANCEL_ANNOUNCE_PREFIX + "t1"
    assert s.cancel_task("t1") == "CANCELLED"  # idempotent

    s.create_task("t2", "fn", "p", "tasks")
    s.set_status("t2", TaskStatus.RUNNING)
    assert s.cancel_task("t2") == "RUNNING"  # refused: too late
    s.finish_task("t2", "COMPLETED", "r")
    assert s.cancel_task("t2") == "COMPLETED"  # terminal: unchanged

    # truth wins over CANCELLED: a result can only reach a CANCELLED
    # record if the cancel lost its race and the task actually executed
    # (nothing can produce a result for a never-dispatched task), so a
    # first_wins write is ADMITTED rather than frozen
    s.create_task("t3", "fn", "p", "tasks")
    s.cancel_task("t3")
    s.finish_task("t3", "COMPLETED", "r", first_wins=True)
    assert s.get_status("t3") == "COMPLETED"
    # ...while a DELETEd record stays frozen (no partial resurrection)
    s.delete("t3")
    s.finish_task("t3", "COMPLETED", "r2", first_wins=True)
    assert s.get_status("t3") is None


def test_cancel_repairs_clobbered_terminal_record():
    """The sub-millisecond-task interleaving: a result lands inside
    cancel_task's read->write window, so its CANCELLED write clobbers the
    landed terminal record — the post-write repair must restore the true
    status (from the redundant final_status stamp) and report it instead
    of claiming the cancel succeeded."""

    class StaleReadStore(MemoryStore):
        """cancel_task's status pre-read lies QUEUED exactly once for a
        COMPLETED record — the stale read that opens the window."""

        def __init__(self):
            super().__init__()
            self.lie_once = False

        def get_status(self, task_id):
            s = super().get_status(task_id)
            if self.lie_once and s == "COMPLETED":
                self.lie_once = False
                return "QUEUED"
            return s

    from tpu_faas.core.task import FIELD_FINISHED_AT

    s = StaleReadStore()
    s.create_task("t", "fn", "p", "tasks")
    s.finish_task("t", "COMPLETED", "the-result")
    finished_at = s.hget("t", FIELD_FINISHED_AT)
    s.lie_once = True
    assert s.cancel_task("t") == "COMPLETED"  # repaired, truth reported
    status, result = s.get_result("t")
    assert (status, result) == ("COMPLETED", "the-result")
    # the finish STAMP is restored too (not the cancel's own timestamp):
    # the TTL sweeper must age the record from when it actually finished
    assert s.hget("t", FIELD_FINISHED_AT) == finished_at


def test_duplicate_announce_does_not_eat_cancel_note():
    """A duplicate announce for a CANCELLED task (dedup-loser adoption,
    stale-bus replay) must not consume the cancel note while the task
    still sits in a pending structure — else the cancelled task would
    dispatch anyway."""
    from tpu_faas.dispatch.base import TaskDispatcher

    s = MemoryStore()
    d = TaskDispatcher(store=s)
    s.create_task("x", "fn", "p", "tasks")
    assert [t.task_id for t in d.poll_tasks(10)] == ["x"]  # x now "pending"
    s.cancel_task("x")
    s.publish("tasks", "x")  # duplicate announce AFTER the cancel
    assert d.poll_tasks(10) == []  # control msg noted; dup announce skipped
    assert d.drop_if_cancelled("x") is True  # note survived the skip


def test_cancel_refuses_claim_only_mid_create_hash():
    """A claim-only hash (idempotency path: status setnx landed, payload
    fields still in flight) must read as unknown to cancel — writing into
    the creator's window could strand its record status-less."""
    from tpu_faas.core.task import FIELD_STATUS

    s = MemoryStore()
    s.hset("t", {FIELD_STATUS: "QUEUED"})  # claim only, no payload yet
    assert s.cancel_task("t") is None
    assert s.hget("t", FIELD_STATUS) == "QUEUED"  # untouched


def test_cancel_deletes_its_own_ghost_after_mid_window_delete():
    """The ran-finished-consumed-DELETEd-inside-the-window interleaving:
    cancel_task's write resurrects the deleted hash as a partial ghost —
    the post-write probe must detect the missing payload fields, delete
    the ghost, and report the task unknown (a lingering ghost would
    swallow a later idempotency-keyed resubmit of the same id)."""

    from tpu_faas.core.task import FIELD_PARAMS

    class StaleReadStore(MemoryStore):
        """Both pre-reads lie exactly once: status QUEUED and params
        present for a record that was in fact already DELETEd."""

        def __init__(self):
            super().__init__()
            self.lie_once = False
            self._lie_params = False

        def get_status(self, task_id):
            if self.lie_once:
                self.lie_once = False
                self._lie_params = True
                return "QUEUED"
            return super().get_status(task_id)

        def hexists(self, key, field):
            if self._lie_params and field == FIELD_PARAMS:
                self._lie_params = False
                return True
            return super().hexists(key, field)

    s = StaleReadStore()
    s.create_task("t", "fn", "p", "tasks")
    s.finish_task("t", "COMPLETED", "r")
    s.delete("t")  # client consumed the result and forgot the task
    s.lie_once = True
    assert s.cancel_task("t") is None  # ghost detected and removed
    assert s.hgetall("t") == {}
    # the same id can now be resubmitted cleanly
    assert s.create_task_if_absent("t", "fn", "p", "tasks") is True
    assert s.get_status("t") == "QUEUED"


def test_stale_cancel_note_does_not_drop_resubmitted_task():
    """An idempotency-keyed resubmit after DELETE reuses the SAME
    deterministic task id. A cancel note left over from the first
    incarnation must not drop the fresh QUEUED task — drop sites verify
    the record really reads CANCELLED before dropping."""
    from tpu_faas.dispatch.base import TaskDispatcher

    s = MemoryStore()
    d = TaskDispatcher(store=s)
    s.create_task("idem-1", "fn", "p", "tasks")
    assert [t.task_id for t in d.poll_tasks(10)] == ["idem-1"]
    s.cancel_task("idem-1")
    assert d.poll_tasks(10) == []  # note recorded
    # client consumes the CANCELLED record, then resubmits the same key
    s.delete("idem-1")
    s.create_task("idem-1", "fn", "p", "tasks")
    # the fresh incarnation must dispatch: the note is stale
    assert d.drop_if_cancelled("idem-1") is False
    assert [t.task_id for t in d.poll_tasks(10)] == ["idem-1"]
    assert d.stats()["cancelled_dropped"] == 0


def test_cancel_wakes_result_subscribers():
    """CANCELLED is terminal: the results channel must announce it so
    parked /result long-polls wake instead of sleeping out their budget."""
    from tpu_faas.store.base import RESULTS_CHANNEL

    s = MemoryStore()
    sub = s.subscribe(RESULTS_CHANNEL)
    s.create_task("t", "fn", "p", "tasks")
    s.cancel_task("t")
    assert sub.get_message() == "t"


def test_dispatcher_intake_skips_and_evicts_cancelled():
    """Both eviction signals: a cancel BEFORE intake is dropped by the
    non-QUEUED announce skip; a cancel AFTER intake is dropped at the
    dispatch site via the noted control message."""
    from tpu_faas.dispatch.base import TaskDispatcher

    s = MemoryStore()
    d = TaskDispatcher(store=s)
    s.create_task("a", "fn", "p", "tasks")
    s.create_task("b", "fn", "p", "tasks")
    assert [t.task_id for t in d.poll_tasks(10)] == ["a", "b"]
    s.cancel_task("b")  # b already sits in dispatcher-local state
    assert d.poll_tasks(10) == []  # drains the control message
    assert d.drop_if_cancelled("b") is True
    assert d.drop_if_cancelled("b") is False  # note consumed
    assert d.drop_if_cancelled("a") is False

    s.create_task("c", "fn", "p", "tasks")
    s.cancel_task("c")  # cancel lands before this dispatcher ever drains c
    assert d.poll_tasks(10) == []  # announce skipped: status is CANCELLED
    assert d.stats()["cancelled_dropped"] == 1


def test_shared_fleet_cancel_note_reaches_every_sibling():
    """Shared mode: every dispatcher on the channel receives the cancel
    control message; whichever sibling CLAIMED the task drops it at its
    dispatch site (store-verified), and the others' notes age out
    harmlessly rather than being load-bearing."""
    from tpu_faas.dispatch.base import TaskDispatcher

    s = MemoryStore()
    a = TaskDispatcher(store=s, shared=True)
    b = TaskDispatcher(store=s, shared=True)
    s.create_task("t", "fn", "p", "tasks")
    kept_a = a.claim_for_dispatch(a.poll_tasks(10))
    kept_b = b.claim_for_dispatch(b.poll_tasks(10))
    assert len(kept_a) + len(kept_b) == 1  # exactly one sibling owns it
    s.cancel_task("t")
    a.poll_tasks(10)
    b.poll_tasks(10)  # both drain the control message
    assert "t" in a.cancelled and "t" in b.cancelled
    owner = a if kept_a else b
    assert owner.drop_if_cancelled("t") is True


# -- race-monitor lifecycle -------------------------------------------------
def test_racecheck_cancel_transitions():
    mon = RaceMonitor()
    store = RaceCheckStore(MemoryStore(), mon, actor="t")
    # clean queued-only cancel: no violations at all
    store.create_task("ok", "fn", "p", "tasks")
    store.cancel_task("ok")
    mon.assert_clean(allow_warnings=False)

    # cancel racing dispatch, both lawful interleavings = warnings only
    store.create_task("race", "fn", "p", "tasks")
    store.set_status("race", TaskStatus.RUNNING)
    store.hset("race", {"status": "CANCELLED"})  # conditional write lost
    store.finish_task("race", "COMPLETED", "r")  # reality converges
    assert mon.errors == []
    kinds = {v.kind for v in mon.warnings}
    assert "cancel-after-dispatch" in kinds
    assert "late-cancel-race" in kinds
    # a genuinely illegal overwrite still errors
    store.hset("race", {"status": "RUNNING"})
    assert any(v.kind == "terminal-overwrite" for v in mon.errors)

    # force-cancel lifecycle: a worker's CANCELLED result is lawful-silent
    # ONLY after an observed kill request; spontaneous ones are surfaced
    store.create_task("f1", "fn", "p", "tasks")
    store.set_status("f1", TaskStatus.RUNNING)
    store.request_kill("f1")
    store.finish_task("f1", "CANCELLED", "x")
    assert not any(v.task_id == "f1" for v in mon.violations)
    store.create_task("f2", "fn", "p", "tasks")
    store.set_status("f2", TaskStatus.RUNNING)
    store.finish_task("f2", "CANCELLED", "x")
    assert any(
        v.kind == "unrequested-cancel-result" and v.task_id == "f2"
        for v in mon.warnings
    )


# -- gateway contract + SDK -------------------------------------------------
def test_gateway_cancel_contract():
    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    raw = make_store(store_handle.url)
    client = FaaSClient(gw.url)
    try:
        r = client.http.post(f"{gw.url}/cancel/ghost")
        assert r.status_code == 404

        # queued (no dispatcher running) -> cancelled; idempotent repeat
        fid = client.register(lambda x: x, name="ident")
        h = client.submit(fid, 1)
        assert h.cancel() is True
        assert h.status() == "CANCELLED"
        assert h.cancel() is True
        with pytest.raises(TaskCancelledError):
            h.result(timeout=5.0)
        # CANCELLED is terminal: DELETE /task accepts it
        h.forget()
        r = client.http.get(f"{gw.url}/status/{h.task_id}")
        assert r.status_code == 404

        # running -> 409, SDK maps to False
        h2 = client.submit(fid, 2)
        raw.set_status(h2.task_id, TaskStatus.RUNNING)
        r = client.http.post(f"{gw.url}/cancel/{h2.task_id}")
        assert r.status_code == 409
        assert h2.cancel() is False

        # terminal -> no-op reporting the terminal status
        raw.finish_task(h2.task_id, "COMPLETED", "r")
        r = client.http.post(f"{gw.url}/cancel/{h2.task_id}")
        assert r.status_code == 200
        body = r.json()
        assert body == {
            "task_id": h2.task_id, "status": "COMPLETED", "cancelled": False,
        }
        assert h2.cancel() is False

        # /stats counts cancel CALLS that reported cancelled=true (the
        # idempotent repeat counts again, by documented design); refused
        # and no-op calls don't
        m = client.http.get(f"{gw.url}/stats").json()
        assert m["cancel_calls"] == 2
    finally:
        gw.stop()
        store_handle.stop()


def test_cancel_wakes_parked_long_poll():
    """A client parked in GET /result?wait= must wake the moment the task
    is cancelled, not after its full wait budget."""
    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    client = FaaSClient(gw.url)
    try:
        fid = client.register(lambda x: x, name="ident")
        h = client.submit(fid, 1)
        threading.Timer(0.5, h.cancel).start()
        t0 = time.monotonic()
        status, _ = client.raw_result(h.task_id, wait=20.0)
        assert status == "CANCELLED"
        assert time.monotonic() - t0 < 10.0  # woke early, not at the cap
    finally:
        gw.stop()
        store_handle.stop()


# -- tpu-push end-to-end (classic batch + device-resident paths) ------------
def _cancel_e2e(resident: bool) -> None:
    """One 1-process worker saturated by a slow blocker; tasks cancelled
    while QUEUED must end CANCELLED without ever running, capacity
    consumed by their (resident) placements must come back, and the whole
    run must be race-clean with zero warnings — cancellation here never
    races dispatch, because the blocker pins the only slot."""
    monitor = RaceMonitor()
    store_handle = start_store_thread()
    gw = start_gateway_thread(
        RaceCheckStore(make_store(store_handle.url), monitor, actor="gateway")
    )
    disp = _make_dispatcher(
        store_handle.url,
        resident=resident,
        store=RaceCheckStore(
            make_store(store_handle.url), monitor, actor="dispatcher"
        ),
    )
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    worker = _spawn_worker(
        "push_worker", 1, f"tcp://127.0.0.1:{disp.port}",
        "--hb", "--hb-period", "0.3",
    )
    client = FaaSClient(gw.url)
    try:
        fid = client.register(sleep_task)
        blocker = client.submit(fid, 2.5)
        deadline = time.time() + 60
        while blocker.status() != "RUNNING" and time.time() < deadline:
            time.sleep(0.05)
        assert blocker.status() == "RUNNING"

        queued = [client.submit(fid, 0.01) for _ in range(4)]
        # cancel only once the dispatcher provably HOLDS all four (drained
        # off the bus into pending / the resident mirror): a cancel landing
        # before intake is honored by the announce skip instead of a drop
        # site, and the ==4 drop-counter assertion below would flake
        tids = {h.task_id for h in queued}
        deadline = time.time() + 60
        while time.time() < deadline:
            try:  # serve thread mutates both structures concurrently
                held = {t.task_id for t in disp.pending}
                held.update(disp._resident_tasks)
            except RuntimeError:
                continue
            if tids <= held:
                break
            time.sleep(0.05)
        else:
            raise AssertionError("queued tasks never reached the dispatcher")
        assert all(h.cancel() for h in queued)

        # follow-up work after the cancels: proves the slot capacity
        # consumed by any resident placements of cancelled tasks came back
        followup = [client.submit(fid, 0.01) for _ in range(2)]
        assert blocker.result(timeout=60.0) == 2.5
        assert [h.result(timeout=60.0) for h in followup] == [0.01] * 2
        for h in queued:
            assert h.status() == "CANCELLED"
            with pytest.raises(TaskCancelledError):
                h.result(timeout=5.0)
        # every cancelled task was dropped by a dispatch site (they were
        # all pending dispatcher-side when cancelled)
        deadline = time.time() + 30
        while disp.n_cancelled_dropped < 4 and time.time() < deadline:
            time.sleep(0.05)
        assert disp.n_cancelled_dropped == 4
        monitor.assert_clean(allow_warnings=False)
    finally:
        if worker.poll() is None:
            worker.kill()
            worker.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def test_tpu_push_cancel_e2e():
    _cancel_e2e(resident=False)


def test_resident_cancel_e2e():
    _cancel_e2e(resident=True)


# -- FORCE cancel: interrupt a RUNNING task ---------------------------------
def test_pool_force_cancel_unit():
    """The pool-level mechanism: a long sleeper is interrupted mid-run
    (terminal CANCELLED, slot freed in place, no pool rebuild), a
    queued-but-unstarted future cancels without a signal, and unknown /
    finished tasks report False."""
    from tpu_faas.core.executor import pack_params
    from tpu_faas.core.serialize import serialize
    from tpu_faas.worker.pool import TaskPool

    pool = TaskPool(1)
    pool.warmup()
    try:
        pool.submit("slow", serialize(sleep_task), pack_params(30.0))
        # with ONE process, a second submit sits queued in the executor
        pool.submit("queued", serialize(sleep_task), pack_params(30.0))
        deadline = time.time() + 30
        while "slow" not in pool._running_pids and time.time() < deadline:
            pool._drain_events()
            time.sleep(0.02)
        assert pool.cancel("queued") is True  # future-level, no signal
        assert pool.cancel("slow") is True  # mid-run interrupt
        t0 = time.time()
        res = {}
        deadline = time.time() + 20
        while len(res) < 2 and time.time() < deadline:
            for r in pool.drain():
                res[r.task_id] = r
            time.sleep(0.02)
        assert res["slow"].status == "CANCELLED"
        assert res["queued"].status == "CANCELLED"
        assert time.time() - t0 < 10.0  # interrupted, not waited out
        assert pool.free == 1  # slot back without a rebuild
        assert pool.cancel("slow") is False  # already drained
        assert pool.cancel("ghost") is False
    finally:
        pool.close()


def test_force_cancel_running_task_e2e():
    """The full stack: a task RUNNING on a saturated worker is
    force-cancelled — the gateway publishes the kill request, the
    dispatcher relays CANCEL to the owning worker, the pool interrupts the
    child mid-run, and the terminal CANCELLED result converges the record
    in seconds instead of the task's natural 30. The freed slot then runs
    a follow-up task, and the run is race-clean with zero warnings (a
    worker-confirmed force cancel is a lawful silent transition)."""
    monitor = RaceMonitor()
    store_handle = start_store_thread()
    gw = start_gateway_thread(
        RaceCheckStore(make_store(store_handle.url), monitor, actor="gateway")
    )
    disp = _make_dispatcher(
        store_handle.url,
        store=RaceCheckStore(
            make_store(store_handle.url), monitor, actor="dispatcher"
        ),
    )
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    worker = _spawn_worker(
        "push_worker", 1, f"tcp://127.0.0.1:{disp.port}",
        "--hb", "--hb-period", "0.3",
    )
    client = FaaSClient(gw.url)
    try:
        fid = client.register(sleep_task)
        h = client.submit(fid, 30.0)
        deadline = time.time() + 60
        while h.status() != "RUNNING" and time.time() < deadline:
            time.sleep(0.05)
        assert h.status() == "RUNNING"

        t0 = time.time()
        assert h.cancel() is False  # soft cancel refuses a RUNNING task
        assert h.cancel(force=True) is False  # async: not CANCELLED *yet*
        with pytest.raises(TaskCancelledError):
            h.result(timeout=30.0)
        assert time.time() - t0 < 25.0  # interrupted, not waited out
        assert h.status() == "CANCELLED"

        # the interrupted slot is free again: a follow-up completes fast
        follow = client.submit(fid, 0.05)
        assert follow.result(timeout=30.0) == 0.05
        monitor.assert_clean(allow_warnings=False)
    finally:
        if worker.poll() is None:
            worker.kill()
            worker.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def test_local_dispatcher_force_cancel_e2e():
    """Local mode rides the same TaskPool: a RUNNING task force-cancels
    in place — the kill note feeds pool.cancel directly (no wire) — and
    the freed slot runs a follow-up."""
    import threading

    from tpu_faas.dispatch.local import LocalDispatcher

    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    disp = LocalDispatcher(num_workers=1, store=make_store(store_handle.url))
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    client = FaaSClient(gw.url)
    try:
        fid = client.register(sleep_task)
        h = client.submit(fid, 30.0)
        deadline = time.time() + 60
        while h.status() != "RUNNING" and time.time() < deadline:
            time.sleep(0.05)
        assert h.status() == "RUNNING"
        t0 = time.time()
        assert h.cancel(force=True) is False  # async kill request
        with pytest.raises(TaskCancelledError):
            h.result(timeout=30.0)
        assert time.time() - t0 < 25.0
        assert h.status() == "CANCELLED"
        follow = client.submit(fid, 0.05)
        assert follow.result(timeout=30.0) == 0.05
    finally:
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def test_push_dispatcher_force_cancel_e2e():
    """Plain push mode (PushDispatcher, heartbeat fleet): the kill relays
    over the ROUTER socket to the worker whose in-flight set holds the
    task."""
    from tests.test_workers_e2e import stack

    with stack("push", n_workers=1, n_procs=1, heartbeat=True) as (
        client, workers, disp,
    ):
        fid = client.register(sleep_task)
        h = client.submit(fid, 30.0)
        deadline = time.time() + 60
        while h.status() != "RUNNING" and time.time() < deadline:
            time.sleep(0.05)
        assert h.status() == "RUNNING"
        t0 = time.time()
        assert h.cancel(force=True) is False
        with pytest.raises(TaskCancelledError):
            h.result(timeout=30.0)
        assert time.time() - t0 < 25.0
        assert h.status() == "CANCELLED"
        follow = client.submit(fid, 0.05)
        assert follow.result(timeout=30.0) == 0.05


def test_pull_dispatcher_force_cancel_e2e():
    """Pull mode: the kill rides the worker's next mandatory reply
    (cancel_ids on TASK/WAIT — REQ/REP can't be pushed to). A RUNNING
    task on a saturated pull worker force-cancels; the saturated
    keepalive transactions deliver the kill."""
    from tests.test_workers_e2e import stack

    with stack("pull", n_workers=1, n_procs=1) as (client, workers, disp):
        fid = client.register(sleep_task)
        h = client.submit(fid, 30.0)
        deadline = time.time() + 60
        while h.status() != "RUNNING" and time.time() < deadline:
            time.sleep(0.05)
        assert h.status() == "RUNNING"
        t0 = time.time()
        assert h.cancel(force=True) is False  # async kill request
        with pytest.raises(TaskCancelledError):
            h.result(timeout=30.0)
        assert time.time() - t0 < 25.0
        assert h.status() == "CANCELLED"
        follow = client.submit(fid, 0.05)
        assert follow.result(timeout=30.0) == 0.05


def test_gateway_force_cancel_contract():
    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    raw = make_store(store_handle.url)
    client = FaaSClient(gw.url)
    try:
        r = client.http.post(f"{gw.url}/cancel/ghost", json={"force": True})
        assert r.status_code == 404
        # force on a QUEUED task is just a normal cancel
        fid = client.register(lambda x: x, name="ident")
        h = client.submit(fid, 1)
        assert h.cancel(force=True) is True
        assert h.status() == "CANCELLED"
        # force on RUNNING: 202 + kill_requested, control published
        from tpu_faas.store.base import KILL_ANNOUNCE_PREFIX

        sub = raw.subscribe("tasks")
        h2 = client.submit(fid, 2)
        raw.set_status(h2.task_id, TaskStatus.RUNNING)
        r = client.http.post(
            f"{gw.url}/cancel/{h2.task_id}", json={"force": True}
        )
        assert r.status_code == 202
        assert r.json()["kill_requested"] is True
        msgs = []
        deadline = time.time() + 5
        while time.time() < deadline:
            msg = sub.get_message()
            if msg is None:
                time.sleep(0.02)
                continue
            msgs.append(msg)
            if msg.startswith(KILL_ANNOUNCE_PREFIX):
                break
        assert KILL_ANNOUNCE_PREFIX + h2.task_id in msgs
        # malformed body
        r = client.http.post(
            f"{gw.url}/cancel/{h2.task_id}",
            data="not json",
            headers={"Content-Type": "application/json"},
        )
        assert r.status_code == 400
    finally:
        gw.stop()
        store_handle.stop()


# -- stale kill notes vs resubmitted task ids (ADVICE r4 medium) ------------
def test_stale_kill_note_invalidated_by_fresh_incarnation():
    """A kill note that went unmatched (its task finished in the
    publish->relay window) must not survive an idempotency-keyed resubmit
    of the SAME task id: the fresh QUEUED incarnation's announce, consumed
    at intake, invalidates the note — otherwise relay_kills would
    interrupt the innocent fresh run for up to CANCEL_NOTE_TTL (900 s)."""
    from tpu_faas.core.serialize import serialize
    from tpu_faas.dispatch.pull import PullDispatcher

    store = MemoryStore()
    d = PullDispatcher(ip="127.0.0.1", port=0, store=store)
    try:
        fnp, pp = serialize(lambda: 1), serialize(((), {}))
        # stale note first, then the resubmitted incarnation's create
        # announce: intake must pop the note and still deliver the task
        store.create_tasks([("reused-id", fnp, pp)])
        d.note_kill("reused-id")
        t = d.poll_next_task()
        assert t is not None and t.task_id == "reused-id"
        assert "reused-id" not in d.kill_requested

        # a LIVE note (task already RUNNING — its announce was consumed
        # long ago; only a duplicate/stale announce can arrive now) is
        # kept: the non-QUEUED skip never reaches the invalidation
        store.create_tasks([("running-id", fnp, pp)])
        store.set_status("running-id", TaskStatus.RUNNING)
        d.note_kill("running-id")
        assert d.poll_next_task() is None  # duplicate announce skipped
        assert "running-id" in d.kill_requested
    finally:
        d.socket.close(linger=0)


def test_cancel_mid_create_claim_is_409_not_404():
    """ADVICE r4: a claim-only record (idempotent submit mid-create: claim
    field written, status not yet) is NOT an unknown id — its task_id was
    just returned to the submitter. The gateway answers 409 'not yet
    cancellable' (mapped to False by the SDK), reserving 404 for ids that
    genuinely don't exist."""
    store_handle = start_store_thread()
    raw = make_store(store_handle.url)
    gw = start_gateway_thread(make_store(store_handle.url))
    client = FaaSClient(gw.url)
    try:
        # what submit's claim write leaves mid-create: the claim field
        # alone, no status (gateway app.py _IDEM_CLAIM_FIELD)
        raw.hset("mid-create", {"idem_claim": "somehash"})
        r = client.http.post(f"{gw.url}/cancel/mid-create")
        assert r.status_code == 409
        assert client.cancel("mid-create") is False  # no HTTPError
        r = client.http.post(f"{gw.url}/cancel/never-existed")
        assert r.status_code == 404
    finally:
        gw.stop()
        store_handle.stop()


def test_misfire_counter_surfaces_in_dispatcher_stats():
    """ADVICE r4: misfire repairs (the one at-least-once execution) ride
    RESULT messages as a cumulative per-worker counter and surface in
    /stats — operators detect doubled side effects without log scraping."""
    from tpu_faas.dispatch.push import PushDispatcher

    d = PushDispatcher(
        ip="127.0.0.1", port=0, store=MemoryStore(), heartbeat=True
    )
    try:
        d._handle(b"w1", "register", {"num_processes": 1})
        assert d.stats()["worker_misfires"] == 0
        d._handle(
            b"w1",
            "result",
            {"task_id": "t", "status": "COMPLETED", "result": "x",
             "misfires": 2},
        )
        assert d.stats()["worker_misfires"] == 2
        # cumulative, not additive: the worker re-reports its total
        d._handle(
            b"w1",
            "result",
            {"task_id": "t2", "status": "COMPLETED", "result": "x",
             "misfires": 2},
        )
        assert d.stats()["worker_misfires"] == 2
        # reference-era workers carry no field: unchanged
        d._handle(
            b"w1",
            "result",
            {"task_id": "t3", "status": "COMPLETED", "result": "x"},
        )
        assert d.stats()["worker_misfires"] == 2
    finally:
        d.socket.close(linger=0)
