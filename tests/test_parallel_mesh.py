"""Sharded scheduler over a virtual 8-device CPU mesh: result parity with the
single-device kernels + invariants."""

import jax
import numpy as np
import pytest

from tpu_faas.parallel.mesh import (
    make_mesh,
    replicate,
    shard_task_arrays,
    sharded_scheduler_tick,
    sharded_sinkhorn_placement,
)
from tpu_faas.sched.problem import PlacementProblem, check_assignment
from tpu_faas.sched.sinkhorn import sinkhorn_placement

#: the raw sharded kernels are written against the jax.shard_map alias;
#: the SchedulerArrays mesh tick below compiles through sharding
#: constraints instead and runs on older JAX too
requires_shard_map = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="this JAX lacks jax.shard_map (sharded kernels unavailable)",
)


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices (virtual CPU mesh or a pod slice)")
    return make_mesh(8)


def _problem(seed, n_tasks=512, n_workers=32):
    rng = np.random.default_rng(seed)
    sizes = rng.uniform(0.5, 5.0, n_tasks).astype(np.float32)
    speeds = rng.uniform(0.5, 4.0, n_workers).astype(np.float32)
    free = rng.integers(0, 6, n_workers).astype(np.int32)
    live = rng.random(n_workers) > 0.2
    return sizes, speeds, free, live


@pytest.mark.parametrize("seed", [0, 1])
@requires_shard_map
def test_sharded_sinkhorn_invariants(mesh, seed):
    sizes, speeds, free, live = _problem(seed)
    p = PlacementProblem.build(sizes, speeds, free, live, T=512, W=32)
    ts, tv = shard_task_arrays(mesh, p.task_size, p.task_valid)
    ws, wf, wl = replicate(mesh, p.worker_speed, p.worker_free, p.worker_live)
    a = np.asarray(
        sharded_sinkhorn_placement(mesh, ts, tv, ws, wf, wl, max_slots=4)
    )
    check_assignment(a, np.asarray(p.task_valid), np.asarray(p.worker_free),
                     np.asarray(p.worker_live))
    cap = int(np.minimum(free, 4)[live].sum())
    assert (a >= 0).sum() == min(len(sizes), cap)


@requires_shard_map
def test_sharded_matches_single_device_plan(mesh):
    """Same soft problem -> same placement count and near-identical cost as
    the single-device sinkhorn kernel."""
    sizes, speeds, free, live = _problem(5)
    p = PlacementProblem.build(sizes, speeds, free, live, T=512, W=32)
    ts, tv = shard_task_arrays(mesh, p.task_size, p.task_valid)
    ws, wf, wl = replicate(mesh, p.worker_speed, p.worker_free, p.worker_live)
    a_sharded = np.asarray(
        sharded_sinkhorn_placement(mesh, ts, tv, ws, wf, wl, max_slots=4)
    )
    a_single = np.asarray(
        sinkhorn_placement(
            p.task_size, p.task_valid, p.worker_speed, p.worker_free,
            p.worker_live, max_slots=4,
        ).assignment
    )
    placed_sh = a_sharded >= 0
    placed_si = a_single >= 0
    assert placed_sh.sum() == placed_si.sum()
    cost_sh = float(np.sum(sizes[placed_sh[:512]] / speeds[a_sharded[placed_sh][: placed_sh.sum()]]))
    cost_si = float(np.sum(sizes[placed_si[:512]] / speeds[a_single[placed_si][: placed_si.sum()]]))
    assert abs(cost_sh - cost_si) <= 0.05 * max(cost_si, 1e-6)


@requires_shard_map
def test_sharded_full_tick(mesh):
    import jax.numpy as jnp

    sizes, speeds, free, live = _problem(7, n_tasks=256, n_workers=16)
    p = PlacementProblem.build(sizes, speeds, free, live, T=256, W=16)
    ts, tv = shard_task_arrays(mesh, p.task_size, p.task_valid)
    active = np.ones(16, dtype=bool)
    hb_age = np.zeros(16, dtype=np.float32)
    hb_age[3] = 100.0  # worker 3 silent beyond expiry
    inflight = np.full(64, -1, dtype=np.int32)
    inflight[0] = 3  # one task in flight on the dead worker
    (ws, wf, wa, ages, pl, iw) = replicate(
        mesh,
        p.worker_speed,
        p.worker_free,
        jnp.asarray(active),
        jnp.asarray(hb_age),
        jnp.asarray(active),
        jnp.asarray(inflight),
    )
    out = sharded_scheduler_tick(
        mesh, ts, tv, ws, wf, wa, ages, pl, iw,
        jnp.float32(10.0), max_slots=4,
    )
    live_out = np.asarray(out.live)
    assert not live_out[3] and live_out[[0, 1, 2]].all()
    assert np.asarray(out.purged)[3]
    assert np.asarray(out.redispatch)[0]
    a = np.asarray(out.assignment)
    assert not (a == 3).any()  # nothing placed on the dead worker
    from tpu_faas.sched.state import SchedulerArrays

    counts = SchedulerArrays.assigned_counts(a, 4)
    assert counts.sum() == (a >= 0).sum()


def test_scheduler_arrays_mesh_matches_single_device(mesh):
    """The mesh-backed SchedulerArrays tick and the single-device tick make
    IDENTICAL rank-placement decisions on identical inputs (the sharded
    global sort is a collective exchange, not a different algorithm)."""
    from tpu_faas.sched.state import SchedulerArrays

    def build(mesh_devices):
        a = SchedulerArrays(
            max_workers=32, max_pending=256, mesh_devices=mesh_devices,
            clock=lambda: 100.0,
        )
        rng = np.random.default_rng(11)
        for i in range(12):
            a.register(f"w{i}".encode(), int(rng.integers(1, 6)))
            a.worker_speed[a.worker_ids[f"w{i}".encode()]] = float(
                rng.uniform(0.5, 3.0)
            )
        return a

    rng = np.random.default_rng(12)
    sizes = rng.uniform(0.1, 9.0, 200).astype(np.float32)
    prios = rng.integers(-2, 3, 200).astype(np.int32)
    single, meshed = build(None), build(8)
    out_s = single.tick(sizes, task_priorities=prios)
    out_m = meshed.tick(sizes, task_priorities=prios)
    np.testing.assert_array_equal(
        np.asarray(out_s.assignment)[:200], np.asarray(out_m.assignment)[:200]
    )
    np.testing.assert_array_equal(
        np.asarray(out_s.live), np.asarray(out_m.live)
    )


def test_scheduler_arrays_mesh_auction_matches_single_device(mesh):
    """The general-cost auction runs SHARDED (round-4: sched/state.py used
    to reject mesh+auction at construction): per-round bids are elementwise
    in the sharded task axis, the winner lexsort lowers to collective
    exchanges, and both the cold seeded solve and the warm price-carried
    tick must be bit-identical to the single-device solver."""
    from tpu_faas.sched.state import SchedulerArrays

    def build(mesh_devices):
        a = SchedulerArrays(
            max_workers=16, max_pending=64, max_slots=4,
            placement="auction", mesh_devices=mesh_devices,
            clock=lambda: 100.0,
        )
        rng = np.random.default_rng(7)
        for i in range(8):
            a.register(
                f"w{i}".encode(), int(1 + i % 4),
                speed=float(rng.uniform(0.5, 4.0)),
            )
        return a

    rng = np.random.default_rng(9)
    sizes = rng.uniform(0.5, 5.0, 24).astype(np.float32)
    single, meshed = build(None), build(8)
    cold_s = np.asarray(single.tick(sizes).assignment)
    cold_m = np.asarray(meshed.tick(sizes).assignment)
    np.testing.assert_array_equal(cold_s, cold_m)
    assert (cold_s >= 0).sum() == 20  # min(24 tasks, capacity)
    # warm tick: both carry their own device-resident prices
    warm_s = np.asarray(single.tick(sizes * 1.01).assignment)
    warm_m = np.asarray(meshed.tick(sizes * 1.01).assignment)
    np.testing.assert_array_equal(warm_s, warm_m)
