"""Sharded scheduler over a virtual 8-device CPU mesh: result parity with the
single-device kernels + invariants."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_faas.parallel.mesh import (
    make_mesh,
    replicate,
    shard_task_arrays,
    sharded_scheduler_tick,
    sharded_sinkhorn_placement,
)
from tpu_faas.sched.problem import PlacementProblem, check_assignment
from tpu_faas.sched.sinkhorn import sinkhorn_placement

from tpu_faas.parallel.mesh import have_shard_map

#: the raw sharded kernels resolve shard_map through mesh._shard_map
#: (jax.shard_map where it exists, the experimental module otherwise) —
#: skip only when NEITHER spelling is importable
requires_shard_map = pytest.mark.skipif(
    not have_shard_map(),
    reason="this JAX lacks any shard_map spelling (sharded kernels "
    "unavailable)",
)


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices (virtual CPU mesh or a pod slice)")
    return make_mesh(8)


def _problem(seed, n_tasks=512, n_workers=32):
    rng = np.random.default_rng(seed)
    sizes = rng.uniform(0.5, 5.0, n_tasks).astype(np.float32)
    speeds = rng.uniform(0.5, 4.0, n_workers).astype(np.float32)
    free = rng.integers(0, 6, n_workers).astype(np.int32)
    live = rng.random(n_workers) > 0.2
    return sizes, speeds, free, live


@pytest.mark.parametrize("seed", [0, 1])
@requires_shard_map
def test_sharded_sinkhorn_invariants(mesh, seed):
    sizes, speeds, free, live = _problem(seed)
    p = PlacementProblem.build(sizes, speeds, free, live, T=512, W=32)
    ts, tv = shard_task_arrays(mesh, p.task_size, p.task_valid)
    ws, wf, wl = replicate(mesh, p.worker_speed, p.worker_free, p.worker_live)
    a = np.asarray(
        sharded_sinkhorn_placement(mesh, ts, tv, ws, wf, wl, max_slots=4)
    )
    check_assignment(a, np.asarray(p.task_valid), np.asarray(p.worker_free),
                     np.asarray(p.worker_live))
    cap = int(np.minimum(free, 4)[live].sum())
    assert (a >= 0).sum() == min(len(sizes), cap)


@requires_shard_map
def test_sharded_matches_single_device_plan(mesh):
    """Same soft problem -> same placement count and near-identical cost as
    the single-device sinkhorn kernel."""
    sizes, speeds, free, live = _problem(5)
    p = PlacementProblem.build(sizes, speeds, free, live, T=512, W=32)
    ts, tv = shard_task_arrays(mesh, p.task_size, p.task_valid)
    ws, wf, wl = replicate(mesh, p.worker_speed, p.worker_free, p.worker_live)
    a_sharded = np.asarray(
        sharded_sinkhorn_placement(mesh, ts, tv, ws, wf, wl, max_slots=4)
    )
    a_single = np.asarray(
        sinkhorn_placement(
            p.task_size, p.task_valid, p.worker_speed, p.worker_free,
            p.worker_live, max_slots=4,
        ).assignment
    )
    placed_sh = a_sharded >= 0
    placed_si = a_single >= 0
    assert placed_sh.sum() == placed_si.sum()
    cost_sh = float(np.sum(sizes[placed_sh[:512]] / speeds[a_sharded[placed_sh][: placed_sh.sum()]]))
    cost_si = float(np.sum(sizes[placed_si[:512]] / speeds[a_single[placed_si][: placed_si.sum()]]))
    assert abs(cost_sh - cost_si) <= 0.05 * max(cost_si, 1e-6)


@requires_shard_map
def test_sharded_full_tick(mesh):
    import jax.numpy as jnp

    sizes, speeds, free, live = _problem(7, n_tasks=256, n_workers=16)
    p = PlacementProblem.build(sizes, speeds, free, live, T=256, W=16)
    ts, tv = shard_task_arrays(mesh, p.task_size, p.task_valid)
    active = np.ones(16, dtype=bool)
    hb_age = np.zeros(16, dtype=np.float32)
    hb_age[3] = 100.0  # worker 3 silent beyond expiry
    inflight = np.full(64, -1, dtype=np.int32)
    inflight[0] = 3  # one task in flight on the dead worker
    (ws, wf, wa, ages, pl, iw) = replicate(
        mesh,
        p.worker_speed,
        p.worker_free,
        jnp.asarray(active),
        jnp.asarray(hb_age),
        jnp.asarray(active),
        jnp.asarray(inflight),
    )
    out = sharded_scheduler_tick(
        mesh, ts, tv, ws, wf, wa, ages, pl, iw,
        jnp.float32(10.0), max_slots=4,
    )
    live_out = np.asarray(out.live)
    assert not live_out[3] and live_out[[0, 1, 2]].all()
    assert np.asarray(out.purged)[3]
    assert np.asarray(out.redispatch)[0]
    a = np.asarray(out.assignment)
    assert not (a == 3).any()  # nothing placed on the dead worker
    from tpu_faas.sched.state import SchedulerArrays

    counts = SchedulerArrays.assigned_counts(a, 4)
    assert counts.sum() == (a >= 0).sum()


def test_scheduler_arrays_mesh_matches_single_device(mesh):
    """The mesh-backed SchedulerArrays tick and the single-device tick make
    IDENTICAL rank-placement decisions on identical inputs (the sharded
    global sort is a collective exchange, not a different algorithm)."""
    from tpu_faas.sched.state import SchedulerArrays

    def build(mesh_devices):
        a = SchedulerArrays(
            max_workers=32, max_pending=256, mesh_devices=mesh_devices,
            clock=lambda: 100.0,
        )
        rng = np.random.default_rng(11)
        for i in range(12):
            a.register(f"w{i}".encode(), int(rng.integers(1, 6)))
            a.worker_speed[a.worker_ids[f"w{i}".encode()]] = float(
                rng.uniform(0.5, 3.0)
            )
        return a

    rng = np.random.default_rng(12)
    sizes = rng.uniform(0.1, 9.0, 200).astype(np.float32)
    prios = rng.integers(-2, 3, 200).astype(np.int32)
    single, meshed = build(None), build(8)
    out_s = single.tick(sizes, task_priorities=prios)
    out_m = meshed.tick(sizes, task_priorities=prios)
    np.testing.assert_array_equal(
        np.asarray(out_s.assignment)[:200], np.asarray(out_m.assignment)[:200]
    )
    np.testing.assert_array_equal(
        np.asarray(out_s.live), np.asarray(out_m.live)
    )


def test_scheduler_arrays_mesh_auction_matches_single_device(mesh):
    """The general-cost auction runs SHARDED (round-4: sched/state.py used
    to reject mesh+auction at construction): per-round bids are elementwise
    in the sharded task axis, the winner lexsort lowers to collective
    exchanges, and both the cold seeded solve and the warm price-carried
    tick must be bit-identical to the single-device solver."""
    from tpu_faas.sched.state import SchedulerArrays

    def build(mesh_devices):
        a = SchedulerArrays(
            max_workers=16, max_pending=64, max_slots=4,
            placement="auction", mesh_devices=mesh_devices,
            clock=lambda: 100.0,
        )
        rng = np.random.default_rng(7)
        for i in range(8):
            a.register(
                f"w{i}".encode(), int(1 + i % 4),
                speed=float(rng.uniform(0.5, 4.0)),
            )
        return a

    rng = np.random.default_rng(9)
    sizes = rng.uniform(0.5, 5.0, 24).astype(np.float32)
    single, meshed = build(None), build(8)
    cold_s = np.asarray(single.tick(sizes).assignment)
    cold_m = np.asarray(meshed.tick(sizes).assignment)
    np.testing.assert_array_equal(cold_s, cold_m)
    assert (cold_s >= 0).sum() == 20  # min(24 tasks, capacity)
    # warm tick: both carry their own device-resident prices
    warm_s = np.asarray(single.tick(sizes * 1.01).assignment)
    warm_m = np.asarray(meshed.tick(sizes * 1.01).assignment)
    np.testing.assert_array_equal(warm_s, warm_m)


# -- explicit-permute winner resolve ----------------------------------------


@requires_shard_map
def test_sharded_auction_permute_exact_parity(mesh):
    """The permute winner-resolve must reproduce the single-device seeded
    auction EXACTLY — same assignment, same round count — because every
    per-cell bid value, max-reduction, and tie rule is identical (see
    sharded_auction_placement's docstring). Not a tolerance test."""
    from tpu_faas.parallel.mesh import sharded_auction_placement
    from tpu_faas.sched.auction import auction_placement

    rng = np.random.default_rng(5)
    T, W, K = 1024, 256, 4
    p = PlacementProblem.build(
        rng.uniform(0.1, 5.0, 700).astype(np.float32),
        rng.uniform(0.5, 4.0, W).astype(np.float32),
        rng.integers(0, K + 1, W).astype(np.int32),
        rng.random(W) > 0.1,
        T=T,
        W=W,
    )
    ts, tv = shard_task_arrays(mesh, p.task_size, p.task_valid)
    ws, wf, wl = replicate(
        mesh, p.worker_speed, p.worker_free, p.worker_live
    )
    res_m = sharded_auction_placement(mesh, ts, tv, ws, wf, wl, max_slots=K)
    res_s = auction_placement(
        p.task_size, p.task_valid, p.worker_speed, p.worker_free,
        p.worker_live, max_slots=K,
    )
    np.testing.assert_array_equal(
        np.asarray(res_m.assignment), np.asarray(res_s.assignment)
    )
    assert int(res_m.n_rounds) == int(res_s.n_rounds)
    np.testing.assert_allclose(
        np.asarray(res_m.prices), np.asarray(res_s.prices), atol=1e-5
    )
    check_assignment(
        np.asarray(res_m.assignment), np.asarray(p.task_valid),
        np.minimum(np.asarray(p.worker_free), K), np.asarray(p.worker_live),
    )


@requires_shard_map
def test_sharded_tick_permute_winner_resolve(mesh):
    """sharded_scheduler_tick(winner_resolve='permute') — the wired-in
    form — matches the default GSPMD lexsort resolution end to end,
    including the liveness/purge/redispatch outputs around it."""
    from tpu_faas.parallel.mesh import sharded_scheduler_tick

    rng = np.random.default_rng(11)
    T, W, K = 512, 64, 4
    sizes = np.zeros(T, np.float32)
    sizes[:300] = rng.uniform(0.2, 3.0, 300)
    valid = np.zeros(T, bool)
    valid[:300] = True
    speeds = rng.uniform(0.5, 4.0, W).astype(np.float32)
    free = rng.integers(0, K + 1, W).astype(np.int32)
    active = rng.random(W) > 0.1
    hb_age = rng.uniform(0.0, 15.0, W).astype(np.float32)
    prev_live = rng.random(W) > 0.5
    inflight = rng.integers(-1, W, 256).astype(np.int32)
    ts, tv = shard_task_arrays(
        mesh, jnp.asarray(sizes), jnp.asarray(valid)
    )
    ws, wf, wa, hb, pl_, iw = replicate(
        mesh, jnp.asarray(speeds), jnp.asarray(free), jnp.asarray(active),
        jnp.asarray(hb_age), jnp.asarray(prev_live), jnp.asarray(inflight),
    )
    kw = dict(max_slots=K, placement="auction")
    out_g = sharded_scheduler_tick(
        mesh, ts, tv, ws, wf, wa, hb, pl_, iw, jnp.float32(10.0), **kw
    )
    out_p = sharded_scheduler_tick(
        mesh, ts, tv, ws, wf, wa, hb, pl_, iw, jnp.float32(10.0),
        winner_resolve="permute", **kw,
    )
    np.testing.assert_array_equal(
        np.asarray(out_g.assignment), np.asarray(out_p.assignment)
    )
    for field in ("live", "purged", "redispatch"):
        np.testing.assert_array_equal(
            np.asarray(getattr(out_g, field)),
            np.asarray(getattr(out_p, field)),
            err_msg=field,
        )
    np.testing.assert_allclose(
        np.asarray(out_g.auction_price),
        np.asarray(out_p.auction_price),
        atol=1e-5,
    )


@requires_shard_map
def test_sharded_auction_permute_warm_carry(mesh):
    """Warm prices thread through the permute path exactly as through the
    single-device warm branch: the same init_price must produce the same
    warm trajectory (assignment AND round count) on both paths."""
    from tpu_faas.parallel.mesh import sharded_auction_placement
    from tpu_faas.sched.auction import auction_placement

    rng = np.random.default_rng(13)
    T, W, K = 512, 128, 4
    p = PlacementProblem.build(
        rng.uniform(0.1, 5.0, 400).astype(np.float32),
        rng.uniform(0.5, 4.0, W).astype(np.float32),
        rng.integers(1, K + 1, W).astype(np.int32),
        np.ones(W, bool),
        T=T,
        W=W,
    )
    ts, tv = shard_task_arrays(mesh, p.task_size, p.task_valid)
    ws, wf, wl = replicate(
        mesh, p.worker_speed, p.worker_free, p.worker_live
    )
    cold = sharded_auction_placement(mesh, ts, tv, ws, wf, wl, max_slots=K)
    warm = sharded_auction_placement(
        mesh, ts, tv, ws, wf, wl, max_slots=K, init_price=cold.prices
    )
    warm_single = auction_placement(
        p.task_size, p.task_valid, p.worker_speed, p.worker_free,
        p.worker_live, max_slots=K,
        init_price=jnp.asarray(np.asarray(cold.prices)),
    )
    np.testing.assert_array_equal(
        np.asarray(warm.assignment), np.asarray(warm_single.assignment)
    )
    assert int(warm.n_rounds) == int(warm_single.n_rounds)
    check_assignment(
        np.asarray(warm.assignment), np.asarray(p.task_valid),
        np.minimum(np.asarray(p.worker_free), K), np.asarray(p.worker_live),
    )
    assert (np.asarray(warm.assignment) >= 0).sum() == (
        np.asarray(cold.assignment) >= 0
    ).sum()
