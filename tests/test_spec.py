"""Speculation plane (tpu_faas/spec): device straggler scoring, anti-
affinity fixup, hedge policy/book, dispatcher lifecycle (launch, first-wins
resolution, loser kill + slot reclaim, promotion on original-worker death),
resident XLA-vs-fused parity with spec state, byte-identity when off, and
the full-stack e2e + chaos legs under the race monitor."""

from __future__ import annotations

import math
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from tpu_faas.core.task import FIELD_SPECULATIVE, TaskStatus
from tpu_faas.dispatch.base import RECLAIM_FIELDS, PendingTask
from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
from tpu_faas.spec import SpeculationPolicy
from tpu_faas.spec.straggler import (
    HEDGE_FIXUP_K,
    anti_affinity_veto,
    hedge_fixup,
    straggler_flags,
)
from tpu_faas.store import MemoryStore
from tpu_faas.store.launch import make_store
from tpu_faas.worker import messages as m

import jax.numpy as jnp


# ---------------------------------------------------------------------------
# device kernels
# ---------------------------------------------------------------------------
def test_straggler_flags_basic():
    elapsed = jnp.asarray([5.0, 5.0, 0.1, 5.0], dtype=jnp.float32)
    pred = jnp.asarray([1.0, 0.0, 1.0, 1.0], dtype=jnp.float32)
    occupied = jnp.asarray([True, True, True, False])
    flags = np.asarray(
        straggler_flags(
            elapsed, pred, occupied, jnp.float32(3.0), jnp.float32(0.05)
        )
    )
    # slot 0: elapsed 5 > 3x1 -> flagged; slot 1: pred 0 opts out;
    # slot 2: not past threshold; slot 3: unoccupied
    assert flags.tolist() == [True, False, False, False]


def test_straggler_min_runtime_floor():
    """A tight prediction on a tiny task must not hedge on noise: the
    absolute floor dominates mult x pred when pred is small."""
    elapsed = jnp.asarray([0.04, 0.2], dtype=jnp.float32)
    pred = jnp.asarray([0.01, 0.01], dtype=jnp.float32)
    occupied = jnp.asarray([True, True])
    flags = np.asarray(
        straggler_flags(
            elapsed, pred, occupied, jnp.float32(2.0), jnp.float32(0.05)
        )
    )
    # 0.04 < floor 0.05 -> not flagged even though > 2 x 0.01
    assert flags.tolist() == [False, True]


def test_anti_affinity_veto_masks_only_forbidden_pairing():
    assignment = jnp.asarray([0, 1, 2, -1], dtype=jnp.int32)
    avoid = jnp.asarray([0, -1, 1, 2], dtype=jnp.int32)
    out = np.asarray(anti_affinity_veto(assignment, avoid))
    # task 0 hit its forbidden row -> vetoed; task 2 avoids row 1 but got
    # row 2 -> untouched; unplaced stays unplaced
    assert out.tolist() == [-1, 1, 2, -1]


def test_hedge_fixup_replaces_on_fastest_other_worker():
    # 3 workers; task 0 was placed on its forbidden row 0; rows 1 (slow)
    # and 2 (fast) have capacity -> re-placed on row 2
    assignment = jnp.asarray([0, -1], dtype=jnp.int32)
    avoid = jnp.asarray([0, -1], dtype=jnp.int32)
    speed = jnp.asarray([1.0, 0.5, 2.0], dtype=jnp.float32)
    free = jnp.asarray([1, 1, 1], dtype=jnp.int32)
    live = jnp.asarray([True, True, True])
    out = np.asarray(hedge_fixup(assignment, avoid, speed, free, live))
    assert out[0] == 2


def test_hedge_fixup_no_capacity_elsewhere_stays_queued():
    assignment = jnp.asarray([0], dtype=jnp.int32)
    avoid = jnp.asarray([0], dtype=jnp.int32)
    speed = jnp.asarray([1.0, 1.0], dtype=jnp.float32)
    free = jnp.asarray([2, 0], dtype=jnp.int32)  # only the forbidden row
    live = jnp.asarray([True, True])
    out = np.asarray(hedge_fixup(assignment, avoid, speed, free, live))
    assert out[0] == -1  # never onto the forbidden worker, never dropped


def test_hedge_fixup_respects_remaining_capacity():
    """Two vetoed ghosts, one free slot elsewhere: only one re-places (the
    fixup's greedy loop consumes capacity as it assigns)."""
    assignment = jnp.asarray([0, 0], dtype=jnp.int32)
    avoid = jnp.asarray([0, 0], dtype=jnp.int32)
    speed = jnp.asarray([1.0, 1.0], dtype=jnp.float32)
    free = jnp.asarray([2, 1], dtype=jnp.int32)
    live = jnp.asarray([True, True])
    out = np.asarray(hedge_fixup(assignment, avoid, speed, free, live))
    assert sorted(out.tolist()) == [-1, 1]
    assert HEDGE_FIXUP_K >= 2  # the bound documented as "rarely binding"


# ---------------------------------------------------------------------------
# policy / hedge book
# ---------------------------------------------------------------------------
def test_policy_knob_validation():
    with pytest.raises(ValueError):
        SpeculationPolicy(1.0)  # mult must exceed 1
    with pytest.raises(ValueError):
        SpeculationPolicy(3.0, max_frac=0.0)


def test_policy_budget_and_dup_gates():
    p = SpeculationPolicy(3.0, max_frac=0.5)
    assert p.consider("a", 0, n_dispatched=10) is not None
    # one hedge outstanding for "a": a re-flag is ignored
    assert p.consider("a", 0, n_dispatched=10) is None
    assert p.n_launched == 1
    # budget: 0.5 x 4 = 2 -> second hedge fits, third does not
    assert p.consider("b", 1, n_dispatched=4) is not None
    assert not p.within_budget(4)
    assert p.consider("c", 1, n_dispatched=4) is None
    assert p.n_suppressed_budget == 1


def test_policy_resolution_and_loser_accounting():
    p = SpeculationPolicy(3.0)
    e = p.consider("a", 0, n_dispatched=100)
    e.hedge_row = 1
    p.resolve("a", winner="replica", loser_row=0)
    assert p.n_replica_wins == 1 and "a" not in p.entries
    # sender-checked: a duplicate from the WINNER's row (or an unknown
    # sender) must not consume the entry or book waste
    assert p.note_loser_result("a", 1, 9.9) is None
    assert p.note_loser_result("a", None, 9.9) is None
    # the loser's late result attributes its window once
    assert p.note_loser_result("a", 0, 1.5) == 1.5
    assert p.note_loser_result("a", 0, 1.5) is None  # consumed
    assert p.wasted_exec_s == 1.5
    # unknown ids are not losers
    assert p.note_loser_result("zzz", 0, 1.0) is None


def test_policy_abandon_and_promote_counters():
    p = SpeculationPolicy(3.0)
    p.consider("a", 0, n_dispatched=100)
    p.consider("b", 0, n_dispatched=100)
    assert p.abandon("a") is not None
    assert p.promote("b") is not None
    assert p.abandon("a") is None  # already gone
    assert p.n_abandoned == 1 and p.n_promoted == 1
    assert p.stats()["outstanding"] == 0


# ---------------------------------------------------------------------------
# resident parity: XLA vs fused, spec state carried
# ---------------------------------------------------------------------------
def _spec_resident(backend, clock):
    from tpu_faas.sched.resident import ResidentScheduler

    return ResidentScheduler(
        max_workers=4, max_pending=16, max_inflight=32, max_slots=2,
        time_to_expire=100.0, clock=clock, use_priority=True,
        tick_backend=backend, spec_mult=2.0, spec_min_s=0.01,
    )


def _drive_spec_script(backend):
    """One deterministic script: dispatch, stamp pred, advance time past
    the threshold, hedge with anti-affinity — returns the observables."""
    t = [0.0]
    a = _spec_resident(backend, lambda: t[0])
    a.register(b"w0", 2)
    a.register(b"w1", 2)
    a.pending_add("t0", 1.0)
    a.tick_resident()
    r = a.resolve_next()
    placed1 = list(r.placed)
    for tid, row in r.placed:
        a.inflight_add(tid, row, pred=0.1)
    t[0] += 1.0
    a.tick_resident()
    r = a.resolve_next()
    assert not r.straggler_slots  # stamp applies this tick; elapsed 0
    t[0] += 5.0
    a.tick_resident()
    r = a.resolve_next()
    flagged = list(r.straggler_slots)
    orig_row = int(a.inflight_worker[flagged[0]]) if flagged else -1
    a.pending_add("t0", 1.0, avoid=orig_row)
    a.tick_resident()
    r2 = a.resolve_next()
    hedge_placed = list(r2.placed)
    return placed1, flagged, orig_row, hedge_placed


def test_resident_spec_parity_xla_vs_fused_interpret():
    from tpu_faas.sched.pallas_fused import fused_ok

    xla = _drive_spec_script("xla")
    assert xla[1], "XLA tick flagged no straggler"
    # the hedge placed, and not on the original's row
    assert xla[3] and all(row != xla[2] for _, row in xla[3])
    if not fused_ok():
        pytest.skip("pallas unavailable")
    fused = _drive_spec_script("fused_interpret")
    assert fused == xla


def test_resident_spec_off_packet_unchanged():
    """Speculation off = the resident packet (the wire between host and
    device, and the multihost broadcast buffer) is byte-identical to the
    pre-speculation layout: no avoid lane, no pred lane, no spec tail."""
    from tpu_faas.sched.resident import ResidentScheduler

    off = ResidentScheduler(
        max_workers=4, max_pending=16, max_inflight=32, max_slots=2,
        use_priority=True,
    )
    expected = (
        9  # header
        + off.KA * 2  # sizes + priority lanes
        + 2 * (off.KH + off.KF + off.KI + off.KS + off.KB)
    )
    assert off.packet_len() == expected
    assert off.KG == 1  # straggler output collapsed to its pad
    on = ResidentScheduler(
        max_workers=4, max_pending=16, max_inflight=32, max_slots=2,
        use_priority=True, spec_mult=2.0,
    )
    assert on.packet_len() == expected + on.KA + on.KI + 2


def test_batch_tick_spec_off_has_no_straggler_output():
    from tpu_faas.sched.state import SchedulerArrays

    a = SchedulerArrays(max_workers=4, max_pending=8, max_inflight=16)
    a.register(b"w0", 2)
    out = a.tick(np.asarray([1.0], dtype=np.float32))
    assert out.straggler is None


def test_batch_tick_dead_worker_redispatches_never_flags():
    """The straggler and redispatch sets are disjoint: a dead worker's
    slot rides the reclaim plane, not the hedge plane."""
    from tpu_faas.sched.state import SchedulerArrays

    t = [100.0]
    a = SchedulerArrays(
        max_workers=4, max_pending=8, max_inflight=16,
        time_to_expire=5.0, clock=lambda: t[0],
    )
    a.spec_mult = 2.0
    a.spec_min_s = 0.01
    a.register(b"w0", 2)
    a.register(b"w1", 2)
    a.tick(np.zeros(0, dtype=np.float32))  # seed prev_live
    a.inflight_add("x", 0, pred=0.1)
    t[0] += 100.0  # far past both the straggler threshold AND the hb TTL
    out = a.tick(np.zeros(0, dtype=np.float32))
    redis = np.asarray(out.redispatch)
    flags = np.asarray(out.straggler)
    assert redis[0] and not flags[0]


# ---------------------------------------------------------------------------
# tail-aware placement feedback (worker health)
# ---------------------------------------------------------------------------
def test_worker_health_decay_floor_recovery_and_register_reset():
    """note_hedge_loss decays multiplicatively to a hard floor; the tick
    recovers toward 1.0 at HEALTH_RECOVERY_TAU and SNAPS to exactly 1.0
    (the bit-stable steady state the cached device upload keys on); a
    recycled row registers with a clean slate."""
    from tpu_faas.sched.state import SchedulerArrays

    t = [100.0]
    a = SchedulerArrays(
        max_workers=4, max_pending=8, max_inflight=16, clock=lambda: t[0]
    )
    a.spec_mult = 2.0
    r0 = a.register(b"w0", 2)
    r1 = a.register(b"w1", 2)
    a.note_hedge_loss(r0)
    assert a.worker_health[r0] == pytest.approx(a.HEALTH_DECAY)
    for _ in range(30):
        a.note_hedge_loss(r0)
    assert a.worker_health[r0] == pytest.approx(a.HEALTH_FLOOR)
    # inactive and out-of-range rows are ignored (a purged worker's late
    # hedge resolution must not decay whoever recycled its row)
    a.deactivate(r1)
    a.note_hedge_loss(r1)
    assert a.worker_health[r1] == 1.0
    a.note_hedge_loss(-1)
    a.note_hedge_loss(99)
    # recovery: one tau closes ~63% of the gap, long idle snaps to 1.0
    a.tick(np.zeros(0, dtype=np.float32))  # primes the recovery stamp
    h0 = float(a.worker_health[r0])
    t[0] += a.HEALTH_RECOVERY_TAU
    a.tick(np.zeros(0, dtype=np.float32))
    h1 = float(a.worker_health[r0])
    assert h1 == pytest.approx(h0 + (1 - h0) * (1 - math.exp(-1)), abs=1e-3)
    t[0] += 40 * a.HEALTH_RECOVERY_TAU
    a.tick(np.zeros(0, dtype=np.float32))
    assert (a.worker_health == 1.0).all()
    # a fresh registrant on a recycled row does not inherit the penalty
    a.note_hedge_loss(r0)
    a.deactivate(r0)
    assert a.register(b"w0b", 2) == r0
    assert a.worker_health[r0] == 1.0


def test_worker_health_steers_placement_away_from_lossy_worker():
    """The _impl twin folds health into EFFECTIVE speed: a worker whose
    raw speed grade still says 'fastest' loses placements once its health
    multiplier says the tail disagrees."""
    from tpu_faas.sched.state import SchedulerArrays

    t = [100.0]
    a = SchedulerArrays(
        max_workers=2, max_pending=4, max_inflight=8, clock=lambda: t[0]
    )
    a.spec_mult = 2.0
    fast = a.register(b"fast", 2, speed=1.0)
    slow = a.register(b"slow", 2, speed=0.6)
    a.tick(np.zeros(0, dtype=np.float32))  # seed prev_live
    out = a.tick(np.asarray([1.0], dtype=np.float32))
    assert int(np.asarray(out.assignment)[0]) == fast
    # repeated lost hedge races: effective speed 1.0*0.25 < 0.6
    for _ in range(10):
        a.note_hedge_loss(fast)
    out = a.tick(np.asarray([1.0], dtype=np.float32))
    assert int(np.asarray(out.assignment)[0]) == slow


def test_worker_health_off_plane_is_inert():
    """Speculation off: no health operand reaches the tick (byte-identical
    trace) and a decayed value neither recovers nor influences placement."""
    from tpu_faas.sched.state import SchedulerArrays

    t = [100.0]
    a = SchedulerArrays(
        max_workers=2, max_pending=4, max_inflight=8, clock=lambda: t[0]
    )
    fast = a.register(b"fast", 2, speed=1.0)
    a.register(b"slow", 2, speed=0.6)
    a.worker_health[fast] = 0.1  # would lose every placement if consumed
    a.tick(np.zeros(0, dtype=np.float32))
    out = a.tick(np.asarray([1.0], dtype=np.float32))
    assert int(np.asarray(out.assignment)[0]) == fast
    t[0] += 1000.0
    a.tick(np.zeros(0, dtype=np.float32))
    assert a.worker_health[fast] == pytest.approx(0.1)  # no silent recovery


# ---------------------------------------------------------------------------
# dispatcher lifecycle units (fake worker rows, no sockets)
# ---------------------------------------------------------------------------
def _spec_dispatcher(clock, store=None, **kw):
    defaults = dict(
        ip="127.0.0.1", port=0, store=store or MemoryStore(),
        max_workers=8, max_pending=64, max_inflight=128, max_slots=2,
        tick_period=0.01, time_to_expire=1000.0, clock=clock,
        estimate_runtimes=False, speculate_mult=3.0,
        # single-task unit scenarios: the wasted-work budget must admit a
        # hedge with one dispatch on the books (the budget gate itself is
        # covered by test_budget_suppression_is_counted)
        speculate_max_frac=1.0, speculate_min_s=0.05,
    )
    defaults.update(kw)
    return TpuPushDispatcher(**defaults)


def _seed_speculative_task(disp, tid="task-1", cost=0.1):
    disp.store.create_task(
        tid, "fnp", "pp",
        extra_fields={FIELD_SPECULATIVE: "1", "cost": repr(cost)},
    )
    disp.pending.append(
        PendingTask(tid, "fnp", "pp", cost=cost, speculative=True)
    )


def _run_hedge_to_dispatched(disp, t, tid="task-1"):
    """Drive the batch dispatcher until the hedge replica is on the
    (fake) wire; returns the entry."""
    disp.tick(intake=False)  # dispatches the original
    assert disp.arrays.inflight_owner(tid) is not None
    t[0] += 0.5
    disp.tick(intake=False)  # no flag yet? pred=0.1 mult=3 -> 0.3 < 0.5 ok
    # the flag may land on this or the next tick depending on stamps;
    # iterate a couple of periods
    for _ in range(3):
        if disp.spec.entries.get(tid):
            break
        t[0] += 0.5
        disp.tick(intake=False)
    assert tid in disp.spec.entries, "straggler never flagged"
    # next tick places the ghost with anti-affinity and dispatches it
    disp.tick(intake=False)
    entry = disp.spec.entries[tid]
    assert entry.dispatched
    assert entry.hedge_row != entry.orig_row
    return entry


def test_dispatcher_hedges_straggler_and_replica_wins():
    t = [0.0]
    disp = _spec_dispatcher(lambda: t[0])
    try:
        a = disp.arrays
        r0 = a.register(b"w0", 2)
        r1 = a.register(b"w1", 2)
        _seed_speculative_task(disp)
        entry = _run_hedge_to_dispatched(disp, t)
        orig_row = entry.orig_row
        hedge_row = entry.hedge_row
        free_before = int(a.worker_free[orig_row])
        # replica's result arrives first -> replica wins, loser killed
        hedge_wid = a.row_ids[hedge_row]
        disp._handle(
            hedge_wid, m.RESULT,
            {"task_id": "task-1", "status": "COMPLETED", "result": "42",
             "elapsed": 0.05},
        )
        assert disp.spec.n_replica_wins == 1
        assert "task-1" not in disp.spec.entries
        # tail feedback: the loser's worker row took one health decay
        assert a.worker_health[orig_row] == pytest.approx(a.HEALTH_DECAY)
        assert a.worker_health[hedge_row] == 1.0
        assert a.inflight_owner("task-1") is None  # original's slot freed
        assert int(a.worker_free[orig_row]) == free_before + 1
        assert int(a.worker_free[hedge_row]) == 2  # replica slot back
        assert disp.store.get_status("task-1") == "COMPLETED"
        # the loser's late CANCELLED result: frozen write, waste counted
        orig_wid = a.row_ids[orig_row]
        disp._handle(
            orig_wid, m.RESULT,
            {"task_id": "task-1", "status": "CANCELLED", "result": "x",
             "elapsed": 1.2},
        )
        assert disp.store.get_status("task-1") == "COMPLETED"  # first wins
        assert disp.spec.wasted_exec_s == pytest.approx(1.2)
        assert int(a.worker_free[orig_row]) == free_before + 1  # no double
        assert r0 != r1  # sanity: two distinct rows existed
    finally:
        disp.close()


def test_dispatcher_original_wins_and_replica_is_killed():
    t = [0.0]
    disp = _spec_dispatcher(lambda: t[0])
    try:
        a = disp.arrays
        a.register(b"w0", 2)
        a.register(b"w1", 2)
        _seed_speculative_task(disp)
        entry = _run_hedge_to_dispatched(disp, t)
        orig_wid = a.row_ids[entry.orig_row]
        hedge_row = entry.hedge_row
        disp._handle(
            orig_wid, m.RESULT,
            {"task_id": "task-1", "status": "COMPLETED", "result": "7",
             "elapsed": 2.0},
        )
        assert disp.spec.n_original_wins == 1
        assert a.inflight_owner("task-1") is None
        assert int(a.worker_free[hedge_row]) == 2  # replica slot reclaimed
        assert disp.store.get_status("task-1") == "COMPLETED"
        # replica's late result is a frozen no-op and counted as waste
        disp._handle(
            a.row_ids[hedge_row], m.RESULT,
            {"task_id": "task-1", "status": "CANCELLED", "result": "x",
             "elapsed": 0.3},
        )
        assert disp.spec.wasted_exec_s == pytest.approx(0.3)
    finally:
        disp.close()


def test_dispatcher_promotes_replica_when_original_worker_dies():
    t = [0.0]
    disp = _spec_dispatcher(lambda: t[0], time_to_expire=5.0)
    try:
        a = disp.arrays
        a.register(b"w0", 2)
        a.register(b"w1", 2)
        _seed_speculative_task(disp)
        entry = _run_hedge_to_dispatched(disp, t)
        orig_row, hedge_row = entry.orig_row, entry.hedge_row
        hedge_wid = a.row_ids[hedge_row]
        # only the hedge's worker keeps heartbeating; the original's dies
        for _ in range(4):
            t[0] += 2.0
            a.heartbeat(hedge_wid)
            disp.tick(intake=False)
        assert disp.spec.n_promoted == 1
        assert "task-1" not in disp.spec.entries
        # the replica IS the owner now: its result completes the task
        assert a.inflight_owner("task-1") == hedge_row
        disp._handle(
            hedge_wid, m.RESULT,
            {"task_id": "task-1", "status": "COMPLETED", "result": "9",
             "elapsed": 0.1},
        )
        assert disp.store.get_status("task-1") == "COMPLETED"
        assert a.inflight_owner("task-1") is None
        assert int(a.worker_free[hedge_row]) == 2
        assert orig_row not in a.row_ids  # purged
    finally:
        disp.close()


def test_dispatcher_abandons_hedge_when_its_worker_dies():
    t = [0.0]
    disp = _spec_dispatcher(lambda: t[0], time_to_expire=5.0)
    try:
        a = disp.arrays
        a.register(b"w0", 2)
        a.register(b"w1", 2)
        _seed_speculative_task(disp)
        entry = _run_hedge_to_dispatched(disp, t)
        orig_wid = a.row_ids[entry.orig_row]
        # only the ORIGINAL's worker keeps heartbeating
        for _ in range(4):
            t[0] += 2.0
            a.heartbeat(orig_wid)
            disp.tick(intake=False)
        assert disp.spec.n_abandoned == 1
        # the still-straggling original may legitimately be RE-hedged —
        # but with no capacity off its own worker the new ghost can
        # never dispatch (anti-affinity holds it queued)
        e = disp.spec.entries.get("task-1")
        assert e is None or not e.dispatched
        # the original still owns the task and completes it normally
        assert a.inflight_owner("task-1") == entry.orig_row
        disp._handle(
            orig_wid, m.RESULT,
            {"task_id": "task-1", "status": "COMPLETED", "result": "1",
             "elapsed": 3.0},
        )
        assert disp.store.get_status("task-1") == "COMPLETED"
    finally:
        disp.close()


def test_non_speculative_task_never_hedges():
    t = [0.0]
    disp = _spec_dispatcher(lambda: t[0])
    try:
        a = disp.arrays
        a.register(b"w0", 2)
        a.register(b"w1", 2)
        disp.store.create_task("plain", "fnp", "pp",
                               extra_fields={"cost": repr(0.1)})
        disp.pending.append(PendingTask("plain", "fnp", "pp", cost=0.1))
        disp.tick(intake=False)
        for _ in range(4):
            t[0] += 2.0
            disp.tick(intake=False)
        assert disp.spec.n_launched == 0
        assert not disp.spec.entries
    finally:
        disp.close()


def test_budget_suppression_is_counted():
    t = [0.0]
    disp = _spec_dispatcher(lambda: t[0], speculate_max_frac=0.01)
    try:
        a = disp.arrays
        a.register(b"w0", 2)
        a.register(b"w1", 2)
        _seed_speculative_task(disp)
        disp.tick(intake=False)
        for _ in range(4):
            t[0] += 2.0
            disp.tick(intake=False)
        # 1 task dispatched, budget 0.01 -> a single hedge never fits
        assert disp.spec.n_launched == 0
        assert disp.spec.n_suppressed_budget > 0
        assert disp.stats()["speculation"]["suppressed_budget"] > 0
    finally:
        disp.close()


def test_estimator_graded_by_winner_only():
    """The replica's (winner's) exec window grades its worker; the
    loser's CANCELLED window must not move any grade (satellite pinned
    independently in test_estimator.py; this is the dispatcher-level
    integration)."""
    t = [0.0]
    disp = _spec_dispatcher(lambda: t[0], estimate_runtimes=True)
    try:
        a = disp.arrays
        a.register(b"w0", 2)
        a.register(b"w1", 2)
        _seed_speculative_task(disp)
        entry = _run_hedge_to_dispatched(disp, t)
        hedge_wid = a.row_ids[entry.hedge_row]
        orig_wid = a.row_ids[entry.orig_row]
        disp._handle(
            hedge_wid, m.RESULT,
            {"task_id": "task-1", "status": "COMPLETED", "result": "42",
             "elapsed": 0.05},
        )
        n_after_win = disp.estimator.n_observations
        assert n_after_win >= 1  # winner observed
        disp._handle(
            orig_wid, m.RESULT,
            {"task_id": "task-1", "status": "CANCELLED", "result": "x",
             "elapsed": 9.9},
        )
        assert disp.estimator.n_observations == n_after_win  # loser not
    finally:
        disp.close()


def test_spec_off_is_inert_everywhere():
    """No --speculate-mult = None policy, no spec metrics families, no
    straggler lanes in the tick, stats block None — the plane costs
    nothing and changes nothing."""
    disp = TpuPushDispatcher(
        ip="127.0.0.1", port=0, store=MemoryStore(),
        max_workers=8, max_pending=64, max_inflight=128,
        estimate_runtimes=False,
    )
    try:
        assert disp.spec is None
        assert disp.stats()["speculation"] is None
        assert disp.arrays.spec_mult is None
        assert not hasattr(disp, "m_hedges")
        assert "tpu_faas_dispatcher_hedges_total" not in disp.render_metrics()
    finally:
        disp.close()


def test_mesh_and_multihost_refuse_speculation():
    with pytest.raises(ValueError, match="single-device"):
        TpuPushDispatcher(
            ip="127.0.0.1", port=0, store=MemoryStore(),
            mesh_devices=2, speculate_mult=3.0,
        )


def test_reclaim_fields_carry_the_speculative_flag():
    assert FIELD_SPECULATIVE in RECLAIM_FIELDS
    pt = PendingTask.from_fields(
        "t", {"fn_payload": "f", "param_payload": "p",
              FIELD_SPECULATIVE: "1"},
    )
    assert pt.speculative
    pt2 = PendingTask.from_fields(
        "t", {"fn_payload": "f", "param_payload": "p"},
    )
    assert not pt2.speculative


# ---------------------------------------------------------------------------
# gateway / SDK surface
# ---------------------------------------------------------------------------
def test_gateway_hint_parse_speculative():
    from tpu_faas.gateway.app import _parse_hints

    assert FIELD_SPECULATIVE not in _parse_hints(None, None)
    assert FIELD_SPECULATIVE not in _parse_hints(
        None, None, speculative=False
    )
    assert _parse_hints(None, None, speculative=True)[
        FIELD_SPECULATIVE
    ] == "1"
    with pytest.raises(ValueError, match="speculative"):
        _parse_hints(None, None, speculative="yes")


def test_gateway_safety_poll_knob_and_counter():
    from tpu_faas.gateway.app import make_app, CTX_KEY

    app = make_app(MemoryStore(), wait_safety_poll_s=5.0)
    ctx = app[CTX_KEY]
    assert ctx.wait_safety_poll_s == 5.0
    ctx.m_safety_poll.inc()
    from tpu_faas.obs.metrics import render

    text = render([ctx.metrics])
    assert "tpu_faas_gateway_safety_poll_served_total 1" in text


# ---------------------------------------------------------------------------
# full-stack e2e + chaos (real store server, gateway, workers)
# ---------------------------------------------------------------------------
def _spawn_push_worker(url, delay=None):
    from tests.test_workers_e2e import _GroupPopen
    from tpu_faas.bench.harness import REPO, cpu_worker_env

    env = cpu_worker_env()
    if delay:
        env["TPU_FAAS_EXEC_DELAY_S"] = str(delay)
    # _GroupPopen: a SIGKILL must reap the worker's forkserver/resource-
    # tracker children too (group kill), or chaos tests leak them
    return _GroupPopen(
        [sys.executable, "-m", "tpu_faas.worker.push_worker", "2", url,
         "--hb", "--hb-period", "0.3"],
        env=env, cwd=REPO, start_new_session=True,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def _spec_stack(monitor, speculate=True, time_to_expire=2.0):
    from tpu_faas.gateway import start_gateway_thread
    from tpu_faas.store.launch import start_store_thread
    from tpu_faas.store.racecheck import RaceCheckStore

    handle = start_store_thread()
    gw = start_gateway_thread(
        RaceCheckStore(make_store(handle.url), monitor, actor="gateway")
    )
    kw = dict(
        ip="127.0.0.1", port=0,
        store=RaceCheckStore(
            make_store(handle.url), monitor, actor="dispatcher"
        ),
        max_workers=64, max_pending=256, max_inflight=512, max_slots=2,
        tick_period=0.01, time_to_expire=time_to_expire,
        estimate_runtimes=False,
    )
    if speculate:
        kw.update(
            speculate_mult=3.0, speculate_max_frac=0.5,
            speculate_min_s=0.05,
        )
    disp = TpuPushDispatcher(**kw)
    thread = threading.Thread(target=disp.start, daemon=True)
    thread.start()
    return handle, gw, disp, thread


def test_e2e_hedge_replica_wins_under_race_monitor():
    """Full stack, one sick worker (3 s exec delay): speculative tasks
    that land on it are hedged and complete fast via the replica; slot
    accounting converges; zero race-monitor errors."""
    from tpu_faas.client import FaaSClient
    from tpu_faas.core.serialize import serialize
    from tpu_faas.store.racecheck import RaceMonitor
    from tpu_faas.workloads import straggler_sleep

    monitor = RaceMonitor()
    handle, gw, disp, thread = _spec_stack(monitor, time_to_expire=5.0)
    url = f"tcp://127.0.0.1:{disp.port}"
    slow = _spawn_push_worker(url, delay=3.0)
    fast = _spawn_push_worker(url)
    try:
        time.sleep(1.5)
        c = FaaSClient(gw.url)
        fid = c.register_payload(
            "straggler_sleep", serialize(straggler_sleep)
        )
        for h in c.submit_many(fid, [(((0.01,), {}))] * 4):  # warm pools
            h.result(timeout=60)
        handles = [
            c.submit_with(fid, (0.05,), cost=0.05, speculative=True)
            for _ in range(8)
        ]
        t0 = time.monotonic()
        results = [h.result(timeout=120) for h in handles]
        elapsed = time.monotonic() - t0
        assert results == [0.05] * 8
        # the hedges carried the slow worker's victims: far under the
        # 3 s the sick worker would have cost
        assert elapsed < 2.5, f"hedging did not beat the straggler ({elapsed:.2f}s)"
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and disp.spec.entries:
            time.sleep(0.05)
        assert disp.spec.n_launched >= 1
        assert disp.spec.n_replica_wins >= 1
        assert not disp.spec.entries
        assert disp.arrays.n_inflight == 0
        assert not monitor.errors, [str(v) for v in monitor.errors]
        # hedge metrics on the rendered scrape
        text = disp.render_metrics()
        assert 'tpu_faas_dispatcher_hedges_total{outcome="launched"}' in text
    finally:
        for w in (slow, fast):
            w.kill()
            w.wait()
        disp.stop()
        thread.join(timeout=10)
        gw.stop()
        handle.stop()


def test_e2e_chaos_sigkill_original_mid_hedge_zero_loss():
    """The chaos story: SIGKILL the worker running the ORIGINALS while
    hedges are outstanding — every admitted task still completes (via the
    replicas or promotion), zero race-monitor errors."""
    from tpu_faas.client import FaaSClient
    from tpu_faas.core.serialize import serialize
    from tpu_faas.store.racecheck import RaceMonitor
    from tpu_faas.workloads import straggler_sleep

    monitor = RaceMonitor()
    handle, gw, disp, thread = _spec_stack(monitor, time_to_expire=2.0)
    url = f"tcp://127.0.0.1:{disp.port}"
    slow = _spawn_push_worker(url, delay=8.0)
    fast = _spawn_push_worker(url)
    try:
        time.sleep(1.5)
        c = FaaSClient(gw.url)
        fid = c.register_payload(
            "straggler_sleep", serialize(straggler_sleep)
        )
        for h in c.submit_many(fid, [(((0.01,), {}))] * 4):
            h.result(timeout=60)
        handles = [
            c.submit_with(fid, (0.05,), cost=0.05, speculative=True)
            for _ in range(8)
        ]
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and disp.spec.n_launched == 0:
            time.sleep(0.02)
        assert disp.spec.n_launched > 0, "no hedge launched before kill"
        slow.kill()
        slow.wait()
        results = [h.result(timeout=120) for h in handles]
        assert results == [0.05] * 8  # zero admitted-task loss
        assert not monitor.errors, [str(v) for v in monitor.errors]
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and disp.arrays.n_inflight:
            time.sleep(0.05)
        assert disp.arrays.n_inflight == 0
    finally:
        for w in (slow, fast):
            if w.poll() is None:
                w.kill()
                w.wait()
        disp.stop()
        thread.join(timeout=10)
        gw.stop()
        handle.stop()


def test_resident_ghost_slot_swaps_back_to_reclaimed_original():
    """Review regression: in resident mode a hedge GHOST occupying
    _resident_tasks must not make the move loop drop the REAL task when
    its reclaimed original comes back around — the ghost's device slot
    becomes the re-dispatch vehicle (payload swapped), not a silent
    drop that strands the task until lease adoption."""
    t = [0.0]
    disp = _spec_dispatcher(
        lambda: t[0], resident=True, time_to_expire=5.0,
    )
    try:
        a = disp.arrays
        a.register(b"w0", 2)
        _seed_speculative_task(disp)
        disp.tick(intake=False)  # original dispatched to w0
        assert a.inflight_owner("task-1") is not None
        # flag the straggler; the ghost queues but can NEVER place (the
        # only live worker is the forbidden one)
        for _ in range(4):
            t[0] += 0.5
            a.heartbeat(b"w0")
            disp.tick(intake=False)
        assert "task-1" in disp.spec.entries
        assert not disp.spec.entries["task-1"].dispatched
        # the ghost now holds the task id in the device pending set
        assert disp._resident_tasks.get("task-1") is not None
        assert disp._resident_tasks["task-1"].is_hedge
        # original's worker dies: reclaim abandons the hedge and
        # re-queues the REAL task, which must displace the ghost
        for _ in range(4):
            t[0] += 2.0
            disp.tick(intake=False)
        assert "task-1" not in disp.spec.entries
        occ = disp._resident_tasks.get("task-1")
        assert occ is not None and not occ.is_hedge, (
            "reclaimed original was dropped in favor of a dead ghost"
        )
        # a replacement worker appears: the task dispatches to it
        a.register(b"w1", 2)
        for _ in range(3):
            t[0] += 0.2
            disp.tick(intake=False)
        owner = a.inflight_owner("task-1")
        assert owner is not None and a.row_ids[owner] == b"w1"
    finally:
        disp.close()


def test_promoted_replica_result_rides_first_wins():
    """Review regression: a purged-but-alive zombie original can still
    ship a result after its replica was promoted — the promoted
    replica's own write must ride first-wins so it can never overwrite
    the terminal record a client may already have consumed."""
    t = [0.0]
    disp = _spec_dispatcher(lambda: t[0], time_to_expire=5.0)
    try:
        a = disp.arrays
        a.register(b"w0", 2)
        a.register(b"w1", 2)
        _seed_speculative_task(disp)
        entry = _run_hedge_to_dispatched(disp, t)
        hedge_wid = a.row_ids[entry.hedge_row]
        orig_wid = a.row_ids[entry.orig_row]
        for _ in range(4):  # purge the (stalled, not dead) original
            t[0] += 2.0
            a.heartbeat(hedge_wid)
            disp.tick(intake=False)
        assert disp.spec.n_promoted == 1
        # the zombie wakes up and ships its result FIRST
        disp._handle(
            orig_wid, m.RESULT,
            {"task_id": "task-1", "status": "COMPLETED",
             "result": "zombie", "elapsed": 9.0},
        )
        assert disp.store.hget("task-1", "result") == "zombie"
        # the promoted replica's later result must NOT overwrite it
        disp._handle(
            hedge_wid, m.RESULT,
            {"task_id": "task-1", "status": "COMPLETED",
             "result": "replica", "elapsed": 0.1},
        )
        assert disp.store.hget("task-1", "result") == "zombie"
        assert disp.store.get_status("task-1") == "COMPLETED"
    finally:
        disp.close()
