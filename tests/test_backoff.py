"""Unit tests for the shared jittered-exponential backoff policy
(tpu_faas/utils/backoff.py) — the single retry schedule behind the SDK
overload loops, the pull worker's blob fetch, and the replica link."""
from __future__ import annotations

import random

import pytest

from tpu_faas.utils.backoff import Backoff, BackoffPolicy


def test_base_grows_exponentially_to_cap():
    p = BackoffPolicy(floor_s=0.25, factor=2.0, cap_s=30.0)
    assert p.base(0) == 0.25
    assert p.base(1) == 0.5
    assert p.base(2) == 1.0
    # 0.25 * 2**7 = 32 > cap
    assert p.base(7) == 30.0
    assert p.base(100) == 30.0


def test_hint_is_a_lower_bound_not_a_ceiling():
    p = BackoffPolicy(floor_s=0.25, factor=2.0, cap_s=30.0)
    # server asked for more than the local schedule: honor it
    assert p.base(0, hint=5.0) == 5.0
    # local schedule has overtaken the hint: keep growing
    assert p.base(6, hint=5.0) == 16.0


def test_jitter_bounds_and_determinism():
    p = BackoffPolicy(floor_s=1.0, factor=2.0, cap_s=30.0,
                      jitter_lo=0.8, jitter_hi=1.3)
    rng = random.Random(7)
    for attempt in range(6):
        base = p.base(attempt)
        d = p.delay(attempt, rng=rng)
        assert base * 0.8 <= d <= base * 1.3
    # same seed -> same sequence
    a = [p.delay(i, rng=random.Random(42)) for i in range(5)]
    b = [p.delay(i, rng=random.Random(42)) for i in range(5)]
    assert a == b


def test_unit_jitter_is_identity():
    p = BackoffPolicy(floor_s=0.3, jitter_lo=1.0, jitter_hi=1.0)
    assert p.delay(0) == 0.3
    assert p.delay(1) == 0.6


def test_clamp_bounds_base_before_jitter():
    p = BackoffPolicy(floor_s=10.0, cap_s=30.0, jitter_lo=1.0, jitter_hi=1.0)
    assert p.delay(0, clamp=2.5) == 2.5
    # a negative remaining budget clamps to zero, never negative
    assert p.delay(0, clamp=-1.0) == 0.0
    # jitter applies to the clamped value (may exceed the clamp by at
    # most jitter_hi - documented call-site semantics)
    pj = BackoffPolicy(floor_s=10.0, jitter_lo=1.2, jitter_hi=1.2)
    assert pj.delay(0, clamp=2.0) == pytest.approx(2.4)


def test_stateful_backoff_advances_and_resets():
    bo = Backoff(BackoffPolicy(floor_s=0.5, factor=2.0, cap_s=8.0,
                               jitter_lo=1.0, jitter_hi=1.0))
    assert bo.peek() == 0.5
    assert bo.next() == 0.5
    assert bo.peek() == 1.0
    assert bo.next() == 1.0
    assert bo.next() == 2.0
    bo.reset()
    assert bo.next() == 0.5


def test_call_site_policies_match_pre_refactor_constants():
    """The refactor must not change the shipped schedules."""
    from tpu_faas.client.aio import CONNECT_BACKOFF
    from tpu_faas.client.sdk import OVERLOAD_BACKOFF
    from tpu_faas.store.replication import ACK_PERIOD, RECONNECT_BACKOFF
    from tpu_faas.worker.pull_worker import _BLOB_BACKOFF

    assert (OVERLOAD_BACKOFF.floor_s, OVERLOAD_BACKOFF.factor,
            OVERLOAD_BACKOFF.cap_s) == (0.25, 2.0, 30.0)
    assert (OVERLOAD_BACKOFF.jitter_lo, OVERLOAD_BACKOFF.jitter_hi) == (0.8, 1.3)
    assert (CONNECT_BACKOFF.floor_s, CONNECT_BACKOFF.factor) == (0.3, 2.0)
    assert CONNECT_BACKOFF.jitter_lo == CONNECT_BACKOFF.jitter_hi == 1.0
    assert RECONNECT_BACKOFF.floor_s == ACK_PERIOD
    assert _BLOB_BACKOFF.cap_s == 1.0  # liveness-bounded: see pull_worker
