"""Unified telemetry (tpu_faas/obs): registry semantics, exposition-format
conformance under the strict grammar, record-while-scrape thread safety,
per-task lifecycle timelines for the success/retry/cancel/timeout paths,
the device-tick profiling hooks, and the /metrics + /trace HTTP surface on
a dispatcher driven end to end."""

from __future__ import annotations

import json
import logging
import threading
import time

import pytest
import requests

from tpu_faas.obs import (
    EVENTS,
    REGISTRY,
    MetricsRegistry,
    TaskTraceBook,
    render,
)
from tpu_faas.obs.expofmt import ExpositionError, parse_exposition
from tpu_faas.obs.profile import TickProfiler
from tpu_faas.core.task import FIELD_SUBMITTED_AT
from tpu_faas.store.memory import MemoryStore
from tpu_faas.utils.logging import JsonFormatter, TickTracer, percentile
from tpu_faas.worker import messages as m


# -- registry primitives -----------------------------------------------------


def test_counter_gauge_histogram_basics():
    r = MetricsRegistry()
    c = r.counter("c_total", "help")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)
    g = r.gauge("g", "help")
    g.set(7)
    g.dec(2)
    assert g.value == 5
    h = r.histogram("h_seconds", "help", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(100.0)
    # re-registration returns the SAME object; conflicts are rejected
    assert r.counter("c_total", "help") is c
    with pytest.raises(ValueError):
        r.gauge("c_total", "different type")
    with pytest.raises(ValueError):
        r.counter("c_total", "help", ("newlabel",))


def test_labeled_children_and_validation():
    r = MetricsRegistry()
    c = r.counter("req_total", "help", ("route",))
    c.labels(route="a").inc()
    c.labels("a").inc()  # positional addressing hits the same child
    assert c.labels(route="a").value == 2
    with pytest.raises(ValueError):
        c.inc()  # labeled metric needs .labels()
    with pytest.raises(ValueError):
        c.labels(nope="x")
    with pytest.raises(ValueError):
        r.counter("bad name", "help")
    with pytest.raises(ValueError):
        r.counter("ok", "help", ("__reserved",))


def test_unlabeled_families_render_at_zero_before_traffic():
    """The catalog is visible from the first scrape — a dashboard must not
    need traffic before its queries resolve."""
    r = MetricsRegistry()
    r.counter("quiet_total", "never incremented")
    fams = parse_exposition(render([r]))
    assert fams["quiet_total"].samples[0].value == 0


def test_collector_refreshes_gauges_at_render_time():
    r = MetricsRegistry()
    g = r.gauge("depth", "queue depth")
    state = {"n": 3}
    r.register_collector(lambda: g.set(state["n"]))
    assert parse_exposition(render([r]))["depth"].samples[0].value == 3
    state["n"] = 9
    assert parse_exposition(render([r]))["depth"].samples[0].value == 9
    r.unregister_collector(next(iter(r._collectors)))


def test_render_rejects_duplicate_family_across_registries():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.counter("dup_total", "x")
    b.counter("dup_total", "x")
    with pytest.raises(ValueError):
        render([a, b])


# -- exposition conformance --------------------------------------------------


def _full_registry() -> MetricsRegistry:
    r = MetricsRegistry()
    c = r.counter("t_total", "tasks", ("status",))
    c.labels(status="COMPLETED").inc(3)
    c.labels(status='we"ird\\la\nbel').inc()
    r.gauge("depth", "pending").set(17)
    h = r.histogram("lat_seconds", "latency", ("stage",), buckets=(0.01, 0.1, 1))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.labels(stage="exec").observe(v)
    return r


def test_rendered_exposition_passes_strict_grammar():
    fams = parse_exposition(render([_full_registry()]))
    assert fams["t_total"].mtype == "counter"
    assert fams["lat_seconds"].mtype == "histogram"
    # escaping round-trips: the parser recovers the raw label value
    values = {
        s.labels["status"] for s in fams["t_total"].samples
    }
    assert 'we"ird\\la\nbel' in values
    # histogram invariants verified by the parser; spot-check cumulative
    exec_buckets = [
        s.value
        for s in fams["lat_seconds"].samples
        if s.name == "lat_seconds_bucket"
    ]
    assert exec_buckets == sorted(exec_buckets)
    [count] = [
        s.value
        for s in fams["lat_seconds"].samples
        if s.name == "lat_seconds_count"
    ]
    assert count == 4


@pytest.mark.parametrize(
    "body",
    [
        # sample before any declaration
        "orphan_total 1\n",
        # TYPE before HELP
        "# TYPE x counter\n# HELP x help\nx 1\n",
        # repeated HELP
        "# HELP x h\n# TYPE x counter\nx 1\n# HELP x h\n",
        # sample outside its declared family
        "# HELP x h\n# TYPE x counter\ny_total 1\n",
        # counter with a negative value
        "# HELP x h\n# TYPE x counter\nx -1\n",
        # histogram without +Inf
        "# HELP h h\n# TYPE h histogram\n"
        'h_bucket{le="1.0"} 1\nh_sum 1\nh_count 1\n',
        # non-cumulative buckets
        "# HELP h h\n# TYPE h histogram\n"
        'h_bucket{le="1.0"} 5\nh_bucket{le="+Inf"} 3\nh_sum 1\nh_count 3\n',
        # _count disagrees with the +Inf bucket
        "# HELP h h\n# TYPE h histogram\n"
        'h_bucket{le="1.0"} 1\nh_bucket{le="+Inf"} 2\nh_sum 1\nh_count 9\n',
        # missing _sum
        "# HELP h h\n# TYPE h histogram\n"
        'h_bucket{le="+Inf"} 1\nh_count 1\n',
        # bad escape in a label value
        '# HELP x h\n# TYPE x counter\nx{a="\\q"} 1\n',
        # duplicate series
        "# HELP x h\n# TYPE x counter\nx 1\nx 2\n",
        # missing trailing newline
        "# HELP x h\n# TYPE x counter\nx 1",
    ],
)
def test_parser_rejects_malformed_exposition(body):
    with pytest.raises(ExpositionError):
        parse_exposition(body)


def test_concurrent_record_while_scrape():
    """Hot-path recording from several threads while another thread renders
    continuously: no exceptions, every intermediate render parses, final
    totals are exact."""
    r = MetricsRegistry()
    c = r.counter("n_total", "count", ("t",))
    h = r.histogram("d_seconds", "durations", buckets=(0.5,))
    stop = threading.Event()
    errors: list[BaseException] = []
    N, THREADS = 2000, 4

    def writer(tag: str) -> None:
        try:
            child = c.labels(t=tag)
            for i in range(N):
                child.inc()
                h.observe(0.1 if i % 2 else 0.9)
        except BaseException as exc:  # pragma: no cover - failure path
            errors.append(exc)

    def scraper() -> None:
        try:
            while not stop.is_set():
                parse_exposition(render([r]))
        except BaseException as exc:  # pragma: no cover - failure path
            errors.append(exc)

    threads = [
        threading.Thread(target=writer, args=(f"w{i}",))
        for i in range(THREADS)
    ]
    s = threading.Thread(target=scraper)
    s.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    s.join()
    assert errors == []
    fams = parse_exposition(render([r]))
    totals = {s_.labels["t"]: s_.value for s_ in fams["n_total"].samples}
    assert totals == {f"w{i}": N for i in range(THREADS)}
    [count] = [
        s_.value for s_ in fams["d_seconds"].samples
        if s_.name == "d_seconds_count"
    ]
    assert count == N * THREADS


# -- TickTracer + percentile fix ---------------------------------------------


def test_percentile_nearest_rank():
    data = [float(i) for i in range(1, 101)]  # 1..100
    assert percentile(data, 0.99) == 99.0  # was 100.0 with the old indexing
    assert percentile(data, 0.5) == 50.0
    assert percentile([7.0], 0.99) == 7.0
    assert percentile([1.0, 2.0], 0.99) == 2.0
    with pytest.raises(ValueError):
        percentile([], 0.5)


def test_tracer_summary_uses_nearest_rank_p99():
    tr = TickTracer()
    for i in range(1, 101):
        tr.record("x", float(i))
    assert tr.summary()["x"]["p99"] == 99.0


def test_tracer_mirror_feeds_registry_histogram():
    r = MetricsRegistry()
    h = r.histogram("span_seconds", "spans", ("span",), buckets=(0.5, 2.0))
    tr = TickTracer(mirror=h)
    tr.record("tick", 0.1)
    tr.record("tick", 1.0)
    fams = parse_exposition(render([r]))
    [count] = [
        s.value
        for s in fams["span_seconds"].samples
        if s.name == "span_seconds_count" and s.labels["span"] == "tick"
    ]
    assert count == 2
    assert tr.summary()["tick"]["count"] == 2  # the /stats view agrees


# -- task timelines ----------------------------------------------------------


def test_trace_book_stage_math_and_rings():
    r = MetricsRegistry()
    book = TaskTraceBook(r, recent_cap=4, slowest_cap=2)
    t0 = 1000.0
    for i, ev in enumerate(EVENTS[:-1]):
        book.note("t1", ev, ts=t0 + i)
    book.finish("t1", outcome="COMPLETED", ts=t0 + len(EVENTS) - 1)
    rec = book.timeline("t1")
    assert rec["complete"] is True
    assert rec["outcome"] == "COMPLETED"
    assert list(rec["events"]) == list(EVENTS)
    assert rec["stages"]["execution"] == 1.0
    assert rec["stages"]["total"] == 8.0
    # aggregated into the stage histogram
    fams = parse_exposition(render([r]))
    sums = {
        s.labels["stage"]: s.value
        for s in fams["tpu_faas_task_stage_seconds"].samples
        if s.name.endswith("_sum")
    }
    assert sums["total"] == 8.0
    # unknown finish is a no-op; duplicate events keep the first stamp
    book.finish("ghost", outcome="COMPLETED")
    assert book.timeline("ghost") is None


def test_trace_book_bounds_and_slowest():
    r = MetricsRegistry()
    book = TaskTraceBook(r, active_cap=8, recent_cap=4, slowest_cap=2)
    for i in range(32):
        tid = f"t{i}"
        book.note(tid, "intake", ts=100.0)
        book.note(tid, "scheduled", ts=100.0 + i)
        book.note(tid, "submitted", ts=99.0)
        book.finish(tid, outcome="COMPLETED", ts=200.0)
    assert len(book.recent(100)) == 4
    slow = book.slowest()
    assert len(slow) == 2
    # open timelines are capped too
    for i in range(100):
        book.note(f"open{i}", "announced")
    assert book.stats()["active"] <= 8


# -- a dispatcher driven end to end (no subprocesses) ------------------------


def _drive_dispatcher():
    """TpuPushDispatcher over a MemoryStore with a fake registered worker
    (sends to a never-connected peer are dropped by ZMQ — the bench's
    config-9 trick), driven through submit -> tick -> synthetic RESULT."""
    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher

    store = MemoryStore()
    disp = TpuPushDispatcher(
        ip="127.0.0.1",
        port=0,
        store=store,
        max_workers=8,
        max_pending=64,
        max_inflight=128,
        recover_queued=False,
        estimate_runtimes=False,
    )
    disp._handle(b"w1", m.REGISTER, {"num_processes": 4})
    return store, disp


def _submit(store, tid: str, **extra: str) -> None:
    store.create_task(
        tid, "F", "P", "tasks",
        {FIELD_SUBMITTED_AT: repr(time.time()), **extra},
    )


def _result(disp, tid: str, status: str = "COMPLETED") -> None:
    # a real child starts AFTER the send and finishes BEFORE its result
    # arrives: give the synthetic stamps the same ordering (sleep past the
    # dispatch, then back-date exec_start/exec_end inside the gap)
    time.sleep(0.03)
    started = time.time() - 0.02
    disp._handle(
        b"w1",
        m.RESULT,
        {
            "task_id": tid,
            "status": status,
            "result": "r",
            "elapsed": 0.01,
            "started_at": started,
        },
    )


def test_timeline_success_path_has_all_nine_events():
    store, disp = _drive_dispatcher()
    try:
        _submit(store, "ok-1")
        disp.tick()
        _result(disp, "ok-1")
        rec = disp.traces.timeline("ok-1")
        assert rec is not None and rec["complete"], rec
        assert list(rec["events"]) == list(EVENTS)
        assert rec["outcome"] == "COMPLETED"
        assert rec["stages"]["execution"] > 0
        assert disp.m_results.labels(status="COMPLETED").value == 1
    finally:
        disp.socket.close(linger=0)
        disp.close()


def test_timeline_timeout_path_closes_as_failed():
    """A task killed by its execution budget ships a FAILED result — the
    timeline closes complete with outcome FAILED."""
    store, disp = _drive_dispatcher()
    try:
        _submit(store, "slow-1", timeout="0.05")
        disp.tick()
        _result(disp, "slow-1", status="FAILED")
        rec = disp.traces.timeline("slow-1")
        assert rec["complete"] and rec["outcome"] == "FAILED"
    finally:
        disp.socket.close(linger=0)
        disp.close()


def test_timeline_cancel_path_closes_without_dispatch():
    store, disp = _drive_dispatcher()
    try:
        _submit(store, "c-1")
        disp._intake()  # task is sitting in pending when the cancel lands
        assert store.cancel_task("c-1") == "CANCELLED"
        disp.note_cancelled("c-1")
        disp.tick(intake=False)
        rec = disp.traces.timeline("c-1")
        assert rec is not None and rec["outcome"] == "dropped_cancelled"
        assert "sent" not in rec["events"]  # never went to a worker
        assert disp.m_cancelled_dropped.value == 1
    finally:
        disp.socket.close(linger=0)
        disp.close()


def test_timeline_retry_path_records_reclaims():
    store, disp = _drive_dispatcher()
    try:
        _submit(store, "r-1")
        disp.tick()  # dispatched to the fake worker
        # worker dies: reclaim the in-flight task (the device-tick purge
        # path funnels into the same helper)
        pt = disp.reclaim_or_fail("r-1", 0, 3)
        assert pt is not None and pt.retries == 1
        disp.task_retries["r-1"] = pt.retries
        disp.pending.append(pt)
        disp.arrays.inflight_done("r-1")
        disp.tick()  # re-dispatch
        _result(disp, "r-1")
        rec = disp.traces.timeline("r-1")
        assert rec["complete"] and rec["retries"] == 1
        assert rec["outcome"] == "COMPLETED"
    finally:
        disp.socket.close(linger=0)
        disp.close()


def test_dispatcher_metrics_and_trace_http_endpoints():
    """The dispatcher's scrape surface end to end over HTTP: /metrics is
    valid exposition carrying the required series, /trace/<id> returns the
    closed nine-event timeline, /trace lists rings, /stats stays JSON."""
    store, disp = _drive_dispatcher()
    server = disp.serve_stats(0)
    port = server.server_address[1]
    base = f"http://127.0.0.1:{port}"
    try:
        _submit(store, "e2e-1")
        disp.tick()
        _result(disp, "e2e-1")

        r = requests.get(f"{base}/metrics")
        assert r.status_code == 200
        fams = parse_exposition(r.text)
        for family in (
            "tpu_faas_dispatcher_pending_tasks",
            "tpu_faas_dispatcher_inflight_tasks",
            "tpu_faas_dispatcher_workers_registered",
            "tpu_faas_dispatcher_tasks_dispatched_total",
            "tpu_faas_dispatcher_results_total",
            "tpu_faas_dispatcher_workers_purged_total",
            "tpu_faas_dispatcher_worker_misfires",
            "tpu_faas_task_stage_seconds",
            "tpu_faas_span_seconds",
            "tpu_faas_jit_recompiles_total",
            "tpu_faas_tick_shape",
        ):
            assert family in fams, f"missing {family}"
        assert fams["tpu_faas_dispatcher_workers_registered"].samples[0].value == 1
        [disp_total] = fams["tpu_faas_dispatcher_tasks_dispatched_total"].samples
        assert disp_total.value == 1
        # device-tick duration made it into the span histogram
        tick_counts = [
            s.value
            for s in fams["tpu_faas_span_seconds"].samples
            if s.name.endswith("_count") and s.labels["span"] == "device_tick"
        ]
        assert tick_counts and tick_counts[0] > 0

        r = requests.get(f"{base}/trace/e2e-1")
        assert r.status_code == 200
        rec = r.json()
        assert list(rec["events"]) == list(EVENTS) and rec["complete"]

        assert requests.get(f"{base}/trace/ghost").status_code == 404
        ring = requests.get(f"{base}/trace").json()
        assert ring["completed"] >= 1
        assert any(t["task_id"] == "e2e-1" for t in ring["recent"])
        assert requests.get(f"{base}/stats").json()["store_down"] is False
    finally:
        disp.socket.close(linger=0)
        disp.stop()
        disp.close()


# -- device-tick profiling hooks ---------------------------------------------


def test_tick_profiler_counts_signatures_once():
    r = MetricsRegistry()
    p = TickProfiler(r)
    sig_a = ("batch", 64, 8, 4, "rank", False)
    assert p.observe_shape(tasks=64, workers=8, slots=4, signature=sig_a)
    assert not p.observe_shape(tasks=64, workers=8, slots=4, signature=sig_a)
    sig_b = ("batch", 64, 8, 4, "rank", True)  # priority lane appears
    assert p.observe_shape(tasks=64, workers=8, slots=4, signature=sig_b)
    fams = parse_exposition(render([r]))
    assert fams["tpu_faas_jit_recompiles_total"].samples[0].value == 2
    shape = {
        s.labels["dim"]: s.value for s in fams["tpu_faas_tick_shape"].samples
    }
    assert shape == {"tasks": 64, "workers": 8, "slots": 4}
    assert fams["tpu_faas_device_ticks_total"].samples[0].value == 3


def test_tick_profiler_steady_state_stays_flat():
    """The real dispatcher's batch tick presents ONE signature in steady
    state — the recompile counter must not creep with traffic."""
    store, disp = _drive_dispatcher()
    try:
        for i in range(3):
            _submit(store, f"p-{i}")
            disp.tick()
            _result(disp, f"p-{i}")
        assert disp.profiler.n_signatures == 1
        assert (
            disp.metrics._metrics["tpu_faas_jit_recompiles_total"].value == 1
        )
    finally:
        disp.socket.close(linger=0)
        disp.close()


def test_tick_capture_no_env_is_noop(monkeypatch):
    monkeypatch.delenv("TPU_FAAS_JAX_PROFILE_DIR", raising=False)
    p = TickProfiler(MetricsRegistry())
    with p.tick_capture():
        pass
    p.close()


# -- structured JSON logging -------------------------------------------------


def test_json_formatter_emits_correlation_fields():
    from tpu_faas.utils.logging import log_ctx

    fmt = JsonFormatter()
    rec = logging.LogRecord(
        "tpu_faas.test", logging.INFO, __file__, 1,
        "dispatched %s", ("t-9",), None,
    )
    for k, v in log_ctx(task_id="t-9", worker_id="w-3", absent=None).items():
        setattr(rec, k, v)
    out = json.loads(fmt.format(rec))
    assert out["msg"] == "dispatched t-9"
    assert out["task_id"] == "t-9"
    assert out["worker_id"] == "w-3"
    assert out["level"] == "INFO"
    assert "absent" not in out


def test_log_format_env_switches_handler(monkeypatch):
    import importlib

    import tpu_faas.utils.logging as ulog

    monkeypatch.setenv("TPU_FAAS_LOG_FORMAT", "json")
    assert isinstance(ulog._make_formatter(), JsonFormatter)
    monkeypatch.delenv("TPU_FAAS_LOG_FORMAT")
    assert not isinstance(ulog._make_formatter(), JsonFormatter)
    importlib.reload(ulog)  # leave the module as other tests expect


# -- global registry sanity --------------------------------------------------


def test_store_round_trip_series_counts_pipelined_batches():
    from tpu_faas.store.launch import make_store, start_store_thread

    handle = start_store_thread()
    store = make_store(handle.url)
    try:
        series = REGISTRY._metrics[
            "tpu_faas_store_round_trips_total"
        ].labels(backend="resp")
        before = series.value
        store.hset("k", {"a": "1"})
        store.hget_many(["k", "k2", "k3"], "a")  # one pipelined round
        delta = series.value - before
        assert delta == store.n_round_trips == 2
    finally:
        store.close()
        handle.stop()


# -- full stack: gateway + tpu-push dispatcher + real worker -----------------


def test_trace_endpoint_full_stack_e2e():
    """Acceptance path: one task submitted through the REST gateway,
    executed by a real push-worker subprocess, then /trace/<task_id> on the
    dispatcher returns a complete nine-event timeline whose exec window
    came from the worker's own stamps, and /metrics covers the store
    round-trip series (RESP backend in play)."""
    import threading

    from tpu_faas.client import FaaSClient
    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
    from tpu_faas.gateway import start_gateway_thread
    from tpu_faas.store.launch import make_store, start_store_thread
    from tpu_faas.workloads import sleep_task
    from tests.test_workers_e2e import _spawn_worker

    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    disp = TpuPushDispatcher(
        ip="127.0.0.1",
        port=0,
        store=make_store(store_handle.url),
        max_workers=16,
        max_pending=64,
        max_inflight=128,
        tick_period=0.01,
    )
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    server = disp.serve_stats(0)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    worker = _spawn_worker(
        "push_worker", 2, f"tcp://127.0.0.1:{disp.port}", "--hb"
    )
    client = FaaSClient(gw.url)
    try:
        fid = client.register(sleep_task)
        handle = client.submit(fid, 0.2)
        assert handle.result(timeout=120) == 0.2

        deadline = time.monotonic() + 10
        rec = None
        while time.monotonic() < deadline:
            r = requests.get(f"{base}/trace/{handle.task_id}")
            if r.status_code == 200 and r.json()["complete"]:
                rec = r.json()
                break
            time.sleep(0.1)
        assert rec is not None, "timeline never completed"
        assert list(rec["events"]) == list(EVENTS)
        assert rec["outcome"] == "COMPLETED"
        # the exec window is the worker-measured ~0.2 s sleep, and every
        # stage delta is non-negative (monotonic-anchored stamps)
        assert 0.15 <= rec["stages"]["execution"] <= 5.0
        assert all(v >= 0 for v in rec["stages"].values())
        assert rec["stages"]["total"] >= rec["stages"]["execution"]

        fams = parse_exposition(requests.get(f"{base}/metrics").text)
        assert "tpu_faas_store_round_trips_total" in fams
        [done] = [
            s
            for s in fams["tpu_faas_dispatcher_results_total"].samples
            if s.labels["status"] == "COMPLETED"
        ]
        assert done.value >= 1
    finally:
        if worker.poll() is None:
            worker.kill()
            worker.wait()
        disp.stop()
        t.join(timeout=10)
        disp.close()
        gw.stop()
        store_handle.stop()


def test_cross_process_trace_assembly_e2e():
    """The distributed-tracing acceptance path: gateway with ``--trace``,
    tpu-push dispatcher, a REAL push-worker subprocess, and a trace-minting
    SDK client. ``GET /trace/<task_id>`` on the GATEWAY must assemble the
    cross-process timeline — >= 3 processes (gateway, dispatcher, worker)
    and >= 8 stages, including the gateway observe span (the poll gap no
    dispatcher-local view can see) — and the handle's trace id must be the
    assembled trace's key."""
    import threading

    from tpu_faas.client import FaaSClient
    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
    from tpu_faas.gateway import start_gateway_thread
    from tpu_faas.store.launch import make_store, start_store_thread
    from tpu_faas.workloads import sleep_task
    from tests.test_workers_e2e import _spawn_worker

    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url), trace=True)
    disp = TpuPushDispatcher(
        ip="127.0.0.1",
        port=0,
        store=make_store(store_handle.url),
        max_workers=16,
        max_pending=64,
        max_inflight=128,
        tick_period=0.01,
    )
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    worker = _spawn_worker(
        "push_worker", 2, f"tcp://127.0.0.1:{disp.port}", "--hb"
    )
    client = FaaSClient(gw.url, trace=True)
    try:
        fid = client.register(sleep_task)
        handle = client.submit(fid, 0.1)
        assert handle.trace_id is not None
        assert handle.result(timeout=120) == 0.1

        # spans flush on ~0.25 s cadences (dispatcher serve loop, gateway
        # background task): poll until the full catalog assembles
        deadline = time.monotonic() + 20
        tl = None
        while time.monotonic() < deadline:
            r = requests.get(f"{gw.url}/trace/{handle.task_id}")
            if r.status_code == 200:
                tl = r.json()
                if len(tl["processes"]) >= 3 and tl["n_stages"] >= 9:
                    break
            time.sleep(0.2)
        assert tl is not None, "trace never assembled"
        assert tl["trace_id"] == handle.trace_id
        assert set(tl["processes"]) >= {"gateway", "dispatcher", "worker"}
        assert tl["n_stages"] >= 8, tl
        stages = {(s["process"], s["stage"]) for s in tl["spans"]}
        for expected in (
            ("gateway", "admit"),
            ("gateway", "create"),
            ("dispatcher", "intake"),
            ("dispatcher", "queue"),
            ("dispatcher", "dispatch"),
            ("dispatcher", "inflight"),
            ("dispatcher", "finalize"),
            ("worker", "exec"),
        ):
            assert expected in stages, (expected, stages)
        # the worker-measured exec window survived the trip
        [exec_span] = [s for s in tl["spans"] if s["stage"] == "exec"]
        assert 0.05 <= exec_span["duration_s"] <= 5.0
        assert all(s["duration_s"] >= 0 for s in tl["spans"])
        # an unknown task still 404s
        assert requests.get(f"{gw.url}/trace/ghost").status_code == 404
        # the e2e histograms observed the delivery; /slo serves
        fams = parse_exposition(requests.get(f"{gw.url}/metrics").text)
        counts = {
            s.labels["phase"]: s.value
            for s in fams["tpu_faas_task_e2e_seconds"].samples
            if s.name.endswith("_count")
        }
        assert counts["submit_to_observe"] >= 1
        slo = requests.get(f"{gw.url}/slo").json()
        assert {o["name"] for o in slo["objectives"]} == {
            "submit_to_finish", "submit_to_observe",
        }
    finally:
        if worker.poll() is None:
            worker.kill()
            worker.wait()
        disp.stop()
        t.join(timeout=10)
        disp.close()
        gw.stop()
        store_handle.stop()


def test_gateway_trace_off_runs_unchanged():
    """With tracing off (the default) the submit surface is byte-identical
    to the pre-trace contract: no trace_id in responses, no trace field on
    records, no span hashes in the store — and /trace/<id> still resolves
    (zero spans) instead of 404ing a real task."""
    from tpu_faas.core.task import FIELD_TRACE_ID
    from tpu_faas.gateway import start_gateway_thread
    from tpu_faas.obs.tracectx import TRACE_PREFIX

    store = MemoryStore()
    gw = start_gateway_thread(store)
    try:
        r = requests.post(
            f"{gw.url}/register_function",
            json={"name": "f", "payload": "P"},
        )
        fid = r.json()["function_id"]
        r = requests.post(
            f"{gw.url}/execute_function",
            # a client-minted trace id is IGNORED while tracing is off
            json={"function_id": fid, "payload": "x", "trace_id": "ab" * 8},
        )
        body = r.json()
        assert "trace_id" not in body
        assert FIELD_TRACE_ID not in store.hgetall(body["task_id"])
        assert not [k for k in store.keys() if k.startswith(TRACE_PREFIX)]
        r = requests.get(f"{gw.url}/trace/{body['task_id']}")
        assert r.status_code == 200
        assert r.json()["spans"] == [] and r.json()["trace_id"] is None
    finally:
        gw.stop()


class _PingFailStore(MemoryStore):
    def __init__(self) -> None:
        super().__init__()
        self.fail_ping = False

    def ping(self) -> bool:
        if self.fail_ping:
            raise ConnectionError("store down")
        return True


def test_gateway_readyz_liveness_vs_readiness():
    from tpu_faas.gateway import start_gateway_thread

    store = _PingFailStore()
    gw = start_gateway_thread(store)
    try:
        assert requests.get(f"{gw.url}/healthz").status_code == 200
        r = requests.get(f"{gw.url}/readyz")
        assert r.status_code == 200 and r.json()["ready"] is True
        store.fail_ping = True
        r = requests.get(f"{gw.url}/readyz")
        assert r.status_code == 503
        assert r.json() == {"ready": False, "reason": "store_unreachable"}
        # liveness stays green: a degraded gateway is drained, not killed
        assert requests.get(f"{gw.url}/healthz").status_code == 200
    finally:
        store.fail_ping = False
        gw.stop()


def test_dispatcher_readyz_and_slo_endpoints():
    store, disp = _drive_dispatcher()
    server = disp.serve_stats(0)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        assert requests.get(f"{base}/healthz").status_code == 200
        r = requests.get(f"{base}/readyz")
        assert r.status_code == 200 and r.json()["ready"] is True
        slo = requests.get(f"{base}/slo").json()
        assert {o["name"] for o in slo["objectives"]} == {
            "submit_to_result", "queue_wait",
        }
        disp._store_down = True
        r = requests.get(f"{base}/readyz")
        assert r.status_code == 503
        assert r.json()["reason"] == "store_unreachable"
        assert requests.get(f"{base}/healthz").status_code == 200
    finally:
        disp.socket.close(linger=0)
        disp.stop()
        disp.close()


def test_announce_for_terminal_task_closes_timeline():
    """An announce drained for an already-terminal record (cancelled
    before any dispatcher saw it) opens a timeline at drain time that
    nothing downstream would ever close — the intake skip must stamp it
    finished with the record's terminal status instead of letting it age
    out of the active ring."""
    store, disp = _drive_dispatcher()
    try:
        _submit(store, "skip-1")
        assert store.cancel_task("skip-1") == "CANCELLED"
        # the announce is still on the bus: intake drains it, sees the
        # non-QUEUED record, and must close the timeline it just opened
        disp.tick()
        rec = disp.traces.timeline("skip-1")
        assert rec is not None, "timeline lost instead of closed"
        assert rec["outcome"] == "CANCELLED"
        assert disp.traces.stats()["active"] == 0
    finally:
        disp.socket.close(linger=0)
        disp.close()


def test_zombie_second_result_does_not_resurrect_timeline():
    """A late duplicate RESULT for an already-finished task (zombie worker
    of a re-dispatched task) must not reopen the closed timeline — no
    duplicate completion record, and /trace/<id> keeps resolving."""
    store, disp = _drive_dispatcher()
    try:
        _submit(store, "z-1")
        disp.tick()
        _result(disp, "z-1")
        first = disp.traces.timeline("z-1")
        assert first["complete"]
        completed_before = disp.traces.n_completed
        _result(disp, "z-1")  # the zombie's duplicate
        assert disp.traces.n_completed == completed_before
        assert disp.traces.timeline("z-1") == first
        assert disp.traces.stats()["active"] == 0
    finally:
        disp.socket.close(linger=0)
        disp.close()
