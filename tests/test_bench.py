"""Benchmark-harness tests: the timing estimator's math and a smoke run of
the measure_service pipeline (reference client_performance.py analog).

The reference's harness was untested and shipped a units bug (ms printed as
"ns", client_performance.py:301-302); these tests pin ours down.
"""

from __future__ import annotations

import numpy as np
import pytest

import tpu_faas.bench.timing as timing


class _FakePipeline:
    """Deterministic stand-in for a device stream: each run() advances a
    virtual clock by ``per_exec``; every measurement window's closing
    perf_counter read pays a constant ``transport`` (the readback round
    trip). The slope estimator must recover per_exec exactly and ignore
    transport."""

    def __init__(self, per_exec: float, transport: float):
        self.per_exec = per_exec
        self.transport = transport
        self.t = 0.0
        self.calls = 0
        self.jitter: dict[int, float] = {}  # window index -> extra seconds
        self.window = -1

    def run(self, problem):
        self.t += self.per_exec
        return np.zeros(1)

    def perf_counter(self) -> float:
        self.calls += 1
        if self.calls % 2 == 1:  # window opens
            self.window += 1
            return self.t
        return self.t + self.transport + self.jitter.get(self.window, 0.0)


def test_pipeline_slope_recovers_per_exec_time(monkeypatch):
    fake = _FakePipeline(per_exec=0.002, transport=0.070)
    monkeypatch.setattr(timing.time, "perf_counter", fake.perf_counter)
    ms = timing.pipeline_slope_ms(fake.run, [object()], 10, 60)
    # 70 ms of per-window transport, 2 ms/exec device time: the slope sees
    # only the device time
    assert ms == pytest.approx(2.0, abs=1e-9)


def test_pipeline_slope_survives_one_corrupt_window(monkeypatch):
    fake = _FakePipeline(per_exec=0.0015, transport=0.070)
    fake.jitter[2] = 0.5  # one window (a tunnel hiccup) is wildly slow
    monkeypatch.setattr(timing.time, "perf_counter", fake.perf_counter)
    ms = timing.pipeline_slope_ms(fake.run, [object()], 10, 60)
    # Theil-Sen: the median of pairwise slopes sheds the corrupted windows
    assert ms == pytest.approx(1.5, abs=1e-9)


def test_pipeline_slope_rejects_degenerate_depths():
    with pytest.raises(ValueError):
        timing.pipeline_slope_ms(lambda p: np.zeros(1), [object()], 7, 7)


def test_transport_floor_is_positive():
    assert timing.transport_floor_ms(reps=2) > 0.0


def test_measure_service_local_smoke():
    """One tiny local-mode simulation through the real store + gateway +
    dispatcher stack: sane metrics, perfect correctness."""
    from tpu_faas.bench.harness import measure_service

    res = measure_service(
        mode="local",
        n_workers=2,
        n_procs=2,
        tasks_per_worker=2,
        workload="arithmetic",
        size=100,
        n_sims=1,
        timeout=60.0,
    )
    assert res.n_tasks == 4
    assert res.correctness_rate == 1.0
    assert res.throughput_tps > 0
    assert res.avg_latency_s > 0
    assert res.time_to_register_s > 0
    d = res.to_dict()
    assert d["mode"] == "local" and d["sims"] == 1


def test_bench_run_emits_parseable_json_line_on_failure(monkeypatch, capsys):
    """The driver records bench stdout as the round's artifact; a crashed
    run must still leave one parseable JSON line with an error field
    (round 2's artifact was an rc=1 traceback with no JSON — scoreboard
    evidence lost)."""
    import json

    import bench

    def boom():
        raise RuntimeError("UNAVAILABLE: tunnel down")

    monkeypatch.setattr(bench, "main", boom)
    assert bench.run() == 1
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(line)
    assert rec["metric"] == "placement_quality_makespan_vs_lp_50k_x_4k"
    assert rec["value"] is None
    assert "UNAVAILABLE" in rec["error"]


def test_bench_backend_init_retries_transient_unavailable(monkeypatch):
    """First-touch UNAVAILABLE from a flapping tunnel is retried with
    backoff instead of killing the run — and each retry clears the cached
    backend registry so the accelerator is genuinely re-attempted."""
    import jax

    import bench

    calls = {"n": 0, "resets": 0}

    def flaky_devices():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("UNAVAILABLE: backend not ready")
        return ["tpu0"]

    monkeypatch.setattr(jax, "devices", flaky_devices)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(
        bench, "_reset_backend",
        lambda: calls.__setitem__("resets", calls["resets"] + 1),
    )
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    bench._init_backend_with_retry()
    assert calls["n"] == 3
    assert calls["resets"] == 2  # cleared before every re-attempt

    # a permanently-down backend still raises after the attempt budget
    calls["n"] = -100
    with pytest.raises(RuntimeError):
        bench._init_backend_with_retry(max_attempts=2)


def test_bench_refuses_cpu_fallback_after_accelerator_failure(monkeypatch):
    """JAX caches a partially-initialized (CPU-only) backend dict when an
    accelerator plugin fails to init; a later jax.devices() 'succeeds' on
    it. The retry must not record that CPU run as the TPU headline."""
    import jax

    import bench

    calls = {"n": 0}

    def flaky_devices():
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("UNAVAILABLE: tunnel down")
        return ["cpu0"]  # the cached CPU-only registry

    monkeypatch.setattr(jax, "devices", flaky_devices)
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    monkeypatch.setattr(bench, "_reset_backend", lambda: None)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    with pytest.raises(RuntimeError, match="CPU"):
        bench._init_backend_with_retry(max_attempts=3)
