"""Benchmark-harness tests: the timing estimator's math and a smoke run of
the measure_service pipeline (reference client_performance.py analog).

The reference's harness was untested and shipped a units bug (ms printed as
"ns", client_performance.py:301-302); these tests pin ours down.
"""

from __future__ import annotations

import numpy as np
import pytest

import tpu_faas.bench.timing as timing


class _FakePipeline:
    """Deterministic stand-in for a device stream: each run() advances a
    virtual clock by ``per_exec``; every measurement window's closing
    perf_counter read pays a constant ``transport`` (the readback round
    trip). The slope estimator must recover per_exec exactly and ignore
    transport."""

    def __init__(self, per_exec: float, transport: float):
        self.per_exec = per_exec
        self.transport = transport
        self.t = 0.0
        self.calls = 0
        self.jitter: dict[int, float] = {}  # window index -> extra seconds
        self.window = -1

    def run(self, problem):
        self.t += self.per_exec
        return np.zeros(1)

    def perf_counter(self) -> float:
        self.calls += 1
        if self.calls % 2 == 1:  # window opens
            self.window += 1
            return self.t
        return self.t + self.transport + self.jitter.get(self.window, 0.0)


def test_pipeline_slope_recovers_per_exec_time(monkeypatch):
    fake = _FakePipeline(per_exec=0.002, transport=0.070)
    monkeypatch.setattr(timing.time, "perf_counter", fake.perf_counter)
    ms = timing.pipeline_slope_ms(fake.run, [object()], 10, 60)
    # 70 ms of per-window transport, 2 ms/exec device time: the slope sees
    # only the device time
    assert ms == pytest.approx(2.0, abs=1e-9)


def test_pipeline_slope_survives_one_corrupt_window(monkeypatch):
    fake = _FakePipeline(per_exec=0.0015, transport=0.070)
    fake.jitter[2] = 0.5  # one window (a tunnel hiccup) is wildly slow
    monkeypatch.setattr(timing.time, "perf_counter", fake.perf_counter)
    ms = timing.pipeline_slope_ms(fake.run, [object()], 10, 60)
    # Theil-Sen: the median of pairwise slopes sheds the corrupted windows
    assert ms == pytest.approx(1.5, abs=1e-9)


def test_pipeline_slope_rejects_degenerate_depths():
    with pytest.raises(ValueError):
        timing.pipeline_slope_ms(lambda p: np.zeros(1), [object()], 7, 7)


def test_transport_floor_is_positive():
    assert timing.transport_floor_ms(reps=2) > 0.0


def test_measure_service_local_smoke():
    """One tiny local-mode simulation through the real store + gateway +
    dispatcher stack: sane metrics, perfect correctness."""
    from tpu_faas.bench.harness import measure_service

    res = measure_service(
        mode="local",
        n_workers=2,
        n_procs=2,
        tasks_per_worker=2,
        workload="arithmetic",
        size=100,
        n_sims=1,
        timeout=60.0,
    )
    assert res.n_tasks == 4
    assert res.correctness_rate == 1.0
    assert res.throughput_tps > 0
    assert res.avg_latency_s > 0
    assert res.time_to_register_s > 0
    d = res.to_dict()
    assert d["mode"] == "local" and d["sims"] == 1
