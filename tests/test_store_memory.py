"""Unit tests for the in-process task store + announce bus."""

import threading

from tpu_faas.core.task import FIELD_RESULT, FIELD_STATUS, TaskStatus
from tpu_faas.store import MemoryStore
from tpu_faas.store.base import LIVE_INDEX_KEY, TASKS_CHANNEL


def test_hash_ops():
    s = MemoryStore()
    s.hset("k", {"a": "1", "b": "2"})
    s.hset("k", {"b": "3"})
    assert s.hget("k", "a") == "1"
    assert s.hget("k", "b") == "3"
    assert s.hget("k", "missing") is None
    assert s.hget("nokey", "a") is None
    assert s.hgetall("k") == {"a": "1", "b": "3"}
    assert s.keys() == ["k"]
    s.delete("k")
    assert s.hgetall("k") == {}


def test_create_task_contract_and_announce():
    s = MemoryStore()
    sub = s.subscribe(TASKS_CHANNEL)
    s.create_task("tid-1", "FN", "PARAMS")
    fields = s.hgetall("tid-1")
    assert fields == {
        "status": "QUEUED",
        "fn_payload": "FN",
        "param_payload": "PARAMS",
        "result": "None",
    }
    assert sub.get_message() == "tid-1"
    assert sub.get_message() is None


def test_task_lifecycle_helpers():
    s = MemoryStore()
    s.create_task("t", "FN", "P")
    assert s.get_payloads("t") == ("FN", "P")
    s.set_status("t", TaskStatus.RUNNING)
    assert s.get_status("t") == "RUNNING"
    s.finish_task("t", TaskStatus.COMPLETED, "RES")
    assert s.get_result("t") == ("COMPLETED", "RES")
    assert s.hget("t", FIELD_STATUS) == "COMPLETED"
    assert s.hget("t", FIELD_RESULT) == "RES"


def test_pubsub_fire_and_forget_and_fanout():
    s = MemoryStore()
    s.publish("tasks", "lost")  # nobody listening -> dropped
    a = s.subscribe("tasks")
    b = s.subscribe("tasks")
    s.publish("tasks", "m1")
    assert a.get_message() == "m1"
    assert b.get_message() == "m1"
    a.close()
    s.publish("tasks", "m2")
    assert a.get_message() is None  # closed
    assert b.get_message() == "m2"


def test_subscription_blocking_timeout():
    s = MemoryStore()
    sub = s.subscribe("tasks")
    t = threading.Timer(0.05, lambda: s.publish("tasks", "late"))
    t.start()
    assert sub.get_message(timeout=2.0) == "late"
    t.join()


def test_flush_keeps_subscriptions():
    s = MemoryStore()
    sub = s.subscribe("tasks")
    s.hset("k", {"a": "1"})
    s.flush()
    assert s.keys() == []
    s.publish("tasks", "still-works")
    assert sub.get_message() == "still-works"


def test_thread_safety_smoke():
    s = MemoryStore()
    sub = s.subscribe("tasks")

    def writer(i):
        for j in range(100):
            s.create_task(f"t-{i}-{j}", "F", "P")

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    seen = 0
    while sub.get_message() is not None:
        seen += 1
    assert seen == 800
    # +1: the live-task index hash rides alongside the task records
    assert len([k for k in s.keys() if k != LIVE_INDEX_KEY]) == 800


def test_first_wins_does_not_resurrect_deleted_record():
    """A zombie's late result must not recreate a record the client already
    deleted (DELETE /task): absent counts as frozen on first_wins paths."""
    store = MemoryStore()
    store.create_task("t", "F", "P")
    store.set_status("t", "RUNNING")
    store.finish_task("t", "COMPLETED", "real")
    store.delete("t")
    store.finish_task("t", "FAILED", "zombie-late", first_wins=True)
    assert store.hgetall("t") == {}
    store.close()


def test_create_task_if_absent_never_regresses():
    """The keyed-create primitive: one creator wins; a late second create
    cannot reset an already-RUNNING (or terminal) record back to QUEUED —
    and a predecessor that died between its status claim and its field
    write is repaired in place."""
    from tpu_faas.core.task import FIELD_PARAMS, FIELD_STATUS
    from tpu_faas.store.memory import MemoryStore

    s = MemoryStore()
    sub = s.subscribe("tasks")
    assert s.create_task_if_absent("t1", "F", "P") is True
    assert sub.get_message() == "t1"
    # simulate dispatch: RUNNING; a very late duplicate create must not
    # regress the status or re-announce
    s.set_status("t1", "RUNNING")
    assert s.create_task_if_absent("t1", "F", "P") is False
    assert s.get_status("t1") == "RUNNING"
    assert sub.get_message() is None

    # repair path: status claimed but the field write never landed
    s.hset("t2", {FIELD_STATUS: "QUEUED"})
    assert s.create_task_if_absent("t2", "F2", "P2") is True
    assert s.hget("t2", FIELD_PARAMS) == "P2"
    assert sub.get_message() == "t2"


def test_live_index_tracks_task_lifecycle():
    """tasks:index holds exactly the live (non-terminal) task ids: added on
    every create variant, removed on the terminal write — the stranded-task
    rescan reads this instead of KEYS-walking all history."""
    from tpu_faas.store.base import LIVE_INDEX_KEY
    from tpu_faas.store.memory import MemoryStore

    s = MemoryStore()
    s.create_task("t1", "F", "P")
    s.create_tasks([("t2", "F", "P"), ("t3", "F", "P", {"priority": "1"})])
    assert s.create_task_if_absent("t4", "F", "P") is True
    assert set(s.hgetall(LIVE_INDEX_KEY)) == {"t1", "t2", "t3", "t4"}
    s.finish_task("t2", "COMPLETED", "R")
    s.finish_task("t4", "FAILED", "E")
    assert set(s.hgetall(LIVE_INDEX_KEY)) == {"t1", "t3"}
    # hdel removes the hash entirely once empty (Redis semantics)
    s.finish_task("t1", "COMPLETED", "R")
    s.finish_task("t3", "COMPLETED", "R")
    assert s.hgetall(LIVE_INDEX_KEY) == {}


def test_create_tasks_if_absent_batch_semantics():
    """The batched keyed-create: fresh ids are created+announced with
    created=True; ids whose record already exists (any status) write
    NOTHING and return False — a re-sent batch can never regress a
    dispatched task back to QUEUED."""
    s = MemoryStore()
    sub = s.subscribe(TASKS_CHANNEL)
    flags = s.create_tasks_if_absent(
        [("a", "F", "PA"), ("b", "F", "PB", {"priority": "2"})]
    )
    assert flags == [True, True]
    assert {sub.get_message(), sub.get_message()} == {"a", "b"}
    assert s.hget("b", "priority") == "2"
    # "a" progressed; a duplicate batch (retry after lost response) must
    # not touch it, while the genuinely-new "c" is created
    s.set_status("a", TaskStatus.RUNNING)
    flags = s.create_tasks_if_absent(
        [("a", "F", "PA"), ("c", "F", "PC")]
    )
    assert flags == [False, True]
    assert s.get_status("a") == "RUNNING"
    assert s.hget("a", FIELD_STATUS) == "RUNNING"
    assert s.get_status("c") == "QUEUED"
    assert sub.get_message() == "c"
    assert sub.get_message() is None
    # live index tracks the batch form too
    assert "c" in s.hgetall(LIVE_INDEX_KEY)


def test_batched_keyed_create_never_regresses_a_racing_dispatch():
    """The stalled-winner race: gateway A wins the status claim, stalls;
    a duplicate submit adopts the record and a dispatcher marks it
    RUNNING; A's late field write must NOT rewrite status back to QUEUED
    (that would re-announce and run the task twice). The winners' write
    therefore carries no status field at all."""

    class StalledWinner(MemoryStore):
        fired = False

        def hset_many(self, items):
            if not self.fired:
                self.fired = True
                # the adversary acts inside the winner's stall window
                self.set_status("t", TaskStatus.RUNNING)
            super().hset_many(items)

    s = StalledWinner()
    created = s.create_tasks_if_absent([("t", "F", "P")])
    assert created == [True]
    assert s.get_status("t") == "RUNNING"  # dispatch stands, no regression
    assert s.hget("t", "param_payload") == "P"  # fields still landed
