"""Child for the multihost RESIDENT protocol test: two gloo processes run
MultihostResidentScheduler — the lead drives registrations, arrivals,
result churn, and ticks; the follower mirrors packets. Prints placement
fingerprints and exits via the stop protocol.

Run: python tests/_multihost_resident_child.py <rank> <coordinator_port>
     [placement]
"""

from __future__ import annotations

import sys


def main() -> None:
    rank, port = int(sys.argv[1]), sys.argv[2]
    placement = sys.argv[3] if len(sys.argv) > 3 else "rank"

    from tpu_faas.parallel.distributed import initialize_multihost

    assert initialize_multihost(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=2,
        process_id=rank,
        cpu_devices_per_process=4,
    )
    import numpy as np

    from tpu_faas.parallel.multihost_resident import MultihostResidentScheduler

    clock = [100.0]
    r = MultihostResidentScheduler.from_shape(
        max_workers=16,
        max_pending=64,
        max_inflight=128,
        max_slots=4,
        time_to_expire=10.0,
        placement=placement,
        clock=lambda: clock[0],
    )
    if rank != 0:
        r.follow_loop()
        print("MHRES follower done", flush=True)
        return

    rng = np.random.default_rng(0)
    speeds = rng.uniform(0.5, 4.0, 8)
    for i in range(8):
        r.register(b"w%d" % i, 2, speed=float(speeds[i]))
    placed_all = []
    arrival = 0
    for tick in range(12):
        clock[0] += 0.5
        for i in range(8):
            r.heartbeat(b"w%d" % i)
        for _ in range(4):
            r.pending_add(f"t{arrival}", float(rng.uniform(0.5, 5.0)),
                          priority=arrival % 3)
            arrival += 1
        r.tick_resident()
        while True:
            res = r.resolve_next()
            if res is None:
                break
            for tid, row in res.placed:
                placed_all.append((tid, row))
                # model a result arriving immediately: slot frees
                r.worker_free[row] = min(
                    r.worker_free[row] + 1, int(r.worker_procs[row])
                )
    r.lead_stop()
    import zlib

    fp = sum(
        zlib.crc32(t.encode()) * (int(w) + 1) % 1000003 for t, w in placed_all
    )
    print(
        f"MHRES lead placed={len(placed_all)} fingerprint={fp}", flush=True
    )


if __name__ == "__main__":
    main()
