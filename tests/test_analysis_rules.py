"""Per-rule fixtures for ``tpu_faas.analysis``: each checker both fires
(exact rule id + line) and stays clean, plus suppression and baseline
handling. Every snippet is written to a tmp dir and run through the real
``run_paths`` entry point — the same code path the CLI and the tier-1 gate
use."""

from __future__ import annotations

import json
import textwrap
from pathlib import Path

import pytest

from tpu_faas.analysis import run_paths
from tpu_faas.analysis.__main__ import main as analysis_main
from tpu_faas.analysis.core import (
    load_baseline,
    subtract_baseline,
    write_baseline,
)


def check(tmp_path: Path, src: str, name: str = "snippet.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    return run_paths([p])


def hits(findings):
    """(rule, line) pairs for exact assertions."""
    return [(f.rule, f.line) for f in findings]


# -- protocol ----------------------------------------------------------------


def test_protocol_illegal_finish_status_fires(tmp_path):
    findings = check(
        tmp_path,
        """\
        from tpu_faas.core.task import TaskStatus

        def f(store, tid):
            store.finish_task(tid, TaskStatus.QUEUED, "r")
        """,
    )
    assert hits(findings) == [("protocol.illegal-finish-status", 4)]
    assert findings[0].severity == "error"


def test_protocol_unknown_status_fires(tmp_path):
    findings = check(
        tmp_path,
        """\
        def f(store, tid):
            store.set_status(tid, "DONE")
        """,
    )
    assert hits(findings) == [("protocol.unknown-status", 2)]


def test_protocol_terminal_set_status_fires_on_all_spellings(tmp_path):
    findings = check(
        tmp_path,
        """\
        from tpu_faas.core.task import TaskStatus

        def f(store, tid):
            store.set_status(tid, "COMPLETED")
            store.set_status(tid, TaskStatus.FAILED)
            store.set_status(tid, str(TaskStatus.CANCELLED))
        """,
    )
    assert hits(findings) == [
        ("protocol.terminal-set-status", 4),
        ("protocol.terminal-set-status", 5),
        ("protocol.terminal-set-status", 6),
    ]


def test_protocol_running_without_lease_warns(tmp_path):
    findings = check(
        tmp_path,
        """\
        from tpu_faas.core.task import TaskStatus

        def f(store, tid):
            store.set_status(tid, TaskStatus.RUNNING)
        """,
    )
    assert hits(findings) == [("protocol.running-without-lease", 4)]
    assert findings[0].severity == "warning"


def test_protocol_hedge_dispatch_surface_clean(tmp_path):
    """Speculation plane (tpu_faas/spec): the hedge replica's store
    surface — declare_replica + a leased RUNNING mark + both results
    through first-wins finish_task — is exactly the declared-redispatch
    vocabulary the checker already proves. Zero new write paths means
    zero new findings."""
    findings = check(
        tmp_path,
        """\
        from tpu_faas.core.task import FIELD_LEASE_AT, TaskStatus

        def hedge(store, tid, stamp):
            store.declare_replica(tid)
            store.set_status(
                tid, TaskStatus.RUNNING,
                extra_fields={FIELD_LEASE_AT: stamp},
            )
            store.finish_task(tid, TaskStatus.COMPLETED, "r",
                              first_wins=True)
            store.finish_task(tid, TaskStatus.CANCELLED, "k",
                              first_wins=True)
        """,
    )
    assert hits(findings) == []


def test_protocol_hedge_loser_kill_via_set_status_fires(tmp_path):
    """The loser's CANCELLED must ride finish_task's first-wins contract
    (frozen against the winner's record) — a raw terminal set_status
    spelling of the kill would overwrite the winner and fires the
    existing terminal-set-status rule."""
    findings = check(
        tmp_path,
        """\
        from tpu_faas.core.task import TaskStatus

        def bad_kill(store, tid):
            store.declare_replica(tid)
            store.set_status(tid, TaskStatus.CANCELLED)
        """,
    )
    assert hits(findings) == [("protocol.terminal-set-status", 5)]


def test_protocol_raw_status_write_and_publish_fire(tmp_path):
    findings = check(
        tmp_path,
        """\
        from tpu_faas.core.task import FIELD_STATUS
        from tpu_faas.store.base import TASKS_CHANNEL

        def f(store, tid):
            store.hset(tid, {FIELD_STATUS: "RUNNING"})
            store.hset(tid, {"result": "blob"})
            store.publish(TASKS_CHANNEL, tid)
            store.publish("results", tid)
        """,
    )
    assert hits(findings) == [
        ("protocol.raw-status-write", 5),
        ("protocol.raw-status-write", 6),
        ("protocol.raw-task-publish", 7),
        ("protocol.raw-task-publish", 8),
    ]


def test_protocol_raw_blob_write_fires(tmp_path):
    """Raw writes/deletes into the blob namespace outside the store
    package, across the static key spellings: a "blob:..." literal, a
    BLOB_PREFIX concatenation/f-string, and a blob_key() call."""
    findings = check(
        tmp_path,
        """\
        from tpu_faas.store.base import BLOB_PREFIX, blob_key

        def f(store, digest, data):
            store.hset("blob:abc123", {"data": data})
            store.hset(BLOB_PREFIX + digest, {"data": data})
            store.setnx_field(f"{BLOB_PREFIX}{digest}", "data", data)
            store.delete(blob_key(digest))
        """,
    )
    assert hits(findings) == [
        ("protocol.raw-blob-write", 4),
        ("protocol.raw-blob-write", 5),
        ("protocol.raw-blob-write", 6),
        ("protocol.raw-blob-write", 7),
    ]
    assert all(f.severity == "error" for f in findings)


def test_protocol_raw_blob_write_clean(tmp_path):
    """The sanctioned API (put_blob / get_blob / dynamic sweeper key
    lists) stays clean — reads never fire, nor do hsets on ordinary task
    keys."""
    findings = check(
        tmp_path,
        """\
        def f(store, digest, data, stale_keys):
            store.put_blob(digest, data)
            body = store.get_blob(digest)
            store.get_blobs([digest])
            store.delete_many(stale_keys)  # dynamic GC list: out of scope
            store.hset(digest, {"lease_at": "1.0"})
            return body
        """,
    )
    assert hits(findings) == []


def test_protocol_set_status_many_rules(tmp_path):
    """The batched status write: its single shared status argument is held
    to the same terminal/unknown rules as plain set_status — a RUNNING
    batch (the dispatcher's coalesced act-phase flush) stays clean."""
    findings = check(
        tmp_path,
        """\
        from tpu_faas.core.task import TaskStatus

        def f(store, items):
            store.set_status_many(TaskStatus.COMPLETED, items)
            store.set_status_many("DONE", items)
            store.set_status_many(TaskStatus.RUNNING, items)  # clean
        """,
    )
    assert hits(findings) == [
        ("protocol.terminal-set-status", 4),
        ("protocol.unknown-status", 5),
    ]


def test_protocol_finish_task_many_rules(tmp_path):
    """Batched terminal writes: literal item tuples have their status slot
    checked against the legal finish set; dynamically built item lists
    (statuses off the wire) are out of static scope and stay clean."""
    findings = check(
        tmp_path,
        """\
        from tpu_faas.core.task import TaskStatus

        def f(store, tid, results):
            store.finish_task_many([(tid, TaskStatus.QUEUED, "r", False)])
            store.finish_task_many([(tid, "DONE", "r", False)])
            store.finish_task_many(
                [(tid, TaskStatus.COMPLETED, "r", True)]  # clean
            )
            store.finish_task_many(results)  # dynamic: not provable
        """,
    )
    assert hits(findings) == [
        ("protocol.illegal-finish-status", 4),
        ("protocol.unknown-status", 5),
    ]


def test_protocol_waiting_set_status_fires(tmp_path):
    """The graph vocabulary: WAITING may only be written by the store
    package (create with deps + the promotion plane). A bare set_status /
    set_status_many of WAITING anywhere else strands an undispatchable
    node."""
    findings = check(
        tmp_path,
        """\
        from tpu_faas.core.task import TaskStatus

        def f(store, tid):
            store.set_status(tid, TaskStatus.WAITING)
            store.set_status_many("WAITING", [(tid, None)])
        """,
    )
    assert hits(findings) == [
        ("protocol.waiting-set-status", 4),
        ("protocol.waiting-set-status", 5),
    ]
    assert all(f.severity == "error" for f in findings)


def test_protocol_waiting_vocabulary_clean(tmp_path):
    """WAITING via the legal surfaces stays clean: creation with
    status=WAITING (any path), promotion via the store package, and the
    poison's finish_task(FAILED) — the derived sets must know the new
    status (not flag it unknown)."""
    findings = check(
        tmp_path,
        """\
        from tpu_faas.core.task import TaskStatus

        def f(store, tasks, tid):
            store.create_tasks(tasks, status=TaskStatus.WAITING)
            store.finish_task(tid, TaskStatus.FAILED, "dep_failed")
        """,
    )
    assert findings == []
    # inside the store package the promotion plane's own writes are legal
    pkg = tmp_path / "tpu_faas" / "store"
    pkg.mkdir(parents=True)
    (pkg / "promo.py").write_text(
        textwrap.dedent(
            """\
            from tpu_faas.core.task import TaskStatus

            def promote(store, items):
                store.set_status_many(TaskStatus.QUEUED, items)
            """
        )
    )
    assert run_paths([pkg]) == []


def test_protocol_quarantine_drain_terminal_fires(tmp_path):
    """Quarantine is a ROUTING decision: any function named for the
    quarantine plane that calls a terminal-status writer (store surface
    or dispatcher wrapper) turns a health policy into task loss."""
    findings = check(
        tmp_path,
        """\
        from tpu_faas.core.task import TaskStatus

        class D:
            def _quarantine_drain(self, store, tid):
                store.finish_task(tid, TaskStatus.FAILED, "quarantined")

            def quarantine_release(self, tid):
                self.fail_task(tid, "worker was quarantined")
        """,
    )
    assert hits(findings) == [
        ("protocol.quarantine-drain-terminal", 5),
        ("protocol.quarantine-drain-terminal", 8),
    ]
    assert all(f.severity == "error" for f in findings)


def test_protocol_quarantine_drain_clean(tmp_path):
    """The drain path's legitimate bookkeeping (logs, flight recorder,
    metrics, placement-cap math) stays clean — and terminal writes in
    functions NOT on the quarantine path are untouched by this rule."""
    findings = check(
        tmp_path,
        """\
        from tpu_faas.core.task import TaskStatus

        class D:
            def _quarantine_drain(self, row):
                self.log.warning("row %d quarantined", row)
                self.flightrec.emit("quarantine", row=row, action="enter")
                self.m_quarantined.labels(state="active").set(1)

            def _handle_result(self, store, tid):
                store.finish_task(tid, TaskStatus.COMPLETED, "r")
        """,
    )
    assert findings == []


def test_protocol_quarantine_banned_set_is_derived():
    """The banned-call set follows the live TaskStore API (plus the
    dispatcher's named terminal wrappers) — a renamed surface drops out
    instead of rotting as a stale string."""
    from tpu_faas.analysis.protocol import (
        QUARANTINE_BANNED_CALLS,
        TERMINAL_STORE_WRITERS,
    )
    from tpu_faas.store.base import TaskStore

    assert {
        "finish_task", "finish_task_many", "cancel_task", "expire_task"
    } <= TERMINAL_STORE_WRITERS
    for name in TERMINAL_STORE_WRITERS:
        assert hasattr(TaskStore, name)
    assert {"fail_task", "reclaim_or_fail"} <= QUARANTINE_BANNED_CALLS


def test_protocol_clean_fixture(tmp_path):
    """The legal surface: conveniences with legal statuses, hset without
    lifecycle fields, publish on a non-lifecycle channel, dynamic statuses
    (out of static scope), and raw writes inside a store/ package path."""
    findings = check(
        tmp_path,
        """\
        from tpu_faas.core.task import FIELD_LEASE_AT, TaskStatus

        def f(store, tid, status):
            store.create_task(tid, "fn", "params")
            store.set_status(tid, TaskStatus.RUNNING, {FIELD_LEASE_AT: "0"})
            store.finish_task(tid, TaskStatus.COMPLETED, "r")
            store.finish_task(tid, str(TaskStatus.FAILED), "r", first_wins=True)
            store.cancel_task(tid)
            store.hset(tid, {"dispatch_claim": "d1:0"})
            store.hset("fleet:lease_conf", {"t:5": "now"})
            store.publish("heartbeats", "hb")
            store.finish_task(tid, status, "r")  # dynamic: not provable
        """,
    )
    assert findings == []


def test_protocol_store_package_is_exempt(tmp_path):
    pkg = tmp_path / "tpu_faas" / "store"
    pkg.mkdir(parents=True)
    (pkg / "impl.py").write_text(
        textwrap.dedent(
            """\
            def f(store, tid):
                store.hset(tid, {"status": "QUEUED"})
                store.publish("tasks", tid)
            """
        )
    )
    assert run_paths([pkg]) == []
    # the exemption is decided on the ABSOLUTE path, so naming the file
    # directly (different relpath anchor) must not change the verdict
    assert run_paths([pkg / "impl.py"]) == []
    # a random directory named "store" outside tpu_faas is NOT exempt
    other = tmp_path / "store"
    other.mkdir()
    (other / "impl.py").write_text("def f(s, t):\n    s.publish('tasks', t)\n")
    assert [f.rule for f in run_paths([other])] == ["protocol.raw-task-publish"]


def test_protocol_store_file_named_directly_is_exempt():
    """Regression: `python -m tpu_faas.analysis tpu_faas/store/base.py`
    (a documented invocation) must stay clean — the store exemption cannot
    depend on how the path was anchored."""
    import tpu_faas.store.base as store_base

    assert run_paths([Path(store_base.__file__)]) == []


# -- trace-safety ------------------------------------------------------------


def test_trace_hazards_fire_with_exact_lines(tmp_path):
    findings = check(
        tmp_path,
        """\
        import time, random
        import jax
        from functools import partial

        _hits = {}

        @partial(jax.jit, static_argnames=("n",))
        def kern(x, n):
            t = time.time()
            r = random.random()
            v = x.item()
            f = float(x)
            print("tracing")
            _hits["k"] = 1
            y = x + 1
            if y > 0:
                y = y * 2
            return y + t + r + v + f
        """,
    )
    assert hits(findings) == [
        ("trace.host-time", 9),
        ("trace.python-random", 10),
        ("trace.host-sync", 11),
        ("trace.host-sync", 12),
        ("trace.print", 13),
        ("trace.state-mutation", 14),
        ("trace.data-dependent-branch", 16),
    ]


def test_trace_reaches_helpers_and_call_site_wraps(tmp_path):
    """Hazards are found in undecorated helpers reachable from a jit site,
    in jax.jit(...) call-site wraps, and in inline jitted lambdas."""
    findings = check(
        tmp_path,
        """\
        import time
        import jax

        def helper(z):
            time.sleep(0.1)
            return z

        tick = jax.jit(lambda q: helper(q))
        """,
    )
    assert hits(findings) == [("trace.host-time", 5)]


def test_trace_nested_def_hazards_report_once_with_own_scope(tmp_path):
    """A hazard inside a nested function reachable from a jit root is
    reported exactly once, and writes through the NESTED function's own
    params (pallas-style ref[...] = ...) are not mutation findings."""
    findings = check(
        tmp_path,
        """\
        import time
        import jax

        @jax.jit
        def outer(x):
            def scan_body(carry, t):
                time.sleep(0.1)
                return carry, t

            def kernel(x_ref, o_ref):
                o_ref[0] = x_ref[0]

            kernel
            return scan_body(x, x)
        """,
    )
    assert hits(findings) == [("trace.host-time", 7)]


def test_trace_same_named_functions_are_all_analyzed(tmp_path):
    """A name collision (two classes with a same-named method, only the
    second jitted) must not drop the jitted one from analysis."""
    findings = check(
        tmp_path,
        """\
        import time
        import jax

        class Plain:
            def step(self, x):
                return x

        class Jitted:
            @jax.jit
            def step(self, x):
                return x * time.time()
        """,
    )
    assert hits(findings) == [("trace.host-time", 11)]


def test_trace_static_argnums_indices_are_static(tmp_path):
    """Regression: `static_argnums=(0,)` makes parameter 0 static — a
    Python branch on it is legal, not a data-dependent-branch error."""
    findings = check(
        tmp_path,
        """\
        import jax
        from functools import partial

        @partial(jax.jit, static_argnums=(0,))
        def f(n, x):
            if n > 3:
                x = x * 2
            if x > 0:
                x = x + 1
            return x
        """,
    )
    assert hits(findings) == [("trace.data-dependent-branch", 8)]


def test_trace_jax_random_import_spellings_are_exempt(tmp_path):
    """Regression: `from jax import random` (and aliases) is jax.random,
    not stdlib random — the python-random rule must not fire on it."""
    findings = check(
        tmp_path,
        """\
        import jax
        from jax import random
        import jax.random as jrandom

        @jax.jit
        def f(x, key):
            a = random.normal(key, x.shape)
            b = jrandom.uniform(key, x.shape)
            return x + a + b
        """,
    )
    assert findings == []


def test_trace_clean_fixture(tmp_path):
    """Static-arg branches, `is None` probes, shape/len access, jax.random,
    jax.debug.print, and host code OUTSIDE any jit are all legal."""
    findings = check(
        tmp_path,
        """\
        import time
        import jax
        import jax.numpy as jnp
        from functools import partial

        @partial(jax.jit, static_argnames=("mode", "n"))
        def kern(x, key, mode, n, prio=None):
            if mode == "greedy":
                x = x * 2
            if prio is None:
                prio = jnp.zeros_like(x)
            if x.shape[0] > 4 and len(x) > n:
                x = x[:n]
            noise = jax.random.uniform(key, x.shape)
            jax.debug.print("step {}", n)
            y = jnp.where(x > 0, x, 0.0)
            return y + noise + prio

        def host_loop(store):
            while True:
                time.sleep(0.5)
                print(time.time())
        """,
    )
    assert findings == []


def test_trace_shard_map_and_pallas_call_are_roots(tmp_path):
    findings = check(
        tmp_path,
        """\
        import time
        import jax
        from jax.experimental import pallas as pl

        def tick_kernel(x):
            return x * time.time()

        def body_kernel(ref):
            ref[0] = time.perf_counter()

        plan = jax.shard_map(tick_kernel, mesh=None, in_specs=None, out_specs=None)
        out = pl.pallas_call(body_kernel, out_shape=None)
        """,
    )
    assert hits(findings) == [
        ("trace.host-time", 6),
        ("trace.host-time", 9),
    ]


# -- locks -------------------------------------------------------------------


def test_locks_blocking_call_under_lock_fires(tmp_path):
    findings = check(
        tmp_path,
        """\
        import threading, time

        _lock = threading.Lock()

        def f(sock, store, tid):
            with _lock:
                time.sleep(1)
                sock.recv()
                store.hget(tid, "status")
        """,
    )
    assert hits(findings) == [
        ("locks.blocking-call-under-lock", 7),
        ("locks.blocking-call-under-lock", 8),
        ("locks.blocking-call-under-lock", 9),
    ]
    assert "store round trip" in findings[2].message
    # messages are baseline identity: no line numbers allowed in them
    # (baseline_key excludes `line` so entries survive line drift)
    assert not any(any(ch.isdigit() for ch in f.message) for f in findings)


def test_locks_clean_fixture(tmp_path):
    """Pure-CPU critical sections, blocking calls outside the lock, and a
    def under a lock (runs later, lock released) are all legal."""
    findings = check(
        tmp_path,
        """\
        import threading, time

        _lock = threading.Lock()
        _state = {}

        def f(sock):
            with _lock:
                _state["n"] = _state.get("n", 0) + 1

            time.sleep(1)
            sock.recv()

            with _lock:
                def deferred():
                    time.sleep(5)
                return deferred
        """,
    )
    assert findings == []


def test_locks_order_inconsistency_across_modules(tmp_path):
    (tmp_path / "one.py").write_text(
        textwrap.dedent(
            """\
            def f(lock_a, lock_b):
                with lock_a:
                    with lock_b:
                        pass
            """
        )
    )
    (tmp_path / "two.py").write_text(
        textwrap.dedent(
            """\
            def g(lock_a, lock_b):
                with lock_b:
                    with lock_a:
                        pass
            """
        )
    )
    findings = run_paths([tmp_path])
    assert sorted(hits(findings)) == [
        ("locks.lock-order-inconsistent", 3),
        ("locks.lock-order-inconsistent", 3),
    ]
    assert {f.path.rsplit("/", 1)[-1] for f in findings} == {"one.py", "two.py"}
    assert all("ABBA" in f.message for f in findings)


def test_locks_consistent_order_is_clean(tmp_path):
    findings = check(
        tmp_path,
        """\
        def f(lock_a, lock_b):
            with lock_a:
                with lock_b:
                    pass

        def g(lock_a, lock_b):
            with lock_a:
                with lock_b:
                    pass
        """,
    )
    assert findings == []


# -- suppressions ------------------------------------------------------------


def test_inline_allow_suppresses_exact_rule(tmp_path):
    findings = check(
        tmp_path,
        """\
        import threading, time

        _lock = threading.Lock()

        def f():
            with _lock:
                time.sleep(1)  # faas: allow(locks.blocking-call-under-lock)
        """,
    )
    assert findings == []


def test_inline_allow_checker_and_star_forms(tmp_path):
    findings = check(
        tmp_path,
        """\
        def f(store, tid):
            store.set_status(tid, "COMPLETED")  # faas: allow(protocol)
            store.set_status(tid, "FAILED")  # faas: allow(*)
        """,
    )
    assert findings == []


def test_allow_for_wrong_rule_does_not_suppress(tmp_path):
    """A mismatched token suppresses nothing — and is itself reported
    stale, since it absorbed no finding."""
    findings = check(
        tmp_path,
        """\
        def f(store, tid):
            store.set_status(tid, "COMPLETED")  # faas: allow(trace.print)
        """,
    )
    assert hits(findings) == [
        ("core.stale-suppression", 2),
        ("protocol.terminal-set-status", 2),
    ]


# -- baseline ----------------------------------------------------------------


def test_baseline_roundtrip_absorbs_exactly_the_grandfathered_set(tmp_path):
    src = """\
    def f(store, tid):
        store.set_status(tid, "COMPLETED")
    """
    findings = check(tmp_path, src)
    assert len(findings) == 1

    baseline_path = tmp_path / "baseline.json"
    write_baseline(baseline_path, findings)
    remaining = subtract_baseline(findings, load_baseline(baseline_path))
    assert remaining == []

    # a SECOND instance of the same (path, rule, message) is NOT absorbed:
    # one baseline entry grandfathers one finding, never a class of them
    doubled = check(
        tmp_path,
        """\
        def f(store, tid):
            store.set_status(tid, "COMPLETED")
            store.set_status(tid, "COMPLETED")
        """,
    )
    assert len(doubled) == 2
    leftover = subtract_baseline(doubled, load_baseline(baseline_path))
    assert len(leftover) == 1


def test_baseline_rejects_unknown_version(tmp_path):
    bad = tmp_path / "baseline.json"
    bad.write_text(json.dumps({"version": 99, "findings": []}))
    with pytest.raises(ValueError):
        load_baseline(bad)


# -- CLI gate ----------------------------------------------------------------

BAD_SRC = """\
def f(store, tid):
    store.set_status(tid, "COMPLETED")
"""

WARN_SRC = """\
from tpu_faas.core.task import TaskStatus

def f(store, tid):
    store.set_status(tid, TaskStatus.RUNNING)
"""


def test_cli_exits_nonzero_on_seeded_violation(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(BAD_SRC)
    assert analysis_main([str(bad)]) == 1
    out = capsys.readouterr().out
    assert "protocol.terminal-set-status" in out


def test_cli_baseline_gates_only_new_findings(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(BAD_SRC)
    baseline = tmp_path / "baseline.json"
    assert analysis_main([str(bad), "--write-baseline", str(baseline)]) == 0
    assert analysis_main([str(bad), "--baseline", str(baseline)]) == 0
    bad.write_text(BAD_SRC + "    store.finish_task(tid, 'DONE', 'r')\n")
    assert analysis_main([str(bad), "--baseline", str(baseline)]) == 1
    out = capsys.readouterr().out
    assert "protocol.unknown-status" in out


def test_cli_warnings_pass_unless_strict(tmp_path, capsys):
    warn = tmp_path / "warn.py"
    warn.write_text(WARN_SRC)
    assert analysis_main([str(warn)]) == 0
    assert analysis_main([str(warn), "--strict"]) == 1
    capsys.readouterr()


def test_cli_json_output_is_parseable(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(BAD_SRC)
    assert analysis_main([str(bad), "--json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload[0]["rule"] == "protocol.terminal-set-status"
    assert payload[0]["line"] == 2


def test_cli_rejects_nonexistent_and_empty_targets(tmp_path, capsys):
    """A typo'd or empty target must fail the gate (exit 2), never pass it
    vacuously with '0 error(s)'."""
    assert analysis_main([str(tmp_path / "no_such_dir")]) == 2
    empty = tmp_path / "empty"
    empty.mkdir()
    assert analysis_main([str(empty)]) == 2
    (tmp_path / "notpy.txt").write_text("hello")
    assert analysis_main([str(tmp_path / "notpy.txt")]) == 2
    capsys.readouterr()


def test_finding_paths_are_cwd_independent(tmp_path, monkeypatch):
    """Baseline keys must survive a working-directory change: the same scan
    target yields the same finding paths from any cwd."""
    pkg = tmp_path / "proj"
    pkg.mkdir()
    (pkg / "bad.py").write_text(BAD_SRC)

    monkeypatch.chdir(tmp_path)
    from_parent = run_paths([pkg])
    monkeypatch.chdir(pkg)
    from_inside = run_paths([tmp_path / "proj"])
    assert [f.path for f in from_parent] == ["proj/bad.py"]
    assert [f.baseline_key() for f in from_parent] == [
        f.baseline_key() for f in from_inside
    ]

    baseline = tmp_path / "bl.json"
    write_baseline(baseline, from_parent)
    assert subtract_baseline(from_inside, load_baseline(baseline)) == []


def test_syntax_error_is_a_finding_not_a_crash(tmp_path, capsys):
    broken = tmp_path / "broken.py"
    broken.write_text("def f(:\n")
    findings = run_paths([broken])
    assert [f.rule for f in findings] == ["core.syntax-error"]
    assert analysis_main([str(broken)]) == 1
    capsys.readouterr()


# -- obs ---------------------------------------------------------------------


def check_at(tmp_path: Path, src: str, relname: str):
    """Write a snippet at a RELATIVE path under tmp_path (the obs checker
    scopes by module path) and scan the tmp dir."""
    p = tmp_path / relname
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return run_paths([tmp_path])


def test_obs_wall_clock_latency_fires_in_dispatch_paths(tmp_path):
    findings = check_at(
        tmp_path,
        """\
        import time

        def age(stamp):
            return time.time() - stamp

        def until(deadline):
            return deadline - time.time()
        """,
        "dispatch/hot.py",
    )
    assert hits(findings) == [
        ("obs.wall-clock-latency", 4),
        ("obs.wall-clock-latency", 7),
    ]
    assert all(f.severity == "error" for f in findings)


def test_obs_wall_clock_latency_fires_in_worker_paths(tmp_path):
    findings = check_at(
        tmp_path,
        """\
        import time

        def silent_for(last_seen):
            return time.time() - last_seen
        """,
        "worker/loop.py",
    )
    assert hits(findings) == [("obs.wall-clock-latency", 4)]


def test_obs_wall_clock_latency_scoped_to_hot_paths(tmp_path):
    """The same subtraction outside dispatch/worker modules is not a
    finding: gateway uptime math and bench wall timings are not hot-path
    latency measurement."""
    findings = check_at(
        tmp_path,
        """\
        import time

        def uptime(started_at):
            return time.time() - started_at
        """,
        "gateway/app.py",
    )
    assert findings == []


def test_obs_wall_clock_latency_clean_on_obs_api_and_monotonic(tmp_path):
    """Monotonic math and stamping (no subtraction) stay clean — the rule
    targets wall-clock DELTAS, not wall-clock reads."""
    findings = check_at(
        tmp_path,
        """\
        import time

        def span(t0):
            return time.monotonic() - t0

        def stamp():
            return repr(time.time())
        """,
        "dispatch/clean.py",
    )
    assert findings == []


def test_obs_wall_clock_latency_suppressible(tmp_path):
    findings = check_at(
        tmp_path,
        """\
        import time

        def claim_age(published):
            return time.time() - published  # faas: allow(obs.wall-clock-latency)
        """,
        "dispatch/lease.py",
    )
    assert findings == []


# -- protocol: EXPIRED (queue-deadline shedding) -----------------------------


def test_protocol_expired_terminal_set_status_fires(tmp_path):
    """EXPIRED is terminal, so the derived TERMINAL set must catch a bare
    set_status writing it — terminal writes go through expire_task (stamp,
    index drop, results announce), never raw status writes."""
    findings = check(
        tmp_path,
        """\
        def f(store, tid):
            store.set_status(tid, "EXPIRED")
        """,
    )
    assert hits(findings) == [("protocol.terminal-set-status", 2)]
    assert findings[0].severity == "error"


def test_protocol_expired_finish_task_fires(tmp_path):
    """RUNNING -> EXPIRED is deliberately NOT in racecheck._LEGAL (shed is
    QUEUED-only): a finish_task carrying EXPIRED must be an error, proven
    from the derived legal-finish set, not a copied list."""
    findings = check(
        tmp_path,
        """\
        from tpu_faas.core.task import TaskStatus

        def f(store, tid):
            store.finish_task(tid, TaskStatus.EXPIRED, "r")
        """,
    )
    assert hits(findings) == [("protocol.illegal-finish-status", 4)]
    assert findings[0].severity == "error"


def test_protocol_expire_task_call_is_clean(tmp_path):
    """The sanctioned shed path: store.expire_task carries its own stamp/
    index/announce contract inside the store package — call sites are
    clean."""
    findings = check(
        tmp_path,
        """\
        def f(store, tid, channel):
            status = store.expire_task(tid, channel)
            return status
        """,
    )
    assert hits(findings) == []


# -- eventloop ---------------------------------------------------------------


def test_eventloop_blocking_calls_fire_with_exact_lines(tmp_path):
    findings = check(
        tmp_path,
        """\
        import time

        async def handler(ctx, tid):
            record = ctx.store.hgetall(tid)
            time.sleep(0.1)
            data = open("/tmp/x").read()
            return record, data
        """,
    )
    assert hits(findings) == [
        ("eventloop.blocking-store-call", 4),
        ("eventloop.blocking-sleep", 5),
        ("eventloop.blocking-file-io", 6),
    ]
    assert all(f.severity == "error" for f in findings)


def test_eventloop_lock_forms_fire(tmp_path):
    findings = check(
        tmp_path,
        """\
        async def handler(self, tid):
            self._lock.acquire()
            with self._state_lock:
                self.seen.add(tid)
        """,
    )
    assert hits(findings) == [
        ("eventloop.blocking-lock", 2),
        ("eventloop.blocking-lock", 3),
    ]


def test_eventloop_sanctioned_escapes_are_clean(tmp_path):
    """The executor forms pass the callable UNCALLED; asyncio.sleep is the
    coroutine form; nested sync defs are values, not loop code."""
    findings = check(
        tmp_path,
        """\
        import asyncio
        import functools

        async def handler(ctx, tid, loop):
            await loop.run_in_executor(None, ctx.store.hgetall, tid)
            await loop.run_in_executor(
                None, functools.partial(ctx.store.hget, tid, "status")
            )
            await asyncio.to_thread(ctx.store.delete, tid)
            await asyncio.sleep(0.1)

            def thunk():
                return ctx.store.hgetall(tid)

            return await loop.run_in_executor(None, thunk)
        """,
    )
    assert hits(findings) == []


def test_eventloop_reaches_same_module_sync_helpers(tmp_path):
    """A sync helper doing the blocking on the coroutine's behalf is
    caught through the same-module call closure — free functions and
    same-class methods both."""
    findings = check(
        tmp_path,
        """\
        import time

        def helper(store, tid):
            return store.hgetall(tid)

        class Server:
            def _checkpoint(self):
                time.sleep(1.0)

            async def serve(self, store, tid):
                self._checkpoint()
                return helper(store, tid)
        """,
    )
    assert hits(findings) == [
        ("eventloop.blocking-store-call", 4),
        ("eventloop.blocking-sleep", 8),
    ]
    assert "reachable from async def serve" in findings[0].message


def test_eventloop_quadratic_scan_fires_and_set_is_clean(tmp_path):
    findings = check(
        tmp_path,
        """\
        async def validate(nodes):
            refs = []
            seen = set()
            for node in nodes:
                if node in refs:
                    continue
                refs.append(node)
                if node in seen:
                    continue
                seen.add(node)
            return refs
        """,
    )
    assert hits(findings) == [("eventloop.quadratic-scan", 5)]


def test_eventloop_sync_code_is_out_of_scope(tmp_path):
    """The dispatcher serve loops are threads, not coroutines — the same
    calls outside async reach are the locks/obs checkers' business."""
    findings = check(
        tmp_path,
        """\
        import time

        def serve_loop(store, tid):
            time.sleep(0.1)
            return store.hgetall(tid)
        """,
    )
    assert hits(findings) == []


def test_eventloop_suppressible_with_justification(tmp_path):
    findings = check(
        tmp_path,
        """\
        import snapshot

        class Server:
            async def stop(self):
                # blocking on the loop IS the consistency cut (Redis SAVE)
                snapshot.save_file("/tmp/s", {})  # faas: allow(eventloop.blocking-file-io)
        """,
    )
    assert hits(findings) == []


def test_eventloop_dict_churn_fires_in_dispatcher_loop(tmp_path):
    """A task-shaped dict ({"task_id": ...}) built per iteration of a
    Dispatcher-method loop is serve-loop allocator churn — the rule needs
    no async roots (the push serve loop is a plain sync loop)."""
    findings = check(
        tmp_path,
        """\
        class ToyDispatcher:
            def serve_once(self, batch):
                frames = []
                for t in batch:
                    frames.append({"task_id": t.task_id, "fn_payload": t.fn})
                return frames
        """,
    )
    assert hits(findings) == [("eventloop.hot-loop-dict-churn", 5)]
    assert findings[0].severity == "warning"


def test_eventloop_dict_churn_fires_in_task_message_kwargs(tmp_path):
    """The per-dispatch materializer fires wherever it lives — the rule's
    anchors (class-name suffix, method name) scope it without path gates,
    so the column-backed twin in core/ is held to the same discipline."""
    findings = check(
        tmp_path,
        """\
        class RowView:
            def task_message_kwargs(self):
                return {"task_id": self.task_id, "param_payload": self.params}
        """,
    )
    assert hits(findings) == [("eventloop.hot-loop-dict-churn", 3)]


def test_eventloop_dict_churn_exemptions_are_clean(tmp_path):
    """Out of scope by design: non-task-shaped dicts in loops, logging
    extra= dicts (the log call dwarfs the dict), task-shaped dicts built
    once outside any loop, and non-Dispatcher classes."""
    findings = check(
        tmp_path,
        """\
        class ToyDispatcher:
            def serve_once(self, batch, log):
                for t in batch:
                    stats = {"elapsed": t.elapsed}
                    log.info("done", extra={"task_id": t.task_id})
                return {"task_id": "summary", "n": len(batch)}

        class Collector:
            def gather(self, batch):
                return [{"task_id": t.task_id} for t in batch]
        """,
    )
    assert hits(findings) == []


def test_eventloop_dict_churn_suppressible_at_wire_boundary(tmp_path):
    findings = check(
        tmp_path,
        """\
        class RowView:
            def task_message_kwargs(self):
                return {  # faas: allow(eventloop.hot-loop-dict-churn) wire contract
                    "task_id": self.task_id,
                }
        """,
    )
    assert hits(findings) == []


# -- replication (registry drift) --------------------------------------------


_TOY_SERVER = """\
class StoreServer:
    async def _dispatch(self, cmd, writer):
        name = cmd[0].upper()
        if name == "PING":
            writer.write(b"+PONG")
        elif name == "HSET":
            self.apply(cmd)
            self._replicate(cmd)
        elif name == "HFOO":
            self.apply(cmd)
            self._replicate(cmd)

    def apply_replicated(self, cmd):
        name = cmd[0].upper()
        if name == "HSET":
            self.apply(cmd)
        elif name == "HFOO":
            self.apply(cmd)
"""


def test_registry_drift_fires_when_forward_set_lags(tmp_path):
    """THE regression shape: a toy server grows a mutating command (its
    dispatch branch replicates) that the toy replication forward list
    never learned — the drift must fire at the forward set."""
    (tmp_path / "toy_server.py").write_text(_TOY_SERVER)
    (tmp_path / "toy_replication.py").write_text(
        'MUTATING_COMMANDS = frozenset({"HSET"})\n'
    )
    findings = run_paths([tmp_path])
    drift = [f for f in findings if f.rule == "replication.registry-drift"]
    assert len(drift) == 1, findings
    assert Path(drift[0].path).name == "toy_replication.py"
    assert "HFOO" in drift[0].message
    assert "forward set" in drift[0].message
    assert drift[0].severity == "error"


def test_registry_drift_fires_on_partitioner_and_monitor_gaps(tmp_path):
    """A mutating primitive absent from the class-shaped registries
    (ShardedStore / RaceCheckStore method surface) fires once per
    incomplete registry."""
    (tmp_path / "toy_server.py").write_text(_TOY_SERVER)
    (tmp_path / "toy_replication.py").write_text(
        'MUTATING_COMMANDS = frozenset({"HSET", "HFOO"})\n'
    )
    (tmp_path / "toy_sharding.py").write_text(
        textwrap.dedent(
            """\
            class ShardedStore:
                def hset(self, key, fields):
                    pass
            """
        )
    )
    (tmp_path / "toy_racecheck.py").write_text(
        textwrap.dedent(
            """\
            class RaceCheckStore:
                def hset(self, key, fields):
                    pass

                def hfoo(self, key):
                    pass
            """
        )
    )
    findings = run_paths([tmp_path])
    drift = [f for f in findings if f.rule == "replication.registry-drift"]
    assert [(Path(f.path).name, f.line) for f in drift] == [
        ("toy_sharding.py", 1)
    ]
    assert "HFOO" in drift[0].message
    assert "hfoo" in drift[0].message  # names the expected method spellings


def test_registry_drift_clean_when_registries_agree(tmp_path):
    (tmp_path / "toy_server.py").write_text(_TOY_SERVER)
    (tmp_path / "toy_replication.py").write_text(
        'MUTATING_COMMANDS = frozenset({"HSET", "HFOO"})\n'
    )
    findings = run_paths([tmp_path])
    assert [f for f in findings if f.rule.startswith("replication.")] == []


def test_registry_drift_ignores_non_switch_dispatch_methods(tmp_path):
    """A dispatcher-side method that merely shares the _dispatch name
    (no command branches) is not a registry — PR-10 regression: the
    multihost dispatcher's _dispatch must not be held to the RESP set."""
    (tmp_path / "toy_replication.py").write_text(
        'MUTATING_COMMANDS = frozenset({"HSET"})\n'
    )
    (tmp_path / "toy_dispatch.py").write_text(
        textwrap.dedent(
            """\
            class Dispatcher:
                def _dispatch(self, task, worker):
                    worker.send(task)
            """
        )
    )
    findings = run_paths([tmp_path])
    assert [f for f in findings if f.rule.startswith("replication.")] == []


def test_registry_drift_real_tree_is_synchronized():
    """The shipped five registries (plus the native table) agree on the
    full mutating set — and the checker is demonstrably LOOKING at them:
    it must have collected all six registry instances from the real
    store package."""
    from tpu_faas.analysis.registries import RegistryChecker
    from tpu_faas.analysis.core import Module

    checker = RegistryChecker()
    package = Path(__file__).parent.parent / "tpu_faas"
    for name in (
        "store/server.py", "store/replication.py",
        "store/sharding.py", "store/racecheck.py",
    ):
        p = package / name
        list(checker.check(Module.parse(p, name, p.read_text())))
    kinds = sorted(r.kind for r in checker._registries)
    assert kinds == [
        "apply", "dispatch", "forward", "native", "racecheck", "sharded",
    ]
    assert list(checker.finalize()) == []
    # the derived mutating set is the documented seven
    assert {
        "HSET", "HSETNX", "HINCRBY", "HDEL", "DEL", "PUBLISH", "FLUSHDB"
    } <= {c for r in checker._registries for c in r.commands | r.replicating}


# -- shard safety ------------------------------------------------------------


def test_shard_undeclared_namespace_fires(tmp_path):
    findings = check(
        tmp_path,
        """\
        def f(store, tid):
            store.hset("speed_grades:" + tid, {"v": "1"})
            store.hget(f"leaderboard:{tid}", "rank")
        """,
    )
    assert hits(findings) == [
        ("shard.undeclared-namespace", 2),
        ("shard.undeclared-namespace", 3),
    ]
    assert all(f.severity == "error" for f in findings)


def test_shard_declared_namespaces_are_clean(tmp_path):
    findings = check(
        tmp_path,
        """\
        from tpu_faas.store.base import LIVE_INDEX_KEY, blob_key

        FLEET_HEALTH_KEY = "fleet:health"

        def f(store, tid, digest, trace_id):
            store.hget(LIVE_INDEX_KEY, tid)
            store.hgetall(FLEET_HEALTH_KEY)
            store.hget(blob_key(digest), "data")
            store.hset(f"trace:{trace_id}", {"t0": "1"})
            store.hgetall(tid)  # dynamic key: plain ring routing
        """,
    )
    assert hits(findings) == []


def test_shard_blobreq_namespace_is_declared(tmp_path):
    """The result-blob plane's lazy-materialization claims
    (``blobreq:<digest>``) are a declared ring-routed namespace: the
    gateway spelling (``blobreq_key()`` helper, f-string head, and the
    BLOBREQ_PREFIX constant) all resolve clean, while a near-miss
    spelling outside the namespace still fires."""
    findings = check(
        tmp_path,
        """\
        from tpu_faas.store.base import BLOBREQ_PREFIX, blobreq_key

        def f(store, digest):
            store.setnx_field(blobreq_key(digest), "req_at", "1")
            store.delete(f"blobreq:{digest}")
            store.hget(BLOBREQ_PREFIX + digest, "req_at")
            store.hset("blobrequest:" + digest, {"v": "1"})  # NOT declared
        """,
    )
    assert hits(findings) == [("shard.undeclared-namespace", 7)]


def test_shard_blobreq_mixed_batch_fires(tmp_path):
    """A literal batch mixing a ring-routed blobreq claim with a
    broadcast key is the exact coupling the rule exists to catch."""
    findings = check(
        tmp_path,
        """\
        from tpu_faas.store.base import DISPATCHERS_KEY

        def f(store, digest):
            store.delete_many([f"blobreq:{digest}", DISPATCHERS_KEY])
        """,
    )
    assert hits(findings) == [("shard.mixed-routing-pipeline", 4)]


def test_shard_mixed_routing_pipeline_fires(tmp_path):
    findings = check(
        tmp_path,
        """\
        from tpu_faas.store.base import LIVE_INDEX_KEY

        def f(store, digest):
            store.hgetall_many([LIVE_INDEX_KEY, f"blob:{digest}"])
        """,
    )
    assert hits(findings) == [("shard.mixed-routing-pipeline", 4)]


def test_shard_single_class_batches_and_dynamic_batches_clean(tmp_path):
    findings = check(
        tmp_path,
        """\
        def f(store, digests, items):
            store.hgetall_many([f"blob:{d}" for d in digests])
            store.hset_many(items)
            store.hgetall_many(["blob:aa", "blob:bb"])
        """,
    )
    assert hits(findings) == []


def test_shard_store_package_may_mix_routing(tmp_path):
    """ShardedStore's own batch forms special-case broadcast keys — the
    store package is the one place a literal mix is the implementation,
    not a bypass."""
    pkg = tmp_path / "tpu_faas" / "store"
    pkg.mkdir(parents=True)
    (pkg / "impl.py").write_text(
        textwrap.dedent(
            """\
            from tpu_faas.store.base import LIVE_INDEX_KEY

            def fan(store, digest):
                store.hgetall_many([LIVE_INDEX_KEY, f"blob:{digest}"])
            """
        )
    )
    findings = run_paths([tmp_path / "tpu_faas"])
    assert [f for f in findings if f.rule == "shard.mixed-routing-pipeline"] == []


def test_shard_suppressible(tmp_path):
    findings = check(
        tmp_path,
        """\
        def f(store):
            # one-off migration key, never read by fleet routing
            store.hset("migration:v2", {"done": "1"})  # faas: allow(shard.undeclared-namespace)
        """,
    )
    assert hits(findings) == []


# -- metrics discipline ------------------------------------------------------


def test_metrics_counter_not_total_fires(tmp_path):
    findings = check(
        tmp_path,
        """\
        def build(registry):
            return registry.counter("tpu_faas_requests", "requests served")
        """,
    )
    assert hits(findings) == [("metrics.counter-not-total", 2)]
    assert findings[0].severity == "error"


def test_metrics_unbounded_label_fires_at_declaration_and_use(tmp_path):
    findings = check(
        tmp_path,
        """\
        def build(metrics, task_id):
            m = metrics.counter(
                "tpu_faas_lookups_total", "lookups", ("task_id",)
            )
            m.labels(task_id=task_id).inc()
            m.labels(str(task_id)).inc()
        """,
    )
    assert hits(findings) == [
        ("metrics.unbounded-cardinality-label", 2),
        ("metrics.unbounded-cardinality-label", 5),
        ("metrics.unbounded-cardinality-label", 6),
    ]


def test_metrics_label_vocabulary_drift_fires_cross_module(tmp_path):
    (tmp_path / "gateway_m.py").write_text(
        textwrap.dedent(
            """\
            def build(metrics):
                return metrics.histogram(
                    "tpu_faas_stage_seconds", "stage", ("stage",)
                )
            """
        )
    )
    (tmp_path / "dispatch_m.py").write_text(
        textwrap.dedent(
            """\
            def build(registry):
                return registry.histogram(
                    "tpu_faas_stage_seconds", "stage", ("phase",)
                )
            """
        )
    )
    findings = run_paths([tmp_path])
    drift = [
        f for f in findings if f.rule == "metrics.label-vocabulary-drift"
    ]
    assert [(Path(f.path).name, f.line) for f in drift] == [
        ("dispatch_m.py", 2),
        ("gateway_m.py", 2),
    ]
    assert "one family, one vocabulary" in drift[0].message


def test_metrics_same_vocab_in_two_processes_is_clean(tmp_path):
    """The gateway and a dispatcher legitimately re-register the same
    family in their per-process registries — identical vocabulary is not
    drift."""
    for name in ("a.py", "b.py"):
        (tmp_path / name).write_text(
            textwrap.dedent(
                """\
                def build(registry):
                    registry.counter(
                        "tpu_faas_dup_events_total", "dups", ("event",)
                    )
                    registry.gauge("tpu_faas_depth", "queue depth")
                """
            )
        )
    findings = run_paths([tmp_path])
    assert [f for f in findings if f.rule.startswith("metrics.")] == []


def test_metrics_non_registry_receivers_are_ignored(tmp_path):
    findings = check(
        tmp_path,
        """\
        def f(machine, task_id):
            machine.counter("spins")
            machine.labels(task_id)
        """,
    )
    assert hits(findings) == []


def test_metrics_derived_label_values_are_clean(tmp_path):
    """A value DERIVED from an unbounded id (shard index, status) is
    bounded by construction."""
    findings = check(
        tmp_path,
        """\
        def f(m, ring, task_id):
            m.labels(shard=str(ring.shard_of(task_id))).inc()
        """,
    )
    assert hits(findings) == []


# -- stale suppressions ------------------------------------------------------


def test_stale_suppression_warns_and_strict_promotes(tmp_path, capsys):
    p = tmp_path / "snippet.py"
    p.write_text(
        textwrap.dedent(
            """\
            def f(x):
                return x + 1  # faas: allow(obs.wall-clock-latency)
            """
        )
    )
    findings = run_paths([p])
    assert hits(findings) == [("core.stale-suppression", 2)]
    assert findings[0].severity == "warning"
    # default gate passes (warning), --strict fails
    assert analysis_main([str(p)]) == 0
    assert analysis_main(["--strict", str(p)]) == 1
    capsys.readouterr()


def test_stale_suppression_per_token_granularity(tmp_path):
    """One live token plus one dead token on the same line: only the dead
    one is reported."""
    findings = check(
        tmp_path,
        """\
        def f(store, tid):
            store.set_status(tid, "COMPLETED")  # faas: allow(protocol.terminal-set-status, trace.print)
        """,
    )
    assert hits(findings) == [("core.stale-suppression", 2)]
    assert "trace.print" in findings[0].message


def test_live_suppressions_stay_silent(tmp_path):
    findings = check(
        tmp_path,
        """\
        def f(store, tid):
            store.set_status(tid, "COMPLETED")  # faas: allow(protocol.terminal-set-status)
        """,
    )
    assert hits(findings) == []


def test_docstring_spelled_allow_is_not_a_suppression(tmp_path):
    """The directive quoted in a docstring (rule catalogs, examples) must
    neither suppress nor count as stale — only real comment tokens that
    START with the directive register."""
    findings = check(
        tmp_path,
        '''\
        def f(store, tid):
            """Suppress with ``# faas: allow(protocol.terminal-set-status)``."""
            store.set_status(tid, "COMPLETED")
        ''',
    )
    assert hits(findings) == [("protocol.terminal-set-status", 3)]


# -- SARIF -------------------------------------------------------------------


def test_sarif_output_shape_and_gate_exit(tmp_path, capsys):
    p = tmp_path / "snippet.py"
    p.write_text(
        textwrap.dedent(
            """\
            def f(store, tid):
                store.set_status(tid, "COMPLETED")
            """
        )
    )
    out = tmp_path / "out.sarif"
    rc = analysis_main(["--sarif", str(out), str(p)])
    assert rc == 1  # SARIF emission never weakens the gate
    capsys.readouterr()
    doc = json.loads(out.read_text())
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "tpu-faas-analysis"
    (result,) = run["results"]
    assert result["ruleId"] == "protocol.terminal-set-status"
    assert result["level"] == "error"
    loc = result["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "snippet.py"
    assert loc["region"]["startLine"] == 2
    # rule metadata present for every distinct rule id
    rules = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert rules == {"protocol.terminal-set-status"}


def test_sarif_respects_baseline_subtraction(tmp_path, capsys):
    p = tmp_path / "snippet.py"
    p.write_text(
        textwrap.dedent(
            """\
            def f(store, tid):
                store.set_status(tid, "COMPLETED")
            """
        )
    )
    baseline = tmp_path / "baseline.json"
    assert analysis_main(["--write-baseline", str(baseline), str(p)]) == 0
    out = tmp_path / "out.sarif"
    rc = analysis_main(
        ["--baseline", str(baseline), "--sarif", str(out), str(p)]
    )
    assert rc == 0
    capsys.readouterr()
    doc = json.loads(out.read_text())
    assert doc["runs"][0]["results"] == []


def test_registry_drift_fires_when_apply_switch_lags(tmp_path):
    """The forwarded-and-DROPPED shape: dispatch replicates HFOO and the
    forward set carries it, but the replica apply switch never learned
    it — fires at apply_replicated."""
    (tmp_path / "toy_server.py").write_text(
        textwrap.dedent(
            """\
            class StoreServer:
                async def _dispatch(self, cmd, writer):
                    name = cmd[0].upper()
                    if name == "HSET":
                        self._replicate(cmd)
                    elif name == "HFOO":
                        self._replicate(cmd)

                def apply_replicated(self, cmd):
                    name = cmd[0].upper()
                    if name == "HSET":
                        self.apply(cmd)
            """
        )
    )
    (tmp_path / "toy_replication.py").write_text(
        'MUTATING_COMMANDS = frozenset({"HSET", "HFOO"})\n'
    )
    findings = run_paths([tmp_path])
    drift = [f for f in findings if f.rule == "replication.registry-drift"]
    assert len(drift) == 1
    assert Path(drift[0].path).name == "toy_server.py"
    assert "HFOO" in drift[0].message
    assert "apply_replicated" in drift[0].message


def test_registry_drift_fires_when_dispatch_mutates_without_replicate(tmp_path):
    """The silently-un-replicates shape: the dispatch HANDLES a mutating
    primitive (branch exists, applies state) but never forwards it —
    replicas would silently diverge. Must fire at the dispatch even
    though the command is spelled in every registry."""
    (tmp_path / "toy_server.py").write_text(
        textwrap.dedent(
            """\
            class StoreServer:
                async def _dispatch(self, cmd, writer):
                    name = cmd[0].upper()
                    if name == "HSET":
                        self.apply(cmd)
                        self._replicate(cmd)
                    elif name == "HFOO":
                        self.apply(cmd)  # forgot _replicate

                def apply_replicated(self, cmd):
                    name = cmd[0].upper()
                    if name == "HSET":
                        self.apply(cmd)
                    elif name == "HFOO":
                        self.apply(cmd)
            """
        )
    )
    (tmp_path / "toy_replication.py").write_text(
        'MUTATING_COMMANDS = frozenset({"HSET", "HFOO"})\n'
    )
    findings = run_paths([tmp_path])
    drift = [f for f in findings if f.rule == "replication.registry-drift"]
    assert len(drift) == 1
    assert Path(drift[0].path).name == "toy_server.py"
    assert "HFOO" in drift[0].message
    assert "WITHOUT a _replicate call" in drift[0].message


def test_shard_literal_namespaces_pin_their_runtime_constants():
    """shardsafety spells the admission/obs-owned namespaces literally
    (importing those packages would crash the gate on the broken
    checkouts it exists to diagnose) — this pin keeps the literals from
    drifting against the runtime constants."""
    from tpu_faas.analysis import shardsafety
    from tpu_faas.admission.signal import FLEET_HEALTH_KEY
    from tpu_faas.obs.tracectx import TRACE_PREFIX

    assert shardsafety.FLEET_HEALTH_KEY == FLEET_HEALTH_KEY
    assert shardsafety.TRACE_PREFIX == TRACE_PREFIX
    declared = {s for s, _k, _r in shardsafety.NAMESPACES}
    assert {FLEET_HEALTH_KEY, TRACE_PREFIX} <= declared


# -- trace: fused-kernel helper shape (sched/pallas_fused.py) ----------------


def test_trace_fused_kernel_helper_chain_fires(tmp_path):
    """The fused resident kernel's structure — a pallas_call whose kernel
    closure reads refs and traces through module-level ``_impl`` helpers —
    keeps the whole helper chain pallas-REACHABLE: a host-time call or a
    data-dependent Python branch smuggled into any layer of the chain
    must fire exactly as if it sat in the kernel body."""
    findings = check(
        tmp_path,
        """\
        import time
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def _solver_impl(x):
            stamp = time.monotonic()
            return x + stamp

        def _tick_impl(x):
            return _solver_impl(x) * 2

        def fused_tick(packed):
            def kernel(packed_ref, out_ref):
                out_ref[...] = _tick_impl(packed_ref[...])

            return pl.pallas_call(kernel, out_shape=None)(packed)
        """,
    )
    assert ("trace.host-time", 7) in hits(findings)


def test_trace_fused_kernel_shape_clean(tmp_path):
    """The real fused-kernel idioms — a closure kernel writing refs, a
    make_jaxpr constant lift, fori_loop streaming over dynamic slices —
    carry no trace hazards and must stay clean."""
    findings = check(
        tmp_path,
        """\
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def _stream_impl(x, price):
            def chunk(j, acc):
                c = jax.lax.dynamic_slice(price, (j * 8,), (8,))
                return jnp.maximum(acc, c.max())

            return jax.lax.fori_loop(0, 4, chunk, jnp.float32(-1e30)) + x

        def fused_tick(packed, price):
            closed = jax.make_jaxpr(_stream_impl)(packed, price)
            consts = [jnp.atleast_1d(jnp.asarray(c)) for c in closed.consts]

            def kernel(*refs):
                vals = [r[...] for r in refs[:-1]]
                refs[-1][...] = jax.core.eval_jaxpr(
                    closed.jaxpr, vals[2:], *vals[:2]
                )

            return pl.pallas_call(kernel, out_shape=None)(
                packed, price, *consts
            )
        """,
    )
    assert hits(findings) == []


def test_trace_real_fused_modules_analyzed_clean():
    """The static gate's live proof: the shipped fused-kernel modules are
    in scope for the trace checker (pallas_call roots) and carry zero
    findings — the kernel stays trace-safe as it grows."""
    import tpu_faas.sched.pallas_fused as pf
    import tpu_faas.sched.pallas_kernels as pk

    findings = run_paths([Path(pf.__file__), Path(pk.__file__)])
    assert [f for f in findings if f.rule.startswith("trace.")] == []


def test_trace_partial_jit_assignment_wrap_is_a_root(tmp_path):
    """The _impl/jitted-twin split (`foo = partial(jax.jit, ...)(foo_impl)`)
    must keep foo_impl a traced ROOT with its statics known: a hazard in
    the impl fires, and a branch on a declared static stays exempt."""
    findings = check(
        tmp_path,
        """\
        import time
        import jax
        from functools import partial

        def solver_impl(x, mode="fast"):
            t = time.time()
            if mode == "fast":
                x = x * 2
            if x > 0:
                x = x + 1
            return x + t

        solver = partial(jax.jit, static_argnames=("mode",))(solver_impl)
        """,
    )
    assert hits(findings) == [
        ("trace.host-time", 6),
        ("trace.data-dependent-branch", 9),
    ]


# -- kernelparity ------------------------------------------------------------


def test_kernelparity_group_order_drift_fires(tmp_path):
    findings = check(
        tmp_path,
        """\
        from typing import NamedTuple

        class FooState(NamedTuple):
            a: object  # f32[T]
            b: object  # i32[T]
            c: object  # f32[T]
            d: object  # bool[T]

        def kernel(st, run):
            return run(st.a, st.b, st.d, st.c)
        """,
    )
    assert hits(findings) == [("kernelparity.state-leaf-drift", 10)]
    assert "out of declaration order" in findings[0].message


def test_kernelparity_group_missing_leaf_fires(tmp_path):
    findings = check(
        tmp_path,
        """\
        from typing import NamedTuple

        class PodState(NamedTuple):
            a: object  # f32[T]
            b: object  # f32[T]
            c: object  # f32[T]
            d: object  # f32[T]
            e: object  # f32[T]
            f: object  # f32[T]
            g: object  # f32[T]
            h: object  # f32[T]

        def kernel(st, run):
            return run(st.a, st.b, st.c, st.d, st.e, st.f, st.g)
        """,
    )
    assert hits(findings) == [("kernelparity.state-leaf-drift", 14)]
    assert "missing ['h']" in findings[0].message


def test_kernelparity_full_consumption_clean(tmp_path):
    findings = check(
        tmp_path,
        """\
        from typing import NamedTuple

        class FooState(NamedTuple):
            a: object  # f32[T]
            b: object  # i32[T]
            c: object  # f32[T]
            d: object  # bool[T]

        def kernel(st, run):
            return run(st.a, st.b, st.c, st.d)

        def tick(st):
            return (st.a, st.b, st.c, st.d)
        """,
    )
    assert findings == []


def test_kernelparity_partial_reads_below_threshold_clean(tmp_path):
    """Helper sites reading a handful of leaves out of order (the XLA
    tick's scheduler_tick_impl shape) are not full-consumption sites."""
    findings = check(
        tmp_path,
        """\
        from typing import NamedTuple

        class PodState(NamedTuple):
            a: object  # f32[T]
            b: object  # f32[T]
            c: object  # f32[T]
            d: object  # f32[T]
            e: object  # f32[T]
            f: object  # f32[T]
            g: object  # f32[T]
            h: object  # f32[T]

        def helper(st, run):
            return run(st.e, st.a, st.c)
        """,
    )
    assert findings == []


def test_kernelparity_ctor_arity_fires(tmp_path):
    findings = check(
        tmp_path,
        """\
        from typing import NamedTuple

        class FooState(NamedTuple):
            a: object  # f32[T]
            b: object  # i32[T]
            c: object  # f32[T]
            d: object  # bool[T]

        def rebuild(x, y, z):
            return FooState(x, y, z)
        """,
    )
    assert hits(findings) == [("kernelparity.state-leaf-drift", 10)]
    assert "constructs 3 leaves" in findings[0].message


def test_kernelparity_ctor_positional_token_fires(tmp_path):
    findings = check(
        tmp_path,
        """\
        from typing import NamedTuple

        class FooState(NamedTuple):
            a: object  # f32[T]
            b: object  # i32[T]
            c: object  # f32[T]
            d: object  # bool[T]

        def rebuild(st, b_new, d_new):
            return FooState(st.a, st.c, b_new, d_new)
        """,
    )
    assert hits(findings) == [("kernelparity.state-leaf-drift", 10)]
    assert "'c' at position 1" in findings[0].message


def test_kernelparity_ctor_mixed_computed_leaves_clean(tmp_path):
    """The resident tick's constructor shape: passthrough st.* leaves at
    their declared positions interleaved with freshly-computed values."""
    findings = check(
        tmp_path,
        """\
        from typing import NamedTuple

        class FooState(NamedTuple):
            a: object  # f32[T]
            b: object  # i32[T]
            c: object  # f32[T]
            d: object  # bool[T]

        def rebuild(st, b_next, flag):
            return FooState(st.a, b_next, st.c, st.d if flag else st.d)
        """,
    )
    assert findings == []


def test_kernelparity_alias_span_fires(tmp_path):
    findings = check(
        tmp_path,
        """\
        from typing import NamedTuple

        class FooState(NamedTuple):
            a: object  # f32[T]
            b: object  # i32[T]
            c: object  # f32[T]
            d: object  # bool[T]

        def build(pallas_call, kern):
            return pallas_call(
                kern,
                input_output_aliases={k: 2 + k for k in range(1, 4)},
            )
        """,
    )
    assert [f.rule for f in findings] == ["kernelparity.state-leaf-drift"]
    assert "spans 3 state operands" in findings[0].message


def test_kernelparity_spec_tuple_length_fires(tmp_path):
    findings = check(
        tmp_path,
        """\
        from typing import NamedTuple

        class FooState(NamedTuple):
            a: object  # f32[T]
            b: object  # i32[T]
            c: object  # f32[T]
            d: object  # bool[T]

        def build(pallas_call, kern, ps):
            in_specs = (ps, ps, ps, ps)
            return pallas_call(
                kern,
                input_output_aliases={k: 2 + k for k in range(1, 5)},
            )
        """,
    )
    assert [f.rule for f in findings] == ["kernelparity.state-leaf-drift"]
    assert "in_specs holds 4 entries but 5 are required" in findings[0].message


def test_kernelparity_out_shape_dtype_drift_fires(tmp_path):
    findings = check(
        tmp_path,
        """\
        import jax
        import jax.numpy as jnp
        from typing import NamedTuple

        class FooState(NamedTuple):
            a: object  # f32[T]
            b: object  # i32[T]
            c: object  # f32[T]
            d: object  # bool[T]

        def build(pallas_call, kern, ps):
            f32, i32, b = jnp.float32, jnp.int32, jnp.bool_
            out_shape = (
                jax.ShapeDtypeStruct((4,), f32),
                jax.ShapeDtypeStruct((4,), f32),
                jax.ShapeDtypeStruct((4,), f32),
                jax.ShapeDtypeStruct((4,), f32),
                jax.ShapeDtypeStruct((4,), f32),
                jax.ShapeDtypeStruct((4,), f32),
                jax.ShapeDtypeStruct((4,), b),
            )
            return pallas_call(
                kern,
                input_output_aliases={k: 2 + k for k in range(1, 5)},
            )
        """,
    )
    assert [f.rule for f in findings] == ["kernelparity.state-dtype-drift"]
    assert "leaf 'b' as f32" in findings[0].message


def test_kernelparity_out_shape_dtypes_match_comments_clean(tmp_path):
    findings = check(
        tmp_path,
        """\
        import jax
        import jax.numpy as jnp
        from typing import NamedTuple

        class FooState(NamedTuple):
            a: object  # f32[T]
            b: object  # i32[T]
            c: object  # f32[T]
            d: object  # bool[T]

        def build(pallas_call, kern, ps):
            f32, i32, b = jnp.float32, jnp.int32, jnp.bool_
            out_shape = (
                jax.ShapeDtypeStruct((4,), f32),
                jax.ShapeDtypeStruct((4,), f32),
                jax.ShapeDtypeStruct((4,), f32),
                jax.ShapeDtypeStruct((4,), i32),
                jax.ShapeDtypeStruct((4,), f32),
                jax.ShapeDtypeStruct((4,), b),
            )
            return pallas_call(
                kern,
                input_output_aliases={k: 1 + k for k in range(1, 5)},
            )
        """,
    )
    assert findings == []


def test_kernelparity_twin_unknown_kwarg_fires(tmp_path):
    findings = check(
        tmp_path,
        """\
        def tick_impl(x, y, mode="fast"):
            return x

        def run(x, y):
            return tick_impl(x, y, lanes=4)
        """,
    )
    assert hits(findings) == [("kernelparity.twin-signature-drift", 5)]
    assert "['lanes']" in findings[0].message


def test_kernelparity_twin_required_coverage_fires(tmp_path):
    findings = check(
        tmp_path,
        """\
        def tick_impl(x, y, z):
            return x

        def run(x):
            return tick_impl(x)
        """,
    )
    assert hits(findings) == [("kernelparity.twin-signature-drift", 5)]
    assert "['y', 'z']" in findings[0].message


def test_kernelparity_twin_splat_resolved_clean(tmp_path):
    """The fused tick's ``**statics`` idiom: a local dict literal (even
    one bound in an enclosing scope) closes the kwarg set."""
    findings = check(
        tmp_path,
        """\
        def tick_impl(x, T, S):
            return x

        def outer(x):
            statics = dict(T=4, S=8)

            def run():
                return tick_impl(x, **statics)

            return run
        """,
    )
    assert findings == []


def test_kernelparity_jit_static_argnames_drift_fires(tmp_path):
    findings = check(
        tmp_path,
        """\
        import jax
        from functools import partial

        def solve_impl(x, mode):
            return x

        solve = partial(jax.jit, static_argnames=("mode", "lanes"))(solve_impl)
        """,
    )
    assert [f.rule for f in findings] == ["kernelparity.twin-signature-drift"]
    assert "['lanes']" in findings[0].message


def test_kernelparity_suppressible(tmp_path):
    findings = check(
        tmp_path,
        """\
        from typing import NamedTuple

        class FooState(NamedTuple):
            a: object  # f32[T]
            b: object  # i32[T]
            c: object  # f32[T]
            d: object  # bool[T]

        def kernel(st, run):
            return run(st.a, st.b, st.d, st.c)  # faas: allow(kernelparity)
        """,
    )
    assert findings == []


def test_kernelparity_real_tree_registry_pin():
    """Real-tree synchronization pin: the 16-leaf _ResidentState registry
    is derived from the shipped declarations, and the shipped backends
    carry zero parity findings."""
    import tpu_faas.sched.pallas_fused as pf
    import tpu_faas.sched.pallas_kernels as pk
    import tpu_faas.sched.resident as rs
    from tpu_faas.analysis import KernelParityChecker
    from tpu_faas.analysis.core import Module

    checker = KernelParityChecker()
    for mod in (rs, pf, pk):
        path = Path(mod.__file__)
        m = Module.parse(path, path.name, path.read_text(encoding="utf-8"))
        list(checker.check(m))
    assert list(checker.finalize()) == []
    regs = {r.name: r.leaves for r in checker.registries}
    assert regs["_ResidentState"] == [
        "sizes", "valid", "prio", "tenant", "last_hb", "free",
        "inflight", "prev_live", "speed", "active", "price",
        "t_deficit", "infl_start", "infl_pred", "avoid", "refresh",
    ]


def test_kernelparity_live_mutation_drop_leaf_flips_gate(tmp_path, capsys):
    """The ISSUE's live-verified mutation: delete one state leaf from only
    the Pallas consumer and the strict gate flips from 0 to 1."""
    (tmp_path / "state.py").write_text(
        textwrap.dedent(
            """\
            from typing import NamedTuple

            class PodState(NamedTuple):
                a: object  # f32[T]
                b: object  # f32[T]
                c: object  # f32[T]
                d: object  # f32[T]
                e: object  # f32[T]
                f: object  # f32[T]
                g: object  # f32[T]
                h: object  # f32[T]

            def xla_tick(st, run):
                return run(st.a, st.b, st.c, st.d, st.e, st.f, st.g, st.h)
            """
        )
    )
    pallas_full = textwrap.dedent(
        """\
        def pallas_tick(st, run):
            return run(st.a, st.b, st.c, st.d, st.e, st.f, st.g, st.h)
        """
    )
    (tmp_path / "pallas.py").write_text(pallas_full)
    assert analysis_main(["--strict", str(tmp_path)]) == 0
    capsys.readouterr()
    (tmp_path / "pallas.py").write_text(
        pallas_full.replace(", st.e", "")
    )
    assert analysis_main(["--strict", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "kernelparity.state-leaf-drift" in out
    assert "pallas.py" in out


# -- devicesnapshot ----------------------------------------------------------


def test_devicesnapshot_asarray_then_index_assign_fires(tmp_path):
    findings = check(
        tmp_path,
        """\
        import jax.numpy as jnp

        def f(host):
            dev = jnp.asarray(host)
            host[0] = 1.0
            return dev
        """,
    )
    assert hits(findings) == [("devicesnapshot.unsnapshotted-upload", 4)]
    assert "mutated in place at line 5" in findings[0].message


def test_devicesnapshot_device_put_attr_chain_and_augassign_fires(tmp_path):
    findings = check(
        tmp_path,
        """\
        import jax

        class S:
            def push(self):
                dev = jax.device_put(self.buf)
                self.buf += 1
                return dev
        """,
    )
    assert hits(findings) == [("devicesnapshot.unsnapshotted-upload", 5)]


def test_devicesnapshot_mutating_method_fires(tmp_path):
    findings = check(
        tmp_path,
        """\
        import jax.numpy as jnp

        def f(host):
            dev = jnp.asarray(host)
            host.fill(0)
            return dev
        """,
    )
    assert hits(findings) == [("devicesnapshot.unsnapshotted-upload", 4)]


def test_devicesnapshot_copy_upload_clean(tmp_path):
    findings = check(
        tmp_path,
        """\
        import jax.numpy as jnp

        def f(host):
            dev = jnp.asarray(host.copy())
            host[0] = 1.0
            return dev
        """,
    )
    assert findings == []


def test_devicesnapshot_mutate_before_upload_clean(tmp_path):
    """The build-then-upload idiom: locals that finish mutating before
    the transfer are snapshots by construction."""
    findings = check(
        tmp_path,
        """\
        import jax.numpy as jnp

        def f(n):
            host = [0] * n
            host[0] = 1.0
            return jnp.asarray(host)
        """,
    )
    assert findings == []


def test_devicesnapshot_rebind_breaks_aliasing_clean(tmp_path):
    findings = check(
        tmp_path,
        """\
        import jax.numpy as jnp

        def f(host):
            dev = jnp.asarray(host)
            host = host * 2
            host[0] = 1.0
            return dev, host
        """,
    )
    assert findings == []


def test_devicesnapshot_np_asarray_is_host_side_and_clean(tmp_path):
    findings = check(
        tmp_path,
        """\
        import numpy as np

        def f(host):
            mirror = np.asarray(host)
            host[0] = 1.0
            return mirror
        """,
    )
    assert findings == []


def test_devicesnapshot_nested_scopes_are_independent(tmp_path):
    findings = check(
        tmp_path,
        """\
        import jax.numpy as jnp

        def f(host):
            dev = jnp.asarray(host)

            def later():
                host[0] = 1.0

            return dev, later
        """,
    )
    assert findings == []


def test_devicesnapshot_suppressible(tmp_path):
    findings = check(
        tmp_path,
        """\
        import jax.numpy as jnp

        def f(host):
            dev = jnp.asarray(host)  # faas: allow(devicesnapshot)
            host[0] = 1.0
            return dev
        """,
    )
    assert findings == []


def test_devicesnapshot_real_sched_and_push_dispatch_clean():
    """The PR 5 bug class stays fixed: every shipped upload in the
    scheduler and the TPU push dispatcher is a snapshot."""
    import tpu_faas.dispatch.tpu_push as tp
    import tpu_faas.sched as sched

    findings = run_paths(
        [Path(sched.__file__).parent, Path(tp.__file__)]
    )
    assert [
        f for f in findings if f.rule.startswith("devicesnapshot.")
    ] == []


# -- planegate ---------------------------------------------------------------


def test_planegate_ungated_field_write_fires(tmp_path):
    """The ISSUE's live-verified mutation shape: a FIELD_* write gated by
    its plane flag at one site and naked at another."""
    findings = check(
        tmp_path,
        """\
        CAP_TRACE = "trace"
        FIELD_TRACE_ID = "t0"

        def submit(extra, ctx, tid):
            if ctx.trace:
                extra[FIELD_TRACE_ID] = tid

        def observe(extra, tid):
            extra[FIELD_TRACE_ID] = tid
        """,
    )
    assert hits(findings) == [("planegate.ungated-field-write", 9)]


def test_planegate_gate_forms_are_recognized_clean(tmp_path):
    findings = check(
        tmp_path,
        """\
        CAP_BLOB = "blob"
        FIELD_FN_DIGEST = "fn_digest"

        def negotiated(extra, caps, dig):
            if CAP_BLOB in caps:
                extra[FIELD_FN_DIGEST] = dig

        def flagged(extra, use_payload_plane, dig):
            if use_payload_plane:
                extra[FIELD_FN_DIGEST] = dig

        def ctx_attr(extra, ctx, dig):
            if ctx.payload_plane and dig:
                extra[FIELD_FN_DIGEST] = dig
        """,
    )
    assert findings == []


def test_planegate_presence_gate_satisfies_but_never_registers(tmp_path):
    """A value-presence check satisfies a gated write (the round-trip
    idiom) but cannot itself register a field as plane-gated."""
    findings = check(
        tmp_path,
        """\
        CAP_TRACE = "trace"
        FIELD_TRACE_ID = "t0"
        FIELD_SUBMITTED_AT = "s0"

        def submit(extra, ctx, tid, now):
            if ctx.trace:
                extra[FIELD_TRACE_ID] = tid
            extra[FIELD_SUBMITTED_AT] = repr(now)

        def restore(extra, tid, at):
            if tid is not None:
                extra[FIELD_TRACE_ID] = tid
            if at is not None:
                extra[FIELD_SUBMITTED_AT] = at

        def stamp(extra, now):
            extra[FIELD_SUBMITTED_AT] = repr(now)
        """,
    )
    assert findings == []


def test_planegate_else_branch_does_not_inherit_gate(tmp_path):
    findings = check(
        tmp_path,
        """\
        CAP_TRACE = "trace"
        FIELD_TRACE_ID = "t0"

        def submit(extra, ctx, tid):
            if ctx.trace:
                extra[FIELD_TRACE_ID] = tid
            else:
                extra[FIELD_TRACE_ID] = "missing"
        """,
    )
    assert hits(findings) == [("planegate.ungated-field-write", 8)]


def test_planegate_unknown_capability_fires(tmp_path):
    findings = check(
        tmp_path,
        """\
        CAP_TRACE = "trace"

        def negotiate(caps):
            return CAP_TRACING in caps
        """,
    )
    assert hits(findings) == [("planegate.unknown-capability", 4)]


def test_planegate_ungated_wire_write_fires(tmp_path):
    findings = check(
        tmp_path,
        """\
        CAP_TRACE = "trace"
        FIELD_TRACE_ID = "trace_id"

        def frame(out, ctx, tid):
            if ctx.trace:
                out["trace_id"] = tid

        def echo(out, tid):
            out["trace_id"] = tid
        """,
    )
    assert hits(findings) == [("planegate.ungated-wire-write", 9)]


def test_planegate_non_vocab_wire_keys_unconstrained(tmp_path):
    """A literal dict key outside the FIELD_* vocabulary must not be
    conscripted by an incidental flag — only declared wire fields carry
    the byte-identical-surface contract."""
    findings = check(
        tmp_path,
        """\
        CAP_TRACE = "trace"

        def fast_path(out, use_fast, v):
            if use_fast:
                out["shard_hint"] = v

        def slow_path(out, v):
            out["shard_hint"] = v
        """,
    )
    assert findings == []


def test_planegate_reference_surface_exempt(tmp_path):
    """Fields read by to_fields() predate every plane: gating one site
    does not constrain the reference writes."""
    findings = check(
        tmp_path,
        """\
        CAP_TRACE = "trace"
        FIELD_STATUS = "status"

        class Task:
            def to_fields(self):
                return {FIELD_STATUS: self.status}

        def gated(out, ctx, s):
            if ctx.trace:
                out[FIELD_STATUS] = s

        def reference(out, s):
            out[FIELD_STATUS] = s
            out["status"] = s
        """,
    )
    assert findings == []


def test_planegate_field_constant_and_wire_key_cross_register(tmp_path):
    """Gating the FIELD_*-keyed spelling constrains the literal wire-key
    spelling of the same field, and vice versa."""
    findings = check(
        tmp_path,
        """\
        CAP_TRACE = "trace"
        FIELD_TRACE_ID = "trace_id"

        def gated(extra, ctx, tid):
            if ctx.trace:
                extra[FIELD_TRACE_ID] = tid

        def echo(frame, tid):
            frame["trace_id"] = tid
        """,
    )
    assert hits(findings) == [("planegate.ungated-wire-write", 9)]


def test_planegate_suppressible(tmp_path):
    findings = check(
        tmp_path,
        """\
        CAP_TRACE = "trace"
        FIELD_TRACE_ID = "t0"

        def submit(extra, ctx, tid):
            if ctx.trace:
                extra[FIELD_TRACE_ID] = tid

        def observe(extra, tid):
            extra[FIELD_TRACE_ID] = tid  # faas: allow(planegate)
        """,
    )
    assert findings == []


def test_planegate_real_tree_capability_map_pin():
    """Real-tree synchronization pin: the derived capability registry is
    exactly the negotiated WORKER_CAPS vocabulary, and the trace/payload
    plane fields are derived as gated."""
    import tpu_faas
    from tpu_faas.analysis import PlaneGateChecker
    from tpu_faas.analysis.core import Module

    pkg = Path(tpu_faas.__file__).parent
    checker = PlaneGateChecker()
    for path in sorted(pkg.rglob("*.py")):
        m = Module.parse(
            path,
            str(path.relative_to(pkg.parent)),
            path.read_text(encoding="utf-8"),
        )
        list(checker.check(m))
    assert list(checker.finalize()) == []
    assert set(checker.capabilities.values()) == {
        "blob", "bin", "trace", "batch", "rblob",
    }
    assert {
        "FIELD_TRACE_ID", "FIELD_TRACE_PARENT", "FIELD_FN_DIGEST",
    } <= checker.gated_fields
    # unconditional gateway stamps stay unconstrained by derivation
    assert "FIELD_SUBMITTED_AT" not in checker.gated_fields


def test_planegate_live_mutation_ungated_write_flips_gate(tmp_path, capsys):
    """The ISSUE's second live-verified mutation: move a gated FIELD_*
    write outside its plane flag and the strict gate flips from 0 to 1."""
    gated = textwrap.dedent(
        """\
        CAP_TRACE = "trace"
        FIELD_TRACE_ID = "t0"

        def submit(extra, ctx, tid):
            if ctx.trace:
                extra[FIELD_TRACE_ID] = tid

        def batch(extra, ctx, tid):
            if ctx.trace:
                extra[FIELD_TRACE_ID] = tid
        """
    )
    (tmp_path / "gw.py").write_text(gated)
    assert analysis_main(["--strict", str(tmp_path)]) == 0
    capsys.readouterr()
    (tmp_path / "gw.py").write_text(
        gated.replace(
            "def batch(extra, ctx, tid):\n    if ctx.trace:\n"
            "        extra[FIELD_TRACE_ID] = tid",
            "def batch(extra, ctx, tid):\n"
            "    extra[FIELD_TRACE_ID] = tid",
        )
    )
    assert analysis_main(["--strict", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "planegate.ungated-field-write" in out


# -- trace: mesh axis-name discipline ----------------------------------------


def test_trace_unknown_axis_fires(tmp_path):
    findings = check(
        tmp_path,
        """\
        import jax
        import numpy as np
        from jax.sharding import Mesh

        mesh = Mesh(np.array([0]), ("tasks",))

        def combine(x):
            return jax.lax.psum(x, "task")
        """,
    )
    assert hits(findings) == [("trace.unknown-axis-name", 8)]
    assert "'task'" in findings[0].message


def test_trace_axis_via_constant_and_param_default_clean(tmp_path):
    """The mesh.py idiom end to end: the axis constant names the mesh
    axis, collectives resolve it through the constant, a parameter
    default, and axis_index's zeroth position."""
    findings = check(
        tmp_path,
        """\
        import jax
        import numpy as np
        from jax.sharding import Mesh

        TASK_AXIS = "tasks"
        mesh = Mesh(np.array([0]), (TASK_AXIS,))

        def ring(x, axis=TASK_AXIS):
            return jax.lax.ppermute(x, axis, [(0, 0)])

        def gid():
            return jax.lax.axis_index(TASK_AXIS)

        def total(x):
            return jax.lax.psum(x, axis_name=TASK_AXIS)
        """,
    )
    assert findings == []


def test_trace_no_mesh_in_run_skips_axis_rule(tmp_path):
    findings = check(
        tmp_path,
        """\
        import jax

        def combine(x):
            return jax.lax.psum(x, "anything")
        """,
    )
    assert findings == []


def test_trace_axis_declared_cross_module(tmp_path):
    """The mesh declaration and the collective may live in different
    modules of one run — declared axes are a run-wide registry."""
    (tmp_path / "meshdef.py").write_text(
        textwrap.dedent(
            """\
            import numpy as np
            from jax.sharding import Mesh

            mesh = Mesh(np.array([0]), ("tasks",))
            """
        )
    )
    (tmp_path / "kern.py").write_text(
        textwrap.dedent(
            """\
            import jax

            def good(x):
                return jax.lax.pmax(x, "tasks")

            def bad(x):
                return jax.lax.pmax(x, "rows")
            """
        )
    )
    findings = run_paths([tmp_path])
    assert hits(findings) == [("trace.unknown-axis-name", 7)]
    assert findings[0].path.endswith("kern.py")


def test_trace_dynamic_axis_is_skipped(tmp_path):
    findings = check(
        tmp_path,
        """\
        import jax
        import numpy as np
        from jax.sharding import Mesh

        mesh = Mesh(np.array([0]), ("tasks",))

        def combine(x, axis):
            return jax.lax.psum(x, axis)
        """,
    )
    assert findings == []


def test_trace_unknown_axis_suppressible(tmp_path):
    findings = check(
        tmp_path,
        """\
        import jax
        import numpy as np
        from jax.sharding import Mesh

        mesh = Mesh(np.array([0]), ("tasks",))

        def combine(x):
            return jax.lax.psum(x, "task")  # faas: allow(trace.unknown-axis-name)
        """,
    )
    assert findings == []


# -- CLI: --only -------------------------------------------------------------


def test_cli_only_runs_exactly_the_selected_checker(tmp_path, capsys):
    """--only kernelparity runs that checker and nothing else: a snippet
    carrying both a protocol and a kernelparity violation reports only
    the kernelparity rule."""
    p = tmp_path / "snippet.py"
    p.write_text(
        textwrap.dedent(
            """\
            from typing import NamedTuple

            class FooState(NamedTuple):
                a: object  # f32[T]
                b: object  # i32[T]
                c: object  # f32[T]
                d: object  # bool[T]

            def kernel(st, run):
                return run(st.a, st.b, st.d, st.c)

            def finishes(store, tid):
                store.set_status(tid, "COMPLETED")
            """
        )
    )
    rc = analysis_main(["--only", "kernelparity", "--json", str(p)])
    assert rc == 1
    rules = {f["rule"] for f in json.loads(capsys.readouterr().out)}
    assert rules == {"kernelparity.state-leaf-drift"}
    rc = analysis_main(["--only", "protocol", "--json", str(p)])
    assert rc == 1
    rules = {f["rule"] for f in json.loads(capsys.readouterr().out)}
    assert rules == {"protocol.terminal-set-status"}
    rc = analysis_main(
        ["--only", "protocol,kernelparity", "--json", str(p)]
    )
    assert rc == 1
    rules = {f["rule"] for f in json.loads(capsys.readouterr().out)}
    assert rules == {
        "kernelparity.state-leaf-drift",
        "protocol.terminal-set-status",
    }


def test_cli_only_rejects_unknown_checker(tmp_path, capsys):
    p = tmp_path / "snippet.py"
    p.write_text("x = 1\n")
    rc = analysis_main(["--only", "nosuch", str(p)])
    assert rc == 2
    err = capsys.readouterr().err
    assert "nosuch" in err and "kernelparity" in err


def test_cli_only_does_not_stale_foreign_suppressions(tmp_path, capsys):
    """A narrowed run cannot judge staleness for checkers it skipped:
    suppressions owned by unselected rules stay silent even under
    --strict."""
    p = tmp_path / "snippet.py"
    p.write_text(
        textwrap.dedent(
            """\
            def finishes(store, tid):
                store.set_status(tid, "COMPLETED")  # faas: allow(protocol.terminal-set-status)
            """
        )
    )
    assert analysis_main(["--strict", str(p)]) == 0
    capsys.readouterr()
    assert analysis_main(["--only", "kernelparity", "--strict", str(p)]) == 0


# -- SARIF: new rule ids -----------------------------------------------------


def test_sarif_carries_new_device_plane_rule_ids(tmp_path, capsys):
    """One module firing all three new checkers lands all three rule ids
    in the SARIF rule metadata and results."""
    p = tmp_path / "snippet.py"
    p.write_text(
        textwrap.dedent(
            """\
            import jax.numpy as jnp
            from typing import NamedTuple

            CAP_TRACE = "trace"
            FIELD_TRACE_ID = "t0"

            class PodState(NamedTuple):
                a: object  # f32[T]
                b: object  # i32[T]
                c: object  # f32[T]
                d: object  # bool[T]

            def kernel(st, run):
                return run(st.a, st.b, st.d, st.c)

            def upload(host):
                dev = jnp.asarray(host)
                host[0] = 1.0
                return dev

            def submit(extra, ctx, tid):
                if ctx.trace:
                    extra[FIELD_TRACE_ID] = tid

            def observe(extra, tid):
                extra[FIELD_TRACE_ID] = tid
            """
        )
    )
    out = tmp_path / "out.sarif"
    rc = analysis_main(["--sarif", str(out), str(p)])
    assert rc == 1
    capsys.readouterr()
    doc = json.loads(out.read_text())
    run = doc["runs"][0]
    rules = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {
        "kernelparity.state-leaf-drift",
        "devicesnapshot.unsnapshotted-upload",
        "planegate.ungated-field-write",
    } <= rules
    assert {r["ruleId"] for r in run["results"]} == rules
