"""Property tests for the rank-matching placement kernel + fused tick."""

import numpy as np
import pytest

from tpu_faas.sched.greedy import (
    host_greedy_reference,
    makespan,
    rank_match_placement,
)
from tpu_faas.sched.oracle import makespan_lower_bound
from tpu_faas.sched.problem import PlacementProblem, check_assignment
from tpu_faas.sched.state import SchedulerArrays


def _random_problem(rng, n_tasks, n_workers, max_free=8, hetero=True):
    sizes = rng.uniform(0.1, 10.0, n_tasks).astype(np.float32)
    speeds = (
        rng.uniform(0.5, 4.0, n_workers).astype(np.float32)
        if hetero
        else np.ones(n_workers, dtype=np.float32)
    )
    free = rng.integers(0, max_free + 1, n_workers).astype(np.int32)
    live = rng.random(n_workers) > 0.2
    return sizes, speeds, free, live


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
@pytest.mark.parametrize("n_tasks,n_workers", [(50, 10), (500, 64), (40, 100)])
def test_rank_match_invariants(seed, n_tasks, n_workers):
    rng = np.random.default_rng(seed)
    sizes, speeds, free, live = _random_problem(rng, n_tasks, n_workers)
    p = PlacementProblem.build(sizes, speeds, free, live)
    a = np.asarray(
        rank_match_placement(
            p.task_size, p.task_valid, p.worker_speed, p.worker_free,
            p.worker_live, max_slots=8,
        )
    )
    check_assignment(a, np.asarray(p.task_valid), np.asarray(p.worker_free),
                     np.asarray(p.worker_live))
    # places min(valid tasks, total live free slots) tasks
    cap = int(np.minimum(free, 8)[live].sum())
    expected = min(n_tasks, cap)
    assert (a >= 0).sum() == expected


def test_rank_match_fills_all_when_capacity_sufficient():
    p = PlacementProblem.build(
        [1.0] * 10, [1.0] * 5, [4] * 5, [True] * 5
    )
    a = np.asarray(
        rank_match_placement(
            p.task_size, p.task_valid, p.worker_speed, p.worker_free,
            p.worker_live,
        )
    )
    valid = np.asarray(p.task_valid)
    assert (a[valid] >= 0).all()
    assert (a[~valid] == -1).all()


def test_rank_match_prefers_fast_workers_for_big_tasks():
    # 2 workers: speed 4 and 1, one slot each; big task must go to fast one
    p = PlacementProblem.build([100.0, 1.0], [4.0, 1.0], [1, 1])
    a = np.asarray(
        rank_match_placement(
            p.task_size, p.task_valid, p.worker_speed, p.worker_free,
            p.worker_live,
        )
    )
    assert a[0] == 0 and a[1] == 1


def test_no_live_workers_places_nothing():
    p = PlacementProblem.build([1.0] * 4, [1.0] * 3, [2] * 3, [False] * 3)
    a = np.asarray(
        rank_match_placement(
            p.task_size, p.task_valid, p.worker_speed, p.worker_free,
            p.worker_live,
        )
    )
    assert (a == -1).all()


@pytest.mark.parametrize("seed", [10, 11, 12])
def test_makespan_within_bound_vs_lp_oracle(seed):
    """One-wave makespan of the kernel is near the LP lower bound and not
    worse than the reference-style greedy baseline."""
    rng = np.random.default_rng(seed)
    sizes, speeds, free, live = _random_problem(rng, 400, 64, hetero=True)
    # sufficient capacity for one wave
    free = np.full(64, 8, dtype=np.int32)
    live = np.ones(64, dtype=bool)
    p = PlacementProblem.build(sizes, speeds, free, live)
    a = np.asarray(
        rank_match_placement(
            p.task_size, p.task_valid, p.worker_speed, p.worker_free,
            p.worker_live,
        )
    )[: len(sizes)]
    ms_kernel = makespan(a, sizes, speeds)
    ms_greedy = makespan(
        host_greedy_reference(sizes, speeds, free, live), sizes, speeds
    )
    lb = makespan_lower_bound(sizes, speeds, free, live)
    assert ms_kernel <= ms_greedy * 1.01  # never meaningfully worse
    # LPT-style pairing is near the bound at this density; generous factor
    assert ms_kernel <= lb * 1.5


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def test_scheduler_tick_liveness_purge_redistribution():
    clock = FakeClock(0.0)
    s = SchedulerArrays(
        max_workers=8, max_pending=16, max_inflight=32, time_to_expire=10.0,
        clock=clock,
    )
    r0 = s.register(b"w0", num_processes=2)
    r1 = s.register(b"w1", num_processes=2)
    out = s.tick(np.array([1.0, 2.0, 3.0], dtype=np.float32))
    a = np.asarray(out.assignment)[:3]
    assert (a >= 0).sum() == 3  # 4 slots, 3 tasks
    assert bool(np.asarray(out.live)[r0]) and bool(np.asarray(out.live)[r1])
    assert not np.asarray(out.purged).any()

    # simulate dispatch of task "t0" to w0 and time passing beyond expiry
    # with only w1 heartbeating
    s.worker_free[r0] -= 1
    slot = s.inflight_add("t0", r0)
    clock.t = 11.0
    s.heartbeat(b"w1")
    out = s.tick(np.zeros(0, dtype=np.float32))
    live = np.asarray(out.live)
    purged = np.asarray(out.purged)
    redis = np.asarray(out.redispatch)
    assert not live[r0] and live[r1]
    assert purged[r0] and not purged[r1]
    assert redis[slot]  # t0 must be re-dispatched
    # purge bookkeeping, worker reconnects with current capacity at front
    s.deactivate(r0)
    assert s.inflight_clear_slot(slot) == "t0"
    r0b = s.reconnect(b"w0", free_processes=2)
    assert r0b == r0  # same row recycled for same identity
    out = s.tick(np.array([5.0], dtype=np.float32))
    assert np.asarray(out.live)[r0]
    assert np.asarray(out.assignment)[0] >= 0


def test_scheduler_tick_assigned_counts_host_side():
    """Per-worker counts come from the host-side bincount helper (the
    device tick deliberately doesn't scatter-add them — TickOutput note)."""
    s = SchedulerArrays(max_workers=4, max_pending=8, clock=FakeClock(0.0))
    s.register(b"a", 3)
    s.register(b"b", 1)
    out = s.tick(np.array([1.0, 1.0, 1.0, 1.0], dtype=np.float32))
    a = np.asarray(out.assignment)
    counts = SchedulerArrays.assigned_counts(a, 4)
    for w in range(4):
        assert counts[w] == (a == w).sum()
    assert counts.sum() == 4


def test_inflight_table_roundtrip():
    s = SchedulerArrays(max_workers=2, max_inflight=4, clock=FakeClock(0.0))
    r = s.register(b"w", 4)
    slots = [s.inflight_add(f"t{i}", r) for i in range(4)]
    assert len(set(slots)) == 4
    with pytest.raises(RuntimeError):
        s.inflight_add("overflow", r)
    assert s.inflight_done("t2") == r
    s.inflight_add("t4", r)  # reuses freed slot
    assert s.inflight_done("missing") is None


def test_rank_match_fcfs_admission_no_starvation():
    """Under overload, admission is by arrival order: a small early task is
    admitted even when later larger tasks could fill all slots."""
    # 2 slots; task 0 small and earliest, tasks 1-3 large
    p = PlacementProblem.build([0.1, 9.0, 9.0, 9.0], [1.0], [2], [True])
    a = np.asarray(
        rank_match_placement(
            p.task_size, p.task_valid, p.worker_speed, p.worker_free,
            p.worker_live, max_slots=2,
        )
    )
    assert a[0] >= 0 and a[1] >= 0  # two earliest admitted
    assert a[2] == -1 and a[3] == -1


def test_zombie_identity_does_not_alias_recycled_row():
    """A purged worker's identity must not keep pointing at its old row after
    the row is recycled by a new worker."""
    clock = FakeClock(0.0)
    s = SchedulerArrays(max_workers=1, max_pending=4, clock=clock)
    r0 = s.register(b"old", num_processes=4)
    s.deactivate(r0)
    r_new = s.register(b"new", num_processes=2)
    assert r_new == r0  # row recycled
    # zombie heartbeat must be a no-op, not refresh the recycled row
    hb_before = s.last_heartbeat[r_new]
    clock.t = 5.0
    s.heartbeat(b"old")
    assert s.last_heartbeat[r_new] == hb_before
    # zombie re-register with a full table raises rather than stealing the row
    with pytest.raises(RuntimeError):
        s.register(b"old", num_processes=4)


@pytest.mark.parametrize("placement", ["auction", "sinkhorn"])
def test_scheduler_arrays_placement_kernels_live(placement):
    """The fused tick can serve the auction/Sinkhorn kernels in place of
    rank-match (dispatcher --placement knob): same fleet bookkeeping, full
    capacity placed, only live rows used."""
    arrays = SchedulerArrays(
        max_workers=8,
        max_pending=32,
        max_inflight=16,
        max_slots=2,
        placement=placement,
    )
    rows = [arrays.register(f"w{i}".encode(), 2) for i in range(4)]
    out = arrays.tick(np.ones(10, dtype=np.float32))
    a = np.asarray(out.assignment)[:10]
    assert (a >= 0).sum() == 8  # 4 workers x 2 free slots
    assert set(a[a >= 0]) <= set(rows)


def test_scheduler_arrays_rejects_unknown_placement_at_construction():
    # fail fast: a dispatcher must not bind its port and adopt tasks only
    # to die on the first device tick of a typo'd kernel name
    with pytest.raises(ValueError, match="unknown placement"):
        SchedulerArrays(max_workers=4, max_pending=8, placement="magic")


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_host_greedy_vectorized_matches_heap(seed):
    """The numpy grant-order greedy is bit-identical to the heap walk it
    vectorizes (the bench's pinned vs_baseline denominator)."""
    from tpu_faas.sched.greedy import host_greedy_reference, host_greedy_vectorized

    rng = np.random.default_rng(seed)
    n_tasks = int(rng.integers(0, 500))
    n_workers = int(rng.integers(1, 60))
    sizes = rng.uniform(0.1, 5.0, n_tasks).astype(np.float32)
    speeds = rng.uniform(0.5, 4.0, n_workers).astype(np.float32)
    free = rng.integers(0, 5, n_workers).astype(np.int32)
    live = rng.random(n_workers) > 0.2
    np.testing.assert_array_equal(
        host_greedy_vectorized(sizes, speeds, free, live),
        host_greedy_reference(sizes, speeds, free, live),
    )
