"""Poison-task guard: a task that repeatedly takes its worker down is FAILED
after ``max_task_retries`` reclaims instead of cycling through the fleet
forever. (The reference *loses* such tasks outright — SURVEY §5.3; our
re-dispatch upgrade needs this bound to stay safe against crash-looping
payloads, e.g. a function that segfaults its pool process.)"""

from __future__ import annotations

from tpu_faas.core.serialize import deserialize
from tpu_faas.dispatch.push import PushDispatcher
from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
from tpu_faas.store import MemoryStore
from tpu_faas.worker import messages as m


def _drain_failed(store, task_id):
    status, result = store.get_result(task_id)
    assert status == "FAILED"
    err = deserialize(result)
    assert isinstance(err, RuntimeError)
    assert "lost with its worker" in str(err)


def test_push_hb_poison_task_fails_after_max_retries():
    store = MemoryStore()
    disp = PushDispatcher(
        ip="127.0.0.1",
        port=0,
        store=store,
        heartbeat=True,
        time_to_expire=5.0,
        max_task_retries=2,
    )
    try:
        store.create_task("t1", "F", "P", "tasks")
        for round_no in range(3):  # dispatch at retries 0, 1, 2; then FAILED
            wid = f"w{round_no}".encode()
            disp._handle(wid, m.REGISTER, {"num_processes": 1})
            assert disp._dispatch_round() == 1
            assert store.get_status("t1") == "RUNNING"
            # the worker dies silently: age its heartbeat past expiry
            disp.workers[wid].last_heartbeat -= 100.0
            disp.purge_workers()
        assert not disp.requeue  # nothing cycles after the guard trips
        _drain_failed(store, "t1")
    finally:
        disp.socket.close(linger=0)


def test_push_hb_result_clears_retry_count():
    """A reclaim followed by a successful run must not leave stale retry
    state that could fail a later unrelated reclaim early."""
    store = MemoryStore()
    disp = PushDispatcher(
        ip="127.0.0.1",
        port=0,
        store=store,
        heartbeat=True,
        time_to_expire=5.0,
        max_task_retries=1,
    )
    try:
        store.create_task("t1", "F", "P", "tasks")
        disp._handle(b"w0", m.REGISTER, {"num_processes": 1})
        assert disp._dispatch_round() == 1
        disp.workers[b"w0"].last_heartbeat -= 100.0
        disp.purge_workers()  # reclaim #1 (== max_task_retries: still OK)
        disp._handle(b"w1", m.REGISTER, {"num_processes": 1})
        assert disp._dispatch_round() == 1
        # this time the worker finishes it
        disp._handle(
            b"w1", m.RESULT, {"task_id": "t1", "status": "COMPLETED", "result": "R"}
        )
        assert store.get_status("t1") == "COMPLETED"
        assert not disp.workers[b"w1"].inflight_retries
    finally:
        disp.socket.close(linger=0)


def test_push_hb_zombie_result_freezes_record():
    """A heartbeat-silent worker whose task was reclaimed may still finish
    it. Its late result must stick (first terminal write wins) and the
    requeued copy must be dropped instead of regressing the record to
    RUNNING and re-running the task."""
    store = MemoryStore()
    disp = PushDispatcher(
        ip="127.0.0.1",
        port=0,
        store=store,
        heartbeat=True,
        time_to_expire=5.0,
    )
    try:
        store.create_task("t1", "F", "P", "tasks")
        disp._handle(b"w0", m.REGISTER, {"num_processes": 1})
        assert disp._dispatch_round() == 1
        disp.workers[b"w0"].last_heartbeat -= 100.0
        disp.purge_workers()  # t1 reclaimed into the requeue
        assert len(disp.requeue) == 1
        # the zombie was only slow — its result arrives after the purge
        # (unknown sender path: the record was deleted with the purge)
        disp._handle(
            b"w0", m.RESULT, {"task_id": "t1", "status": "COMPLETED", "result": "R"}
        )
        assert store.get_result("t1") == ("COMPLETED", "R")
        # a fresh worker must NOT receive the requeued copy
        disp._handle(b"w1", m.REGISTER, {"num_processes": 1})
        assert disp._dispatch_round() == 0
        assert store.get_result("t1") == ("COMPLETED", "R")
        assert not disp.requeue
    finally:
        disp.socket.close(linger=0)


def test_tpu_push_zombie_result_freezes_record():
    store = MemoryStore()
    disp = TpuPushDispatcher(
        ip="127.0.0.1",
        port=0,
        store=store,
        max_workers=4,
        max_pending=8,
        max_inflight=16,
        recover_queued=False,
        time_to_expire=5.0,
    )
    try:
        store.create_task("t1", "F", "P", "tasks")
        disp._handle(b"w0", m.REGISTER, {"num_processes": 1})
        assert disp.tick() == 1
        row = disp.arrays.worker_ids[b"w0"]
        disp.arrays.last_heartbeat[row] -= 100.0
        disp.tick()  # purge + reclaim into pending
        assert len(disp.pending) == 1
        disp._handle(
            b"w0", m.RESULT, {"task_id": "t1", "status": "COMPLETED", "result": "R"}
        )
        assert store.get_result("t1") == ("COMPLETED", "R")
        disp._handle(b"w1", m.REGISTER, {"num_processes": 1})
        assert disp.tick() == 0  # requeued copy dropped at dispatch
        assert store.get_result("t1") == ("COMPLETED", "R")
        assert not disp.task_retries
    finally:
        disp.socket.close(linger=0)


def test_tpu_push_poison_task_fails_after_max_retries():
    store = MemoryStore()
    disp = TpuPushDispatcher(
        ip="127.0.0.1",
        port=0,
        store=store,
        max_workers=4,
        max_pending=8,
        max_inflight=16,
        recover_queued=False,
        time_to_expire=5.0,
        max_task_retries=2,
    )
    try:
        store.create_task("t1", "F", "P", "tasks")
        for round_no in range(3):
            wid = f"w{round_no}".encode()
            disp._handle(wid, m.REGISTER, {"num_processes": 1})
            assert disp.tick() == 1
            assert store.get_status("t1") == "RUNNING"
            row = disp.arrays.worker_ids[wid]
            disp.arrays.last_heartbeat[row] -= 100.0
            disp.tick()  # purge + reclaim (or FAILED on the last round)
        assert not disp.pending
        assert not disp.task_retries
        _drain_failed(store, "t1")
    finally:
        disp.socket.close(linger=0)


def test_tpu_push_zombie_result_does_not_leak_new_owner_capacity():
    """A zombie's late result for a task that was already re-dispatched must
    not release the NEW owner's in-flight slot: only the owner's own result
    frees its process, otherwise the fleet's capacity drains under churn."""
    store = MemoryStore()
    disp = TpuPushDispatcher(
        ip="127.0.0.1",
        port=0,
        store=store,
        max_workers=4,
        max_pending=8,
        max_inflight=16,
        recover_queued=False,
        time_to_expire=5.0,
    )
    try:
        store.create_task("t1", "F", "P", "tasks")
        disp._handle(b"w0", m.REGISTER, {"num_processes": 1})
        assert disp.tick() == 1  # t1 -> w0
        a = disp.arrays
        a.last_heartbeat[a.worker_ids[b"w0"]] -= 100.0
        disp._handle(b"w1", m.REGISTER, {"num_processes": 1})
        disp.tick()  # purge w0, reclaim t1 into pending
        assert disp.tick() == 1  # re-dispatch t1 -> w1
        row1 = a.worker_ids[b"w1"]
        assert a.inflight_owner("t1") == row1
        assert a.worker_free[row1] == 0

        # zombie w0 finishes t1 late: record freezes, but w1 still holds it
        disp._handle(
            b"w0", m.RESULT, {"task_id": "t1", "status": "COMPLETED", "result": "R"}
        )
        assert store.get_result("t1") == ("COMPLETED", "R")
        assert a.inflight_owner("t1") == row1, "zombie must not pop w1's slot"
        assert a.worker_free[row1] == 0, "zombie must not free w1's process"

        # the owner's own result releases the slot exactly once
        disp._handle(
            b"w1", m.RESULT, {"task_id": "t1", "status": "COMPLETED", "result": "R2"}
        )
        assert a.inflight_owner("t1") is None
        assert a.worker_free[row1] == 1
        assert store.get_result("t1") == ("COMPLETED", "R"), "first write won"

        # capacity intact: w1 can take the next task
        store.create_task("t2", "F", "P", "tasks")
        assert disp.tick() == 1
    finally:
        disp.socket.close(linger=0)
