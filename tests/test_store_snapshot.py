"""Checkpoint/resume for the task store.

The reference has no durability at all (SURVEY §5.4: restarted store loses
every task hash). These tests cover the snapshot format (a replayable RESP
HSET log), the in-proc MemoryStore, the Python asyncio server, and the
native C++ server — all of which read and write the identical file.
"""

from __future__ import annotations

import pytest

from tpu_faas.store import resp, snapshot
from tpu_faas.store.client import RespStore
from tpu_faas.store.launch import start_store_thread
from tpu_faas.store.memory import MemoryStore

WEIRD = {
    "task-1": {"status": "QUEUED", "payload": "with\r\ncrlf", "empty": ""},
    "täsk-2": {"ünïcode": "välue", "b64": "aGVsbG8=" * 100},
    "k": {"f": "v"},
}


def test_dump_load_roundtrip():
    assert snapshot.load_hashes(snapshot.dump_hashes(WEIRD)) == WEIRD


def test_dump_load_empty():
    assert snapshot.load_hashes(b"") == {}
    assert snapshot.dump_hashes({}) == b""


def test_load_rejects_garbage():
    with pytest.raises(resp.ProtocolError):
        snapshot.load_hashes(b"not a snapshot")
    # a command outside the log grammar (HSET/DEL/HDEL) must be rejected,
    # not silently skipped
    with pytest.raises(resp.ProtocolError):
        snapshot.load_hashes(resp.encode_command("SET", "k", "v"))
    # malformed arity of a known command is rejected too
    with pytest.raises(resp.ProtocolError):
        snapshot.load_hashes(resp.encode_command("HSET", "k", "f"))


def test_load_missing_file_is_empty(tmp_path):
    assert snapshot.load_file(str(tmp_path / "nope.snap")) == {}


# -- deletion records (HA / log-merge completeness) --------------------------


def test_dump_with_deleted_keys_roundtrip():
    """DEL records make deletions EXPRESSIBLE in the log format: a dump
    carrying tombstones loads to a state where those keys are absent, and
    keys both dumped and tombstoned (a caller bug) stay dumped — the DEL
    record is filtered, not applied over live state."""
    data = snapshot.dump_hashes(WEIRD, deleted=["gone-blob", "gone-index"])
    assert b"DEL" in data
    assert snapshot.load_hashes(data) == WEIRD
    # a tombstone colliding with a live key is dropped at dump time
    data2 = snapshot.dump_hashes(WEIRD, deleted=["k", "really-gone"])
    loaded = snapshot.load_hashes(data2)
    assert loaded["k"] == {"f": "v"}
    assert "really-gone" not in loaded


def test_load_applies_del_and_hdel_in_order():
    """The log replays strictly in order, so a dump + appended mutations
    (the replication stream's shape) cannot resurrect deleted keys."""
    log = (
        resp.encode_command("HSET", "t1", "status", "COMPLETED")
        + resp.encode_command("HSET", "blob:abc", "data", "x" * 64)
        + resp.encode_command("DEL", "blob:abc")  # GC'd after the dump
        + resp.encode_command("HSET", "tasks:index", "t1", "1", "t2", "1")
        + resp.encode_command("HDEL", "tasks:index", "t1")
        + resp.encode_command("HSET", "t2", "status", "QUEUED")
        + resp.encode_command("HDEL", "t2", "status")  # emptied -> absent
    )
    loaded = snapshot.load_hashes(log)
    assert "blob:abc" not in loaded  # the GC'd blob stays gone
    assert loaded["tasks:index"] == {"t2": "1"}  # live-index entry dropped
    assert "t2" not in loaded  # empty hash = absent key (Redis semantics)
    assert loaded["t1"] == {"status": "COMPLETED"}
    # inverse order DOES resurrect — proving order-sensitivity is real
    relog = resp.encode_command("DEL", "k") + resp.encode_command(
        "HSET", "k", "f", "v"
    )
    assert snapshot.load_hashes(relog) == {"k": {"f": "v"}}


def test_server_snapshot_records_deletions(tmp_path):
    """A checkpoint taken AFTER a deletion carries the tombstone: merging
    it over an older log (cat old new | replay) cannot revive the key —
    the resurrection the pure-HSET format allowed."""
    path = str(tmp_path / "tomb.snap")
    h = start_store_thread(snapshot_path=path)
    try:
        c = RespStore(port=h.port)
        c.hset("keep", {"a": "1"})
        c.hset("gc-me", {"data": "blob-bytes"})
        c.hset("empty-me", {"f": "v"})
        c.save()
        c.delete("gc-me")
        c.hdel("empty-me", "f")  # HDEL to empty = key deleted
        c.save()
        raw = open(path, "rb").read()
        assert b"DEL" in raw
        loaded = snapshot.load_hashes(raw)
        assert "gc-me" not in loaded and "empty-me" not in loaded
        # the merge scenario: an older full dump followed by the new
        # snapshot replays WITHOUT resurrecting the deleted keys
        old = snapshot.dump_hashes(
            {"gc-me": {"data": "blob-bytes"}, "keep": {"a": "0"}}
        )
        merged = snapshot.load_hashes(old + raw)
        assert "gc-me" not in merged
        assert merged["keep"] == {"a": "1"}
        c.close()
    finally:
        h.stop()


def test_memory_store_save_load(tmp_path):
    path = str(tmp_path / "mem.snap")
    a = MemoryStore()
    for key, fields in WEIRD.items():
        a.hset(key, fields)
    a.save(path)

    b = MemoryStore()
    b.hset("stale", {"x": "y"})  # load() replaces, not merges
    b.load(path)
    assert sorted(b.keys()) == sorted(WEIRD)
    for key, fields in WEIRD.items():
        assert b.hgetall(key) == fields


def test_python_server_restart_resumes(tmp_path):
    path = str(tmp_path / "py.snap")

    h1 = start_store_thread(snapshot_path=path)
    try:
        c1 = RespStore(port=h1.port)
        c1.hset("task-a", {"status": "COMPLETED", "result": "42"})
        c1.hset("task-b", {"status": "QUEUED"})
        c1.close()
    finally:
        h1.stop()  # stop() checkpoints

    h2 = start_store_thread(snapshot_path=path)
    try:
        c2 = RespStore(port=h2.port)
        assert c2.hgetall("task-a") == {"status": "COMPLETED", "result": "42"}
        assert c2.hget("task-b", "status") == "QUEUED"
        c2.close()
    finally:
        h2.stop()


def test_python_server_explicit_save_command(tmp_path):
    path = str(tmp_path / "explicit.snap")
    h = start_store_thread()  # no --snapshot configured
    try:
        c = RespStore(port=h.port)
        # SAVE without a path must error when no snapshot path is configured
        with pytest.raises(resp.RespError):
            c.save()
        c.hset("k", {"f": "v"})
        c.save(path)
        c.close()
    finally:
        h.stop()
    assert snapshot.load_file(path) == {"k": {"f": "v"}}


def test_native_server_restart_resumes(tmp_path):
    native = pytest.importorskip("tpu_faas.store.native")
    try:
        native.build_native_store()
    except native.NativeStoreUnavailable as exc:
        pytest.skip(f"native store unavailable: {exc}")

    path = str(tmp_path / "native.snap")
    h1 = native.start_native_store(snapshot_path=path)
    try:
        c1 = RespStore(port=h1.port)
        for key, fields in WEIRD.items():
            c1.hset(key, fields)
        c1.save()  # explicit checkpoint to the configured path
        c1.close()
    finally:
        h1.stop()

    h2 = native.start_native_store(snapshot_path=path)
    try:
        c2 = RespStore(port=h2.port)
        for key, fields in WEIRD.items():
            assert c2.hgetall(key) == fields
        c2.close()
    finally:
        h2.stop()


def test_cross_server_snapshot_compat(tmp_path):
    """A snapshot written by the Python server loads in the native server."""
    native = pytest.importorskip("tpu_faas.store.native")
    try:
        native.build_native_store()
    except native.NativeStoreUnavailable as exc:
        pytest.skip(f"native store unavailable: {exc}")

    path = str(tmp_path / "cross.snap")
    h1 = start_store_thread(snapshot_path=path)
    try:
        c1 = RespStore(port=h1.port)
        c1.hset("task-x", {"status": "RUNNING", "blob": "x" * 10_000})
        c1.close()
    finally:
        h1.stop()

    h2 = native.start_native_store(snapshot_path=path)
    try:
        c2 = RespStore(port=h2.port)
        assert c2.hgetall("task-x") == {"status": "RUNNING", "blob": "x" * 10_000}
        c2.close()
    finally:
        h2.stop()


def test_client_reconnects_after_server_restart(tmp_path):
    """A store restart must not wedge long-lived clients: commands reconnect
    transparently, subscriptions resubscribe (missed messages are lost by
    design), and snapshot state is visible through the same client object."""
    path = str(tmp_path / "reconnect.snap")
    h1 = start_store_thread(port=0, snapshot_path=path)
    port = h1.port
    c = RespStore(port=port)
    sub = c.subscribe("tasks")
    c.hset("persist", {"status": "COMPLETED"})
    h1.stop()  # checkpoint + close every connection

    h2 = start_store_thread(port=port, snapshot_path=path)
    try:
        # command connection heals and sees the snapshot
        assert c.hget("persist", "status") == "COMPLETED"
        # subscription heals: first call absorbs the dead socket, then a
        # fresh publish is delivered on the re-established subscription
        sub.get_message()
        deadline = __import__("time").monotonic() + 5
        got = None
        while got is None and __import__("time").monotonic() < deadline:
            c.publish("tasks", "hello-again")
            got = sub.get_message(timeout=0.2)
        assert got == "hello-again"
        sub.close()
        c.close()
    finally:
        h2.stop()


def test_python_server_shutdown_exits_despite_attached_subscriber(tmp_path):
    """SHUTDOWN must checkpoint and terminate the process even while another
    client holds an open subscription: since Python 3.12,
    ``Server.wait_closed()`` waits for every live connection handler, so the
    server has to drop idle clients itself or hang forever."""
    import re
    import subprocess
    import sys

    path = str(tmp_path / "sd.snap")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "tpu_faas.store.server",
            "--port", "0", "--snapshot", path,
        ],
        stdout=subprocess.PIPE,
        text=True,
    )
    try:
        line = proc.stdout.readline()
        port = int(re.search(r":(\d+)\s*$", line).group(1))
        sub_holder = RespStore(port=port)
        sub = sub_holder.subscribe("tasks")  # idle connection held open
        writer = RespStore(port=port)
        writer.hset("k", {"f": "v"})
        try:
            writer._command("SHUTDOWN")
        except ConnectionError:
            pass  # server may die before writing any reply
        assert proc.wait(timeout=15) == 0
        assert snapshot.load_file(path) == {"k": {"f": "v"}}
        sub.close()
        sub_holder.close()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
