"""RESP protocol + server + client tests (same contract as MemoryStore)."""

import threading

import pytest

from tpu_faas.store import resp
from tpu_faas.store.client import RespStore
from tpu_faas.store.base import LIVE_INDEX_KEY
from tpu_faas.store.launch import make_store, start_store_thread


# -- pure protocol tests ----------------------------------------------------


def test_encode_command():
    assert resp.encode_command("HGET", "k", "f") == (
        b"*3\r\n$4\r\nHGET\r\n$1\r\nk\r\n$1\r\nf\r\n"
    )


def test_parser_incremental_feed():
    p = resp.RespParser()
    payload = b"*2\r\n$2\r\nhi\r\n:42\r\n+OK\r\n"
    for i in range(len(payload)):
        p2 = resp.RespParser()
        p2.feed(payload[:i])
        # never raises on partial input; just returns NEED_MORE at some point
        p2.pop_all()
    p.feed(payload)
    assert p.pop_all() == [["hi", 42], "OK"]


def test_parser_nil_and_error():
    p = resp.RespParser()
    p.feed(b"$-1\r\n-ERR nope\r\n")
    items = p.pop_all()
    assert items[0] is None
    assert isinstance(items[1], resp.RespError)


def test_parser_bulk_with_crlf_in_body():
    body = "a\r\nb"
    p = resp.RespParser()
    p.feed(b"$4\r\n" + body.encode() + b"\r\n")
    assert p.pop() == body


# -- server/client integration ---------------------------------------------


@pytest.fixture(params=["python", "native"])
def store_server(request):
    """Run the full contract suite against BOTH store servers: the asyncio
    fallback and the native C++ one (same RESP subset)."""
    if request.param == "python":
        handle = start_store_thread()
    else:
        from tpu_faas.store.native import (
            NativeStoreUnavailable,
            start_native_store,
        )

        try:
            handle = start_native_store()
        except NativeStoreUnavailable as exc:
            pytest.skip(f"native store unavailable: {exc}")
    yield handle
    handle.stop()


def test_resp_store_contract(store_server):
    s = make_store(store_server.url)
    assert s.ping()
    s.hset("k", {"a": "1", "b": "2"})
    assert s.hget("k", "a") == "1"
    assert s.hget("k", "zzz") is None
    assert s.hgetall("k") == {"a": "1", "b": "2"}
    # HMGET: one round trip, None per missing field, missing key -> all None
    assert s.hmget("k", ["b", "nope", "a"]) == ["2", None, "1"]
    assert s.hmget("ghost", ["a", "b"]) == [None, None]
    # HEXISTS: presence without transferring the value (cancel_task probes)
    assert s.hexists("k", "a") is True
    assert s.hexists("k", "zzz") is False
    assert s.hexists("ghost", "a") is False
    # finish_task announces the terminal write on the results channel
    from tpu_faas.store.base import RESULTS_CHANNEL

    with s.subscribe(RESULTS_CHANNEL) as rsub:
        s.create_task("rt1", "F", "P")
        s.finish_task("rt1", "COMPLETED", "R")
        assert rsub.get_message(timeout=2.0) == "rt1"
        assert s.get_result("rt1") == ("COMPLETED", "R")
        # frozen first_wins write: no second announce
        s.finish_task("rt1", "FAILED", "X", first_wins=True)
        assert rsub.get_message(timeout=0.3) is None
        assert s.get_result("rt1") == ("COMPLETED", "R")
        s.delete("rt1")
    assert s.keys() == ["k"]
    s.delete("k")
    assert s.hgetall("k") == {}
    s.flush()
    s.close()


def test_resp_pubsub_and_task_lifecycle(store_server):
    s = make_store(store_server.url)
    sub = s.subscribe("tasks")
    s.create_task("t1", "FN", "PARAMS")
    assert sub.get_message(timeout=2.0) == "t1"
    assert sub.get_message() is None
    assert s.get_payloads("t1") == ("FN", "PARAMS")
    s.set_status("t1", "RUNNING")
    s.finish_task("t1", "COMPLETED", "RES")
    assert s.get_result("t1") == ("COMPLETED", "RES")
    sub.close()
    s.close()


def test_resp_pubsub_fanout_and_fire_and_forget(store_server):
    s = make_store(store_server.url)
    s.publish("tasks", "lost")  # no subscribers yet
    a = s.subscribe("tasks")
    b = s.subscribe("tasks")
    s.publish("tasks", "m1")
    assert a.get_message(timeout=2.0) == "m1"
    assert b.get_message(timeout=2.0) == "m1"
    a.close()
    s.publish("tasks", "m2")
    assert b.get_message(timeout=2.0) == "m2"
    b.close()
    s.close()


def test_resp_store_multithreaded_clients(store_server):
    s = make_store(store_server.url)
    sub = s.subscribe("tasks")

    def writer(i):
        c = make_store(store_server.url)
        for j in range(50):
            c.create_task(f"t-{i}-{j}", "F", "P")
        c.close()

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    seen = set()
    while True:
        m = sub.get_message(timeout=0.5)
        if m is None:
            break
        seen.add(m)
    assert len(seen) == 200
    # +1: the live-task index hash rides alongside the task records
    assert len([k for k in s.keys() if k != LIVE_INDEX_KEY]) == 200
    sub.close()
    s.close()


def test_large_payload_roundtrip(store_server):
    s = make_store(store_server.url)
    big = "x" * 1_000_000
    s.hset("big", {"v": big})
    assert s.hget("big", "v") == big
    s.close()


def test_make_store_memory_shared():
    a = make_store("memory://")
    b = make_store("memory://")
    a.hset("k", {"f": "v"})
    assert b.hget("k", "f") == "v"
    c = make_store("memory://fresh")
    assert c.hget("k", "f") is None
    a.flush()


def _start_info_server(kind: str, snapshot_path: str):
    if kind == "python":
        from tpu_faas.store.launch import start_store_thread

        return start_store_thread(snapshot_path=snapshot_path)
    from tpu_faas.store.native import start_native_store

    return start_native_store(snapshot_path=snapshot_path)


@pytest.mark.parametrize("kind", ["python", "native"])
def test_info_command(kind, tmp_path):
    """INFO returns the same "key:value" introspection lines from the Python
    and native servers; counters reflect live state."""
    try:
        handle = _start_info_server(kind, str(tmp_path / f"{kind}.snap"))
    except Exception as exc:
        if kind == "native":
            pytest.skip(f"native store unavailable: {exc}")
        raise
    c = None
    sub = None
    try:
        c = RespStore(port=handle.port)
        c.hset("k1", {"f": "v"})
        c.hset("k2", {"f": "v"})
        sub = c.subscribe("tasks")
        info = c.info()
        assert info["server"] == f"tpu-faas-store-{kind}"
        assert info["keys"] == "2", info
        assert info["subscribers"] == "1", info
        assert info["dirty"] == "1", info
        assert info["snapshot_path"].endswith(".snap"), info
    finally:
        if sub is not None:
            sub.close()
        if c is not None:
            c.close()
        handle.stop()


@pytest.mark.parametrize("kind", ["python", "native"])
def test_pipeline_one_round_trip_semantics(kind, tmp_path):
    """N commands, one write, N in-order replies; error replies come back
    in place without masking the rest — on BOTH servers."""
    try:
        handle = _start_info_server(kind, str(tmp_path / "pl.snap"))
    except Exception as exc:
        if kind == "native":
            pytest.skip(f"native store unavailable: {exc}")
        raise
    c = RespStore(port=handle.port)
    try:
        replies = c.pipeline(
            [
                ("HSET", "pk", "f", "1"),
                ("HGET", "pk", "f"),
                ("BOGUS-CMD",),
                ("HGET", "pk", "f"),
            ]
        )
        assert replies[0] == 1  # fields added
        assert replies[1] == "1"
        assert isinstance(replies[2], resp.RespError)
        assert replies[3] == "1"
        assert c.pipeline([]) == []
    finally:
        c.close()
        handle.stop()


def test_batched_data_plane_ops(store_server):
    """The pipelined data-plane forms — hgetall_many / set_status_many /
    finish_task_many — against BOTH store servers: reply shapes, missing-key
    behavior, per-item extra fields, intra-batch first_wins, and the
    announce-per-written-item contract on the results channel."""
    from tpu_faas.store.base import RESULTS_CHANNEL

    s = make_store(store_server.url)
    try:
        s.create_tasks([(f"b{i}", f"F{i}", f"P{i}") for i in range(3)])
        # hgetall_many: one dict per key, {} for a missing key, order kept
        recs = s.hgetall_many(["b0", "ghost", "b2"])
        assert recs[0]["fn_payload"] == "F0" and recs[0]["status"] == "QUEUED"
        assert recs[1] == {}
        assert recs[2]["param_payload"] == "P2"
        assert s.hgetall_many([]) == []
        # set_status_many: one shared status, per-item extra fields
        s.set_status_many(
            "RUNNING", [("b0", {"lease_at": "1.5"}), ("b1", None)]
        )
        assert s.hget_many(["b0", "b1", "b2"], "status") == [
            "RUNNING", "RUNNING", "QUEUED",
        ]
        assert s.hget("b0", "lease_at") == "1.5"
        assert s.hget("b1", "lease_at") is None
        with s.subscribe(RESULTS_CHANNEL) as rsub:
            s.finish_task_many(
                [
                    ("b0", "COMPLETED", "r0", False),
                    ("b1", "FAILED", "r1", False),
                    # intra-batch first_wins: b0 is already terminal from
                    # the item above — this write must be skipped, exactly
                    # as if the items were applied sequentially
                    ("b0", "FAILED", "late", True),
                ]
            )
            # one announce per WRITTEN item, each after its record write
            assert rsub.get_message(timeout=2.0) == "b0"
            assert rsub.get_message(timeout=2.0) == "b1"
            assert rsub.get_message(timeout=0.3) is None
        assert s.get_result("b0") == ("COMPLETED", "r0")
        assert s.get_result("b1") == ("FAILED", "r1")
        # terminal writes dropped both ids from the live index
        assert set(s.hgetall(LIVE_INDEX_KEY)) == {"b2"}
        # store-state first_wins: a frozen record stays frozen in a batch
        s.finish_task_many([("b1", "COMPLETED", "second", True)])
        assert s.get_result("b1") == ("FAILED", "r1")
        # ...but a plain (non-first_wins) batch item still overwrites,
        # matching finish_task's sequential semantics
        s.finish_task_many([("b2", "COMPLETED", "r2", False)])
        assert s.get_result("b2") == ("COMPLETED", "r2")
        s.flush()
    finally:
        s.close()


def test_create_tasks_pipelined_announces_after_writes():
    """Batch create: every hash readable, every announce delivered, and no
    announce precedes its hash (subscriber sees ids whose payloads exist)."""
    handle = start_store_thread()
    c = RespStore(port=handle.port)
    reader = RespStore(port=handle.port)
    try:
        sub = reader.subscribe("tasks")
        c.create_tasks([(f"bt{i}", f"F{i}", f"P{i}") for i in range(20)])
        seen = []
        for _ in range(20):
            msg = sub.get_message(timeout=5.0)
            assert msg is not None
            # the announced task's payloads are already readable
            assert reader.get_payloads(msg) == (
                f"F{msg[2:]}", f"P{msg[2:]}"
            )
            seen.append(msg)
        assert sorted(seen) == sorted(f"bt{i}" for i in range(20))
        assert reader.hget_many([f"bt{i}" for i in range(20)], "status") == [
            "QUEUED"
        ] * 20
        sub.close()
    finally:
        c.close()
        reader.close()
        handle.stop()


# -- binary-batch fast path (CAPS / MHGETALL / MFINISH) ----------------------


def _flat_to_dict(flat):
    """Decode one hgetall_many_raw entry ([f, v, f, v, ...], bytes on a
    negotiated connection, str on the fallback) for comparison."""
    def _s(x):
        return x.decode() if isinstance(x, (bytes, bytearray)) else x

    return {_s(flat[i]): _s(flat[i + 1]) for i in range(0, len(flat) - 1, 2)}


def test_binbatch_negotiation_and_parity(store_server):
    """binbatch=True negotiates the aggregate forms on servers advertising
    them (CAPS -> MHGETALL/MFINISH) and silently stays on the plain
    pipeline elsewhere (the native server answers -ERR to CAPS) — the
    observable results are identical either way, which is the whole
    contract: the knob changes round trips, never semantics."""
    from tpu_faas.store.base import RESULTS_CHANNEL

    s = make_store(store_server.url, binbatch=True)
    plain = make_store(store_server.url)
    try:
        s.create_tasks([(f"m{i}", f"F{i}", f"P{i}") for i in range(3)])
        recs = s.hgetall_many(["m0", "ghost", "m2"])
        assert recs == plain.hgetall_many(["m0", "ghost", "m2"])
        assert recs[1] == {}
        assert s.hgetall_many([]) == []
        # raw form: one flat [field, value, ...] per key, order kept,
        # [] for a missing key; decodes to exactly the dict form
        flats = s.hgetall_many_raw(["m0", "ghost", "m2"])
        assert len(flats) == 3 and list(flats[1]) == []
        assert _flat_to_dict(flats[0]) == recs[0]
        assert _flat_to_dict(flats[2]) == recs[2]
        assert s.hgetall_many_raw([]) == []
        with plain.subscribe(RESULTS_CHANNEL) as rsub:
            s.finish_task_many(
                [
                    ("m0", "COMPLETED", "r0", False),
                    # intra-batch first_wins: m0 turned terminal one item
                    # up — this write must be skipped, exactly as if the
                    # items were applied sequentially
                    ("m0", "FAILED", "late", True),
                    ("m1", "FAILED", "r1", False),
                ]
            )
            assert rsub.get_message(timeout=2.0) == "m0"
            assert rsub.get_message(timeout=2.0) == "m1"
            assert rsub.get_message(timeout=0.3) is None
        assert plain.get_result("m0") == ("COMPLETED", "r0")
        assert plain.get_result("m1") == ("FAILED", "r1")
        # store-state first_wins: the frozen record stays frozen
        s.finish_task_many([("m0", "COMPLETED", "again", True)])
        assert plain.get_result("m0") == ("COMPLETED", "r0")
        # live index dropped both terminal ids
        assert set(plain.hgetall(LIVE_INDEX_KEY)) == {"m2"}
        s.flush()
    finally:
        s.close()
        plain.close()


def test_binbatch_off_wire_surface_is_plain_redis(monkeypatch):
    """The default (binbatch=False) client must put NOTHING non-Redis on
    the wire: no CAPS probe, no MHGETALL/MFINISH — every command name in
    the recorded stream is part of the plain-Redis subset. The opt-in
    client on the same server shows the aggregate forms, proving the spy
    would have caught them."""
    sent: list[str] = []
    real_encode = resp.encode_command

    def spy(*parts):
        sent.append(str(parts[0]).upper())
        return real_encode(*parts)

    monkeypatch.setattr(resp, "encode_command", spy)
    handle = start_store_thread()
    try:
        s = make_store(handle.url)
        s.create_tasks([("w0", "F", "P"), ("w1", "F", "P")])
        s.hgetall_many(["w0", "w1"])
        s.hgetall_many_raw(["w0", "w1"])
        s.finish_task_many([("w0", "COMPLETED", "r", False)])
        s.close()
        forbidden = {"CAPS", "MHGETALL", "MFINISH"}
        assert not forbidden & set(sent), sorted(forbidden & set(sent))
        sent.clear()
        fast = make_store(handle.url, binbatch=True)
        fast.hgetall_many_raw(["w0", "w1"])
        fast.finish_task_many([("w1", "COMPLETED", "r", False)])
        fast.close()
        assert "CAPS" in sent and "MHGETALL" in sent and "MFINISH" in sent
    finally:
        handle.stop()
