"""The store client against a REAL Redis server (skip-if-absent).

store/client.py:1-11 promises the RESP client speaks a strict subset of the
Redis protocol so a real Redis drops in for the bundled servers. This suite
backs that claim with an actual redis-server when one is installed on the
host; environments without the binary skip (the claim is then exercised
only against the two in-repo servers, which implement the same subset).
"""

from __future__ import annotations

import shutil
import socket
import subprocess
import time

import pytest

from tpu_faas.store.launch import make_store

REDIS = shutil.which("redis-server")

pytestmark = pytest.mark.skipif(
    REDIS is None, reason="redis-server not installed on this host"
)


@pytest.fixture()
def redis_url():
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    proc = subprocess.Popen(
        [REDIS, "--port", str(port), "--save", "", "--appendonly", "no"],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            try:
                s = make_store(f"resp://127.0.0.1:{port}")
                if s.ping():
                    s.close()
                    break
            except OSError:
                time.sleep(0.05)
        else:
            raise RuntimeError("redis-server did not come up")
        yield f"resp://127.0.0.1:{port}"
    finally:
        proc.kill()
        proc.wait()


def test_store_contract_against_real_redis(redis_url):
    """The full task-store contract — create/announce, status, idempotent
    claims, finish+wake, live index, TTL-sweeper primitives — against
    stock Redis."""
    from tpu_faas.store.base import LIVE_INDEX_KEY

    s = make_store(redis_url)
    try:
        sub = s.subscribe("tasks")
        wake = s.subscribe("results")
        time.sleep(0.1)  # real redis: subscribe is asynchronous
        s.create_task("t1", "FN", "PAR", channel="tasks", extra_fields={"priority": "2"})
        deadline = time.monotonic() + 5
        msg = None
        while msg is None and time.monotonic() < deadline:
            msg = sub.get_message(timeout=0.2)
        assert msg == "t1"
        assert s.get_status("t1") == "QUEUED"
        assert s.get_payloads("t1") == ("FN", "PAR")
        assert s.hget("t1", "priority") == "2"
        assert s.hgetall(LIVE_INDEX_KEY) == {"t1": "1"}

        # idempotency primitive
        assert s.setnx_field("t1", "claim", "a") == (True, "a")
        assert s.setnx_field("t1", "claim", "b") == (False, "a")
        assert s.setnx_fields([("t1", "c"), ("t2x", "d")], "claim") == [
            (False, "a"),
            (True, "d"),
        ]
        s.delete("t2x")

        # pipelined batch ops
        s.create_tasks([("t2", "FN", "P2"), ("t3", "FN", "P3")])
        assert s.hget_many(["t1", "t2", "t3"], "status") == [
            "QUEUED", "QUEUED", "QUEUED",
        ]
        s.hset_many([("t2", {"lease_at": "1.0"}), ("t3", {"lease_at": "2.0"})])
        assert s.hmget("t2", ["status", "lease_at"]) == ["QUEUED", "1.0"]

        # terminal write: result + wake + index removal in one round trip
        s.finish_task("t1", "COMPLETED", "RES")
        deadline = time.monotonic() + 5
        msg = None
        while msg is None and time.monotonic() < deadline:
            msg = wake.get_message(timeout=0.2)
        assert msg == "t1"
        assert s.get_result("t1") == ("COMPLETED", "RES")
        assert set(s.hgetall(LIVE_INDEX_KEY)) == {"t2", "t3"}

        s.delete_many(["t2", "t3"])
        assert s.get_status("t2") is None
    finally:
        s.close()


def test_local_dispatch_e2e_against_real_redis(redis_url):
    """A local dispatcher serving real traffic out of stock Redis."""
    import threading

    from tpu_faas.core.serialize import deserialize, serialize
    from tpu_faas.dispatch.local import LocalDispatcher
    from tpu_faas.gateway import start_gateway_thread

    gw = start_gateway_thread(make_store(redis_url))
    disp = LocalDispatcher(num_workers=2, store=make_store(redis_url))
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    try:
        import requests

        fid = requests.post(
            f"{gw.url}/register_function",
            json={"name": "sq", "payload": serialize(lambda x: x * x)},
        ).json()["function_id"]
        tid = requests.post(
            f"{gw.url}/execute_function",
            json={"function_id": fid, "payload": serialize(((6,), {}))},
        ).json()["task_id"]
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            body = requests.get(f"{gw.url}/result/{tid}").json()
            if body["status"] in ("COMPLETED", "FAILED"):
                break
            time.sleep(0.1)
        assert body["status"] == "COMPLETED"
        assert deserialize(body["result"]) == 36
    finally:
        disp.stop()
        t.join(timeout=10)
        gw.stop()
