"""The store client against Redis: real server when installed, protocol
fixtures everywhere.

store/client.py:1-11 promises the RESP client speaks a strict subset of the
Redis protocol so a real Redis drops in for the bundled servers. Two layers
back the claim:

1. The full task-store contract runs against a backend parametrization that
   always includes :class:`tests.redis_fixture.RedisSemanticsServer` — a
   responder with REAL Redis's reply shapes (integer HSET replies, ``*0``
   HGETALL on missing keys, pub/sub push frames, case-insensitive names) —
   and additionally against an actual redis-server when one is installed
   (the parameter is only generated when the binary exists, so environments
   without it run the fixture backend with zero skips).
2. Byte-level wire pins: `encode_command` must emit the exact request bytes
   redis-server parses, and `RespParser` must decode authentic Redis reply
   bytes — including nil bulks/arrays, empty bulks, pushed message frames,
   errors, and replies split at arbitrary byte boundaries.
"""

from __future__ import annotations

import socket
import subprocess
import time

import pytest

from tpu_faas.store import resp
from tpu_faas.store.launch import make_store

#: a real redis-server binary: $PATH first, then the checksum-pinned local
#: build (native/build_redis.sh) — environments without egress can drop
#: the pinned tarball and build once to flip the "real" leg from skip to
#: run. Shared discovery with bench.py's redis_interop field.
from tpu_faas.store.launch import find_redis_server

REDIS = find_redis_server()


def _real_redis_server():
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    proc = subprocess.Popen(
        [REDIS, "--port", str(port), "--save", "", "--appendonly", "no"],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        try:
            s = make_store(f"resp://127.0.0.1:{port}")
            if s.ping():
                s.close()
                break
        except OSError:
            time.sleep(0.05)
    else:
        proc.kill()
        raise RuntimeError("redis-server did not come up")
    return proc, f"resp://127.0.0.1:{port}"


# "real" is only a parameter when the binary exists: the contract must
# execute (not skip) in every environment, via the fixture backend
BACKENDS = ["fixture"] + (["real"] if REDIS else [])


@pytest.fixture(params=BACKENDS)
def redis_url(request):
    if request.param == "real":
        proc, url = _real_redis_server()
        try:
            yield url
        finally:
            proc.kill()
            proc.wait()
    else:
        from tests.redis_fixture import RedisSemanticsServer

        server = RedisSemanticsServer()
        try:
            yield server.url
        finally:
            server.stop()


def test_store_contract_against_redis(redis_url):
    """The full task-store contract — create/announce, status, idempotent
    claims, finish+wake, live index, TTL-sweeper primitives — against
    Redis reply semantics."""
    from tpu_faas.store.base import LIVE_INDEX_KEY

    s = make_store(redis_url)
    try:
        sub = s.subscribe("tasks")
        wake = s.subscribe("results")
        time.sleep(0.1)  # real redis: subscribe is asynchronous
        s.create_task("t1", "FN", "PAR", channel="tasks", extra_fields={"priority": "2"})
        deadline = time.monotonic() + 5
        msg = None
        while msg is None and time.monotonic() < deadline:
            msg = sub.get_message(timeout=0.2)
        assert msg == "t1"
        assert s.get_status("t1") == "QUEUED"
        assert s.get_payloads("t1") == ("FN", "PAR")
        assert s.hget("t1", "priority") == "2"
        assert s.hgetall(LIVE_INDEX_KEY) == {"t1": "1"}

        # idempotency primitive
        assert s.setnx_field("t1", "claim", "a") == (True, "a")
        assert s.setnx_field("t1", "claim", "b") == (False, "a")
        assert s.setnx_fields([("t1", "c"), ("t2x", "d")], "claim") == [
            (False, "a"),
            (True, "d"),
        ]
        s.delete("t2x")

        # pipelined batch ops
        s.create_tasks([("t2", "FN", "P2"), ("t3", "FN", "P3")])
        assert s.hget_many(["t1", "t2", "t3"], "status") == [
            "QUEUED", "QUEUED", "QUEUED",
        ]
        s.hset_many([("t2", {"lease_at": "1.0"}), ("t3", {"lease_at": "2.0"})])
        assert s.hmget("t2", ["status", "lease_at"]) == ["QUEUED", "1.0"]
        # missing key/fields: all-nil array, not an error
        assert s.hmget("nope", ["a", "b"]) == [None, None]
        assert s.hgetall("nope") == {}

        # pipelined data-plane forms (the dispatcher's batched intake /
        # coalesced act writes / batched result path) against Redis reply
        # semantics: *0 HGETALL for a missing key, in-order pipelining
        recs = s.hgetall_many(["t2", "nope", "t3"])
        assert recs[0]["param_payload"] == "P2"
        assert recs[1] == {}
        assert recs[2]["status"] == "QUEUED"
        s.set_status_many(
            "RUNNING", [("t2", {"lease_at": "3.0"}), ("t3", None)]
        )
        assert s.hget_many(["t2", "t3"], "status") == ["RUNNING", "RUNNING"]
        assert s.hget("t2", "lease_at") == "3.0"
        s.finish_task_many(
            [
                ("t2", "COMPLETED", "R2", False),
                ("t2", "FAILED", "late", True),  # intra-batch first_wins
                ("t3", "COMPLETED", "R3", False),
            ]
        )
        assert s.get_result("t2") == ("COMPLETED", "R2")
        assert s.get_result("t3") == ("COMPLETED", "R3")
        assert s.hgetall(LIVE_INDEX_KEY) == {"t1": "1"}
        # one wake per WRITTEN batch item (the skipped first_wins item
        # announces nothing)
        woken = []
        deadline = time.monotonic() + 5
        while len(woken) < 2 and time.monotonic() < deadline:
            w = wake.get_message(timeout=0.2)
            if w is not None:
                woken.append(w)
        assert woken == ["t2", "t3"]

        # terminal write: result + wake + index removal in one round trip
        s.finish_task("t1", "COMPLETED", "RES")
        deadline = time.monotonic() + 5
        msg = None
        while msg is None and time.monotonic() < deadline:
            msg = wake.get_message(timeout=0.2)
        assert msg == "t1"
        assert s.get_result("t1") == ("COMPLETED", "RES")
        # t2/t3 left the index with their batched terminal writes above;
        # t1's single finish_task just removed the last entry
        assert s.hgetall(LIVE_INDEX_KEY) == {}

        s.delete_many(["t2", "t3"])
        assert s.get_status("t2") is None
    finally:
        s.close()


def test_binbatch_knob_degrades_silently_against_redis(redis_url):
    """binbatch=True against a backend that is NOT our store server: the
    CAPS probe gets Redis's -ERR unknown command, negotiation reads that
    as no capabilities, and every batched op rides the plain pipelined
    forms — same results, no errors, no retries. This is the drop-in-Redis
    half of the binary-batch contract (the other half — byte-identical
    wire with the knob OFF — is pinned in test_store_resp.py)."""
    s = make_store(redis_url, binbatch=True)
    try:
        s.create_tasks([(f"bb{i}", f"F{i}", f"P{i}") for i in range(3)])
        recs = s.hgetall_many(["bb0", "ghost", "bb2"])
        assert recs[0]["fn_payload"] == "F0"
        assert recs[1] == {}
        assert recs[2]["param_payload"] == "P2"
        flats = s.hgetall_many_raw(["bb1", "ghost"])
        assert dict(zip(flats[0][::2], flats[0][1::2]))["fn_payload"] == "F1"
        assert list(flats[1]) == []
        s.finish_task_many(
            [("bb0", "COMPLETED", "r0", False), ("bb0", "FAILED", "x", True)]
        )
        assert s.get_result("bb0") == ("COMPLETED", "r0")
        s.delete_many(["bb0", "bb1", "bb2"])
    finally:
        s.close()


def test_local_dispatch_e2e_against_redis(redis_url):
    """A local dispatcher serving real traffic out of a Redis-semantics
    store."""
    import threading

    from tpu_faas.core.serialize import deserialize, serialize
    from tpu_faas.dispatch.local import LocalDispatcher
    from tpu_faas.gateway import start_gateway_thread

    gw = start_gateway_thread(make_store(redis_url))
    disp = LocalDispatcher(num_workers=2, store=make_store(redis_url))
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    try:
        import requests

        fid = requests.post(
            f"{gw.url}/register_function",
            json={"name": "sq", "payload": serialize(lambda x: x * x)},
        ).json()["function_id"]
        tid = requests.post(
            f"{gw.url}/execute_function",
            json={"function_id": fid, "payload": serialize(((6,), {}))},
        ).json()["task_id"]
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            body = requests.get(f"{gw.url}/result/{tid}").json()
            if body["status"] in ("COMPLETED", "FAILED"):
                break
            time.sleep(0.1)
        assert body["status"] == "COMPLETED"
        assert deserialize(body["result"]) == 36
    finally:
        disp.stop()
        t.join(timeout=10)
        gw.stop()


# -- byte-level wire pins ---------------------------------------------------

def test_encode_command_exact_request_bytes():
    """Requests must be byte-identical to what redis-server parses: arrays
    of bulk strings with exact length prefixes (binary payloads counted in
    BYTES, not characters)."""
    assert resp.encode_command("PING") == b"*1\r\n$4\r\nPING\r\n"
    assert resp.encode_command("HSET", "k", "f", "v") == (
        b"*4\r\n$4\r\nHSET\r\n$1\r\nk\r\n$1\r\nf\r\n$1\r\nv\r\n"
    )
    # integers are sent as bulk strings of their decimal form
    assert resp.encode_command("DEL", 42) == b"*2\r\n$3\r\nDEL\r\n$2\r\n42\r\n"
    # utf-8 payloads: $-length counts bytes
    assert resp.encode_command("HSET", "k", "f", "é") == (
        b"*4\r\n$4\r\nHSET\r\n$1\r\nk\r\n$1\r\nf\r\n$2\r\n\xc3\xa9\r\n"
    )


# authentic redis-server reply bytes -> expected decoded value
WIRE_REPLIES = [
    (b"+PONG\r\n", "PONG"),
    (b"+OK\r\n", "OK"),
    (b":0\r\n", 0),
    (b":1\r\n", 1),
    (b":-1\r\n", -1),
    (b"$-1\r\n", None),  # nil bulk (HGET miss)
    (b"$0\r\n\r\n", ""),  # empty bulk
    (b"$5\r\nhello\r\n", "hello"),
    (b"$7\r\na\r\nb\r\nc\r\n", "a\r\nb\r\nc"),  # CRLF inside a bulk body
    (b"*0\r\n", []),  # HGETALL miss
    (b"*-1\r\n", None),  # nil array (BLPOP timeout style)
    (b"*2\r\n$1\r\nf\r\n$1\r\nv\r\n", ["f", "v"]),
    (b"*3\r\n$1\r\na\r\n$-1\r\n$1\r\nc\r\n", ["a", None, "c"]),  # HMGET
    (  # SUBSCRIBE confirmation push
        b"*3\r\n$9\r\nsubscribe\r\n$5\r\ntasks\r\n:1\r\n",
        ["subscribe", "tasks", 1],
    ),
    (  # published message push
        b"*3\r\n$7\r\nmessage\r\n$5\r\ntasks\r\n$2\r\nt9\r\n",
        ["message", "tasks", "t9"],
    ),
]


@pytest.mark.parametrize(
    "wire,expected",
    WIRE_REPLIES,
    ids=[w[:16].decode("ascii", "replace").replace("\r\n", "~") for w, _ in WIRE_REPLIES],
)
def test_parser_decodes_authentic_reply_bytes(wire, expected):
    p = resp.RespParser()
    p.feed(wire)
    assert p.pop() == expected
    assert p.pop() is resp.NEED_MORE
    assert p.pending() == 0


def test_parser_decodes_error_reply():
    p = resp.RespParser()
    p.feed(b"-ERR unknown command 'FOO', with args beginning with: \r\n")
    err = p.pop()
    assert isinstance(err, resp.RespError)
    assert "unknown command" in str(err)


def test_parser_handles_arbitrary_split_boundaries():
    """TCP gives no framing guarantees: a pipelined reply stream fed one
    byte at a time must decode identically."""
    stream = b"".join(w for w, _ in WIRE_REPLIES)
    expected = [e for _, e in WIRE_REPLIES]
    p = resp.RespParser()
    got = []
    for i in range(len(stream)):
        p.feed(stream[i : i + 1])
        while True:
            item = p.pop()
            if item is resp.NEED_MORE:
                break
            got.append(item)
    assert got == expected
    assert p.pending() == 0


def test_real_redis_interop_leg_visibility():
    """The real-server interop leg must never vanish SILENTLY: when
    redis-server is absent this shows up as an explicit skip in the run
    summary (and bench.py records the same fact in its JSON artifact), so
    'any Redis drops in' is never claimed on fixture evidence alone
    without saying so."""
    if REDIS is None:
        pytest.skip(
            "redis-server not installed and native/redis-server not built "
            "(no egress to fetch the pinned tarball — run "
            "native/build_redis.sh where egress or a tarball drop exists): "
            "real-server interop leg NOT run (contract verified against "
            "reply-shape fixture + wire pins; the reference's own "
            "redis-client stack runs against OUR server in "
            "tests/test_reference_worker_interop.py)"
        )


def test_shim_pubsub_nonblocking_on_partial_reply():
    """ADVICE r5: the shim's PubSub.get_message must honor its non-blocking
    contract even when a published payload arrives SPLIT across TCP
    segments — the old fast-path check ('any CRLF buffered?') walked into
    read_reply's unguarded socket fills on exactly that shape and blocked
    until the rest of the frame arrived. With the reply-span lookahead, a
    partial frame returns None immediately and the complete message is
    delivered once the tail lands."""
    import socket as _socket
    import threading
    import time

    from tpu_faas.compat.redis_shim.redis import PubSub

    # a hand-rolled one-shot RESP server: accepts the SUBSCRIBE, then
    # dribbles a large published message in two delayed halves
    srv = _socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    payload = b"X" * 4096
    msg = (
        b"*3\r\n$7\r\nmessage\r\n$5\r\ntasks\r\n$%d\r\n%s\r\n"
        % (len(payload), payload)
    )

    def serve():
        conn, _ = srv.accept()
        conn.recv(4096)  # the SUBSCRIBE command
        conn.sendall(b"*3\r\n$9\r\nsubscribe\r\n$5\r\ntasks\r\n:1\r\n")
        time.sleep(0.15)
        conn.sendall(msg[: len(msg) // 2])  # partial frame...
        time.sleep(0.6)
        conn.sendall(msg[len(msg) // 2:])  # ...tail later
        time.sleep(0.5)
        conn.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    ps = PubSub("127.0.0.1", port)
    try:
        ps.subscribe("tasks")
        time.sleep(0.3)  # the partial half is now buffered server-side
        t0 = time.monotonic()
        first = ps.get_message(timeout=0.05)
        waited = time.monotonic() - t0
        assert first is None  # partial reply: no message, and...
        assert waited < 0.45  # ...no block past the timeout window
        # once the tail lands, the message is delivered whole
        deadline = time.monotonic() + 5.0
        got = None
        while got is None and time.monotonic() < deadline:
            got = ps.get_message(timeout=0.1)
        assert got == {
            "type": "message", "channel": b"tasks", "data": payload
        }
    finally:
        ps.close()
        srv.close()
        t.join(timeout=5)
