"""The estimation loop (sched/estimator.py): learned function runtimes and
worker speeds must make the heterogeneous placement machinery engage on the
live path with NO client hints — the round-3 verdict's top item."""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from tpu_faas.sched.estimator import FN_STATS_KEY, RuntimeEstimator, fn_digest


def test_size_ewma_converges_to_observed_runtime():
    est = RuntimeEstimator()
    d = fn_digest("payload-A")
    for _ in range(30):
        est.observe(d, 2.5, b"w0")
    assert est.size_for(d) == pytest.approx(2.5, rel=1e-3)
    # a second function learns independently
    d2 = fn_digest("payload-B")
    for _ in range(30):
        est.observe(d2, 0.1, b"w0")
    assert est.size_for(d2) == pytest.approx(0.1, rel=1e-3)
    # the prior for a NEVER-seen function sits mid-field, not at payload
    # bytes scale
    assert 0.1 <= est.default_size() <= 2.5


def test_speed_learning_separates_mixed_fleet():
    """Fast and slow workers running the same functions must separate in
    the speed estimate, with the fast/slow ratio approaching truth."""
    est = RuntimeEstimator()
    rng = np.random.default_rng(3)
    fns = [(fn_digest(f"fn{i}"), s) for i, s in enumerate([4.0, 1.0, 0.25])]
    workers = {b"fast": 4.0, b"slow": 0.5}
    for _ in range(120):
        d, size = fns[int(rng.integers(len(fns)))]
        wid = [b"fast", b"slow"][int(rng.integers(2))]
        true_speed = workers[wid]
        est.observe(d, size / true_speed, wid)
    ratio = est.speed_for(b"fast") / est.speed_for(b"slow")
    assert ratio > 3.0, ratio  # truth is 8x; well-separated is what matters
    # gauge sanity: estimates stay in the clamp band
    for wid in workers:
        assert 0.05 <= est.speed_for(wid) <= 20.0


def test_bad_observations_ignored():
    est = RuntimeEstimator()
    d = fn_digest("x")
    est.observe(d, 0.0, b"w")
    est.observe(d, -1.0, b"w")
    est.observe(d, float("nan"), b"w")
    assert est.size_for(d) is None
    assert est.n_observations == 0


def test_persistence_roundtrip_via_store():
    from tpu_faas.store.launch import make_store

    store = make_store("memory://")
    box = [0.0]
    est = RuntimeEstimator(store=store, persist_period=0.0, clock=lambda: box[0])
    d = fn_digest("persist-me")
    for _ in range(10):
        est.observe(d, 1.5, "tok-w0")  # str = stable token: persists
        est.observe(d, 1.5, b"w0")  # bytes = socket identity: ephemeral
    box[0] = 1.0
    # the fn estimate AND the TOKEN's speed grade persist (round-5: worker
    # grades survive restarts, VERDICT r4 missing #4); the socket-identity
    # grade stays in memory only (never seen again after its worker dies)
    assert est.maybe_persist() == 2
    # a fresh estimator (dispatcher restart) loads both learned values
    est2 = RuntimeEstimator(store=store)
    assert est2.size_for(d) == pytest.approx(est.size_for(d))
    assert est2.speed_for("tok-w0") == pytest.approx(
        est.speed_for("tok-w0"), rel=1e-4
    )
    assert est2.speed_for(b"w0") == 1.0  # ephemeral: not persisted
    # malformed persisted entries degrade instead of wedging the load
    store.hset(FN_STATS_KEY, {"garbage": "not:numbers:at-all"})
    est3 = RuntimeEstimator(store=store)
    assert est3.size_for(d) is not None


def test_learned_estimates_beat_unhinted_placement_on_makespan():
    """The verdict's acceptance bar: a deliberately mixed fleet + mixed
    workload, NO client hints — placement driven by learned sizes/speeds
    must measurably beat the speed=1.0/size=1.0 placement on makespan
    (computed against the TRUE sizes and speeds)."""
    from tpu_faas.sched.greedy import makespan, rank_match_placement

    # truth: 4 fast workers (speed 4) + 4 slow (speed 0.5), interleaved so
    # index order carries no information; two function classes 8.0 / 1.0
    true_speeds = np.array(
        [4.0, 0.5, 4.0, 0.5, 4.0, 0.5, 4.0, 0.5], dtype=np.float32
    )
    wids = [f"w{i}".encode() for i in range(8)]
    fn_big, fn_small = "fn-big", "fn-small"
    true_size = {fn_big: 8.0, fn_small: 1.0}

    # learning phase: the estimator sees exactly what a live dispatcher
    # would — worker-measured elapsed = size / true_speed
    est = RuntimeEstimator()
    rng = np.random.default_rng(0)
    for _ in range(200):
        fn = [fn_big, fn_small][int(rng.integers(2))]
        w = int(rng.integers(8))
        noise = float(rng.uniform(0.9, 1.1))  # runtime jitter
        est.observe(
            fn_digest(fn), true_size[fn] / true_speeds[w] * noise, wids[w]
        )

    # placement phase: 16 tasks interleaved big/small, 2 slots per worker
    tasks = [fn_big, fn_small] * 8
    true_sizes = np.array([true_size[f] for f in tasks], dtype=np.float32)
    valid = np.ones(16, dtype=bool)
    free = np.full(8, 2, dtype=np.int32)
    live = np.ones(8, dtype=bool)

    learned_sizes = np.array(
        [est.size_for(fn_digest(f)) for f in tasks], dtype=np.float32
    )
    learned_speeds = np.array(
        [est.speed_for(w) for w in wids], dtype=np.float32
    )
    a_learned = np.asarray(
        rank_match_placement(
            learned_sizes, valid, learned_speeds, free, live, max_slots=2
        )
    )
    a_blind = np.asarray(
        rank_match_placement(
            np.ones(16, dtype=np.float32), valid,
            np.ones(8, dtype=np.float32), free, live, max_slots=2,
        )
    )
    ms_learned = makespan(a_learned, true_sizes, true_speeds, max_slots=2)
    ms_blind = makespan(a_blind, true_sizes, true_speeds, max_slots=2)
    # optimal here is 2.0 (big tasks alone on fast slots); blind placement
    # sends big tasks to slow workers (16.0). Require a decisive win, not
    # a lucky tie-break.
    assert ms_learned <= 0.5 * ms_blind, (ms_learned, ms_blind)
    assert ms_learned == pytest.approx(2.0, rel=0.2)


def test_dispatcher_learns_sizes_end_to_end():
    """Socket e2e: tpu-push dispatcher + real push worker, two functions
    with ~10x different runtimes, ZERO hints — the dispatcher's estimator
    must learn the ratio from the elapsed field on RESULT messages, and
    stamped batches must carry the learned sizes."""
    from tpu_faas.client import FaaSClient
    from tpu_faas.gateway import start_gateway_thread
    from tpu_faas.store.launch import make_store, start_store_thread
    from tests.test_tpu_push_e2e import _make_dispatcher
    from tests.test_workers_e2e import _spawn_worker

    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    disp = _make_dispatcher(store_handle.url)
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    worker = _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
    client = FaaSClient(gw.url)
    try:
        def slow(x):
            time.sleep(0.2)
            return x

        def quick(x):
            time.sleep(0.02)
            return x

        fid_slow = client.register(slow)
        fid_quick = client.register(quick)
        handles = []
        for i in range(6):
            handles.append(client.submit(fid_slow, i))
            handles.append(client.submit(fid_quick, i))
        for h in handles:
            h.result(timeout=60.0)
        est = disp.estimator
        assert est is not None and est.n_observations >= 10
        # find the two learned estimates; their ratio reflects ~10x truth
        sizes = sorted(est._fn_est.values())
        assert len(sizes) == 2
        assert sizes[1] / sizes[0] > 3.0, sizes
    finally:
        if worker.poll() is None:
            worker.kill()
            worker.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


# -- round 5: param-aware sizing (VERDICT r4 missing #3) --------------------
def test_mixed_param_function_beats_fn_level_ewma_on_makespan():
    """The verdict's acceptance bar: ONE function id whose runtime varies
    by parameter (the reference's own corpus shape — sleep_n/arithmetic(n),
    client_performance.py:19-92). The exact-param level must separate the
    variants; the fn-level EWMA collapses them to the historical mean, and
    the resulting placements must differ measurably on makespan."""
    from tpu_faas.sched.greedy import makespan, rank_match_placement

    rng = np.random.default_rng(5)
    n_workers, max_slots = 16, 4
    true_speeds = np.where(np.arange(n_workers) % 2 == 0, 4.0, 0.5).astype(
        np.float32
    )
    wids = [f"w{i}".encode() for i in range(n_workers)]
    d = fn_digest("arithmetic")
    # one function, four parameterizations spanning 64x in runtime
    variants = {f"n={n}": float(sz) for n, sz in
                [(1000, 0.125), (8000, 1.0), (64000, 8.0), (128000, 16.0)]}
    pdigests = {p: fn_digest(p) for p in variants}

    est = RuntimeEstimator()
    for _ in range(600):
        p = list(variants)[int(rng.integers(len(variants)))]
        w = int(rng.integers(n_workers))
        elapsed = variants[p] / true_speeds[w] * rng.uniform(0.97, 1.03)
        est.observe(d, elapsed, wids[w], pdigests[p], len(p))

    # a wave of mixed-param tasks of the SAME function
    n_tasks = n_workers * max_slots
    task_params = [list(variants)[int(rng.integers(len(variants)))]
                   for _ in range(n_tasks)]
    true_sizes = np.array([variants[p] for p in task_params], np.float32)
    param_aware = np.array(
        [est.size_for(d, pdigests[p], len(p)) for p in task_params],
        np.float32,
    )
    fn_level = np.array(
        [est.size_for(d) for p in task_params], np.float32
    )
    assert np.all(param_aware > 0)
    # fn-level sees ONE size for everything; param-aware recovers truth
    assert np.allclose(fn_level, fn_level[0])
    assert np.corrcoef(param_aware, true_sizes)[0, 1] > 0.99

    speeds = np.array([est.speed_for(w) for w in wids], np.float32)
    valid = np.ones(n_tasks, dtype=bool)
    live = np.ones(n_workers, dtype=bool)

    def place(sizes):
        free = np.full(n_workers, max_slots, np.int32)
        a = np.asarray(rank_match_placement(
            sizes, valid, speeds, free, live, max_slots=max_slots
        ))
        return makespan(a, true_sizes, true_speeds, max_slots=max_slots)

    ms_param = place(param_aware)
    ms_fn = place(fn_level)
    assert ms_param < ms_fn * 0.85, (ms_param, ms_fn)


def test_byte_regression_generalizes_to_unseen_param_sizes():
    """Data-sized workloads (sorts: param bytes scale with n) must predict
    runtimes for byte sizes NEVER observed, via the per-function log-log
    byte regression; constant-byte workloads must NOT engage it."""
    est = RuntimeEstimator()
    d = fn_digest("sort")
    rng = np.random.default_rng(7)
    # runtime ~ bytes^1.1 over a 100x byte range
    for _ in range(80):
        nbytes = int(rng.integers(1_000, 100_000))
        size = (nbytes / 10_000.0) ** 1.1
        est.observe(d, size, b"w", fn_digest(str(nbytes)), nbytes)
    # an UNSEEN byte count far outside any exact-param key
    pred = est.size_for(d, fn_digest("fresh"), 50_000)
    truth = (50_000 / 10_000.0) ** 1.1
    assert pred == pytest.approx(truth, rel=0.35)
    # constant-byte function: regression must stay out of the way
    d2 = fn_digest("sleeper")
    for n, sz in [(1, 0.1), (2, 4.0)] * 30:
        est.observe(d2, sz, b"w", fn_digest(f"sleep{n}"), 64)
    # unseen param at the same 64 bytes: falls back to the fn-level mean,
    # never an exploding extrapolation
    fallback = est.size_for(d2, fn_digest("sleep3"), 64)
    assert 0.05 <= fallback <= 8.0


# -- round 5: durable worker grades (VERDICT r4 missing #4) -----------------
def test_worker_speed_survives_dispatcher_restart_and_purge():
    """A dispatcher restart (new TpuPushDispatcher, same store) must apply
    persisted speed grades to a token-bearing worker at REGISTER time with
    zero relearn window, and a purged zombie that reconnects under a fresh
    socket identity but the same token keeps its grade."""
    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
    from tpu_faas.store.memory import MemoryStore

    store = MemoryStore()

    def make_disp():
        return TpuPushDispatcher(
            ip="127.0.0.1", port=0, store=store, max_workers=8,
            max_pending=32, max_inflight=64,
        )

    d1 = make_disp()
    try:
        d1._handle(b"sock-1", "register", {"num_processes": 2,
                                           "token": "machine-A"})
        d1._handle(b"sock-B", "register", {"num_processes": 2,
                                           "token": "machine-B"})
        row = d1.arrays.worker_ids[b"sock-1"]
        row_b = d1.arrays.worker_ids[b"sock-B"]
        # interleave a slow baseline (elapsed 1.0) with machine-A (elapsed
        # 0.25) on the same function+param: the alternating estimation
        # separates them ~4x in speed
        fd = fn_digest("fn")
        for i in range(40):
            for sock, r, elapsed in (
                (b"sock-B", row_b, 1.0), (b"sock-1", row, 0.25),
            ):
                tid = f"t{i}-{elapsed}"
                d1._task_digest[tid] = (fd, fn_digest("p"), 8)
                d1._observe_result(sock, r, tid,
                                   {"elapsed": elapsed,
                                    "status": "COMPLETED"})
        graded = d1.estimator.speed_for("machine-A")
        assert graded / d1.estimator.speed_for("machine-B") > 2.0
        assert graded > 1.5
        d1.estimator.maybe_persist(force=True)
    finally:
        d1.socket.close(linger=0)

    # restart: a fresh dispatcher on the same store
    d2 = make_disp()
    try:
        assert d2.estimator.speed_for("machine-A") == pytest.approx(
            graded, rel=1e-4
        )
        d2._handle(b"sock-2", "register", {"num_processes": 2,
                                           "token": "machine-A"})
        row2 = d2.arrays.worker_ids[b"sock-2"]
        assert float(d2.arrays.worker_speed[row2]) == pytest.approx(
            graded, rel=1e-3
        )
        # purge the worker (zombie): the token-stable grade is KEPT...
        token = d2._wid_token.get(b"sock-2")
        assert token == "machine-A"
        # simulate the purge path's bookkeeping
        d2._wid_token.pop(b"sock-2")
        assert d2.estimator.speed_for("machine-A") == pytest.approx(
            graded, rel=1e-4
        )
        # ...and a reconnect under a NEW socket identity re-applies it
        d2._handle(b"sock-3", "register", {"num_processes": 2,
                                           "token": "machine-A"})
        row3 = d2.arrays.worker_ids[b"sock-3"]
        assert float(d2.arrays.worker_speed[row3]) == pytest.approx(
            graded, rel=1e-3
        )
        # a TOKENLESS (reference-era) worker's grade is ephemeral: purge
        # forgets it
        d2.estimator._speed_est["deadbeef"] = 3.0
        d2.estimator.forget_worker(bytes.fromhex("deadbeef"))
        assert d2.estimator.speed_for(bytes.fromhex("deadbeef")) == 1.0
    finally:
        d2.socket.close(linger=0)


def test_shared_siblings_adopt_each_others_grades():
    """Two estimators over one store (--shared fleet): a worker graded by
    sibling A becomes visible to sibling B at B's next persist period."""
    from tpu_faas.store.memory import MemoryStore

    store = MemoryStore()
    box = [0.0]
    a = RuntimeEstimator(store=store, persist_period=0.0,
                         clock=lambda: box[0])
    b = RuntimeEstimator(store=store, persist_period=0.0,
                         clock=lambda: box[0])
    d = fn_digest("fn")
    # slow baseline first (settles the size at ~2.0), then the fast
    # worker's 0.5 s runs grade it up
    for _ in range(20):
        a.observe(d, 2.0, "tok-slow", fn_digest("p"), 8)
        a.observe(d, 0.5, "tok-x", fn_digest("p"), 8)
    graded = a.speed_for("tok-x")
    assert graded > 1.0
    box[0] = 1.0
    a.maybe_persist()
    # B has its own dirt to flush (any observation), which triggers the
    # sibling read
    b.observe(d, 1.0, "tok-own", fn_digest("p"), 8)
    b.maybe_persist()
    assert b.speed_for("tok-x") == pytest.approx(graded)


def test_quantized_speed_row_drifts_back_to_truth():
    """VERDICT r4 weak #5: live speed updates into the device-cached row
    are gated at 5% (dispatch/tpu_push.py) so tiny EWMA moves don't dirty
    the cache every tick — but a row that starts WRONG must still converge.
    Seed a persisted wrong grade, then feed correct observations: the
    estimator drifts continuously and the quantized row follows in >5%
    steps, ending within a gate-width of the estimator's value and far
    from the wrong start."""
    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
    from tpu_faas.store.memory import MemoryStore

    from tpu_faas.sched.estimator import WORKER_STATS_KEY

    store = MemoryStore()
    # a stale persisted grade: machine-X recorded SLOW (0.25) by an old
    # session, but the hardware now runs 4x the fleet baseline
    store.hset(WORKER_STATS_KEY, {"machine-X": "0.25"})
    d = TpuPushDispatcher(
        ip="127.0.0.1", port=0, store=store, max_workers=8,
        max_pending=32, max_inflight=64,
    )
    try:
        d._handle(b"sx", "register", {"num_processes": 2,
                                      "token": "machine-X"})
        d._handle(b"sb", "register", {"num_processes": 2,
                                      "token": "machine-base"})
        row = d.arrays.worker_ids[b"sx"]
        row_b = d.arrays.worker_ids[b"sb"]
        assert float(d.arrays.worker_speed[row]) == pytest.approx(0.25)
        fd = fn_digest("fn")
        for i in range(160):
            for sock, r, elapsed in ((b"sb", row_b, 1.0), (b"sx", row, 0.25)):
                tid = f"q{i}-{elapsed}"
                d._task_digest[tid] = (fd, fn_digest("p"), 8)
                d._observe_result(sock, r, tid,
                                  {"elapsed": elapsed,
                                   "status": "COMPLETED"})
        est_val = d.estimator.speed_for("machine-X")
        row_val = float(d.arrays.worker_speed[row])
        base_val = float(d.arrays.worker_speed[row_b])
        # the grade climbed out of the wrong basin...
        assert est_val / d.estimator.speed_for("machine-base") > 2.0
        assert row_val / base_val > 2.0
        # ...and the quantized row tracks the estimator within the 5% gate
        assert abs(row_val - est_val) <= 0.05 * est_val + 1e-6
    finally:
        d.socket.close(linger=0)


def test_dispatcher_learns_param_variants_end_to_end():
    """Socket e2e for the param-aware axis: ONE function (sleep_task) run
    with two parameterizations (~10x apart) through the real
    gateway/dispatcher/worker stack — the estimator must hold separate
    exact-param estimates under the single function digest, with the
    ratio reflecting truth (the fn-level estimate collapses to one mean,
    useless for mixed-param placement)."""
    from tpu_faas.client import FaaSClient
    from tpu_faas.gateway import start_gateway_thread
    from tpu_faas.store.launch import make_store, start_store_thread
    from tpu_faas.workloads import sleep_task
    from tests.test_tpu_push_e2e import _make_dispatcher
    from tests.test_workers_e2e import _spawn_worker

    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    disp = _make_dispatcher(store_handle.url)
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    worker = _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
    client = FaaSClient(gw.url)
    try:
        fid = client.register(sleep_task)
        handles = []
        for _ in range(5):
            handles.append(client.submit(fid, 0.02))
            handles.append(client.submit(fid, 0.2))
        for h in handles:
            h.result(timeout=60.0)
        est = disp.estimator
        # exactly one function learned, two exact-param variants under it
        assert len(est._fn_est) == 1
        (fn_d,) = est._fn_est
        variants = sorted(
            v for k, v in est._param_est.items()
            if k.startswith(fn_d + ":")
        )
        assert len(variants) == 2, est._param_est
        assert variants[1] / variants[0] > 3.0, variants
        # the fn-level estimate sits between the two — the collapse the
        # exact-param level exists to avoid
        assert variants[0] < est._fn_est[fn_d] < variants[1]
    finally:
        if worker.poll() is None:
            worker.kill()
            worker.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def test_ephemeral_uuid_tokens_never_persist_and_forget_on_purge():
    """ADVICE r5 (medium): a worker launched without --token mints a uuid
    per process start and flags it ephemeral on REGISTER. Its grade works
    in memory (reconnects keep it) but is NEVER written to
    WORKER_STATS_KEY — and the purge path forgets it — so ad-hoc restarts
    stop leaking one store entry per process forever. Operator/deploy
    tokens stay durable."""
    import numpy as np

    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
    from tpu_faas.sched.estimator import WORKER_STATS_KEY
    from tpu_faas.store.memory import MemoryStore

    store = MemoryStore()
    disp = TpuPushDispatcher(
        ip="127.0.0.1", port=0, store=store, max_workers=8,
        max_pending=32, max_inflight=64,
    )
    try:
        disp._handle(
            b"s-eph", "register",
            {"num_processes": 2, "token": "uuid-minted", "ephemeral": True},
        )
        disp._handle(
            b"s-dur", "register",
            {"num_processes": 2, "token": "deploy-slot0"},
        )
        row_e = disp.arrays.worker_ids[b"s-eph"]
        row_d = disp.arrays.worker_ids[b"s-dur"]
        fd = fn_digest("fn")
        for i in range(30):
            for sock, r, elapsed in (
                (b"s-dur", row_d, 1.0), (b"s-eph", row_e, 0.25),
            ):
                tid = f"t{i}-{elapsed}"
                disp._task_digest[tid] = (fd, fn_digest("p"), 8)
                disp._observe_result(
                    sock, r, tid, {"elapsed": elapsed, "status": "COMPLETED"}
                )
        # both graded in memory; the ephemeral grade is live and useful
        assert disp.estimator.speed_for("uuid-minted") > 1.5
        assert disp.estimator.is_ephemeral("uuid-minted")
        assert not disp.estimator.is_ephemeral("deploy-slot0")
        disp.estimator.maybe_persist(force=True)
        persisted = store.hgetall(WORKER_STATS_KEY)
        assert "deploy-slot0" in persisted  # durable token persisted
        assert "uuid-minted" not in persisted  # ephemeral NEVER persisted

        # purge the ephemeral worker through the real reap path: grade gone
        disp.arrays.heartbeat(b"s-eph")
        disp._reap_dead_workers([], [int(row_e)], lambda pt: None)
        assert disp.estimator.speed_for("uuid-minted") == 1.0
        # the durable worker's purge keeps its grade (unchanged behavior)
        disp._reap_dead_workers([], [int(row_d)], lambda pt: None)
        assert disp.estimator.speed_for("deploy-slot0") > 0.0
        assert "deploy-slot0" in store.hgetall(WORKER_STATS_KEY)
        assert isinstance(np.asarray(disp.arrays.worker_active), np.ndarray)
    finally:
        disp.socket.close(linger=0)


def test_restart_churn_keeps_worker_stats_key_bounded():
    """ADVICE r5 regression, restart-LOOP form: many generations of ad-hoc
    (uuid-token, ephemeral-flagged) workers registering, getting graded,
    and being purged must leave WORKER_STATS_KEY holding ONLY the durable
    deploy tokens — the store key is bounded by the operator-managed
    fleet, not by restarts-ever."""
    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
    from tpu_faas.sched.estimator import WORKER_STATS_KEY
    from tpu_faas.store.memory import MemoryStore

    store = MemoryStore()
    disp = TpuPushDispatcher(
        ip="127.0.0.1", port=0, store=store, max_workers=8,
        max_pending=32, max_inflight=64,
    )
    try:
        fd = fn_digest("churn-fn")
        for gen in range(25):
            sock = f"churn-{gen}".encode()
            disp._handle(
                sock, "register",
                {
                    "num_processes": 2,
                    "token": f"uuid-{gen:04d}" + "f" * 24,
                    "ephemeral": True,
                },
            )
            row = disp.arrays.worker_ids[sock]
            # grade it (speed observations make the entry dirty if the
            # ephemeral flag were ever dropped)
            for i in range(6):
                tid = f"g{gen}-t{i}"
                disp._task_digest[tid] = (fd, fn_digest("p"), 8)
                disp._observe_result(
                    sock, row, tid,
                    {"elapsed": 0.25, "status": "COMPLETED"},
                )
            disp.estimator.maybe_persist(force=True)
            # the process dies; the purge path forgets the token
            disp.arrays.heartbeat(sock)
            disp._reap_dead_workers([], [int(row)], lambda pt: None)
        # one durable deploy token beside the churn persists normally
        disp._handle(
            b"stable", "register",
            {"num_processes": 2, "token": "deploy-slot0"},
        )
        row = disp.arrays.worker_ids[b"stable"]
        for i in range(6):
            tid = f"stable-t{i}"
            disp._task_digest[tid] = (fd, fn_digest("p"), 8)
            disp._observe_result(
                b"stable", row, tid, {"elapsed": 0.5, "status": "COMPLETED"}
            )
        disp.estimator.maybe_persist(force=True)
        persisted = store.hgetall(WORKER_STATS_KEY)
        assert set(persisted) == {"deploy-slot0"}  # bounded: zero churn leak
        # in-memory grade table bounded by the live fleet too
        assert len(disp.estimator._speed_est) <= 2
    finally:
        disp.socket.close(linger=0)
        disp.close()


def test_push_worker_flags_minted_token_ephemeral():
    """The wire contract behind the leak fix: no --token -> ephemeral=True
    rides REGISTER; an operator token -> ephemeral=False."""
    from tpu_faas.worker.push_worker import PushWorker

    # DEALER connect doesn't bind, so construction is cheap and offline
    w = PushWorker(1, "tcp://127.0.0.1:1")
    try:
        assert w.token_is_ephemeral is True and len(w.token) == 32
    finally:
        w.pool.close()
        w.socket.close(linger=0)
    w = PushWorker(1, "tcp://127.0.0.1:1", token="deploy-slot1")
    try:
        assert w.token_is_ephemeral is False and w.token == "deploy-slot1"
    finally:
        w.pool.close()
        w.socket.close(linger=0)


def test_constant_byte_workload_falls_back_to_fn_level_grading():
    """ADVICE r5: when the byte regression DECLINES (constant param bytes,
    var_x under _REG_MIN_VAR) and the function's runtime spread is small,
    worker speed learning must degrade to the fn-level prev instead of
    stopping — a never-repeating-params workload with uniform runtimes
    used to grade NO workers at all."""
    est = RuntimeEstimator()
    d = fn_digest("const-bytes-fn")
    # params never repeat (fresh digest per task), bytes constant, runtime
    # uniform: exact-param never settles and the regression never engages
    for i in range(20):
        est.observe(d, 1.0, "baseline", param_digest=f"p{i}", param_bytes=64)
    for i in range(20, 32):
        est.observe(d, 0.5, "fast", param_digest=f"p{i}", param_bytes=64)
    assert est.speed_for("fast") > 1.05  # learning engaged via fallback
    assert est.speed_for("baseline") == pytest.approx(1.0, rel=0.2)


def test_mixed_runtime_function_still_refuses_fn_level_grading():
    """The fallback's guard: a function whose runtime genuinely varies by
    parameter (large log-space spread) must NOT grade workers against its
    fn-level mean — that mean mis-grades every worker that happens to draw
    the small (or large) params."""
    est = RuntimeEstimator()
    d = fn_digest("mixed-runtime-fn")
    runtimes = [0.1, 10.0]
    for i in range(40):
        est.observe(
            d, runtimes[i % 2], "victim", param_digest=f"q{i}", param_bytes=64
        )
    assert est.speed_for("victim") == 1.0  # never graded


def test_spread_accumulator_survives_persist_roundtrip():
    """The 6-term regression accumulator (syy included) persists and
    reloads; a restarted dispatcher keeps the fallback gate's evidence."""
    from tpu_faas.store.memory import MemoryStore

    store = MemoryStore()
    est = RuntimeEstimator(store=store, persist_period=0.0)
    d = fn_digest("persist-fn")
    for i in range(12):
        est.observe(d, 1.0, "w", param_digest=f"r{i}", param_bytes=64)
    est.maybe_persist(force=True)
    est2 = RuntimeEstimator(store=store)
    assert est2._fn_reg[d] == pytest.approx(est._fn_reg[d])
    assert est2._runtime_spread_small(d)


def test_legacy_five_term_regression_record_loads_conservatively():
    """A pre-r6 persisted record (5 accumulator terms, no syy) loads with
    the unknown-spread sentinel: the fallback stays OFF for it until the
    accumulator re-learns with fresh samples."""
    from tpu_faas.store.memory import MemoryStore

    store = MemoryStore()
    store.hset(FN_STATS_KEY, {"legacyfn": "1.5:20:20:80:20:336:84"})
    est = RuntimeEstimator(store=store)
    assert est._fn_reg["legacyfn"][5] == -1.0
    assert not est._runtime_spread_small("legacyfn")


def test_ungraded_regime_speeds_stay_prior():
    """The documented ungraded-worker regime (module docstring): params
    never repeat, bytes carry no spread, runtimes genuinely vary — so no
    estimate level is a trustworthy grading reference. The whole FLEET
    must stay at the 1.0 prior (no worker graded, nothing dirty to
    persist) while SIZE learning continues, and placement degrades to
    size-only: with equal speeds the rank kernel's pairing is
    speed-blind, so every live worker's slots are interchangeable."""
    import numpy as np

    from tpu_faas.sched.greedy import rank_match_placement

    est = RuntimeEstimator()
    d = fn_digest("ungraded-regime-fn")
    runtimes = [0.05, 5.0]
    workers = ["w0", "w1", "w2"]
    for i in range(60):
        est.observe(
            d,
            runtimes[i % 2],
            workers[i % 3],
            param_digest=f"u{i}",  # never repeats
            param_bytes=128,  # no byte spread
        )
    # fleet speeds pinned at prior; no speed ever queued for persistence
    for w in workers:
        assert est.speed_for(w) == 1.0
    assert not est._dirty_speeds
    # size learning is unaffected (the fn-level EWMA tracks the mix)
    assert est._fn_est[d] == pytest.approx(2.5, rel=0.5)
    assert not est._runtime_spread_small(d)  # the gate's reason
    # placement degradation: with all speeds at the prior, assignment is
    # exactly the size-only rank matching — permuting the (equal) speed
    # vector cannot change which workers are loaded how much
    sizes = np.asarray([5.0, 4.0, 3.0, 2.0, 1.0, 0.5], np.float32)
    valid = np.ones(6, bool)
    free = np.asarray([2, 2, 2], np.int32)
    live = np.ones(3, bool)
    speeds = np.asarray([est.speed_for(w) for w in workers], np.float32)
    a = np.asarray(
        rank_match_placement(sizes, valid, speeds, free, live, max_slots=2)
    )
    counts = np.bincount(a[a >= 0], minlength=3)
    assert (counts == 2).all()  # pure process-balancing, no speed skew


def test_loser_exec_window_never_grades_workers():
    """Speculation-plane guard (tpu_faas/spec): a hedge LOSER's execution
    window — a CANCELLED result, or any result arriving from a worker
    that is not the task's current owner — must not move worker speed
    grades. The mechanism lands with the dispatcher's result path
    (_observe_result gates on COMPLETED + current ownership; hedge
    resolution feeds only the WINNER's window); this test pins it
    independently of the hedge machinery."""
    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
    from tpu_faas.store import MemoryStore
    from tpu_faas.worker import messages as m

    disp = TpuPushDispatcher(
        ip="127.0.0.1", port=0, store=MemoryStore(),
        max_workers=8, max_pending=32, max_inflight=64,
        estimate_runtimes=True,
    )
    try:
        a = disp.arrays
        a.register(b"w0", 2)
        a.register(b"w1", 2)
        est = disp.estimator
        # settle the size estimate so speed grading is armed
        d = fn_digest("fn")
        for _ in range(5):
            est.observe(d, 1.0, "warm", param_digest="p", param_bytes=3)
        speeds_before = dict(est._speed_est)

        # a CANCELLED window from the task's own worker: never observed
        disp.store.create_task("t-cancel", "fn", "p")
        disp._task_digest["t-cancel"] = (d, fn_digest("p"), 3)
        a.inflight_add("t-cancel", 0)
        n0 = est.n_observations
        disp._handle(
            b"w0", m.RESULT,
            {"task_id": "t-cancel", "status": "CANCELLED", "result": "x",
             "elapsed": 123.0},
        )
        assert est.n_observations == n0

        # a COMPLETED window from a NON-owner (zombie/loser): never
        # observed either — only the current owner's window grades
        disp.store.create_task("t-zombie", "fn", "p")
        disp._task_digest["t-zombie"] = (d, fn_digest("p"), 3)
        a.inflight_add("t-zombie", 0)  # owned by w0
        disp._handle(
            b"w1", m.RESULT,
            {"task_id": "t-zombie", "status": "COMPLETED", "result": "y",
             "elapsed": 456.0},
        )
        assert est.n_observations == n0
        assert dict(est._speed_est) == speeds_before
    finally:
        disp.close()
