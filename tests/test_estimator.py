"""The estimation loop (sched/estimator.py): learned function runtimes and
worker speeds must make the heterogeneous placement machinery engage on the
live path with NO client hints — the round-3 verdict's top item."""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from tpu_faas.sched.estimator import FN_STATS_KEY, RuntimeEstimator, fn_digest


def test_size_ewma_converges_to_observed_runtime():
    est = RuntimeEstimator()
    d = fn_digest("payload-A")
    for _ in range(30):
        est.observe(d, 2.5, b"w0")
    assert est.size_for(d) == pytest.approx(2.5, rel=1e-3)
    # a second function learns independently
    d2 = fn_digest("payload-B")
    for _ in range(30):
        est.observe(d2, 0.1, b"w0")
    assert est.size_for(d2) == pytest.approx(0.1, rel=1e-3)
    # the prior for a NEVER-seen function sits mid-field, not at payload
    # bytes scale
    assert 0.1 <= est.default_size() <= 2.5


def test_speed_learning_separates_mixed_fleet():
    """Fast and slow workers running the same functions must separate in
    the speed estimate, with the fast/slow ratio approaching truth."""
    est = RuntimeEstimator()
    rng = np.random.default_rng(3)
    fns = [(fn_digest(f"fn{i}"), s) for i, s in enumerate([4.0, 1.0, 0.25])]
    workers = {b"fast": 4.0, b"slow": 0.5}
    for _ in range(120):
        d, size = fns[int(rng.integers(len(fns)))]
        wid = [b"fast", b"slow"][int(rng.integers(2))]
        true_speed = workers[wid]
        est.observe(d, size / true_speed, wid)
    ratio = est.speed_for(b"fast") / est.speed_for(b"slow")
    assert ratio > 3.0, ratio  # truth is 8x; well-separated is what matters
    # gauge sanity: estimates stay in the clamp band
    for wid in workers:
        assert 0.05 <= est.speed_for(wid) <= 20.0


def test_bad_observations_ignored():
    est = RuntimeEstimator()
    d = fn_digest("x")
    est.observe(d, 0.0, b"w")
    est.observe(d, -1.0, b"w")
    est.observe(d, float("nan"), b"w")
    assert est.size_for(d) is None
    assert est.n_observations == 0


def test_persistence_roundtrip_via_store():
    from tpu_faas.store.launch import make_store

    store = make_store("memory://")
    box = [0.0]
    est = RuntimeEstimator(store=store, persist_period=0.0, clock=lambda: box[0])
    d = fn_digest("persist-me")
    for _ in range(10):
        est.observe(d, 1.5, b"w0")
    box[0] = 1.0
    assert est.maybe_persist() == 1
    # a fresh estimator (dispatcher restart) loads the learned value
    est2 = RuntimeEstimator(store=store)
    assert est2.size_for(d) == pytest.approx(est.size_for(d))
    # malformed persisted entries degrade instead of wedging the load
    store.hset(FN_STATS_KEY, {"garbage": "not:numbers:at-all"})
    est3 = RuntimeEstimator(store=store)
    assert est3.size_for(d) is not None


def test_learned_estimates_beat_unhinted_placement_on_makespan():
    """The verdict's acceptance bar: a deliberately mixed fleet + mixed
    workload, NO client hints — placement driven by learned sizes/speeds
    must measurably beat the speed=1.0/size=1.0 placement on makespan
    (computed against the TRUE sizes and speeds)."""
    from tpu_faas.sched.greedy import makespan, rank_match_placement

    # truth: 4 fast workers (speed 4) + 4 slow (speed 0.5), interleaved so
    # index order carries no information; two function classes 8.0 / 1.0
    true_speeds = np.array(
        [4.0, 0.5, 4.0, 0.5, 4.0, 0.5, 4.0, 0.5], dtype=np.float32
    )
    wids = [f"w{i}".encode() for i in range(8)]
    fn_big, fn_small = "fn-big", "fn-small"
    true_size = {fn_big: 8.0, fn_small: 1.0}

    # learning phase: the estimator sees exactly what a live dispatcher
    # would — worker-measured elapsed = size / true_speed
    est = RuntimeEstimator()
    rng = np.random.default_rng(0)
    for _ in range(200):
        fn = [fn_big, fn_small][int(rng.integers(2))]
        w = int(rng.integers(8))
        noise = float(rng.uniform(0.9, 1.1))  # runtime jitter
        est.observe(
            fn_digest(fn), true_size[fn] / true_speeds[w] * noise, wids[w]
        )

    # placement phase: 16 tasks interleaved big/small, 2 slots per worker
    tasks = [fn_big, fn_small] * 8
    true_sizes = np.array([true_size[f] for f in tasks], dtype=np.float32)
    valid = np.ones(16, dtype=bool)
    free = np.full(8, 2, dtype=np.int32)
    live = np.ones(8, dtype=bool)

    learned_sizes = np.array(
        [est.size_for(fn_digest(f)) for f in tasks], dtype=np.float32
    )
    learned_speeds = np.array(
        [est.speed_for(w) for w in wids], dtype=np.float32
    )
    a_learned = np.asarray(
        rank_match_placement(
            learned_sizes, valid, learned_speeds, free, live, max_slots=2
        )
    )
    a_blind = np.asarray(
        rank_match_placement(
            np.ones(16, dtype=np.float32), valid,
            np.ones(8, dtype=np.float32), free, live, max_slots=2,
        )
    )
    ms_learned = makespan(a_learned, true_sizes, true_speeds, max_slots=2)
    ms_blind = makespan(a_blind, true_sizes, true_speeds, max_slots=2)
    # optimal here is 2.0 (big tasks alone on fast slots); blind placement
    # sends big tasks to slow workers (16.0). Require a decisive win, not
    # a lucky tie-break.
    assert ms_learned <= 0.5 * ms_blind, (ms_learned, ms_blind)
    assert ms_learned == pytest.approx(2.0, rel=0.2)


def test_dispatcher_learns_sizes_end_to_end():
    """Socket e2e: tpu-push dispatcher + real push worker, two functions
    with ~10x different runtimes, ZERO hints — the dispatcher's estimator
    must learn the ratio from the elapsed field on RESULT messages, and
    stamped batches must carry the learned sizes."""
    from tpu_faas.client import FaaSClient
    from tpu_faas.gateway import start_gateway_thread
    from tpu_faas.store.launch import make_store, start_store_thread
    from tests.test_tpu_push_e2e import _make_dispatcher
    from tests.test_workers_e2e import _spawn_worker

    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    disp = _make_dispatcher(store_handle.url)
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    worker = _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
    client = FaaSClient(gw.url)
    try:
        def slow(x):
            time.sleep(0.2)
            return x

        def quick(x):
            time.sleep(0.02)
            return x

        fid_slow = client.register(slow)
        fid_quick = client.register(quick)
        handles = []
        for i in range(6):
            handles.append(client.submit(fid_slow, i))
            handles.append(client.submit(fid_quick, i))
        for h in handles:
            h.result(timeout=60.0)
        est = disp.estimator
        assert est is not None and est.n_observations >= 10
        # find the two learned estimates; their ratio reflects ~10x truth
        sizes = sorted(est._fn_est.values())
        assert len(sizes) == 2
        assert sizes[1] / sizes[0] > 3.0, sizes
    finally:
        if worker.poll() is None:
            worker.kill()
            worker.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()
