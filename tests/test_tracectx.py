"""Distributed trace context + latency-SLO plane (tpu_faas/obs/tracectx,
tpu_faas/obs/slo): trace-id validation, span codec, the buffered
first-write-wins SpanSink (duplicates counted, outages absorbed, buffer
bounded), cross-process timeline assembly, span-hash TTL sweeping, SLO
objective parsing + multi-window burn rates, the trace book's
first-write-wins duplicate counter + terminal-labeled stage histogram,
and strict exposition conformance for every metric family this plane (and
the PR-6 HA work) added."""

from __future__ import annotations

import json
import time

import pytest

from tpu_faas.core.task import (
    FIELD_STATUS,
    FIELD_SUBMITTED_AT,
    FIELD_TRACE_ID,
)
from tpu_faas.obs import MetricsRegistry, TaskTraceBook, render
from tpu_faas.obs.expofmt import parse_exposition, require_series
from tpu_faas.obs.slo import (
    Objective,
    SLOTracker,
    objectives_from_env,
    parse_objectives,
)
from tpu_faas.obs.tracectx import (
    TRACE_AT_FIELD,
    SpanSink,
    assemble_timeline,
    decode_span,
    encode_span,
    new_trace_id,
    sweep_stale_traces,
    trace_key,
    valid_trace_id,
)
from tpu_faas.store.memory import MemoryStore


# -- trace ids + span codec --------------------------------------------------


def test_trace_id_validation():
    assert valid_trace_id(new_trace_id())
    assert valid_trace_id("deadbeef")  # 8 hex chars: minimum
    assert not valid_trace_id("DEADBEEF")  # uppercase rejected
    assert not valid_trace_id("dead")  # too short
    assert not valid_trace_id("g" * 16)  # non-hex
    assert not valid_trace_id("a" * 65)  # too long
    assert not valid_trace_id(12345)  # non-string becomes no store key
    assert not valid_trace_id(None)


def test_span_codec_round_trip_and_garbage():
    raw = encode_span(1.25, 2.5, {"outcome": "COMPLETED"})
    assert decode_span("worker:exec", raw) == (
        "worker", "exec", 1.25, 2.5, {"outcome": "COMPLETED"},
    )
    # stage names may contain ':' themselves — split once on the left
    assert decode_span("a:b:c", raw)[1] == "b:c"
    assert decode_span("nofield", raw) is None  # no process separator
    assert decode_span("p:s", "not json") is None
    assert decode_span("p:s", '{"a": 1}') is None  # wrong shape
    # non-dict attrs degrade to {} instead of breaking assembly
    assert decode_span("p:s", "[1.0, 2.0, 7]")[4] == {}


# -- SpanSink ----------------------------------------------------------------


def test_span_sink_flush_is_first_write_wins():
    store = MemoryStore()
    r = MetricsRegistry()
    sink = SpanSink(store=store, process="gateway", registry=r)
    tid = new_trace_id()
    sink.emit(tid, "admit", 10.0, 10.5)
    assert len(sink) == 1
    assert sink.flush() == 1
    # a replay re-emits the same span with DIFFERENT stamps: the original
    # must stand, the duplicate must be counted
    sink.emit(tid, "admit", 99.0, 99.9)
    assert sink.flush() == 0
    assert sink.n_duplicates == 1
    raw = store.hgetall(trace_key(tid))
    assert json.loads(raw["gateway:admit"])[0] == 10.0
    fams = parse_exposition(render([r]))
    [dup] = [
        s
        for s in fams["tpu_faas_trace_duplicate_events_total"].samples
        if s.labels.get("event") == "gateway:admit"
    ]
    assert dup.value == 1
    # the TTL stamp landed beside the spans
    assert TRACE_AT_FIELD in raw


def test_span_sink_emit_as_writes_foreign_process():
    store = MemoryStore()
    sink = SpanSink(store=store, process="dispatcher")
    tid = new_trace_id()
    sink.emit_as("worker", tid, "exec", 1.0, 2.0)
    sink.flush()
    assert "worker:exec" in store.hgetall(trace_key(tid))


class _OutageStore(MemoryStore):
    def __init__(self) -> None:
        super().__init__()
        self.down = False
        self.stamp_down = False

    def hsetnx_many(self, items):
        if self.down:
            raise ConnectionError("store down")
        return super().hsetnx_many(items)

    def hset_many(self, items):
        if self.stamp_down:
            raise ConnectionError("store down")
        return super().hset_many(items)


def test_span_sink_outage_keeps_buffer_and_retries():
    store = _OutageStore()
    sink = SpanSink(store=store, process="gateway")
    tid = new_trace_id()
    store.down = True
    sink.emit(tid, "admit", 1.0, 2.0)
    assert sink.flush() == 0  # swallowed, not raised
    assert len(sink) == 1  # batch restored
    store.down = False
    assert sink.flush() == 1
    assert "gateway:admit" in store.hgetall(trace_key(tid))


def test_span_sink_stamp_failure_does_not_fabricate_duplicates():
    """A TTL-stamp write failing AFTER its spans landed must retry ONLY
    the stamp: restoring the whole batch would re-HSETNX landed spans on
    the next flush and spike the duplicate counter — the replay-storm
    alarm — from a single store hiccup."""
    store = _OutageStore()
    sink = SpanSink(store=store, process="gateway")
    tid = new_trace_id()
    store.stamp_down = True
    sink.emit(tid, "admit", 1.0, 2.0)
    assert sink.flush() == 1  # spans landed despite the stamp failure
    assert len(sink) == 0  # NOT restored
    assert TRACE_AT_FIELD not in store.hgetall(trace_key(tid))
    # the parked stamp keeps the sink dirty: flush-gates that check the
    # buffer alone would strand it (an unstamped hash never sweeps)
    assert sink.dirty
    store.stamp_down = False
    assert sink.flush() == 0  # nothing new to write...
    assert TRACE_AT_FIELD in store.hgetall(trace_key(tid))  # ...stamp retried
    assert sink.n_duplicates == 0  # and no duplicates were fabricated
    assert not sink.dirty


def test_span_sink_buffer_bounded_drops_oldest():
    r = MetricsRegistry()
    sink = SpanSink(
        store=MemoryStore(), process="gateway", registry=r, max_buffer=4
    )
    for i in range(7):
        sink.emit(new_trace_id(), f"s{i}", 1.0, 2.0)
    assert len(sink) == 4
    assert sink.n_dropped == 3
    # the SURVIVORS are the newest emits
    assert {s.field for s in sink._buf} == {
        "gateway:s3", "gateway:s4", "gateway:s5", "gateway:s6",
    }


# -- assembly ----------------------------------------------------------------


def _make_task(store, task_id: str, trace_id: str | None) -> None:
    fields = {FIELD_STATUS: "COMPLETED", FIELD_SUBMITTED_AT: "100.0"}
    if trace_id:
        fields[FIELD_TRACE_ID] = trace_id
    store.hset(task_id, fields)


def test_assemble_timeline_orders_spans_and_reports_gaps():
    store = MemoryStore()
    tid = new_trace_id()
    _make_task(store, "t1", tid)
    sink = SpanSink(store=store, process="gateway")
    sink.emit(tid, "admit", 100.0, 100.2)
    sink.emit(tid, "observe", 101.0, 101.5)
    sink.emit_as("dispatcher", tid, "queue", 100.2, 100.6)
    sink.emit_as("worker", tid, "exec", 100.6, 100.8)
    sink.flush()
    tl = assemble_timeline(store, "t1")
    assert tl["trace_id"] == tid
    assert [s["stage"] for s in tl["spans"]] == [
        "admit", "queue", "exec", "observe",
    ]
    assert tl["processes"] == ["gateway", "dispatcher", "worker"]
    assert tl["n_stages"] == 4
    assert tl["total_s"] == pytest.approx(1.5)
    # covered: [100.0,100.8] + [101.0,101.5] -> 0.2 s gap before observe
    assert tl["uncovered_s"] == pytest.approx(0.2)


def test_assemble_timeline_untraced_and_unknown():
    store = MemoryStore()
    _make_task(store, "plain", None)
    tl = assemble_timeline(store, "plain")
    assert tl is not None and tl["trace_id"] is None and tl["spans"] == []
    assert assemble_timeline(store, "ghost") is None


def test_assemble_timeline_skips_foreign_garbage_fields():
    store = MemoryStore()
    tid = new_trace_id()
    _make_task(store, "t1", tid)
    store.hset(
        trace_key(tid),
        {
            "gateway:admit": encode_span(1.0, 2.0, None),
            "nonsense": "not a span",
            "p:broken": "{{{",
            TRACE_AT_FIELD: "1.0",
        },
    )
    tl = assemble_timeline(store, "t1")
    assert tl["n_stages"] == 1  # garbage skipped, assembly survives


def test_sweep_stale_traces_uses_t0_stamp():
    store = MemoryStore()
    now = time.time()
    for name, stamp in (
        ("old", repr(now - 100.0)),
        ("fresh", repr(now - 1.0)),
        ("garbage", "not-a-float"),
    ):
        store.hset(trace_key(name), {TRACE_AT_FIELD: stamp, "p:s": "x"})
    store.hset(trace_key("unstamped"), {"p:s": "x"})
    stale = sweep_stale_traces(store, store.keys(), ttl=50.0, now=now)
    assert stale == [trace_key("old")]
    # non-trace keys are never touched
    store.hset("task-1", {FIELD_STATUS: "COMPLETED"})
    assert "task-1" not in sweep_stale_traces(
        store, store.keys(), ttl=0.0, now=now + 1e6
    )


def test_sweep_stale_traces_spares_live_tasks():
    """An aged trace hash whose task is still QUEUED/RUNNING must NOT be
    swept (its stamp only refreshes when new spans flush — a task queued
    past the TTL would lose its early spans mid-flight); terminal and
    already-swept tasks collect normally."""
    from tpu_faas.obs.tracectx import TRACE_TASK_FIELD

    store = MemoryStore()
    now = time.time()
    old = repr(now - 100.0)
    cases = (
        ("live-q", "t-q", "QUEUED"),
        ("live-r", "t-r", "RUNNING"),
        ("done", "t-d", "COMPLETED"),
        ("gone", "t-gone", None),  # record already swept
    )
    for name, tid, status in cases:
        store.hset(
            trace_key(name),
            {TRACE_AT_FIELD: old, TRACE_TASK_FIELD: tid, "p:s": "x"},
        )
        if status is not None:
            store.hset(tid, {FIELD_STATUS: status})
    stale = sweep_stale_traces(store, store.keys(), ttl=50.0, now=now)
    assert sorted(stale) == [trace_key("done"), trace_key("gone")]


# -- SLO objectives + tracker ------------------------------------------------


def test_parse_objectives_good_and_bad():
    objs = parse_objectives(
        "fast=total:0.25:0.99, queue=queue_wait:0.1:0.95,"
    )
    assert objs == [
        Objective("fast", "total", 0.25, 0.99),
        Objective("queue", "queue_wait", 0.1, 0.95),
    ]
    for bad in (
        "noequals",
        "x=only_two:0.5",
        "x=s:nan:0.99",
        "x=s:0.5:1.5",  # target out of (0,1)
        "x=s:-1:0.5",  # non-positive threshold
    ):
        with pytest.raises(ValueError):
            parse_objectives(bad)


def test_objectives_from_env(monkeypatch):
    default = [Objective("d", "total", 1.0, 0.5)]
    monkeypatch.delenv("TPU_FAAS_SLO", raising=False)
    assert objectives_from_env(default) == default
    monkeypatch.setenv("TPU_FAAS_SLO", "mine=execution:0.5:0.9")
    assert objectives_from_env(default) == [
        Objective("mine", "execution", 0.5, 0.9)
    ]


class _FakeHist:
    """Synthetic SLO source: fixed uppers, mutable per-bucket counts
    (non-cumulative, overflow slot last — _HistogramChild.snapshot's
    shape)."""

    def __init__(self) -> None:
        self.uppers = (0.1, 0.25, 1.0)
        self.counts = [0, 0, 0, 0]

    def source(self, stage: str):
        if stage != "total":
            return None
        return self.uppers, list(self.counts)


def test_slo_tracker_burn_rate_math():
    clock = [0.0]
    hist = _FakeHist()
    r = MetricsRegistry()
    tracker = SLOTracker(
        r,
        [Objective("fast", "total", 0.25, 0.9)],
        hist.source,
        clock=lambda: clock[0],
    )
    # 8 good (<= 0.25 s), 2 bad -> ratio 0.8, burn (1-0.8)/(1-0.9) = 2.0
    hist.counts = [5, 3, 1, 1]
    clock[0] = 10.0
    snap = tracker.snapshot()
    w = snap["objectives"][0]["windows"]["5m"]
    assert w["events"] == 10
    assert w["good_ratio"] == pytest.approx(0.8)
    assert w["burn_rate"] == pytest.approx(2.0)
    # gauges agree at collect time
    tracker.collect()
    fams = parse_exposition(render([r]))
    burn = {
        s.labels["window"]: s.value
        for s in fams["tpu_faas_slo_burn_rate"].samples
        if s.labels["objective"] == "fast"
    }
    assert burn["5m"] == pytest.approx(2.0)
    assert fams["tpu_faas_slo_target_ratio"].samples[0].value == 0.9


def test_slo_tracker_threshold_between_buckets_is_conservative():
    clock = [0.0]
    hist = _FakeHist()
    tracker = SLOTracker(
        MetricsRegistry(),
        # threshold 0.5 sits BETWEEN the 0.25 and 1.0 boundaries: the
        # straddling bucket counts BAD, so good = the first two buckets
        [Objective("mid", "total", 0.5, 0.9)],
        hist.source,
        clock=lambda: clock[0],
    )
    hist.counts = [4, 4, 2, 0]
    clock[0] = 10.0
    w = tracker.snapshot()["objectives"][0]["windows"]["5m"]
    assert w["good_ratio"] == pytest.approx(0.8)  # 8/10, not 10/10


def test_slo_tracker_windows_age_out_old_events():
    clock = [0.0]
    hist = _FakeHist()
    tracker = SLOTracker(
        MetricsRegistry(),
        [Objective("fast", "total", 0.25, 0.9)],
        hist.source,
        clock=lambda: clock[0],
    )
    hist.counts = [0, 0, 0, 10]  # 10 bad events, early
    clock[0] = 100.0
    tracker.update()
    # ~50 min later, no new traffic: the events aged out of the 5 m
    # window but still sit inside the 1 h one
    clock[0] = 3000.0
    tracker.update()
    snap = tracker.snapshot()
    w5 = snap["objectives"][0]["windows"]["5m"]
    assert w5["events"] == 0 and w5["good_ratio"] == 1.0
    w1h = snap["objectives"][0]["windows"]["1h"]
    assert w1h["events"] == 10 and w1h["good_ratio"] == 0.0


def test_slo_tracker_no_source_stays_quiet():
    tracker = SLOTracker(
        MetricsRegistry(),
        [Objective("ghost", "nope", 0.25, 0.9)],
        lambda stage: None,
    )
    w = tracker.snapshot()["objectives"][0]["windows"]["5m"]
    assert w["events"] == 0 and w["burn_rate"] == 0.0


def test_slo_tracker_source_present_flags_inert_objectives():
    """A stage name that never matches a histogram (typo, or a stage
    foreign to this process under a fleet-wide TPU_FAAS_SLO) must be
    VISIBLY inert: quiet burn gauges alone read as 'perfectly green'."""
    clock = [0.0]
    hist = _FakeHist()
    r = MetricsRegistry()
    tracker = SLOTracker(
        r,
        [
            Objective("live", "total", 0.25, 0.9),
            Objective("typo", "totall", 0.25, 0.9),
        ],
        hist.source,
        clock=lambda: clock[0],
    )
    clock[0] = 10.0
    snap = tracker.snapshot()
    by_name = {o["name"]: o for o in snap["objectives"]}
    assert by_name["live"]["source_present"] is True
    assert by_name["typo"]["source_present"] is False
    fams = parse_exposition(render([r]))
    present = {
        s.labels["objective"]: s.value
        for s in fams["tpu_faas_slo_source_present"].samples
    }
    assert present == {"live": 1.0, "typo": 0.0}


# -- trace book: first-write-wins + terminal labels + trace ids --------------


def test_trace_book_duplicate_events_counted():
    r = MetricsRegistry()
    book = TaskTraceBook(r)
    book.note("t1", "intake", ts=1.0)
    book.note("t1", "intake", ts=2.0)  # replayed announce
    book.note("t1", "intake", ts=3.0)
    fams = parse_exposition(render([r]))
    [dup] = [
        s
        for s in fams["tpu_faas_trace_duplicate_events_total"].samples
        if s.labels.get("event") == "intake"
    ]
    assert dup.value == 2
    # and the original stamp stood
    assert book.timeline("t1")["events"]["intake"] == 1.0


def test_trace_book_terminal_label_separates_populations():
    r = MetricsRegistry()
    book = TaskTraceBook(r)
    for tid, outcome in (("a", "COMPLETED"), ("b", "expired")):
        book.note(tid, "announced", ts=1.0)
        book.note(tid, "scheduled", ts=2.0)
        book.finish(tid, outcome=outcome, ts=3.0)
    fams = parse_exposition(render([r]))
    counts = {
        (s.labels["stage"], s.labels["terminal"]): s.value
        for s in fams["tpu_faas_task_stage_seconds"].samples
        if s.name.endswith("_count")
    }
    assert counts[("queue_wait", "COMPLETED")] == 1
    assert counts[("queue_wait", "expired")] == 1
    # the SLO source sees ONLY the COMPLETED population by default —
    # shed tasks must not burn the latency error budget
    uppers, total = book.stage_snapshot("queue_wait")
    assert sum(total) == 1
    _, everything = book.stage_snapshot("queue_wait", terminal=None)
    assert sum(everything) == 2
    assert book.stage_snapshot("no_such_stage") is None


def test_trace_book_routine_retry_restamps_not_counted_as_duplicates():
    """The scheduled/sent re-stamps of a reclaimed task's redispatch are
    normal at-least-once operation (visible as `retries`), NOT a replay
    storm — counting them would page operators on steady worker churn."""
    r = MetricsRegistry()
    book = TaskTraceBook(r)
    book.note("t1", "scheduled", ts=1.0)
    book.note("t1", "sent", ts=1.1)
    book.note_retry("t1")
    # redispatch after reclaim: caller knows it's routine
    book.note("t1", "scheduled", ts=2.0, count_dup=False)
    book.note("t1", "sent", ts=2.1, count_dup=False)
    # a genuine replay duplicate still counts
    book.note("t1", "intake", ts=1.0)
    book.note("t1", "intake", ts=3.0)
    fams = parse_exposition(render([r]))
    dups = {
        s.labels["event"]: s.value
        for s in fams["tpu_faas_trace_duplicate_events_total"].samples
        if s.value > 0
    }
    assert dups == {"intake": 1}
    # first stamps stood either way, and the retry is on the record
    tl = book.timeline("t1")
    assert tl["events"]["scheduled"] == 1.0 and tl["events"]["sent"] == 1.1
    assert tl["retries"] == 1


def test_trace_book_first_completion_wins_on_replayed_announce():
    """A replayed announce (store-failover re-arm) for a task whose rich
    closed record still sits in the ring opens a stub timeline; closing
    that stub must be DISCARDED — not clobber the record, not double-count
    the completion — and counted as a suppressed 'finished' duplicate."""
    r = MetricsRegistry()
    book = TaskTraceBook(r)
    book.note("t1", "announced", ts=1.0)
    book.note("t1", "intake", ts=1.1)
    book.note("t1", "scheduled", ts=1.2)
    book.finish("t1", outcome="COMPLETED", ts=2.0)
    assert book.n_completed == 1
    rich = book.timeline("t1")
    assert "intake" in rich["events"]
    # the replayed announce re-opens a stub, then the terminal-record skip
    # path closes it again
    book.note("t1", "announced", ts=50.0)
    book.finish("t1", outcome="COMPLETED", ts=50.1)
    assert book.n_completed == 1  # not double-counted
    assert book.timeline("t1") is rich  # record not clobbered
    assert all(rec is rich for rec in book.recent() if rec["task_id"] == "t1")
    fams = parse_exposition(render([r]))
    [dup] = [
        s
        for s in fams["tpu_faas_trace_duplicate_events_total"].samples
        if s.labels.get("event") == "finished"
    ]
    assert dup.value == 1


def test_note_dispatch_attaches_trace_for_rescan_adopted_task():
    """A rescan-adopted task never passes _note_intake: its timeline is
    opened by note_dispatch's 'scheduled' stamp, and the trace id must
    attach THERE (note first, then note_trace — note_trace only attaches
    to an open timeline), or the close hook emits no spans for it."""
    from tpu_faas.dispatch.base import PendingTask
    from tpu_faas.dispatch.local import LocalDispatcher

    disp = LocalDispatcher(store=MemoryStore(), num_workers=1)
    try:
        task = PendingTask(
            task_id="adopted-1",
            fn_payload="f",
            param_payload="p",
            trace_id="aabbccdd",
        )
        assert disp.traces.timeline("adopted-1") is None  # no intake ran
        disp.note_dispatch(task)
        tl = disp.traces.timeline("adopted-1")
        assert tl is not None and "scheduled" in tl["events"]
        assert tl["trace_id"] == "aabbccdd"
    finally:
        disp.close()


def test_trace_book_carries_trace_id_to_close_hook():
    book = TaskTraceBook(MetricsRegistry())
    closed: list[dict] = []
    book.on_close = closed.append
    book.note("t1", "intake", ts=1.0)
    book.note_trace("t1", "aabbccdd")
    book.note_trace("t1", "ffffffff")  # first write wins here too
    assert book.timeline("t1")["trace_id"] == "aabbccdd"
    book.finish("t1", outcome="COMPLETED", ts=2.0)
    assert closed and closed[0]["trace_id"] == "aabbccdd"
    # untraced tasks close with trace_id None
    book.note("t2", "intake", ts=1.0)
    book.finish("t2", outcome="COMPLETED", ts=2.0)
    assert closed[1]["trace_id"] is None
    # discard forgets the trace id with the timeline
    book.note("t3", "intake", ts=1.0)
    book.note_trace("t3", "aaaaaaaa")
    book.discard("t3")
    assert book.timeline("t3") is None


def test_gateway_e2e_slo_source_filters_to_completed():
    """The gateway's SLO data source must mirror the dispatcher policy:
    shed (EXPIRED) and cancelled deliveries land in their own terminal
    series and never reach the burn-rate math — deadline shedding under
    overload is intended behavior, not an SLO violation."""
    from tpu_faas.gateway.app import GatewayContext

    ctx = GatewayContext(store=MemoryStore(), trace=False)
    base = {FIELD_SUBMITTED_AT: "100.0", "finished_at": "100.1"}
    ctx.note_result_observed("ok", {FIELD_STATUS: "COMPLETED", **base})
    ctx.note_result_observed("shed", {FIELD_STATUS: "EXPIRED", **base})
    ctx.note_result_observed("cxl", {FIELD_STATUS: "CANCELLED", **base})
    uppers, counts = ctx._e2e_snapshot("submit_to_finish")
    assert sum(counts) == 1  # COMPLETED only
    fams = parse_exposition(render([ctx.metrics]))
    by_terminal = {
        s.labels["terminal"]: s.value
        for s in fams["tpu_faas_task_e2e_seconds"].samples
        if s.name.endswith("_count")
        and s.labels["phase"] == "submit_to_finish"
    }
    # every population is still measured — just separately
    assert by_terminal["COMPLETED"] == 1
    assert by_terminal["EXPIRED"] == 1
    assert by_terminal["CANCELLED"] == 1


def test_skipped_timeline_close_normalizes_expired_label():
    """A drained announce for an already-EXPIRED record closes with
    terminal="expired" — the same label the shed_if_expired drop sites
    use — not the raw record status, which would split one shed
    population across two label vocabularies."""
    from tpu_faas.dispatch.local import LocalDispatcher

    disp = LocalDispatcher(store=MemoryStore(), num_workers=1)
    try:
        disp.traces.note("t1", "announced", ts=1.0)
        disp._close_skipped_timeline("t1", "EXPIRED")
        assert disp.traces.timeline("t1")["outcome"] == "expired"
        # non-expired terminals keep the record vocabulary
        disp.traces.note("t2", "announced", ts=1.0)
        disp._close_skipped_timeline("t2", "CANCELLED")
        assert disp.traces.timeline("t2")["outcome"] == "CANCELLED"
    finally:
        disp.close()


# -- exposition conformance for every family added since PR 3 ----------------


def test_new_families_render_strict_exposition():
    """Every series this PR (slo/trace/e2e) and PR 6 (HA gauges) added,
    rendered and strict-parsed from REAL constructors — the conformance
    gate that keeps /metrics scrapeable as families accumulate."""
    from tpu_faas.gateway.app import GatewayContext

    ctx = GatewayContext(store=MemoryStore(), trace=True)
    # traffic through the new surfaces so samples carry real values
    ctx.note_result_observed(
        "t1",
        {
            FIELD_STATUS: "COMPLETED",
            FIELD_SUBMITTED_AT: "100.0",
            "finished_at": "100.2",
            FIELD_TRACE_ID: new_trace_id(),
        },
    )
    ctx.m_store_role.set(1.0)
    ctx.m_repl_lag.set(3.0)
    fams = parse_exposition(render([ctx.metrics]))
    missing = require_series(
        fams,
        [
            # this PR's families
            "tpu_faas_task_e2e_seconds",
            "tpu_faas_slo_burn_rate",
            "tpu_faas_slo_good_ratio",
            "tpu_faas_slo_target_ratio",
            "tpu_faas_slo_threshold_seconds",
            "tpu_faas_slo_source_present",
            "tpu_faas_trace_duplicate_events_total",
            "tpu_faas_trace_spans_dropped_total",
            # PR 6's HA gauges
            "tpu_faas_gateway_store_role",
            "tpu_faas_store_replication_lag_commands",
            "tpu_faas_gateway_store_up",
        ],
    )
    assert not missing, missing
    e2e_counts = {
        s.labels["phase"]: s.value
        for s in fams["tpu_faas_task_e2e_seconds"].samples
        if s.name.endswith("_count")
    }
    assert e2e_counts["submit_to_finish"] == 1
    assert e2e_counts["submit_to_observe"] == 1
    # repeat delivery is deduped
    ctx.note_result_observed(
        "t1", {FIELD_STATUS: "COMPLETED", FIELD_SUBMITTED_AT: "100.0"}
    )
    fams = parse_exposition(render([ctx.metrics]))
    e2e_counts = {
        s.labels["phase"]: s.value
        for s in fams["tpu_faas_task_e2e_seconds"].samples
        if s.name.endswith("_count")
    }
    assert e2e_counts["submit_to_observe"] == 1


def test_dispatcher_scrape_carries_slo_and_trace_families():
    from tpu_faas.dispatch.local import LocalDispatcher

    disp = LocalDispatcher(store=MemoryStore(), num_workers=1)
    try:
        fams = parse_exposition(disp.render_metrics())
        missing = require_series(
            fams,
            [
                "tpu_faas_slo_burn_rate",
                "tpu_faas_slo_threshold_seconds",
                "tpu_faas_slo_source_present",
                "tpu_faas_trace_duplicate_events_total",
                "tpu_faas_trace_spans_dropped_total",
                "tpu_faas_task_stage_seconds",
                "tpu_faas_dispatcher_failover_rearms_total",
            ],
        )
        assert not missing, missing
    finally:
        disp.close()
