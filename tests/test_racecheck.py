"""Race detector (store/racecheck.py): unit tests of the lifecycle state
machine, offline trace replay, and an end-to-end run under the monitor.

The reference has no race detection (SURVEY §5.2 — safety "by construction");
this framework's re-dispatch upgrade creates a real zombie-vs-replacement
race, so the protocol is machine-checked instead."""

import threading

import pytest

from tpu_faas.core.executor import pack_params
from tpu_faas.core.serialize import serialize
from tpu_faas.dispatch.local import LocalDispatcher
from tpu_faas.store import MemoryStore
from tpu_faas.store.racecheck import RaceCheckStore, RaceMonitor, check_trace
from tpu_faas.workloads import arithmetic

S, R = "status", "result"


def _mon() -> RaceMonitor:
    return RaceMonitor()


def _lifecycle(m: RaceMonitor, tid: str = "t", actor: str = "d") -> None:
    m.observe("gw", "create", tid, {S: "QUEUED", R: "None"})
    m.observe(actor, "status", tid, {S: "RUNNING"})
    m.observe(actor, "finish", tid, {S: "COMPLETED", R: "42"})


def test_clean_lifecycle_has_no_violations():
    m = _mon()
    _lifecycle(m)
    m.assert_clean()
    assert m.unfinished() == []


def test_terminal_overwrite_is_error():
    m = _mon()
    _lifecycle(m)
    m.observe("zombie", "finish", "t", {S: "COMPLETED", R: "43"})
    assert [v.kind for v in m.errors] == ["terminal-overwrite"]
    assert "zombie" in str(m.errors[0])


def test_idempotent_terminal_rewrite_is_clean():
    """Same terminal status + same result payload: benign (a retried store
    write), not a race."""
    m = _mon()
    _lifecycle(m)
    m.observe("d", "finish", "t", {S: "COMPLETED", R: "42"})
    m.assert_clean()


def test_terminal_to_running_is_error():
    m = _mon()
    _lifecycle(m)
    m.observe("d", "status", "t", {S: "RUNNING"})
    assert [v.kind for v in m.errors] == ["terminal-overwrite"]


def test_create_as_running_is_illegal():
    m = _mon()
    m.observe("d", "status", "t", {S: "RUNNING"})
    assert [v.kind for v in m.errors] == ["illegal-transition"]


def test_double_dispatch_warns_but_declared_redispatch_does_not():
    m = _mon()
    m.observe("gw", "create", "t", {S: "QUEUED"})
    m.observe("d", "status", "t", {S: "RUNNING"})
    m.observe("d", "status", "t", {S: "RUNNING"})  # undeclared: warn
    assert [v.kind for v in m.warnings] == ["double-dispatch"]

    m2 = _mon()
    m2.observe("gw", "create", "t", {S: "QUEUED"})
    m2.observe("d", "status", "t", {S: "RUNNING"})
    m2.expect_redispatch("t")
    m2.observe("d", "status", "t", {S: "RUNNING"})  # declared: clean
    m2.assert_clean()


def test_result_without_dispatch_warns():
    m = _mon()
    m.observe("gw", "create", "t", {S: "QUEUED"})
    m.observe("d", "finish", "t", {S: "COMPLETED", R: "1"})
    assert [v.kind for v in m.warnings] == ["result-without-dispatch"]
    assert not m.errors


def test_unfinished_reports_lost_tasks_only():
    m = _mon()
    m.observe("gw", "create", "lost", {S: "QUEUED"})
    m.observe("gw", "status", "lost", {S: "RUNNING"})
    _lifecycle(m, "done")
    # a status-less key (function-registry hash) is not a task
    m.observe("gw", "status", "fn-registry-key", {"payload": "blob"})
    assert m.unfinished() == ["lost"]


def test_strict_mode_flags_unknown_task_writes():
    m = RaceMonitor(strict=True)
    m.observe("d", "status", "t", {S: "RUNNING"})
    kinds = {v.kind for v in m.warnings}
    assert "unknown-task" in kinds


def test_flush_resets_state():
    m = _mon()
    _lifecycle(m)
    m.observe_flush("bench")
    _lifecycle(m)  # same task id, fresh lifecycle: clean
    m.assert_clean()


def test_offline_replay_reproduces_verdict():
    m = _mon()
    _lifecycle(m)
    m.observe("zombie", "finish", "t", {S: "FAILED", R: "boom"})
    replayed = check_trace(list(m.events))
    assert [v.kind for v in replayed] == [v.kind for v in m.violations]
    assert any(v.kind == "terminal-overwrite" for v in replayed)


def test_live_events_deque_roundtrips_through_check_trace():
    """Post-mortem contract: feeding a live monitor's ``events`` deque (as
    recorded through the RaceCheckStore write path, not hand-built observe
    calls) into check_trace reproduces the online verdict EXACTLY — same
    kinds, severities, task ids and detail strings, in the same order — for
    a history mixing clean lifecycles, errors, warnings, deletes and a
    flush. (Declared re-dispatches are not part of the event stream, so this
    holds only for undeclared histories — the offline replay is strictly
    more suspicious than the live run, never less.)"""
    monitor = RaceMonitor()
    store = RaceCheckStore(MemoryStore(), monitor, actor="gw")

    # clean lifecycle + consume
    store.hset("a", {S: "QUEUED", R: "None"})
    store.hset("a", {S: "RUNNING"})
    store.hset("a", {S: "COMPLETED", R: "1"})
    store.delete("a")
    # terminal-overwrite error (zombie second result)
    store.hset("b", {S: "QUEUED"})
    store.hset("b", {S: "RUNNING"})
    store.hset("b", {S: "COMPLETED", R: "2"})
    store.hset("b", {S: "FAILED", R: "boom"})
    # illegal-transition error + result-without-dispatch warning
    store.hset("c", {S: "RUNNING"})
    store.hset("d", {S: "QUEUED"})
    store.hset("d", {S: "COMPLETED", R: "4"})
    # double-dispatch warning (undeclared RUNNING -> RUNNING)
    store.hset("e", {S: "QUEUED"})
    store.hset("e", {S: "RUNNING"})
    store.hset("e", {S: "RUNNING"})
    # flush resets the model mid-history; writes after it must re-validate
    store.flush()
    store.hset("f", {S: "QUEUED"})
    store.hset("f", {S: "RUNNING"})

    assert monitor.errors and monitor.warnings  # the scenario is non-trivial
    replayed = check_trace(monitor.events)  # the deque itself, not a copy

    def signature(violations):
        return [(v.kind, v.severity, v.task_id, v.detail) for v in violations]

    assert signature(replayed) == signature(monitor.violations)
    # the replayed violations carry replayed events for the same task
    for live, offline in zip(monitor.violations, replayed):
        assert [e.task_id for e in live.events] == [
            e.task_id for e in offline.events
        ]


def test_monitor_is_thread_safe_under_concurrent_writers():
    m = _mon()

    def writer(i: int) -> None:
        for j in range(200):
            tid = f"t-{i}-{j}"
            m.observe("gw", "create", tid, {S: "QUEUED"})
            m.observe(f"d{i}", "status", tid, {S: "RUNNING"})
            m.observe(f"d{i}", "finish", tid, {S: "COMPLETED", R: "ok"})

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    m.assert_clean()
    assert m.unfinished() == []
    # seq numbers are unique and dense
    seqs = [e.seq for e in m.events]
    assert len(set(seqs)) == len(seqs)


# -- store wrapper + live dispatcher under the monitor ----------------------


def test_wrapped_store_classifies_ops_and_first_wins_guard_holds():
    inner = MemoryStore()
    m = _mon()
    gw = RaceCheckStore(inner, m, actor="gateway")
    d = RaceCheckStore(inner, m, actor="dispatcher")

    gw.create_task("t", serialize(arithmetic), pack_params(5))
    d.set_status("t", "RUNNING")
    d.finish_task("t", "COMPLETED", "first")
    # zombie result behind the first_wins guard: write is suppressed before
    # the store, so the monitor correctly observes nothing
    d.finish_task("t", "FAILED", "late-zombie", first_wins=True)
    m.assert_clean()
    assert inner.get_result("t") == ("COMPLETED", "first")

    # the same write WITHOUT the guard is the bug the detector exists for
    d.finish_task("t", "FAILED", "late-zombie")
    assert [v.kind for v in m.errors] == ["terminal-overwrite"]


def test_local_dispatcher_e2e_is_race_clean():
    inner = MemoryStore()
    m = _mon()
    disp = LocalDispatcher(
        num_workers=2, store=RaceCheckStore(inner, m, actor="dispatcher")
    )
    client_store = RaceCheckStore(inner, m, actor="gateway")
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    try:
        for i in range(10):
            client_store.create_task(
                f"t{i}", serialize(arithmetic), pack_params(100 + i)
            )
        import time

        deadline = time.monotonic() + 60
        while m.unfinished() and time.monotonic() < deadline:
            time.sleep(0.02)
    finally:
        disp.stop()
        t.join(timeout=15)
    assert m.unfinished() == []
    m.assert_clean()


def test_non_enum_status_is_flagged_not_crashed():
    """A corrupt status string must produce violations, never a ValueError
    out of observe() (the monitor is a detector, not an enforcer)."""
    m = _mon()
    m.observe("gw", "create", "t", {S: "QUEUED"})
    m.observe("x", "status", "t", {S: "BOGUS"})
    m.observe("d", "status", "t", {S: "RUNNING"})  # from BOGUS: also illegal
    m.observe("d", "finish", "t", {S: "COMPLETED", R: "1"})
    kinds = [v.kind for v in m.errors]
    assert "illegal-transition" in kinds
    # and the task tracker still works
    assert m.unfinished() == []


# -- queue-deadline expiry (EXPIRED) -----------------------------------------


def test_queued_to_expired_is_clean_and_terminal():
    m = _mon()
    m.observe("gw", "create", "t", {S: "QUEUED"})
    m.observe("d", "status", "t", {S: "EXPIRED"})
    m.assert_clean()
    assert m.unfinished() == []


def test_running_to_expired_is_error():
    """The shed is QUEUED-only by protocol: an EXPIRED write over a
    dispatched task is exactly the bug class this monitor exists for."""
    m = _mon()
    m.observe("gw", "create", "t", {S: "QUEUED"})
    m.observe("d", "status", "t", {S: "RUNNING"})
    m.observe("d", "status", "t", {S: "EXPIRED"})
    kinds = [v.kind for v in m.errors]
    assert kinds == ["illegal-transition"]


def test_result_over_expired_is_late_race_warning():
    """A zombie's genuine result landing over a (lost-race) EXPIRED record
    is truth overwriting a stale never-ran claim — warning, like the
    cancel analog."""
    m = _mon()
    m.observe("gw", "create", "t", {S: "QUEUED"})
    m.observe("d", "status", "t", {S: "EXPIRED"})
    m.observe("d", "finish", "t", {S: "COMPLETED", R: "42"})
    assert m.errors == []
    assert [v.kind for v in m.warnings] == ["late-cancel-race"]


def test_cancel_expire_cross_writes_warn_not_error():
    """A cancel racing a deadline shed: both assert never-ran; whichever
    stands tells the client the truth."""
    m = _mon()
    m.observe("gw", "create", "t", {S: "QUEUED"})
    m.observe("gw", "status", "t", {S: "CANCELLED"})
    m.observe("d", "status", "t", {S: "EXPIRED"})
    assert m.errors == []
    assert [v.kind for v in m.warnings] == ["cancel-expire-race"]


def test_expire_clobbering_landed_result_is_repairable_warning():
    m = _mon()
    m.observe("gw", "create", "t", {S: "QUEUED"})
    m.observe("d", "status", "t", {S: "RUNNING"})
    m.observe("d", "finish", "t", {S: "COMPLETED", R: "42"})
    m.observe("d", "status", "t", {S: "EXPIRED"})
    assert m.errors == []
    assert [v.kind for v in m.warnings] == ["cancel-after-finish"]


def test_keyed_create_via_setnx_is_observed_as_create():
    """create_task_if_absent claims QUEUED through setnx_field; the
    wrapped store must surface that claim as the task's create, or every
    keyed submit's later RUNNING reads as None -> RUNNING."""
    m = _mon()
    store = RaceCheckStore(MemoryStore(), m, actor="gw")
    assert store.create_task_if_absent("t", "F", "P")
    store.set_status("t", "RUNNING", extra_fields={"lease_at": "1"})
    store.finish_task("t", "COMPLETED", "42")
    m.assert_clean(allow_warnings=True)
    assert m.errors == []


# -- speculation plane: declared hedge replicas (tpu_faas/spec) --------------


def test_declared_replica_second_running_is_clean():
    """A hedge replica's second RUNNING mark rides expect_replica exactly
    like a reclaim rides expect_redispatch: declared = clean, undeclared =
    the double-dispatch warning this monitor exists to raise."""
    m = _mon()
    m.observe("gw", "create", "t", {S: "QUEUED"})
    m.observe("d", "status", "t", {S: "RUNNING"})
    m.expect_replica("t")
    m.observe("d", "status", "t", {S: "RUNNING"})  # the replica's mark
    m.assert_clean()


def test_hedge_loser_cancelled_after_winner_is_warning_not_error():
    """The loser's CANCEL-kill confirmation landing after the winner's
    terminal write: with the replica declared, the monitor attributes it
    (hedge-loser-cancelled, warning) instead of the generic repairable
    cancel-after-finish — and it is never an error."""
    m = _mon()
    m.observe("gw", "create", "t", {S: "QUEUED"})
    m.observe("d", "status", "t", {S: "RUNNING"})
    m.expect_replica("t")
    m.observe("d", "status", "t", {S: "RUNNING"})
    m.observe("d", "finish", "t", {S: "COMPLETED", R: "42"})  # winner
    m.observe("d", "finish", "t", {S: "CANCELLED", R: "kill"})  # loser
    assert not m.errors
    assert [v.kind for v in m.warnings] == ["hedge-loser-cancelled"]


def test_hedge_double_completion_with_different_result_stays_error():
    """What 'the monitor proves no double-completion' means at runtime: a
    declared replica does NOT license a second COMPLETED carrying a
    different result — that is exactly the corruption first_wins exists
    to prevent, and seeing it means some writer bypassed it."""
    m = _mon()
    m.observe("gw", "create", "t", {S: "QUEUED"})
    m.observe("d", "status", "t", {S: "RUNNING"})
    m.expect_replica("t")
    m.observe("d", "status", "t", {S: "RUNNING"})
    m.observe("d", "finish", "t", {S: "COMPLETED", R: "42"})
    m.observe("d", "finish", "t", {S: "COMPLETED", R: "43"})
    assert [v.kind for v in m.errors] == ["terminal-overwrite"]


def test_undeclared_hedge_loser_cancel_is_generic_warning():
    """Without the declaration the same interleaving keeps its generic
    classification — the hedge attribution never masks a real bug class."""
    m = _mon()
    _lifecycle(m)
    m.observe("w", "finish", "t", {S: "CANCELLED", R: "x"})
    assert [v.kind for v in m.warnings] == ["cancel-after-finish"]


def test_racecheck_store_declares_replica_through():
    """RaceCheckStore.declare_replica feeds the monitor AND the wrapped
    store's (no-op) hook — the dispatcher's hedge path works identically
    against monitored and bare stores."""
    from tpu_faas.core.task import FIELD_LEASE_AT, TaskStatus

    monitor = _mon()
    store = RaceCheckStore(MemoryStore(), monitor, actor="d")
    store.create_task("t", "f", "p")
    store.set_status("t", TaskStatus.RUNNING,
                     extra_fields={FIELD_LEASE_AT: "1.0"})
    store.declare_replica("t")
    store.set_status("t", TaskStatus.RUNNING,
                     extra_fields={FIELD_LEASE_AT: "1.0"})
    store.finish_task("t", TaskStatus.COMPLETED, "42", first_wins=True)
    # the loser's first-wins write is frozen BEFORE any store write, so
    # the monitor never even sees it — the record stands
    store.finish_task("t", TaskStatus.CANCELLED, "x", first_wins=True)
    assert store.get_status("t") == "COMPLETED"
    monitor.assert_clean()
