"""Task priority + cost hints, end to end.

New capability (no reference analog — the reference's dispatch order is
strictly FCFS off the announce channel): clients may tag a task with an
integer ``priority`` (higher admitted first under overload, FCFS within a
class) and a float ``cost`` (estimated run-cost, refines largest-task <->
fastest-slot pairing). The hints ride optional store hash fields, so
reference-style clients that never send them see identical behavior.
"""

from __future__ import annotations

import threading
import time

import jax.numpy as jnp
import numpy as np
import requests

from tpu_faas.client import FaaSClient
from tpu_faas.core.serialize import serialize
from tpu_faas.core.task import FIELD_COST, FIELD_PRIORITY, TaskStatus
from tpu_faas.dispatch.base import PendingTask
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.sched.greedy import rank_match_placement
from tpu_faas.store.launch import make_store, start_store_thread
from tpu_faas.workloads import sleep_task
from tests.test_tpu_push_e2e import _make_dispatcher
from tests.test_workers_e2e import _spawn_worker


# -- PendingTask parsing -----------------------------------------------------


def test_pending_task_from_fields_parses_hints():
    t = PendingTask.from_fields(
        "t1",
        {
            "fn_payload": "F",
            "param_payload": "P",
            FIELD_PRIORITY: "7",
            FIELD_COST: "2.5",
        },
    )
    assert t.priority == 7
    assert t.cost == 2.5
    assert t.size_estimate == 2.5  # cost hint wins over payload bytes


def test_pending_task_defaults_and_malformed_hints():
    t = PendingTask.from_fields(
        "t2", {"fn_payload": "FF", "param_payload": "PP"}
    )
    assert t.priority == 0 and t.cost is None
    assert t.size_estimate == 4.0  # payload bytes
    # a rogue producer writing a huge priority straight into the store must
    # not OverflowError the dispatcher's int32 batch build — clamp, don't die
    t = PendingTask.from_fields(
        "t2b",
        {"fn_payload": "F", "param_payload": "P", FIELD_PRIORITY: str(2**40)},
    )
    assert t.priority == 2**30
    assert int(np.int32(-t.priority)) == -(2**30)  # negation-safe on device
    # malformed / out-of-domain hints degrade to defaults, never raise
    for prio, cost in [("high", "-1"), ("1.5", "nan"), ("", "oops")]:
        t = PendingTask.from_fields(
            "t3",
            {
                "fn_payload": "F",
                "param_payload": "P",
                FIELD_PRIORITY: prio,
                FIELD_COST: cost,
            },
        )
        assert t.priority == 0 and t.cost is None


# -- kernel admission --------------------------------------------------------


def test_rank_match_priority_admission_under_overload():
    T = 10
    sizes = jnp.ones(T, dtype=jnp.float32)
    valid = jnp.ones(T, dtype=bool)
    prio = np.zeros(T, dtype=np.int32)
    prio[6:] = 5  # the LAST four arrivals carry high priority
    a = np.asarray(
        rank_match_placement(
            sizes,
            valid,
            jnp.ones(1, dtype=jnp.float32),
            jnp.asarray([4], dtype=jnp.int32),
            jnp.ones(1, dtype=bool),
            max_slots=4,
            task_priority=jnp.asarray(prio),
        )
    )
    # capacity is 4: exactly the high-priority tasks are admitted, despite
    # arriving after six low-priority ones
    assert set(np.flatnonzero(a >= 0)) == {6, 7, 8, 9}


def test_rank_match_priority_tie_breaks_fcfs():
    T = 8
    sizes = jnp.asarray(np.linspace(1.0, 2.0, T), dtype=jnp.float32)
    valid = jnp.ones(T, dtype=bool)
    workers = (
        jnp.ones(2, dtype=jnp.float32),
        jnp.asarray([1, 2], dtype=jnp.int32),
        jnp.ones(2, dtype=bool),
    )
    base = np.asarray(
        rank_match_placement(sizes, valid, *workers, max_slots=4)
    )
    uniform = np.asarray(
        rank_match_placement(
            sizes,
            valid,
            *workers,
            max_slots=4,
            task_priority=jnp.zeros(T, dtype=jnp.int32),
        )
    )
    # uniform priorities admit exactly the FCFS set (the no-priority path)
    assert set(np.flatnonzero(uniform >= 0)) == set(np.flatnonzero(base >= 0))


# -- gateway contract --------------------------------------------------------


def test_gateway_stores_hints_and_validates():
    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    probe = make_store(store_handle.url)
    try:
        fid = requests.post(
            f"{gw.url}/register_function",
            json={"name": "sleep", "payload": serialize(sleep_task)},
        ).json()["function_id"]
        params = serialize(((0.0,), {}))

        r = requests.post(
            f"{gw.url}/execute_function",
            json={
                "function_id": fid,
                "payload": params,
                "priority": 3,
                "cost": 1.25,
            },
        )
        assert r.status_code == 200
        fields = probe.hgetall(r.json()["task_id"])
        assert fields[FIELD_PRIORITY] == "3"
        assert float(fields[FIELD_COST]) == 1.25
        assert fields["status"] == str(TaskStatus.QUEUED)

        # hints omitted -> fields absent (wire parity with the reference)
        r = requests.post(
            f"{gw.url}/execute_function",
            json={"function_id": fid, "payload": params},
        )
        fields = probe.hgetall(r.json()["task_id"])
        assert FIELD_PRIORITY not in fields and FIELD_COST not in fields

        # batch with parallel hint lists
        r = requests.post(
            f"{gw.url}/execute_batch",
            json={
                "function_id": fid,
                "payloads": [params, params],
                "priorities": [2, None],
                "costs": [None, 0.5],
            },
        )
        assert r.status_code == 200
        t0, t1 = r.json()["task_ids"]
        assert probe.hgetall(t0).get(FIELD_PRIORITY) == "2"
        assert FIELD_COST not in probe.hgetall(t0)
        assert float(probe.hgetall(t1).get(FIELD_COST)) == 0.5

        # validation: 400s, nothing written
        bad = [
            {"priority": "high"},
            {"priority": True},
            {"priority": 2**40},  # out of the kernel's int32-safe range
            {"cost": -1.0},
            {"cost": "x"},
        ]
        for extra in bad:
            r = requests.post(
                f"{gw.url}/execute_function",
                json={"function_id": fid, "payload": params, **extra},
            )
            assert r.status_code == 400, extra
        r = requests.post(
            f"{gw.url}/execute_batch",
            json={
                "function_id": fid,
                "payloads": [params, params],
                "priorities": [1],  # wrong length
            },
        )
        assert r.status_code == 400
    finally:
        gw.stop()
        store_handle.stop()


# -- end to end through the TPU push dispatcher ------------------------------


def test_tpu_push_priority_ordering_e2e():
    """One single-process worker, five pre-queued sleep tasks submitted with
    ascending priorities: the dispatcher must start them in descending
    priority order (the reverse of submission order)."""
    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    client = FaaSClient(gw.url)
    fid = client.register(sleep_task)
    handles = client.submit_many(
        fid,
        [((0.25,), {}) for _ in range(5)],
        priorities=[0, 1, 2, 3, 4],
    )
    by_id = {h.task_id: i for i, h in enumerate(handles)}

    # dispatcher created AFTER submission: the startup rescan adopts all five
    # as pending, so the first dispatch decision sees the full batch
    disp = _make_dispatcher(store_handle.url)
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    worker = _spawn_worker("push_worker", 1, url, "--hb", "--hb-period", "0.3")

    probe = make_store(store_handle.url)
    started_order: list[int] = []
    try:
        deadline = time.monotonic() + 30
        while len(started_order) < 5 and time.monotonic() < deadline:
            for tid, idx in by_id.items():
                if idx in started_order:
                    continue
                status = probe.get_status(tid)
                if status is not None and status != str(TaskStatus.QUEUED):
                    started_order.append(idx)
            time.sleep(0.02)
        assert started_order == [4, 3, 2, 1, 0], started_order
        for h in handles:
            h.result(timeout=30)
    finally:
        worker.kill()
        worker.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def test_priority_admission_matches_oracle_randomized():
    """Property: for random (priorities, validity, capacity), the admitted
    set equals the top-capacity tasks ordered by (priority desc, arrival
    asc) — checked against a plain-numpy oracle."""
    rng = np.random.default_rng(7)
    for _ in range(10):
        T = int(rng.integers(5, 200))
        W = int(rng.integers(1, 20))
        K = int(rng.integers(1, 5))
        valid = rng.random(T) > 0.3
        prio = rng.integers(-3, 4, T).astype(np.int32)
        free = rng.integers(0, K + 1, W).astype(np.int32)
        live = rng.random(W) > 0.2
        a = np.asarray(
            rank_match_placement(
                jnp.asarray(rng.uniform(0.1, 5, T).astype(np.float32)),
                jnp.asarray(valid),
                jnp.asarray(rng.uniform(0.5, 2, W).astype(np.float32)),
                jnp.asarray(free),
                jnp.asarray(live),
                max_slots=K,
                task_priority=jnp.asarray(prio),
            )
        )
        cap = int(np.minimum(free, K)[live].sum())
        # oracle: stable sort of valid tasks by priority desc (arrival is
        # the tie-break via stability)
        valid_idx = np.flatnonzero(valid)
        order = valid_idx[np.argsort(-prio[valid_idx], kind="stable")]
        expect = set(order[: min(cap, len(order))].tolist())
        assert set(np.flatnonzero(a >= 0).tolist()) == expect
