"""Config system (utils/config.py): defaults, INI override, env override —
loaded explicitly, no import-time side effects, no dead keys (the reference
loads config.ini at import with a cwd change and then hard-codes half the
values anyway, SURVEY §5.6)."""

from __future__ import annotations

from tpu_faas.utils.config import Config


def test_defaults():
    cfg = Config.load(ini_path=None, env=False)
    assert cfg.time_to_expire == 10.0  # reference config.ini:4
    assert cfg.tasks_channel == "tasks"  # reference config.ini:7
    assert cfg.dispatcher_ip == "0.0.0.0"


def test_ini_override(tmp_path):
    ini = tmp_path / "cfg.ini"
    ini.write_text(
        "[dispatcher]\n"
        "time_to_expire = 2.5\n"
        "dispatcher_port = 7777\n"
        "[redis]\n"
        "tasks_channel = jobs\n"
    )
    cfg = Config.load(ini_path=str(ini), env=False)
    assert cfg.time_to_expire == 2.5
    assert cfg.dispatcher_port == 7777
    assert cfg.tasks_channel == "jobs"
    assert cfg.store_url == Config().store_url  # untouched keys keep defaults


def test_env_overrides_ini(tmp_path, monkeypatch):
    ini = tmp_path / "cfg.ini"
    ini.write_text("[dispatcher]\ntime_to_expire = 2.5\n")
    monkeypatch.setenv("TPU_FAAS_TIME_TO_EXPIRE", "7.0")
    monkeypatch.setenv("TPU_FAAS_STORE_URL", "resp://10.0.0.9:6400")
    cfg = Config.load(ini_path=str(ini))
    assert cfg.time_to_expire == 7.0  # env beats ini
    assert cfg.store_url == "resp://10.0.0.9:6400"


def test_missing_ini_is_defaults(tmp_path):
    cfg = Config.load(ini_path=str(tmp_path / "nope.ini"), env=False)
    assert cfg == Config()
