"""Parity tests for the fused Pallas bidding kernel.

CPU CI runs the kernel in interpret mode against the XLA matrix path. Both
paths share the elementwise `_bid_block` formula, but compiler-dependent FMA
contraction can perturb single values by ~1 ulp, so the contract is:
values equal within a tight tolerance, and argmax indices equal wherever the
top-2 gap exceeds that tolerance (a near-tie may legitimately flip). The
auction-level test checks solver-level invariants across backends.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_faas.sched.auction import auction_placement
from tpu_faas.sched.pallas_kernels import (
    CHUNK_S,
    TILE_T,
    bid_top2_pallas,
    bid_top2_xla,
)
from tpu_faas.sched.problem import PlacementProblem, check_assignment

ATOL = 1e-5


def _random_inputs(rng, T, S, frac_valid=0.8):
    task_size = rng.uniform(0.1, 5.0, T).astype(np.float32)
    inv_speed = (1.0 / rng.uniform(0.5, 4.0, S)).astype(np.float32)
    valid = (rng.random(S) < frac_valid).astype(np.float32)
    price = rng.uniform(0.0, 3.0, S).astype(np.float32)
    return (
        jnp.asarray(task_size),
        jnp.asarray(inv_speed),
        jnp.asarray(valid),
        jnp.asarray(price),
    )


def _assert_top2_equiv(xla_out, pallas_out):
    v1x, bx, v2x = (np.asarray(a) for a in xla_out)
    v1p, bp, v2p = (np.asarray(a) for a in pallas_out)
    np.testing.assert_allclose(v1x, v1p, rtol=0, atol=ATOL)
    np.testing.assert_allclose(v2x, v2p, rtol=0, atol=ATOL)
    decisive = np.isfinite(v1x) & ((v1x - v2x) > 2 * ATOL)
    np.testing.assert_array_equal(bx[decisive], bp[decisive])


@pytest.mark.parametrize(
    "T,S",
    [
        (TILE_T, CHUNK_S),
        (2 * TILE_T, CHUNK_S),
        # multi-chunk: exercises the cross-chunk top-2 union + tie-keep in
        # the kernel accumulator (j > 0 path)
        (TILE_T, 3 * CHUNK_S),
    ],
)
def test_bid_top2_parity(T, S):
    rng = np.random.default_rng(0)
    args = _random_inputs(rng, T, S)
    scale = jnp.float32(2.5e-4)
    _assert_top2_equiv(
        bid_top2_xla(*args, scale),
        bid_top2_pallas(*args, scale, interpret=True),
    )


def test_bid_top2_cross_chunk_duplicate_max():
    """A max duplicated across two chunks must keep the earlier index and
    report v2 == v1 (the XLA path excludes only the argmax-first position)."""
    T, S = TILE_T, 2 * CHUNK_S
    ts = jnp.ones(T, dtype=jnp.float32)
    inv = jnp.ones(S, dtype=jnp.float32)
    price = jnp.ones(S, dtype=jnp.float32)
    # two identical standout slots, one per chunk; zero jitter keeps the tie
    price = price.at[37].set(0.0).at[CHUNK_S + 911].set(0.0)
    valid = jnp.ones(S, dtype=jnp.float32)
    scale = jnp.float32(0.0)
    v1x, bx, v2x = bid_top2_xla(ts, inv, valid, price, scale)
    v1p, bp, v2p = bid_top2_pallas(ts, inv, valid, price, scale, interpret=True)
    assert np.all(np.asarray(bx) == 37) and np.all(np.asarray(bp) == 37)
    np.testing.assert_array_equal(np.asarray(v1x), np.asarray(v1p))
    np.testing.assert_array_equal(np.asarray(v2x), np.asarray(v2p))
    np.testing.assert_array_equal(np.asarray(v1p), np.asarray(v2p))


def test_bid_top2_all_invalid_slots():
    rng = np.random.default_rng(1)
    ts, inv, _, price = _random_inputs(rng, TILE_T, CHUNK_S)
    none = jnp.zeros(CHUNK_S, dtype=jnp.float32)
    scale = jnp.float32(1e-4)
    out_x = bid_top2_xla(ts, inv, none, price, scale)
    out_p = bid_top2_pallas(ts, inv, none, price, scale, interpret=True)
    assert np.all(np.asarray(out_x[0]) == -np.inf)
    assert np.all(np.asarray(out_p[0]) == -np.inf)
    assert np.all(np.asarray(out_p[2]) == -np.inf)


def test_bid_top2_single_valid_slot():
    """v2 must be -inf when exactly one slot is biddable (the auction caps
    the bid increment at 1.0 in that case)."""
    rng = np.random.default_rng(2)
    ts, inv, _, price = _random_inputs(rng, TILE_T, CHUNK_S)
    one = jnp.zeros(CHUNK_S, dtype=jnp.float32).at[137].set(1.0)
    scale = jnp.float32(1e-4)
    out_x = bid_top2_xla(ts, inv, one, price, scale)
    out_p = bid_top2_pallas(ts, inv, one, price, scale, interpret=True)
    for v1, b, v2 in (out_x, out_p):
        assert np.all(np.asarray(b) == 137)
        assert np.all(np.asarray(v2) == -np.inf)
    _assert_top2_equiv(out_x, out_p)


def test_auction_backend_invariant():
    """Solver-level invariants must hold through either bid path, and the
    two placements must agree in count and near-agree in cost (near-ties may
    be broken differently under FMA contraction). Shapes meet the kernel's
    tiling (T=1024, S=512*4=2048); the task count is small so the
    interpreted kernel converges in few rounds."""
    rng = np.random.default_rng(3)
    n_tasks, n_workers, max_slots = 60, 300, 4
    p = PlacementProblem.build(
        rng.uniform(0.1, 5.0, n_tasks).astype(np.float32),
        rng.uniform(0.5, 4.0, n_workers).astype(np.float32),
        rng.integers(0, max_slots + 1, n_workers).astype(np.int32),
        rng.random(n_workers) > 0.1,
        T=TILE_T,
        W=512,
    )

    def run(backend):
        return auction_placement(
            p.task_size, p.task_valid, p.worker_speed, p.worker_free,
            p.worker_live, max_slots=max_slots, backend=backend,
        )

    def cost(assign):
        a = np.asarray(assign)
        placed = a >= 0
        return float(
            (np.asarray(p.task_size)[placed]
             / np.asarray(p.worker_speed)[a[placed]]).sum()
        )

    res_x = run("xla")
    res_p = run("pallas_interpret")
    ax = np.asarray(res_x.assignment)
    ap = np.asarray(res_p.assignment)
    for a in (ax, ap):
        check_assignment(
            a, np.asarray(p.task_valid),
            np.minimum(np.asarray(p.worker_free), max_slots),
            np.asarray(p.worker_live),
        )
    assert (ax >= 0).sum() == (ap >= 0).sum()
    # both are eps-optimal: costs agree within the auction's optimality slack
    assert abs(cost(ax) - cost(ap)) <= n_tasks * 1e-3 + 1e-4


def test_auto_backend_routing_by_problem_size():
    """'auto' resolves to the XLA matrix path where the [T, S] matrix fits
    comfortably and to the streaming kernel past XLA_CELL_BUDGET (the
    regime where the XLA path OOMs a real chip — measured, bench config 7).
    Tiling misfits fall back to XLA regardless of size."""
    from tpu_faas.sched.pallas_kernels import (
        CHUNK_S,
        TILE_T,
        XLA_CELL_BUDGET,
        resolve_backend,
    )

    assert resolve_backend(10_240, 8_192) == "xla"  # config-3 scale
    big_T, big_S = 50 * TILE_T, 16 * CHUNK_S  # headline-ish, tiled
    assert big_T * big_S > XLA_CELL_BUDGET
    assert resolve_backend(big_T, big_S) == "pallas"
    # same size but misaligned tiling: pallas can't run it -> xla
    assert resolve_backend(big_T + 1, big_S) == "xla"
