"""REST-contract tests for the gateway (analog of reference test_suit.py)."""

import pytest
import requests

from tpu_faas.core.serialize import serialize
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store import MemoryStore
from tpu_faas.workloads import arithmetic

VALID_STATUSES = ["QUEUED", "RUNNING", "COMPLETED", "FAILED"]


@pytest.fixture()
def gw():
    store = MemoryStore()
    handle = start_gateway_thread(store)
    yield handle, store
    handle.stop()


def test_register_and_execute_schema(gw):
    handle, store = gw
    r = requests.post(
        f"{handle.url}/register_function",
        json={"name": "arithmetic", "payload": serialize(arithmetic)},
    )
    assert r.status_code == 200
    fid = r.json()["function_id"]
    assert isinstance(fid, str) and fid

    r = requests.post(
        f"{handle.url}/execute_function",
        json={"function_id": fid, "payload": serialize(((10,), {}))},
    )
    assert r.status_code == 200
    tid = r.json()["task_id"]
    assert isinstance(tid, str) and tid

    # store-side contract: full hash written + QUEUED
    fields = store.hgetall(tid)
    assert fields["status"] == "QUEUED"
    assert fields["fn_payload"] == serialize(arithmetic)
    assert fields["param_payload"] == serialize(((10,), {}))
    assert fields["result"] == "None"

    r = requests.get(f"{handle.url}/status/{tid}")
    assert r.status_code == 200
    assert r.json() == {"task_id": tid, "status": "QUEUED"}
    assert r.json()["status"] in VALID_STATUSES

    r = requests.get(f"{handle.url}/result/{tid}")
    assert r.status_code == 200
    body = r.json()
    assert body["task_id"] == tid and body["status"] == "QUEUED"


def test_execute_announces_on_channel(gw):
    handle, store = gw
    sub = store.subscribe("tasks")
    fid = requests.post(
        f"{handle.url}/register_function",
        json={"name": "f", "payload": serialize(arithmetic)},
    ).json()["function_id"]
    tid = requests.post(
        f"{handle.url}/execute_function",
        json={"function_id": fid, "payload": serialize(((5,), {}))},
    ).json()["task_id"]
    assert sub.get_message(timeout=2.0) == tid


def test_error_paths(gw):
    handle, _ = gw
    assert (
        requests.post(f"{handle.url}/register_function", json={"nope": 1}).status_code
        == 400
    )
    assert (
        requests.post(
            f"{handle.url}/execute_function",
            json={"function_id": "ghost", "payload": "x"},
        ).status_code
        == 404
    )
    assert requests.get(f"{handle.url}/status/ghost").status_code == 404
    assert requests.get(f"{handle.url}/result/ghost").status_code == 404


def test_healthz_and_stats(gw):
    handle, store = gw
    base = handle.url
    assert requests.get(f"{base}/healthz").json() == {"ok": True}

    fid = requests.post(
        f"{base}/register_function",
        json={"name": "arith", "payload": serialize(arithmetic)},
    ).json()["function_id"]
    requests.post(
        f"{base}/execute_function",
        json={"function_id": fid, "payload": serialize(((10,), {}))},
    )

    m = requests.get(f"{base}/stats").json()
    assert m["store_ok"] is True
    assert m["functions_registered"] == 1
    assert m["tasks_submitted"] == 1
    assert m["uptime_s"] >= 0
    # per-route latency stats exist for the endpoints just hit
    assert "POST /register_function" in m["requests"]
    assert "POST /execute_function" in m["requests"]
    reg = m["requests"]["POST /register_function"]
    assert reg["count"] == 1  # monotonic counter, not the latency ring
    assert reg["latency"]["p50"] > 0


def test_metrics_prometheus_exposition(gw):
    """/metrics is Prometheus text exposition now: valid under the strict
    parser, and the counters agree with the JSON /stats twin."""
    from tpu_faas.obs.expofmt import parse_exposition

    handle, store = gw
    base = handle.url
    fid = requests.post(
        f"{base}/register_function",
        json={"name": "arith", "payload": serialize(arithmetic)},
    ).json()["function_id"]
    requests.post(
        f"{base}/execute_function",
        json={"function_id": fid, "payload": serialize(((10,), {}))},
    )
    r = requests.get(f"{base}/metrics")
    assert r.status_code == 200
    assert r.headers["Content-Type"].startswith("text/plain")
    families = parse_exposition(r.text)
    assert families["tpu_faas_gateway_tasks_submitted_total"].samples[0].value == 1
    assert (
        families["tpu_faas_gateway_functions_registered_total"].samples[0].value
        == 1
    )
    [up] = families["tpu_faas_gateway_store_up"].samples
    assert up.value == 1
    # the per-route latency histogram saw the submit
    lat = families["tpu_faas_gateway_request_latency_seconds"]
    routes = {
        s.labels["route"]
        for s in lat.samples
        if s.name.endswith("_count") and s.value > 0
    }
    assert "POST /execute_function" in routes


def test_many_completed_full_stack():
    """100 tasks through the REST contract, each verified against local
    re-execution (analog of the reference's extended suite:
    examples/process_pool_example/test_suit.py:133-171 test_many_completed)."""
    import threading
    import time

    from tpu_faas.dispatch.local import LocalDispatcher

    store = MemoryStore()
    handle = start_gateway_thread(store)
    disp = LocalDispatcher(num_workers=4, store=store)
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    base = handle.url
    try:
        fid = requests.post(
            f"{base}/register_function",
            json={"name": "arith", "payload": serialize(arithmetic)},
        ).json()["function_id"]
        tids = [
            requests.post(
                f"{base}/execute_function",
                json={"function_id": fid, "payload": serialize(((n,), {}))},
            ).json()["task_id"]
            for n in range(100, 200)
        ]
        deadline = time.monotonic() + 120
        expected = {tid: arithmetic(n) for tid, n in zip(tids, range(100, 200))}
        pending = set(tids)
        while pending and time.monotonic() < deadline:
            for tid in list(pending):
                body = requests.get(f"{base}/result/{tid}").json()
                if body["status"] == "COMPLETED":
                    from tpu_faas.core.serialize import deserialize

                    assert deserialize(body["result"]) == expected[tid]
                    pending.discard(tid)
                else:
                    assert body["status"] in ("QUEUED", "RUNNING")
            time.sleep(0.05)
        assert not pending, f"{len(pending)} tasks never completed"
    finally:
        disp.stop()
        t.join(timeout=15)
        handle.stop()


def test_delete_task_lifecycle(gw):
    handle, store = gw
    base = handle.url
    import requests as rq

    # unknown -> 404
    assert rq.delete(f"{base}/task/nope").status_code == 404
    # live task -> 409 (the dispatcher still owns it)
    store.create_task("t-live", "F", "P")
    assert rq.delete(f"{base}/task/t-live").status_code == 409
    # terminal -> deleted, then reads 404
    store.finish_task("t-live", "COMPLETED", "r")
    assert rq.delete(f"{base}/task/t-live").json() == {
        "task_id": "t-live",
        "deleted": True,
    }
    assert rq.get(f"{base}/status/t-live").status_code == 404


def test_result_long_poll(gw):
    """?wait=N holds the request until terminal or deadline; completion
    mid-poll returns early."""
    import threading
    import time

    handle, store = gw
    base = handle.url
    store.create_task("lp", "F", "P")

    t0 = time.monotonic()
    body = requests.get(f"{base}/result/lp", params={"wait": 0.5}).json()
    held = time.monotonic() - t0
    assert body["status"] == "QUEUED"
    assert held >= 0.45, held  # parked at the gateway, not an instant reply

    threading.Timer(
        0.3, lambda: store.finish_task("lp", "COMPLETED", "r")
    ).start()
    t0 = time.monotonic()
    body = requests.get(f"{base}/result/lp", params={"wait": 10}).json()
    early = time.monotonic() - t0
    assert body["status"] == "COMPLETED" and body["result"] == "r"
    assert early < 5.0, early  # returned on completion, not at the deadline

    # invalid wait -> 400; wait on unknown task -> 404 immediately
    assert (
        requests.get(f"{base}/result/lp", params={"wait": "x"}).status_code
        == 400
    )
    t0 = time.monotonic()
    assert (
        requests.get(f"{base}/result/ghost", params={"wait": 5}).status_code
        == 404
    )
    assert time.monotonic() - t0 < 2.0


def test_long_poll_nan_rejected_and_stop_releases_waiters():
    """wait=nan must 400 (not bypass the cap), and gateway stop() must not
    hang behind a parked 30s long-poll."""
    import threading
    import time

    store = MemoryStore()
    handle = start_gateway_thread(store)
    base = handle.url
    store.create_task("parked", "F", "P")
    assert (
        requests.get(f"{base}/result/parked", params={"wait": "nan"}).status_code
        == 400
    )
    # park a waiter for the full cap, then stop the gateway mid-poll
    replies = []
    waiter = threading.Thread(
        target=lambda: replies.append(
            requests.get(f"{base}/result/parked", params={"wait": 30}).json()
        ),
        daemon=True,
    )
    waiter.start()
    time.sleep(0.5)
    t0 = time.monotonic()
    handle.stop()
    stopped_in = time.monotonic() - t0
    assert stopped_in < 10.0, f"stop() hung {stopped_in:.1f}s behind a waiter"
    waiter.join(timeout=5)
    assert replies and replies[0]["status"] == "QUEUED"


def test_execute_batch_schema_and_memory_store_path(gw):
    """Batch endpoint contract on the in-proc store (default loop-based
    create_tasks, vs the RESP client's pipelined override)."""
    handle, store = gw
    base = handle.url
    fid = requests.post(
        f"{base}/register_function",
        json={"name": "f", "payload": serialize(arithmetic)},
    ).json()["function_id"]
    sub = store.subscribe("tasks")
    r = requests.post(
        f"{base}/execute_batch",
        json={
            "function_id": fid,
            "payloads": [serialize(((n,), {})) for n in range(5)],
        },
    )
    assert r.status_code == 200
    tids = r.json()["task_ids"]
    assert len(tids) == 5
    for tid in tids:
        assert store.hgetall(tid)["status"] == "QUEUED"
    announced = {sub.get_message(timeout=2.0) for _ in range(5)}
    assert announced == set(tids)
    # error paths
    assert (
        requests.post(
            f"{base}/execute_batch",
            json={"function_id": "ghost", "payloads": ["x"]},
        ).status_code
        == 404
    )
    assert (
        requests.post(
            f"{base}/execute_batch",
            json={"function_id": fid, "payloads": [5]},
        ).status_code
        == 400
    )


def test_gateway_replicas_share_registry_through_store():
    """Two gateway replicas over one store: a function registered via
    replica A is invocable via replica B, and either replica serves the
    result — the registry lives in the store (function:<id> hashes), not in
    gateway memory, so gateways scale horizontally behind a load balancer."""
    from tpu_faas.core.executor import execute_fn
    from tpu_faas.core.task import TaskStatus

    store = MemoryStore()
    a = start_gateway_thread(store)
    b = start_gateway_thread(store)
    try:
        fid = requests.post(
            f"{a.url}/register_function",
            json={"name": "arithmetic", "payload": serialize(arithmetic)},
        ).json()["function_id"]
        r = requests.post(
            f"{b.url}/execute_function",
            json={"function_id": fid, "payload": serialize(((7,), {}))},
        )
        assert r.status_code == 200
        tid = r.json()["task_id"]
        # finish the task out-of-band (no dispatcher in this test)
        fields = store.hgetall(tid)
        res = execute_fn(
            tid, fields["fn_payload"], fields["param_payload"]
        )
        status, result = res.status, res.result
        store.finish_task(tid, status, result)
        for url in (a.url, b.url):
            body = requests.get(f"{url}/result/{tid}").json()
            assert body["status"] == str(TaskStatus.COMPLETED)
    finally:
        a.stop()
        b.stop()


def test_long_poll_wakes_on_result_publish():
    """A parked ``/result?wait=`` request must return almost immediately
    after finish_task lands — woken by the results-channel announce, not by
    the coarse fallback re-read (0.5 s+)."""
    import threading
    import time

    from tpu_faas.core.task import TaskStatus

    store = MemoryStore()
    handle = start_gateway_thread(store)
    try:
        store.create_task("wk1", "F", "P")
        got = {}

        def parked():
            r = requests.get(
                f"{handle.url}/result/wk1", params={"wait": 10}, timeout=15
            )
            got["at"] = time.monotonic()
            got["body"] = r.json()

        th = threading.Thread(target=parked)
        th.start()
        time.sleep(0.6)  # past the first fallback window, request is parked
        t_finish = time.monotonic()
        store.finish_task("wk1", "COMPLETED", serialize(42))
        th.join(timeout=5)
        assert got["body"]["status"] == str(TaskStatus.COMPLETED)
        wake_latency = got["at"] - t_finish
        assert wake_latency < 0.4, f"woke by fallback, not publish: {wake_latency:.3f}s"
    finally:
        handle.stop()


def test_result_ttl_sweeper_expires_only_old_terminal_records():
    """--result-ttl ages out consumed results (the reference's store grows
    until a manual FLUSHDB): only terminal records older than the TTL go;
    live tasks, fresh results, and the function registry survive."""
    import time

    from tpu_faas.core.task import FIELD_FINISHED_AT
    from tpu_faas.gateway.app import _sweep_expired_results

    store = MemoryStore()
    now = time.time()
    store.hset("function:f1", {"name": "f", "payload": "P"})
    store.create_task("queued", "F", "P")
    store.create_task("old-done", "F", "P")
    store.finish_task("old-done", "COMPLETED", "R")
    store.hset("old-done", {FIELD_FINISHED_AT: repr(now - 100)})
    store.create_task("fresh-done", "F", "P")
    store.finish_task("fresh-done", "COMPLETED", "R")
    store.create_task("unstamped", "F", "P")
    store.hset("unstamped", {"status": "COMPLETED", "result": "R"})

    # claim-only hashes (idempotency winner died between claim and create):
    # the claim value's embedded timestamp dates them — old ones go, fresh
    # ones (winner may be in flight) and foreign status-less hashes stay
    from tpu_faas.gateway.app import _IDEM_CLAIM_FIELD, _idem_claim_value

    store.hset(
        "old-claim", {_IDEM_CLAIM_FIELD: _idem_claim_value("P", now - 100)}
    )
    store.hset(
        "fresh-claim", {_IDEM_CLAIM_FIELD: _idem_claim_value("P", now)}
    )
    store.hset("foreign", {"someone": "elses data"})

    n = _sweep_expired_results(store, ttl=30.0, now=now)
    assert n == 2
    assert store.get_status("old-done") is None  # expired
    assert store.get_status("queued") == "QUEUED"  # live: untouched
    assert store.get_status("fresh-done") == "COMPLETED"  # within TTL
    assert store.get_status("unstamped") == "COMPLETED"  # no stamp: kept
    assert store.hgetall("function:f1")  # registry never swept
    assert not store.hgetall("old-claim")  # abandoned claim: GC'd
    assert store.hgetall("fresh-claim")  # recent claim: kept
    assert store.hgetall("foreign")  # not ours: never touched


def test_result_ttl_end_to_end():
    """A gateway with a short TTL: the record exists right after completion
    and 404s after the sweep."""
    import time

    from tpu_faas.core.executor import execute_fn

    store = MemoryStore()
    handle = start_gateway_thread(store, result_ttl=1.0)
    try:
        fid = requests.post(
            f"{handle.url}/register_function",
            json={"name": "arithmetic", "payload": serialize(arithmetic)},
        ).json()["function_id"]
        tid = requests.post(
            f"{handle.url}/execute_function",
            json={"function_id": fid, "payload": serialize(((5,), {}))},
        ).json()["task_id"]
        fields = store.hgetall(tid)
        res = execute_fn(
            tid, fields["fn_payload"], fields["param_payload"]
        )
        status, result = res.status, res.result
        store.finish_task(tid, status, result)
        assert requests.get(f"{handle.url}/result/{tid}").status_code == 200
        deadline = time.monotonic() + 10
        while (
            requests.get(f"{handle.url}/result/{tid}").status_code != 404
            and time.monotonic() < deadline
        ):
            time.sleep(0.1)
        assert requests.get(f"{handle.url}/result/{tid}").status_code == 404
    finally:
        handle.stop()


def test_client_connect_retry_bridges_gateway_restart():
    """The SDK retries CONNECTION failures (gateway restarting behind a
    stable address): a request issued while the port is briefly dark
    succeeds once the replacement gateway binds. Read/status errors are
    never retried — re-sending a possibly-applied POST could run a task
    twice."""
    import socket
    import threading
    import time

    from tpu_faas.client import FaaSClient

    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()

    store = MemoryStore()
    holder = {}

    def bring_up_late():
        time.sleep(0.7)  # first connect attempt(s) must fail
        holder["gw"] = start_gateway_thread(store, port=port)

    th = threading.Thread(target=bring_up_late)
    th.start()
    try:
        client = FaaSClient(f"http://127.0.0.1:{port}")
        # issued while the port is still dark; connect retries bridge it
        fid = client.register(arithmetic)
        assert isinstance(fid, str) and fid
        assert store.hgetall(f"function:{fid}")  # actually registered
    finally:
        th.join()
        gw = holder.get("gw")
        if gw is not None:
            gw.stop()


def test_async_client_connect_retry_bridges_gateway_restart():
    """The async SDK's request() helper mirrors the sync adapter: connect
    failures during a gateway restart are retried, anything after the
    request reaches the wire is not."""
    import asyncio
    import socket
    import threading
    import time

    from tpu_faas.client import AsyncFaaSClient

    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()

    store = MemoryStore()
    holder = {}

    def bring_up_late():
        time.sleep(0.7)
        holder["gw"] = start_gateway_thread(store, port=port)

    th = threading.Thread(target=bring_up_late)
    th.start()

    async def scenario():
        async with AsyncFaaSClient(f"http://127.0.0.1:{port}") as client:
            return await client.register(arithmetic)

    try:
        fid = asyncio.run(scenario())
        assert isinstance(fid, str) and fid
        assert store.hgetall(f"function:{fid}")
    finally:
        th.join()
        gw = holder.get("gw")
        if gw is not None:
            gw.stop()


def test_idempotency_key_dedupes_resubmits():
    """A client-supplied idempotency key makes submits safely retryable:
    the same (function, key) always addresses the same task — a lost
    response re-sent runs NOTHING twice — while different keys (or no key)
    still create distinct tasks, and re-submitting after the task finished
    returns the completed record instead of re-running it."""
    import threading
    import time

    from tpu_faas.core.serialize import deserialize
    from tpu_faas.dispatch.local import LocalDispatcher

    store = MemoryStore()
    handle = start_gateway_thread(store)
    disp = LocalDispatcher(num_workers=2, store=store)
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    base = handle.url
    try:
        fid = requests.post(
            f"{base}/register_function",
            json={"name": "arith", "payload": serialize(arithmetic)},
        ).json()["function_id"]
        payload = serialize(((123,), {}))
        body = {"function_id": fid, "payload": payload, "idempotency_key": "job-42"}

        r1 = requests.post(f"{base}/execute_function", json=body).json()
        r2 = requests.post(f"{base}/execute_function", json=body).json()
        assert r1["task_id"] == r2["task_id"]
        assert r2.get("deduplicated") is True

        # distinct keys and keyless submits create distinct tasks
        other = requests.post(
            f"{base}/execute_function", json={**body, "idempotency_key": "job-43"}
        ).json()
        assert other["task_id"] != r1["task_id"]
        free = requests.post(
            f"{base}/execute_function",
            json={"function_id": fid, "payload": payload},
        ).json()
        assert free["task_id"] != r1["task_id"]

        # wait for completion, then re-submit the SAME key: same (finished)
        # task back, not a re-execution
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            b = requests.get(f"{base}/result/{r1['task_id']}").json()
            if b["status"] == "COMPLETED":
                break
            time.sleep(0.05)
        assert deserialize(b["result"]) == arithmetic(123)
        r3 = requests.post(f"{base}/execute_function", json=body).json()
        assert r3["task_id"] == r1["task_id"] and r3.get("deduplicated") is True
        b = requests.get(f"{base}/result/{r1['task_id']}").json()
        assert b["status"] == "COMPLETED"  # record untouched (not re-QUEUED)

        # validation
        bad = requests.post(
            f"{base}/execute_function", json={**body, "idempotency_key": ""}
        )
        assert bad.status_code == 400
    finally:
        disp.stop()
        t.join(timeout=15)
        handle.stop()


def test_idempotency_key_payload_mismatch_409():
    """Reusing a key with DIFFERENT params must 409, not silently hand back
    another request's task/result."""
    store = MemoryStore()
    handle = start_gateway_thread(store)
    try:
        fid = requests.post(
            f"{handle.url}/register_function",
            json={"name": "arith", "payload": serialize(arithmetic)},
        ).json()["function_id"]
        body = {
            "function_id": fid,
            "payload": serialize(((1,), {})),
            "idempotency_key": "k1",
        }
        first = requests.post(f"{handle.url}/execute_function", json=body)
        assert first.status_code == 200
        clash = requests.post(
            f"{handle.url}/execute_function",
            json={**body, "payload": serialize(((2,), {}))},
        )
        assert clash.status_code == 409
    finally:
        handle.stop()


def test_store_setnx_field_atomic():
    """setnx_field: exactly one creator, and EVERY caller (winner or loser)
    walks away with the winning value — concurrently on the memory store,
    sequentially on the RESP server (single-threaded server => HSETNX
    added-count is the atomic arbiter)."""
    import concurrent.futures

    from tpu_faas.store.launch import make_store, start_store_thread

    mem = MemoryStore()
    with concurrent.futures.ThreadPoolExecutor(8) as pool:
        results = list(
            pool.map(
                lambda i: mem.setnx_field("k", "claim", f"v{i}"), range(32)
            )
        )
    assert sum(created for created, _ in results) == 1
    winning = mem.hget("k", "claim")
    assert all(current == winning for _, current in results)

    h = start_store_thread()
    try:
        s = make_store(h.url)
        assert s.setnx_field("k", "claim", "first") == (True, "first")
        assert s.setnx_field("k", "claim", "second") == (False, "first")
        assert s.setnx_fields(
            [("k", "third"), ("k2", "fresh")], "claim"
        ) == [(False, "first"), (True, "fresh")]
        s.close()
    finally:
        h.stop()


def test_idempotency_abandoned_claim_adopted():
    """A claim whose winner died between claim and create (claim field
    exists, no task record) must not strand retries: the dedup loser adopts
    the claim and creates the record itself, so /status works immediately."""
    from tpu_faas.gateway.app import _IDEM_CLAIM_FIELD, _idem_claim_value

    store = MemoryStore()
    handle = start_gateway_thread(store)
    try:
        fid = requests.post(
            f"{handle.url}/register_function",
            json={"name": "arith", "payload": serialize(arithmetic)},
        ).json()["function_id"]
        payload = serialize(((7,), {}))
        body = {
            "function_id": fid,
            "payload": payload,
            "idempotency_key": "dead-winner",
        }
        # simulate the dead winner: write the claim exactly as a crashed
        # gateway would have, with NO task record behind it
        from tpu_faas.gateway.app import _idempotent_task_id

        tid = _idempotent_task_id(fid, "dead-winner")
        store.hset(tid, {_IDEM_CLAIM_FIELD: _idem_claim_value(payload)})

        r = requests.post(f"{handle.url}/execute_function", json=body)
        assert r.status_code == 200
        got = r.json()
        assert got["task_id"] == tid and got.get("deduplicated") is True
        # the record now exists (adoption created it) — no stranded 404
        s = requests.get(f"{handle.url}/status/{tid}")
        assert s.status_code == 200 and s.json()["status"] == "QUEUED"

        # mismatch against a claim-only hash is still a 409 (the claim
        # value carries the payload hash; no record needed to compare)
        clash = requests.post(
            f"{handle.url}/execute_function",
            json={**body, "payload": serialize(((8,), {}))},
        )
        assert clash.status_code == 409
    finally:
        handle.stop()


def test_adopted_claim_response_carries_callers_trace_id():
    """Adoption writes THIS caller's trace context onto the record (the
    winner died before writing one) — so unlike a plain dedup hit, the
    response must return the caller's trace_id: it IS the id on the
    record, and the client needs it to correlate logs and key /trace."""
    from tpu_faas.gateway.app import (
        _IDEM_CLAIM_FIELD,
        _idem_claim_value,
        _idempotent_task_id,
    )
    from tpu_faas.core.task import FIELD_TRACE_ID

    store = MemoryStore()
    handle = start_gateway_thread(store, trace=True)
    try:
        fid = requests.post(
            f"{handle.url}/register_function",
            json={"name": "arith", "payload": serialize(arithmetic)},
        ).json()["function_id"]
        payload = serialize(((7,), {}))
        tid = _idempotent_task_id(fid, "dead-winner")
        store.hset(tid, {_IDEM_CLAIM_FIELD: _idem_claim_value(payload)})
        r = requests.post(
            f"{handle.url}/execute_function",
            json={
                "function_id": fid,
                "payload": payload,
                "idempotency_key": "dead-winner",
                "trace_id": "aabbccdd11223344",
            },
        )
        assert r.status_code == 200
        got = r.json()
        assert got.get("deduplicated") is True
        assert got.get("trace_id") == "aabbccdd11223344"
        assert store.hget(tid, FIELD_TRACE_ID) == "aabbccdd11223344"

        # a PLAIN dedup hit (record exists) still suppresses trace_id —
        # the record carries the winner's id, not this caller's
        dup = requests.post(
            f"{handle.url}/execute_function",
            json={
                "function_id": fid,
                "payload": payload,
                "idempotency_key": "dead-winner",
                "trace_id": "ffff0000ffff0000",
            },
        ).json()
        assert dup.get("deduplicated") is True
        assert "trace_id" not in dup
        assert store.hget(tid, FIELD_TRACE_ID) == "aabbccdd11223344"
    finally:
        handle.stop()


def test_batch_duplicate_trace_ids_rejected():
    """Two batch items sharing one client-minted trace id would fight
    over the same span hash (identical process:stage fields lose the
    first-write-wins race) — a 400, mirroring duplicate idempotency_keys."""
    store = MemoryStore()
    handle = start_gateway_thread(store, trace=True)
    try:
        fid = requests.post(
            f"{handle.url}/register_function",
            json={"name": "arith", "payload": serialize(arithmetic)},
        ).json()["function_id"]
        payloads = [serialize(((i,), {})) for i in range(2)]
        r = requests.post(
            f"{handle.url}/execute_batch",
            json={
                "function_id": fid,
                "payloads": payloads,
                "trace_ids": ["aabbccdd11223344", "aabbccdd11223344"],
            },
        )
        assert r.status_code == 400
        assert "duplicates" in r.json()["error"]
        # distinct ids (and holes, minted server-side) still pass
        ok = requests.post(
            f"{handle.url}/execute_batch",
            json={
                "function_id": fid,
                "payloads": payloads,
                "trace_ids": ["aabbccdd11223344", None],
            },
        )
        assert ok.status_code == 200
        tids = ok.json()["trace_ids"]
        assert tids[0] == "aabbccdd11223344" and tids[1]
    finally:
        handle.stop()


def test_batch_duplicate_idempotency_keys_rejected():
    """Two items with one idempotency_key in a single batch is a client
    error (400) — the claim round would silently dedup the second against
    the first before its payload is even written."""
    store = MemoryStore()
    handle = start_gateway_thread(store)
    try:
        fid = requests.post(
            f"{handle.url}/register_function",
            json={"name": "arith", "payload": serialize(arithmetic)},
        ).json()["function_id"]
        r = requests.post(
            f"{handle.url}/execute_batch",
            json={
                "function_id": fid,
                "payloads": [serialize(((1,), {})), serialize(((2,), {}))],
                "idempotency_keys": ["same", "same"],
            },
        )
        assert r.status_code == 400
        assert "duplicate" in r.json()["error"]
    finally:
        handle.stop()


def test_batch_mismatch_409_does_not_burn_other_claims():
    """A batch 409 (one key reused with a different payload) must not leave
    the OTHER items' keys unusable: validation happens before any claim is
    written, so a follow-up batch with the bad item fixed fully succeeds."""
    store = MemoryStore()
    handle = start_gateway_thread(store)
    try:
        fid = requests.post(
            f"{handle.url}/register_function",
            json={"name": "arith", "payload": serialize(arithmetic)},
        ).json()["function_id"]
        pa, pb, pc = (serialize(((n,), {})) for n in (1, 2, 3))
        # seed key "a" with payload pa
        first = requests.post(
            f"{handle.url}/execute_batch",
            json={
                "function_id": fid,
                "payloads": [pa],
                "idempotency_keys": ["a"],
            },
        ).json()
        # now a batch where "a" clashes and "b" is fresh -> 409, no claims
        clash = requests.post(
            f"{handle.url}/execute_batch",
            json={
                "function_id": fid,
                "payloads": [pb, pc],
                "idempotency_keys": ["a", "b"],
            },
        )
        assert clash.status_code == 409
        # "b" was NOT burned: submitting it again creates a real task
        retry = requests.post(
            f"{handle.url}/execute_batch",
            json={
                "function_id": fid,
                "payloads": [pc],
                "idempotency_keys": ["b"],
            },
        ).json()
        assert retry["deduplicated"] == [False]
        tid = retry["task_ids"][0]
        assert store.hgetall(tid).get("param_payload") == pc
        assert first["task_ids"][0] != tid
    finally:
        handle.stop()


def test_batch_idempotency_keys():
    """The batch endpoint honors per-item idempotency keys with one
    pipelined claim round trip: duplicates dedup item-wise, mixed
    None/keyed entries work, and a key clash 409s."""
    store = MemoryStore()
    handle = start_gateway_thread(store)
    try:
        fid = requests.post(
            f"{handle.url}/register_function",
            json={"name": "arith", "payload": serialize(arithmetic)},
        ).json()["function_id"]
        p1, p2 = serialize(((1,), {})), serialize(((2,), {}))
        body = {
            "function_id": fid,
            "payloads": [p1, p2],
            "idempotency_keys": ["a", None],
        }
        r1 = requests.post(f"{handle.url}/execute_batch", json=body).json()
        assert r1["deduplicated"] == [False, False]
        r2 = requests.post(f"{handle.url}/execute_batch", json=body).json()
        assert r2["task_ids"][0] == r1["task_ids"][0]  # keyed: same task
        assert r2["task_ids"][1] != r1["task_ids"][1]  # keyless: new task
        assert r2["deduplicated"] == [True, False]
        # only non-deduplicated items were (re)written/announced: the keyed
        # record kept its original payload
        assert store.hgetall(r1["task_ids"][0])["param_payload"] == p1

        clash = requests.post(
            f"{handle.url}/execute_batch",
            json={
                "function_id": fid,
                "payloads": [p2],
                "idempotency_keys": ["a"],  # reuse with different payload
            },
        )
        assert clash.status_code == 409
        bad = requests.post(
            f"{handle.url}/execute_batch",
            json={
                "function_id": fid,
                "payloads": [p1],
                "idempotency_keys": ["a", "b"],  # wrong length
            },
        )
        assert bad.status_code == 400
    finally:
        handle.stop()


def test_two_gateway_replicas_dedupe_concurrent_keyed_submits():
    """Gateway replicas share one store, so the idempotency claim must
    arbitrate across processes: hammer the SAME key through two replicas
    concurrently — exactly one task record is created, every response
    agrees on the task id, and the task runs once."""
    import concurrent.futures

    from tpu_faas.store.launch import make_store, start_store_thread

    store_handle = start_store_thread()
    gw1 = start_gateway_thread(make_store(store_handle.url))
    gw2 = start_gateway_thread(make_store(store_handle.url))
    try:
        fid = requests.post(
            f"{gw1.url}/register_function",
            json={"name": "arith", "payload": serialize(arithmetic)},
        ).json()["function_id"]
        payload = serialize(((5,), {}))
        body = {
            "function_id": fid,
            "payload": payload,
            "idempotency_key": "xgw",
        }

        def submit(base):
            return requests.post(f"{base}/execute_function", json=body).json()

        with concurrent.futures.ThreadPoolExecutor(8) as pool:
            results = list(
                pool.map(
                    submit,
                    [gw1.url, gw2.url] * 8,
                )
            )
        ids = {r["task_id"] for r in results}
        assert len(ids) == 1, ids
        # exactly one submit was the winner (created the record); the rest
        # deduplicated against it
        dedups = sum(bool(r.get("deduplicated")) for r in results)
        assert dedups == len(results) - 1
        # one live record in the store, QUEUED exactly once
        s = make_store(store_handle.url)
        assert s.get_status(next(iter(ids))) == "QUEUED"
        s.close()
    finally:
        gw1.stop()
        gw2.stop()
        store_handle.stop()
