"""Fleet-wide lease-cadence coordination.

Advisor r2: a tpu-push rescanner started with a tight ``--lease-timeout``
(at or below ~2-3x the siblings' fixed 10 s renew period) could adopt tasks
whose push/pull/local owner is alive and renewing — double execution in
mixed fleets. The fix is coordination through the store (LEASE_CONF_KEY):
the rescanner publishes its adoption horizon, every dispatcher folds
timeout/3 into its renew cadence, and renewals re-read the key so
late-joining rescanners reach already-running dispatchers.
"""

import threading
import time

from tpu_faas.core.executor import pack_params
from tpu_faas.core.serialize import serialize
from tpu_faas.dispatch.base import TaskDispatcher
from tpu_faas.dispatch.local import LocalDispatcher
from tpu_faas.store import MemoryStore
from tpu_faas.core.task import FIELD_LEASE_AT
from tpu_faas.workloads import sleep_task


def test_publish_tightens_sibling_renew_cadence():
    store = MemoryStore()
    rescanner = TaskDispatcher(store=store)
    rescanner.publish_lease_timeout(3.0)
    assert rescanner.lease_renew_period == 1.0  # folds into its own cadence
    # a dispatcher connecting afterwards adapts at construction
    sibling = TaskDispatcher(store=store)
    assert sibling.lease_renew_period == 1.0


def test_publish_keeps_tightest_value_on_concurrent_rescanners():
    store = MemoryStore()
    d = TaskDispatcher(store=store)
    d.publish_lease_timeout(3.0)
    d.publish_lease_timeout(9.0)  # a slacker rescanner must not loosen it
    other = TaskDispatcher(store=store)
    assert other.lease_renew_period == 1.0


def test_late_joining_rescanner_reaches_running_dispatcher():
    store = MemoryStore()
    sibling = TaskDispatcher(store=store)
    assert sibling.lease_renew_period == TaskDispatcher.LEASE_RENEW_PERIOD
    rescanner = TaskDispatcher(store=store)
    rescanner.publish_lease_timeout(6.0)
    # the sibling picks the new horizon up on its next renewal round trip
    sibling.renew_leases([])
    assert sibling.lease_renew_period == 2.0


def test_unshared_local_dispatcher_renews_running_leases():
    """A NON-shared local dispatcher must renew leases of in-pool tasks
    (advisor r2: it renewed only when shared=True, so any task running
    longer than a co-located rescanner's lease_timeout was adopted and
    re-executed)."""
    store = MemoryStore()
    d = LocalDispatcher(num_workers=1, store=store)
    assert not d.shared
    d.lease_renew_period = 0.05
    t = threading.Thread(target=d.start, daemon=True)
    t.start()
    try:
        store.create_task(
            "slow", serialize(sleep_task), pack_params(1.0)
        )
        # collect two lease stamps while the task is RUNNING
        deadline = time.monotonic() + 30
        stamps = set()
        while time.monotonic() < deadline and len(stamps) < 2:
            if store.get_status("slow") == "COMPLETED":
                break
            stamp = store.hget("slow", FIELD_LEASE_AT)
            if stamp is not None:
                stamps.add(stamp)
            time.sleep(0.02)
        assert len(stamps) >= 2, (
            f"lease never renewed while running: {stamps}"
        )
    finally:
        d.stop()
        t.join(timeout=15)


def test_concurrent_publishers_converge_on_min():
    """Value-keyed setnx publication: the LARGER value landing last must
    not overwrite the smaller one (a single shared field with
    read-modify-write would lose that race)."""
    store = MemoryStore()
    a = TaskDispatcher(store=store)
    b = TaskDispatcher(store=store)
    a.publish_lease_timeout(5.0)
    b.publish_lease_timeout(30.0)  # lands after: must not win
    assert a.read_fleet_lease_conf()[0] == 5.0
    assert b.read_fleet_lease_conf()[0] == 5.0
    assert b.lease_renew_period == 5.0 / 3.0


def test_adoption_horizon_grace_window_after_fresh_publication():
    """A rescanner must not adopt against a freshly-published tight
    horizon: siblings renewing at the old (default 10 s) cadence can have
    stamps up to 10 s old on perfectly live owners. Until one old-cadence
    renewal has elapsed since first publication, adoption is floored at
    2.5x LEASE_RENEW_PERIOD; afterwards the tight horizon applies."""
    import time as _time

    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher

    store = MemoryStore()
    d = TpuPushDispatcher(
        ip="127.0.0.1", port=0, store=store, max_workers=4, max_pending=8,
        max_inflight=8, lease_timeout=2.0,
    )
    try:
        # publication just happened (in the constructor)
        assert d._adoption_horizon() == 2.5 * d.LEASE_RENEW_PERIOD
        # age the publication past the window: the tight horizon applies
        value, _published = d._fleet_lease_conf
        d._fleet_lease_conf = (value, _time.time() - 2 * d.LEASE_RENEW_PERIOD)
        assert d._adoption_horizon() == 2.0
    finally:
        d.socket.close(0)


def test_lease_conf_republished_after_store_data_loss():
    """A store that comes back without LEASE_CONF_KEY (crash without
    snapshot, FLUSHDB) must not permanently silence the tight horizon:
    every rescan re-issues the idempotent publish, and the recreated key
    re-opens the grace window so siblings re-tighten before adoptions
    resume."""
    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
    from tpu_faas.store.base import LEASE_CONF_KEY

    store = MemoryStore()
    d = TpuPushDispatcher(
        ip="127.0.0.1", port=0, store=store, max_workers=4, max_pending=8,
        max_inflight=8, lease_timeout=2.0,
    )
    try:
        assert d.read_fleet_lease_conf() is not None
        store.delete(LEASE_CONF_KEY)  # simulated data loss
        assert d.read_fleet_lease_conf() is None
        d._recover_stranded()  # any later rescan republishes
        conf = d.read_fleet_lease_conf()
        assert conf is not None and conf[0] == 2.0
        # fresh publication time -> the grace floor applies again
        assert d._adoption_horizon() == 2.5 * d.LEASE_RENEW_PERIOD
    finally:
        d.socket.close(0)
