"""Minimum end-to-end slice: client -> gateway -> store server -> local
dispatcher -> pool -> result poll (analog of reference test_roundtrip,
test_suit.py:62-92, and test_local, test_client.py:209-219)."""

import threading

import pytest

from tpu_faas.client import FaaSClient, TaskFailedError
from tpu_faas.dispatch.local import LocalDispatcher
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store.launch import make_store, start_store_thread
from tpu_faas.workloads import arithmetic, failing_task, make_workload


@pytest.fixture()
def stack():
    """Full stack over real TCP: RESP store server + gateway + local dispatcher."""
    store_handle = start_store_thread()
    gw_store = make_store(store_handle.url)
    gw = start_gateway_thread(gw_store)
    dispatcher = LocalDispatcher(num_workers=4, store=make_store(store_handle.url))
    thread = threading.Thread(target=dispatcher.start, daemon=True)
    thread.start()
    client = FaaSClient(gw.url)
    yield client
    dispatcher.stop()
    thread.join(timeout=10)
    gw.stop()
    store_handle.stop()


def test_roundtrip_single(stack):
    client = stack
    fid = client.register(arithmetic)
    handle = client.submit(fid, 1000)
    assert handle.result(timeout=30) == arithmetic(1000)


def test_roundtrip_many_tasks_verified_against_local_oracle(stack):
    client = stack
    fn, params = make_workload("sort_numbers", 20, 50, seed=1)
    fid = client.register(fn)
    handles = [client.submit(fid, *args, **kwargs) for args, kwargs in params]
    for handle, (args, kwargs) in zip(handles, params):
        assert handle.result(timeout=60) == fn(*args, **kwargs)


def test_failed_task_surfaces_exception(stack):
    client = stack
    fid = client.register(failing_task)
    handle = client.submit(fid, "kaput")
    with pytest.raises(TaskFailedError) as ei:
        handle.result(timeout=30)
    assert isinstance(ei.value.cause, ValueError)
    assert "kaput" in str(ei.value.cause)


def test_lambda_roundtrip(stack):
    client = stack
    k = 5
    assert client.run(lambda x: x * k, 8, timeout=30) == 40


def test_client_map_in_order_and_failure_raises(stack):
    client = stack
    assert client.map(arithmetic, range(10, 30)) == [
        arithmetic(n) for n in range(10, 30)
    ]
    with pytest.raises(TaskFailedError):
        client.map(failing_task, ["a", "b"])


def test_handle_forget_frees_store(stack):
    client = stack
    handle = client.submit(client.register(arithmetic), 500)
    assert handle.result(timeout=30) == arithmetic(500)
    handle.forget()
    import requests as rq

    assert rq.get(f"{client.base_url}/status/{handle.task_id}").status_code == 404


def test_submit_many_batch_endpoint(stack):
    client = stack
    fid = client.register(arithmetic)
    handles = client.submit_many(fid, [((n,), {}) for n in range(50, 70)])
    assert [h.result(timeout=60) for h in handles] == [
        arithmetic(n) for n in range(50, 70)
    ]


def test_async_client_end_to_end(stack):
    """AsyncFaaSClient: register, concurrent submits, batch submit, failure
    surfaced as the task's exception — all multiplexed on one event loop."""
    import asyncio

    from tpu_faas.client import AsyncFaaSClient

    sync_client = stack

    async def scenario() -> None:
        async with AsyncFaaSClient(sync_client.base_url) as client:
            fid = await client.register(arithmetic)
            handles = await asyncio.gather(
                *(client.submit(fid, n) for n in range(100, 110))
            )
            values = await asyncio.gather(
                *(h.result(timeout=60) for h in handles)
            )
            assert values == [arithmetic(n) for n in range(100, 110)]

            batch = await client.submit_many(
                fid, [((n,), {}) for n in range(200, 210)]
            )
            values = await asyncio.gather(
                *(h.result(timeout=60) for h in batch)
            )
            assert values == [arithmetic(n) for n in range(200, 210)]

            with pytest.raises(TaskFailedError):
                await client.run(failing_task, "nope", timeout=30)

            # async task GC mirrors the sync surface
            done = await client.submit(fid, 7)
            assert await done.result(timeout=30) == arithmetic(7)
            await done.forget()

            # scheduling hints mirror the sync surface (submit_with +
            # submit_many parallel lists); local mode ignores them, but the
            # gateway must accept and store the fields
            h = await client.submit_with(
                fid, args=(11,), priority=3, cost=1.5
            )
            assert await h.result(timeout=30) == arithmetic(11)
            hinted = await client.submit_many(
                fid,
                [((n,), {}) for n in range(300, 303)],
                priorities=[2, 1, 0],
                costs=[1.0, 2.0, 3.0],
            )
            values = await asyncio.gather(
                *(x.result(timeout=60) for x in hinted)
            )
            assert values == [arithmetic(n) for n in range(300, 303)]

    asyncio.run(scenario())
