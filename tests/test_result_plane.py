"""Result data plane (--result-blobs): digest-form terminal writes and
announces, the worker result cache + dep delivery, the dispatcher's
reverse-pull machinery (child re-fills and store materialization for
legacy readers), the byte-weighted parent-locality placement lane, and
the off-plane byte-identical contract — unit through in-process e2e.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest
import requests

from tpu_faas.core.executor import pack_params
from tpu_faas.core.payload import RESULT_BLOB_MIN_BYTES, payload_digest
from tpu_faas.core.serialize import deserialize, serialize
from tpu_faas.core.task import (
    FIELD_CHILDREN,
    FIELD_DEPS,
    FIELD_PENDING_DEPS,
    FIELD_RESULT,
    FIELD_RESULT_DIGEST,
    FIELD_RESULT_SIZE,
    FIELD_STATUS,
    TaskStatus,
)
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store import MemoryStore
from tpu_faas.store.base import (
    BLOBREQ_AT_FIELD,
    RESULT_DIGEST_PREFIX,
    blobreq_key,
    decode_result_announce,
    decode_result_announce_full,
    encode_result_announce,
)
from tpu_faas.store.sharding import ShardedStore
from tpu_faas.worker import messages as m
from tpu_faas.worker.push_worker import PushWorker
from tpu_faas.workloads import big_result, merge_deps

WAITING = str(TaskStatus.WAITING)
QUEUED = str(TaskStatus.QUEUED)
COMPLETED = str(TaskStatus.COMPLETED)


# -- announce codec ----------------------------------------------------------


def test_result_announce_digest_form_roundtrip():
    d = payload_digest("BODY")
    payload = encode_result_announce(
        "t1", COMPLETED, "", result_digest=d, result_size=9000
    )
    assert payload.startswith(RESULT_DIGEST_PREFIX)
    # body-oblivious consumers: wake-up with status, NO result (they
    # re-read the record — an empty-string result here would be served
    # as a real body by the express lane)
    tid, status, result = decode_result_announce(payload)
    assert (tid, status, result) == ("t1", COMPLETED, None)
    # digest-aware consumers get the full tuple
    full = decode_result_announce_full(payload)
    assert full == ("t1", COMPLETED, None, d, 9000)


def test_result_announce_legacy_forms_unchanged():
    # id-only and inline express forms decode exactly as before
    assert decode_result_announce("plain-id") == ("plain-id", None, None)
    inline = encode_result_announce("t2", COMPLETED, "small", inline_max=64)
    assert decode_result_announce(inline) == ("t2", COMPLETED, "small")
    assert decode_result_announce_full(inline)[3] is None


# -- store: digest-form terminal writes --------------------------------------


def test_finish_task_digest_form_fields():
    store = MemoryStore()
    store.create_task("t1", "f", "p")
    d = payload_digest("R" * 5000)
    store.finish_task("t1", COMPLETED, "", result_digest=d, result_size=5000)
    rec = store.hgetall("t1")
    assert rec[FIELD_STATUS] == COMPLETED
    assert rec[FIELD_RESULT] == ""
    assert rec[FIELD_RESULT_DIGEST] == d
    assert rec[FIELD_RESULT_SIZE] == "5000"


def test_finish_task_many_mixed_digest_and_legacy_items():
    store = MemoryStore()
    for tid in ("a", "b"):
        store.create_task(tid, "f", "p")
    d = payload_digest("BIG")
    store.finish_task_many(
        [
            ("a", COMPLETED, "", False, d, 3),
            ("b", COMPLETED, "inline-body", False),
        ]
    )
    assert store.hgetall("a")[FIELD_RESULT_DIGEST] == d
    assert store.hgetall("a")[FIELD_RESULT] == ""
    rec_b = store.hgetall("b")
    assert rec_b[FIELD_RESULT] == "inline-body"
    assert FIELD_RESULT_DIGEST not in rec_b


def test_cross_shard_digest_record_and_blob():
    """Satellite: the parent's task record, its result blob, and the
    waiting child can all land on DIFFERENT shards — the digest form
    routes each key independently (record by task id, blob/blobreq by
    digest) and readers resolve across the ring."""
    from tpu_faas.store.base import blob_key

    mems = [MemoryStore() for _ in range(3)]
    store = ShardedStore(mems)
    # find ids/bodies spread over three distinct shards
    parent = next(
        f"p{i}" for i in range(300) if store.shard_of(f"p{i}") == 0
    )
    body, d = next(
        (b, payload_digest(b))
        for b in ("B" * 4200 + str(i) for i in range(300))
        if store.shard_of(blob_key(payload_digest(b))) == 1
    )
    child = next(
        f"c{i}" for i in range(300) if store.shard_of(f"c{i}") == 2
    )
    store.create_task(parent, "f", "p", extra_fields={FIELD_CHILDREN: child})
    store.create_tasks(
        [(child, "f", "p", {FIELD_DEPS: parent, FIELD_PENDING_DEPS: "1"})],
        status=TaskStatus.WAITING,
    )
    store.finish_task(
        parent, COMPLETED, "", result_digest=d, result_size=len(body)
    )
    # the digest-form record landed on the parent's ring shard, readable
    # through the sharded facade
    rec = store.hgetall(parent)
    assert rec[FIELD_RESULT_DIGEST] == d and rec[FIELD_RESULT] == ""
    # materialization (BLOB_MISS fill path writes via put_blob) routes by
    # digest; the read resolves whatever shard it landed on
    assert store.put_blob(d, body) is True
    assert store.get_blob(d) == body
    assert sum(1 for mem in mems if mem.get_blob(d) == body) == 1
    # the blobreq claim key rides the same digest routing
    store.setnx_field(blobreq_key(d), BLOBREQ_AT_FIELD, "1.0")
    assert store.hget(blobreq_key(d), BLOBREQ_AT_FIELD) == "1.0"
    store.delete(blobreq_key(d))
    assert store.hget(blobreq_key(d), BLOBREQ_AT_FIELD) is None


# -- frontier bookkeeping ----------------------------------------------------


class _Task:
    def __init__(self, tid):
        self.task_id = tid


def test_frontier_confirmed_parents_and_cleanup():
    from tpu_faas.graph.frontier import GraphFrontier

    g = GraphFrontier()
    g.add(_Task("child"), ["p1", "p2", "p3"])
    d = payload_digest("RES")
    g.note_parent("p1", True, row=2, digest=d, size=4500)
    g.note_parent("p2", True, row=5)  # store-resident parent: no digest
    g.note_parent("p3", False, row=1)  # failed: never delivered
    assert g.confirmed_parents("child") == [
        ("p1", d, 4500),
        ("p2", None, 0),
    ]
    # pop drops the edges and the now-unreferenced parent states
    assert g.pop("child") is not None
    assert g.confirmed_parents("child") == []
    assert g._parent_state == {}


def test_frontier_pref_arrays_weighs_holder_bytes():
    from tpu_faas.graph.frontier import GraphFrontier

    g = GraphFrontier()
    g.add(_Task("c"), ["p1", "p2"])
    d1, d2 = payload_digest("one"), payload_digest("two")
    g.note_parent("p1", True, row=0, digest=d1, size=6000)
    g.note_parent("p2", True, row=1, digest=d2, size=9000)
    rows = {3: "c"}
    # worker row 4 holds BOTH parents, row 7 only the bigger one
    triplets = g.pref_arrays(
        rows, 16, {d1: {4}, d2: {4, 7}}
    )
    assert triplets is not None
    child, row, nbytes = triplets
    acc = {
        (int(c), int(r)): float(b)
        for c, r, b in zip(child, row, nbytes)
        if int(c) != 16
    }
    assert acc == {(3, 4): 15000.0, (3, 7): 9000.0}
    # no digest-form parents anywhere -> None (jit signature stays off)
    g2 = GraphFrontier()
    g2.add(_Task("c"), ["p"])
    g2.note_parent("p", True, row=0)
    assert g2.pref_arrays({0: "c"}, 16, {}) is None


# -- device lane: parent_pref ------------------------------------------------


def test_parent_pref_scores_max_bytes_and_tie_breaks_low_row():
    import jax.numpy as jnp

    from tpu_faas.graph.frontier import pad_pref, parent_pref

    T = 8
    child, row, nbytes = pad_pref(
        [2, 2, 5, 5], [3, 1, 6, 4], [100.0, 900.0, 500.0, 500.0], T
    )
    out = np.asarray(
        parent_pref(
            jnp.asarray(child), jnp.asarray(row), jnp.asarray(nbytes), T=T
        )
    )
    assert out[2] == 1  # row 1 holds 900 > row 3's 100
    assert out[5] == 4  # equal bytes: lowest row wins
    assert all(out[i] == -1 for i in (0, 1, 3, 4, 6, 7))  # lane-free rows


def test_parent_pref_xla_vs_pallas_interpret_parity():
    """The _impl twin discipline: the same un-jitted body traced by XLA's
    jit and inside a pallas_call (interpret mode on CPU CI) must produce
    EXACTLY equal rows — any drift is a plumbing bug, exactly the
    contract the solver kernels pin in test_sched_pallas.py."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from tpu_faas.graph.frontier import pad_pref, parent_pref_impl

    T = 16
    rng = np.random.default_rng(7)
    lanes = 24
    child = rng.integers(0, T, size=lanes).tolist()
    row = rng.integers(0, 8, size=lanes).tolist()
    nbytes = (rng.integers(0, 5, size=lanes) * 1024.0).tolist()
    c, r, b = pad_pref(child, row, nbytes, T)

    xla = np.asarray(
        jax.jit(parent_pref_impl, static_argnames=("T",))(
            jnp.asarray(c), jnp.asarray(r), jnp.asarray(b), T=T
        )
    )

    def kernel(c_ref, r_ref, b_ref, o_ref):
        o_ref[...] = parent_pref_impl(
            c_ref[...], r_ref[...], b_ref[...], T=T
        )

    pallas = np.asarray(
        pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((T,), jnp.int32),
            interpret=True,
        )(jnp.asarray(c), jnp.asarray(r), jnp.asarray(b))
    )
    assert (xla == pallas).all()


def test_packed_tick_pref_lane_overrides_function_locality():
    """Identical-placement pin for the composed lane: with the pref
    triplets on, a ready child lands on the worker holding its parent's
    result bytes even when function locality prefers another equal-speed
    worker — and the pref-free call keeps its signature (None lanes)."""
    import jax.numpy as jnp

    from tpu_faas.graph.frontier import pad_pref
    from tpu_faas.sched.state import _packed_tick

    T, W = 8, 3
    packed = np.zeros(T + 2 * W, dtype=np.float32)
    packed[:T] = 1.0  # sizes
    packed[T + W :] = 1.0  # one slot per worker: every worker gets a holder
    common = dict(
        n_valid=jnp.int32(3),
        worker_speed=jnp.ones(W, jnp.float32),
        worker_active=jnp.ones(W, dtype=bool),
        prev_live=jnp.ones(W, dtype=bool),
        inflight_worker=jnp.full(16, -1, jnp.int32),
        time_to_expire=jnp.float32(60.0),
        task_priority=None,
        auction_price=None,
    )
    task_pref = np.full(T, -1, dtype=np.int32)
    task_pref[0] = 1  # function locality: worker 1
    base = _packed_tick(
        jnp.asarray(packed),
        *common.values(),
        task_pref=jnp.asarray(task_pref),
        T=T,
        W=W,
        max_slots=4,
        placement="rank",
    )
    c, r, b = pad_pref([0], [2], [8192.0], T)  # result bytes: worker 2
    pref = _packed_tick(
        jnp.asarray(packed),
        *common.values(),
        task_pref=jnp.asarray(task_pref),
        pref_child=jnp.asarray(c),
        pref_row=jnp.asarray(r),
        pref_bytes=jnp.asarray(b),
        T=T,
        W=W,
        max_slots=4,
        placement="rank",
    )
    # three equal tasks on three equal single-slot workers: the exchange
    # can always swap task 0 onto its preferred row
    assert int(np.asarray(base.assignment)[0]) == 1
    assert int(np.asarray(pref.assignment)[0]) == 2


# -- dispatcher: digest intake + reverse pulls --------------------------------


def _mk_disp(**kw):
    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher

    defaults = dict(
        ip="127.0.0.1",
        port=0,
        max_workers=64,
        max_pending=256,
        max_inflight=512,
        tick_period=0.01,
        recover_queued=False,
        store=MemoryStore(),
    )
    defaults.update(kw)
    return TpuPushDispatcher(**defaults)


RBLOB_CAPS = ["blob", "bin", "batch", "rblob"]


def _drain_announces(disp):
    while disp.subscriber.get_message() is not None:
        pass


def test_result_blobs_requires_graph_frontier():
    # frontier-less modes (resident/multihost/shared/mesh) refuse the
    # plane at construction instead of silently never delivering deps
    with pytest.raises(ValueError):
        _mk_disp(result_blobs=True, shared=True)


def test_digest_result_intake_and_dep_digest_dispatch():
    """A digest-only RESULT writes the digest-form record, registers the
    producer, and the waiting child's TASK frame ships dep_digests (no
    body anywhere on the wire or in the store); the parent's own frame
    carried rblob_min because its child was already waiting."""
    disp = _mk_disp(result_blobs=True)
    sent = []
    orig = disp.send_task_frame

    def spy(buf, wid, caps, task, blob, extra=None):
        sent.append((task.task_id, extra))
        return orig(buf, wid, caps, task, blob, extra)

    disp.send_task_frame = spy
    try:
        store = disp.store
        disp._handle(
            b"w0", m.REGISTER, {"num_processes": 2, "caps": RBLOB_CAPS}
        )
        store.create_tasks(
            [
                (
                    "child",
                    "f",
                    "p",
                    {FIELD_DEPS: "parent", FIELD_PENDING_DEPS: "1"},
                )
            ],
            status=TaskStatus.WAITING,
        )
        store.create_tasks([("parent", "f", "p", {FIELD_CHILDREN: "child"})])
        disp.tick()
        assert sent and sent[0][0] == "parent"
        assert sent[0][1] == {"rblob_min": RESULT_BLOB_MIN_BYTES}
        body = "R" * 6000
        d = payload_digest(body)
        disp._handle(
            b"w0",
            m.RESULT,
            {
                "task_id": "parent",
                "status": COMPLETED,
                "result_digest": d,
                "result_size": len(body),
            },
        )
        rec = store.hgetall("parent")
        assert rec[FIELD_RESULT_DIGEST] == d and rec[FIELD_RESULT] == ""
        assert disp._rblob_src[d] == b"w0"
        assert d in disp._worker_rdigests[b"w0"]
        assert store.get_status("child") == QUEUED
        _drain_announces(disp)
        disp.tick()
        child_frames = [e for tid, e in sent if tid == "child"]
        assert child_frames == [{"dep_digests": {"parent": d}}]
        # zero result bytes round-tripped the store
        assert disp.m_result_store_bytes.labels(dir="read").value == 0
        assert disp.m_result_store_bytes.labels(dir="write").value == 0
    finally:
        disp.close()


def test_reverse_pull_fans_out_to_worker_and_store():
    """BLOB_MISS from a child worker AND a gateway blobreq for the same
    digest: one pull to the producer, the FILL fans to the parked worker
    and materializes into the store (request key deleted)."""
    disp = _mk_disp(result_blobs=True)
    wire = []
    disp._send_worker = lambda wid, mt, **kw: wire.append((wid, mt, kw))
    try:
        body = "B" * 5000
        d = payload_digest(body)
        disp._handle(
            b"prod", m.REGISTER, {"num_processes": 2, "caps": RBLOB_CAPS}
        )
        disp._handle(
            b"cons", m.REGISTER, {"num_processes": 2, "caps": RBLOB_CAPS}
        )
        disp._rblob_note_producer(d, len(body), b"prod")
        # a child worker misses, and a legacy reader asks via blobreq
        disp._handle(b"cons", m.BLOB_MISS, {"digest": d})
        disp.note_blobreq(d)
        pulls = [w for w in wire if w[1] == m.BLOB_MISS]
        assert pulls and all(w[0] == b"prod" for w in pulls)
        assert disp._rblob_want[d] == [("worker", b"cons"), ("store", None)]
        # the blobreq claim exists (gateway wrote it) — the fill clears it
        disp.store.setnx_field(blobreq_key(d), BLOBREQ_AT_FIELD, "1.0")
        disp._handle(b"prod", m.BLOB_FILL, {"digest": d, "data": body})
        fills = [w for w in wire if w[1] == m.BLOB_FILL]
        assert fills == [(b"cons", m.BLOB_FILL, {"digest": d, "data": body})]
        assert d in disp._worker_rdigests[b"cons"]  # fill seeds the mirror
        assert disp.store.get_blob(d) == body
        assert disp.store.hget(blobreq_key(d), BLOBREQ_AT_FIELD) is None
        assert disp.m_rblob_pulls.labels(outcome="filled").value == 1
        assert (
            disp.m_result_store_bytes.labels(dir="write").value == len(body)
        )
        assert d not in disp._rblob_want
    finally:
        disp.close()


def test_reverse_pull_missing_body_fails_consumers():
    disp = _mk_disp(result_blobs=True)
    wire = []
    disp._send_worker = lambda wid, mt, **kw: wire.append((wid, mt, kw))
    try:
        d = payload_digest("evicted")
        disp._handle(
            b"prod", m.REGISTER, {"num_processes": 2, "caps": RBLOB_CAPS}
        )
        disp._handle(
            b"cons", m.REGISTER, {"num_processes": 2, "caps": RBLOB_CAPS}
        )
        disp._rblob_note_producer(d, 10, b"prod")
        disp._handle(b"cons", m.BLOB_MISS, {"digest": d})
        disp._handle(b"prod", m.BLOB_FILL, {"digest": d, "missing": True})
        assert (b"cons", m.BLOB_FILL, {"digest": d, "missing": True}) in wire
        assert d not in disp._rblob_src  # the source is forgotten
        assert disp.m_rblob_pulls.labels(outcome="missing").value == 1
        # a pull for a digest NO producer ever announced fails immediately
        ghost = payload_digest("never")
        disp._handle(b"cons", m.BLOB_MISS, {"digest": ghost})
        assert (
            b"cons",
            m.BLOB_FILL,
            {"digest": ghost, "missing": True},
        ) in wire
    finally:
        disp.close()


def test_reverse_pull_resend_sweep_and_reconnect_clears_mirror():
    from tpu_faas.dispatch.tpu_push import _RBLOB_PULL_RESEND_S

    disp = _mk_disp(result_blobs=True)
    wire = []
    disp._send_worker = lambda wid, mt, **kw: wire.append((wid, mt, kw))
    now = [100.0]
    disp.clock = lambda: now[0]
    try:
        d = payload_digest("slow")
        disp._handle(
            b"prod", m.REGISTER, {"num_processes": 2, "caps": RBLOB_CAPS}
        )
        disp._rblob_note_producer(d, 10, b"prod")
        disp._rblob_pull(d, ("store", None))
        assert len([w for w in wire if w[1] == m.BLOB_MISS]) == 1
        disp._rblob_resend_sweep()  # too soon: no resend
        assert len([w for w in wire if w[1] == m.BLOB_MISS]) == 1
        now[0] += _RBLOB_PULL_RESEND_S + 0.1
        disp._rblob_resend_sweep()
        assert len([w for w in wire if w[1] == m.BLOB_MISS]) == 2
        # a fresh-process RECONNECT (empty result cache) drops the mirror
        assert disp._worker_rdigests.get(b"prod")
        disp._handle(
            b"prod",
            m.RECONNECT,
            {"free_processes": 2, "rcache_n": 0, "rcache_bytes": 0},
        )
        assert b"prod" not in disp._worker_rdigests
    finally:
        disp.close()


def test_plane_off_frames_and_records_are_legacy_shaped():
    """Both flags off: every TASK frame ships with extra=None (the wire
    is byte-identical to the pre-plane dispatcher) and a full-body RESULT
    writes the legacy record with no digest fields."""
    disp = _mk_disp()
    assert disp.result_blobs is False and disp.dep_results_on is False
    sent = []
    orig = disp.send_task_frame

    def spy(buf, wid, caps, task, blob, extra=None):
        sent.append((task.task_id, extra))
        return orig(buf, wid, caps, task, blob, extra)

    disp.send_task_frame = spy
    try:
        store = disp.store
        disp._handle(
            b"w0", m.REGISTER, {"num_processes": 2, "caps": RBLOB_CAPS}
        )
        store.create_tasks(
            [
                (
                    "child",
                    "f",
                    "p",
                    {FIELD_DEPS: "parent", FIELD_PENDING_DEPS: "1"},
                )
            ],
            status=TaskStatus.WAITING,
        )
        store.create_tasks([("parent", "f", "p", {FIELD_CHILDREN: "child"})])
        disp.tick()
        # digest fields on the frame are ignored off-plane: a worker never
        # sends them without rblob_min, but even a rogue one cannot flip
        # the record into digest form
        disp._handle(
            b"w0",
            m.RESULT,
            {
                "task_id": "parent",
                "status": COMPLETED,
                "result": "full-body",
                "result_digest": payload_digest("x"),
                "result_size": 1,
            },
        )
        rec = store.hgetall("parent")
        assert rec[FIELD_RESULT] == "full-body"
        assert FIELD_RESULT_DIGEST not in rec
        _drain_announces(disp)
        disp.tick()
        assert sent and all(extra is None for _tid, extra in sent)
        assert not disp._rblob_src and not disp._result_meta
    finally:
        disp.close()


# -- in-process e2e: worker digest ship, cache delivery, gateway read --------


def _make_chain(store, n_kib=8, tag="mrg"):
    """parent (big_result) -> child (merge_deps) directly in the store."""
    store.create_tasks(
        [
            (
                "child",
                serialize(merge_deps),
                pack_params(tag),
                {FIELD_DEPS: "parent", FIELD_PENDING_DEPS: "1"},
            )
        ],
        status=TaskStatus.WAITING,
    )
    store.create_tasks(
        [
            (
                "parent",
                serialize(big_result),
                pack_params(n_kib),
                {FIELD_CHILDREN: "child"},
            )
        ]
    )


def test_result_plane_e2e_chain_never_round_trips_store():
    """Full in-process stack: TpuPushDispatcher(--result-blobs) + a real
    PushWorker. The parent's 8 KiB result stays in the worker's result
    cache (digest-form record, zero result store bytes in either
    direction), the child consumes it via dep_digests from that cache,
    and a legacy gateway reader then materializes the body on demand
    through the reverse pull."""
    store = MemoryStore()
    disp = _mk_disp(result_blobs=True, store=store)
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    gw = start_gateway_thread(store)
    _make_chain(store)
    worker = PushWorker(
        2,
        f"tcp://127.0.0.1:{disp.port}",
        heartbeat=True,
        heartbeat_period=0.2,
    )
    wt = threading.Thread(target=worker.run, daemon=True)
    wt.start()
    try:
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if store.get_status("child") == COMPLETED:
                break
            time.sleep(0.02)
        status, child_result = store.get_result("child")
        assert status == COMPLETED
        # every parent byte arrived at the child (8 KiB body, 1 parent)
        assert deserialize(child_result) == "mrg:1:8192"
        # the parent record is digest-form: no body in the store
        rec = store.hgetall("parent")
        digest = rec[FIELD_RESULT_DIGEST]
        assert rec[FIELD_RESULT] == "" and int(rec[FIELD_RESULT_SIZE]) > 4096
        assert store.get_blob(digest) is None  # never materialized so far
        assert worker.result_cache.hits >= 1  # dep served from the cache
        assert disp.m_result_store_bytes.labels(dir="read").value == 0
        # the only result body the store ever saw is the child's own tiny
        # final answer (below the blob threshold — a leaf result is FOR
        # the client, it must land); the parent's 8 KiB never wrote
        assert (
            0
            < disp.m_result_store_bytes.labels(dir="write").value
            < RESULT_BLOB_MIN_BYTES
        )
        # legacy reader: gateway /result materializes via the reverse pull
        r = requests.get(f"{gw.url}/result/parent", timeout=10)
        assert r.status_code == 200
        body = r.json()["result"]
        assert deserialize(body) == big_result(8)
        assert store.get_blob(digest) == body  # now store-resident
        assert disp.m_rblob_pulls.labels(outcome="filled").value >= 1
    finally:
        worker.stop()
        wt.join(timeout=10)
        gw.stop()
        disp.stop()
        t.join(timeout=10)
        disp.close()


def test_dep_results_control_lane_reads_bodies_from_store():
    """--dep-results without --result-blobs: the store-mediated control
    lane. The parent's full body lands in the store, and the child's
    frame carries dep_results read back from it — the read the digest
    path deletes, counted in result_store_bytes{dir=read}."""
    store = MemoryStore()
    disp = _mk_disp(dep_results=True, store=store)
    assert disp.dep_results_on and not disp.result_blobs
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    _make_chain(store, tag="ctl")
    worker = PushWorker(
        2,
        f"tcp://127.0.0.1:{disp.port}",
        heartbeat=True,
        heartbeat_period=0.2,
    )
    wt = threading.Thread(target=worker.run, daemon=True)
    wt.start()
    try:
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if store.get_status("child") == COMPLETED:
                break
            time.sleep(0.02)
        status, child_result = store.get_result("child")
        assert status == COMPLETED
        assert deserialize(child_result) == "ctl:1:8192"
        rec = store.hgetall("parent")
        assert rec[FIELD_RESULT] != ""  # full body in the store
        assert FIELD_RESULT_DIGEST not in rec
        assert disp.m_result_store_bytes.labels(dir="read").value >= 8192
        assert worker.result_cache.hits == 0  # nothing rode the cache
    finally:
        worker.stop()
        wt.join(timeout=10)
        disp.stop()
        t.join(timeout=10)
        disp.close()


def test_gateway_returns_410_when_body_unrecoverable():
    """A digest-form record whose producer is gone (no dispatcher will
    ever answer the blobreq): the gateway's bounded materialization poll
    expires and the reader gets a permanent 410, not a hang."""
    from tpu_faas.gateway import app as gw_app

    store = MemoryStore()
    store.create_task("t-gone", "f", "p")
    d = payload_digest("lost-forever")
    store.finish_task(
        "t-gone", COMPLETED, "", result_digest=d, result_size=12
    )
    gw = start_gateway_thread(store)
    old_wait = gw_app._BLOBREQ_WAIT_S
    gw_app._BLOBREQ_WAIT_S = 0.3  # keep the test fast
    try:
        r = requests.get(f"{gw.url}/result/t-gone", timeout=10)
        assert r.status_code == 410
        # the request claim was left for the sweeper to age out
        assert store.hget(blobreq_key(d), BLOBREQ_AT_FIELD) is not None
    finally:
        gw_app._BLOBREQ_WAIT_S = old_wait
        gw.stop()


def test_blob_gc_result_blobs_and_blobreq_aging():
    """Satellite: the refcount-or-TTL sweep extends to result blobs — a
    blob referenced by a digest-form record survives any staleness, an
    orphaned one ages out, and stale blobreq claims are collected."""
    from tpu_faas.gateway.app import _sweep_expired_results
    from tpu_faas.store.base import BLOB_AT_FIELD, blob_key

    store = MemoryStore()
    now = time.time()
    # blobs age at 4x the result TTL (a refill costs more than a stale
    # record): 15 000 s > 4 * 3600, past both the blob and blobreq bars
    old = repr(now - 15_000.0)
    # referenced by a terminal digest-form record: kept however stale
    d_ref = payload_digest("REFERENCED")
    store.put_blob(d_ref, "REFERENCED")
    store.hset(blob_key(d_ref), {BLOB_AT_FIELD: old})
    store.create_task("t-done", "f", "p")
    store.finish_task(
        "t-done", COMPLETED, "", result_digest=d_ref, result_size=10
    )
    # orphaned result blob (its record was swept long ago): collected
    d_orphan = payload_digest("ORPHANED")
    store.put_blob(d_orphan, "ORPHANED")
    store.hset(blob_key(d_orphan), {BLOB_AT_FIELD: old})
    # stale + fresh blobreq claims
    d_req = payload_digest("REQ")
    store.setnx_field(blobreq_key(d_req), BLOBREQ_AT_FIELD, old)
    d_req2 = payload_digest("REQ2")
    store.setnx_field(blobreq_key(d_req2), BLOBREQ_AT_FIELD, repr(now))
    _sweep_expired_results(store, ttl=3600.0, now=now)
    assert store.get_blob(d_ref) == "REFERENCED"
    assert store.get_blob(d_orphan) is None
    assert store.hget(blobreq_key(d_req), BLOBREQ_AT_FIELD) is None
    assert store.hget(blobreq_key(d_req2), BLOBREQ_AT_FIELD) is not None
