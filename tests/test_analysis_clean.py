"""Tier-1 gate: the static analysis pass is clean on the shipped tree.

This is the static complement of the runtime racecheck suite: every store
write site, every jitted kernel, every lock region, every ``async def``,
every store-command registry, every statically-spelled store key, and
every metric registration in ``tpu_faas/`` is verified at rest. A new
error-severity finding here means a change broke the store-write
protocol, made a jitted function trace-unsafe, put a blocking call under
a lock or on an event loop, let the store-command registries drift apart,
minted an undeclared shard-routing namespace, or broke metrics
discipline — fix it or suppress it at the site with a justified
``# faas: allow(<rule>)`` (a suppression that stops matching becomes a
``core.stale-suppression`` warning, which this gate also keeps at
zero).
"""

from __future__ import annotations

from pathlib import Path

import tpu_faas
from tpu_faas.analysis import run_paths
from tpu_faas.analysis.__main__ import main as analysis_main

PACKAGE = Path(tpu_faas.__file__).parent


def test_package_has_no_error_findings():
    findings = run_paths([PACKAGE])
    errors = [f for f in findings if f.severity == "error"]
    assert not errors, "static analysis found:\n" + "\n".join(
        str(f) for f in errors
    )


def test_package_has_no_warning_findings():
    """Warnings don't fail the CLI gate, but the shipped tree keeps zero of
    them too — a warning that appears is either fixed or explicitly
    suppressed with a justification, never left to normalize noise."""
    findings = run_paths([PACKAGE])
    assert not findings, "static analysis found:\n" + "\n".join(
        str(f) for f in findings
    )


def test_cli_exits_zero_on_package(capsys):
    assert analysis_main([str(PACKAGE)]) == 0
    out = capsys.readouterr().out
    assert "0 error(s)" in out


def test_trace_scope_covers_the_scheduler_kernels():
    """Guard the discovery half of the trace checker: if a refactor ever
    made jit-site detection silently miss the kernels, the clean result
    above would be vacuous. The scheduler/parallel layers ship 12+ jit
    sites today; require the checker to keep seeing jitted functions in
    the core kernel modules."""
    from tpu_faas.analysis.core import Module
    from tpu_faas.analysis.tracesafety import TraceSafetyChecker

    kernel_modules = [
        PACKAGE / "sched" / "sinkhorn.py",
        PACKAGE / "sched" / "greedy.py",
        PACKAGE / "sched" / "auction.py",
        PACKAGE / "sched" / "resident.py",
        PACKAGE / "sched" / "state.py",
        PACKAGE / "sched" / "pallas_kernels.py",
        PACKAGE / "parallel" / "mesh.py",
    ]
    traced_total = 0
    for path in kernel_modules:
        module = Module.parse(path, str(path), path.read_text())
        checker = TraceSafetyChecker()
        seen: list[str] = []
        original = checker._check_traced

        def record(mod, fn, fn_name, static, _seen=seen, _orig=original):
            _seen.append(fn_name)
            return _orig(mod, fn, fn_name, static)

        checker._check_traced = record
        list(checker.check(module))
        assert seen, f"no traced functions discovered in {path.name}"
        traced_total += len(seen)
    assert traced_total >= 12
