"""Black-box integration tests for pull/push modes with real worker
subprocesses (analog of reference test_client.py: spawn everything, submit a
workload over REST, verify every result against local re-execution)."""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from contextlib import contextmanager

import pytest

from tpu_faas.client import FaaSClient
from tpu_faas.dispatch.pull import PullDispatcher
from tpu_faas.dispatch.push import PushDispatcher
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store.launch import make_store, start_store_thread
from tpu_faas.workloads import make_workload, sleep_task

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _GroupPopen(subprocess.Popen):
    """Popen whose kill()/terminate() signal the whole process group.

    A worker owns a multiprocessing pool (children + a resource_tracker
    helper). Crash tests SIGKILL the worker pid; with a plain Popen the
    helpers are orphaned to pid 1 and ACCUMULATE across test runs — hundreds
    of them were measured saturating a CI box (load >19), starving later
    tests. start_new_session=True puts every helper in the worker's group so
    one killpg reaps the lot."""

    def send_signal(self, sig) -> None:
        # The single signal-routing point (POSIX Popen.kill()/terminate()
        # both funnel here): SIGKILL goes to the GROUP — a plain Popen
        # delivers it to the leader only, re-orphaning the pool helpers
        # this class exists to reap (94 of them measured after one
        # full-suite run, load >9, flaking the scale tests). Every other
        # signal (notably SIGTERM) stays leader-only ON PURPOSE:
        # graceful-drain tests SIGTERM the worker and need its pool
        # children alive to finish their in-flight tasks.
        if sig == signal.SIGKILL:
            try:
                os.killpg(self.pid, sig)
                return
            except (ProcessLookupError, PermissionError):
                pass
        super().send_signal(sig)


def _spawn_worker(kind: str, n_procs: int, url: str, *extra: str):
    # shared env builder: repo on PYTHONPATH, jax-importing sitecustomize
    # dirs stripped (see cpu_worker_env's docstring for the cold-start
    # numbers behind this)
    from tpu_faas.bench.harness import cpu_worker_env

    env = cpu_worker_env()
    return _GroupPopen(
        [sys.executable, "-m", f"tpu_faas.worker.{kind}", str(n_procs), url]
        + list(extra),
        env=env,
        cwd=REPO,
        start_new_session=True,
    )


@contextmanager
def stack(mode: str, n_workers: int = 2, n_procs: int = 2, **disp_kw):
    """store server + gateway + dispatcher thread + worker subprocesses."""
    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    if mode == "pull":
        disp = PullDispatcher(
            ip="127.0.0.1", port=0, store=make_store(store_handle.url), **disp_kw
        )
        worker_kind, extra = "pull_worker", ("--delay", "0.005")
    else:
        disp = PushDispatcher(
            ip="127.0.0.1", port=0, store=make_store(store_handle.url), **disp_kw
        )
        worker_kind = "push_worker"
        extra = ("--hb", "--hb-period", "0.3") if disp_kw.get("heartbeat") else ()
    disp_thread = threading.Thread(target=disp.start, daemon=True)
    disp_thread.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        _spawn_worker(worker_kind, n_procs, url, *extra)
        for _ in range(n_workers)
    ]
    try:
        yield FaaSClient(gw.url), workers, disp
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.wait()
        disp.stop()
        disp_thread.join(timeout=10)
        gw.stop()
        store_handle.stop()


def service_test(client: FaaSClient, n_tasks: int = 20, timeout: float = 90.0):
    """The reference's correctness oracle (test_client.py:95-129): submit
    n_tasks, poll all results, compare to local re-execution."""
    fn, params = make_workload("arithmetic", n_tasks, 2000, seed=1)
    fid = client.register(fn)
    handles = [client.submit(fid, *a, **k) for a, k in params]
    for handle, (a, k) in zip(handles, params):
        assert handle.result(timeout=timeout) == fn(*a, **k)


@pytest.mark.parametrize(
    "mode,kw",
    [
        ("pull", {}),
        ("push", {}),
        ("push", {"process_lb": True}),
        ("push", {"heartbeat": True}),
    ],
    ids=["pull", "push-lru", "push-plb", "push-hb"],
)
def test_mode_end_to_end(mode, kw):
    with stack(mode, n_workers=2, n_procs=2, **kw) as (client, workers, _):
        service_test(client, n_tasks=20)


def test_push_hb_worker_crash_redispatches_inflight():
    """The capability the reference lacks (SURVEY §5.3): killing a worker
    with tasks in flight must not lose them — the dispatcher purges the
    worker and re-queues its tasks onto the survivors."""
    with stack(
        "push", n_workers=2, n_procs=2, heartbeat=True, time_to_expire=1.5
    ) as (client, workers, disp):
        fid = client.register(sleep_task)
        # enough slow tasks to occupy both workers fully, then some
        handles = [client.submit(fid, 1.0) for _ in range(8)]
        time.sleep(0.8)  # let tasks land on workers
        workers[0].send_signal(signal.SIGKILL)  # hard crash, no goodbye
        workers[0].wait()
        for h in handles:
            assert h.result(timeout=60.0) == 1.0


def test_push_worker_reconnect_after_dispatcher_restart_message():
    """A worker unknown to the dispatcher (e.g. after dispatcher restart)
    gets a reconnect request and resumes serving."""
    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    disp = PushDispatcher(
        ip="127.0.0.1", port=0, store=make_store(store_handle.url),
        heartbeat=True, time_to_expire=5.0,
    )
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    worker = _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
    client = FaaSClient(gw.url)
    try:
        service_test(client, n_tasks=4)
        # simulate dispatcher restart: forget the worker entirely
        disp.workers.clear()
        disp.free_lru.clear()
        # worker's next heartbeat triggers reconnect handshake; tasks flow again
        service_test(client, n_tasks=4)
    finally:
        worker.kill()
        worker.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def test_process_lb_free_tokens_lazy_and_bounded():
    """Process-LB free-list maintenance is O(1) per event: stale tokens are
    discarded lazily by _pick_worker's validation, and a reconnect storm
    triggers a (rare, amortized) compaction instead of unbounded growth."""
    from tpu_faas.dispatch.push import PushDispatcher
    from tpu_faas.store.memory import MemoryStore

    d = PushDispatcher(
        ip="127.0.0.1", port=0, store=MemoryStore(), process_lb=True,
        heartbeat=True,
    )
    try:
        d._handle(b"w1", "register", {"num_processes": 2})
        d._handle(b"w2", "register", {"num_processes": 2})
        assert len(d.free_procs) == 4
        # a reconnect storm: re-register w1 fifty times — tokens stay
        # bounded by the compaction guard (4x real capacity)
        for _ in range(50):
            d._handle(b"w1", "register", {"num_processes": 2})
        assert len(d.free_procs) <= 4 * 4
        # every pick still lands on a worker with real capacity, and total
        # picks cannot exceed true capacity
        picks = []
        while True:
            wid = d._pick_worker()
            if wid is None:
                break
            d.workers[wid].free_processes -= 1  # what dispatch would do
            picks.append(wid)
        assert len(picks) == 4  # 2+2 real process slots, stale tokens skipped
        assert picks.count(b"w1") == 2 and picks.count(b"w2") == 2
    finally:
        d.socket.close(linger=0)


def test_bounded_drain_leaves_excess_for_next_round():
    """A flooding worker must not starve the serve loop: one drain round
    decodes at most _DRAIN_CAP messages; the excess stays in the ZMQ
    buffer and (level-triggered poller) is picked up next round — the
    dispatcher gets its purge/dispatch steps in between."""
    import zmq

    from tpu_faas.dispatch.push import PushDispatcher
    from tpu_faas.store.memory import MemoryStore
    from tpu_faas.worker import messages as m

    d = PushDispatcher(
        ip="127.0.0.1", port=0, store=MemoryStore(), heartbeat=True
    )
    flooder = zmq.Context.instance().socket(zmq.DEALER)
    # fail fast instead of hanging if the ZMQ HWMs + TCP buffers can't
    # absorb the whole flood before any drain runs
    flooder.setsockopt(zmq.SNDTIMEO, 5000)
    flooder.setsockopt(zmq.SNDHWM, 0)  # unlimited sender queue
    flooder.connect(f"tcp://127.0.0.1:{d.port}")
    try:
        n_flood = d._DRAIN_CAP + 500
        flooder.send(m.encode(m.REGISTER, num_processes=1))
        for _ in range(n_flood - 1):
            flooder.send(m.encode(m.HEARTBEAT))
        # wait until the messages are deliverable, then drain ONE round
        poller = zmq.Poller()
        poller.register(d.socket, zmq.POLLIN)
        assert dict(poller.poll(5000)), "flood never arrived"
        handled = []
        deadline = time.time() + 10
        first = 0
        while time.time() < deadline:
            n = d.drain_worker_messages(
                d.socket, lambda w, t, data: handled.append(t)
            )
            if not first:
                first = n
            if len(handled) >= n_flood:
                break
            time.sleep(0.01)
        # the flood genuinely exceeded one round (excess left for later
        # rounds — the starvation fix's observable behavior), and the
        # total still arrived across rounds with nothing lost
        assert first < n_flood, "one round drained the whole flood"
        assert len(handled) == n_flood
        assert handled[0] == m.REGISTER
    finally:
        flooder.close(linger=0)
        d.socket.close(linger=0)


def poll_stats(port: int, timeout: float = 30.0) -> dict:
    """Poll a dispatcher's /stats endpoint until it answers (shared by the
    chaos and multihost e2e suites — one copy of the retry loop)."""
    import json
    import urllib.request

    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/stats", timeout=2
            ) as r:
                return json.loads(r.read())
        except OSError:
            time.sleep(0.2)
    raise AssertionError(f"stats endpoint on port {port} never came up")
