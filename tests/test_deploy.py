"""Worker-fleet deployer: supervised spawn, crash respawn, graceful drain.

Production counterpart of the reference's scrap-heap launcher
(old/deploy_workers.py) — plus the supervision it lacked: a SIGKILLed
worker is respawned and the dispatcher re-dispatches its in-flight tasks,
so the fleet self-heals end to end.
"""

from __future__ import annotations

import threading
import time

from tpu_faas.client import FaaSClient
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store.launch import make_store, start_store_thread
from tpu_faas.worker.deploy import WorkerFleet
from tpu_faas.workloads import arithmetic
from tests.test_tpu_push_e2e import _make_dispatcher


def test_fleet_spawn_crash_respawn_drain():
    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    disp = _make_dispatcher(store_handle.url, time_to_expire=1.5)
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"

    fleet = WorkerFleet(
        2,
        1,
        url,
        heartbeat=True,
        hb_period=0.3,
        restart=True,
        restart_backoff=0.1,
    )
    client = FaaSClient(gw.url)
    try:
        fleet.start()
        assert fleet.n_live == 2

        fid = client.register(arithmetic)
        assert [h.result(30) for h in (client.submit(fid, 100),)] == [
            arithmetic(100)
        ]

        # SIGKILL one worker: poll() must respawn it (crash path), and the
        # stack must keep completing work through the heal
        fleet.procs[0].kill()
        fleet.procs[0].wait()
        deadline = time.monotonic() + 10
        while fleet.n_live < 2 and time.monotonic() < deadline:
            fleet.poll()
            time.sleep(0.05)
        assert fleet.restarts == 1
        assert fleet.n_live == 2

        handles = [client.submit(fid, n) for n in range(5)]
        assert [h.result(30) for h in handles] == [
            arithmetic(n) for n in range(5)
        ]

        # graceful drain: everyone exits, nothing respawns
        fleet.stop()
        assert fleet.n_live == 0
        assert fleet.poll() == 0
    finally:
        if fleet.n_live:
            fleet.stop()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def test_autoscaler_policy_unit():
    """Pure policy: up on backlog, down only after a sustained quiet
    period, always within [min, max] — driven with fake stats, no HTTP."""
    from tpu_faas.worker.deploy import AutoScaler

    class FakeFleet:
        def __init__(self):
            self.n_live = 2

        def scale_up(self):
            self.n_live += 1

        def scale_down(self):
            self.n_live -= 1
            return self.n_live

    fleet = FakeFleet()
    sc = AutoScaler(fleet, min_workers=1, max_workers=4, idle_decisions=3)

    assert sc.step({"pending": 10, "inflight": 0}) == "up"
    assert sc.step({"pending": 10, "inflight": 0}) == "up"
    assert fleet.n_live == 4
    assert sc.step({"pending": 10, "inflight": 0}) is None  # at max

    # busy-but-not-backlogged: hold steady, idle streak resets
    assert sc.step({"pending": 0, "inflight": 3}) is None
    assert sc.step({"pending": 0, "inflight": 0}) is None  # idle 1
    assert sc.step({"pending": 0, "inflight": 1}) is None  # reset
    for _ in range(2):
        assert sc.step({"pending": 0, "inflight": 0}) is None
    assert sc.step({"pending": 0, "inflight": 0}) == "down"  # idle 3
    assert fleet.n_live == 3
    # streak restarts after a shrink: no immediate second drain
    assert sc.step({"pending": 0, "inflight": 0}) is None


def test_autoscaler_end_to_end_grows_and_shrinks():
    """Real stack: a burst of slow tasks grows the fleet from 1 toward max;
    a sustained quiet period drains it back down — gracefully, so every
    result still lands."""
    from tpu_faas.worker.deploy import AutoScaler, _fetch_stats

    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    disp = _make_dispatcher(store_handle.url)
    stats_server = disp.serve_stats(port=0)
    stats_url = f"http://127.0.0.1:{stats_server.server_address[1]}/stats"
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"

    fleet = WorkerFleet(1, 1, url, heartbeat=True, hb_period=0.3)
    scaler = AutoScaler(fleet, min_workers=1, max_workers=3, idle_decisions=4)
    client = FaaSClient(gw.url)
    try:
        fleet.start()
        from tpu_faas.workloads import sleep_task

        fid = client.register(sleep_task)
        handles = client.submit_many(fid, [((0.8,), {}) for _ in range(8)])

        deadline = time.monotonic() + 60
        while fleet.n_live < 3 and time.monotonic() < deadline:
            fleet.poll()
            stats = _fetch_stats(stats_url)
            if stats:
                scaler.step(stats)
            time.sleep(0.3)
        assert fleet.n_live == 3, "backlog did not grow the fleet"
        assert scaler.scale_ups >= 2

        assert [h.result(timeout=60) for h in handles] == [0.8] * 8

        deadline = time.monotonic() + 60
        while fleet.n_live > 1 and time.monotonic() < deadline:
            fleet.poll()
            stats = _fetch_stats(stats_url)
            if stats:
                scaler.step(stats)
            time.sleep(0.2)
        assert fleet.n_live == 1, "quiet fleet did not shrink to the floor"
        assert scaler.scale_downs >= 2
    finally:
        fleet.stop()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()
