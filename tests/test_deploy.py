"""Worker-fleet deployer: supervised spawn, crash respawn, graceful drain.

Production counterpart of the reference's scrap-heap launcher
(old/deploy_workers.py) — plus the supervision it lacked: a SIGKILLed
worker is respawned and the dispatcher re-dispatches its in-flight tasks,
so the fleet self-heals end to end.
"""

from __future__ import annotations

import threading
import time

from tpu_faas.client import FaaSClient
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store.launch import make_store, start_store_thread
from tpu_faas.worker.deploy import WorkerFleet
from tpu_faas.workloads import arithmetic
from tests.test_tpu_push_e2e import _make_dispatcher


def test_fleet_spawn_crash_respawn_drain():
    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    disp = _make_dispatcher(store_handle.url, time_to_expire=1.5)
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"

    fleet = WorkerFleet(
        2,
        1,
        url,
        heartbeat=True,
        hb_period=0.3,
        restart=True,
        restart_backoff=0.1,
    )
    client = FaaSClient(gw.url)
    try:
        fleet.start()
        assert fleet.n_live == 2

        fid = client.register(arithmetic)
        assert [h.result(30) for h in (client.submit(fid, 100),)] == [
            arithmetic(100)
        ]

        # SIGKILL one worker: poll() must respawn it (crash path), and the
        # stack must keep completing work through the heal
        fleet.procs[0].kill()
        fleet.procs[0].wait()
        deadline = time.monotonic() + 10
        while fleet.n_live < 2 and time.monotonic() < deadline:
            fleet.poll()
            time.sleep(0.05)
        assert fleet.restarts == 1
        assert fleet.n_live == 2

        handles = [client.submit(fid, n) for n in range(5)]
        assert [h.result(30) for h in handles] == [
            arithmetic(n) for n in range(5)
        ]

        # graceful drain: everyone exits, nothing respawns
        fleet.stop()
        assert fleet.n_live == 0
        assert fleet.poll() == 0
    finally:
        if fleet.n_live:
            fleet.stop()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def test_autoscaler_policy_unit():
    """Pure policy: up on backlog, down only after a sustained quiet
    period, always within [min, max] — driven with fake stats, no HTTP."""
    from tpu_faas.worker.deploy import AutoScaler

    class FakeFleet:
        def __init__(self):
            self.n_live = 2

        def scale_up(self):
            self.n_live += 1

        def scale_down(self):
            self.n_live -= 1
            return self.n_live

    fleet = FakeFleet()
    sc = AutoScaler(fleet, min_workers=1, max_workers=4, idle_decisions=3)

    assert sc.step({"pending": 10, "inflight": 0}) == "up"
    assert sc.step({"pending": 10, "inflight": 0}) == "up"
    assert fleet.n_live == 4
    assert sc.step({"pending": 10, "inflight": 0}) is None  # at max

    # busy-but-not-backlogged: hold steady, idle streak resets
    assert sc.step({"pending": 0, "inflight": 3}) is None
    assert sc.step({"pending": 0, "inflight": 0}) is None  # idle 1
    assert sc.step({"pending": 0, "inflight": 1}) is None  # reset
    for _ in range(2):
        assert sc.step({"pending": 0, "inflight": 0}) is None
    assert sc.step({"pending": 0, "inflight": 0}) == "down"  # idle 3
    assert fleet.n_live == 3
    # streak restarts after a shrink: no immediate second drain
    assert sc.step({"pending": 0, "inflight": 0}) is None


def test_autoscaler_backlog_estimate_sizes_the_jump():
    """With a learned-runtime backlog estimate the scaler adds ENOUGH
    nodes to hit the drain target in one decision (bounded by max), and
    falls back to one-node steps when the estimate is absent/None."""
    from tpu_faas.worker.deploy import AutoScaler

    class FakeFleet:
        def __init__(self):
            self.n_live = 2

        def scale_up(self):
            self.n_live += 1

        def scale_down(self):
            self.n_live -= 1
            return self.n_live

    fleet = FakeFleet()
    sc = AutoScaler(
        fleet, min_workers=1, max_workers=16, idle_decisions=3,
        drain_target_s=30.0,
    )
    # 2 registered nodes drain in 90s -> want 3x total -> +4 nodes at once
    assert sc.step(
        {"pending": 50, "inflight": 0, "backlog_est_s": 90.0,
         "workers_registered": 2}
    ) == "up"
    assert fleet.n_live == 6
    # SAME stats next decision (spawned nodes not yet registered): the
    # desired total is computed from workers_registered, so the jump does
    # NOT compound toward max while registration is in flight
    assert sc.step(
        {"pending": 50, "inflight": 0, "backlog_est_s": 90.0,
         "workers_registered": 2}
    ) is None
    assert fleet.n_live == 6
    # below the target: a single-node nudge
    assert sc.step(
        {"pending": 5, "inflight": 0, "backlog_est_s": 10.0,
         "workers_registered": 6}
    ) == "up"
    assert fleet.n_live == 7
    # estimator off (None): classic one-node policy
    assert sc.step(
        {"pending": 5, "inflight": 0, "backlog_est_s": None}
    ) == "up"
    assert fleet.n_live == 8
    # the jump is capped at max_workers
    assert sc.step(
        {"pending": 500, "inflight": 0, "backlog_est_s": 3600.0,
         "workers_registered": 8}
    ) == "up"
    assert fleet.n_live == 16
    assert sc.step(
        {"pending": 500, "inflight": 0, "backlog_est_s": 3600.0,
         "workers_registered": 16}
    ) is None  # at max


def test_dispatcher_backlog_estimate():
    """tpu-push serves backlog_est_s from learned runtimes: None before
    anything is learned, then pending-work seconds over the fleet's
    procs x speed rate."""
    from tpu_faas.dispatch.base import PendingTask
    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
    from tpu_faas.store.memory import MemoryStore

    disp = TpuPushDispatcher(
        ip="127.0.0.1", port=0, max_workers=8, max_pending=32,
        max_inflight=32, store=MemoryStore(),
    )
    try:
        est = disp.estimator
        assert est is not None
        assert disp._backlog_estimate_s() is None  # nothing learned yet
        for _ in range(4):
            est.observe("digest-a", 2.0, b"w0")  # runtime 2 s at speed 1
        a = disp.arrays
        a.register(b"w0", 2)  # one worker, 2 procs, speed 1.0
        disp.pending.extend(
            PendingTask(f"t{i}", "F", "P", learned=2.0) for i in range(6)
        )
        # 6 tasks x 2 s over rate 2 procs x 1.0 = 6 s
        assert abs(disp._backlog_estimate_s() - 6.0) < 1e-6
        assert disp.stats()["backlog_est_s"] == 6.0
    finally:
        disp.socket.close(linger=0)  # never served: close the bind directly
        disp.close()


def test_autoscaler_end_to_end_grows_and_shrinks():
    """Real stack: a burst of slow tasks grows the fleet from 1 toward max;
    a sustained quiet period drains it back down — gracefully, so every
    result still lands."""
    from tpu_faas.worker.deploy import AutoScaler, _fetch_stats

    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    disp = _make_dispatcher(store_handle.url)
    stats_server = disp.serve_stats(port=0)
    stats_url = f"http://127.0.0.1:{stats_server.server_address[1]}/stats"
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"

    fleet = WorkerFleet(1, 1, url, heartbeat=True, hb_period=0.3)
    scaler = AutoScaler(fleet, min_workers=1, max_workers=3, idle_decisions=4)
    client = FaaSClient(gw.url)
    try:
        fleet.start()
        from tpu_faas.workloads import sleep_task

        fid = client.register(sleep_task)
        handles = client.submit_many(fid, [((0.8,), {}) for _ in range(8)])

        deadline = time.monotonic() + 60
        while fleet.n_live < 3 and time.monotonic() < deadline:
            fleet.poll()
            stats = _fetch_stats(stats_url)
            if stats:
                scaler.step(stats)
            time.sleep(0.3)
        assert fleet.n_live == 3, "backlog did not grow the fleet"
        assert scaler.scale_ups >= 2

        assert [h.result(timeout=60) for h in handles] == [0.8] * 8

        deadline = time.monotonic() + 60
        while fleet.n_live > 1 and time.monotonic() < deadline:
            fleet.poll()
            stats = _fetch_stats(stats_url)
            if stats:
                scaler.step(stats)
            time.sleep(0.2)
        assert fleet.n_live == 1, "quiet fleet did not shrink to the floor"
        assert scaler.scale_downs >= 2
    finally:
        fleet.stop()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def test_worker_tokens_are_fleet_namespaced():
    """ADVICE r5: two supervisors on ONE host serving DIFFERENT dispatchers
    must not mint colliding durable tokens (colliding tokens merge the two
    fleets' speed grades in the estimator). The fleet id — a hash of the
    dispatcher URL — namespaces them; the token stays stable across
    supervisor restarts for the SAME dispatcher."""
    from tpu_faas.worker.deploy import fleet_id

    fleet_a = WorkerFleet(1, 2, "tcp://hostA:5555", protocol="push")
    fleet_b = WorkerFleet(1, 2, "tcp://hostB:5555", protocol="push")
    fleet_a2 = WorkerFleet(1, 2, "tcp://hostA:5555", protocol="push")

    def token_of(fleet):
        cmd = fleet._command(0)
        return cmd[cmd.index("--token") + 1]

    assert token_of(fleet_a) != token_of(fleet_b)  # different dispatchers
    assert token_of(fleet_a) == token_of(fleet_a2)  # restart-stable
    assert fleet_id("tcp://hostA:5555") in token_of(fleet_a)
    # same slot shape, same host, same protocol — ONLY the fleet id differs
    assert token_of(fleet_a).replace(
        fleet_id("tcp://hostA:5555"), fleet_id("tcp://hostB:5555")
    ) == token_of(fleet_b)
