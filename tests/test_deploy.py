"""Worker-fleet deployer: supervised spawn, crash respawn, graceful drain.

Production counterpart of the reference's scrap-heap launcher
(old/deploy_workers.py) — plus the supervision it lacked: a SIGKILLed
worker is respawned and the dispatcher re-dispatches its in-flight tasks,
so the fleet self-heals end to end.
"""

from __future__ import annotations

import threading
import time

from tpu_faas.client import FaaSClient
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store.launch import make_store, start_store_thread
from tpu_faas.worker.deploy import WorkerFleet
from tpu_faas.workloads import arithmetic
from tests.test_tpu_push_e2e import _make_dispatcher


def test_fleet_spawn_crash_respawn_drain():
    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    disp = _make_dispatcher(store_handle.url, time_to_expire=1.5)
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"

    fleet = WorkerFleet(
        2,
        1,
        url,
        heartbeat=True,
        hb_period=0.3,
        restart=True,
        restart_backoff=0.1,
    )
    client = FaaSClient(gw.url)
    try:
        fleet.start()
        assert fleet.n_live == 2

        fid = client.register(arithmetic)
        assert [h.result(30) for h in (client.submit(fid, 100),)] == [
            arithmetic(100)
        ]

        # SIGKILL one worker: poll() must respawn it (crash path), and the
        # stack must keep completing work through the heal
        fleet.procs[0].kill()
        fleet.procs[0].wait()
        deadline = time.monotonic() + 10
        while fleet.n_live < 2 and time.monotonic() < deadline:
            fleet.poll()
            time.sleep(0.05)
        assert fleet.restarts == 1
        assert fleet.n_live == 2

        handles = [client.submit(fid, n) for n in range(5)]
        assert [h.result(30) for h in handles] == [
            arithmetic(n) for n in range(5)
        ]

        # graceful drain: everyone exits, nothing respawns
        fleet.stop()
        assert fleet.n_live == 0
        assert fleet.poll() == 0
    finally:
        if fleet.n_live:
            fleet.stop()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()
