"""Combined modes in ONE run (VERDICT r2 item 9): two --shared tpu-push
dispatchers, EACH with a 4-device mesh tick (sinkhorn placement), over one
store — atomic claims, lease renewal, dead-sibling adoption, and the
sharded device step all exercised together, race-clean under the protocol
monitor. Previously these features were tested pairwise at most
(test_shared_dispatchers.py without meshes, test_parallel_mesh.py without
sharing)."""

from __future__ import annotations

import signal
import threading
import time

import jax
import pytest

from tpu_faas.client import FaaSClient
from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store.launch import make_store, start_store_thread
from tpu_faas.store.racecheck import RaceCheckStore, RaceMonitor
from tpu_faas.workloads import sleep_task
from tests.test_shared_dispatchers import _wait_until_hot
from tests.test_workers_e2e import _spawn_worker


def test_shared_mesh_dispatchers_claims_adoption_sharded_tick():
    if not hasattr(jax, "shard_map"):
        # this environment's JAX predates the jax.shard_map alias the
        # sharded tick (parallel/mesh.py) is written against — skip, don't
        # fail: the combination is covered wherever the alias exists
        pytest.skip("this JAX lacks jax.shard_map (sharded tick unavailable)")
    monitor = RaceMonitor()
    store_handle = start_store_thread()
    gw = start_gateway_thread(
        RaceCheckStore(make_store(store_handle.url), monitor, actor="gateway")
    )

    def make_disp(name):
        return TpuPushDispatcher(
            ip="127.0.0.1",
            port=0,
            store=RaceCheckStore(
                make_store(store_handle.url), monitor, actor=name
            ),
            max_workers=32,
            # small window so BOTH dispatchers must claim work (see
            # test_shared_dispatchers.py for why this de-races the
            # both-active assertion)
            max_pending=8,
            max_inflight=256,
            tick_period=0.01,
            time_to_expire=2.0,
            rescan_period=0.5,
            lease_timeout=3.0,
            shared=True,
            placement="sinkhorn",
            mesh_devices=4,  # conftest provides 8 virtual CPU devices
        )

    d1, d2 = make_disp("disp-1"), make_disp("disp-2")
    threads = [
        threading.Thread(target=d.start, daemon=True) for d in (d1, d2)
    ]
    for t in threads:
        t.start()
    w1 = _spawn_worker(
        "push_worker", 2, f"tcp://127.0.0.1:{d1.port}", "--hb",
        "--hb-period", "0.3",
    )
    w2 = _spawn_worker(
        "push_worker", 2, f"tcp://127.0.0.1:{d2.port}", "--hb",
        "--hb-period", "0.3",
    )
    client = FaaSClient(gw.url)
    try:
        _wait_until_hot(d1, d2)
        assert d1.arrays.mesh is not None and d1.arrays.mesh.size == 4
        assert d2.arrays.mesh is not None and d2.arrays.mesh.size == 4

        fid = client.register(sleep_task)
        handles = [client.submit(fid, 0.3) for _ in range(24)]
        # phase 1: both mesh dispatchers live — the claim split must be real
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline and not (
            d1.n_dispatched > 0 and d2.n_dispatched > 0
        ):
            time.sleep(0.05)
        assert d1.n_dispatched > 0 and d2.n_dispatched > 0

        # phase 2: kill d1 AND its fleet mid-run — d2's rescan must adopt
        # d1's queued claims (dead owner) and in-flight tasks (stale lease)
        # and finish everything through ITS sharded tick
        w1.send_signal(signal.SIGKILL)
        w1.wait()
        d1.stop()
        threads[0].join(timeout=10)
        assert [h.result(timeout=150) for h in handles] == [0.3] * 24
        assert d1.n_dispatched + d2.n_dispatched >= 24  # adoption re-dispatches allowed
        monitor.assert_clean()
        assert monitor.unfinished() == []
        # the survivor kept running the SHARDED tick the whole time
        assert (
            d2.tracer.summary().get("device_tick", {}).get("count", 0) > 0
        )
    finally:
        for w in (w1, w2):
            if w.poll() is None:
                w.kill()
                w.wait()
        d1.stop()
        d2.stop()
        for t in threads:
            t.join(timeout=10)
        gw.stop()
        store_handle.stop()
