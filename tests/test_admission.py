"""Per-component tests for the overload-robustness surface: the saturation
signal, the admission controller (quota / bounded inflight / brownout),
the store circuit breaker, queue-deadline expiry at the store and
dispatcher levels, and the gateway + SDK integration (429/503 with
Retry-After, fast-fail while the store is down, client backoff)."""

from __future__ import annotations

import time

import pytest
import requests

from tpu_faas.admission import (
    AdmissionController,
    CircuitBreaker,
    CapacitySnapshot,
    FLEET_HEALTH_KEY,
    TokenBucket,
    publish_snapshot,
    read_fleet_health,
)
from tpu_faas.admission.controller import AdmissionConfig
from tpu_faas.core.serialize import serialize
from tpu_faas.core.task import FIELD_DEADLINE, FIELD_STATUS, TaskStatus
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store import MemoryStore
from tpu_faas.workloads import arithmetic


class FakeClock:
    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# -- saturation signal -------------------------------------------------------


def test_capacity_snapshot_roundtrip_and_garbage():
    snap = CapacitySnapshot(
        pending=12, inflight=34, capacity=56, drain_rate=7.25,
        published_at=123456.5,
    )
    assert CapacitySnapshot.decode(snap.encode()) == snap
    for garbage in ("", "v0:1:2:3:4:5", "v1:x:2:3:4:5", "v1:1:2:3"):
        assert CapacitySnapshot.decode(garbage) is None


def test_read_fleet_health_aggregates_and_skips_stale():
    store = MemoryStore()
    now = time.time()
    publish_snapshot(
        store, "d1", CapacitySnapshot(10, 20, 8, 5.0, now)
    )
    publish_snapshot(
        store, "d2", CapacitySnapshot(1, 2, 4, 1.0, now - 0.5)
    )
    # stale: ignored but kept; ancient: ignored AND GC'd; garbled: GC'd
    publish_snapshot(
        store, "stale", CapacitySnapshot(100, 100, 100, 9.0, now - 60)
    )
    publish_snapshot(
        store, "ancient", CapacitySnapshot(7, 7, 7, 7.0, now - 1000)
    )
    store.hset(FLEET_HEALTH_KEY, {"garbled": "not-a-snapshot"})
    health = read_fleet_health(store, now=now)
    assert (health.pending, health.inflight, health.capacity) == (11, 22, 12)
    assert health.drain_rate == pytest.approx(6.0)
    assert health.dispatchers == 2
    assert health.in_system == 33
    left = store.hgetall(FLEET_HEALTH_KEY)
    assert "ancient" not in left
    assert "stale" in left  # merely stale entries are NOT deleted
    # undecodable entries are KEPT (a newer-format publisher during a
    # rolling upgrade must not be GC'd by old readers), just ignored
    assert "garbled" in left


def test_read_fleet_health_none_when_empty():
    assert read_fleet_health(MemoryStore()) is None


# -- token bucket ------------------------------------------------------------


def test_token_bucket_rate_and_burst():
    b = TokenBucket(rate=2.0, burst=4.0, now=0.0)
    assert b.take(4, now=0.0)  # full burst available
    assert not b.take(1, now=0.0)  # drained
    assert b.take(1, now=0.5)  # 0.5 s * 2/s = 1 token refilled
    assert not b.take(4, now=1.0)
    assert b.wait_for(4) > 0


# -- admission controller ----------------------------------------------------


def _health(pending=0, inflight=0, capacity=8, drain=10.0):
    from tpu_faas.admission.signal import FleetHealth

    return FleetHealth(
        pending=pending, inflight=inflight, capacity=capacity,
        drain_rate=drain, dispatchers=1, freshest_at=time.time(),
    )


def test_admit_fails_open_without_signal_or_bound():
    ctrl = AdmissionController()
    d = ctrl.admit(n=1000, priority=-5)
    assert d.admitted


def test_bound_and_saturation_full_stop():
    ctrl = AdmissionController(AdmissionConfig(max_system_inflight=10))
    ctrl.update_health(_health(pending=8, inflight=2))  # in_system = 10
    d = ctrl.admit(n=1, priority=100)
    assert not d.admitted and d.reason == "saturated"
    assert d.retry_after >= 1.0


def test_brownout_sheds_lowest_priority_first():
    cfg = AdmissionConfig(max_system_inflight=100)
    ctrl = AdmissionController(cfg)
    # load 0.8: in the [start, hard) band — only below-default priority shed
    ctrl.update_health(_health(pending=80))
    assert not ctrl.admit(priority=-1).admitted
    assert ctrl.admit(priority=0).admitted
    # load ~0.95: [hard, 1.0) — default priority shed too, positive admitted
    ctrl.update_health(_health(pending=95))
    assert not ctrl.admit(priority=0).admitted
    assert ctrl.admit(priority=3).admitted
    assert ctrl.admit(priority=0, client_id="x").admitted is False


def test_admitted_since_refresh_bridges_snapshot_staleness():
    """A burst admitted between two snapshot refreshes must count against
    the bound immediately — the snapshot alone is up to a TTL stale."""
    ctrl = AdmissionController(AdmissionConfig(max_system_inflight=20))
    ctrl.update_health(_health(pending=0, inflight=0))
    assert ctrl.admit(n=20).admitted  # fills the bound
    assert not ctrl.admit(n=1).admitted  # no refresh happened, still full


def test_live_index_anchor_covers_snapshot_blind_spot():
    """The dispatcher snapshot misses tasks still buffered in announce
    subscriptions; the store's live-task index counts them — the max of
    the two views governs. Re-read every refresh, so it cannot drift."""
    ctrl = AdmissionController(AdmissionConfig(max_system_inflight=10))
    ctrl.update_health(_health(pending=0, inflight=0), live_in_system=10)
    d = ctrl.admit(n=1)
    assert not d.admitted and d.reason == "saturated"
    # a fresh refresh with the backlog drained re-opens admission — no
    # ratchet (the old submits-minus-finishes ledger could only go up)
    ctrl.update_health(_health(pending=0, inflight=0), live_in_system=0)
    assert ctrl.admit(n=1).admitted


def test_batch_larger_than_quota_burst_is_permanent_reject():
    ctrl = AdmissionController(
        AdmissionConfig(quota_rate=10.0, quota_burst=20.0)
    )
    d = ctrl.admit(n=100, client_id="c")
    assert not d.admitted and d.reason == "quota_exceeds_burst"
    # and it consumed no tokens: a fitting batch still goes through
    assert ctrl.admit(n=20, client_id="c").admitted


def test_retry_after_uses_drain_rate():
    ctrl = AdmissionController(AdmissionConfig(max_system_inflight=100))
    # 100 in system, drain 10/s, brownout_start 0.75 -> excess 25 -> ~3 s
    ctrl.update_health(_health(pending=100, drain=10.0))
    d = ctrl.admit(priority=0)
    assert not d.admitted
    assert 2.0 <= d.retry_after <= 4.0


def test_overload_rejects_consume_no_quota_tokens():
    """Saturation/brownout run before the quota take: a client backing
    off through a saturated window keeps its full bucket for when the
    system re-opens."""
    clock = FakeClock()
    ctrl = AdmissionController(
        AdmissionConfig(
            max_system_inflight=10, quota_rate=2.0, quota_burst=2.0
        ),
        clock=clock,
    )
    ctrl.update_health(_health(pending=10))  # saturated
    for _ in range(5):
        d = ctrl.admit(n=1, client_id="alice")
        assert not d.admitted and d.reason == "saturated"
    ctrl.update_health(_health(pending=0))  # backlog drained
    # full burst still available despite five rejected attempts
    assert ctrl.admit(n=2, client_id="alice").admitted


def test_quota_clips_per_client_even_when_healthy():
    clock = FakeClock()
    ctrl = AdmissionController(
        AdmissionConfig(quota_rate=2.0, quota_burst=2.0), clock=clock
    )
    assert ctrl.admit(n=2, client_id="alice").admitted
    d = ctrl.admit(n=1, client_id="alice")
    assert not d.admitted and d.reason == "quota"
    assert ctrl.admit(n=2, client_id="bob").admitted  # independent bucket
    clock.advance(1.0)
    assert ctrl.admit(n=2, client_id="alice").admitted  # refilled
    # no client id -> no quota applies
    assert ctrl.admit(n=100, client_id=None).admitted


def test_quota_bucket_table_is_bounded():
    cfg = AdmissionConfig(quota_rate=1000.0, max_clients=10)
    ctrl = AdmissionController(cfg)
    for i in range(50):
        ctrl.admit(client_id=f"c{i}")
    assert len(ctrl._buckets) <= 10


# -- circuit breaker ---------------------------------------------------------


def test_breaker_opens_after_threshold_and_half_open_probe():
    clock = FakeClock()
    br = CircuitBreaker(failure_threshold=3, reset_timeout=5.0, clock=clock)
    assert br.state == "closed"
    for _ in range(2):
        br.record_failure()
    assert br.state == "closed" and br.allow()
    br.record_failure()  # third consecutive: open
    assert br.state == "open"
    assert not br.allow()
    assert 1.0 <= br.retry_after() <= 5.0
    clock.advance(5.1)
    assert br.state == "half_open"
    assert br.allow()  # the single probe
    assert not br.allow()  # everyone else keeps fast-failing
    br.record_failure()  # probe failed: re-open, fresh window
    assert br.state == "open"
    clock.advance(5.1)
    assert br.allow()
    br.record_success()  # probe succeeded: closed, counters reset
    assert br.state == "closed"
    br.record_failure()
    assert br.state == "closed"  # count restarted from zero


def test_breaker_aborted_probe_releases_the_slot():
    """A probe that ends without a store verdict (cancelled request,
    non-outage exception) must release the half-open slot — otherwise the
    breaker wedges open forever, since every other caller is fast-failed
    and nothing could ever record an outcome."""
    clock = FakeClock()
    br = CircuitBreaker(failure_threshold=1, reset_timeout=5.0, clock=clock)
    br.record_failure()
    clock.advance(5.1)
    assert br.allow()  # the probe
    br.record_aborted()  # ...dies without a verdict
    assert br.allow()  # the NEXT caller can probe
    br.record_success()
    assert br.state == "closed"


def test_breaker_failed_probe_rotates_endpoint_and_stays_half_open():
    """Store HA (store/replication.py): with the rotation hook installed,
    a failed half-open probe rotates the store client to the next
    endpoint and STAYS half-open — the very next caller probes the
    replica immediately instead of waiting out another full open window
    against the dead primary."""
    clock = FakeClock()
    br = CircuitBreaker(failure_threshold=1, reset_timeout=5.0, clock=clock)
    rotations = []
    br.set_rotate_hook(lambda: rotations.append(clock()), budget=1)
    br.record_failure()  # open
    clock.advance(5.1)
    assert br.allow()  # the probe, against the dead primary
    br.record_failure()  # probe failed -> rotate, not re-open
    assert rotations == [clock()]
    assert br.state == "half_open"  # window NOT restarted
    assert br.n_rotations == 1
    assert br.allow()  # next caller probes the replica immediately
    br.record_success()
    assert br.state == "closed"
    assert br.snapshot()["endpoint_rotations"] == 1


def test_breaker_rotation_budget_exhaustion_reopens_fresh_window():
    """Once every other endpoint has had its immediate probe (budget =
    endpoints - 1), a still-failing probe re-opens a fresh window as
    before — rotation cannot turn the breaker into a hot retry loop.
    A successful close refills the budget for the next incident."""
    clock = FakeClock()
    br = CircuitBreaker(failure_threshold=1, reset_timeout=5.0, clock=clock)
    rotations = []
    br.set_rotate_hook(lambda: rotations.append(1), budget=2)
    br.record_failure()  # open
    clock.advance(5.1)
    for expected in (1, 2):  # two rotations: both OTHER endpoints probed
        assert br.allow()
        br.record_failure()
        assert len(rotations) == expected
        assert br.state == "half_open"
    assert br.allow()  # third probe this window...
    br.record_failure()  # ...fails with no endpoint left
    assert br.state == "open"  # fresh open window
    assert len(rotations) == 2  # no extra rotation spent
    clock.advance(5.1)
    assert br.allow()
    br.record_success()  # close refills the budget
    br.record_failure()
    clock.advance(5.1)
    assert br.allow()
    br.record_failure()
    assert len(rotations) == 3  # budget was reset on close
    assert br.state == "half_open"


def test_breaker_without_hook_keeps_legacy_reopen():
    """Single-endpoint deployments: no hook installed, a failed probe
    re-opens with a fresh window exactly as before this PR."""
    clock = FakeClock()
    br = CircuitBreaker(failure_threshold=1, reset_timeout=5.0, clock=clock)
    br.record_failure()
    clock.advance(5.1)
    assert br.allow()
    br.record_failure()
    assert br.state == "open"
    assert br.n_rotations == 0


# -- queue-deadline expiry (store level) -------------------------------------


def test_expire_task_queued_only_and_idempotent():
    store = MemoryStore()
    store.create_task("t1", "F", "P")
    assert store.expire_task("t1") == "EXPIRED"
    assert store.get_status("t1") == "EXPIRED"
    assert store.expire_task("t1") == "EXPIRED"  # idempotent
    # RUNNING task: untouched
    store.create_task("t2", "F", "P")
    store.set_status("t2", TaskStatus.RUNNING)
    assert store.expire_task("t2") == "RUNNING"
    assert store.get_status("t2") == "RUNNING"
    # unknown id
    assert store.expire_task("nope") is None
    # terminal stamps: finished_at written, live index dropped
    from tpu_faas.store.base import LIVE_INDEX_KEY

    assert store.hget("t1", "finished_at") is not None
    assert "t1" not in store.hgetall(LIVE_INDEX_KEY)


def test_expire_task_repairs_clobbered_result():
    """A result landing inside expire's read->write window is restored
    from the redundant final_status stamp (same repair as cancel_task)."""
    store = MemoryStore()
    store.create_task("t", "F", "P")

    class RacingStore(MemoryStore):
        pass

    # simulate the interleaving: finish lands AFTER expire's status read.
    # Easiest deterministic approximation: finish first, then force the
    # raw EXPIRED write + repair path by replaying expire's write half.
    store.set_status("t", TaskStatus.RUNNING)
    store.finish_task("t", TaskStatus.COMPLETED, "42")
    # expire on a terminal record is a no-op reporting the truth
    assert store.expire_task("t") == "COMPLETED"
    # now the true window: status still QUEUED at read time, final stamps
    # present from a prior-generation zombie write landing mid-window
    store2 = MemoryStore()
    store2.create_task("u", "F", "P")
    real_get_status = store2.get_status

    def stale_queued(task_id):
        status = real_get_status(task_id)
        if task_id == "u" and not stale_queued.fired:
            stale_queued.fired = True
            # the result lands right after expire's read
            store2.set_status("u", TaskStatus.RUNNING)
            store2.finish_task("u", TaskStatus.COMPLETED, "7")
            return str(TaskStatus.QUEUED)
        return status

    stale_queued.fired = False
    store2.get_status = stale_queued
    assert store2.expire_task("u") == "COMPLETED"
    store2.get_status = real_get_status
    assert store2.get_status("u") == "COMPLETED"
    assert store2.hget("u", "result") == "7"


# -- dispatcher-side shedding ------------------------------------------------


def test_dispatcher_sheds_lapsed_deadline_and_spares_fresh():
    from tpu_faas.dispatch.base import PendingTask, TaskDispatcher

    disp = TaskDispatcher(store_url="memory://")
    try:
        now = time.time()
        disp.store.create_task("lapsed", "F", "P")
        disp.store.create_task("fresh", "F", "P")
        lapsed = PendingTask("lapsed", "F", "P", deadline_at=now - 1.0)
        fresh = PendingTask("fresh", "F", "P", deadline_at=now + 60.0)
        none = PendingTask("none", "F", "P")
        reclaimed = PendingTask(
            "reclaimed", "F", "P", retries=1, deadline_at=now - 1.0
        )
        assert disp.shed_if_expired(lapsed)
        assert disp.store.get_status("lapsed") == "EXPIRED"
        assert disp.n_expired == 1
        assert not disp.shed_if_expired(fresh)
        assert not disp.shed_if_expired(none)
        # reclaimed tasks are exempt: their record is RUNNING, EXPIRED is
        # QUEUED-only by protocol
        assert not disp.shed_if_expired(reclaimed)
    finally:
        disp.close()


def test_tpu_push_tick_sheds_expired_before_dispatch():
    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
    from tpu_faas.store.racecheck import RaceCheckStore, RaceMonitor

    monitor = RaceMonitor()
    store = RaceCheckStore(MemoryStore(), monitor, actor="dispatcher")
    disp = TpuPushDispatcher(
        ip="127.0.0.1", port=0, store=store, max_workers=8,
        max_pending=32, max_inflight=64, recover_queued=False,
    )
    try:
        past = repr(time.time() - 5.0)
        store.create_task(
            "doomed", "F", "P", extra_fields={FIELD_DEADLINE: past}
        )
        disp.tick()
        assert store.get_status("doomed") == "EXPIRED"
        assert disp.n_expired == 1
        # the runtime protocol monitor proves QUEUED -> EXPIRED was legal
        assert monitor.errors == []
    finally:
        disp.close()


# -- capacity publishing -----------------------------------------------------


def test_dispatcher_publishes_capacity_snapshot():
    from tpu_faas.dispatch.base import TaskDispatcher

    disp = TaskDispatcher(store_url="memory://")
    try:
        disp.maybe_publish_capacity(
            pending=3, inflight=2, capacity=8, results=0
        )
        health = read_fleet_health(disp.store)
        assert health is not None
        assert (health.pending, health.inflight, health.capacity) == (3, 2, 8)
        # second call within the period is a no-op (no state change)
        disp.maybe_publish_capacity(
            pending=99, inflight=99, capacity=99, results=99
        )
        health = read_fleet_health(disp.store)
        assert health.pending == 3
    finally:
        disp.close()


# -- gateway integration -----------------------------------------------------


def _register(url: str) -> str:
    r = requests.post(
        f"{url}/register_function",
        json={"name": "arith", "payload": serialize(arithmetic)},
    )
    r.raise_for_status()
    return r.json()["function_id"]


def _submit(url: str, fid: str, **extra):
    return requests.post(
        f"{url}/execute_function",
        json={
            "function_id": fid,
            "payload": serialize(((1,), {})),
            **extra,
        },
    )


def test_gateway_admission_429_with_retry_after_and_priority_override():
    store = MemoryStore()
    ctrl = AdmissionController(AdmissionConfig(max_system_inflight=4))
    handle = start_gateway_thread(store, admission=ctrl)
    try:
        fid = _register(handle.url)
        admitted = [_submit(handle.url, fid) for _ in range(4)]
        assert all(r.status_code == 200 for r in admitted)
        # bound reached via the gateway's own local accounting — no
        # dispatcher snapshot exists at all
        r = _submit(handle.url, fid)
        assert r.status_code == 429
        assert int(r.headers["Retry-After"]) >= 1
        body = r.json()
        assert body["reason"] in ("saturated", "brownout")
        assert body["retry_after"] >= 1
        # batch endpoint rejects identically
        rb = requests.post(
            f"{handle.url}/execute_batch",
            json={
                "function_id": fid,
                "payloads": [serialize(((1,), {}))] * 3,
            },
        )
        assert rb.status_code == 429 and "Retry-After" in rb.headers
        # /stats exposes the controller
        stats = requests.get(f"{handle.url}/stats").json()
        assert stats["admission"]["rejected"] >= 2
        assert stats["admission"]["bound"] == 4
    finally:
        handle.stop()


def test_gateway_oversized_batch_is_400_not_retry_loop():
    store = MemoryStore()
    ctrl = AdmissionController(
        AdmissionConfig(quota_rate=5.0, quota_burst=10.0)
    )
    handle = start_gateway_thread(store, admission=ctrl)
    try:
        fid = _register(handle.url)
        r = requests.post(
            f"{handle.url}/execute_batch",
            json={
                "function_id": fid,
                "payloads": [serialize(((1,), {}))] * 50,
            },
            headers={"X-Client-Id": "bulk"},
        )
        # permanently unsubmittable whole: 400, and NO Retry-After bait
        assert r.status_code == 400
        assert "Retry-After" not in r.headers
        assert "quota burst" in r.json()["error"]
    finally:
        handle.stop()


def test_gateway_brownout_honors_priority_hint():
    store = MemoryStore()
    ctrl = AdmissionController(AdmissionConfig(max_system_inflight=10))
    handle = start_gateway_thread(store, admission=ctrl)
    try:
        fid = _register(handle.url)
        for _ in range(9):  # load 0.9+: hard brownout band
            assert _submit(handle.url, fid).status_code == 200
        assert _submit(handle.url, fid, priority=0).status_code == 429
        assert _submit(handle.url, fid, priority=5).status_code == 200
    finally:
        handle.stop()


def test_gateway_deadline_hint_validated_and_stored():
    store = MemoryStore()
    handle = start_gateway_thread(store)
    try:
        fid = _register(handle.url)
        before = time.time()
        r = _submit(handle.url, fid, deadline=30.0)
        assert r.status_code == 200
        tid = r.json()["task_id"]
        stored = float(store.hget(tid, FIELD_DEADLINE))
        assert before + 29.0 <= stored <= time.time() + 31.0
        for bad in (-1, 0, "x", True):
            assert _submit(handle.url, fid, deadline=bad).status_code == 400
    finally:
        handle.stop()


def test_gateway_store_breaker_fast_fails_under_100ms():
    """Kill the store; after the breaker trips, every store-touching
    endpoint answers 503 + Retry-After in well under 100 ms instead of
    hanging on a connect timeout — and a restarted store closes it."""
    from tpu_faas.store.launch import make_store, start_store_thread

    store_handle = start_store_thread()
    port = store_handle.port
    br = CircuitBreaker(failure_threshold=2, reset_timeout=1.0)
    handle = start_gateway_thread(
        make_store(store_handle.url), breaker=br
    )
    try:
        fid = _register(handle.url)
        assert _submit(handle.url, fid).status_code == 200
        store_handle.stop()
        # trip it: a couple of requests fail against the dead store (these
        # may each pay a fast connection-refused error)
        for _ in range(4):
            requests.get(f"{handle.url}/status/nope", timeout=10)
        assert br.is_open
        t0 = time.perf_counter()
        r = requests.get(f"{handle.url}/status/nope", timeout=10)
        elapsed = time.perf_counter() - t0
        assert r.status_code == 503
        assert "Retry-After" in r.headers
        assert elapsed < 0.1, f"fast-fail took {elapsed:.3f}s"
        # submits fast-fail identically
        r = _submit(handle.url, fid)
        assert r.status_code == 503 and "Retry-After" in r.headers
        # store returns: the half-open probe closes the breaker
        store_handle = start_store_thread(port=port)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if requests.get(f"{handle.url}/status/nope").status_code == 404:
                break
            time.sleep(0.3)
        else:
            raise AssertionError("breaker never closed after store return")
    finally:
        handle.stop()
        store_handle.stop()


def test_sdk_retries_429_honoring_retry_after_and_dedupes():
    """A saturation-rejected submit with retries enabled succeeds once the
    backlog drains mid-backoff, and the auto idempotency key makes the
    retried submit address one record."""
    import threading

    from tpu_faas.client import FaaSClient

    store = MemoryStore()
    ctrl = AdmissionController(
        AdmissionConfig(max_system_inflight=2, max_retry_after=2.0)
    )
    handle = start_gateway_thread(store, admission=ctrl)
    try:
        client = FaaSClient(handle.url, overload_retries=4)
        fid = client.register(arithmetic)
        first = [client.submit(fid, 1) for _ in range(2)]  # fills the bound

        def drain() -> None:
            # a "worker" finishes one task mid-backoff; the RESULTS_CHANNEL
            # publish drops the gateway's local in-system estimate
            time.sleep(0.4)
            store.finish_task(
                first[0].task_id, TaskStatus.COMPLETED, serialize(2)
            )

        t = threading.Thread(target=drain)
        t.start()
        t0 = time.perf_counter()
        h3 = client.submit(fid, 3)  # 429 first, then retried after backoff
        elapsed = time.perf_counter() - t0
        t.join()
        assert elapsed > 0.3  # it actually backed off
        assert store.get_status(h3.task_id) == "QUEUED"
        assert len({h.task_id for h in first} | {h3.task_id}) == 3
    finally:
        handle.stop()


def test_sdk_raises_after_retry_budget_exhausted():
    from tpu_faas.client import FaaSClient

    store = MemoryStore()
    ctrl = AdmissionController(AdmissionConfig(max_system_inflight=1))
    handle = start_gateway_thread(store, admission=ctrl)
    try:
        client = FaaSClient(handle.url, overload_retries=1)
        fid = client.register(arithmetic)
        client.submit(fid, 1)  # fills the bound
        with pytest.raises(requests.HTTPError) as err:
            client.submit(fid, 2)
        assert err.value.response.status_code == 429
    finally:
        handle.stop()


def test_expired_surfaces_as_task_expired_error():
    from tpu_faas.client import FaaSClient, TaskExpiredError

    store = MemoryStore()
    handle = start_gateway_thread(store)
    try:
        client = FaaSClient(handle.url)
        fid = client.register(arithmetic)
        h = client.submit_with(fid, (1,), deadline=60.0)
        store.expire_task(h.task_id)
        with pytest.raises(TaskExpiredError):
            h.result(timeout=5.0)
        assert h.status() == "EXPIRED"
    finally:
        handle.stop()


def test_async_client_retries_and_deadline(event_loop=None):
    import asyncio

    from tpu_faas.client.aio import AsyncFaaSClient, TaskExpiredError

    store = MemoryStore()
    ctrl = AdmissionController(
        AdmissionConfig(quota_rate=3.0, quota_burst=2.0, max_retry_after=2.0)
    )
    handle = start_gateway_thread(store, admission=ctrl)

    async def run() -> None:
        async with AsyncFaaSClient(handle.url, overload_retries=4) as client:
            fid = await client.register(arithmetic)
            handles = [
                await client.submit_with(fid, (1,), deadline=60.0)
                for _ in range(4)
            ]
            assert len({h.task_id for h in handles}) == 4
            store.expire_task(handles[0].task_id)
            try:
                await handles[0].result(timeout=5.0)
            except TaskExpiredError:
                pass
            else:
                raise AssertionError("expected TaskExpiredError")

    try:
        asyncio.run(run())
    finally:
        handle.stop()


def test_breaker_stragglers_do_not_slide_the_open_window():
    """Calls already in flight when the breaker opens land their failures
    late; they must not be mistaken for failed half-open probes — each
    would restart the open window and push the recovery probe out
    indefinitely."""
    clock = FakeClock()
    br = CircuitBreaker(failure_threshold=3, reset_timeout=5.0, clock=clock)
    for _ in range(3):
        br.record_failure()
    assert br.state == "open" and br.n_opened == 1
    clock.advance(3.0)
    for _ in range(5):  # stragglers from slow connect timeouts
        br.record_failure()
    assert br.n_opened == 1  # window NOT restarted
    clock.advance(2.1)  # 5.1s since the one true open
    assert br.state == "half_open"
    assert br.allow()  # recovery probe arrives on schedule
    br.record_success()
    assert br.state == "closed"
