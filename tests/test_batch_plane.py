"""Batched worker data plane (TASK_BATCH / RESULT_BATCH, worker/messages.py).

Covers: the wire codec; pool bundle execution (one IPC message per K-task
bundle, per-task cancel/misfire/broken-pool semantics intact); the
dispatcher's act-phase frame grouping behind the negotiated ``batch``
capability; BOTH interop directions proven byte-identical to the
unbatched wire (reference-era worker under a batching dispatcher, and a
batch-capable worker under a batching-off dispatcher); the worker-side
RESULT_BATCH negotiation; the express sub-tick's adaptive micro-batching
gate; a full-stack e2e leg; and the chaos leg — a worker SIGKILLed with a
bundle in flight reclaims every bundled task with zero admitted-task loss
under the race monitor.
"""

from __future__ import annotations

import signal
import threading
import time

import pytest

from tests.test_tpu_push_e2e import _make_dispatcher
from tests.test_workers_e2e import _spawn_worker
from tpu_faas.client import FaaSClient
from tpu_faas.core.executor import pack_params
from tpu_faas.core.serialize import deserialize, serialize
from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store import MemoryStore
from tpu_faas.store.launch import make_store, start_store_thread
from tpu_faas.worker import messages as m
from tpu_faas.worker.pool import TaskPool
from tpu_faas.workloads import no_op, sleep_task

# -- wire codec ------------------------------------------------------------


def test_batch_frame_codec_roundtrip():
    tasks = [
        {"task_id": "a", "fn_payload": "F", "param_payload": "P"},
        {"task_id": "b", "fn_digest": "d" * 64, "param_payload": "Q",
         "timeout": 2.5, "trace_id": "ab" * 16},
    ]
    for encode in (m.encode, m.encode_bin):
        raw = encode(m.TASK_BATCH, tasks=tasks)
        typ, data = m.decode(raw)
        assert typ == m.TASK_BATCH
        assert data["tasks"] == tasks
    results = [
        {"task_id": "a", "status": "COMPLETED", "result": "r",
         "elapsed": 0.01, "started_at": 1.0},
        {"task_id": "b", "status": "FAILED", "result": "e",
         "elapsed": None, "started_at": None, "trace_id": "cd" * 16},
    ]
    raw = m.encode_for(True, m.RESULT_BATCH, results=results, misfires=3)
    typ, data = m.decode(raw)
    assert typ == m.RESULT_BATCH
    assert data["results"] == results
    assert data["misfires"] == 3


def test_batch_capability_advertised():
    assert m.CAP_BATCH in m.WORKER_CAPS
    assert m.caps_of({"caps": list(m.WORKER_CAPS)}) >= {m.CAP_BATCH}


# -- pool bundles ----------------------------------------------------------


def _drain_until(pool: TaskPool, n: int, timeout: float = 60.0):
    out = []
    deadline = time.monotonic() + timeout
    while len(out) < n and time.monotonic() < deadline:
        out.extend(pool.drain())
        time.sleep(0.01)
    return out


def test_pool_bundle_executes_all_on_one_ipc():
    from tpu_faas.worker.pool import POOL_IPC

    pool = TaskPool(2)
    pool.warmup()
    try:
        fn = serialize(no_op)
        ipc0 = POOL_IPC.value
        pool.submit_bundle(
            [(f"t{i}", fn, pack_params(), None, None) for i in range(5)]
        )
        assert pool.busy == 5
        # the O(1)-pool-wakeups claim: 5 tasks, ONE executor submission
        assert POOL_IPC.value - ipc0 == 1
        results = _drain_until(pool, 5)
        assert sorted(r.task_id for r in results) == [f"t{i}" for i in range(5)]
        assert all(r.status == "COMPLETED" for r in results)
        assert all(deserialize(r.result) == "DONE" for r in results)
        # per-task exec windows measured at the source, element-wise
        assert all(r.elapsed is not None and r.started_at is not None
                   for r in results)
        assert pool.busy == 0
    finally:
        pool.close()


def test_pool_bundle_singleton_falls_through_to_classic_submit():
    pool = TaskPool(1)
    pool.warmup()
    try:
        pool.submit_bundle([("solo", serialize(no_op), pack_params(), None, None)])
        assert not pool._bundle_members  # classic path, no bundle future
        results = _drain_until(pool, 1)
        assert results[0].status == "COMPLETED"
    finally:
        pool.close()


def test_pool_bundle_member_cancel_is_per_task():
    """Force-cancel of ONE bundled member: the deferred-kill interrupt
    lands on exactly that element when its start event arrives; siblings
    complete normally."""
    if not hasattr(signal, "SIGUSR1"):
        pytest.skip("POSIX-only force-cancel")
    pool = TaskPool(1)
    pool.warmup()
    try:
        fn = serialize(sleep_task)
        pool.submit_bundle(
            [
                ("keep", fn, pack_params(0.5), None, None),
                ("kill", fn, pack_params(30.0), None, None),
            ]
        )
        # cancel the second member while the first still runs: its future
        # is the shared bundle future, which must NOT be cancelled — the
        # kill defers to the member's own start event
        assert pool.cancel("kill") is True
        results = _drain_until(pool, 2, timeout=60.0)
        by_id = {r.task_id: r for r in results}
        assert by_id["keep"].status == "COMPLETED"
        assert by_id["kill"].status == "CANCELLED"
    finally:
        pool.close()


def test_pool_bundle_child_death_fails_every_member():
    import os as _os

    def die() -> None:
        _os._exit(13)

    pool = TaskPool(1)
    pool.warmup()
    try:
        pool.submit_bundle(
            [
                ("d0", serialize(die), pack_params(), None, None),
                ("d1", serialize(no_op), pack_params(), None, None),
            ]
        )
        results = _drain_until(pool, 2)
        assert sorted(r.task_id for r in results) == ["d0", "d1"]
        assert all(r.status == "FAILED" for r in results)
        assert pool.busy == 0
        # the rebuilt pool still serves
        pool.submit("after", serialize(no_op), pack_params())
        assert _drain_until(pool, 1)[0].status == "COMPLETED"
    finally:
        pool.close()


# -- dispatcher act-phase grouping -----------------------------------------


class _RecordingSocket:
    """Stand-in for the ROUTER socket: captures (wid, frame) sends."""

    def __init__(self) -> None:
        self.sent: list[tuple[bytes, bytes]] = []

    def send_multipart(self, parts) -> None:
        self.sent.append((parts[0], parts[1]))

    def close(self, linger: int = 0) -> None:
        pass


def _grouping_dispatcher(batch_max: int) -> tuple[TpuPushDispatcher, _RecordingSocket]:
    store = MemoryStore()
    disp = TpuPushDispatcher(
        ip="127.0.0.1", port=0, store=store,
        max_workers=8, max_pending=64, max_inflight=128, max_slots=8,
        recover_queued=False, estimate_runtimes=False,
        batch_max=batch_max,
    )
    disp.socket.close(linger=0)
    disp.socket = _RecordingSocket()
    return disp, disp.socket


def _feed(disp: TpuPushDispatcher, n: int, prefix: str = "t") -> list[str]:
    ids = [f"{prefix}{i}" for i in range(n)]
    disp.store.create_tasks([(tid, "F", "P") for tid in ids])
    return ids


def test_batching_dispatcher_groups_frames_per_worker():
    disp, sock = _grouping_dispatcher(batch_max=32)
    try:
        disp._handle(b"w0", m.REGISTER,
                     {"num_processes": 4, "caps": [m.CAP_BATCH]})
        disp._handle(b"w1", m.REGISTER,
                     {"num_processes": 4, "caps": [m.CAP_BATCH]})
        ids = _feed(disp, 8)
        sent = disp.tick()
        assert sent == 8
        # ONE TASK_BATCH frame per worker, 4 tasks each
        frames = [(wid, *m.decode(raw)) for wid, raw in sock.sent]
        batch_frames = [f for f in frames if f[1] == m.TASK_BATCH]
        assert len(batch_frames) == 2
        assert {f[0] for f in batch_frames} == {b"w0", b"w1"}
        carried = sorted(
            t["task_id"] for f in batch_frames for t in f[2]["tasks"]
        )
        assert carried == sorted(ids)
        assert int(disp.m_task_frames.value) == 2
        assert disp.n_dispatched == 8
    finally:
        disp.close()


def test_batching_dispatcher_splits_frames_at_batch_max():
    disp, sock = _grouping_dispatcher(batch_max=3)
    try:
        disp._handle(b"w0", m.REGISTER,
                     {"num_processes": 8, "caps": [m.CAP_BATCH]})
        _feed(disp, 8)
        assert disp.tick() == 8
        sizes = sorted(
            len(data["tasks"]) if typ == m.TASK_BATCH else 1
            for _, raw in sock.sent
            for typ, data in [m.decode(raw)]
            if typ in (m.TASK, m.TASK_BATCH)
        )
        assert sizes == [2, 3, 3]  # capped at batch_max, remainder flushed
        assert max(sizes) <= 3
    finally:
        disp.close()


def test_singleton_buffer_flushes_as_plain_task_frame():
    """A solo assignment to a batch-capable worker ships as a plain TASK
    frame — the express lane's solo path has zero new wire forms."""
    disp, sock = _grouping_dispatcher(batch_max=32)
    try:
        disp._handle(b"w0", m.REGISTER,
                     {"num_processes": 4, "caps": [m.CAP_BATCH]})
        _feed(disp, 1)
        assert disp.tick() == 1
        task_frames = [
            m.decode(raw) for _, raw in sock.sent
            if m.decode(raw)[0] in (m.TASK, m.TASK_BATCH)
        ]
        assert len(task_frames) == 1
        assert task_frames[0][0] == m.TASK
    finally:
        disp.close()


def _wire_frames(batch_max: int, caps: list[str]) -> list[tuple[bytes, bytes]]:
    """One deterministic dispatch scenario; returns the raw frames sent."""
    disp, sock = _grouping_dispatcher(batch_max=batch_max)
    try:
        reg: dict = {"num_processes": 4}
        if caps:
            reg["caps"] = caps
        disp._handle(b"w0", m.REGISTER, reg)
        _feed(disp, 6)
        disp.tick()
        return list(sock.sent)
    finally:
        disp.close()


def test_interop_reference_worker_wire_is_byte_identical():
    """A batching dispatcher facing a reference-era worker (no ``batch``
    cap) produces byte-for-byte the frames the unbatched build sends."""
    assert _wire_frames(32, caps=[]) == _wire_frames(0, caps=[])


def test_interop_batch_worker_under_unbatched_dispatcher_byte_identical():
    """Batching OFF dispatcher-side: a batch-capable worker's frames are
    byte-identical to the pre-batch build's (capability alone changes
    nothing; the knob is the opt-in)."""
    caps = list(m.WORKER_CAPS)
    frames_off = _wire_frames(0, caps=caps)
    assert frames_off == _wire_frames(1, caps=caps)  # 0 and 1 both disable
    for _, raw in frames_off:
        typ, _ = m.decode(raw)
        assert typ != m.TASK_BATCH


def test_result_batch_releases_slots_and_writes_terminals():
    disp, sock = _grouping_dispatcher(batch_max=32)
    try:
        disp._handle(b"w0", m.REGISTER,
                     {"num_processes": 4, "caps": [m.CAP_BATCH]})
        ids = _feed(disp, 4)
        assert disp.tick() == 4
        assert disp.arrays.n_inflight == 4
        disp._handle(
            b"w0",
            m.RESULT_BATCH,
            {
                "results": [
                    {"task_id": tid, "status": "COMPLETED",
                     "result": serialize(i), "elapsed": 0.001,
                     "started_at": time.time()}
                    for i, tid in enumerate(ids)
                ],
                "misfires": 0,
            },
        )
        assert disp.arrays.n_inflight == 0
        assert disp.n_results == 4
        for tid in ids:
            assert disp.store.get_status(tid) == "COMPLETED"
    finally:
        disp.close()


# -- express adaptive micro-batching gate ----------------------------------


def test_express_gate_depth_triggered():
    from tpu_faas.dispatch.base import PendingQueue, PendingTask

    disp, _ = _grouping_dispatcher(batch_max=8)

    def reset(depth: int, prefix: str) -> None:
        disp.pending = PendingQueue(
            PendingTask(f"{prefix}{i}", "F", "P") for i in range(depth)
        )
        disp._express_hold_until = None

    try:
        disp.batch_window_s = 0.05
        now = 100.0
        # small ready set: flush immediately, never hold
        reset(1, "s")
        assert disp._express_gate(now, True) == (True, True)
        assert disp._express_hold_until is None
        # mid-depth under load: arm the hold
        reset(5, "m")
        run, _ = disp._express_gate(now, True)
        assert run is False
        assert disp._express_hold_until == pytest.approx(now + 0.05)
        # still held before the deadline, runs at/after it
        assert disp._express_gate(now + 0.01, True)[0] is False
        assert disp._express_gate(now + 0.06, True)[0] is True
        assert disp._express_hold_until is None
        # full bundle: flush immediately even inside a window
        reset(8, "f")
        assert disp._express_gate(now, True)[0] is True
        # hold expiry fires without a fresh announce
        reset(5, "h")
        assert disp._express_gate(now, True)[0] is False
        assert disp._express_gate(now + 1.0, False) == (True, False)
    finally:
        disp.close()


def test_express_gate_disabled_without_window():
    disp, _ = _grouping_dispatcher(batch_max=8)
    try:
        # window 0 (default): every express wake ticks immediately — the
        # PR-12 behavior, no intake inside the gate
        assert disp.batch_window_s == 0.0
        assert disp._express_gate(1.0, True) == (True, False)
        assert disp._express_gate(1.0, False) == (False, False)
    finally:
        disp.close()


# -- worker-side negotiation ----------------------------------------------


def test_worker_ships_result_batch_after_task_batch():
    """A PushWorker against a test-owned ROUTER: per-task RESULT before
    any TASK_BATCH arrived; ONE RESULT_BATCH for a bundle after."""
    import zmq

    from tpu_faas.worker.push_worker import PushWorker

    ctx = zmq.Context.instance()
    router = ctx.socket(zmq.ROUTER)
    port = router.bind_to_random_port("tcp://127.0.0.1")
    worker = PushWorker(1, f"tcp://127.0.0.1:{port}", poll_timeout_ms=10)
    t = threading.Thread(target=worker.run, kwargs={"max_tasks": 4}, daemon=True)
    t.start()
    try:
        wid, raw = router.recv_multipart()
        typ, reg = m.decode(raw)
        assert typ == m.REGISTER
        assert m.CAP_BATCH in reg["caps"]
        fn = serialize(no_op)

        def recv(timeout_ms: int = 30000):
            if not router.poll(timeout_ms):
                raise TimeoutError("no worker frame")
            _, raw = router.recv_multipart()
            return m.decode(raw)

        # plain TASK first: the reply must be a plain RESULT (negotiation
        # has not happened — capability alone never changes the sends)
        router.send_multipart(
            [wid, m.encode(m.TASK, task_id="p0", fn_payload=fn,
                           param_payload=pack_params())]
        )
        typ, data = recv()
        assert typ == m.RESULT and data["task_id"] == "p0"
        # TASK_BATCH: 3 tasks, 1-proc pool -> one bundle -> one drain ->
        # ONE RESULT_BATCH frame carrying all three
        router.send_multipart(
            [wid, m.encode(
                m.TASK_BATCH,
                tasks=[
                    {"task_id": f"b{i}", "fn_payload": fn,
                     "param_payload": pack_params()}
                    for i in range(3)
                ],
            )]
        )
        typ, data = recv()
        assert typ == m.RESULT_BATCH
        got = sorted(r["task_id"] for r in data["results"])
        assert got == ["b0", "b1", "b2"]
        assert all(r["status"] == "COMPLETED" for r in data["results"])
        assert "misfires" in data
    finally:
        worker.stop()
        t.join(timeout=30)
        router.close(linger=0)


# -- full stack ------------------------------------------------------------


def test_batched_stack_end_to_end():
    """Real store + gateway + batching express dispatcher + subprocess
    workers: a burst completes correctly AND ships fewer TASK frames than
    tasks (bundling engaged on the live wire)."""
    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    disp = _make_dispatcher(
        store_handle.url, batch_max=16, batch_window_ms=2.0, express=True,
    )
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
        for _ in range(2)
    ]
    client = FaaSClient(gw.url)
    try:
        fid = client.register(sleep_task)
        handles = client.submit_many(fid, [((0.05,), {})] * 24)
        for h in handles:
            assert h.result(timeout=120.0) == 0.05
        assert disp.n_dispatched >= 24
        assert int(disp.m_task_frames.value) < disp.n_dispatched
        assert disp.stats()["batch_max"] == 16
    finally:
        for w in workers:
            w.kill()
            w.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def test_worker_sigkill_mid_bundle_reclaims_every_bundled_task():
    """Chaos: SIGKILL a worker holding an in-flight BUNDLE under the race
    monitor — every bundled task is reclaimed and completes on the
    survivor, zero admitted-task loss, zero protocol errors."""
    from tpu_faas.store.racecheck import RaceCheckStore, RaceMonitor

    monitor = RaceMonitor()
    store_handle = start_store_thread()
    gw = start_gateway_thread(
        RaceCheckStore(make_store(store_handle.url), monitor, actor="gateway")
    )
    disp = _make_dispatcher(
        store_handle.url,
        time_to_expire=1.5,
        batch_max=16,
        store=RaceCheckStore(
            make_store(store_handle.url), monitor, actor="dispatcher"
        ),
    )
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
        for _ in range(2)
    ]
    client = FaaSClient(gw.url)
    try:
        fid = client.register(sleep_task)
        # a burst: each worker's assignments ride TASK_BATCH bundles
        handles = client.submit_many(fid, [((1.0,), {})] * 8)
        deadline = time.monotonic() + 60.0
        while disp.n_dispatched < 4 and time.monotonic() < deadline:
            time.sleep(0.05)  # bundles dispatched, executions in flight
        assert disp.n_dispatched >= 4
        assert int(disp.m_task_frames.value) < disp.n_dispatched
        workers[0].send_signal(signal.SIGKILL)
        workers[0].wait()
        for h in handles:
            assert h.result(timeout=120.0) == 1.0
        monitor.assert_clean()
        assert monitor.unfinished() == []
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()
