"""Chaos end-to-end: everything breaks at once, nothing is lost.

One run exercises the full recovery surface together — a worker SIGKILLed
while holding tasks (purge + device-computed re-dispatch), the store
restarted mid-run (client/subscription reconnect, deferred results,
stranded rescan), and a replacement worker joining late — while the
protocol race monitor watches every store write. The reference has no
fault-injection tests at all (SURVEY §4: tests never kill workers).
"""

from __future__ import annotations

import signal
import threading
import time

from tpu_faas.client import FaaSClient
from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store.launch import make_store, start_store_thread
from tpu_faas.store.racecheck import RaceCheckStore, RaceMonitor
from tpu_faas.workloads import sleep_task
from tests.test_workers_e2e import _spawn_worker

N_TASKS = 40


def test_chaos_worker_kill_plus_store_restart(tmp_path):
    snap = str(tmp_path / "chaos.snap")
    monitor = RaceMonitor()
    h1 = start_store_thread(snapshot_path=snap)
    port = h1.port
    gw = start_gateway_thread(
        RaceCheckStore(make_store(h1.url), monitor, actor="gateway")
    )
    disp = TpuPushDispatcher(
        ip="127.0.0.1",
        port=0,
        store=RaceCheckStore(make_store(h1.url), monitor, actor="dispatcher"),
        max_workers=64,
        max_pending=256,
        max_inflight=512,
        tick_period=0.01,
        time_to_expire=1.5,
        rescan_period=0.5,
    )
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
        for _ in range(3)
    ]
    client = FaaSClient(gw.url)
    store_handle = [h1]
    try:
        fid = client.register(sleep_task)
        handles = [client.submit(fid, 0.4) for _ in range(N_TASKS)]

        time.sleep(1.0)  # tasks flowing on all three workers
        workers[0].send_signal(signal.SIGKILL)  # takes its in-flight tasks
        workers[0].wait()

        time.sleep(1.0)
        store_handle[0].stop()  # store dies mid-run (checkpoints to snap)
        time.sleep(2.0)  # results finish + defer during the outage
        assert t.is_alive(), "dispatcher crashed during the outage"
        store_handle[0] = start_store_thread(port=port, snapshot_path=snap)

        # a replacement worker joins late
        workers.append(
            _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
        )

        for h in handles:
            assert h.result(timeout=120.0) == 0.4

        # protocol clean: no terminal overwrites, no undeclared double
        # dispatch errors. Warnings are legitimate here (e.g. a terminal
        # write on a task whose RUNNING mark was lost to the outage).
        assert monitor.errors == [], "\n".join(str(v) for v in monitor.errors)
        assert monitor.unfinished() == []
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle[0].stop()
