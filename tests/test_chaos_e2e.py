"""Chaos end-to-end: everything breaks at once, nothing is lost.

One run exercises the full recovery surface together — a worker SIGKILLed
while holding tasks (purge + device-computed re-dispatch), the store
restarted mid-run (client/subscription reconnect, deferred results,
stranded rescan), and a replacement worker joining late — while the
protocol race monitor watches every store write. The reference has no
fault-injection tests at all (SURVEY §4: tests never kill workers).
"""

from __future__ import annotations

import signal
import threading
import time

from tpu_faas.client import FaaSClient
from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store.launch import make_store, start_store_thread
from tpu_faas.store.racecheck import RaceCheckStore, RaceMonitor
from tpu_faas.workloads import sleep_task
from tests.test_workers_e2e import _spawn_worker


def _free_port() -> int:
    """An ephemeral port for a dispatcher a test will (re)spawn on."""
    import socket as socketlib

    probe = socketlib.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    return port

N_TASKS = 40


def test_chaos_worker_kill_plus_store_restart(tmp_path):
    snap = str(tmp_path / "chaos.snap")
    monitor = RaceMonitor()
    h1 = start_store_thread(snapshot_path=snap)
    port = h1.port
    gw = start_gateway_thread(
        RaceCheckStore(make_store(h1.url), monitor, actor="gateway")
    )
    disp = TpuPushDispatcher(
        ip="127.0.0.1",
        port=0,
        store=RaceCheckStore(make_store(h1.url), monitor, actor="dispatcher"),
        max_workers=64,
        max_pending=256,
        max_inflight=512,
        tick_period=0.01,
        time_to_expire=1.5,
        rescan_period=0.5,
    )
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
        for _ in range(3)
    ]
    client = FaaSClient(gw.url)
    store_handle = [h1]
    try:
        fid = client.register(sleep_task)
        handles = [client.submit(fid, 0.4) for _ in range(N_TASKS)]

        time.sleep(1.0)  # tasks flowing on all three workers
        workers[0].send_signal(signal.SIGKILL)  # takes its in-flight tasks
        workers[0].wait()

        time.sleep(1.0)
        store_handle[0].stop()  # store dies mid-run (checkpoints to snap)
        time.sleep(2.0)  # results finish + defer during the outage
        assert t.is_alive(), "dispatcher crashed during the outage"
        store_handle[0] = start_store_thread(port=port, snapshot_path=snap)

        # a replacement worker joins late
        workers.append(
            _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
        )

        for h in handles:
            assert h.result(timeout=120.0) == 0.4

        # protocol clean: no terminal overwrites, no undeclared double
        # dispatch errors. Warnings are legitimate here (e.g. a terminal
        # write on a task whose RUNNING mark was lost to the outage).
        assert monitor.errors == [], "\n".join(str(v) for v in monitor.errors)
        assert monitor.unfinished() == []
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle[0].stop()


def _spawn_dispatcher(port: int, store_url: str, *extra: str):
    """A tpu-push dispatcher as a real subprocess (so it can be SIGKILLed)."""
    import subprocess
    import sys

    from tests.test_workers_e2e import REPO
    from tpu_faas.bench.harness import cpu_worker_env

    # cpu_worker_env pins TPU_FAAS_PLATFORM so the child never initializes
    # the (possibly unreachable) tunneled-TPU backend
    env = cpu_worker_env()
    return subprocess.Popen(
        [
            sys.executable, "-m", "tpu_faas.dispatch",
            "-m", "tpu-push", "-p", str(port), "-i", "127.0.0.1",
            "--store", store_url, "--rescan", "0.5", "--tte", "2.0",
        ]
        + list(extra),
        env=env,
        cwd=REPO,
    )


def test_dispatcher_crash_restart_mid_run():
    """SIGKILL the dispatcher with tasks in flight; a replacement on the
    same port recovers everything: workers rejoin via the reconnect
    handshake, results computed during the outage are delivered to the NEW
    dispatcher (DEALER re-delivers over the reconnected socket), and tasks
    stranded QUEUED by announce loss are adopted by the startup rescan.
    Durable state lives in the store, so a dispatcher is disposable — the
    reference's dispatcher is a single process whose death loses the fleet
    (SURVEY §5.4: QUEUED tasks announced during downtime are stranded
    forever)."""
    port = _free_port()

    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    disp_a = _spawn_dispatcher(port, store_handle.url)
    url = f"tcp://127.0.0.1:{port}"
    worker = _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
    client = FaaSClient(gw.url)
    disp_b = None
    try:
        fid = client.register(sleep_task)
        first = [client.submit(fid, 0.5) for _ in range(4)]
        time.sleep(1.2)  # some RUNNING on the worker

        disp_a.kill()  # hard crash, no goodbye
        disp_a.wait()
        # tasks submitted while no dispatcher is listening: their announce
        # is lost (fire-and-forget) — only the rescan can save them
        during = [client.submit(fid, 0.2) for _ in range(4)]
        time.sleep(0.5)

        disp_b = _spawn_dispatcher(port, store_handle.url)
        # every task completes with its actual return value (sleep_task
        # returns its argument) — none lost, none FAILED
        assert [h.result(timeout=90) for h in first] == [0.5] * 4
        assert [h.result(timeout=90) for h in during] == [0.2] * 4
    finally:
        worker.kill()
        worker.wait()
        for d in (disp_a, disp_b):
            if d is not None and d.poll() is None:
                d.kill()
                d.wait()
        gw.stop()
        store_handle.stop()


def test_dispatcher_and_worker_die_together():
    """The RUNNING-recovery hole (VERDICT r1 item 3): a task RUNNING on a
    worker that dies while the dispatcher is ALSO down has no process left
    that knows about it — only the lease stamped on the RUNNING record can
    save it. A replacement dispatcher's rescan adopts RUNNING tasks whose
    lease went stale and re-dispatches them; every task completes."""
    port = _free_port()

    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    lease = ("--lease-timeout", "2.0")
    disp_a = _spawn_dispatcher(port, store_handle.url, *lease)
    url = f"tcp://127.0.0.1:{port}"
    worker_a = _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
    client = FaaSClient(gw.url)
    disp_b = worker_b = None
    try:
        fid = client.register(sleep_task)
        handles = [client.submit(fid, 1.0) for _ in range(4)]
        deadline = time.monotonic() + 30
        # wait until some tasks are genuinely RUNNING on worker_a
        while time.monotonic() < deadline:
            if any(h.status() == "RUNNING" for h in handles):
                break
            time.sleep(0.1)
        else:
            raise AssertionError("no task ever reached RUNNING")

        # both die together: nobody holds the in-flight table anymore
        worker_a.kill()
        worker_a.wait()
        disp_a.kill()
        disp_a.wait()

        disp_b = _spawn_dispatcher(port, store_handle.url, *lease)
        worker_b = _spawn_worker(
            "push_worker", 2, url, "--hb", "--hb-period", "0.3"
        )
        # adoption needs the lease (renewed until the kill) to age past
        # 2 s, then a rescan pass — well within this timeout
        assert [h.result(timeout=90) for h in handles] == [1.0] * 4
    finally:
        for w in (worker_a, worker_b):
            if w is not None and w.poll() is None:
                w.kill()
                w.wait()
        for d in (disp_a, disp_b):
            if d is not None and d.poll() is None:
                d.kill()
                d.wait()
        gw.stop()
        store_handle.stop()


def test_pull_worker_kill_loses_no_tasks():
    """Pull-mode in-flight tracking (VERDICT r1 item 3): the reference's
    pull dispatcher keeps only a worker-id list — kill a pull worker holding
    tasks and they are RUNNING forever. Here the dispatcher tracks what it
    handed to whom, treats request silence as death, and re-queues the dead
    worker's tasks for the survivor. Every task completes."""
    from tpu_faas.dispatch.pull import PullDispatcher

    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    disp = PullDispatcher(
        ip="127.0.0.1",
        port=0,
        store=make_store(store_handle.url),
        time_to_expire=1.5,
    )
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        _spawn_worker("pull_worker", 2, url, "--delay", "0.01")
        for _ in range(2)
    ]
    client = FaaSClient(gw.url)
    try:
        fid = client.register(sleep_task)
        handles = [client.submit(fid, 0.8) for _ in range(8)]
        # condition wait, not a tight wall-clock bound: under full-suite
        # load, worker subprocess startup + first REQ can take tens of
        # seconds — the assert is "tasks start", not "tasks start fast"
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if sum(h.status() == "RUNNING" for h in handles) >= 2:
                break
            time.sleep(0.1)
        else:
            raise AssertionError("tasks never started on the pull fleet")
        workers[0].send_signal(signal.SIGKILL)
        workers[0].wait()
        # generous: the surviving 2-proc worker serially re-runs the dead
        # worker's reclaimed tasks, and a loaded box stretches every leg
        assert [h.result(timeout=180) for h in handles] == [0.8] * 8
        assert disp.n_reclaimed > 0  # the recovery path actually ran
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def test_resident_dispatcher_crash_restart_mid_run():
    """Same disposable-dispatcher contract for --resident: the pending set
    lives in DEVICE memory, which dies with the process — so the restart
    must rebuild everything from the store (reconnects + startup rescan),
    proving no task's fate ever depends on the resident device state."""
    port = _free_port()

    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    disp_a = _spawn_dispatcher(
        port, store_handle.url, "--resident",
        "--max-pending", "256", "--max-fleet", "64",
    )
    url = f"tcp://127.0.0.1:{port}"
    worker = _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
    client = FaaSClient(gw.url)
    disp_b = None
    try:
        fid = client.register(sleep_task)
        first = [client.submit(fid, 0.5) for _ in range(4)]
        time.sleep(1.2)

        disp_a.kill()  # device-resident pending state dies here
        disp_a.wait()
        during = [client.submit(fid, 0.2) for _ in range(4)]
        time.sleep(0.5)

        disp_b = _spawn_dispatcher(
            port, store_handle.url, "--resident",
            "--max-pending", "256", "--max-fleet", "64",
        )
        assert [h.result(timeout=90) for h in first] == [0.5] * 4
        assert [h.result(timeout=90) for h in during] == [0.2] * 4
    finally:
        worker.kill()
        worker.wait()
        for d in (disp_a, disp_b):
            if d is not None and d.poll() is None:
                d.kill()
                d.wait()
        gw.stop()
        store_handle.stop()


def test_dispatcher_sigkill_restart_keeps_fleet_grades():
    """VERDICT r4 missing #4, the chaos form: a dispatcher SIGKILL +
    same-port restart must keep a mixed fleet's learned speed grades with
    NO relearn window — the replacement loads them from the store at
    construction (before any traffic) and re-applies them as the workers
    reconnect under their stable tokens."""
    from tests.test_workers_e2e import poll_stats
    from tpu_faas.sched.estimator import WORKER_STATS_KEY

    port, stats_port = _free_port(), _free_port()
    store_handle = start_store_thread()
    raw = make_store(store_handle.url)
    # yesterday's learning: two machine grades persisted under stable
    # tokens (the e2e-observable form of a mixed fleet's history)
    raw.hset(WORKER_STATS_KEY, {"tok-fast": "4.0", "tok-slow": "0.5"})
    gw = start_gateway_thread(make_store(store_handle.url))
    stats_args = ("--stats-port", str(stats_port))
    disp_a = _spawn_dispatcher(port, store_handle.url, *stats_args)
    url = f"tcp://127.0.0.1:{port}"
    workers = [
        _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3",
                      "--token", tok)
        for tok in ("tok-fast", "tok-slow")
    ]
    client = FaaSClient(gw.url)

    def stats():
        return poll_stats(stats_port)

    disp_b = None
    try:
        fid = client.register(sleep_task)
        assert [
            client.submit(fid, 0.05).result(timeout=60) for _ in range(4)
        ] == [0.05] * 4
        s = stats()["estimator"]
        assert s["workers_graded"] >= 2  # both grades loaded and live

        disp_a.kill()  # hard crash, no goodbye
        disp_a.wait()
        disp_b = _spawn_dispatcher(port, store_handle.url, *stats_args)
        s = stats()["estimator"]
        # the replacement knows the whole fleet's grades BEFORE any
        # result arrives: zero relearn window
        assert s["workers_graded"] >= 2, s
        assert s["observations"] == 0, s
        # and serving resumes across the reconnecting (zombie) workers
        assert [
            client.submit(fid, 0.05).result(timeout=90) for _ in range(4)
        ] == [0.05] * 4
    finally:
        for w in workers:
            w.kill()
            w.wait()
        for d in (disp_a, disp_b):
            if d is not None and d.poll() is None:
                d.kill()
                d.wait()
        gw.stop()
        store_handle.stop()
