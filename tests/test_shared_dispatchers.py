"""Shared-fleet mode: several dispatchers on one store+channel, each task
executed by exactly one of them; a dead sibling's tasks migrate via
lease/claim adoption. The reference architecturally cannot do this — its
single dispatcher IS the fleet (SURVEY §3.2)."""

from __future__ import annotations

import signal
import threading
import time

from tpu_faas.client import FaaSClient
from tpu_faas.core.task import claim_field_for
from tpu_faas.dispatch.base import PendingTask, TaskDispatcher
from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store.launch import make_store, start_store_thread
from tpu_faas.store.memory import MemoryStore
from tpu_faas.store.racecheck import RaceCheckStore, RaceMonitor
from tpu_faas.workloads import arithmetic, sleep_task
from tests.test_workers_e2e import _spawn_worker


def _wait_until_hot(*dispatchers, timeout: float = 120.0):
    """Block until every dispatcher has run its first device tick (paying
    the jit compile) and has at least one registered worker — the timing
    assertions in these tests are structural only once both loops are hot."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(
            d.tracer.summary().get("device_tick", {}).get("count", 0) >= 1
            and len(d.arrays.worker_ids) >= 1
            for d in dispatchers
        ):
            return
        time.sleep(0.1)
    raise AssertionError("dispatchers never became hot")


def test_claim_for_dispatch_partitions_batches():
    """Two dispatchers claiming overlapping batches: every task is kept by
    exactly one (and re-claiming your own keeps it)."""
    store = MemoryStore()
    a = TaskDispatcher(store=store, shared=True)
    b = TaskDispatcher(store=store, shared=True)
    tasks = [PendingTask(f"t{i}", "F", "P") for i in range(20)]
    for t in tasks:
        store.create_task(t.task_id, "F", "P")
    kept_a = a.claim_for_dispatch(tasks)
    kept_b = b.claim_for_dispatch(tasks)
    ids_a = {t.task_id for t in kept_a}
    ids_b = {t.task_id for t in kept_b}
    assert ids_a == {t.task_id for t in tasks}  # a claimed everything first
    assert ids_b == set()  # b lost every claim
    # re-claim of your own batch is idempotent
    assert {t.task_id for t in a.claim_for_dispatch(tasks)} == ids_a
    # adoption arbitration: one winner per generation, takeover once stale
    assert a.claim_adoption("t0", 1, stale_after=60.0) is True
    assert b.claim_adoption("t0", 1, stale_after=60.0) is False
    # a LIVE owner's claim is never stolen, however old the claim stamp is
    # (claims are stamped once, not renewed; liveness comes from the
    # dispatcher heartbeat registry)
    from tpu_faas.core.task import claim_field_for as cff

    store.hset("t1", {cff(2): f"{a.dispatcher_id}:0.0"})  # ancient stamp
    assert b.claim_adoption("t1", 2, stale_after=60.0) is False
    assert b.claim_adoption("t0", 1, stale_after=-1.0) is True  # stale -> take
    # unshared dispatchers never pay any of this
    c = TaskDispatcher(store=store, shared=False)
    assert c.claim_for_dispatch(tasks) is tasks


def test_two_shared_dispatchers_run_each_task_exactly_once():
    """Two tpu-push dispatchers, one store+channel, separate worker fleets:
    40 tasks all complete, the race monitor sees no double-dispatch, and
    BOTH dispatchers did real work (the claim split is live, not one
    dispatcher winning everything)."""
    monitor = RaceMonitor()
    store_handle = start_store_thread()
    gw = start_gateway_thread(
        RaceCheckStore(make_store(store_handle.url), monitor, actor="gateway")
    )

    def make_disp(name):
        return TpuPushDispatcher(
            ip="127.0.0.1",
            port=0,
            store=RaceCheckStore(
                make_store(store_handle.url), monitor, actor=name
            ),
            max_workers=32,
            # small pending window: neither dispatcher can swallow the whole
            # queue into its buffer, so BOTH must do real work — making the
            # both-active assertion below deterministic, not a timing race
            max_pending=8,
            max_inflight=256,
            tick_period=0.01,
            time_to_expire=2.0,
            rescan_period=0.5,
            shared=True,
        )

    d1, d2 = make_disp("disp-1"), make_disp("disp-2")
    threads = [
        threading.Thread(target=d.start, daemon=True) for d in (d1, d2)
    ]
    for t in threads:
        t.start()
    workers = [
        _spawn_worker(
            "push_worker", 2, f"tcp://127.0.0.1:{d.port}", "--hb",
            "--hb-period", "0.3",
        )
        for d in (d1, d2)
    ]
    client = FaaSClient(gw.url)
    try:
        _wait_until_hot(d1, d2)

        fid = client.register(sleep_task)
        handles = client.submit_many(
            fid, [((0.3,), {}) for _ in range(40)]
        )
        assert [h.result(timeout=180) for h in handles] == [0.3] * 40
        # exactly-once: every task dispatched by exactly one dispatcher
        assert d1.n_dispatched + d2.n_dispatched == 40
        # with both loops hot, 40 x 0.3 s tasks cannot drain through one
        # 8-deep window + 2-slot fleet before the sibling claims some
        assert d1.n_dispatched > 0 and d2.n_dispatched > 0
        monitor.assert_clean()
        assert monitor.unfinished() == []
    finally:
        for w in workers:
            w.kill()
            w.wait()
        d1.stop()
        d2.stop()
        for t in threads:
            t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def test_shared_dispatcher_death_migrates_tasks_to_sibling():
    """Kill one shared dispatcher AND its whole worker fleet mid-run: the
    surviving sibling adopts the dead one's tasks (QUEUED via claim-owner
    death, RUNNING via stale lease) and everything completes."""
    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))

    def make_disp():
        return TpuPushDispatcher(
            ip="127.0.0.1",
            port=0,
            store=make_store(store_handle.url),
            max_workers=32,
            max_pending=128,
            max_inflight=256,
            tick_period=0.01,
            time_to_expire=1.5,
            rescan_period=0.5,
            lease_timeout=3.0,
            shared=True,
        )

    d1, d2 = make_disp(), make_disp()
    t1 = threading.Thread(target=d1.start, daemon=True)
    t2 = threading.Thread(target=d2.start, daemon=True)
    t1.start()
    t2.start()
    w1 = _spawn_worker(
        "push_worker", 2, f"tcp://127.0.0.1:{d1.port}", "--hb",
        "--hb-period", "0.3",
    )
    w2 = _spawn_worker(
        "push_worker", 2, f"tcp://127.0.0.1:{d2.port}", "--hb",
        "--hb-period", "0.3",
    )
    client = FaaSClient(gw.url)
    try:
        _wait_until_hot(d1, d2)

        fid = client.register(sleep_task)
        handles = [client.submit(fid, 0.5) for _ in range(16)]
        # wait until d1 actually owns some work, then kill it + its fleet
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and d1.n_dispatched == 0:
            time.sleep(0.05)
        assert d1.n_dispatched > 0
        w1.send_signal(signal.SIGKILL)
        w1.wait()
        d1.stop()
        t1.join(timeout=10)
        # d2 must finish EVERYTHING: d1's queued claims (owner heartbeat
        # gone stale) and its in-flight tasks (leases no longer renewed)
        assert [h.result(timeout=120) for h in handles] == [0.5] * 16
    finally:
        for w in (w1, w2):
            if w.poll() is None:
                w.kill()
                w.wait()
        d1.stop()
        d2.stop()
        t1.join(timeout=5)
        t2.join(timeout=10)
        gw.stop()
        store_handle.stop()
