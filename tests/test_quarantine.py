"""Health-scored worker quarantine (tpu_faas/sched/health.py + the
tpu-push wiring): book policy (enter/release/canary/floors/purge),
the misfire/reclaim health producers and the id-keyed health memory,
the worker_place_cap tick lane (mask + canary + fused-vs-impl parity +
single-device guard), and the dispatcher-level enter -> drain ->
canary -> release lifecycle on fake worker rows."""

from __future__ import annotations

import math

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_faas.sched.health import HUGE_CAP, ENTER, PURGED, QuarantineBook
from tpu_faas.sched.health import REFUSED, RELEASE
from tpu_faas.sched.state import SchedulerArrays, scheduler_tick_impl


# ---------------------------------------------------------------------------
# QuarantineBook policy
# ---------------------------------------------------------------------------
def _book(t, W=4, **kw):
    defaults = dict(
        max_workers=W, enter_below=0.35, release_above=0.8,
        release_streak=2, canary_period_s=2.0, min_live=1,
        min_capacity_frac=0.5, clock=lambda: t[0],
    )
    defaults.update(kw)
    return QuarantineBook(**defaults)


def test_book_enter_canary_and_release_streak():
    t = [100.0]
    q = _book(t)
    health = np.ones(4, np.float32)
    active = np.ones(4, bool)
    procs = np.full(4, 2, np.int32)
    health[1] = 0.2
    assert q.update(health, active, procs) == [(ENTER, 1)]
    assert q.is_quarantined(1) and q.quarantined_rows == (1,)
    assert q.entered_total == 1
    # first place_cap after enter: the row is immediately due a canary
    cap = q.place_cap()
    assert cap[1] == 1 and q.canaries_total == 1
    assert all(cap[r] == HUGE_CAP for r in (0, 2, 3))
    # inside the canary period the ceiling is a hard 0 (drained)
    assert q.place_cap()[1] == 0
    t[0] += 2.5
    assert q.place_cap()[1] == 1  # next probe due
    # release requires the score above the bar for release_streak passes
    health[1] = 0.9
    assert q.update(health, active, procs) == []
    # a re-poisoned score resets the streak
    health[1] = 0.5
    assert q.update(health, active, procs) == []
    health[1] = 0.9
    assert q.update(health, active, procs) == []
    assert q.update(health, active, procs) == [(RELEASE, 1)]
    assert not q.is_quarantined(1) and q.released_total == 1
    assert (np.asarray(q.place_cap()) == HUGE_CAP).all()


def test_book_floors_refuse_rather_than_strand():
    t = [0.0]
    q = _book(t, W=3)
    health = np.full(3, 0.1, np.float32)  # whole fleet looks sick
    active = np.ones(3, bool)
    procs = np.full(3, 2, np.int32)
    events = q.update(health, active, procs)
    # min_capacity_frac=0.5 of 6 slots: only ONE row may be masked; the
    # other two enters are refused and counted, never queued
    assert sorted(k for k, _ in events) == [ENTER, REFUSED, REFUSED]
    assert q.entered_total == 1 and q.refused_total == 2
    assert len(q.quarantined_rows) == 1
    # the capacity snapshot arithmetic the serve loop publishes: the
    # quarantined worker's slots are unavailable, the refused ones count
    avail = active & ~q.quarantined_mask()
    assert int(np.where(avail, procs, 0).sum()) == 4


def test_book_min_live_floor():
    t = [0.0]
    q = _book(t, W=2, min_live=2, min_capacity_frac=0.0)
    health = np.asarray([0.1, 1.0], np.float32)
    active = np.ones(2, bool)
    procs = np.ones(2, np.int32)
    # masking row 0 would leave only 1 live unquarantined < min_live=2
    assert q.update(health, active, procs) == [(REFUSED, 0)]
    assert not q.is_quarantined(0)


def test_book_enters_sickest_first_within_floor_budget():
    t = [0.0]
    q = _book(t, W=4, min_capacity_frac=0.5)
    health = np.asarray([0.3, 0.05, 1.0, 1.0], np.float32)
    active = np.ones(4, bool)
    procs = np.ones(4, np.int32)
    events = q.update(health, active, procs)
    # budget admits two of the two candidates here (2/4 left = 0.5);
    # the sickest row transitions first
    assert events[0] == (ENTER, 1)
    assert (ENTER, 0) in events


def test_book_purges_inactive_rows_without_release_accounting():
    t = [0.0]
    q = _book(t)
    health = np.asarray([0.1, 1.0, 1.0, 1.0], np.float32)
    active = np.ones(4, bool)
    procs = np.ones(4, np.int32)
    q.update(health, active, procs)
    assert q.is_quarantined(0)
    active[0] = False  # liveness purged the worker; row will recycle
    events = q.update(health, active, procs)
    assert (PURGED, 0) in events
    # a purge is not a recovery: released_total stays 0 (the id-keyed
    # health memory carries the penalty to the worker's next identity)
    assert q.released_total == 0 and not q.is_quarantined(0)


# ---------------------------------------------------------------------------
# health producers + id-keyed memory (SchedulerArrays)
# ---------------------------------------------------------------------------
def _arrays(t, W=4):
    return SchedulerArrays(
        max_workers=W, max_pending=8, max_inflight=16, clock=lambda: t[0]
    )


def test_misfire_and_reclaim_decay_with_floor():
    t = [100.0]
    a = _arrays(t)
    r0 = a.register(b"w0", 2)
    a.note_misfire(r0)
    assert a.worker_health[r0] == pytest.approx(a.MISFIRE_DECAY)
    a.note_reclaim(r0)
    assert a.worker_health[r0] == pytest.approx(
        a.MISFIRE_DECAY * a.RECLAIM_DECAY
    )
    # a misfire burst is capped (one RESULT can report many respawns)
    a.register(b"w1", 2)
    a.note_misfire(1, n_new=100)
    assert a.worker_health[1] >= a.HEALTH_FLOOR
    for _ in range(50):
        a.note_reclaim(r0)
    assert a.worker_health[r0] == pytest.approx(a.HEALTH_FLOOR)
    # inactive / out-of-range rows are ignored
    a.deactivate(1)
    a.note_misfire(1)
    a.note_reclaim(-1)
    a.note_reclaim(99)
    assert a.worker_health[1] == pytest.approx(a.HEALTH_FLOOR, abs=0.3)


def test_health_memory_survives_reregistration():
    """Die-and-come-back must not launder the penalty: remember_health
    stashes the score under the worker's stable identity at purge,
    recall_health re-applies it (with elapsed-time recovery credit) when
    that identity registers again — on whatever row it lands."""
    t = [100.0]
    a = _arrays(t)
    r0 = a.register(b"flappy", 2)
    for _ in range(5):
        a.note_reclaim(r0)
    sick = float(a.worker_health[r0])
    a.remember_health(b"tok-1", r0)
    a.deactivate(r0)
    # re-register later on a fresh row: register() wipes to 1.0, recall
    # re-applies the remembered penalty plus recovery for the absence
    t[0] += a.HEALTH_RECOVERY_TAU
    r_new = a.register(b"flappy2", 2)
    assert a.worker_health[r_new] == 1.0
    a.recall_health(b"tok-1", r_new)
    expect = sick + (1.0 - sick) * (1.0 - math.exp(-1.0))
    assert float(a.worker_health[r_new]) == pytest.approx(expect, abs=1e-3)
    # the entry is consumed: a second recall is a no-op
    a.worker_health[r_new] = 1.0
    a.recall_health(b"tok-1", r_new)
    assert a.worker_health[r_new] == 1.0


def test_health_memory_skips_healthy_and_stays_bounded():
    t = [100.0]
    a = _arrays(t)
    r0 = a.register(b"w0", 2)
    a.remember_health(b"healthy", r0)  # score 1.0: nothing worth keeping
    assert b"healthy" not in a.health_memory
    a.note_reclaim(r0)
    for i in range(a.HEALTH_MEMORY_MAX + 5):
        a.remember_health(b"id-%d" % i, r0)
    assert len(a.health_memory) == a.HEALTH_MEMORY_MAX


# ---------------------------------------------------------------------------
# worker_place_cap tick lane
# ---------------------------------------------------------------------------
def test_place_cap_masks_quarantined_and_canary_admits_one():
    t = [100.0]
    a = _arrays(t, W=3)
    for i in range(3):
        a.register(b"w%d" % i, 2)
    a.tick(np.zeros(0, dtype=np.float32))  # seed prev_live
    sizes = np.ones(3, dtype=np.float32)
    cap = np.asarray([0, HUGE_CAP, HUGE_CAP], np.int32)
    out = a.tick(sizes, worker_place_cap=cap)
    asg = np.asarray(out.assignment)[:3]
    assert (asg >= 0).all() and not (asg == 0).any()
    # canary ceiling: exactly ONE task may land on the quarantined row
    a2 = _arrays(t, W=3)
    for i in range(3):
        a2.register(b"v%d" % i, 2)
    a2.tick(np.zeros(0, dtype=np.float32))
    out = a2.tick(
        sizes, worker_place_cap=np.asarray([1, 0, 0], np.int32)
    )
    asg = np.asarray(out.assignment)[:3]
    assert int((asg == 0).sum()) == 1
    assert int((asg >= 0).sum()) == 1  # everyone else is masked


def test_place_cap_parity_fused_vs_impl():
    """The jitted packed tick and the un-jitted scheduler_tick_impl twin
    agree on placements under a ceiling (the PR 13/15 parity rule: every
    optional lane proves its twin)."""
    t = [100.0]
    a = _arrays(t, W=3)
    rows = [a.register(b"w%d" % i, 2) for i in range(3)]
    a.tick(np.zeros(0, dtype=np.float32))
    sizes = np.asarray([1.0, 1.0, 1.0, 1.0], np.float32)
    cap = np.asarray([1, 0, HUGE_CAP], np.int32)
    out_fused = a.tick(sizes, worker_place_cap=cap)
    T = a.max_pending
    padded = np.zeros(T, np.float32)
    padded[:4] = sizes
    valid = np.zeros(T, bool)
    valid[:4] = True
    out_impl = scheduler_tick_impl(
        jnp.asarray(padded),
        jnp.asarray(valid),
        jnp.asarray(a.worker_speed),
        jnp.asarray(a.worker_procs),
        jnp.asarray(a.worker_active),
        jnp.zeros(3, jnp.float32),
        jnp.ones(3, bool),
        jnp.asarray(np.asarray(a.inflight_worker, np.int32)),
        jnp.float32(a.time_to_expire),
        max_slots=a.max_slots,
        worker_place_cap=jnp.asarray(cap),
    )
    np.testing.assert_array_equal(
        np.asarray(out_fused.assignment)[:4],
        np.asarray(out_impl.assignment)[:4],
    )
    asg = np.asarray(out_impl.assignment)[:4]
    assert int((asg == rows[0]).sum()) <= 1  # canary ceiling held
    assert not (asg == rows[1]).any()        # drained row untouched


def test_place_cap_refused_on_sharded_fleets():
    t = [100.0]
    a = _arrays(t, W=2)
    a.register(b"w0", 2)
    a.mesh = object()  # stand-in: the guard must fire before any tick
    with pytest.raises(ValueError, match="single-device"):
        a.tick(
            np.ones(1, np.float32),
            worker_place_cap=np.asarray([0, 0], np.int32),
        )


# ---------------------------------------------------------------------------
# dispatcher lifecycle (fake worker rows, no sockets)
# ---------------------------------------------------------------------------
def _quarantine_dispatcher(clock, **kw):
    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
    from tpu_faas.store import MemoryStore

    defaults = dict(
        ip="127.0.0.1", port=0, store=MemoryStore(),
        max_workers=8, max_pending=64, max_inflight=128, max_slots=2,
        tick_period=0.01, time_to_expire=1000.0, clock=clock,
        estimate_runtimes=False, quarantine=True,
    )
    defaults.update(kw)
    return TpuPushDispatcher(**defaults)


def test_dispatcher_quarantine_enter_drain_release():
    t = [100.0]
    disp = _quarantine_dispatcher(lambda: t[0])
    try:
        a = disp.arrays
        rows = [a.register(b"w%d" % i, 2) for i in range(3)]
        q = disp.quarantine
        assert q is not None and disp._health_on
        # sicken row 0 past the default enter bar (0.35)
        for _ in range(6):
            a.note_reclaim(rows[0])
        disp.tick(intake=False)
        assert q.is_quarantined(rows[0])
        assert disp.stats()["quarantine"]["entered_total"] == 1
        # recovery: long quiet absence snaps health back to 1.0; the
        # release streak then drains over the next passes
        t[0] += 100.0
        for _ in range(q.release_streak + 1):
            disp.tick(intake=False)
        assert not q.is_quarantined(rows[0])
        assert disp.stats()["quarantine"]["released_total"] == 1
        # the lifecycle left a flight-recorder trail
        kinds = [
            (e["kind"], e.get("action"))
            for e in disp.flightrec.snapshot()["events"]
        ]
        assert ("quarantine", "enter") in kinds
        assert ("quarantine", "release") in kinds
    finally:
        disp.close()


def test_dispatcher_quarantine_off_is_inert():
    t = [100.0]
    disp = _quarantine_dispatcher(lambda: t[0], quarantine=False)
    try:
        assert disp.quarantine is None
        assert disp.stats()["quarantine"] is None
        disp.tick(intake=False)  # no place_cap lane reaches the tick
    finally:
        disp.close()


def test_dispatcher_quarantine_refused_on_sharded_modes():
    t = [100.0]
    with pytest.raises(ValueError, match="single-device"):
        _quarantine_dispatcher(lambda: t[0], multihost="2/0/tcp://x:1")


def test_dispatcher_misfire_delta_feeds_health():
    t = [100.0]
    disp = _quarantine_dispatcher(lambda: t[0])
    try:
        a = disp.arrays
        row = a.register(b"w0", 2)
        wid = a.row_ids[row]
        disp.note_worker_misfires(wid, {"misfires": 2})
        assert a.worker_health[row] == pytest.approx(a.MISFIRE_DECAY ** 2)
        # cumulative counter: only the DELTA decays on the next report
        disp.note_worker_misfires(wid, {"misfires": 3})
        assert a.worker_health[row] == pytest.approx(a.MISFIRE_DECAY ** 3)
        # replayed totals are not fresh evidence
        disp.note_worker_misfires(wid, {"misfires": 3})
        assert a.worker_health[row] == pytest.approx(a.MISFIRE_DECAY ** 3)
    finally:
        disp.close()
