"""A Redis-reply-faithful RESP2 responder for compatibility tests.

The drop-in-Redis claim (store/client.py:1-11) needs exercising even on
hosts without a redis-server binary. This module implements the command
subset the store client uses with REAL Redis's reply semantics — the
places where a sloppy server would differ and our client must not care:

- HSET replies ``:<number of NEW fields>`` (not ``+OK``)
- HSETNX replies ``:1``/``:0``
- HGETALL on a missing key replies ``*0`` (not nil)
- HMGET on a missing key replies all-nils
- HDEL/DEL reply with removal counts; a hash emptied by HDEL is deleted
  (KEYS reflects it)
- SUBSCRIBE pushes ``*3 [subscribe, <channel>, :1]``; published messages
  arrive as ``*3 [message, <channel>, <payload>]``; PUBLISH replies with
  the receiver count
- command names are case-insensitive; unknown commands get ``-ERR``

Reply framing is authored against the RESP2 spec and verified manually
against redis-server 7.x behavior (the reference's redis-py dependency
talks to exactly these shapes). Threaded blocking sockets; command
pipelining falls out of sequential per-connection processing.
"""

from __future__ import annotations

import socket
import threading

from tpu_faas.store import resp


class RedisSemanticsServer:
    """Threaded TCP server speaking the Redis subset with authentic replies."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self._hashes: dict[str, dict[str, str]] = {}
        self._subs: dict[socket.socket, set[str]] = {}
        self._lock = threading.RLock()
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(32)
        self.host, self.port = self._listener.getsockname()
        self._stopping = False
        self._conns: list[socket.socket] = []
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"resp://{self.host}:{self.port}"

    def stop(self) -> None:
        self._stopping = True
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            for c in self._conns:
                try:
                    c.close()
                except OSError:
                    pass

    # -- plumbing ----------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stopping:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            with self._lock:
                self._conns.append(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        parser = resp.RespParser()
        try:
            while not self._stopping:
                try:
                    data = conn.recv(65536)
                except OSError:
                    break
                if not data:
                    break
                parser.feed(data)
                out = []
                while True:
                    cmd = parser.pop()
                    if cmd is resp.NEED_MORE:
                        break
                    out.append(self._dispatch(conn, cmd))
                if out:
                    try:
                        conn.sendall(b"".join(out))
                    except OSError:
                        break
        finally:
            with self._lock:
                self._subs.pop(conn, None)
                if conn in self._conns:
                    self._conns.remove(conn)
            try:
                conn.close()
            except OSError:
                pass

    # -- command dispatch with real-Redis reply shapes ---------------------
    def _dispatch(self, conn: socket.socket, cmd) -> bytes:
        if not isinstance(cmd, list) or not cmd:
            return resp.encode_error("protocol error")
        name, args = cmd[0].upper(), cmd[1:]
        with self._lock:
            handler = getattr(self, f"_cmd_{name.lower()}", None)
            if handler is None:
                first = args[0] if args else ""
                return (
                    b"-ERR unknown command '" + name.encode()
                    + b"', with args beginning with: '"
                    + str(first).encode() + b"'\r\n"
                )
            return handler(conn, args)

    def _cmd_ping(self, conn, args) -> bytes:
        if args:
            return resp.encode_bulk(args[0])
        return b"+PONG\r\n"

    def _cmd_hset(self, conn, args) -> bytes:
        key, flat = args[0], args[1:]
        if not flat or len(flat) % 2:
            return (
                b"-ERR wrong number of arguments for 'hset' command\r\n"
            )
        h = self._hashes.setdefault(key, {})
        added = 0
        for f, v in zip(flat[0::2], flat[1::2]):
            added += f not in h
            h[f] = v
        return resp.encode_integer(added)

    def _cmd_hsetnx(self, conn, args) -> bytes:
        key, f, v = args
        h = self._hashes.setdefault(key, {})
        if f in h:
            return resp.encode_integer(0)
        h[f] = v
        return resp.encode_integer(1)

    def _cmd_hget(self, conn, args) -> bytes:
        key, f = args
        return resp.encode_bulk(self._hashes.get(key, {}).get(f))

    def _cmd_hgetall(self, conn, args) -> bytes:
        h = self._hashes.get(args[0], {})
        items = []
        for f, v in h.items():
            items.append(resp.encode_bulk(f))
            items.append(resp.encode_bulk(v))
        return resp.encode_array(items)

    def _cmd_hmget(self, conn, args) -> bytes:
        key, fields = args[0], args[1:]
        h = self._hashes.get(key, {})
        return resp.encode_array(
            [resp.encode_bulk(h.get(f)) for f in fields]
        )

    def _cmd_hdel(self, conn, args) -> bytes:
        key, fields = args[0], args[1:]
        h = self._hashes.get(key)
        if h is None:
            return resp.encode_integer(0)
        removed = 0
        for f in fields:
            removed += h.pop(f, None) is not None
        if not h:
            del self._hashes[key]  # redis deletes empty hashes
        return resp.encode_integer(removed)

    def _cmd_del(self, conn, args) -> bytes:
        removed = 0
        for key in args:
            removed += self._hashes.pop(key, None) is not None
        return resp.encode_integer(removed)

    def _cmd_exists(self, conn, args) -> bytes:
        return resp.encode_integer(
            sum(key in self._hashes for key in args)
        )

    def _cmd_keys(self, conn, args) -> bytes:
        if args[0] != "*":
            return resp.encode_error("only KEYS * is modeled")
        return resp.encode_array(
            [resp.encode_bulk(k) for k in self._hashes]
        )

    def _cmd_flushdb(self, conn, args) -> bytes:
        self._hashes.clear()
        return b"+OK\r\n"

    def _cmd_info(self, conn, args) -> bytes:
        body = (
            "# Server\r\nredis_version:7.2.4\r\n"
            "# Keyspace\r\n"
            f"db0:keys={len(self._hashes)},expires=0\r\n"
        )
        return resp.encode_bulk(body)

    def _cmd_subscribe(self, conn, args) -> bytes:
        chans = self._subs.setdefault(conn, set())
        out = []
        for ch in args:
            chans.add(ch)
            out.append(
                resp.encode_array(
                    [
                        resp.encode_bulk("subscribe"),
                        resp.encode_bulk(ch),
                        resp.encode_integer(len(chans)),
                    ]
                )
            )
        return b"".join(out)

    def _cmd_publish(self, conn, args) -> bytes:
        ch, payload = args
        push = resp.encode_array(
            [
                resp.encode_bulk("message"),
                resp.encode_bulk(ch),
                resp.encode_bulk(payload),
            ]
        )
        n = 0
        for sub_conn, chans in list(self._subs.items()):
            if ch in chans:
                try:
                    sub_conn.sendall(push)
                    n += 1
                except OSError:
                    self._subs.pop(sub_conn, None)
        return resp.encode_integer(n)
