"""Columnar host data plane: the TaskColumns arena, RowTask views, and —
the load-bearing property — the columnar intake lane making EXACTLY the
same decisions as the dict plane over randomized announce streams."""

from __future__ import annotations

import math
import random

import numpy as np
import pytest

from tpu_faas.core.columns import (
    STATUS_DISPATCHED,
    STATUS_FREE,
    STATUS_PENDING,
    RowTask,
    TaskColumns,
)
from tpu_faas.core.task import (
    FIELD_COST,
    FIELD_DEADLINE,
    FIELD_FN_DIGEST,
    FIELD_PRIORITY,
    FIELD_SPECULATIVE,
    FIELD_SUBMITTED_AT,
    FIELD_TENANT,
    FIELD_TIMEOUT,
    FIELD_TRACE_ID,
)
from tpu_faas.dispatch.base import PendingTask, TaskDispatcher
from tpu_faas.store.memory import MemoryStore


# -- arena mechanics ---------------------------------------------------------


def _flat(**fields):
    """kwargs -> the flat [field, value, ...] list hgetall_many_raw hands
    the intake (str spelling; the bytes leg has its own test)."""
    out = []
    for k, v in fields.items():
        out.extend([k, v])
    return out


def test_arena_acquire_release_recycles_and_scrubs():
    a = TaskColumns(capacity=2)
    t = a.intake_flat("t1", _flat(fn_payload="F", param_payload="P",
                                  priority="7", cost="2.5"))
    assert isinstance(t, RowTask)
    row = t.row
    assert a.occupancy == 1
    assert a.row_of("t1") == row
    assert a.status[row] == STATUS_PENDING
    t.release()
    # recycled: id unmapped, row scrubbed, slot reusable
    assert a.occupancy == 0
    assert a.row_of("t1") is None
    assert a.status[row] == STATUS_FREE
    assert a.task_id[row] is None
    assert np.isnan(a.cost[row])
    t2 = a.intake_flat("t2", _flat(fn_payload="G", param_payload="Q"))
    assert t2.row == row  # LIFO free list hands the hot row back
    assert t2.priority == 0 and t2.cost is None


def test_arena_overflow_returns_none_for_dict_fallback():
    a = TaskColumns(capacity=2)
    kept = [a.intake_flat(f"t{i}", _flat(fn_payload="F", param_payload="P"))
            for i in range(2)]
    assert all(isinstance(t, RowTask) for t in kept)
    assert a.intake_flat("t2", _flat(fn_payload="F", param_payload="P")) is None
    kept[0].release()
    assert a.intake_flat("t3", _flat(fn_payload="F", param_payload="P")) is not None


def test_intake_parse_semantics_match_the_dict_plane():
    a = TaskColumns(capacity=8)
    t = a.intake_flat("t", _flat(
        fn_payload="FN", param_payload="PA",
        priority=str(2 ** 40),       # clamps, never overflows the i32 column
        cost="-1",                   # non-positive -> absent
        timeout="inf",               # non-finite -> absent
        deadline="12.5",
        speculative="1",
        fn_digest="", trace_id="", tenant="",  # empty string -> None
    ))
    assert t.priority == 2 ** 30
    assert t.cost is None and t.timeout is None
    assert t.deadline_at == 12.5
    assert t.speculative is True
    assert t.fn_digest is None and t.trace_id is None and t.tenant is None
    junk = a.intake_flat("j", _flat(fn_payload="F", param_payload="P",
                                    priority="wat", cost="nan"))
    assert junk.priority == 0 and junk.cost is None


def test_intake_accepts_bytes_rows():
    """The negotiated binary-batch store hands bytes straight through —
    the intake decodes without a str() detour on the whole row."""
    a = TaskColumns(capacity=4)
    t = a.intake_flat("t", [
        b"fn_payload", b"FN", b"param_payload", b"PAR",
        b"priority", b"-9", b"cost", b"1.5", b"speculative", b"1",
        b"tenant", b"acme",
    ])
    assert t.fn_payload == "FN" and t.param_payload == "PAR"
    assert t.priority == -9 and t.cost == 1.5
    assert t.speculative is True and t.tenant == "acme"
    assert a.payload_bytes[t.row] == 5


def test_rowtask_detach_preserves_every_field():
    a = TaskColumns(capacity=2)
    t = a.intake_flat("t", _flat(
        fn_payload="FN", param_payload="PAR", priority="3", cost="2.0",
        timeout="9.0", submitted_at="100.5", deadline="200.0",
        fn_digest="d1", trace_id="tr", tenant="ten", speculative="1",
    ))
    before = {
        "task_id": t.task_id, "fn_payload": t.fn_payload,
        "param_payload": t.param_payload, "fn_digest": t.fn_digest,
        "trace_id": t.trace_id, "tenant": t.tenant,
        "priority": t.priority, "retries": t.retries,
        "speculative": t.speculative, "cost": t.cost,
        "timeout": t.timeout, "submitted_at": t.submitted_at,
        "deadline_at": t.deadline_at, "size_estimate": t.size_estimate,
    }
    kwargs_before = t.task_message_kwargs(blob=True, trace=True)
    t.release()
    t.release()  # idempotent
    assert t.attached is False and t.row is None
    after = {k: getattr(t, k) for k in before}
    assert after == before
    assert t.task_message_kwargs(blob=True, trace=True) == kwargs_before
    # a detached view still takes writes (parked/requeued copies mutate)
    t.retries = 2
    assert t.retries == 2
    assert a.occupancy == 0


def test_gathers_trust_order_and_dtypes():
    a = TaskColumns(capacity=8)
    t_cost = a.intake_flat("c", _flat(fn_payload="xx", param_payload="yy",
                                      cost="5.0", priority="2"))
    t_learned = a.intake_flat("l", _flat(fn_payload="xx", param_payload="yy"))
    t_learned.learned = 3.0
    t_bytes = a.intake_flat("b", _flat(fn_payload="xxx", param_payload="y"))
    rows = np.array([t_cost.row, t_learned.row, t_bytes.row], dtype=np.intp)
    sizes = a.gather_sizes(rows)
    assert sizes.dtype == np.float32
    assert sizes.tolist() == [5.0, 3.0, 4.0]
    prios = a.gather_priorities(rows)
    assert prios.dtype == np.int32
    assert prios.tolist() == [2, 0, 0]
    # the gathers agree with the scalar views batch-for-batch
    assert [t.size_estimate for t in (t_cost, t_learned, t_bytes)] == [
        5.0, 3.0, 4.0,
    ]


def test_stamp_dispatched_marks_row():
    a = TaskColumns(capacity=2)
    t = a.intake_flat("t", _flat(fn_payload="F", param_payload="P"))
    a.stamp_dispatched(t.row, 42.0)
    assert a.status[t.row] == STATUS_DISPATCHED
    assert a.dispatched_at[t.row] == 42.0


# -- columnar-vs-dict intake equivalence -------------------------------------


_ATTRS = (
    "task_id", "fn_payload", "param_payload", "fn_digest", "trace_id",
    "tenant", "priority", "retries", "speculative", "cost", "timeout",
    "learned", "submitted_at", "deadline_at", "size_estimate",
)


def _random_extra_fields(rng: random.Random) -> dict:
    """A randomized announce record: every optional field independently
    present, with junk spellings mixed in at the rates real clients
    produce them (hand-written curl bodies, reference-era SDKs)."""
    pick = rng.random
    out: dict = {}
    if pick() < 0.7:
        out[FIELD_PRIORITY] = rng.choice(
            ["0", "5", "-3", str(2 ** 40), str(-(2 ** 40)), "wat", "1.5", ""]
        )
    if pick() < 0.6:
        out[FIELD_COST] = rng.choice(
            ["2.5", "0", "-1", "nan", "inf", "junk", "1e3"]
        )
    if pick() < 0.5:
        out[FIELD_TIMEOUT] = rng.choice(["9.0", "-2", "0", "x"])
    if pick() < 0.5:
        out[FIELD_DEADLINE] = rng.choice(["150.25", "-5", "oops"])
    if pick() < 0.5:
        out[FIELD_SPECULATIVE] = rng.choice(["1", "0", "", "yes"])
    if pick() < 0.5:
        out[FIELD_FN_DIGEST] = rng.choice(["sha:abc", ""])
    if pick() < 0.4:
        out[FIELD_TRACE_ID] = rng.choice(["tr-1", ""])
    if pick() < 0.4:
        out[FIELD_TENANT] = rng.choice(["acme", "umbrella", ""])
    return out


def _populate_twins(stores, n: int, seed: int) -> None:
    """Write the IDENTICAL announce stream into both stores (stamps pinned
    so the twins match bit-for-bit despite wall-clock skew). Dispatchers
    must already be constructed — intake only sees announces published
    after its subscription exists."""
    rng = random.Random(seed)
    for i in range(n):
        tid = f"t{i}"
        fn = "F" * rng.randint(1, 12)
        par = "P" * rng.randint(1, 12)
        extra = _random_extra_fields(rng)
        extra[FIELD_SUBMITTED_AT] = f"{100.0 + i}"
        for s in stores:
            s.create_task(tid, fn, par, channel="tasks", extra_fields=extra)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_columnar_intake_equivalent_to_dict_plane(seed):
    """THE acceptance property: the same announce stream through the
    columnar lane and the dict lane yields the same tasks, in the same
    order, with the same derived attributes and the same wire kwargs —
    the arena changes where task state LIVES, never what intake decides."""
    n = 40
    dict_store, col_store = MemoryStore(), MemoryStore()
    d_dict = TaskDispatcher(store=dict_store)
    d_col = TaskDispatcher(store=col_store)
    d_col.enable_columnar(capacity=n)
    _populate_twins((dict_store, col_store), n, seed)
    got_dict = d_dict.poll_tasks(n)
    got_col = d_col.poll_tasks(n)
    assert len(got_dict) == len(got_col) == n
    assert all(isinstance(t, PendingTask) for t in got_dict)
    assert all(isinstance(t, RowTask) for t in got_col)
    for td, tc in zip(got_dict, got_col):
        for attr in _ATTRS:
            vd, vc = getattr(td, attr), getattr(tc, attr)
            assert vd == vc, (td.task_id, attr, vd, vc)
        for blob in (False, True):
            for trace in (False, True):
                assert td.task_message_kwargs(blob=blob, trace=trace) == (
                    tc.task_message_kwargs(blob=blob, trace=trace)
                ), (td.task_id, blob, trace)
    assert d_col.m_columnar_intake.labels(lane="arena").value == n
    assert d_col.m_columnar_intake.labels(lane="fallback").value == 0
    assert d_col.m_arena_occupancy.value == n


def test_columnar_overflow_falls_back_per_task_not_per_poll():
    """A full arena degrades one task at a time to the dict plane — same
    attributes either way, and the lane counters tell the operator the
    arena is undersized."""
    n = 12
    dict_store, col_store = MemoryStore(), MemoryStore()
    d_dict = TaskDispatcher(store=dict_store)
    d_col = TaskDispatcher(store=col_store)
    d_col.enable_columnar(capacity=5)
    _populate_twins((dict_store, col_store), n, seed=7)
    got_dict = d_dict.poll_tasks(n)
    got_col = d_col.poll_tasks(n)
    assert len(got_col) == n
    kinds = [isinstance(t, RowTask) for t in got_col]
    assert kinds.count(True) == 5 and kinds.count(False) == 7
    for td, tc in zip(got_dict, got_col):
        for attr in _ATTRS:
            assert getattr(td, attr) == getattr(tc, attr), (td.task_id, attr)
    assert d_col.m_columnar_intake.labels(lane="arena").value == 5
    assert d_col.m_columnar_intake.labels(lane="fallback").value == 7


def test_claim_loser_releases_its_row():
    """Two dispatchers racing the same announce stream: the claim loser's
    arena rows recycle immediately (a leaked row per lost claim would
    bleed the arena dry in a sharded fleet)."""
    store = MemoryStore()
    winner = TaskDispatcher(store=store, shared=True)
    loser = TaskDispatcher(store=store, shared=True)
    loser.enable_columnar(capacity=6)
    for i in range(6):
        store.create_task(f"t{i}", "F", "P", channel="tasks")
    polled_w = winner.poll_tasks(6)
    polled_l = loser.poll_tasks(6)
    assert winner.claim_for_dispatch(polled_w) == polled_w
    assert loser.claim_for_dispatch(polled_l) == []
    assert loser.arena.occupancy == 0
