"""Wire-parity certification: the REFERENCE's own binaries, unmodified,
against this stack.

Every other e2e suite has both wire ends implemented here; these tests
replace one end (or both) with the reference implementation run straight
from /root/reference:

- ``pull_worker.py`` / ``push_worker.py`` (import only dill+zmq+stdlib —
  pull_worker.py:1-8, push_worker.py:1-7) serve OUR dispatchers and pass
  the service oracle, certifying the register/task/result/heartbeat/
  reconnect envelopes byte-for-byte (push_worker.py:33-37 register,
  helper_functions.py:5-9 dill+base64 serialization).
- Reference workers receive but harmlessly IGNORE our protocol extensions
  (CANCEL messages, per-task ``timeout`` fields) exactly as
  worker/messages.py documents: the push worker's if/elif chain drops
  unknown types (push_worker.py:68-82), and the record converges via the
  ordinary result path.
- The stretch leg runs the reference's own ``task_dispatcher.py``
  (``import redis`` — task_dispatcher.py:2,31-36) against OUR store server
  through the redis-py-surface shim (tpu_faas/compat/redis_shim), with a
  reference worker on the other side: the full reference stack, storage
  swapped for ours, our gateway/client doing the submitting.

The reference workers busy-spin by design (poll(0) loops), so legs keep
fleets small and workloads short.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from contextlib import contextmanager

import pytest

from tests.test_chaos_e2e import _free_port
from tests.test_tpu_push_e2e import _make_dispatcher
from tpu_faas.core.serialize import serialize
from tests.test_workers_e2e import _GroupPopen, _spawn_worker, service_test
from tpu_faas.client import FaaSClient
from tpu_faas.dispatch.pull import PullDispatcher
from tpu_faas.dispatch.push import PushDispatcher
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store.launch import make_store, start_store_thread
from tpu_faas.workloads import sleep_task

REFERENCE_DIR = "/root/reference"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SHIM_DIR = os.path.join(REPO, "tpu_faas", "compat", "redis_shim")

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REFERENCE_DIR),
    reason="reference checkout not present on this host",
)


def _ref_env() -> dict:
    """Subprocess env for reference binaries: inherit, but strip
    sitecustomize dirs that import jax into every interpreter (the
    reference needs only dill+zmq; a multi-second jax import per pool
    child flakes the timing-sensitive legs — see cpu_worker_env)."""
    from tpu_faas.bench.harness import cpu_worker_env

    env = cpu_worker_env()
    # the reference needs nothing from the repo; PYTHONPATH stays anyway
    # (harmless) so pool children resolve the same interpreter setup
    return env


def _spawn_reference_worker(kind: str, n_procs: int, url: str, *extra: str):
    """Run /root/reference/{kind}.py UNMODIFIED (cwd = reference dir so its
    ``from helper_functions import ...`` resolves)."""
    return _GroupPopen(
        [
            sys.executable,
            os.path.join(REFERENCE_DIR, f"{kind}.py"),
            str(n_procs),
            url,
            *extra,
        ],
        env=_ref_env(),
        cwd=REFERENCE_DIR,
        start_new_session=True,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
    )


def _stop_proc(proc) -> str:
    """Kill a reference subprocess and return its captured stderr text."""
    if proc.poll() is None:
        proc.kill()
    try:
        _, err = proc.communicate(timeout=10)
    except subprocess.TimeoutExpired:
        return "<stderr unavailable: communicate timed out>"
    return (err or b"").decode("utf-8", "replace")


@contextmanager
def _ref_worker_stack(mode: str, n_workers: int, n_procs: int, **disp_kw):
    """Our store+gateway+dispatcher; REFERENCE workers on the wire."""
    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    if mode == "pull":
        disp = PullDispatcher(
            ip="127.0.0.1", port=0, store=make_store(store_handle.url),
            **disp_kw,
        )
        # --delay 0.05: the reference worker re-SENDS if a reply misses
        # its delay-wide poll window (REQ crash, pull_worker.py:112-123);
        # on a loaded box our sub-ms reply can land later than 5 ms, and a
        # crashed ref pull worker's task is untracked by design (no
        # worker_id on its messages) — lost exactly as in the reference
        worker_kind, extra = "pull_worker", ("--delay", "0.05")
    elif mode == "tpu_push":
        disp = _make_dispatcher(store_handle.url, **disp_kw)
        worker_kind = "push_worker"
        extra = ("--hb",)
    else:
        disp = PushDispatcher(
            ip="127.0.0.1", port=0, store=make_store(store_handle.url),
            **disp_kw,
        )
        worker_kind = "push_worker"
        extra = ("--hb",) if disp_kw.get("heartbeat") else ()
    disp_thread = threading.Thread(target=disp.start, daemon=True)
    disp_thread.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        _spawn_reference_worker(worker_kind, n_procs, url, *extra)
        for _ in range(n_workers)
    ]
    errs: list[str] = []
    try:
        yield FaaSClient(gw.url), workers, disp
        for w in workers:
            # a reference worker that crashed mid-test (protocol break)
            # must fail the leg even if the oracle somehow passed
            assert w.poll() is None, (
                "reference worker exited early:\n" + _stop_proc(w)
            )
    finally:
        for w in workers:
            errs.append(_stop_proc(w))
        disp.stop()
        disp_thread.join(timeout=10)
        gw.stop()
        store_handle.stop()
        for e in errs:
            # surfaced (not asserted) so teardown noise from the kill
            # itself — KeyboardInterrupt tracebacks etc. — doesn't flake
            # the leg; inside the finally so a failing leg still shows the
            # reference side's stderr
            if e.strip():
                print("reference worker stderr:", e[-2000:])


def test_reference_worker_interop_pull():
    """Reference pull workers (REQ lockstep, register/ready/result with no
    worker_id on result — pull_worker.py:26-34,95-106) against our
    PullDispatcher. Their messages carry no ``worker_id``, so handouts are
    untracked — exactly the reference's own (lack of) in-flight semantics."""
    with _ref_worker_stack("pull", n_workers=2, n_procs=2) as (
        client, _workers, _disp,
    ):
        service_test(client, n_tasks=12)


def test_reference_worker_interop_push():
    """Reference push worker, plain mode (DEALER, no heartbeats) against
    our PushDispatcher LRU mode."""
    with _ref_worker_stack("push", n_workers=2, n_procs=2) as (
        client, _workers, _disp,
    ):
        service_test(client, n_tasks=12)


def test_reference_worker_interop_push_heartbeat():
    """Heartbeat mode. The reference worker never resets its heartbeat
    timer (push_worker.py:60-62 — a documented reference bug), flooding one
    heartbeat per loop iteration after the first second; the dispatcher
    must absorb the flood and keep serving."""
    with _ref_worker_stack(
        "push", n_workers=1, n_procs=2, heartbeat=True, time_to_expire=5.0
    ) as (client, _workers, _disp):
        service_test(client, n_tasks=10)


def test_reference_worker_interop_tpu_push():
    """The TPU device-tick dispatcher serving a reference worker: results
    arrive WITHOUT the ``elapsed`` field (push_worker.py:88-95), so the
    runtime estimator must fall back to its priors while scheduling and
    service stays correct."""
    with _ref_worker_stack("tpu_push", n_workers=1, n_procs=2) as (
        client, _workers, disp,
    ):
        service_test(client, n_tasks=10)
        assert disp.n_dispatched >= 10


def test_reference_worker_interop_mixed_fleet():
    """One reference worker and one of ours on the same dispatcher: the
    protocol extensions are strictly additive, so both serve side by side
    (ours ships ``elapsed``, the reference's doesn't)."""
    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    disp = PushDispatcher(
        ip="127.0.0.1", port=0, store=make_store(store_handle.url),
        heartbeat=True, time_to_expire=5.0,
    )
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    ref_worker = _spawn_reference_worker("push_worker", 2, url, "--hb")
    our_worker = _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
    try:
        service_test(FaaSClient(gw.url), n_tasks=16)
        assert ref_worker.poll() is None and our_worker.poll() is None
    finally:
        _stop_proc(ref_worker)
        if our_worker.poll() is None:
            our_worker.kill()
            our_worker.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def test_reference_worker_ignores_cancel():
    """worker/messages.py's compatibility claim, proven with the other
    side's code: a force-cancel relayed to a reference worker is silently
    dropped (unknown type falls through push_worker.py:68-82's if/elif),
    the task runs to natural completion, and the record converges COMPLETED
    via the ordinary result path — best-effort cancellation degrades to
    exactly the reference's semantics."""
    with _ref_worker_stack(
        "push", n_workers=1, n_procs=1, heartbeat=True, time_to_expire=10.0
    ) as (client, _workers, _disp):
        fid = client.register(sleep_task)
        h = client.submit(fid, 3.0)
        deadline = time.time() + 60
        while h.status() != "RUNNING" and time.time() < deadline:
            time.sleep(0.05)
        assert h.status() == "RUNNING"
        t0 = time.time()
        assert h.cancel(force=True) is False  # asked, not yet terminal
        # the CANCEL reaches the worker and is ignored: the task completes
        # at its natural pace with its real result
        assert h.result(timeout=60.0) == 3.0
        assert time.time() - t0 >= 2.0  # ran out the clock, not interrupted
        assert h.status() == "COMPLETED"


def _run_reference_stack(mode: str, worker_kind: str, *worker_extra: str):
    """The full reference stack on our storage: the reference's OWN
    ``task_dispatcher.py`` (redis-py client surface, hardcoded
    localhost:6379 — task_dispatcher.py:31-36) runs against our RESP store
    server via the redis shim's env override, with an unmodified reference
    worker executing. Our gateway+client submit and collect — the
    drop-in-Redis claim certified from the reference's side of the wire."""
    store_handle = start_store_thread()
    host, port_s = store_handle.url.split("://", 1)[1].rsplit(":", 1)
    gw = start_gateway_thread(make_store(store_handle.url))
    disp_port = _free_port()
    env = dict(
        _ref_env(),
        PYTHONPATH=SHIM_DIR,  # `import redis` -> the shim, nothing else
        REDIS_SHIM_HOST=host,
        REDIS_SHIM_PORT=port_s,
    )
    dispatcher = _GroupPopen(
        [
            sys.executable,
            os.path.join(REFERENCE_DIR, "task_dispatcher.py"),
            "-m", mode, "-p", str(disp_port),
        ],
        env=env,
        cwd=REFERENCE_DIR,
        start_new_session=True,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
    )
    worker = None
    try:
        # Readiness-probe the dispatcher BEFORE spawning the worker: the
        # reference pull worker polls for each REP reply only ``delay``
        # seconds after sending and, missing it, sends again — a REQ-state
        # crash (pull_worker.py:112-123) that fires deterministically when
        # it registers while the dispatcher is still importing. A probe
        # REQ transaction (a 'ready' with no worker state, answered 'wait'
        # while no tasks exist) proves the REP socket is serving. The push
        # path needs no probe (DEALER sends don't require replies) but
        # shares it harmlessly via a plain connect check.
        import zmq as _zmq

        ctx = _zmq.Context.instance()

        def _make_probe():
            p = ctx.socket(_zmq.REQ)
            p.setsockopt(_zmq.LINGER, 0)
            p.setsockopt(_zmq.RCVTIMEO, 500)
            p.connect(f"tcp://127.0.0.1:{disp_port}")
            return p

        probe = _make_probe() if mode == "pull" else None
        deadline = time.time() + 30
        ready = False
        waited = 0.0
        while time.time() < deadline and not ready:
            if dispatcher.poll() is not None:
                pytest.fail(
                    "reference dispatcher exited at startup:\n"
                    + _stop_proc(dispatcher)
                )
            if mode == "pull":
                try:
                    probe.send(serialize({"type": "ready"}).encode("ascii"))
                    probe.recv()
                    ready = True
                except _zmq.Again:
                    # REQ wedged on the unanswered send: rebuild the probe
                    probe.close(linger=0)
                    probe = _make_probe()
            else:
                # push: DEALER sends don't need replies, so plain settling
                # time suffices — but keep polling the process so a
                # dispatcher dying mid-import still fails fast with its
                # stderr instead of a generic service timeout
                time.sleep(0.25)
                waited += 0.25
                ready = waited >= 2.0
        if probe is not None:
            probe.close(linger=0)
        assert ready, "reference dispatcher never answered the REQ probe"
        worker = _spawn_reference_worker(
            worker_kind, 2, f"tcp://127.0.0.1:{disp_port}", *worker_extra
        )
        time.sleep(1.0)  # worker registration before the first announce
        service_test(FaaSClient(gw.url), n_tasks=10, timeout=120.0)
        assert dispatcher.poll() is None, (
            "reference dispatcher died mid-test:\n" + _stop_proc(dispatcher)
        )
        assert worker.poll() is None, (
            "reference worker died mid-test:\n" + _stop_proc(worker)
        )
    finally:
        werr = _stop_proc(worker) if worker is not None else ""
        derr = _stop_proc(dispatcher)
        gw.stop()
        store_handle.stop()
        # inside the finally: a failing leg must still show the reference
        # side's stderr (the one diagnostic this harness exists to capture)
        for name, err in (("dispatcher", derr), ("worker", werr)):
            if err.strip():
                print(f"reference {name} stderr:", err[-2000:])


def test_reference_dispatcher_on_our_store():
    _run_reference_stack("push", "push_worker")


def test_reference_pull_dispatcher_on_our_store():
    """Same full-reference-stack certification over the pull protocol:
    the reference's REP pull dispatcher (task_dispatcher.py:105-187) +
    its REQ pull worker, storage swapped for ours.

    ``--delay 0.05``: the reference worker polls for the REP reply only
    ``delay`` seconds after each send and, missing it, SENDS again — a
    REQ-state crash baked into pull_worker.py:112-123 that its own stack
    dodges only because a local redis answers the dispatcher's pre-reply
    store round trip in microseconds. The reference exposes the delay as
    a CLI knob precisely for slower setups; 50 ms absorbs the shim's TCP
    round trips without modifying the binary."""
    _run_reference_stack("pull", "pull_worker", "--delay", "0.05")


def test_reference_worker_crash_recovery():
    """Our recovery machinery covers REFERENCE workers too: SIGKILL a
    reference push worker while it provably holds in-flight tasks — the
    heartbeat purge reclaims them onto a surviving reference worker and
    every submission still completes (the reference's own dispatcher
    loses such tasks; its README documents it)."""
    import signal

    with _ref_worker_stack(
        "push", n_workers=2, n_procs=2, heartbeat=True, time_to_expire=4.0
    ) as (client, workers, _disp):
        fid = client.register(sleep_task)
        slow = [client.submit(fid, 2.5) for _ in range(6)]
        deadline = time.time() + 60
        while time.time() < deadline:
            if sum(1 for h in slow if h.status() == "RUNNING") >= 4:
                break
            time.sleep(0.1)
        else:
            raise AssertionError("tasks never saturated both ref workers")
        workers[0].send_signal(signal.SIGKILL)
        workers[0].wait()
        assert [h.result(timeout=120.0) for h in slow] == [2.5] * 6
        # teardown asserts workers alive; the killed one is expected dead
        workers.pop(0)
