"""Store high availability (tpu_faas/store/replication.py): streaming
replication, replica promotion, epoch fencing, client failover, and the
kill-the-primary-mid-burst chaos run.

Units: full sync + live stream + offset tracking, read-only replica
gating, stream reconnect after a primary restart, fencing of a
resurrected old primary (both against HA-aware and legacy clients),
REPLAY ring semantics, multi-endpoint client failover + the announce
subscription following it, and the dispatcher's re-arm round.

Chaos: the real stack — primary store as a SIGKILL-able subprocess with
a replica tailing it, gateway with admission + breaker, tpu-push
dispatcher, subprocess workers, race monitor on every store client.
Primary dies mid-burst, the replica is promoted, and the invariants are:
zero admitted-task loss, zero protocol violations (no double terminal
writes), recovery within a pinned window.
"""

from __future__ import annotations

import signal
import socket
import subprocess
import sys
import threading
import time

import pytest
import requests

from tpu_faas.admission import AdmissionController
from tpu_faas.admission.breaker import CircuitBreaker
from tpu_faas.admission.controller import AdmissionConfig
from tpu_faas.client import FaaSClient
from tpu_faas.core.executor import pack_params
from tpu_faas.core.task import TaskStatus
from tpu_faas.core.serialize import serialize
from tpu_faas.dispatch.base import TaskDispatcher
from tpu_faas.dispatch.local import LocalDispatcher
from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store import resp
from tpu_faas.store.client import RespStore
from tpu_faas.store.launch import make_store, start_store_thread
from tpu_faas.store.memory import MemoryStore
from tpu_faas.store.racecheck import RaceCheckStore, RaceMonitor
from tpu_faas.store.replication import (
    AnnounceRing,
    parse_endpoint,
)
from tpu_faas.workloads import sleep_task
from tests.test_workers_e2e import _spawn_worker


def _wait_until(predicate, timeout: float = 5.0, period: float = 0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(period)
    return predicate()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# -- replication units -------------------------------------------------------


def test_replica_full_syncs_then_tails_the_stream():
    """A replica connecting to a primary with existing state adopts it
    via the snapshot full sync, then applies live writes in order; both
    ends track the same replication offset."""
    p = start_store_thread()
    r = None
    try:
        pc = RespStore(port=p.port)
        pc.hset("pre", {"a": "1", "b": "2"})  # state BEFORE the replica
        pre_offset = int(pc.info()["repl_offset"])
        assert pre_offset >= 1
        r = start_store_thread(replica_of=("127.0.0.1", p.port))
        rc = RespStore(port=r.port)
        assert _wait_until(lambda: rc.hget("pre", "a") == "1")
        pc.hset("post", {"x": "y"})  # streamed, not snapshotted
        pc.hset("pre", {"a": "updated"})
        assert _wait_until(lambda: rc.hget("post", "x") == "y")
        assert rc.hget("pre", "a") == "updated"
        # offsets in lockstep, and the primary sees the replica's acks
        p_info = pc.info()
        assert _wait_until(
            lambda: int(rc.info()["repl_offset"]) == int(p_info["repl_offset"])
        )
        assert int(p_info["repl_replicas"]) == 1
        assert _wait_until(lambda: int(pc.info()["repl_lag"]) == 0)
        assert rc.info()["role"] == "replica"
        pc.close(), rc.close()
    finally:
        if r is not None:
            r.stop()
        p.stop()


def test_replicated_deletes_do_not_resurrect():
    """DEL and hash-emptying HDEL replicate: the replica's copy of a
    GC'd blob or a dropped live-index entry is removed too."""
    p = start_store_thread()
    r = start_store_thread(replica_of=("127.0.0.1", p.port))
    try:
        pc = RespStore(port=p.port)
        rc = RespStore(port=r.port)
        pc.hset("blob:dead", {"data": "x"})
        pc.hset("index", {"t1": "1", "t2": "1"})
        assert _wait_until(lambda: rc.hget("blob:dead", "data") == "x")
        pc.delete("blob:dead")
        pc.hdel("index", "t1")
        assert _wait_until(lambda: rc.hget("blob:dead", "data") is None)
        assert rc.hgetall("index") == {"t2": "1"}
        pc.close(), rc.close()
    finally:
        r.stop()
        p.stop()


def test_replica_is_readonly_until_promoted():
    p = start_store_thread()
    r = start_store_thread(replica_of=("127.0.0.1", p.port))
    try:
        rc = RespStore(port=r.port)
        assert _wait_until(lambda: rc.info().get("repl_link_up") == "1")
        with pytest.raises(resp.RespError, match="READONLY"):
            rc.hset("nope", {"f": "v"})
        with pytest.raises(resp.RespError, match="READONLY"):
            rc.publish("tasks", "nope")
        assert rc.role()["role"] == "replica"
        # promotion: takes writes, bumps the epoch, and is idempotent
        assert rc.promote() == 1
        rc.hset("now-ok", {"f": "v"})
        assert rc.hget("now-ok", "f") == "v"
        assert rc.role() == {"role": "primary", "epoch": 1, "offset": rc.role()["offset"]}
        assert rc.promote() == 1  # retried PROMOTE burns no epoch
        rc.close()
    finally:
        r.stop()
        p.stop()


def test_replication_stream_reconnects_after_primary_restart():
    """A lost link is retried: when the primary comes back on the same
    port the replica full-syncs again and resumes tailing."""
    port = _free_port()
    p = start_store_thread(port=port)
    r = start_store_thread(replica_of=("127.0.0.1", port))
    try:
        pc = RespStore(port=port)
        rc = RespStore(port=r.port)
        pc.hset("one", {"f": "v"})
        assert _wait_until(lambda: rc.hget("one", "f") == "v")
        p.stop()  # link drops; replica keeps retrying
        assert _wait_until(lambda: rc.info().get("repl_link_up") == "0")
        p = start_store_thread(port=port)
        pc2 = RespStore(port=port)
        pc2.hset("two", {"f": "w"})
        assert _wait_until(
            lambda: rc.hget("two", "f") == "w", timeout=10.0
        )
        # the restarted (empty) primary's full sync REPLACED the state:
        # the replica mirrors its primary, it does not merge histories
        assert rc.hget("one", "f") is None
        pc.close(), pc2.close(), rc.close()
    finally:
        r.stop()
        p.stop()


def test_epoch_fencing_blocks_resurrected_old_primary():
    """After a promotion, a client that saw the new epoch declares it on
    every handshake — a resurrected old primary (epoch 0) learns it was
    superseded and permanently refuses writes, even from epoch-oblivious
    legacy clients."""
    pport = _free_port()
    p = start_store_thread(port=pport)
    r = start_store_thread(replica_of=("127.0.0.1", pport))
    try:
        endpoints = [("127.0.0.1", pport), ("127.0.0.1", r.port)]
        mc = RespStore(endpoints=endpoints)
        mc.hset("t", {"f": "v"})
        probe = RespStore(port=r.port)
        assert _wait_until(lambda: probe.hget("t", "f") == "v")  # replicated
        probe.close()
        p.stop()  # primary dies
        # failover controller promotes the replica; the client adopts the
        # new epoch on its next (re)connect handshake
        rc = RespStore(port=r.port)
        assert _wait_until(lambda: rc.promote() == 1)
        assert mc.hget("t", "f") == "v"  # reconnected through the ring
        assert mc.known_epoch == 1
        assert mc.port == r.port
        # -- resurrection: old primary returns, same port, epoch 0 -------
        p2 = start_store_thread(port=pport)
        try:
            # untouched so far: fencing needs a client handshake to carry
            # the news (the epoch-carrying rotation below, or any fresh
            # multi-endpoint client's discovery sweep)
            assert not p2.server.repl.fenced
            # the epoch-aware client walks the ring through the stale
            # primary (rotation: exactly what a breaker probe or a
            # replica hiccup triggers), declares epoch 1, fences it, and
            # skips it — settling back on the true primary
            assert mc.rotate_endpoint()
            mc.hset("t2", {"f": "v"})
            assert mc.port == r.port  # never regressed to the stale one
            assert p2.server.repl.fenced
            # once fenced, even epoch-oblivious legacy clients pointed
            # straight at the stale primary are refused writes
            legacy = RespStore(port=pport)
            with pytest.raises(resp.RespError, match="FENCED"):
                legacy.hset("stale", {"f": "v"})
            assert legacy.info()["role"] == "fenced"
            legacy.close()
        finally:
            p2.stop()
        mc.close(), rc.close()
    finally:
        r.stop()
        p.stop()


def test_fresh_client_prefers_highest_epoch_primary_and_fences_stale():
    """A FRESH process (known_epoch 0) whose ring lists a stale primary
    (epoch 0) before the true one (epoch 1) must not split-brain: the
    connect's discovery sweep handshakes every reachable endpoint before
    settling, picks the highest-epoch primary, and actively fences the
    stale one."""
    p = start_store_thread()
    r = start_store_thread(replica_of=("127.0.0.1", p.port))
    try:
        rc = RespStore(port=r.port)
        assert _wait_until(lambda: rc.info().get("repl_link_up") == "1")
        # promote WITHOUT killing the primary: both now claim "primary",
        # epochs 0 and 1 — the resurrected-old-primary shape, both alive
        assert rc.promote() == 1
        mc = RespStore(
            endpoints=[("127.0.0.1", p.port), ("127.0.0.1", r.port)]
        )
        assert mc.port == r.port  # settled on the epoch-1 primary
        assert mc.known_epoch == 1
        assert _wait_until(lambda: p.server.repl.fenced)  # stale: bricked
        mc.hset("safe", {"f": "v"})
        assert rc.hget("safe", "f") == "v"
        mc.close(), rc.close()
    finally:
        r.stop()
        p.stop()


def test_announce_ring_bounds_and_since():
    ring = AnnounceRing(maxlen=4)
    for i in range(1, 8):  # 7 appends into a 4-slot ring
        ring.append(i, "tasks", f"t{i}")
    assert ring.tail == 7
    assert len(ring) == 4
    # since() below the head returns the whole (truncated) ring
    assert [p for _, _, p in ring.since(0)] == ["t4", "t5", "t6", "t7"]
    assert [p for _, _, p in ring.since(5)] == ["t6", "t7"]
    assert ring.since(7) == []


def test_replay_announces_offsets_and_priming():
    p = start_store_thread()
    try:
        c = RespStore(port=p.port)
        tail0, entries = c.replay_announces(-1)  # priming: tail only
        assert entries == []
        c.publish("tasks", "t1")
        c.publish("other", "x")
        c.publish("tasks", "t2")
        tail, entries = c.replay_announces(tail0)
        assert tail > tail0
        assert ("tasks", "t1") in entries and ("tasks", "t2") in entries
        assert ("other", "x") in entries  # replay is channel-agnostic
        # nothing new since the tail
        assert c.replay_announces(tail) == (tail, [])
        c.close()
    finally:
        p.stop()


def test_parse_endpoint_and_multi_endpoint_url():
    assert parse_endpoint("host:123") == ("host", 123)
    assert parse_endpoint("host") == ("host", 6380)
    p = start_store_thread()
    r = start_store_thread(replica_of=("127.0.0.1", p.port))
    try:
        store = make_store(
            f"resp://127.0.0.1:{p.port},127.0.0.1:{r.port}"
        )
        assert store.endpoints == [
            ("127.0.0.1", p.port),
            ("127.0.0.1", r.port),
        ]
        assert store.port == p.port  # settled on the writable primary
        # single-endpoint form unchanged
        single = make_store(f"resp://127.0.0.1:{p.port}")
        assert single.endpoints == [("127.0.0.1", p.port)]
        store.close(), single.close()
    finally:
        r.stop()
        p.stop()


def test_client_fails_over_and_subscription_follows():
    """The multi-endpoint client settles on the promoted replica after
    the primary dies (one failover generation, counted), and the announce
    subscription reattaches to the new endpoint so post-failover
    announces arrive."""
    p = start_store_thread()
    r = start_store_thread(replica_of=("127.0.0.1", p.port))
    try:
        mc = RespStore(
            endpoints=[("127.0.0.1", p.port), ("127.0.0.1", r.port)]
        )
        sub = mc.subscribe("tasks")
        mc.publish("tasks", "before")
        assert _wait_until(lambda: sub.get_message(0.2) == "before")
        gen0 = mc.failover_generation
        p.stop()
        rc = RespStore(port=r.port)
        rc.promote()
        # next command walks the ring and settles on the promoted replica
        assert mc.hget("whatever", "f") is None
        assert mc.failover_generation == gen0 + 1
        assert mc.port == r.port
        # the subscription notices the generation change and reattaches;
        # a publish racing the reattach is the bus's documented
        # fire-and-forget loss (covered by replay), so publish each try
        got = None

        def _drain():
            nonlocal got
            mc.publish("tasks", "after")
            got = got or sub.get_message(0.2)
            return got == "after"

        assert _wait_until(_drain, timeout=5.0)
        sub.close(), mc.close(), rc.close()
    finally:
        r.stop()
        p.stop()


def test_single_endpoint_wire_surface_sends_no_handshake():
    """A classic single-endpoint client must not emit FENCE/ROLE — the
    wire toward a plain Redis is byte-identical to before this PR."""
    p = start_store_thread()
    try:
        c = RespStore(port=p.port)
        c.hset("k", {"f": "v"})
        # the server's offset counts ONLY the mutating command: had the
        # client sent a handshake, FENCE would have been refused... prove
        # it differently — spy on the socket bytes of a fresh connect
        sent = []
        import tpu_faas.store.client as client_mod

        orig_init = client_mod._Conn.__init__

        def spy_init(self, host, port):
            orig_init(self, host, port)
            orig_send = self.send_many

            def spy_send(cmds):
                sent.extend(str(cmd[0]).upper() for cmd in cmds)
                return orig_send(cmds)

            self.send_many = spy_send

        client_mod._Conn.__init__ = spy_init
        try:
            c2 = RespStore(port=p.port)
            c2.ping()
            c2.close()
        finally:
            client_mod._Conn.__init__ = orig_init
        assert "FENCE" not in sent and "ROLE" not in sent
        c.close()
    finally:
        p.stop()


# -- dispatcher re-arm -------------------------------------------------------


class _FailoverableMemoryStore(MemoryStore):
    """MemoryStore with a controllable failover generation — the
    dispatcher re-arm unit test's stand-in for a multi-endpoint client."""

    def __init__(self) -> None:
        super().__init__()
        self.failover_generation = 0


def test_dispatcher_rearm_replays_ring_into_backlog():
    store = _FailoverableMemoryStore()
    d = TaskDispatcher(store=store)
    assert d.maybe_rearm_after_failover() is False  # nothing happened
    # announces land on the ring (drained by nobody — the dead-primary
    # window's shape); channel filtering keeps foreign traffic out
    store.publish(d.channel, "t-lost-1")
    store.publish("other-channel", "foreign")
    store.publish(d.channel, "t-lost-2")
    store.failover_generation += 1
    assert d.maybe_rearm_after_failover() is True
    assert list(d._announce_backlog) == ["t-lost-1", "t-lost-2"]
    assert d.n_failover_rearms == 1
    # consumed: same generation does not re-arm again
    assert d.maybe_rearm_after_failover() is False
    # next failover replays only the NEW window
    store.publish(d.channel, "t-lost-3")
    store.failover_generation += 1
    d._announce_backlog.clear()
    assert d.maybe_rearm_after_failover() is True
    assert list(d._announce_backlog) == ["t-lost-3"]


def test_local_dispatcher_serve_loop_rearms_and_runs_lost_announce():
    """The LOCAL serve loop calls the re-arm too (caught live: a task
    announced during the failover window — after the client settled on
    the new primary, before the subscription reattached — stayed QUEUED
    forever in local mode). The announce lands only in the ring (no
    subscriber yet), the generation bumps, and the loop must replay it
    into intake and execute the task."""
    store = _FailoverableMemoryStore()
    d = LocalDispatcher(num_workers=1, store=store)  # primes ring offset
    store.create_task("lost", serialize(sleep_task), pack_params(0.01))
    store.failover_generation += 1  # nobody subscribed: ring-only announce
    done = []
    t = threading.Thread(target=lambda: done.append(d.start(max_tasks=1)))
    t.start()
    t.join(timeout=30)
    assert done == [1]
    assert store.hget("lost", "status") == "COMPLETED"
    assert d.n_failover_rearms == 1


def test_dispatcher_rearm_degrades_without_replay():
    """Backends without REPLAY (plain Redis): rescan-only re-arm, no
    crash, the generation still gets consumed."""

    class NoReplayStore(_FailoverableMemoryStore):
        def replay_announces(self, after):
            raise resp.RespError("unknown command REPLAY")

    store = NoReplayStore()
    d = TaskDispatcher(store=store)
    store.failover_generation += 1
    assert d.maybe_rearm_after_failover() is True
    assert list(d._announce_backlog) == []
    assert d.maybe_rearm_after_failover() is False


# -- chaos: primary SIGKILL mid-burst ----------------------------------------

BOUND = 30
TASK_S = 0.2
#: recovery bound pinned by the test: from PROMOTE to the first
#: successfully admitted post-failover submit. Breaker window is 1 s;
#: one rotation probe lands on the promoted replica right after it.
RECOVERY_S = 15.0


def _spawn_primary(port: int) -> subprocess.Popen:
    """The primary store as a real subprocess, so SIGKILL means SIGKILL."""
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "tpu_faas.store.server",
            "--host",
            "127.0.0.1",
            "--port",
            str(port),
        ],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=0.5):
                return proc
        except OSError:
            if proc.poll() is not None:
                raise RuntimeError("store server subprocess died at launch")
            time.sleep(0.05)
    proc.kill()
    raise RuntimeError("store server subprocess never bound")


def test_primary_kill_mid_burst_zero_loss():
    pport = _free_port()
    primary = _spawn_primary(pport)
    replica = start_store_thread(replica_of=("127.0.0.1", pport))
    ha_url = f"resp://127.0.0.1:{pport},127.0.0.1:{replica.port}"

    monitor = RaceMonitor()
    admission = AdmissionController(AdmissionConfig(max_system_inflight=BOUND))
    gw = start_gateway_thread(
        RaceCheckStore(make_store(ha_url), monitor, actor="gateway"),
        admission=admission,
        breaker=CircuitBreaker(failure_threshold=3, reset_timeout=1.0),
    )
    disp = TpuPushDispatcher(
        ip="127.0.0.1",
        port=0,
        store=RaceCheckStore(make_store(ha_url), monitor, actor="dispatcher"),
        max_workers=64,
        max_pending=256,
        max_inflight=512,
        tick_period=0.01,
        time_to_expire=1.5,
        rescan_period=0.5,
    )
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
        for _ in range(2)
    ]
    client = FaaSClient(gw.url)
    raw = requests.Session()
    promoted_at: list[float] = []
    recovered_at: list[float] = []
    try:
        fid = client.register(sleep_task)
        payload = pack_params(TASK_S)
        for h in client.submit_many(fid, [((TASK_S,), {})] * 4):
            assert h.result(timeout=60.0) == TASK_S
        # let the replica finish its sync before the fireworks
        rc = RespStore(port=replica.port)
        assert _wait_until(lambda: rc.info().get("repl_link_up") == "1")

        admitted: list[str] = []
        bad_replies = []
        for i in range(3 * BOUND):
            try:
                r = raw.post(
                    f"{gw.url}/execute_function",
                    json={"function_id": fid, "payload": payload},
                    timeout=30,
                )
            except requests.ConnectionError:
                bad_replies.append(("connection-error", i))
                continue
            if r.status_code == 200:
                admitted.append(r.json()["task_id"])
                if promoted_at and not recovered_at:
                    recovered_at.append(time.monotonic())
            elif r.status_code not in (429, 503):
                bad_replies.append((r.status_code, r.text[:200]))
            if i == BOUND:
                # -- the event: primary dies hard, mid-burst ----------
                primary.send_signal(signal.SIGKILL)
                primary.wait()
                # failover controller (the operator runbook's role):
                # promote the replica; clients find it on their next
                # reconnect walk / breaker probe
                rc.promote()
                promoted_at.append(time.monotonic())
            if i > BOUND and not recovered_at:
                time.sleep(0.05)  # give the breaker window room to lapse

        assert not bad_replies, bad_replies
        assert recovered_at, "no submit was admitted after the failover"
        recovery = recovered_at[0] - promoted_at[0]
        assert recovery < RECOVERY_S, f"recovery took {recovery:.1f}s"
        assert len(admitted) >= 1

        # -- drain: zero admitted-task loss across the failover ----------
        probe = RespStore(port=replica.port)
        deadline_wall = time.monotonic() + 120
        statuses: dict[str, str] = {}
        pending = list(admitted)
        while pending and time.monotonic() < deadline_wall:
            got = probe.hget_many(pending, "status")
            still = []
            for tid, status in zip(pending, got):
                if status is not None and TaskStatus.terminal_str(status):
                    statuses[tid] = status
                else:
                    still.append(tid)
            pending = still
            if pending:
                time.sleep(0.25)
        probe.close()
        assert pending == [], f"{len(pending)} admitted tasks lost"
        for tid, status in statuses.items():
            assert status == "COMPLETED", (tid, status)

        # protocol clean under the monitor: no double terminal writes, no
        # illegal transitions — across BOTH stores, since the monitor
        # rides the clients, not the servers
        assert monitor.errors == [], "\n".join(str(v) for v in monitor.errors)
        assert monitor.unfinished() == []
        # the failover actually happened and was re-armed for
        assert disp.n_failover_rearms >= 1
        assert rc.info()["role"] == "primary"
        rc.close()
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        replica.stop()
        if primary.poll() is None:
            primary.kill()
            primary.wait()


# -- store server /healthz //readyz probe pair --------------------------------


def test_store_health_probes_track_role():
    """Probe parity with the gateway/dispatcher stats servers: /healthz
    is unconditional liveness; /readyz 503s while the server cannot take
    writes (unpromoted replica) and flips 200 the moment PROMOTE lands —
    fleet orchestration routes shards on /readyz and restarts on
    /healthz, like every other process."""
    import json
    import urllib.error
    import urllib.request

    primary = start_store_thread(health_port=0)
    replica = start_store_thread(
        replica_of=("127.0.0.1", primary.port), health_port=0
    )
    rc = RespStore(port=replica.port)
    try:
        php = primary.server.health_port
        rhp = replica.server.health_port
        with urllib.request.urlopen(
            f"http://127.0.0.1:{php}/healthz", timeout=5
        ) as r:
            assert r.status == 200
        with urllib.request.urlopen(
            f"http://127.0.0.1:{php}/readyz", timeout=5
        ) as r:
            body = json.load(r)
            assert r.status == 200 and body == {"ready": True, "reason": "ok"}
        # replica: alive, NOT ready
        with urllib.request.urlopen(
            f"http://127.0.0.1:{rhp}/healthz", timeout=5
        ) as r:
            assert r.status == 200
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"http://127.0.0.1:{rhp}/readyz", timeout=5
            )
        assert exc.value.code == 503
        assert json.load(exc.value)["reason"] == "replica"
        # unknown path: 404, not a crash
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"http://127.0.0.1:{rhp}/nope", timeout=5
            )
        assert exc.value.code == 404
        rc.promote()
        with urllib.request.urlopen(
            f"http://127.0.0.1:{rhp}/readyz", timeout=5
        ) as r:
            assert r.status == 200
    finally:
        rc.close()
        replica.stop()
        primary.stop()


def test_store_health_probe_fenced_not_ready():
    """A fenced stale primary keeps answering /healthz but 503s /readyz
    with the fenced reason — exactly the state where orchestration must
    stop routing writes to it without killing the evidence."""
    import json
    import urllib.error
    import urllib.request

    handle = start_store_thread(health_port=0)
    client = RespStore(port=handle.port)
    try:
        # an HA-aware peer declares a higher epoch: the server fences
        client._command("FENCE", 7)
    except resp.RespError:
        pass
    try:
        hp = handle.server.health_port
        with urllib.request.urlopen(
            f"http://127.0.0.1:{hp}/healthz", timeout=5
        ) as r:
            assert r.status == 200
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"http://127.0.0.1:{hp}/readyz", timeout=5
            )
        assert exc.value.code == 503
        assert json.load(exc.value)["reason"] == "fenced"
    finally:
        client.close()
        handle.stop()
