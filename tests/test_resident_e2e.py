"""TpuPushDispatcher --resident end to end: the device-resident pending set
behind the REAL stack — store, gateway, ZMQ push workers — including worker
crash + redistribution and priority admission through the resident kernel.
"""

from __future__ import annotations

import signal
import threading
import time

from tpu_faas.client import FaaSClient
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store.launch import make_store, start_store_thread
from tpu_faas.workloads import sleep_task
from tests.test_tpu_push_e2e import _make_dispatcher
from tests.test_workers_e2e import _spawn_worker, service_test


def _resident_stack(store_url, **kw):
    disp = _make_dispatcher(store_url, resident=True, **kw)
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    return disp, t


def test_resident_end_to_end():
    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    disp, t = _resident_stack(store_handle.url)
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
        for _ in range(2)
    ]
    try:
        service_test(FaaSClient(gw.url), n_tasks=20)
        assert disp.n_dispatched >= 20
        assert disp.resident
        # the device pending set drained fully
        assert not disp._resident_tasks
        assert disp.arrays.n_pending_host == 0
    finally:
        for w in workers:
            w.kill()
            w.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def test_resident_worker_crash_redispatch():
    """SIGKILL a worker holding tasks: the resident tick's compacted
    redispatch readback must reclaim and re-dispatch them to the survivor,
    race-clean under the protocol monitor."""
    from tpu_faas.store.racecheck import RaceCheckStore, RaceMonitor

    monitor = RaceMonitor()
    store_handle = start_store_thread()
    gw = start_gateway_thread(
        RaceCheckStore(make_store(store_handle.url), monitor, actor="gateway")
    )
    disp, t = _resident_stack(
        store_handle.url,
        time_to_expire=1.5,
        store=RaceCheckStore(
            make_store(store_handle.url), monitor, actor="dispatcher"
        ),
    )
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
        for _ in range(2)
    ]
    client = FaaSClient(gw.url)
    try:
        fid = client.register(sleep_task)
        handles = [client.submit(fid, 1.0) for _ in range(8)]
        time.sleep(0.8)
        workers[0].send_signal(signal.SIGKILL)
        workers[0].wait()
        for h in handles:
            assert h.result(timeout=60.0) == 1.0
        monitor.assert_clean()
        assert monitor.unfinished() == []
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def test_resident_priority_admission_e2e():
    """Priority hints flow through the resident kernel: with one
    single-slot worker, a high-priority late submit runs before earlier
    low-priority tasks."""
    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    # hold the tick until all submits land so admission is one batch
    disp, t = _resident_stack(store_handle.url, tick_period=1.0)
    url = f"tcp://127.0.0.1:{disp.port}"
    worker = _spawn_worker("push_worker", 1, url, "--hb", "--hb-period", "0.3")
    client = FaaSClient(gw.url)
    try:
        fid = client.register(sleep_task)
        lows = [
            client.submit_with(fid, args=(0.4,), priority=0) for _ in range(3)
        ]
        hi = client.submit_with(fid, args=(0.4,), priority=9)
        order: list[str] = []
        deadline = time.time() + 60
        pending = {h.task_id: h for h in lows + [hi]}
        while pending and time.time() < deadline:
            for tid, h in list(pending.items()):
                if h.status() == "COMPLETED":
                    order.append(tid)
                    del pending[tid]
            time.sleep(0.05)
        assert not pending, f"{len(pending)} tasks never finished"
        # the high-priority task finished before at least two of the lows
        assert order.index(hi.task_id) <= 1, order
    finally:
        worker.kill()
        worker.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def test_resident_and_plain_shared_dispatchers_exactly_once():
    """The last untested mode pairing: a --resident dispatcher and a plain
    tpu-push dispatcher SHARING one store+channel. Claims partition the
    stream (every task runs exactly once), and both make progress."""
    from tests.test_shared_dispatchers import _wait_until_hot
    from tpu_faas.store.racecheck import RaceCheckStore, RaceMonitor

    monitor = RaceMonitor()
    store_handle = start_store_thread()
    gw = start_gateway_thread(
        RaceCheckStore(make_store(store_handle.url), monitor, actor="gateway")
    )

    def make(name, **kw):
        from tests.test_tpu_push_e2e import _make_dispatcher

        return _make_dispatcher(
            store_handle.url,
            store=RaceCheckStore(
                make_store(store_handle.url), monitor, actor=name
            ),
            max_pending=8,  # small window: both must claim (see
            # test_shared_dispatchers for the determinism argument)
            tick_period=0.01,
            shared=True,
            **kw,
        )

    d1 = make("resident-disp", resident=True)
    d2 = make("plain-disp")
    threads = [
        threading.Thread(target=d.start, daemon=True) for d in (d1, d2)
    ]
    for t in threads:
        t.start()
    workers = [
        _spawn_worker(
            "push_worker", 2, f"tcp://127.0.0.1:{d.port}", "--hb",
            "--hb-period", "0.3",
        )
        for d in (d1, d2)
    ]
    client = FaaSClient(gw.url)
    try:
        _wait_until_hot(d1, d2)
        fid = client.register(sleep_task)
        handles = [client.submit(fid, 0.3) for _ in range(40)]
        assert [h.result(timeout=180) for h in handles] == [0.3] * 40
        assert d1.n_dispatched + d2.n_dispatched == 40
        assert d1.n_dispatched > 0 and d2.n_dispatched > 0
        monitor.assert_clean()
        assert monitor.unfinished() == []
    finally:
        for w in workers:
            w.kill()
            w.wait()
        d1.stop()
        d2.stop()
        for t in threads:
            t.join(timeout=10)
        gw.stop()
        store_handle.stop()
