"""Unit tests for the execution core: serialize, task model, execute_fn."""

import pytest

from tpu_faas.core import (
    TaskStatus,
    deserialize,
    execute_fn,
    new_task_id,
    serialize,
)
from tpu_faas.core.executor import pack_params
from tpu_faas.workloads import arithmetic, failing_task, make_workload


def test_serialize_roundtrip_builtin_types():
    for obj in [42, "hi", [1, 2, 3], {"a": (1, 2)}, None, 3.14, {1, 2}]:
        assert deserialize(serialize(obj)) == obj


def test_serialize_roundtrip_function():
    f = deserialize(serialize(arithmetic))
    assert f(10) == arithmetic(10)


def test_serialize_roundtrip_lambda_and_closure():
    k = 7
    f = deserialize(serialize(lambda x: x + k))
    assert f(1) == 8


def test_serialize_is_ascii_string():
    s = serialize({"payload": b"\x00\xff"})
    assert isinstance(s, str)
    s.encode("ascii")  # must not raise


def test_execute_fn_completed():
    tid = new_task_id()
    out = execute_fn(tid, serialize(arithmetic), pack_params(100))
    assert out.task_id == tid
    assert out.status == "COMPLETED"
    assert deserialize(out.result) == arithmetic(100)


def test_execute_fn_kwargs_contract():
    out = execute_fn("t", serialize(arithmetic), serialize(((), {"n": 50})))
    assert out.status == "COMPLETED"
    assert deserialize(out.result) == arithmetic(50)


def test_execute_fn_failed_on_raise():
    out = execute_fn("t", serialize(failing_task), pack_params("kaput"))
    assert out.status == "FAILED"
    exc = deserialize(out.result)
    assert isinstance(exc, ValueError)
    assert "kaput" in str(exc)


def test_execute_fn_failed_on_garbage_payloads():
    # malformed function payload
    assert execute_fn("t", "not-base64!!!", pack_params()).status == "FAILED"
    # malformed params payload
    assert execute_fn("t", serialize(arithmetic), "junk").status == "FAILED"
    # params not an (args, kwargs) pair
    assert execute_fn("t", serialize(arithmetic), serialize(42)).status == "FAILED"


def test_status_enum():
    assert str(TaskStatus.QUEUED) == "QUEUED"
    assert TaskStatus("COMPLETED").is_terminal()
    assert TaskStatus("FAILED").is_terminal()
    assert not TaskStatus("RUNNING").is_terminal()
    with pytest.raises(ValueError):
        TaskStatus("NOPE")


def test_workload_determinism():
    fn1, p1 = make_workload("sort_numbers", 3, 10, seed=1)
    fn2, p2 = make_workload("sort_numbers", 3, 10, seed=1)
    assert p1 == p2
    args, kwargs = p1[0]
    assert fn1(*args, **kwargs) == sorted(args[0])
