"""Task graphs (tpu_faas/graph): validation, the store promotion plane,
the device frontier kernels, gateway /execute_graph, SDK builders, and the
end-to-end diamond on the tpu-push path — including the acceptance proof
that no WAITING node ever reaches a worker (the race monitor's missing
WAITING -> RUNNING transition)."""

from __future__ import annotations

import signal
import threading
import time

import numpy as np
import pytest
import requests

from tpu_faas.client import FaaSClient, TaskDependencyError
from tpu_faas.core.serialize import deserialize, serialize
from tpu_faas.core.task import (
    FIELD_CHILDREN,
    FIELD_DEPS,
    FIELD_FINISHED_AT,
    FIELD_PENDING_DEPS,
    TaskStatus,
)
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.graph import GraphValidationError, validate_graph
from tpu_faas.store import MemoryStore
from tpu_faas.store.launch import make_store, start_store_thread
from tpu_faas.workloads import arithmetic, failing_task, sleep_task

WAITING = str(TaskStatus.WAITING)
QUEUED = str(TaskStatus.QUEUED)


def _make_waiting(store, task_id, parents, children=None, extra=None):
    fields = {
        FIELD_DEPS: ",".join(parents),
        FIELD_PENDING_DEPS: str(len(parents)),
        **(extra or {}),
    }
    if children:
        fields[FIELD_CHILDREN] = ",".join(children)
    store.create_tasks(
        [(task_id, "f", "p", fields)], status=TaskStatus.WAITING
    )


def _make_parent(store, task_id, children):
    store.create_tasks(
        [(task_id, "f", "p", {FIELD_CHILDREN: ",".join(children)})]
    )


# -- validation --------------------------------------------------------------


def test_validate_graph_accepts_diamond_and_orders_topologically():
    nodes = [
        {"function_id": "f", "payload": "p"},
        {"function_id": "f", "payload": "p", "depends_on": [0]},
        {"function_id": "f", "payload": "p", "depends_on": [0]},
        {"function_id": "f", "payload": "p", "depends_on": [1, 2]},
    ]
    deps, topo = validate_graph(nodes)
    assert deps == [[], [0], [0], [1, 2]]
    pos = {i: k for k, i in enumerate(topo)}
    for i, parents in enumerate(deps):
        for p in parents:
            assert pos[p] < pos[i]


def test_validate_graph_rejects_cycles_refs_and_caps():
    with pytest.raises(GraphValidationError, match="cycle"):
        validate_graph(
            [
                {"function_id": "f", "payload": "p", "depends_on": [1]},
                {"function_id": "f", "payload": "p", "depends_on": [0]},
            ]
        )
    with pytest.raises(GraphValidationError, match="itself"):
        validate_graph(
            [{"function_id": "f", "payload": "p", "depends_on": [0]}]
        )
    with pytest.raises(GraphValidationError, match="out of range"):
        validate_graph(
            [{"function_id": "f", "payload": "p", "depends_on": [5]}]
        )
    with pytest.raises(GraphValidationError, match="unknown node id"):
        validate_graph(
            [{"function_id": "f", "payload": "p", "depends_on": ["ghost"]}]
        )
    with pytest.raises(GraphValidationError, match="duplicates"):
        validate_graph(
            [
                {"function_id": "f", "payload": "p", "id": "a"},
                {"function_id": "f", "payload": "p", "id": "a"},
            ]
        )
    with pytest.raises(GraphValidationError, match="above the cap"):
        validate_graph(
            [{"function_id": "f", "payload": "p"} for _ in range(5)],
            max_nodes=4,
        )
    # string refs resolve by node id
    deps, _ = validate_graph(
        [
            {"function_id": "f", "payload": "p", "id": "root"},
            {"function_id": "f", "payload": "p", "depends_on": ["root"]},
        ]
    )
    assert deps == [[], [0]]


# -- store promotion plane ---------------------------------------------------


def test_promotion_diamond_announces_only_when_last_parent_completes():
    s = MemoryStore()
    _make_waiting(s, "D", ["B", "C"])
    _make_waiting(s, "B", ["A"], children=["D"])
    _make_waiting(s, "C", ["A"], children=["D"])
    _make_parent(s, "A", ["B", "C"])
    sub = s.subscribe("tasks")
    while sub.get_message() is not None:
        pass  # drain the create announces

    s.finish_task("A", TaskStatus.COMPLETED, "r")
    promoted, poisoned = s.complete_dep_many([("A", "COMPLETED")])
    assert sorted(promoted) == ["B", "C"] and poisoned == []
    assert s.get_status("B") == QUEUED and s.get_status("C") == QUEUED
    assert s.get_status("D") == WAITING
    msgs = []
    while True:
        m = sub.get_message()
        if m is None:
            break
        msgs.append(m)
    assert sorted(msgs) == ["B", "C"]  # promoted children re-announced

    s.finish_task("B", TaskStatus.COMPLETED, "r")
    assert s.complete_dep_many([("B", "COMPLETED")]) == ([], [])
    assert s.get_status("D") == WAITING  # one parent still outstanding
    s.finish_task("C", TaskStatus.COMPLETED, "r")
    promoted, _ = s.complete_dep_many([("C", "COMPLETED")])
    assert promoted == ["D"]
    assert s.get_status("D") == QUEUED


def test_poison_walks_transitive_frontier_without_dispatching():
    # chain A -> B -> C -> D; A fails => B, C, D all FAILED, never QUEUED
    s = MemoryStore()
    _make_waiting(s, "D", ["C"])
    _make_waiting(s, "C", ["B"], children=["D"])
    _make_waiting(s, "B", ["A"], children=["C"])
    _make_parent(s, "A", ["B"])
    s.finish_task("A", TaskStatus.FAILED, serialize(ValueError("boom")))
    promoted, poisoned = s.complete_dep_many([("A", "FAILED")])
    assert promoted == [] and poisoned == ["B", "C", "D"]
    for tid, parent in (("B", "A"), ("C", "B"), ("D", "C")):
        assert s.get_status(tid) == "FAILED"
        err = deserialize(s.hget(tid, "result"))
        assert str(err).startswith(f"dep_failed:{parent}"), (tid, err)
        assert s.hget(tid, FIELD_FINISHED_AT) is not None
    # never-dispatched: no record ever read RUNNING, and the live index
    # dropped every poisoned node
    assert s.hgetall("tasks:index") == {}


def test_complete_dep_is_idempotent_across_duplicate_finishes():
    s = MemoryStore()
    _make_waiting(s, "B", ["A"])
    _make_parent(s, "A", ["B"])
    s.finish_task("A", TaskStatus.COMPLETED, "r")
    assert s.complete_dep_many([("A", "COMPLETED")]) == (["B"], [])
    # a zombie's duplicate terminal write replays the walk: the per-edge
    # claim stops the double decrement, the resolution claim the repromote
    assert s.complete_dep_many([("A", "COMPLETED")]) == ([], [])
    assert int(s.hget("B", FIELD_PENDING_DEPS)) == 0
    assert s.get_status("B") == QUEUED


def test_expire_and_cancel_poison_children_in_store():
    s = MemoryStore()
    _make_waiting(s, "B", ["A"])
    _make_parent(s, "A", ["B"])
    assert s.expire_task("A") == "EXPIRED"
    assert s.get_status("B") == "FAILED"
    assert str(deserialize(s.hget("B", "result"))).startswith("dep_failed:A")

    s2 = MemoryStore()
    _make_waiting(s2, "B", ["A"])
    _make_parent(s2, "A", ["B"])
    assert s2.cancel_task("A") == "CANCELLED"
    assert s2.get_status("B") == "FAILED"


def test_resolve_waiting_repairs_lost_promotion_and_poison():
    s = MemoryStore()
    _make_waiting(s, "Y", ["X"])
    _make_parent(s, "X", ["Y"])
    s.finish_task("X", TaskStatus.COMPLETED, "r")  # promotion lost (crash)
    assert s.get_status("Y") == WAITING
    assert s.resolve_waiting("Y", {"X": s.get_status("X")}) == "promoted"
    assert s.get_status("Y") == QUEUED
    # a node with a LIVE parent is left strictly alone
    s2 = MemoryStore()
    _make_waiting(s2, "Y", ["X"])
    _make_parent(s2, "X", ["Y"])
    assert s2.resolve_waiting("Y", {"X": s2.get_status("X")}) is None
    assert s2.get_status("Y") == WAITING
    # vanished parent => poison, transitively
    s3 = MemoryStore()
    _make_waiting(s3, "Z", ["Y"])
    _make_waiting(s3, "Y", ["X"], children=["Z"])
    assert s3.resolve_waiting("Y", {"X": None}) == "poisoned"
    assert s3.get_status("Y") == "FAILED"
    assert s3.get_status("Z") == "FAILED"


def test_sweeper_repairs_orphaned_waiting_nodes():
    from tpu_faas.gateway.app import _sweep_expired_results

    s = MemoryStore()
    _make_waiting(s, "Y", ["X"])
    _make_parent(s, "X", ["Y"])
    s.finish_task("X", TaskStatus.COMPLETED, "r")  # promotion lost
    repaired: list[int] = []
    _sweep_expired_results(
        s, ttl=3600.0, on_waiting_repaired=repaired.append
    )
    assert repaired == [1]
    assert s.get_status("Y") == QUEUED
    # second sweep: nothing left to repair
    _sweep_expired_results(
        s, ttl=3600.0, on_waiting_repaired=repaired.append
    )
    assert repaired == [1]


def test_sweeper_keeps_terminal_parent_while_child_still_waits():
    """A COMPLETED parent whose dep walk is still pending (deferred
    through an outage, resolver crashed) must outlive the result TTL
    while any of its children sits WAITING: resolve_waiting reads a
    missing parent as poison-worthy, so an age-only delete would later
    fail a child whose parents all succeeded. Once the child leaves
    WAITING, the parent expires normally — no leak."""
    import time as _time

    from tpu_faas.gateway.app import _sweep_expired_results

    s = MemoryStore()
    _make_waiting(s, "C", ["P1", "P2"])
    _make_parent(s, "P1", ["C"])
    _make_parent(s, "P2", ["C"])
    s.set_status("P2", TaskStatus.RUNNING)  # sibling still live
    s.finish_task("P1", TaskStatus.COMPLETED, "r")  # dep walk LOST
    aged = _time.time() + 3600  # P1's finish stamp is ancient by then
    deleted = _sweep_expired_results(s, ttl=30.0, now=aged)
    assert deleted == 0
    assert s.get_status("P1") == "COMPLETED"  # survived the TTL
    assert s.get_status("C") == WAITING  # untouched (P2 still live)
    # the deferred walk finally lands: child promoted, parent now free
    s.finish_task("P2", TaskStatus.COMPLETED, "r")
    s.complete_dep_many([("P1", "COMPLETED"), ("P2", "COMPLETED")])
    assert s.get_status("C") == QUEUED
    assert _sweep_expired_results(s, ttl=30.0, now=aged + 3600) >= 2
    assert s.get_status("P1") is None  # expired once nothing waited on it


# -- device frontier kernels -------------------------------------------------


def test_dep_ready_mask_segment_reduce():
    import jax.numpy as jnp

    from tpu_faas.graph.frontier import dep_ready_mask, pad_edges

    T = 8
    child, undone = pad_edges([2, 2, 3], [0, 1, 0], T)
    mask = np.asarray(
        dep_ready_mask(jnp.asarray(child), jnp.asarray(undone), T=T)
    )
    assert not mask[2]  # one unconfirmed parent blocks
    assert mask[3]  # all parents confirmed
    assert mask[0] and mask[7]  # edge-free rows (flat tasks) stay ready


def test_locality_exchange_swaps_only_equal_speed_workers():
    import jax.numpy as jnp

    from tpu_faas.graph.frontier import locality_exchange

    assignment = jnp.asarray(np.array([1, 0, -1, 2], dtype=np.int32))
    speed = jnp.asarray(np.array([1.0, 1.0, 2.0], dtype=np.float32))
    # task 0 prefers w0 (equal speed with its w1): swap with holder task 1
    pref = jnp.asarray(np.array([0, -1, -1, -1], dtype=np.int32))
    out = list(np.asarray(locality_exchange(assignment, pref, speed)))
    assert out == [0, 1, -1, 2]
    # preferring a FASTER worker: no swap (would not be makespan-neutral)
    pref2 = jnp.asarray(np.array([2, -1, -1, -1], dtype=np.int32))
    out2 = list(np.asarray(locality_exchange(assignment, pref2, speed)))
    assert out2 == [1, 0, -1, 2]
    # unassigned preferring task: no swap
    pref3 = jnp.asarray(np.array([-1, -1, 0, -1], dtype=np.int32))
    out3 = list(np.asarray(locality_exchange(assignment, pref3, speed)))
    assert out3 == [1, 0, -1, 2]


# -- gateway /execute_graph --------------------------------------------------


@pytest.fixture()
def gw():
    store = MemoryStore()
    handle = start_gateway_thread(store)
    yield handle, store
    handle.stop()


def _register(url: str, fn) -> str:
    r = requests.post(
        f"{url}/register_function",
        json={"name": fn.__name__, "payload": serialize(fn)},
    )
    assert r.status_code == 200
    return r.json()["function_id"]


def test_execute_graph_creates_children_before_roots(gw):
    handle, store = gw
    fid = _register(handle.url, arithmetic)
    sub = store.subscribe("tasks")
    payload = serialize(((10,), {}))
    nodes = [
        {"function_id": fid, "payload": payload},
        {"function_id": fid, "payload": payload, "depends_on": [0]},
        {"function_id": fid, "payload": payload, "depends_on": [0]},
        {"function_id": fid, "payload": payload, "depends_on": [1, 2]},
    ]
    r = requests.post(f"{handle.url}/execute_graph", json={"nodes": nodes})
    assert r.status_code == 200, r.text
    body = r.json()
    tids = body["task_ids"]
    assert len(tids) == 4
    assert body["graph"] == {"nodes": 4, "roots": 1, "edges": 4}
    root, b, c, sink = tids
    assert store.hgetall(root)["status"] == QUEUED
    assert store.hgetall(root)[FIELD_CHILDREN] == f"{b},{c}"
    for child in (b, c):
        fields = store.hgetall(child)
        assert fields["status"] == WAITING
        assert fields[FIELD_DEPS] == root
        assert fields[FIELD_PENDING_DEPS] == "1"
        assert fields[FIELD_CHILDREN] == sink
    fields = store.hgetall(sink)
    assert fields["status"] == WAITING
    assert fields[FIELD_DEPS] == f"{b},{c}"
    assert fields[FIELD_PENDING_DEPS] == "2"
    # every announce must follow its record write; children announce
    # before roots (creation order proves a parent can never walk edges
    # to missing records)
    announced = []
    while True:
        m = sub.get_message(timeout=1.0)
        if m is None:
            break
        announced.append(m)
    assert set(announced) == set(tids)
    assert announced.index(root) > max(
        announced.index(t) for t in (b, c, sink)
    )


def test_execute_graph_validation_errors(gw):
    handle, _store = gw
    fid = "nonexistent"
    payload = serialize(((1,), {}))
    # cycle -> 400
    r = requests.post(
        f"{handle.url}/execute_graph",
        json={
            "nodes": [
                {"function_id": fid, "payload": payload, "depends_on": [1]},
                {"function_id": fid, "payload": payload, "depends_on": [0]},
            ]
        },
    )
    assert r.status_code == 400 and "cycle" in r.json()["error"]
    # malformed body -> 400
    assert (
        requests.post(f"{handle.url}/execute_graph", json={}).status_code
        == 400
    )
    # unknown function -> 404 (graph validated first)
    r = requests.post(
        f"{handle.url}/execute_graph",
        json={"nodes": [{"function_id": fid, "payload": payload}]},
    )
    assert r.status_code == 404
    # bad hint names the node
    r = requests.post(
        f"{handle.url}/execute_graph",
        json={
            "nodes": [
                {"function_id": fid, "payload": payload, "priority": "x"}
            ]
        },
    )
    assert r.status_code == 400 and "nodes[0]" in r.json()["error"]


# -- SDK builders ------------------------------------------------------------


def test_graph_builder_validation():
    client = FaaSClient("http://127.0.0.1:1")  # never contacted
    g = client.graph()
    other = client.graph()
    n = other.call("fid", 1)
    with pytest.raises(ValueError, match="from this builder"):
        g.call("fid", 2, after=[n])
    with pytest.raises(RuntimeError, match="not submitted"):
        g.call("fid", 3).handle  # noqa: B018 - the property raises


def test_graph_builder_end_to_end_local_dispatcher():
    """client.graph() -> /execute_graph -> local dispatcher: a diamond
    completes in dependency order, entirely through the store promotion
    plane (the local dispatcher has no device frontier)."""
    from tpu_faas.dispatch.local import LocalDispatcher

    store_handle = start_store_thread()
    gw_handle = start_gateway_thread(make_store(store_handle.url))
    disp = LocalDispatcher(num_workers=2, store=make_store(store_handle.url))
    thread = threading.Thread(target=disp.start, daemon=True)
    thread.start()
    client = FaaSClient(gw_handle.url)
    try:
        g = client.graph()
        root = g.call(arithmetic, 100)
        mids = [g.call(arithmetic, 200, after=[root]) for _ in range(3)]
        sink = g.call(arithmetic, 300, after=mids)
        handles = g.submit()
        assert len(handles) == 5 and all(h.task_id for h in handles)
        assert sink.result(timeout=90.0) == arithmetic(300)
        for mid in mids:
            assert mid.result(timeout=30.0) == arithmetic(200)
        # dependency order: every parent's finish stamp precedes its
        # children's
        store = make_store(store_handle.url)
        try:
            t_root = float(store.hget(root.task_id, FIELD_FINISHED_AT))
            t_mids = [
                float(store.hget(m.task_id, FIELD_FINISHED_AT)) for m in mids
            ]
            t_sink = float(store.hget(sink.task_id, FIELD_FINISHED_AT))
        finally:
            store.close()
        assert t_root <= min(t_mids) and max(t_mids) <= t_sink
    finally:
        disp.stop()
        thread.join(timeout=10)
        gw_handle.stop()
        store_handle.stop()


def test_graph_poison_raises_task_dependency_error_sync_and_async():
    """A failing parent poisons its dependents: result() raises
    TaskDependencyError carrying the parent id, in both SDKs, and the
    poisoned nodes never ran."""
    import asyncio

    from tpu_faas.client.aio import AsyncFaaSClient
    from tpu_faas.dispatch.local import LocalDispatcher

    store_handle = start_store_thread()
    gw_handle = start_gateway_thread(make_store(store_handle.url))
    disp = LocalDispatcher(num_workers=2, store=make_store(store_handle.url))
    thread = threading.Thread(target=disp.start, daemon=True)
    thread.start()
    client = FaaSClient(gw_handle.url)
    try:
        g = client.graph()
        bad = g.call(failing_task, "kaput")
        child = g.call(arithmetic, 100, after=[bad])
        grandchild = g.call(arithmetic, 100, after=[child])
        g.submit()
        with pytest.raises(TaskDependencyError) as ei:
            child.result(timeout=60.0)
        assert ei.value.parent_id == bad.task_id
        with pytest.raises(TaskDependencyError) as ei2:
            grandchild.result(timeout=30.0)
        assert ei2.value.parent_id == child.task_id

        async def async_leg():
            async with AsyncFaaSClient(gw_handle.url) as aclient:
                ag = aclient.graph()
                abad = ag.call(failing_task, "kaput")
                achild = ag.call(arithmetic, 50, after=[abad])
                await ag.submit()
                with pytest.raises(TaskDependencyError) as aei:
                    await achild.result(timeout=60.0)
                assert aei.value.parent_id == abad.task_id

        asyncio.run(async_leg())
    finally:
        disp.stop()
        thread.join(timeout=10)
        gw_handle.stop()
        store_handle.stop()


# -- tpu-push: device frontier + e2e ----------------------------------------


def _make_tpu_dispatcher(store_url, **kw):
    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher

    defaults = dict(
        ip="127.0.0.1",
        port=0,
        max_workers=64,
        max_pending=256,
        max_inflight=512,
        tick_period=0.01,
    )
    defaults.update(kw)
    if "store" not in defaults:
        defaults["store"] = make_store(store_url)
    return TpuPushDispatcher(**defaults)


def test_frontier_dispatches_in_tick_when_promotion_announce_is_lost():
    """The device-frontier acceptance slice, deterministic: a chain's
    child is held WAITING in the frontier; the parent's result lands and
    its dep round is confirmed; the promotion ANNOUNCE is stolen off the
    bus (simulating the fire-and-forget loss) — the next tick must still
    dispatch the child, readiness computed by the in-tick mask, and only
    from a QUEUED record."""
    from tpu_faas.store.racecheck import RaceCheckStore, RaceMonitor
    from tpu_faas.worker import messages as m

    monitor = RaceMonitor()
    raw = MemoryStore()
    disp = _make_tpu_dispatcher(
        "memory://",
        store=RaceCheckStore(raw, monitor, actor="dispatcher"),
        recover_queued=False,
    )
    try:
        assert disp.graph is not None
        disp._handle(b"w0", m.REGISTER, {"num_processes": 2})
        feeder = RaceCheckStore(raw, monitor, actor="gateway")
        feeder.create_tasks(
            [
                (
                    "child",
                    "f",
                    "p",
                    {FIELD_DEPS: "parent", FIELD_PENDING_DEPS: "1"},
                )
            ],
            status=TaskStatus.WAITING,
        )
        feeder.create_tasks(
            [("parent", "f", "p", {FIELD_CHILDREN: "child"})]
        )
        disp.tick()  # intake: parent -> pending+dispatch, child -> frontier
        assert "child" in disp.graph.waiting
        assert disp.arrays.inflight_owner("parent") is not None
        # parent's result arrives from its worker
        disp._handle(
            b"w0",
            m.RESULT,
            {"task_id": "parent", "status": "COMPLETED", "result": "r"},
        )
        assert raw.get_status("child") == QUEUED  # promotion plane ran
        # steal every buffered announce (incl. the promotion announce):
        # the frontier must not depend on the fire-and-forget bus
        while disp.subscriber.get_message() is not None:
            pass
        disp.tick()
        assert disp.n_frontier_dispatches == 1
        assert "child" not in disp.graph.waiting
        assert disp.arrays.inflight_owner("child") is not None
        disp._handle(
            b"w0",
            m.RESULT,
            {"task_id": "child", "status": "COMPLETED", "result": "r"},
        )
        # the monitor proves the child was never touched while WAITING
        # (WAITING -> RUNNING is an illegal transition it would flag)
        monitor.assert_clean()
        assert monitor.unfinished() == []
    finally:
        disp.close()


def test_frontier_dispatched_mid_node_still_promotes_its_children():
    """Regression: a mid-graph node (both child AND parent) dispatched
    straight from the device frontier never re-enters intake — its
    forward edges must have been registered at the WAITING drain, or its
    children would strand until the sweeper. Chain A -> B -> C with B and
    C frontier-held; every promotion announce is stolen, so the frontier
    fast path is the ONLY route — C must still complete."""
    from tpu_faas.worker import messages as m

    disp = _make_tpu_dispatcher("memory://", recover_queued=False)
    try:
        store = disp.store
        disp._handle(b"w0", m.REGISTER, {"num_processes": 2})
        store.create_tasks(
            [("C", "f", "p", {FIELD_DEPS: "B", FIELD_PENDING_DEPS: "1"})],
            status=TaskStatus.WAITING,
        )
        store.create_tasks(
            [
                (
                    "B",
                    "f",
                    "p",
                    {
                        FIELD_DEPS: "A",
                        FIELD_PENDING_DEPS: "1",
                        FIELD_CHILDREN: "C",
                    },
                )
            ],
            status=TaskStatus.WAITING,
        )
        store.create_tasks([("A", "f", "p", {FIELD_CHILDREN: "B"})])
        disp.tick()  # A dispatches; B, C held in the frontier
        assert {"B", "C"} <= set(disp.graph.waiting)
        assert "B" in disp.graph_parents  # registered at the WAITING drain
        disp._handle(
            b"w0",
            m.RESULT,
            {"task_id": "A", "status": "COMPLETED", "result": "r"},
        )
        while disp.subscriber.get_message() is not None:
            pass  # steal B's promotion announce: frontier-only route
        disp.tick()
        assert disp.arrays.inflight_owner("B") is not None
        disp._handle(
            b"w0",
            m.RESULT,
            {"task_id": "B", "status": "COMPLETED", "result": "r"},
        )
        # B's result must walk the promotion plane even though B never
        # passed QUEUED intake — C promotes and dispatches
        assert store.get_status("C") == QUEUED
        while disp.subscriber.get_message() is not None:
            pass  # steal C's announce too
        disp.tick()
        assert disp.arrays.inflight_owner("C") is not None
        assert disp.n_frontier_dispatches == 2
    finally:
        disp.close()


def test_frontier_blocks_unready_children():
    """A child whose parent is still in flight occupies a frontier row
    but the in-tick mask keeps it out of placement entirely."""
    from tpu_faas.worker import messages as m

    disp = _make_tpu_dispatcher("memory://", recover_queued=False)
    try:
        store = disp.store
        disp._handle(b"w0", m.REGISTER, {"num_processes": 4})
        store.create_tasks(
            [
                (
                    "child",
                    "f",
                    "p",
                    {FIELD_DEPS: "parent", FIELD_PENDING_DEPS: "1"},
                )
            ],
            status=TaskStatus.WAITING,
        )
        store.create_tasks([("parent", "slow", "p", {})])
        for _ in range(3):
            disp.tick()
        assert "child" in disp.graph.waiting
        assert disp.arrays.inflight_owner("child") is None
        assert store.get_status("child") == WAITING
        assert disp.n_frontier_dispatches == 0
    finally:
        disp.close()


def test_tpu_push_graph_diamond_e2e():
    """Acceptance: a 1 -> N -> 1 diamond submitted via /execute_graph
    completes end to end on the tpu-push path with children dispatched
    only after parents finish — race-monitored, so any WAITING node
    reaching a worker (WAITING -> RUNNING) or double write would fail."""
    from tests.test_workers_e2e import _spawn_worker
    from tpu_faas.store.racecheck import RaceCheckStore, RaceMonitor

    monitor = RaceMonitor()
    store_handle = start_store_thread()
    gw_handle = start_gateway_thread(
        RaceCheckStore(
            make_store(store_handle.url), monitor, actor="gateway"
        )
    )
    disp = _make_tpu_dispatcher(
        store_handle.url,
        store=RaceCheckStore(
            make_store(store_handle.url), monitor, actor="dispatcher"
        ),
    )
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
        for _ in range(2)
    ]
    client = FaaSClient(gw_handle.url)
    try:
        g = client.graph()
        root = g.call(arithmetic, 500)
        mids = [g.call(arithmetic, 700, after=[root]) for _ in range(4)]
        sink = g.call(arithmetic, 900, after=mids)
        g.submit()
        assert sink.result(timeout=120.0) == arithmetic(900)
        assert root.result(timeout=10.0) == arithmetic(500)
        for mid in mids:
            assert mid.result(timeout=30.0) == arithmetic(700)
        store = make_store(store_handle.url)
        try:
            t_root = float(store.hget(root.task_id, FIELD_FINISHED_AT))
            t_mids = [
                float(store.hget(m_.task_id, FIELD_FINISHED_AT))
                for m_ in mids
            ]
            t_sink = float(store.hget(sink.task_id, FIELD_FINISHED_AT))
        finally:
            store.close()
        assert t_root <= min(t_mids) and max(t_mids) <= t_sink
        monitor.assert_clean()
        assert monitor.unfinished() == []
    finally:
        for w in workers:
            w.kill()
            w.wait()
        disp.stop()
        t.join(timeout=10)
        gw_handle.stop()
        store_handle.stop()


def test_graph_chaos_worker_kill_mid_diamond():
    """Chaos leg: SIGKILL a worker while the diamond's middle layer runs.
    With retries available the reclaimed middle tasks re-dispatch and the
    sink still completes; the run stays race-clean (re-dispatch declared,
    no WAITING node ever dispatched), and after the result-TTL sweeper
    runs no WAITING record remains in the store."""
    from tests.test_workers_e2e import _spawn_worker
    from tpu_faas.gateway.app import _sweep_expired_results
    from tpu_faas.store.racecheck import RaceCheckStore, RaceMonitor

    monitor = RaceMonitor()
    store_handle = start_store_thread()
    gw_handle = start_gateway_thread(
        RaceCheckStore(
            make_store(store_handle.url), monitor, actor="gateway"
        )
    )
    disp = _make_tpu_dispatcher(
        store_handle.url,
        time_to_expire=1.5,
        store=RaceCheckStore(
            make_store(store_handle.url), monitor, actor="dispatcher"
        ),
    )
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
        for _ in range(2)
    ]
    client = FaaSClient(gw_handle.url)
    try:
        g = client.graph()
        root = g.call(sleep_task, 0.2)
        mids = [g.call(sleep_task, 1.2, after=[root]) for _ in range(4)]
        sink = g.call(sleep_task, 0.1, after=mids)
        g.submit()
        # wait for the middle layer to be in flight, then kill a worker
        assert root.result(timeout=60.0) == 0.2
        time.sleep(0.6)
        workers[0].send_signal(signal.SIGKILL)
        workers[0].wait()
        assert sink.result(timeout=120.0) == 0.1
        monitor.assert_clean()
        assert monitor.unfinished() == []
        # the sweeper must leave no orphaned WAITING node behind
        store = make_store(store_handle.url)
        try:
            _sweep_expired_results(store, ttl=3600.0)
            statuses = store.hget_many(store.keys(), "status")
            assert WAITING not in statuses
        finally:
            store.close()
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.wait()
        disp.stop()
        t.join(timeout=10)
        gw_handle.stop()
        store_handle.stop()


def test_graph_poison_chaos_failed_parent_never_dispatches_frontier():
    """Chaos leg 2: the middle layer FAILS (poison path, no retries
    involved) — the sink is transitively poisoned without dispatching,
    the monitor stays clean, and no WAITING record survives the sweep."""
    from tests.test_workers_e2e import _spawn_worker
    from tpu_faas.gateway.app import _sweep_expired_results
    from tpu_faas.store.racecheck import RaceCheckStore, RaceMonitor

    monitor = RaceMonitor()
    store_handle = start_store_thread()
    gw_handle = start_gateway_thread(
        RaceCheckStore(
            make_store(store_handle.url), monitor, actor="gateway"
        )
    )
    disp = _make_tpu_dispatcher(
        store_handle.url,
        store=RaceCheckStore(
            make_store(store_handle.url), monitor, actor="dispatcher"
        ),
    )
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    worker = _spawn_worker(
        "push_worker", 2, url, "--hb", "--hb-period", "0.3"
    )
    client = FaaSClient(gw_handle.url)
    try:
        g = client.graph()
        root = g.call(arithmetic, 100)
        bad = g.call(failing_task, "mid-diamond", after=[root])
        ok = g.call(arithmetic, 100, after=[root])
        sink = g.call(arithmetic, 100, after=[bad, ok])
        g.submit()
        with pytest.raises(TaskDependencyError) as ei:
            sink.result(timeout=90.0)
        assert ei.value.parent_id == bad.task_id
        assert ok.result(timeout=30.0) == arithmetic(100)
        monitor.assert_clean()
        assert monitor.unfinished() == []
        store = make_store(store_handle.url)
        try:
            _sweep_expired_results(store, ttl=3600.0)
            statuses = store.hget_many(store.keys(), "status")
            assert WAITING not in statuses
        finally:
            store.close()
    finally:
        if worker.poll() is None:
            worker.kill()
            worker.wait()
        disp.stop()
        t.join(timeout=10)
        gw_handle.stop()
        store_handle.stop()
