"""TpuPushDispatcher integration: unmodified push workers, device-tick
scheduling, crash recovery, and stranded-task recovery on startup."""

from __future__ import annotations

import signal
import threading
import time

from tpu_faas.client import FaaSClient
from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store.launch import make_store, start_store_thread
from tpu_faas.workloads import sleep_task
from tests.test_workers_e2e import _spawn_worker, service_test


def _make_dispatcher(store_url, **kw):
    defaults = dict(
        ip="127.0.0.1",
        port=0,
        max_workers=64,
        max_pending=256,
        max_inflight=512,
        tick_period=0.01,
    )
    defaults.update(kw)
    if "store" not in defaults:  # an explicit store= must not leak a default
        defaults["store"] = make_store(store_url)
    return TpuPushDispatcher(**defaults)


def test_tpu_push_end_to_end():
    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    disp = _make_dispatcher(store_handle.url)
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
        for _ in range(2)
    ]
    try:
        service_test(FaaSClient(gw.url), n_tasks=20)
        assert disp.n_dispatched >= 20
        stats = disp.tracer.summary().get("device_tick", {})
        assert stats.get("count", 0) > 0
    finally:
        for w in workers:
            w.kill()
            w.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def test_tpu_push_worker_crash_redispatch():
    """Device-computed purge + redistribution: SIGKILL a worker holding
    tasks; everything still completes on the survivor — and the whole run is
    race-clean under the protocol monitor (store/racecheck.py): the declared
    re-dispatch is not a double-dispatch, and no zombie result overwrites a
    terminal record."""
    from tpu_faas.store.racecheck import RaceCheckStore, RaceMonitor

    monitor = RaceMonitor()
    store_handle = start_store_thread()
    gw = start_gateway_thread(
        RaceCheckStore(make_store(store_handle.url), monitor, actor="gateway")
    )
    disp = _make_dispatcher(
        store_handle.url,
        time_to_expire=1.5,
        store=RaceCheckStore(
            make_store(store_handle.url), monitor, actor="dispatcher"
        ),
    )
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
        for _ in range(2)
    ]
    client = FaaSClient(gw.url)
    try:
        fid = client.register(sleep_task)
        handles = [client.submit(fid, 1.0) for _ in range(8)]
        time.sleep(0.8)
        workers[0].send_signal(signal.SIGKILL)
        workers[0].wait()
        for h in handles:
            assert h.result(timeout=60.0) == 1.0
        monitor.assert_clean()
        assert monitor.unfinished() == []
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def test_tpu_push_recovers_stranded_queued_tasks():
    """Tasks submitted while NO dispatcher is running are stranded by
    fire-and-forget pub/sub; a fresh TpuPushDispatcher adopts them from the
    store on startup (the reference cannot — SURVEY §5.4)."""
    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    client = FaaSClient(gw.url)
    fid = client.register(sleep_task)
    orphan = client.submit(fid, 0.1)  # announced into the void
    time.sleep(0.2)
    assert orphan.status() == "QUEUED"

    disp = _make_dispatcher(store_handle.url)
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    worker = _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
    try:
        assert orphan.result(timeout=60.0) == 0.1
    finally:
        worker.kill()
        worker.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def test_tick_overflow_does_not_crash():
    """Pending queue beyond max_pending (e.g. purge re-queued into a full
    queue) must defer, not crash the tick with a ValueError."""
    from tpu_faas.dispatch.base import PendingTask
    from tpu_faas.store import MemoryStore

    store = MemoryStore()
    disp = TpuPushDispatcher(
        ip="127.0.0.1", port=0, store=store,
        max_workers=4, max_pending=8, max_inflight=16, recover_queued=False,
    )
    try:
        for i in range(20):  # 2.5x max_pending
            disp.pending.append(PendingTask(f"t{i}", "F", "P"))
        sent = disp.tick()  # no workers -> nothing sent, nothing lost
        assert sent == 0
        assert len(disp.pending) == 20
        # ticking repeatedly stays stable
        disp.tick()
        assert len(disp.pending) == 20
    finally:
        disp.socket.close(linger=0)


def test_tpu_push_midrun_rescan_adopts_stranded_task():
    """A task whose hash exists but whose announce was lost WHILE the
    dispatcher is already serving (store restart eats the PUBLISH — the
    client deliberately never replays it) is adopted by the periodic
    stranded rescan, without a dispatcher restart."""
    from tpu_faas.core.executor import pack_params
    from tpu_faas.core.serialize import serialize

    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    disp = _make_dispatcher(store_handle.url, rescan_period=0.3)
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    worker = _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
    client = FaaSClient(gw.url)
    raw = make_store(store_handle.url)
    try:
        # healthy path first, proving the dispatcher is live
        fid = client.register(sleep_task)
        assert client.submit(fid, 0.05).result(timeout=60.0) == 0.05
        # now a task hash written with NO announce (the lost-PUBLISH shape)
        raw.hset(
            "orphan-midrun",
            {
                "status": "QUEUED",
                "fn_payload": serialize(sleep_task),
                "param_payload": pack_params(0.05),
                "result": "None",
            },
        )
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            status, _result = raw.get_result("orphan-midrun")
            if status == "COMPLETED":
                break
            time.sleep(0.1)
        assert status == "COMPLETED"
    finally:
        raw.close()
        worker.kill()
        worker.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def test_tpu_push_survives_store_outage_and_defers_results(tmp_path):
    """Kill the store WHILE a task is running; the dispatcher must degrade
    (not crash — a store-outage ConnectionError used to propagate out of
    start()), buffer the worker's result, and replay it once the store is
    back on the same port."""
    snap = str(tmp_path / "outage.snap")
    h1 = start_store_thread(snapshot_path=snap)
    port = h1.port
    gw = start_gateway_thread(make_store(h1.url))
    disp = _make_dispatcher(h1.url, rescan_period=0.5)
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    worker = _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
    client = FaaSClient(gw.url)
    try:
        fid = client.register(sleep_task)
        assert client.submit(fid, 0.05).result(timeout=60.0) == 0.05

        slow = client.submit(fid, 2.0)
        deadline = time.monotonic() + 10
        while slow.status() != "RUNNING" and time.monotonic() < deadline:
            time.sleep(0.05)
        assert slow.status() == "RUNNING"

        h1.stop()  # store dies mid-task (stop() checkpoints to snap)
        time.sleep(3.0)  # worker finishes during the outage; result deferred
        assert t.is_alive(), "dispatcher crashed during store outage"

        h2 = start_store_thread(port=port, snapshot_path=snap)
        try:
            assert slow.result(timeout=30.0) == 2.0  # deferred write replayed
            # and the stack still serves new work
            assert client.submit(fid, 0.05).result(timeout=30.0) == 0.05
        finally:
            h2.stop()
    finally:
        worker.kill()
        worker.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()


def test_stats_endpoint_serves_dispatcher_state():
    from tpu_faas.store import MemoryStore
    import requests as rq

    disp = TpuPushDispatcher(
        ip="127.0.0.1", port=0, store=MemoryStore(),
        max_workers=4, max_pending=8, max_inflight=16, recover_queued=False,
    )
    server = disp.serve_stats(port=0)
    try:
        port = server.server_address[1]
        assert rq.get(f"http://127.0.0.1:{port}/healthz").json() == {"ok": True}
        s = rq.get(f"http://127.0.0.1:{port}/stats").json()
        assert s["pending"] == 0 and s["workers_registered"] == 0
        assert s["store_down"] is False
        assert rq.get(f"http://127.0.0.1:{port}/other").status_code == 404
    finally:
        disp.stop()  # shuts down + closes the stats server's socket too
        disp.socket.close(linger=0)


def test_tpu_push_scale_16_workers_500_tasks():
    """Scale shake-out on the real socket fabric: 16 worker processes x 2
    procs, 500 tasks submitted in batches, every result verified. Catches
    what tiny-fleet tests cannot: LRU/placement fairness across a wider
    fleet, announce-bus throughput, and batch intake under sustained load."""
    from tpu_faas.workloads import arithmetic

    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    disp = _make_dispatcher(store_handle.url, max_workers=64, max_pending=1024)
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.5")
        for _ in range(16)
    ]
    client = FaaSClient(gw.url)
    try:
        # wait for the WHOLE fleet to register before submitting: 16 fresh
        # interpreters (each warming a 2-child forkserver pool before its
        # REGISTER) start at very different speeds on a loaded box, and
        # near-instant tasks would otherwise drain before stragglers join
        deadline = time.monotonic() + 180
        while (
            len(disp.arrays.worker_ids) < 16 and time.monotonic() < deadline
        ):
            time.sleep(0.1)
        assert len(disp.arrays.worker_ids) == 16
        fid = client.register(arithmetic)
        handles = client.submit_many(
            fid, [((100 + i,), {}) for i in range(500)]
        )
        results = [h.result(timeout=180.0) for h in handles]
        assert results == [arithmetic(100 + i) for i in range(500)]
        assert disp.n_results >= 500
        assert disp.n_purged == 0  # healthy fleet: nobody falsely purged
    finally:
        for w in workers:
            w.kill()
            w.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def test_tpu_push_auction_placement_e2e():
    """The --placement auction kernel serving live traffic: unmodified
    workers, every result correct."""
    from tpu_faas.workloads import arithmetic

    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    disp = _make_dispatcher(store_handle.url, placement="auction")
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
        for _ in range(2)
    ]
    client = FaaSClient(gw.url)
    try:
        fid = client.register(arithmetic)
        handles = client.submit_many(fid, [((50 + i,), {}) for i in range(10)])
        assert [h.result(timeout=120) for h in handles] == [
            arithmetic(50 + i) for i in range(10)
        ]
    finally:
        for w in workers:
            w.kill()
            w.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def test_tpu_push_mesh_dispatcher_e2e():
    """Multi-chip as a product, not a kernel demo: a dispatcher whose
    pending-task axis is sharded over the full 8-device mesh (--mesh 8)
    serves unmodified push workers end to end — real sockets, real store,
    every result correct (VERDICT r1 item 2)."""
    from tpu_faas.workloads import arithmetic

    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    disp = _make_dispatcher(store_handle.url, mesh_devices=8)
    assert disp.arrays.mesh is not None and disp.arrays.mesh.size == 8
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
        for _ in range(2)
    ]
    client = FaaSClient(gw.url)
    try:
        fid = client.register(arithmetic)
        handles = client.submit_many(fid, [((30 + i,), {}) for i in range(24)])
        assert [h.result(timeout=120) for h in handles] == [
            arithmetic(30 + i) for i in range(24)
        ]
        assert disp.n_dispatched >= 24
    finally:
        for w in workers:
            w.kill()
            w.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def test_rescan_uses_live_index_and_gcs_stale_entries():
    """Rescan cost is O(live tasks), not O(history): indexed passes read
    tasks:index (and GC entries whose record finished or vanished); every
    10th pass is a full KEYS scan that also catches foreign-producer tasks
    written without the index (the raw reference contract)."""
    from tpu_faas.core.task import TaskStatus
    from tpu_faas.store.base import LIVE_INDEX_KEY
    from tpu_faas.store.memory import MemoryStore

    store = MemoryStore()
    disp = TpuPushDispatcher(
        ip="127.0.0.1", port=0, store=store, recover_queued=False
    )
    try:
        disp._rescan_count = 1  # force the next pass to be indexed
        store.create_task("idx-task", "F", "P")
        while disp.subscriber.get_message() is not None:
            pass  # drop the announce: the task is now stranded
        # a create in flight (index written, record not yet): must NOT be
        # GC'd — deleting it would hide the task from indexed rescans
        store.hset(LIVE_INDEX_KEY, {"mid-create": "1"})
        # a terminal record whose finish-path HDEL was lost: must be GC'd
        store.create_task("finished", "F", "P")
        store.hset("finished", {"status": str(TaskStatus.COMPLETED)})
        while disp.subscriber.get_message() is not None:
            pass
        # foreign producer: task record only, no index entry
        store.hset(
            "foreign",
            {
                "status": str(TaskStatus.QUEUED),
                "fn_payload": "F",
                "param_payload": "P",
                "result": "None",
            },
        )
        disp._recover_stranded()
        ids = {t.task_id for t in disp.pending}
        assert "idx-task" in ids  # found via the index
        assert "foreign" not in ids  # invisible to an indexed pass
        index = set(store.hgetall(LIVE_INDEX_KEY))
        assert "finished" not in index  # terminal leftover: GC'd
        assert "mid-create" in index  # status-None entry: kept

        disp._rescan_count = 10  # next pass: full-scan fallback
        disp._recover_stranded()
        ids = {t.task_id for t in disp.pending}
        assert "foreign" in ids  # the fallback catches it
    finally:
        disp.socket.close(linger=0)
