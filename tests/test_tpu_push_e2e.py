"""TpuPushDispatcher integration: unmodified push workers, device-tick
scheduling, crash recovery, and stranded-task recovery on startup."""

from __future__ import annotations

import signal
import threading
import time

from tpu_faas.client import FaaSClient
from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store.launch import make_store, start_store_thread
from tpu_faas.workloads import sleep_task
from tests.test_workers_e2e import _spawn_worker, service_test


def _make_dispatcher(store_url, **kw):
    defaults = dict(
        ip="127.0.0.1",
        port=0,
        store=make_store(store_url),
        max_workers=64,
        max_pending=256,
        max_inflight=512,
        tick_period=0.01,
    )
    defaults.update(kw)
    return TpuPushDispatcher(**defaults)


def test_tpu_push_end_to_end():
    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    disp = _make_dispatcher(store_handle.url)
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
        for _ in range(2)
    ]
    try:
        service_test(FaaSClient(gw.url), n_tasks=20)
        assert disp.n_dispatched >= 20
        stats = disp.tracer.summary().get("device_tick", {})
        assert stats.get("count", 0) > 0
    finally:
        for w in workers:
            w.kill()
            w.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def test_tpu_push_worker_crash_redispatch():
    """Device-computed purge + redistribution: SIGKILL a worker holding
    tasks; everything still completes on the survivor."""
    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    disp = _make_dispatcher(store_handle.url, time_to_expire=1.5)
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
        for _ in range(2)
    ]
    client = FaaSClient(gw.url)
    try:
        fid = client.register(sleep_task)
        handles = [client.submit(fid, 1.0) for _ in range(8)]
        time.sleep(0.8)
        workers[0].send_signal(signal.SIGKILL)
        workers[0].wait()
        for h in handles:
            assert h.result(timeout=60.0) == 1.0
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def test_tpu_push_recovers_stranded_queued_tasks():
    """Tasks submitted while NO dispatcher is running are stranded by
    fire-and-forget pub/sub; a fresh TpuPushDispatcher adopts them from the
    store on startup (the reference cannot — SURVEY §5.4)."""
    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    client = FaaSClient(gw.url)
    fid = client.register(sleep_task)
    orphan = client.submit(fid, 0.1)  # announced into the void
    time.sleep(0.2)
    assert orphan.status() == "QUEUED"

    disp = _make_dispatcher(store_handle.url)
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    worker = _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
    try:
        assert orphan.result(timeout=60.0) == 0.1
    finally:
        worker.kill()
        worker.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()


def test_tick_overflow_does_not_crash():
    """Pending queue beyond max_pending (e.g. purge re-queued into a full
    queue) must defer, not crash the tick with a ValueError."""
    from tpu_faas.dispatch.base import PendingTask
    from tpu_faas.store import MemoryStore

    store = MemoryStore()
    disp = TpuPushDispatcher(
        ip="127.0.0.1", port=0, store=store,
        max_workers=4, max_pending=8, max_inflight=16, recover_queued=False,
    )
    try:
        for i in range(20):  # 2.5x max_pending
            disp.pending.append(PendingTask(f"t{i}", "F", "P"))
        sent = disp.tick()  # no workers -> nothing sent, nothing lost
        assert sent == 0
        assert len(disp.pending) == 20
        # ticking repeatedly stays stable
        disp.tick()
        assert len(disp.pending) == 20
    finally:
        disp.socket.close(linger=0)
