"""Multi-host integration: the sharded scheduler tick over a REAL
two-process global mesh.

The rest of the suite shards over 8 virtual devices inside ONE process;
this test is the actual multi-host path — two OS processes join one JAX
runtime via ``jax.distributed`` (gloo collectives over a CPU "pod", 4 local
devices each), and the identical fused tick — Sinkhorn's distributed
logsumexp included — runs over the global 8-device mesh. Both ranks must
agree bit-for-bit on the placement. On Cloud TPU the same code path forms
the mesh across pod-slice hosts (parallel/distributed.py).
"""

from __future__ import annotations

import os
import re
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def cpu_pod_supported() -> bool:
    """True when THIS JAX can simulate a multi-process CPU pod: the
    children need the ``jax_num_cpu_devices`` config option
    (parallel/distributed.py initialize_multihost) and the sharded tick
    needs the ``jax.shard_map`` alias. Probed in the parent — the children
    run the same installation."""
    import jax

    return hasattr(jax.config, "jax_num_cpu_devices") and hasattr(
        jax, "shard_map"
    )


def test_two_process_global_mesh_sharded_tick():
    import pytest

    if not cpu_pod_supported():
        pytest.skip("this JAX cannot simulate a multi-process CPU pod")
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()

    existing = os.environ.get("PYTHONPATH", "")
    env = dict(
        os.environ, PYTHONPATH=f"{REPO}:{existing}" if existing else REPO
    )
    # children must form their own CPU pod: scrub the parent suite's
    # virtual-device flags so they don't fight initialize_multihost's config
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, "tests/_multihost_child.py", str(rank), str(port)],
            env=env,
            cwd=REPO,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for rank in (0, 1)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=240)
        outs.append(out)
        assert p.returncode == 0, out[-2000:]
    lines = [
        re.search(r"MULTIHOST rank=\d (.*)", out).group(1) for out in outs
    ]
    # both ranks computed the identical global placement
    assert lines[0] == lines[1], lines
    assert "placed=" in lines[0]

    # priority + auction legs (round 4): ranks agree with each other...
    prio_fps = [
        int(re.search(r"PRIO rank=\d fingerprint=(-?\d+)", out).group(1))
        for out in outs
    ]
    auction_fps = [
        int(re.search(r"AUCTION rank=\d fingerprint=(-?\d+)", out).group(1))
        for out in outs
    ]
    assert prio_fps[0] == prio_fps[1]
    assert auction_fps[0] == auction_fps[1]
    # ...and with the SINGLE-HOST tick on the identical inputs (rebuilt
    # from the child's seeds): priority admission order and the auction's
    # assignment do not change when the problem spans processes
    import jax.numpy as jnp
    import numpy as np

    from tpu_faas.sched.state import scheduler_tick

    T, W, I = 64, 16, 32
    rng = np.random.default_rng(5)
    task_size = jnp.asarray(rng.uniform(0.1, 5.0, T).astype(np.float32))
    task_valid = jnp.asarray(rng.random(T) > 0.2)
    speed = jnp.asarray(rng.uniform(0.5, 4.0, W).astype(np.float32))
    free = jnp.asarray(rng.integers(0, 4, W).astype(np.int32))
    hb_age = jnp.asarray(rng.uniform(0.0, 15.0, W).astype(np.float32))
    inflight = jnp.asarray(rng.integers(-1, W, I).astype(np.int32))
    ones = jnp.ones(W, dtype=bool)
    prio = jnp.asarray(
        np.random.default_rng(6).integers(-2, 3, T).astype(np.int32)
    )
    out_p = scheduler_tick(
        task_size, task_valid, speed, free, ones, hb_age, ones, inflight,
        jnp.float32(10.0), max_slots=4, placement="rank",
        task_priority=prio,
    )
    ap = np.asarray(out_p.assignment)
    assert int((ap * np.arange(1, T + 1)).sum()) == prio_fps[0]
    out_a = scheduler_tick(
        task_size, task_valid, speed, free, ones, hb_age, ones, inflight,
        jnp.float32(10.0), max_slots=4, placement="auction",
    )
    aa = np.asarray(out_a.assignment)
    assert int((aa * np.arange(1, T + 1)).sum()) == auction_fps[0]

    # warm-auction leg: the MultihostTick protocol's per-process price
    # carry (tick 2 warm-starts from tick 1's prices) stays in lockstep
    # across ranks and matches the single-host product path
    warm_fps = [
        int(
            re.search(r"WARMAUCTION rank=\d fingerprint=(-?\d+)", out).group(1)
        )
        for out in outs
    ]
    assert warm_fps[0] == warm_fps[1]
    from tpu_faas.sched.state import SchedulerArrays

    arr = SchedulerArrays(
        max_workers=8, max_pending=32, max_slots=2, placement="auction",
        clock=lambda: 100.0,
    )
    rng3 = np.random.default_rng(8)
    sizes_w = rng3.uniform(0.5, 5.0, 20).astype(np.float32)
    speed_w = rng3.uniform(0.5, 4.0, 8).astype(np.float32)
    for i in range(8):
        arr.register(f"w{i}".encode(), 2, speed=float(speed_w[i]))
    arr.tick(sizes_w)
    out2 = arr.tick(sizes_w * 1.01)
    a2 = np.asarray(out2.assignment)
    assert int((a2 * np.arange(1, len(a2) + 1)).sum()) == warm_fps[0]


def test_multihost_tick_host_side_redispatch_matches_kernel():
    """lead_tick computes redispatch HOST-side (the in-flight table no
    longer rides the broadcast); it must stay bit-identical to the device
    kernel's formula on the same inputs. Runs single-process (a 1-process
    'fleet' degenerates broadcast/allgather to identity), over the
    suite's 8 virtual CPU devices."""
    import numpy as np
    import jax.numpy as jnp

    from tpu_faas.parallel.multihost_tick import MultihostTick
    from tpu_faas.sched.state import scheduler_tick

    T, W, I = 64, 16, 48
    rng = np.random.default_rng(9)
    mt = MultihostTick(max_pending=T, max_workers=W, max_slots=4)
    sizes = rng.uniform(0.1, 5.0, 40).astype(np.float32)
    speed = rng.uniform(0.5, 4.0, W).astype(np.float32)
    free = rng.integers(0, 4, W).astype(np.int32)
    active = np.ones(W, dtype=bool)
    hb_age = rng.uniform(0.0, 15.0, W).astype(np.float32)  # some dead
    inflight = rng.integers(-1, W, I).astype(np.int32)

    out = mt.lead_tick(sizes, speed, free, active, hb_age, inflight, 10.0)

    padded = np.zeros(mt.T, dtype=np.float32)
    padded[:40] = sizes
    ref = scheduler_tick(
        jnp.asarray(padded),
        jnp.arange(mt.T) < 40,
        jnp.asarray(speed),
        jnp.asarray(free),
        jnp.asarray(active),
        jnp.asarray(hb_age),
        jnp.zeros(W, dtype=bool),  # prev_live: first tick on both sides
        jnp.asarray(inflight),
        jnp.float32(10.0),
        max_slots=4,
    )
    np.testing.assert_array_equal(out.live, np.asarray(ref.live))
    np.testing.assert_array_equal(
        out.redispatch, np.asarray(ref.redispatch)
    )
    assert out.redispatch.any()  # the case is non-trivial


def test_lead_mid_tick_failure_marks_fleet_broken(monkeypatch):
    """A lead failure AFTER the broadcast leaves followers inside that
    tick's collectives: the tick must mark the fleet broken, later ticks
    must refuse immediately, and lead_stop must NOT issue the (mismatched)
    stop broadcast that would hang the lead's own shutdown."""
    import numpy as np
    import pytest

    from tpu_faas.parallel.multihost_tick import MultihostTick

    mt = MultihostTick(max_pending=32, max_workers=8, max_slots=2)
    broadcasts = []
    monkeypatch.setattr(
        mt, "_broadcast", lambda buf: broadcasts.append(1) or buf
    )

    def boom(buf):
        raise RuntimeError("kernel error mid-tick")

    monkeypatch.setattr(mt, "_run", boom)
    args = (
        np.ones(4, dtype=np.float32),
        np.ones(8, dtype=np.float32),
        np.ones(8, dtype=np.int32),
        np.ones(8, dtype=bool),
        np.zeros(8, dtype=np.float32),
        np.full(4, -1, dtype=np.int32),
        10.0,
    )
    with pytest.raises(RuntimeError, match="kernel error"):
        mt.lead_tick(*args)
    assert mt._broken
    n_broadcasts = len(broadcasts)
    # subsequent ticks refuse before broadcasting anything
    with pytest.raises(RuntimeError, match="restarted"):
        mt.lead_tick(*args)
    assert len(broadcasts) == n_broadcasts
    # and the stop path skips its broadcast instead of hanging
    mt.lead_stop()
    assert len(broadcasts) == n_broadcasts
