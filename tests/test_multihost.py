"""Multi-host integration: the sharded scheduler tick over a REAL
two-process global mesh.

The rest of the suite shards over 8 virtual devices inside ONE process;
this test is the actual multi-host path — two OS processes join one JAX
runtime via ``jax.distributed`` (gloo collectives over a CPU "pod", 4 local
devices each), and the identical fused tick — Sinkhorn's distributed
logsumexp included — runs over the global 8-device mesh. Both ranks must
agree bit-for-bit on the placement. On Cloud TPU the same code path forms
the mesh across pod-slice hosts (parallel/distributed.py).
"""

from __future__ import annotations

import os
import re
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_two_process_global_mesh_sharded_tick():
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()

    existing = os.environ.get("PYTHONPATH", "")
    env = dict(
        os.environ, PYTHONPATH=f"{REPO}:{existing}" if existing else REPO
    )
    # children must form their own CPU pod: scrub the parent suite's
    # virtual-device flags so they don't fight initialize_multihost's config
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, "tests/_multihost_child.py", str(rank), str(port)],
            env=env,
            cwd=REPO,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for rank in (0, 1)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=240)
        outs.append(out)
        assert p.returncode == 0, out[-2000:]
    lines = [
        re.search(r"MULTIHOST rank=\d (.*)", out).group(1) for out in outs
    ]
    # both ranks computed the identical global placement
    assert lines[0] == lines[1], lines
    assert "placed=" in lines[0]
