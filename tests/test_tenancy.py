"""Tenancy plane (tpu_faas/tenancy): config, in-tick fairness kernels,
resident XLA-vs-fused parity with tenant state, dispatcher wiring,
gateway/SDK tenant propagation, per-tenant observability, hot reload —
plus the worker-bookkeeping churn soak (VERDICT item 4 satellite).
"""

from __future__ import annotations

import threading
import time

import numpy as np
import jax.numpy as jnp
import pytest
import requests

from tpu_faas.core.serialize import serialize
from tpu_faas.core.task import FIELD_TENANT
from tpu_faas.sched.state import scheduler_tick_impl
from tpu_faas.store.base import TENANT_CONF_KEY
from tpu_faas.store.memory import MemoryStore
from tpu_faas.tenancy import (
    DEFAULT_TENANT,
    TenantTable,
    parse_caps,
    parse_shares,
    valid_tenant,
)
from tpu_faas.tenancy.config import decode_conf, encode_conf
from tpu_faas.tenancy.fairshare import (
    tenant_deficit_update,
    tenant_fair_admission,
)


# -- config / table ---------------------------------------------------------


def test_parse_shares_and_caps():
    assert parse_shares("a=3,b=1.5") == {"a": 3.0, "b": 1.5}
    assert parse_shares("") == {}
    assert parse_caps("a=100, b=2") == {"a": 100, "b": 2}
    for bad in ("a", "a=x", "a=-1", "a=0", "a=inf", "a=1,a=2", "bad name=1"):
        with pytest.raises(ValueError):
            parse_shares(bad)


def test_valid_tenant():
    assert valid_tenant("team-a") and valid_tenant("A.b_c-9")
    for bad in ("", "-lead", "has space", "x" * 65, "colon:bad", None, 7):
        assert not valid_tenant(bad)


def test_conf_roundtrip():
    v = encode_conf("a=3,b=1", now=123.5)
    assert decode_conf(v) == ("a=3,b=1", 123.5)
    assert decode_conf(None) is None
    assert decode_conf("garbled") is None


def test_tenant_table_rows_overflow_and_labels():
    t = TenantTable(shares={"a": 2.0}, caps={"b": 5}, max_tenants=3)
    assert t.row_for(None) == 0 and t.row_for(DEFAULT_TENANT) == 0
    ra, rb = t.row_for("a"), t.row_for("b")
    assert ra != 0 and rb != 0 and ra != rb
    assert t.row_for("a") == ra  # stable
    # table full: the next distinct name accounts to default, counted
    assert t.row_for("c") == 0
    assert t.overflowed == 1
    # label vocabulary is bounded by the CONFIGURED names
    assert t.label_for("a") == "a" and t.label_for("b") == "b"
    assert t.label_for("c") == "other"
    assert t.label_for(None) == DEFAULT_TENANT
    assert float(t.share[ra]) == 2.0 and int(t.cap[rb]) == 5
    st = t.stats()
    assert st["tenants"]["a"]["share"] == 2.0
    assert st["overflowed"] == 1


def test_parse_caps_rejects_fractional_values():
    """int() truncation would turn 'batch=0.5' into cap 0 = UNCAPPED —
    the inverse of the operator's tightest-possible ask."""
    for bad in ("a=0.5", "a=2.7"):
        with pytest.raises(ValueError):
            parse_caps(bad)
    assert parse_caps("a=2") == {"a": 2}


def test_table_overflow_never_retunes_default_row():
    """A configured tenant that doesn't fit the table must be SKIPPED,
    not written onto row 0 — cap[0]=N would hard-cap every header-less
    client. Configuring 'default' explicitly still works."""
    t = TenantTable(max_tenants=2)
    t.row_for("filler")  # table now full (default + filler)
    t.apply_specs("overflow-tenant=5", "overflow-tenant=3")
    assert float(t.share[0]) == 1.0  # default row untouched
    assert int(t.cap[0]) == 0
    assert t.label_for("overflow-tenant") == "other"  # not labelled either
    t2 = TenantTable(max_tenants=2)
    t2.apply_specs("default=4", "default=7")
    assert float(t2.share[0]) == 4.0 and int(t2.cap[0]) == 7


def test_apply_specs_is_all_or_nothing():
    """Valid shares + malformed caps in one retune must fail WHOLE: a
    half-applied reload would leave new shares silently live beside old
    caps while reporting 'no change'."""
    t = TenantTable(max_tenants=8)
    t.apply_specs("a=2", "a=5")
    with pytest.raises(ValueError):
        t.apply_specs("a=9", "a=bad")
    assert float(t.share[t.row_for("a")]) == 2.0  # shares NOT applied
    # and the store-driven reload path reports no change + keeps config
    store = MemoryStore()
    store.hset(
        TENANT_CONF_KEY,
        {"shares": encode_conf("a=9"), "caps": encode_conf("a=broken")},
    )
    assert t.maybe_reload(store) is False
    assert float(t.share[t.row_for("a")]) == 2.0


def test_tenant_table_apply_specs_change_detection():
    t = TenantTable(max_tenants=8)
    assert t.apply_specs("a=2", None) is True
    assert t.apply_specs("a=2", None) is False  # unchanged
    assert t.apply_specs("a=4", "a=9") is True
    assert float(t.share[t.row_for("a")]) == 4.0
    assert int(t.cap[t.row_for("a")]) == 9
    with pytest.raises(ValueError):
        t.apply_specs("broken==", None)


def test_tenant_table_hot_reload_via_store():
    store = MemoryStore()
    t = TenantTable(max_tenants=8)
    t.apply_specs("a=2", "")
    t.publish(store)
    # a second table (another dispatcher) picks the config up
    t2 = TenantTable(max_tenants=8)
    assert t2.maybe_reload(store) is True
    assert float(t2.share[t2.row_for("a")]) == 2.0
    assert t2.maybe_reload(store) is False  # unchanged
    # operator hot-updates the hash; both tables converge
    store.hset(TENANT_CONF_KEY, {"shares": encode_conf("a=7")})
    assert t.maybe_reload(store) is True and t2.maybe_reload(store) is True
    assert float(t.share[t.row_for("a")]) == 7.0
    # malformed published spec: ignored, last good config kept
    store.hset(TENANT_CONF_KEY, {"shares": encode_conf("a==broken")})
    assert t.maybe_reload(store) is False
    assert float(t.share[t.row_for("a")]) == 7.0


# -- the in-tick kernels ----------------------------------------------------


def _admit(valid, tenant, share, deficit=None, ahead=None, cap=None,
           prio=None, **kw):
    N = share.shape[0]
    z = lambda dt: jnp.zeros(N, dt)  # noqa: E731
    return tenant_fair_admission(
        jnp.asarray(valid), jnp.asarray(tenant, jnp.int32),
        None if prio is None else jnp.asarray(prio, jnp.int32),
        jnp.asarray(share, jnp.float32),
        z(jnp.float32) if deficit is None else jnp.asarray(deficit, jnp.float32),
        z(jnp.int32) if ahead is None else jnp.asarray(ahead, jnp.int32),
        z(jnp.int32) if cap is None else jnp.asarray(cap, jnp.int32),
        **kw,
    )


def test_weighted_interleave_tracks_shares():
    # alternating tenants 0/1, shares 3:1 -> any admitted prefix of the
    # fair order holds ~3 tenant-0 per tenant-1
    tenant = np.array([0, 1] * 16, np.int32)
    share = np.array([3.0, 1.0], np.float32)
    _elig, rank, _demand = _admit(np.ones(32, bool), tenant, share)
    order = np.asarray(tenant)[np.argsort(np.asarray(rank))]
    for k in (8, 16, 24):
        frac0 = (order[:k] == 0).mean()
        assert 0.6 <= frac0 <= 0.85, (k, order[:k])


def test_work_conservation_idle_tenant_spills():
    # tenant 1 has NO tasks: tenant 0 takes every admitted slot
    tenant = np.zeros(8, np.int32)
    share = np.array([1.0, 100.0], np.float32)  # huge idle share
    elig, rank, demand = _admit(np.ones(8, bool), tenant, share)
    assert np.asarray(elig).all()
    assert sorted(np.asarray(rank)[:8]) == list(range(8))
    assert list(np.asarray(demand)) == [True, False]


def test_fcfs_within_tenant_preserved():
    tenant = np.array([0, 0, 0, 0], np.int32)
    share = np.array([1.0], np.float32)
    _e, rank, _d = _admit(np.ones(4, bool), tenant, share)
    assert list(np.asarray(rank)) == [0, 1, 2, 3]


def test_inflight_cap_masks_surplus():
    tenant = np.array([0, 0, 0, 1, 1, 1], np.int32)
    share = np.array([1.0, 1.0], np.float32)
    elig, _r, demand = _admit(
        np.ones(6, bool), tenant, share,
        ahead=np.array([0, 2], np.int32), cap=np.array([0, 3], np.int32),
    )
    # tenant 1: cap 3, 2 already inflight -> only its FIRST pending row
    # stays eligible; tenant 0 uncapped
    assert list(np.asarray(elig)) == [True, True, True, True, False, False]
    assert list(np.asarray(demand)) == [True, True]


def test_priority_classes_dominate_fairness():
    tenant = np.array([0, 0, 1, 1], np.int32)
    share = np.array([100.0, 1.0], np.float32)
    prio = np.array([0, 0, 1, 1], np.int32)
    _e, rank, _d = _admit(np.ones(4, bool), tenant, share, prio=prio)
    order = list(np.argsort(np.asarray(rank)))
    assert order == [2, 3, 0, 1]  # the priority class first, shares within


def test_starvation_boost_rides_priority_lane():
    tenant = np.array([0, 0, 1, 1], np.int32)
    share = np.array([1.0, 1.0], np.float32)
    prio = np.array([1, 1, 0, 0], np.int32)
    # below threshold: tenant 0's priority class wins outright
    _e, rank, _d = _admit(
        np.ones(4, bool), tenant, share, prio=prio,
        deficit=np.array([0.0, 4.0], np.float32),
        starve_deficit=8.0, starve_boost=1,
    )
    assert list(np.argsort(np.asarray(rank)))[:2] == [0, 1]
    # past threshold: the starving tenant is boosted one class and its
    # huge deficit pulls its whole queue to the front of that class
    _e, rank, _d = _admit(
        np.ones(4, bool), tenant, share, prio=prio,
        deficit=np.array([0.0, 9.0], np.float32),
        starve_deficit=8.0, starve_boost=1,
    )
    assert list(np.argsort(np.asarray(rank)))[:2] == [2, 3]


def test_deficit_update_drr_semantics():
    tenant = np.array([0, 0, 1, 1], np.int32)
    share = jnp.asarray(np.array([1.0, 1.0], np.float32))
    demand = jnp.asarray(np.array([True, True]))
    # tenant 1 backlogged but got nothing: its deficit grows by its
    # entitlement (half of 2 placements); tenant 0 over-served, clamps at 0
    assignment = jnp.asarray(np.array([0, 1, -1, -1], np.int32))
    new = np.asarray(
        tenant_deficit_update(
            assignment, jnp.asarray(tenant, jnp.int32), demand, share,
            jnp.zeros(2, jnp.float32),
        )
    )
    assert new[0] == 0.0 and new[1] == pytest.approx(1.0)
    # a tenant with no demand RESETS (DRR: credit is for waiting work)
    new2 = np.asarray(
        tenant_deficit_update(
            assignment, jnp.asarray(tenant, jnp.int32),
            jnp.asarray(np.array([True, False])), share,
            jnp.asarray(np.array([0.0, 3.0], np.float32)),
        )
    )
    assert new2[1] == 0.0


def test_starved_tenant_recovers_through_tick_iterations():
    """End-to-end through scheduler_tick_impl: a priority-0 tenant starved
    by a priority-1 flood accumulates deficit tick over tick until the
    starvation guard boosts it into the admitted class."""
    T = 8
    tenant = jnp.asarray(np.array([0, 1] * 4, np.int32))
    prio = jnp.asarray(np.array([1, 0] * 4, np.int32))
    share = jnp.asarray(np.ones(2, np.float32))
    deficit = jnp.zeros(2, jnp.float32)
    ws = jnp.ones(2, jnp.float32)
    wa = jnp.ones(2, bool)
    hb = jnp.zeros(2, jnp.float32)
    pl = jnp.ones(2, bool)
    iw = jnp.full(4, -1, jnp.int32)
    placed_t1 = []
    for _ in range(6):
        out = scheduler_tick_impl(
            jnp.ones(T, jnp.float32), jnp.ones(T, bool), ws,
            jnp.asarray(np.array([1, 1], np.int32)), wa, hb, pl, iw,
            jnp.float32(10.0), max_slots=1, task_priority=prio,
            task_tenant=tenant, tenant_share=share, tenant_deficit=deficit,
            tenant_ahead=jnp.zeros(2, jnp.int32),
            tenant_cap=jnp.zeros(2, jnp.int32),
            starve_deficit=2.5, starve_boost=1,
        )
        a = np.asarray(out.assignment)
        placed_t1.append(int(((a >= 0) & (np.asarray(tenant) == 1)).sum()))
        deficit = out.tenant_deficit
    # starved at first (priority flood takes both slots), then the guard
    # engages and tenant 1 gets placements
    assert placed_t1[0] == 0
    assert any(n > 0 for n in placed_t1[2:]), placed_t1
    assert float(np.asarray(deficit)[0]) >= 0.0


def test_tick_without_tenancy_unchanged():
    """task_tenant=None must trace the pre-tenancy graph: identical
    assignment, no deficit output."""
    T = 6
    args = (
        jnp.asarray(np.arange(T, 0, -1), jnp.float32),
        jnp.ones(T, bool),
        jnp.ones(3, jnp.float32),
        jnp.asarray(np.array([2, 2, 2], np.int32)),
        jnp.ones(3, bool),
        jnp.zeros(3, jnp.float32),
        jnp.ones(3, bool),
        jnp.full(8, -1, jnp.int32),
        jnp.float32(10.0),
    )
    out = scheduler_tick_impl(*args, max_slots=2)
    assert out.tenant_deficit is None
    out2 = scheduler_tick_impl(
        *args, max_slots=2,
        task_tenant=jnp.zeros(T, jnp.int32),
        tenant_share=jnp.ones(1, jnp.float32),
        tenant_deficit=jnp.zeros(1, jnp.float32),
        tenant_ahead=jnp.zeros(1, jnp.int32),
        tenant_cap=jnp.zeros(1, jnp.int32),
    )
    # one tenant, no caps: fairness degenerates to FCFS — same placement
    assert np.array_equal(
        np.asarray(out.assignment), np.asarray(out2.assignment)
    )
    assert out2.tenant_deficit is not None


def test_cap_mask_applies_to_auction_placement():
    """Auction/sinkhorn get the hard eligibility mask even though the
    fair ORDERING is rank-only: a capped tenant's surplus never places."""
    T = 6
    tenant = jnp.asarray(np.array([0, 0, 0, 0, 1, 1], np.int32))
    out = scheduler_tick_impl(
        jnp.ones(T, jnp.float32), jnp.ones(T, bool),
        jnp.ones(2, jnp.float32), jnp.asarray(np.array([4, 4], np.int32)),
        jnp.ones(2, bool), jnp.zeros(2, jnp.float32), jnp.ones(2, bool),
        jnp.full(4, -1, jnp.int32), jnp.float32(10.0),
        max_slots=4, placement="auction",
        task_tenant=tenant,
        tenant_share=jnp.ones(2, jnp.float32),
        tenant_deficit=jnp.zeros(2, jnp.float32),
        tenant_ahead=jnp.zeros(2, jnp.int32),
        tenant_cap=jnp.asarray(np.array([2, 0], np.int32)),
    )
    a = np.asarray(out.assignment)
    t = np.asarray(tenant)
    assert ((a >= 0) & (t == 0)).sum() == 2  # capped at 2
    assert ((a >= 0) & (t == 1)).sum() == 2  # its whole backlog


# -- resident parity (XLA oracle vs fused Pallas kernel) --------------------


def _resident_script(backend):
    from tpu_faas.sched.resident import ResidentScheduler

    ten = TenantTable(shares={"a": 2.0, "b": 1.0}, caps={"b": 3},
                      max_tenants=4)
    clock = [100.0]
    r = ResidentScheduler(
        max_workers=8, max_pending=32, max_inflight=64, max_slots=2,
        time_to_expire=10.0, clock=lambda: clock[0], use_priority=True,
        tick_backend=backend, tenancy=ten,
    )
    for w in range(2):
        r.register(f"w{w}".encode(), 2)
    ra, rb = ten.row_for("a"), ten.row_for("b")
    log = []
    for i in range(4):
        r.pending_add(f"a{i}", 1.0, 0, ra)
        r.pending_add(f"b{i}", 1.0, 0, rb)
    for step in range(4):
        clock[0] += 0.1
        r.tick_resident()
        while True:
            res = r.resolve_next()
            if res is None:
                break
            for tid, row in sorted(res.placed):
                ten.note_dispatched(ra if tid.startswith("a") else rb)
                log.append((step, tid, row))
        if step == 1:
            # results arrive: capacity frees, inflight counts drop
            for w in range(2):
                r.release_slot(w)
                r.release_slot(w)
            ten.inflight[:] = 0
        # a mid-run hot reload flips the shares — values, not statics
        if step == 2:
            ten.apply_specs("a=1,b=5", None)
    return log, r.tenant_deficits()


@pytest.mark.parametrize("fused", ["fused_interpret"])
def test_resident_fused_parity_with_tenant_state(fused):
    """The PR-11 parity pin extended to tenancy: identical placement
    streams and deficit carries from the XLA oracle and the one-dispatch
    fused kernel, through caps, share hot-reload, and capacity churn."""
    from tpu_faas.sched.pallas_fused import fused_ok

    if not fused_ok():
        pytest.skip("pallas unavailable")
    log_x, def_x = _resident_script("xla")
    log_f, def_f = _resident_script(fused)
    assert log_x == log_f
    assert np.allclose(def_x, def_f)
    assert len(log_x) > 0


def test_resident_tenant_packet_roundtrip():
    """Arrival tenant rows survive the packet -> device -> readback loop:
    with a hard cap of 0 admitted... (cap=1 and ahead=1) the capped
    tenant's tasks stay device-pending while the other drains."""
    from tpu_faas.sched.resident import ResidentScheduler

    ten = TenantTable(shares={"a": 1.0, "b": 1.0}, caps={"b": 1},
                      max_tenants=4)
    r = ResidentScheduler(
        max_workers=4, max_pending=16, max_inflight=16, max_slots=4,
        time_to_expire=10.0, clock=lambda: 50.0, use_priority=True,
        tick_backend="xla", tenancy=ten,
    )
    r.register(b"w0", 4)
    ra, rb = ten.row_for("a"), ten.row_for("b")
    ten.inflight[rb] = 1  # b already at its cap
    for i in range(3):
        r.pending_add(f"a{i}", 1.0, 0, ra)
        r.pending_add(f"b{i}", 1.0, 0, rb)
    r.tick_resident()
    res = r.resolve_next()
    placed = sorted(tid for tid, _ in res.placed)
    assert placed == ["a0", "a1", "a2"]  # b fully masked by its cap
    assert res.n_pending == 3  # b's tasks still valid device-side


# -- dispatcher wiring ------------------------------------------------------


def _mk_disp(**kw):
    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher

    defaults = dict(
        ip="127.0.0.1", port=0, store=MemoryStore(), max_workers=16,
        max_pending=64, max_inflight=128, tick_period=0.01,
        recover_queued=False, estimate_runtimes=False,
    )
    defaults.update(kw)
    return TpuPushDispatcher(**defaults)


def test_dispatcher_tenancy_requires_single_device():
    with pytest.raises(ValueError):
        _mk_disp(tenant_shares="a=1", multihost=True)


def test_dispatcher_fair_dispatch_and_observability():
    """In-process fairness e2e (batch path): a heavy tenant's flood ahead
    of a light tenant's task in arrival order does not starve the light
    tenant; per-tenant counters, gauges, /stats block, and the strict
    exposition all carry the bounded tenant vocabulary."""
    from tpu_faas.obs.expofmt import parse_exposition
    from tpu_faas.worker import messages as m

    disp = _mk_disp(tenant_shares="heavy=1,light=1")
    try:
        disp._handle(b"w0", m.REGISTER, {"num_processes": 4})
        store = disp.store
        # heavy floods 12 tasks, then ONE light task arrives LAST
        for i in range(12):
            store.create_task(
                f"h{i}", "F", "P", extra_fields={FIELD_TENANT: "heavy"}
            )
        store.create_task(
            "light0", "F", "P", extra_fields={FIELD_TENANT: "light"}
        )
        disp.tick()
        # 4 slots: weighted-fair admission gives light its slot in the
        # first tick even though 12 heavy tasks queued ahead of it
        sent = set(disp.arrays._inflight_slot)
        assert "light0" in sent
        assert len(sent) == 4
        # inflight accounting per tenant
        ten = disp.tenancy
        assert int(ten.inflight[ten.row_for("light")]) == 1
        assert int(ten.inflight[ten.row_for("heavy")]) == 3
        # result for the light task releases its charge
        disp._handle(
            b"w0", m.RESULT,
            {"task_id": "light0", "status": "COMPLETED", "result": "42"},
        )
        assert int(ten.inflight[ten.row_for("light")]) == 0
        # /stats tenancy block + deficits
        block = disp.stats()["tenancy"]
        assert block["tenants"]["heavy"]["dispatched"] == 3
        assert block["tenants"]["light"]["dispatched"] == 1
        # strict exposition carries the families with bounded labels
        fams = parse_exposition(disp.render_metrics())
        f = fams["tpu_faas_tasks_dispatched_total"]
        labels = {s.labels["tenant"] for s in f.samples}
        assert {"heavy", "light", "default", "other"} <= labels
        assert fams["tpu_faas_tenant_queue_depth"] is not None
        assert fams["tpu_faas_tenant_inflight_tasks"] is not None
    finally:
        disp.close()


def test_dispatcher_unregistered_tenant_buckets_to_other():
    from tpu_faas.worker import messages as m

    disp = _mk_disp(tenant_shares="known=1", max_tenants=4)
    try:
        disp._handle(b"w0", m.REGISTER, {"num_processes": 2})
        disp.store.create_task(
            "t0", "F", "P", extra_fields={FIELD_TENANT: "surprise"}
        )
        disp.tick()
        ten = disp.tenancy
        assert ten.label_for("surprise") == "other"
        # it still got its own fair-queue row (capacity permitting)
        assert ten.row_for("surprise", register=False) != 0
    finally:
        disp.close()


def test_dispatcher_hot_reload_from_store():
    disp = _mk_disp(tenant_shares="a=1")
    try:
        disp.store.hset(
            TENANT_CONF_KEY, {"shares": encode_conf("a=9,b=2")}
        )
        disp._last_tenant_reload = -1e9
        disp._maybe_reload_tenant_conf()
        ten = disp.tenancy
        assert float(ten.share[ten.row_for("a")]) == 9.0
        assert float(ten.share[ten.row_for("b")]) == 2.0
    finally:
        disp.close()


def test_dispatcher_resident_tenancy_e2e():
    """Resident path: tenant rows ride the delta packet; the capped
    tenant's surplus stays device-side."""
    from tpu_faas.worker import messages as m

    disp = _mk_disp(
        tenant_shares="a=1,b=1", tenant_caps="b=1", resident=True
    )
    try:
        disp._handle(b"w0", m.REGISTER, {"num_processes": 4})
        for i in range(3):
            disp.store.create_task(
                f"a{i}", "F", "P", extra_fields={FIELD_TENANT: "a"}
            )
            disp.store.create_task(
                f"b{i}", "F", "P", extra_fields={FIELD_TENANT: "b"}
            )
        disp.tick()
        sent = set(disp.arrays.slot_task.values()) | set(
            disp.arrays._inflight_slot
        )
        inflight = set(disp.arrays._inflight_slot)
        assert {"a0", "a1", "a2"} <= inflight
        assert len([t for t in inflight if t.startswith("b")]) == 1
    finally:
        disp.close()


def test_inflight_gauge_sums_rows_sharing_other_label():
    """Two dynamically-registered tenants share the 'other' label; the
    gauge must SUM their inflight, not report whichever row looped last."""
    from tpu_faas.obs.expofmt import parse_exposition
    from tpu_faas.worker import messages as m

    disp = _mk_disp(tenant_shares="known=1", max_tenants=8)
    try:
        disp._handle(b"w0", m.REGISTER, {"num_processes": 4})
        for i, name in enumerate(["dyn-a", "dyn-a", "dyn-b"]):
            disp.store.create_task(
                f"t{i}", "F", "P", extra_fields={FIELD_TENANT: name}
            )
        disp.tick()
        fams = parse_exposition(disp.render_metrics())
        vals = {
            s.labels["tenant"]: s.value
            for s in fams["tpu_faas_tenant_inflight_tasks"].samples
        }
        assert vals["other"] == 3.0  # dyn-a's 2 + dyn-b's 1, not 1
    finally:
        disp.close()


def test_tenant_deficits_survives_donated_state_read():
    """Fused backend donates the state pytree each tick: a stats-thread
    snapshot of a deleted buffer degrades to None, never raises."""
    from tpu_faas.sched.pallas_fused import fused_ok
    from tpu_faas.sched.resident import ResidentScheduler

    if not fused_ok():
        pytest.skip("pallas unavailable")
    ten = TenantTable(shares={"a": 1.0}, max_tenants=2)
    r = ResidentScheduler(
        max_workers=2, max_pending=8, max_inflight=8, max_slots=1,
        time_to_expire=10.0, clock=lambda: 1.0, use_priority=True,
        tick_backend="fused_interpret", tenancy=ten,
    )
    r.register(b"w0", 1)
    r.tick_resident()
    st = r._r_state
    # simulate the donation race: the snapshot's buffer gets deleted
    st.t_deficit.delete()
    assert r.tenant_deficits() is None


# -- gateway / SDK propagation ----------------------------------------------


@pytest.fixture()
def gw():
    from tpu_faas.gateway import start_gateway_thread

    store = MemoryStore()
    handle = start_gateway_thread(store)
    yield handle, store
    handle.stop()


def _register(handle) -> str:
    r = requests.post(
        f"{handle.url}/register_function",
        json={"name": "f", "payload": serialize(lambda: 1)},
    )
    return r.json()["function_id"]


def test_gateway_stamps_tenant_header(gw):
    handle, store = gw
    fid = _register(handle)
    r = requests.post(
        f"{handle.url}/execute_function",
        json={"function_id": fid, "payload": serialize(((), {}))},
        headers={"X-Tenant-Id": "team-a"},
    )
    assert r.status_code == 200
    assert store.hgetall(r.json()["task_id"])[FIELD_TENANT] == "team-a"
    # absent header: no field (legacy default tenant)
    r = requests.post(
        f"{handle.url}/execute_function",
        json={"function_id": fid, "payload": serialize(((), {}))},
    )
    assert FIELD_TENANT not in store.hgetall(r.json()["task_id"])


def test_gateway_rejects_malformed_tenant(gw):
    handle, _store = gw
    fid = _register(handle)
    r = requests.post(
        f"{handle.url}/execute_function",
        json={"function_id": fid, "payload": serialize(((), {}))},
        headers={"X-Tenant-Id": "bad tenant!"},
    )
    assert r.status_code == 400
    assert "X-Tenant-Id" in r.json()["error"]


def test_gateway_batch_and_graph_carry_tenant(gw):
    handle, store = gw
    fid = _register(handle)
    r = requests.post(
        f"{handle.url}/execute_batch",
        json={"function_id": fid, "payloads": [serialize(((), {}))] * 3},
        headers={"X-Tenant-Id": "b-tenant"},
    )
    assert r.status_code == 200
    for tid in r.json()["task_ids"]:
        assert store.hgetall(tid)[FIELD_TENANT] == "b-tenant"
    r = requests.post(
        f"{handle.url}/execute_graph",
        json={
            "nodes": [
                {"function_id": fid, "payload": serialize(((), {}))},
                {
                    "function_id": fid,
                    "payload": serialize(((), {})),
                    "depends_on": [0],
                },
            ]
        },
        headers={"X-Tenant-Id": "g-tenant"},
    )
    assert r.status_code == 200
    for tid in r.json()["task_ids"]:
        assert store.hgetall(tid)[FIELD_TENANT] == "g-tenant"


def test_sdk_clients_send_tenant_header():
    from tpu_faas.client import FaaSClient
    from tpu_faas.client.aio import AsyncFaaSClient

    c = FaaSClient("http://127.0.0.1:1", tenant="team-z")
    assert c.http.headers["X-Tenant-Id"] == "team-z"
    assert FaaSClient("http://127.0.0.1:1").http.headers.get(
        "X-Tenant-Id"
    ) is None

    import asyncio

    async def probe():
        async with AsyncFaaSClient(
            "http://127.0.0.1:1", tenant="a-z"
        ) as ac:
            return ac.http.headers.get("X-Tenant-Id")

    assert asyncio.run(probe()) == "a-z"


def test_sdk_tenant_reaches_store_end_to_end(gw):
    handle, store = gw
    from tpu_faas.client import FaaSClient

    client = FaaSClient(handle.url, tenant="sdk-tenant")
    fid = client.register_payload("f", serialize(lambda: 1))
    tid = client.execute_payload(fid, serialize(((), {})))
    assert store.hgetall(tid)[FIELD_TENANT] == "sdk-tenant"


# -- churn soak (satellite: bounded per-worker bookkeeping) -----------------


def test_churn_soak_bookkeeping_stays_bounded():
    """~10k register/misfire/purge/reconnect cycles: every per-worker and
    per-task map on the tpu-push dispatcher must stay bounded by the LIVE
    fleet, and the fleet misfire total stays monotone across purges (the
    worker_misfires dict used to leak one entry per purged socket
    identity forever; _wid_token leaked whenever the estimator was off)."""
    from tpu_faas.worker import messages as m

    disp = _mk_disp()  # estimator OFF: the historical _wid_token leak path
    try:
        a = disp.arrays
        total_reported = 0
        last_total = 0
        for i in range(10_000):
            wid = f"churn-{i}".encode()
            disp._handle(
                wid, m.REGISTER,
                {
                    "num_processes": 1,
                    "token": f"tok-{i}",
                    "ephemeral": True,
                    "caps": ["blob", "bin"],
                },
            )
            # the worker reports a cumulative misfire total on a RESULT
            # for a task we never dispatched (suspicious path: store
            # write is first_wins, harmless) — every cycle leaks one
            # dict entry without the purge fold
            disp.note_worker_misfires(wid, {"misfires": 2})
            total_reported += 2
            row = a.worker_ids[wid]
            disp._reap_dead_workers([], [row], lambda t: None)
            cur = disp.total_worker_misfires()
            assert cur >= last_total
            last_total = cur
        assert disp.total_worker_misfires() == total_reported
        # every per-worker map bounded (empty: the whole fleet was purged)
        assert len(disp.worker_misfires) == 0
        assert len(disp._wid_token) == 0
        assert len(disp._wid_caps) == 0
        assert len(a.worker_ids) == 0 and len(a.row_ids) == 0
        # per-task maps untouched by pure worker churn
        assert len(disp._task_digest) == 0
        assert len(disp.task_retries) == 0
        assert len(disp._task_tenant_row) == 0
    finally:
        disp.close()


def test_push_dispatcher_purge_folds_misfires():
    """The classic push dispatcher's purge path folds too (same leak)."""
    from tpu_faas.dispatch.push import PushDispatcher
    from tpu_faas.worker import messages as m

    clock = [0.0]
    disp = PushDispatcher(
        ip="127.0.0.1", port=0, store=MemoryStore(), heartbeat=True,
        time_to_expire=5.0, clock=lambda: clock[0],
    )
    try:
        for i in range(50):
            wid = f"pw-{i}".encode()
            disp._handle(wid, m.REGISTER, {"num_processes": 1})
            disp.note_worker_misfires(wid, {"misfires": 1})
            clock[0] += 10.0  # past time_to_expire: next purge reaps it
            disp.purge_workers()
        assert len(disp.worker_misfires) == 0
        assert disp.total_worker_misfires() == 50
        assert len(disp.workers) == 0
    finally:
        disp.close()
