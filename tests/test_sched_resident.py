"""ResidentScheduler: device-resident pending set, delta uploads, compacted
readbacks — semantics must match the one-shot SchedulerArrays.tick path."""

import numpy as np
import pytest

from tpu_faas.sched.resident import ResidentScheduler
from tpu_faas.sched.state import SchedulerArrays


def _mk(max_workers=16, max_pending=64, max_inflight=32, **kw):
    clock_box = [100.0]
    r = ResidentScheduler(
        max_workers=max_workers,
        max_pending=max_pending,
        max_inflight=max_inflight,
        max_slots=4,
        time_to_expire=10.0,
        clock=lambda: clock_box[0],
        **kw,
    )
    r._clock_box = clock_box
    return r


def _drain(r):
    out = []
    while True:
        res = r.resolve_next()
        if res is None:
            return out
        out.append(res)


def test_resident_places_like_oneshot():
    r = _mk()
    plain = SchedulerArrays(
        max_workers=16, max_pending=64, max_slots=4, time_to_expire=10.0,
        clock=lambda: 100.0,
    )
    rng = np.random.default_rng(0)
    speeds = rng.uniform(0.5, 4.0, 6)
    for i in range(6):
        r.register(b"w%d" % i, 1 + i % 3, speed=float(speeds[i]))
        plain.register(b"w%d" % i, 1 + i % 3, speed=float(speeds[i]))
    sizes = rng.uniform(0.5, 5.0, 20).astype(np.float32)
    for i, s in enumerate(sizes):
        r.pending_add(f"t{i}", float(s))
    out = r.tick_resident()
    res = _drain(r)[-1]
    ref = plain.tick(sizes)
    ref_a = np.asarray(ref.assignment)[:20]
    cap = sum(min(1 + i % 3, 4) for i in range(6))
    assert len(res.placed) == (ref_a >= 0).sum() == min(20, cap)
    # identical placement decision task-for-task (same kernel, same inputs;
    # resident arrival slots are allocated in index order so slot i == task i)
    placed_map = dict(res.placed)
    for i in range(20):
        if ref_a[i] >= 0:
            assert placed_map[f"t{i}"] == ref_a[i]
        else:
            assert f"t{i}" not in placed_map
    assert res.n_pending == 20 - len(res.placed)


def test_resident_multi_tick_steady_state():
    r = _mk()
    for i in range(4):
        r.register(b"w%d" % i, 2, speed=1.0)
    # tick 1: 8 tasks, capacity 8
    for i in range(8):
        r.pending_add(f"a{i}", 1.0)
    r.tick_resident()
    placed = _drain(r)[-1].placed
    assert len(placed) == 8
    # resolve_next already consumed capacity (device + mirrors); the caller
    # only tracks the dispatch itself
    assert r.worker_free[:4].sum() == 0
    for tid, row in placed:
        r.inflight_add(tid, row)
    # tick 2: nothing free, 4 new tasks stay pending
    for i in range(4):
        r.pending_add(f"b{i}", 1.0)
    r.tick_resident()
    res = _drain(r)[-1]
    assert len(res.placed) == 0 and res.n_pending == 4
    # results for 4 tasks: slots free up; tick 3 places the 4 queued
    for tid, row in placed[:4]:
        row2 = r.inflight_done(tid)
        r.worker_free[row2] += 1
    r._clock_box[0] += 1.0
    for i in range(4):
        r.heartbeat(b"w%d" % i)
    r.tick_resident()
    res = _drain(r)[-1]
    assert len(res.placed) == 4
    assert {t for t, _ in res.placed} == {f"b{i}" for i in range(4)}
    assert res.n_pending == 0


def test_resident_purge_and_redispatch():
    r = _mk()
    for i in range(2):
        r.register(b"w%d" % i, 2, speed=1.0)
    for i in range(4):
        r.pending_add(f"t{i}", 1.0)
    r.tick_resident()
    placed = _drain(r)[-1].placed
    assert len(placed) == 4
    slots = {}
    for tid, row in placed:
        slots[tid] = r.inflight_add(tid, row)
    # w0 goes silent past time_to_expire; w1 keeps heartbeating
    r._clock_box[0] += 11.0
    r.heartbeat(b"w1")
    r.tick_resident()
    res = _drain(r)[-1]
    assert list(res.purged_rows) == [0]
    dead_slots = {slots[t] for t, row in placed if row == 0}
    assert set(res.redispatch_slots) == dead_slots


def test_resident_overflow_flush_path():
    # KA=4 forces the flush-kernel path for a 19-task burst
    r = _mk(KA=4)
    for i in range(8):
        r.register(b"w%d" % i, 4, speed=1.0)
    for i in range(19):
        r.pending_add(f"t{i}", 1.0 + i)
    r.tick_resident()
    results = _drain(r)
    placed = [p for res in results for p in res.placed]
    assert len(placed) == 19  # capacity 32 >= 19: everything lands this tick
    assert {t for t, _ in placed} == {f"t{i}" for i in range(19)}


def test_resident_buffer_full_rejects_and_requeues():
    r = _mk(max_pending=8, max_workers=4)
    r.register(b"w0", 1, speed=1.0)
    for i in range(12):
        r.pending_add(f"t{i}", 1.0)
    r.tick_resident()
    res = _drain(r)[-1]
    # 8 fit in the buffer; 1 placed (capacity); 4 bounced and re-queued
    assert res.rejected == 4
    assert len(res.placed) == 1
    assert r.n_pending_host == 12 - 1
    # next tick re-attempts the bounced arrivals (1 more placed after a free)
    r.worker_free[0] = 1
    r.tick_resident()
    res = _drain(r)[-1]
    assert len(res.placed) == 1


def test_resident_kp_compaction_replaces_surplus_next_tick():
    # KP=2 < placements=6: only 2 reported per tick, surplus stays valid
    r = _mk(KP=2)
    for i in range(3):
        r.register(b"w%d" % i, 2, speed=1.0)
    for i in range(6):
        r.pending_add(f"t{i}", 1.0)
    seen = []
    for _ in range(3):
        r.tick_resident()
        seen += _drain(r)[-1].placed
    assert len(seen) == 6
    assert {t for t, _ in seen} == {f"t{i}" for i in range(6)}


def test_resident_priority_admission():
    r = _mk(use_priority=True, max_workers=4)
    r.register(b"w0", 2, speed=1.0)
    # capacity 2; the two high-priority late arrivals must win admission
    r.pending_add("lo1", 1.0, priority=0)
    r.pending_add("lo2", 1.0, priority=0)
    r.pending_add("hi1", 1.0, priority=5)
    r.pending_add("hi2", 1.0, priority=5)
    r.tick_resident()
    res = _drain(r)[-1]
    assert {t for t, _ in res.placed} == {"hi1", "hi2"}


def test_resident_pipelined_ticks_never_double_book():
    """Two ticks issued back-to-back WITHOUT resolving the first: the
    device decrements its own free counts for reported placements, so the
    second tick must not re-book the same capacity."""
    r = _mk()
    for i in range(2):
        r.register(b"w%d" % i, 2, speed=1.0)  # capacity 4 total
    for i in range(4):
        r.pending_add(f"a{i}", 1.0)
    r.tick_resident()
    for i in range(4):
        r.pending_add(f"b{i}", 1.0)
    r.tick_resident()  # issued before any resolve
    first = r.resolve_next()
    second = r.resolve_next()
    assert len(first.placed) == 4
    assert len(second.placed) == 0  # no capacity left on device
    # per-worker totals never exceed the 2 slots each worker has
    counts = {}
    for _, row in first.placed + second.placed:
        counts[row] = counts.get(row, 0) + 1
    assert all(c <= 2 for c in counts.values())
    assert r.worker_free[:2].sum() == 0


def test_result_arrival_between_tick_and_resolve_cannot_overbook():
    """The interleaving dd15b99 documented as a bounded over-booking
    window: a tick's device-side placement decrement, then a host-side
    result arrival on the SAME worker row before the host resolves the
    tick. With the additive-delta free protocol the next tick must see
    only the result's +1, never an absolute value resurrecting the slot
    the device consumed."""
    r = _mk()
    r.register(b"w0", 2)  # 2 process slots
    # one task in flight occupies a slot; the other is free
    r.inflight_add("busy", 0)
    r.worker_free[0] = 1
    # tick 1: place one task into the last free slot (device free 1 -> 0)
    r.pending_add("a", 1.0)
    r.tick_resident()
    # BEFORE resolving, the in-flight result arrives host-side and frees
    # its slot — the host (still unaware of 'a') now believes free == 2
    row = r.inflight_done("busy")
    r.worker_free[row] = min(r.worker_free[row] + 1, int(r.worker_procs[row]))
    # tick 2: two more pending tasks, but TRUE remaining capacity is one
    # slot ('a' holds one, the result freed one)
    r.pending_add("b", 1.0)
    r.pending_add("c", 1.0)
    r.tick_resident()
    resolved = _drain(r)
    placed = [p for res in resolved for p in res.placed]
    names = sorted(tid for tid, _ in placed)
    # 'a' plus exactly ONE of b/c — an absolute-value upload would have
    # set device free to 2 and booked all three onto two process slots
    assert len(placed) == 2
    assert "a" in names


def test_heartbeat_epoch_rebase_keeps_deltas_flowing():
    """Past EPOCH_REBASE_S of uptime the epoch re-bases and every stamp
    re-uploads, so f32 stamp spacing never approaches heartbeat
    granularity (advisor finding, round 3)."""
    r = _mk()
    r.register(b"w0", 2)
    r.pending_add("a", 1.0)
    r.tick_resident()
    _drain(r)
    epoch0 = r._epoch
    # jump far past the re-base horizon; the worker keeps heartbeating
    r._clock_box[0] += ResidentScheduler.EPOCH_REBASE_S + 12_345.0
    r.heartbeat(b"w0")
    r.pending_add("b", 1.0)
    out = r.tick_resident()
    res = _drain(r)[-1]
    assert r._epoch > epoch0  # re-based
    assert not np.asarray(out.purged).any()  # fresh heartbeat survived
    assert len(res.placed) == 1  # and placement still works
    # subsequent sub-second heartbeats produce small, well-resolved ages
    r._clock_box[0] += 0.25
    r.heartbeat(b"w0")
    out2 = r.tick_resident()
    _drain(r)
    assert not np.asarray(out2.purged).any()


def test_resident_rejected_arrivals_keep_fcfs_order():
    """Bounced arrivals re-queue for the next tick in original order."""
    r = _mk(max_pending=4, max_workers=4)
    r.register(b"w0", 1, speed=1.0)
    for i in range(8):
        r.pending_add(f"t{i}", 1.0)
    r.tick_resident()
    res = _drain(r)[-1]
    assert res.rejected == 4
    # the bounced arrivals must be t4..t7 in that order
    assert [a.task_id for a in r._rejected] == [f"t{i}" for i in range(4, 8)]


def test_resident_rejected_fcfs_across_multiple_packets():
    """A burst split over flush + main packets, ALL bounced: the retry
    order must stay t0..t(n-1) — per-packet front-insertion would put the
    later packet's rejects ahead of the earlier packet's."""
    # KA=4 splits 10 arrivals into 2 flush packets + 1 main packet;
    # max_pending=8 with all 8 slots occupied bounces every arrival
    r = _mk(max_pending=8, max_workers=4, KA=4)
    r.register(b"w0", 0, speed=1.0)  # no capacity: occupants never leave
    for i in range(8):
        r.pending_add(f"occ{i}", 1.0)
    r.tick_resident()
    _drain(r)
    assert len(r.slot_task) == 8  # buffer full
    for i in range(10):
        r.pending_add(f"t{i}", 1.0)
    r.tick_resident()
    results = _drain(r)
    assert sum(res.rejected for res in results) == 10
    assert [a.task_id for a in r._rejected] == [f"t{i}" for i in range(10)]
    # and the next tick retries them in that same order
    r.tick_resident()
    _drain(r)
    assert [a.task_id for a in r._rejected] == [f"t{i}" for i in range(10)]


def test_resident_auction_matches_oneshot_across_ticks():
    """Resident auction (round 4): the in-kernel price carry makes tick 1
    open from the analytic dual seed (== the one-shot cold solve) and
    tick 2 from the carried equilibrium (== the one-shot warm solve) —
    placements must match the SchedulerArrays auction product path
    tick-for-tick."""
    r = _mk(placement="auction")
    plain = SchedulerArrays(
        max_workers=16, max_pending=64, max_slots=4, time_to_expire=10.0,
        clock=lambda: 100.0, placement="auction",
    )
    rng = np.random.default_rng(5)
    speeds = rng.uniform(0.5, 4.0, 6)
    for i in range(6):
        r.register(b"w%d" % i, 2, speed=float(speeds[i]))
        plain.register(b"w%d" % i, 2, speed=float(speeds[i]))
    sizes = rng.uniform(0.5, 5.0, 10).astype(np.float32)
    for i, sz in enumerate(sizes):
        r.pending_add(f"t{i}", float(sz))
    r.tick_resident()
    res1 = _drain(r)[-1]
    ref1 = np.asarray(plain.tick(sizes).assignment)[:10]
    assert dict(res1.placed) == {
        f"t{i}": int(w) for i, w in enumerate(ref1) if w >= 0
    }
    # tick 2: results free the slots; perturbed re-submissions warm-start
    # from carried prices on BOTH paths
    for tid, row in res1.placed:
        r.worker_free[row] = min(
            r.worker_free[row] + 1, int(r.worker_procs[row])
        )
    plain.worker_free[:6] = 2
    r._clock_box[0] += 0.5
    for i in range(6):
        r.heartbeat(b"w%d" % i)
        plain.heartbeat(b"w%d" % i)
    sizes2 = (sizes * 1.01).astype(np.float32)
    for i, sz in enumerate(sizes2):
        r.pending_add(f"u{i}", float(sz))
    r.tick_resident()
    res2 = _drain(r)[-1]
    ref2 = np.asarray(plain.tick(sizes2).assignment)[:10]
    assert dict(res2.placed) == {
        f"u{i}": int(w) for i, w in enumerate(ref2) if w >= 0
    }


def _mesh_scenario(r):
    """Registrations, prioritized arrivals, heartbeats, a result freeing a
    slot, late arrivals — resolved tick-for-tick."""
    rng = np.random.default_rng(0)
    speeds = rng.uniform(0.5, 4.0, 6)
    for i in range(6):
        r.register(b"w%d" % i, 1 + i % 3, speed=float(speeds[i]))
    for i, s in enumerate(rng.uniform(0.5, 5.0, 20)):
        r.pending_add(f"t{i}", float(s), priority=i % 3)
    r.tick_resident()
    outs = _drain(r)
    r._clock_box[0] += 1.0
    for i in range(6):
        r.heartbeat(b"w%d" % i)
    r.pending_add("late1", 2.0)
    r.pending_add("late2", 0.3)
    r.tick_resident()
    outs += _drain(r)
    return [(sorted(res.placed), res.n_pending) for res in outs]


@pytest.mark.parametrize("placement", ["rank", "sinkhorn", "auction"])
def test_resident_mesh_matches_single_device(placement):
    """--resident composes with --mesh: the SAME delta packets applied to
    task-sharded resident state must resolve like the single-device
    resident path (round-4: the fast path and the multi-chip path are the
    same path). The deterministic rank path must match PLACEMENT-FOR-
    PLACEMENT; the entropic path matches on placed counts and pending
    totals (its soft plan's argmax tie-breaks shift with f32 reduction
    order across sharding layouts — same caveat as the sharded one-shot
    tick in __graft_entry__.py)."""
    single = _mk(placement=placement, use_priority=True)
    mesh = _mk(placement=placement, use_priority=True, mesh_devices=8)
    assert mesh.mesh is not None and mesh.mesh.size == 8
    a = _mesh_scenario(single)
    b = _mesh_scenario(mesh)
    if placement in ("rank", "auction"):
        # deterministic solvers: placement-for-placement equality
        assert a == b
    else:
        assert [(len(p), n) for p, n in a] == [(len(p), n) for p, n in b]


def test_resident_dispatcher_bulk_loads_cold_backlog():
    """A restart/adoption backlog bigger than one delta packet enters the
    EMPTY device pending set via one bulk upload, not ceil(n/KA) flush
    dispatches (and everything still places correctly)."""
    from tpu_faas.dispatch.base import PendingTask
    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
    from tpu_faas.store import MemoryStore

    d = TpuPushDispatcher(
        ip="127.0.0.1", port=0, store=MemoryStore(),
        max_workers=16, max_pending=256, max_inflight=128,
        resident=True, recover_queued=False,
    )
    try:
        a = d.arrays
        assert a.KA == 256  # clamped to max_pending; backlog must exceed it
        for i in range(4):
            a.register(b"w%d" % i, 4)
        for i in range(300):
            d.store.create_task(f"t{i}", "F", "P")
            d.pending.append(PendingTask(f"t{i}", "F", "P"))
        d.tick(intake=False)
        # bulk path: the device set was filled by ONE upload (no flush
        # packets queued), placements all went to the 16 free slots
        assert len(a._unresolved) == 0  # tick drained them all
        assert d.n_dispatched == 16  # 4 workers x 4 slots placed
        assert len(d._resident_tasks) + d.n_dispatched == 300
    finally:
        d.close()
        d.socket.close(linger=0)


def test_resident_sinkhorn_placement():
    """--resident composes with placement=sinkhorn: the fused delta tick
    runs the entropic kernel and placements stay legal and complete."""
    r = _mk(placement="sinkhorn")
    rng = np.random.default_rng(7)
    for i in range(6):
        r.register(b"w%d" % i, 2, speed=float(rng.uniform(0.5, 4.0)))
    for i in range(10):
        r.pending_add(f"t{i}", float(rng.uniform(0.5, 5.0)))
    r.tick_resident()
    res = _drain(r)[-1]
    assert len(res.placed) == 10  # capacity 12 >= 10
    counts = {}
    for _, row in res.placed:
        counts[row] = counts.get(row, 0) + 1
    assert all(c <= 2 for c in counts.values())
    assert res.n_pending == 0
