"""LocalDispatcher unit tests against the in-proc store, including the
failure paths the reference leaks on (SURVEY §2 LocalDispatcher: a dead pool
child permanently loses a slot there)."""

import os
import threading

import pytest

from tpu_faas.core.executor import pack_params
from tpu_faas.core.serialize import deserialize, serialize
from tpu_faas.dispatch.local import LocalDispatcher
from tpu_faas.store import MemoryStore
from tpu_faas.workloads import arithmetic


def _child_killer():
    os._exit(17)  # simulates user code hard-killing the pool child


@pytest.fixture()
def dispatcher_stack():
    store = MemoryStore()
    d = LocalDispatcher(num_workers=2, store=store)
    t = threading.Thread(target=d.start, daemon=True)
    t.start()
    yield store, d
    d.stop()
    t.join(timeout=15)


def _submit(store, tid, fn, *args):
    store.create_task(tid, serialize(fn), pack_params(*args))


def _wait_terminal(store, tid, timeout=30.0):
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        status = store.get_status(tid)
        if status in ("COMPLETED", "FAILED"):
            return status
        time.sleep(0.01)
    raise TimeoutError(f"{tid} stuck at {store.get_status(tid)}")


def test_completes_tasks(dispatcher_stack):
    store, _ = dispatcher_stack
    _submit(store, "t1", arithmetic, 100)
    assert _wait_terminal(store, "t1") == "COMPLETED"
    assert deserialize(store.get_result("t1")[1]) == arithmetic(100)


def test_child_death_marks_failed_and_recovers(dispatcher_stack):
    store, _ = dispatcher_stack
    _submit(store, "killer", _child_killer)
    assert _wait_terminal(store, "killer", timeout=60) == "FAILED"
    # pool recovered: subsequent tasks complete on all slots
    for i in range(4):
        _submit(store, f"after-{i}", arithmetic, 50)
    for i in range(4):
        assert _wait_terminal(store, f"after-{i}", timeout=60) == "COMPLETED"


def test_unpicklable_exception_degrades_to_repr(dispatcher_stack):
    store, _ = dispatcher_stack

    def raise_unpicklable():
        import threading as th

        class Evil(Exception):
            def __init__(self):
                super().__init__("evil")
                self.lock = th.Lock()  # unpicklable attribute

        raise Evil()

    _submit(store, "evil", raise_unpicklable)
    assert _wait_terminal(store, "evil", timeout=60) == "FAILED"
    exc = deserialize(store.get_result("evil")[1])
    assert isinstance(exc, Exception)


def test_stale_announce_does_not_stall_intake():
    store = MemoryStore()
    d = LocalDispatcher(num_workers=2, store=store)
    # two announces whose hashes are gone, then a real one behind them
    store.publish("tasks", "ghost-1")
    store.publish("tasks", "ghost-2")
    store.create_task("real", serialize(arithmetic), pack_params(10))
    t = threading.Thread(target=d.start, kwargs={"max_tasks": 1}, daemon=True)
    t.start()
    t.join(timeout=60)
    assert not t.is_alive()
    assert store.get_status("real") == "COMPLETED"
    d.stop()
