"""Child process for the multi-host integration test (test_multihost.py).

Joins a two-process gloo-backed CPU "pod" via initialize_multihost, runs the
full sharded scheduler tick over the GLOBAL 8-device mesh (4 local devices
per process), and prints a deterministic summary line the parent compares
across ranks. Run: python tests/_multihost_child.py <rank> <coordinator_port>
"""

from __future__ import annotations

import sys


def main() -> None:
    rank, port = int(sys.argv[1]), sys.argv[2]

    from tpu_faas.parallel.distributed import initialize_multihost

    assert initialize_multihost(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=2,
        process_id=rank,
        cpu_devices_per_process=4,
    )

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    assert jax.process_count() == 2
    assert len(jax.devices()) == 8, jax.devices()

    from tpu_faas.parallel.mesh import (
        make_mesh,
        replicate,
        shard_task_arrays,
        sharded_scheduler_tick,
    )

    mesh = make_mesh(8)
    T, W, I = 64, 16, 32
    rng = np.random.default_rng(5)  # same seed every rank: global arrays
    task_size, task_valid = shard_task_arrays(
        mesh,
        jnp.asarray(rng.uniform(0.1, 5.0, T).astype(np.float32)),
        jnp.asarray(rng.random(T) > 0.2),
    )
    speed, free, active, hb_age, prev_live, inflight = replicate(
        mesh,
        jnp.asarray(rng.uniform(0.5, 4.0, W).astype(np.float32)),
        jnp.asarray(rng.integers(0, 4, W).astype(np.int32)),
        jnp.ones(W, dtype=bool),
        jnp.asarray(rng.uniform(0.0, 15.0, W).astype(np.float32)),
        jnp.ones(W, dtype=bool),
        jnp.asarray(rng.integers(-1, W, I).astype(np.int32)),
    )
    out = sharded_scheduler_tick(
        mesh,
        task_size,
        task_valid,
        speed,
        free,
        active,
        hb_age,
        prev_live,
        inflight,
        jnp.float32(10.0),
        max_slots=4,
        placement="sinkhorn",
    )
    jax.block_until_ready(out)
    # replicate the (process-spanning) assignment onto every host so each
    # rank can print the full result for the parent's cross-rank comparison
    gather = jax.jit(
        lambda a: a, out_shardings=NamedSharding(mesh, PartitionSpec())
    )
    a = np.asarray(gather(out.assignment))
    cap = int(np.minimum(np.asarray(free), 4).sum())
    placed = int((a >= 0).sum())
    assert placed <= cap
    print(
        f"MULTIHOST rank={rank} placed={placed} "
        f"checksum={int(a.sum())} purged={int(np.asarray(out.purged).sum())}",
        flush=True,
    )

    # -- rank + PRIORITIES over the 2-process mesh (round 4) ---------------
    # deterministic: the parent recomputes the same tick single-device and
    # compares the full assignment fingerprint — priority admission order
    # must match the single-host path exactly across processes
    rng2 = np.random.default_rng(6)  # fresh seed: parent replays it
    prio = shard_task_arrays(
        mesh, jnp.asarray(rng2.integers(-2, 3, T).astype(np.int32))
    )[0]
    out_p = sharded_scheduler_tick(
        mesh, task_size, task_valid, speed, free, active, hb_age,
        prev_live, inflight, jnp.float32(10.0), max_slots=4,
        placement="rank", task_priority=prio,
    )
    ap = np.asarray(gather(out_p.assignment))
    fp = int((ap * np.arange(1, T + 1)).sum())
    print(f"PRIO rank={rank} fingerprint={fp}", flush=True)

    # -- auction over the 2-process mesh (round 4) -------------------------
    out_a = sharded_scheduler_tick(
        mesh, task_size, task_valid, speed, free, active, hb_age,
        prev_live, inflight, jnp.float32(10.0), max_slots=4,
        placement="auction",
    )
    aa = np.asarray(gather(out_a.assignment))
    fa = int((aa * np.arange(1, T + 1)).sum())
    print(f"AUCTION rank={rank} fingerprint={fa}", flush=True)

    # -- WARM auction through the MultihostTick PROTOCOL (round 4) ---------
    # Two consecutive ticks through the production lead/follower path: the
    # second tick warm-starts from per-process carried prices, whose
    # refresh decision must stay in lockstep across ranks. Fingerprints
    # from tick 2 are compared across ranks and against the single-host
    # SchedulerArrays product path by the parent.
    from tpu_faas.parallel.multihost_tick import MultihostTick

    mt = MultihostTick(
        max_pending=32, max_workers=8, max_slots=2, placement="auction"
    )
    rng3 = np.random.default_rng(8)
    sizes_w = rng3.uniform(0.5, 5.0, 20).astype(np.float32)
    speed_w = rng3.uniform(0.5, 4.0, 8).astype(np.float32)
    free_w = np.full(8, 2, dtype=np.int32)
    active_w = np.ones(8, dtype=bool)
    hb_w = np.zeros(8, dtype=np.float32)
    infl_w = np.full(4, -1, dtype=np.int32)
    if rank == 0:
        mt.lead_tick(sizes_w, speed_w, free_w, active_w, hb_w, infl_w, 10.0)
        out2 = mt.lead_tick(
            sizes_w * 1.01, speed_w, free_w, active_w, hb_w, infl_w, 10.0
        )
        a2 = np.asarray(out2.assignment)
        mt.lead_stop()
    else:
        for _ in range(2):
            out2 = mt._run(
                mt._broadcast(np.zeros(mt.buflen, dtype=np.float32))
            )
        a2 = np.asarray(out2.assignment)
        assert mt._run(
            mt._broadcast(np.zeros(mt.buflen, dtype=np.float32))
        ) is None
    f2 = int((a2 * np.arange(1, len(a2) + 1)).sum())
    print(f"WARMAUCTION rank={rank} fingerprint={f2}", flush=True)


if __name__ == "__main__":
    main()
