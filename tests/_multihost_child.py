"""Child process for the multi-host integration test (test_multihost.py).

Joins a two-process gloo-backed CPU "pod" via initialize_multihost, runs the
full sharded scheduler tick over the GLOBAL 8-device mesh (4 local devices
per process), and prints a deterministic summary line the parent compares
across ranks. Run: python tests/_multihost_child.py <rank> <coordinator_port>
"""

from __future__ import annotations

import sys


def main() -> None:
    rank, port = int(sys.argv[1]), sys.argv[2]

    from tpu_faas.parallel.distributed import initialize_multihost

    assert initialize_multihost(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=2,
        process_id=rank,
        cpu_devices_per_process=4,
    )

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    assert jax.process_count() == 2
    assert len(jax.devices()) == 8, jax.devices()

    from tpu_faas.parallel.mesh import (
        make_mesh,
        replicate,
        shard_task_arrays,
        sharded_scheduler_tick,
    )

    mesh = make_mesh(8)
    T, W, I = 64, 16, 32
    rng = np.random.default_rng(5)  # same seed every rank: global arrays
    task_size, task_valid = shard_task_arrays(
        mesh,
        jnp.asarray(rng.uniform(0.1, 5.0, T).astype(np.float32)),
        jnp.asarray(rng.random(T) > 0.2),
    )
    speed, free, active, hb_age, prev_live, inflight = replicate(
        mesh,
        jnp.asarray(rng.uniform(0.5, 4.0, W).astype(np.float32)),
        jnp.asarray(rng.integers(0, 4, W).astype(np.int32)),
        jnp.ones(W, dtype=bool),
        jnp.asarray(rng.uniform(0.0, 15.0, W).astype(np.float32)),
        jnp.ones(W, dtype=bool),
        jnp.asarray(rng.integers(-1, W, I).astype(np.int32)),
    )
    out = sharded_scheduler_tick(
        mesh,
        task_size,
        task_valid,
        speed,
        free,
        active,
        hb_age,
        prev_live,
        inflight,
        jnp.float32(10.0),
        max_slots=4,
        use_sinkhorn=True,
    )
    jax.block_until_ready(out)
    # replicate the (process-spanning) assignment onto every host so each
    # rank can print the full result for the parent's cross-rank comparison
    gather = jax.jit(
        lambda a: a, out_shardings=NamedSharding(mesh, PartitionSpec())
    )
    a = np.asarray(gather(out.assignment))
    cap = int(np.minimum(np.asarray(free), 4).sum())
    placed = int((a >= 0).sum())
    assert placed <= cap
    print(
        f"MULTIHOST rank={rank} placed={placed} "
        f"checksum={int(a.sum())} purged={int(np.asarray(out.purged).sum())}",
        flush=True,
    )


if __name__ == "__main__":
    main()
