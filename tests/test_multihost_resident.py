"""resident + multihost: the delta-packet protocol over a REAL two-process
gloo pod (parallel/multihost_resident.py).

The child (tests/_multihost_resident_child.py) drives registrations,
prioritized arrivals, result churn, 12 ticks, and the stop broadcast
through MultihostResidentScheduler; the follower mirrors every packet.
This parent asserts both ranks exit cleanly through the STOP protocol (not
coordinator-death containment) and that the lead's placements are
IDENTICAL to a single-process ResidentScheduler fed the same scenario —
the packet protocol adds no semantics.
"""

from __future__ import annotations

import os
import re
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

import pytest

from tests.test_multihost import cpu_pod_supported

if not cpu_pod_supported():
    pytest.skip(
        "this JAX cannot simulate a multi-process CPU pod "
        "(jax_num_cpu_devices / jax.shard_map missing)",
        allow_module_level=True,
    )



def _single_process_reference(placement: str) -> tuple[int, int]:
    """The child's exact scenario on a plain single-device
    ResidentScheduler; returns (n_placed, fingerprint)."""
    from tpu_faas.sched.resident import ResidentScheduler

    clock = [100.0]
    r = ResidentScheduler(
        max_workers=16,
        max_pending=64,
        max_inflight=128,
        max_slots=4,
        time_to_expire=10.0,
        clock=lambda: clock[0],
        use_priority=True,
        placement=placement,
    )
    rng = np.random.default_rng(0)
    speeds = rng.uniform(0.5, 4.0, 8)
    for i in range(8):
        r.register(b"w%d" % i, 2, speed=float(speeds[i]))
    placed_all = []
    arrival = 0
    for _ in range(12):
        clock[0] += 0.5
        for i in range(8):
            r.heartbeat(b"w%d" % i)
        for _ in range(4):
            r.pending_add(
                f"t{arrival}", float(rng.uniform(0.5, 5.0)),
                priority=arrival % 3,
            )
            arrival += 1
        r.tick_resident()
        while True:
            res = r.resolve_next()
            if res is None:
                break
            for tid, row in res.placed:
                placed_all.append((tid, row))
                r.worker_free[row] = min(
                    r.worker_free[row] + 1, int(r.worker_procs[row])
                )
    import zlib

    fp = sum(
        zlib.crc32(t.encode()) * (int(w) + 1) % 1000003 for t, w in placed_all
    )
    return len(placed_all), fp


@pytest.mark.parametrize("placement", ["rank", "auction"])
def test_two_process_resident_packet_protocol(placement):
    """rank: the default path. auction: the round-4 price/refresh carry —
    two extra replicated state fields whose out-sharding and broadcast
    lockstep only engage with this placement."""
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()

    existing = os.environ.get("PYTHONPATH", "")
    env = dict(
        os.environ, PYTHONPATH=f"{REPO}:{existing}" if existing else REPO
    )
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [
                sys.executable, "tests/_multihost_resident_child.py",
                str(rank), str(port), placement,
            ],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )
        for rank in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
            assert p.returncode == 0, out[-2000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    m = re.search(r"MHRES lead placed=(\d+) fingerprint=(\d+)", outs[0])
    assert m, outs[0][-1500:]
    placed, fp = int(m.group(1)), int(m.group(2))
    # follower exited through the STOP protocol, not containment
    assert "MHRES follower done" in outs[1], outs[1][-1500:]
    assert "Terminating process" not in outs[1]
    # the packet protocol changes nothing: single-process resident makes
    # the identical placements
    ref_placed, ref_fp = _single_process_reference(placement)
    assert (placed, fp) == (ref_placed, ref_fp)
