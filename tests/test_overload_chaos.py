"""Overload chaos end-to-end: a burst >= 3x fleet capacity PLUS a worker
SIGKILL mid-burst, race monitor on, against the full real stack (store
server over TCP, gateway with admission engaged, tpu-push dispatcher,
subprocess workers). The invariants under fire:

- no admitted task is lost: every id the gateway acknowledged reaches a
  terminal state (COMPLETED for plain tasks; COMPLETED or EXPIRED for the
  deadline slice), even though a worker died holding tasks;
- every reject is a clean 429/503 carrying a Retry-After header — no
  hangs, no 500s, no silent drops;
- EXPIRED happens only from QUEUED: the runtime race monitor would flag
  any RUNNING -> EXPIRED write as an illegal-transition ERROR, and the
  run must end with zero protocol errors.
"""

from __future__ import annotations

import signal
import threading
import time

import requests

from tpu_faas.admission import AdmissionController
from tpu_faas.admission.controller import AdmissionConfig
from tpu_faas.client import FaaSClient
from tpu_faas.core.executor import pack_params
from tpu_faas.core.serialize import serialize
from tpu_faas.core.task import TaskStatus
from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store.launch import make_store, start_store_thread
from tpu_faas.store.racecheck import RaceCheckStore, RaceMonitor
from tpu_faas.workloads import sleep_task
from tests.test_workers_e2e import _spawn_worker

BOUND = 40
TASK_S = 0.25


def test_overload_burst_worker_kill_invariants():
    monitor = RaceMonitor()
    store_handle = start_store_thread()
    admission = AdmissionController(
        AdmissionConfig(max_system_inflight=BOUND)
    )
    gw = start_gateway_thread(
        RaceCheckStore(
            make_store(store_handle.url), monitor, actor="gateway"
        ),
        admission=admission,
    )
    disp = TpuPushDispatcher(
        ip="127.0.0.1",
        port=0,
        store=RaceCheckStore(
            make_store(store_handle.url), monitor, actor="dispatcher"
        ),
        max_workers=64,
        max_pending=256,
        max_inflight=512,
        tick_period=0.01,
        time_to_expire=1.5,
        rescan_period=0.5,
    )
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        _spawn_worker("push_worker", 2, url, "--hb", "--hb-period", "0.3")
        for _ in range(3)
    ]
    client = FaaSClient(gw.url)
    raw = requests.Session()  # NO retries: rejects must surface raw
    try:
        fid = client.register(sleep_task)
        payload = pack_params(TASK_S)

        # warmup (worker pools spawn, first dill decode) — small, admitted
        for h in client.submit_many(fid, [((TASK_S,), {})] * 6):
            assert h.result(timeout=60.0) == TASK_S

        # -- the burst: ~3x what the fleet can hold, raw posts ------------
        # 6 slots x 0.25 s tasks drain ~24/s; the bound admits at most
        # BOUND in-system. Offer 3 * BOUND quickly; the tail must reject.
        admitted: list[str] = []
        deadline_ids: list[str] = []
        rejects = 0
        bad_rejects = []
        for i in range(3 * BOUND):
            body = {"function_id": fid, "payload": payload}
            if i % 5 == 4:
                # the deadline slice: lapses while queued behind ~BOUND
                # tasks unless it lands near the front
                body["deadline"] = 0.8
            r = raw.post(f"{gw.url}/execute_function", json=body, timeout=30)
            if r.status_code == 200:
                tid = r.json()["task_id"]
                admitted.append(tid)
                if "deadline" in body:
                    deadline_ids.append(tid)
            elif r.status_code in (429, 503):
                rejects += 1
                if not r.headers.get("Retry-After"):
                    bad_rejects.append((r.status_code, dict(r.headers)))
            else:
                bad_rejects.append((r.status_code, r.text[:200]))
            if i == BOUND:  # mid-burst: a worker dies holding tasks
                workers[0].send_signal(signal.SIGKILL)
                workers[0].wait()

        assert rejects > 0, "burst never tripped admission"
        assert not bad_rejects, bad_rejects
        assert len(admitted) >= 1

        # -- drain: every admitted task reaches a terminal state ----------
        probe = make_store(store_handle.url)
        deadline_wall = time.monotonic() + 120
        statuses: dict[str, str] = {}
        pending = list(admitted)
        while pending and time.monotonic() < deadline_wall:
            got = probe.hget_many(pending, "status")
            still = []
            for tid, status in zip(pending, got):
                if status is not None and TaskStatus.terminal_str(status):
                    statuses[tid] = status
                else:
                    still.append(tid)
            pending = still
            if pending:
                time.sleep(0.25)
        probe.close()
        assert pending == [], f"{len(pending)} admitted tasks lost"

        # plain tasks all COMPLETED (worker kill recovered by re-dispatch);
        # the deadline slice may legitimately EXPIRE instead
        deadline_set = set(deadline_ids)
        for tid, status in statuses.items():
            if tid in deadline_set:
                assert status in ("COMPLETED", "EXPIRED"), (tid, status)
            else:
                assert status == "COMPLETED", (tid, status)

        # protocol clean: zero errors means, among everything else, that
        # every EXPIRED write came from QUEUED (RUNNING -> EXPIRED is an
        # illegal-transition ERROR) and the worker kill double-dispatched
        # nothing undeclared
        assert monitor.errors == [], "\n".join(str(v) for v in monitor.errors)
        assert monitor.unfinished() == []
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.wait()
        disp.stop()
        t.join(timeout=10)
        gw.stop()
        store_handle.stop()
