"""Composed tail-SLO attribution plane (tpu_faas/obs/attribution.py +
obs/flightrec.py): class derivation totality, the closed attribution
vocabulary and its pre-created child set under the strict exposition
grammar, the hi-res bucket ladder, per-class SLO objective parsing, the
flight recorder's ring bounds / cursor semantics / emit-while-scrape
thread safety — and the proof that with every new knob OFF the default
metrics surface stays byte-identical."""

from __future__ import annotations

import json
import threading

import pytest

from tpu_faas.obs import MetricsRegistry, TaskTraceBook, render
from tpu_faas.obs.attribution import (
    ATTRIB_VOCAB,
    CLASS_ENV,
    DEFAULT_CLASS,
    HIRES_ENV,
    SLO_CLASSES,
    AttributionBook,
    class_of,
    class_of_fields,
    hires_buckets,
    latency_buckets,
    normalize_class,
)
from tpu_faas.obs.expofmt import parse_exposition
from tpu_faas.obs.flightrec import FlightRecorder
from tpu_faas.obs.metrics import LATENCY_BUCKETS
from tpu_faas.obs.slo import Objective, parse_objectives
from tpu_faas.core.task import FIELD_PRIORITY, FIELD_SLO_CLASS


# -- class derivation --------------------------------------------------------


def test_class_of_is_total_and_never_off_vocabulary():
    # explicit valid declaration wins over the priority sign
    assert class_of("batch", 5) == "batch"
    assert class_of(" Interactive ", -3) == "interactive"
    # no declaration: the priority sign decides
    assert class_of(None, 7) == "interactive"
    assert class_of(None, -1) == "batch"
    assert class_of(None, 0) == DEFAULT_CLASS
    assert class_of(None, None) == DEFAULT_CLASS
    # garbage degrades, never raises, never escapes the vocabulary
    for junk_cls in ("gold", 17, b"\xff\xfe", object()):
        for junk_prio in ("not-a-number", object()):
            assert class_of(junk_cls, junk_prio) in SLO_CLASSES


def test_normalize_class_accepts_only_the_closed_vocabulary():
    assert normalize_class("interactive") == "interactive"
    assert normalize_class(b"batch") == "batch"
    assert normalize_class("BATCH ") == "batch"
    assert normalize_class("premium") is None
    assert normalize_class(None) is None
    assert normalize_class(3.14) is None
    assert normalize_class(b"\xff\xfe") is None


def test_class_of_fields_reads_store_record_vocabulary():
    assert (
        class_of_fields({FIELD_SLO_CLASS: "batch", FIELD_PRIORITY: "9"})
        == "batch"
    )
    assert class_of_fields({FIELD_PRIORITY: "9"}) == "interactive"
    assert class_of_fields({}) == DEFAULT_CLASS


# -- attribution counter family ----------------------------------------------


def test_attribution_family_prerenders_full_closed_vocabulary():
    r = MetricsRegistry()
    book = AttributionBook(r, enabled=True)
    fams = parse_exposition(render([r]))
    fam = fams["tpu_faas_task_attrib_total"]
    got = {
        (s.labels["plane"], s.labels["outcome"], s.labels["class"])
        for s in fam.samples
    }
    want = {
        (plane, outcome, cls)
        for plane, outcomes in ATTRIB_VOCAB.items()
        for outcome in outcomes
        for cls in SLO_CLASSES
    }
    # explicit zeros for the whole plane x outcome x class product — the
    # bench's "plane live" check is a plain nonzero read against these
    assert got == want
    assert all(s.value == 0 for s in fam.samples)
    book.note("express", "inline", "interactive")
    book.note("speculation", "hedged_won", "batch", n=3)
    fams = parse_exposition(render([r]))
    by_key = {
        (s.labels["plane"], s.labels["outcome"], s.labels["class"]): s.value
        for s in fams["tpu_faas_task_attrib_total"].samples
    }
    assert by_key[("express", "inline", "interactive")] == 1
    assert by_key[("speculation", "hedged_won", "batch")] == 3


def test_attribution_rejects_off_vocabulary_outcomes():
    r = MetricsRegistry()
    book = AttributionBook(r, enabled=True)
    with pytest.raises(ValueError):
        book.note("express", "teleported", "default")
    with pytest.raises(ValueError):
        book.note("warp", "inline", "default")
    # off-vocabulary CLASSES degrade instead (they come from user input)
    book.note("express", "inline", "platinum")
    fams = parse_exposition(render([r]))
    by_key = {
        (s.labels["plane"], s.labels["outcome"], s.labels["class"]): s.value
        for s in fams["tpu_faas_task_attrib_total"].samples
    }
    assert by_key[("express", "inline", DEFAULT_CLASS)] == 1


def test_disabled_attribution_is_byte_identical_and_noop():
    r_plain = MetricsRegistry()
    r_plain.counter("unrelated_total", "help").inc()
    r_with = MetricsRegistry()
    r_with.counter("unrelated_total", "help").inc()
    book = AttributionBook(r_with, enabled=False)
    book.note("express", "inline", "interactive")  # must be a no-op
    book.note("warp", "teleported", "x")  # disabled: not even validated
    assert render([r_with]) == render([r_plain])
    assert "tpu_faas_task_attrib_total" not in render([r_with])


# -- bucket ladders ----------------------------------------------------------


def test_hires_ladder_is_log_spaced_and_strictly_increasing():
    b = hires_buckets()
    assert len(b) == 30
    assert b[0] == pytest.approx(0.001)
    assert b[-1] == pytest.approx(60.0)
    assert all(hi > lo for lo, hi in zip(b, b[1:]))
    # roughly constant ratio (log spacing), ~1.46x per step
    ratios = [hi / lo for lo, hi in zip(b, b[1:])]
    assert all(1.3 < q < 1.6 for q in ratios)


def test_latency_buckets_env_gate(monkeypatch):
    monkeypatch.delenv(HIRES_ENV, raising=False)
    assert latency_buckets(LATENCY_BUCKETS) == LATENCY_BUCKETS
    monkeypatch.setenv(HIRES_ENV, "1")
    assert latency_buckets(LATENCY_BUCKETS) == hires_buckets()
    monkeypatch.setenv(HIRES_ENV, "off")
    assert latency_buckets(LATENCY_BUCKETS) == LATENCY_BUCKETS


# -- default-surface byte identity -------------------------------------------


def test_trace_book_default_surface_byte_identical(monkeypatch):
    """With both env gates unset, a trace book + attribution book render
    the exact bytes of the pre-attribution two-label surface: no class
    label, no attrib family, the default bucket ladder."""
    monkeypatch.delenv(CLASS_ENV, raising=False)
    monkeypatch.delenv(HIRES_ENV, raising=False)
    r_new = MetricsRegistry()
    book = TaskTraceBook(r_new)
    AttributionBook(r_new)
    assert book.class_enabled is False
    book.note("t1", "submitted", ts=1.0)
    book.note_class("t1", "interactive")  # gate off: must be a no-op
    book.finish("t1", "COMPLETED", ts=2.0)
    body = render([r_new])
    assert 'class="' not in body
    assert "tpu_faas_task_attrib_total" not in body
    # same driving sequence against an explicitly class-blind book
    # produces the identical bytes — the label plumbing is invisible off
    r_old = MetricsRegistry()
    old = TaskTraceBook(r_old, class_enabled=False)
    old.note("t1", "submitted", ts=1.0)
    old.finish("t1", "COMPLETED", ts=2.0)
    assert body == render([r_old])


def test_trace_book_class_label_on_records_and_restricts():
    r = MetricsRegistry()
    book = TaskTraceBook(r, class_enabled=True)
    book.note("t1", "submitted", ts=1.0)
    book.note_class("t1", "interactive")
    book.note_class("t1", "batch")  # first write wins
    book.note_class("t1", "gold")  # off-vocabulary: ignored
    book.finish("t1", "COMPLETED", ts=2.0)
    rec = book.timeline("t1")
    assert rec["slo_class"] == "interactive"
    snap = book.stage_snapshot("total", cls="interactive")
    assert snap is not None and sum(snap[1]) == 1
    snap_other = book.stage_snapshot("total", cls="batch")
    assert snap_other is not None and sum(snap_other[1]) == 0
    # class-blind book: a class-restricted read must refuse (None), not
    # silently alias the aggregate
    blind = TaskTraceBook(MetricsRegistry(), class_enabled=False)
    assert blind.stage_snapshot("total", cls="interactive") is None


# -- per-class SLO objectives ------------------------------------------------


def test_parse_objectives_with_class_suffix():
    objs = parse_objectives(
        "int_p999=total@interactive:0.3:0.999, all_p99=total:0.25:0.99"
    )
    assert objs[0] == Objective(
        "int_p999", "total", 0.3, 0.999, "interactive"
    )
    assert objs[1].cls is None
    with pytest.raises(ValueError):
        parse_objectives("bad=total@platinum:0.3:0.99")
    with pytest.raises(ValueError):
        parse_objectives("bad=total@interactive")


# -- flight recorder ---------------------------------------------------------


def test_flightrec_ring_is_bounded_and_counts_drops():
    rec = FlightRecorder(capacity=8, clock=lambda: 42.0)
    for i in range(20):
        rec.emit("tick", i=i)
    snap = rec.snapshot()
    assert snap["cursor"] == 20
    assert snap["capacity"] == 8
    assert snap["dropped"] == 12
    assert [e["seq"] for e in snap["events"]] == list(range(13, 21))
    assert snap["events"][0]["i"] == 12  # payload fields ride verbatim
    assert snap["events"][0]["t"] == 42.0


def test_flightrec_since_cursor_polls_incrementally():
    rec = FlightRecorder(capacity=64)
    rec.emit("a")
    rec.emit("b")
    first = rec.snapshot()
    assert [e["kind"] for e in first["events"]] == ["a", "b"]
    rec.emit("c")
    second = rec.snapshot(since=first["cursor"])
    assert [e["kind"] for e in second["events"]] == ["c"]
    assert rec.snapshot(since=second["cursor"])["events"] == []


def test_flightrec_limit_keeps_newest():
    rec = FlightRecorder(capacity=64)
    for i in range(10):
        rec.emit("e", i=i)
    snap = rec.snapshot(limit=3)
    assert snap["truncated"] == 7
    assert [e["i"] for e in snap["events"]] == [7, 8, 9]


def test_flightrec_dump_json_round_trips():
    rec = FlightRecorder(capacity=4)
    rec.emit("hedge", task_id="t-1", verdict="launched")
    body = json.loads(rec.dump_json())
    assert body["events"][0]["kind"] == "hedge"
    assert body["events"][0]["task_id"] == "t-1"


def test_flightrec_rejects_degenerate_capacity():
    with pytest.raises(ValueError):
        FlightRecorder(capacity=0)


def test_flightrec_concurrent_emit_and_scrape():
    """Writers hammer emit() while a reader snapshots: no exceptions, no
    torn reads (seqs strictly increase within every snapshot), and the
    final cursor accounts for every emit exactly once."""
    rec = FlightRecorder(capacity=256)
    n_writers, per_writer = 4, 2000
    errors: list[BaseException] = []
    stop = threading.Event()

    def write(w: int) -> None:
        try:
            for i in range(per_writer):
                rec.emit("tick", w=w, i=i)
        except BaseException as exc:  # pragma: no cover - failure path
            errors.append(exc)

    def read() -> None:
        try:
            while not stop.is_set():
                snap = rec.snapshot()
                seqs = [e["seq"] for e in snap["events"]]
                assert seqs == sorted(seqs)
                assert len(seqs) <= rec.capacity
                json.loads(rec.dump_json())
        except BaseException as exc:  # pragma: no cover - failure path
            errors.append(exc)

    writers = [
        threading.Thread(target=write, args=(w,)) for w in range(n_writers)
    ]
    reader = threading.Thread(target=read)
    reader.start()
    for t in writers:
        t.start()
    for t in writers:
        t.join()
    stop.set()
    reader.join()
    assert not errors
    assert rec.snapshot()["cursor"] == n_writers * per_writer
