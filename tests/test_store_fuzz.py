"""Differential store-backend fuzz (VERDICT item 7, scoped).

One SEEDED random command sequence — raw hash ops, setnx-field claims,
HINCRBY counters, live-index field ops, pub/sub, batch/pipelined forms,
an occasional FLUSHDB — driven over several interleaved connections
against each store backend, asserting the full decoded reply log is
IDENTICAL across backends. The RESP decode is deterministic (one wire
form per reply value in store/resp.py), so equal decoded logs are equal
wire bytes for the server-backed legs; MemoryStore is the executable
spec the servers are differenced against.

Backends: MemoryStore (the reference), the asyncio RESP server through
RespStore clients (one socket per logical connection), and the native
C++ server when its binary is built (skipped otherwise — same gating as
test_store_resp).

Known, deliberately-excluded divergence (found by this fuzz's first
run): HINCRBY against a field holding a NON-integer string errors on
the RESP servers (Redis semantics, "-ERR hash value is not an integer")
but coerces to 0 in MemoryStore and the TaskStore base default (their
documented lenient contract). No production caller increments a field
it didn't itself write as an integer — the promotion plane owns
FIELD_PENDING_DEPS end to end — so the program keeps counter fields in
a namespace its string-writing ops never touch, exercising the shared
contract rather than the documented edge split.
"""

from __future__ import annotations

import random

import pytest

from tpu_faas.store.base import LIVE_INDEX_KEY
from tpu_faas.store.launch import make_store, start_store_thread
from tpu_faas.store.memory import MemoryStore

SEED = 0xFAA5
N_OPS = 400
N_CONNS = 3
KEYS = [f"fuzz:{i}" for i in range(8)] + [LIVE_INDEX_KEY]
FIELDS = [f"f{i}" for i in range(6)]
#: counter fields live in their own namespace: string-writing ops never
#: touch them (see the module docstring's HINCRBY note)
COUNTER_FIELDS = [f"cf{i}" for i in range(4)]
CHANNELS = ["fuzz-chan-a", "fuzz-chan-b"]


def _gen_ops(seed: int, n: int) -> list[tuple]:
    """The shared random program: (conn_index, op, args...) tuples, a pure
    function of the seed so every backend replays the identical sequence."""
    rng = random.Random(seed)
    ops: list[tuple] = []

    def key() -> str:
        return rng.choice(KEYS)

    def field() -> str:
        return rng.choice(FIELDS)

    def cfield() -> str:
        return rng.choice(COUNTER_FIELDS)

    def value() -> str:
        # cover empty values, NUL-free binary-ish text, and multi-line
        return rng.choice(
            ["", "v", "line1\r\nline2", "x" * rng.randrange(1, 64)]
        ) + str(rng.randrange(1000))

    for _ in range(n):
        c = rng.randrange(N_CONNS)
        op = rng.choices(
            [
                "hset", "hget", "hgetall", "hmget", "hexists", "hdel",
                "delete", "setnx_field", "hincrby", "keys", "publish",
                "drain", "hset_many", "hget_many", "hgetall_many",
                "setnx_fields", "hincrby_many", "flush",
            ],
            weights=[
                10, 10, 8, 6, 6, 5, 3, 8, 8, 3, 8, 8, 4, 4, 4, 4, 4, 1,
            ],
        )[0]
        if op == "hset":
            ops.append(
                (c, op, key(), {field(): value() for _ in range(rng.randrange(1, 4))})
            )
        elif op in ("hget", "hexists"):
            ops.append((c, op, key(), field()))
        elif op in ("hgetall", "delete"):
            ops.append((c, op, key()))
        elif op == "hmget":
            ops.append(
                (c, op, key(), [field() for _ in range(rng.randrange(1, 4))])
            )
        elif op == "hdel":
            # deleting a counter field is legal everywhere (absent = 0)
            fs = [field() for _ in range(rng.randrange(1, 3))]
            if rng.random() < 0.3:
                fs.append(cfield())
            ops.append((c, op, key(), tuple(fs)))
        elif op == "setnx_field":
            ops.append((c, op, key(), field(), value()))
        elif op == "hincrby":
            ops.append((c, op, key(), cfield(), rng.randrange(-5, 9)))
        elif op == "keys":
            ops.append((c, op))
        elif op == "publish":
            ops.append((c, op, rng.choice(CHANNELS), value()))
        elif op == "drain":
            ops.append((c, op, rng.choice(CHANNELS)))
        elif op == "hset_many":
            ops.append(
                (
                    c, op,
                    [
                        (key(), {field(): value()})
                        for _ in range(rng.randrange(1, 4))
                    ],
                )
            )
        elif op in ("hget_many", "hgetall_many"):
            ops.append(
                (c, op, [key() for _ in range(rng.randrange(1, 4))], field())
            )
        elif op == "setnx_fields":
            ops.append(
                (
                    c, op,
                    [(key(), value()) for _ in range(rng.randrange(1, 4))],
                    field(),
                )
            )
        elif op == "hincrby_many":
            ops.append(
                (
                    c, op,
                    [
                        (key(), cfield(), rng.randrange(-3, 6))
                        for _ in range(rng.randrange(1, 4))
                    ],
                )
            )
        elif op == "flush":
            ops.append((c, op))
    return ops


def _drain(sub) -> list[str]:
    out = []
    while True:
        # a bounded timeout absorbs server-side delivery latency (the
        # asyncio server fans out on its loop thread); MemoryStore
        # delivers synchronously so the timeout never actually waits once
        # the queue is empty and nothing was published
        msg = sub.get_message(timeout=0.2)
        if msg is None:
            return out
        out.append(msg)


def _run_program(conns, subs, ops) -> list[str]:
    """Execute the program, returning the decoded reply log. ``conns`` is
    one store handle per logical connection; ``subs`` maps channel ->
    subscription (owned by conn 0's backend)."""
    log: list[str] = []
    for step in ops:
        c, op, args = step[0], step[1], step[2:]
        s = conns[c]
        if op == "hset":
            log.append(repr(s.hset(*args)))
        elif op == "hget":
            log.append(repr(s.hget(*args)))
        elif op == "hgetall":
            log.append(repr(sorted(s.hgetall(*args).items())))
        elif op == "hmget":
            log.append(repr(s.hmget(*args)))
        elif op == "hexists":
            log.append(repr(s.hexists(*args)))
        elif op == "hdel":
            log.append(repr(s.hdel(args[0], *args[1])))
        elif op == "delete":
            log.append(repr(s.delete(*args)))
        elif op == "setnx_field":
            log.append(repr(s.setnx_field(*args)))
        elif op == "hincrby":
            log.append(repr(s.hincrby(*args)))
        elif op == "keys":
            log.append(repr(sorted(s.keys())))
        elif op == "publish":
            log.append(repr(s.publish(*args)))
        elif op == "drain":
            log.append(repr(_drain(subs[args[0]])))
        elif op == "hset_many":
            log.append(repr(s.hset_many(*args)))
        elif op == "hget_many":
            log.append(repr(s.hget_many(*args)))
        elif op == "hgetall_many":
            log.append(
                repr([sorted(h.items()) for h in s.hgetall_many(args[0])])
            )
        elif op == "setnx_fields":
            log.append(repr(s.setnx_fields(*args)))
        elif op == "hincrby_many":
            log.append(repr(s.hincrby_many(*args)))
        elif op == "flush":
            log.append(repr(s.flush()))
        else:  # pragma: no cover - generator/runner drift guard
            raise AssertionError(f"unknown op {op}")
    return log


def _memory_log(ops) -> list[str]:
    store = MemoryStore()
    subs = {ch: store.subscribe(ch) for ch in CHANNELS}
    try:
        return _run_program([store] * N_CONNS, subs, ops)
    finally:
        for sub in subs.values():
            sub.close()
        store.close()


@pytest.fixture(params=["python", "native"])
def server_handle(request):
    if request.param == "python":
        handle = start_store_thread()
    else:
        from tpu_faas.store.native import (
            NativeStoreUnavailable,
            start_native_store,
        )

        try:
            handle = start_native_store()
        except NativeStoreUnavailable as exc:
            pytest.skip(f"native store unavailable: {exc}")
    yield handle
    handle.stop()


def test_differential_fuzz_server_matches_memory(server_handle):
    """The seeded program's reply log over interleaved real connections
    must match MemoryStore's byte for byte (decoded form)."""
    ops = _gen_ops(SEED, N_OPS)
    golden = _memory_log(ops)
    conns = [make_store(server_handle.url) for _ in range(N_CONNS)]
    subs = {ch: conns[0].subscribe(ch) for ch in CHANNELS}
    try:
        got = _run_program(conns, subs, ops)
    finally:
        for sub in subs.values():
            sub.close()
        for conn in conns:
            conn.close()
    assert len(got) == len(golden)
    for i, (a, b) in enumerate(zip(golden, got)):
        assert a == b, (
            f"reply divergence at op {i} ({ops[i][1]}): memory={a!r} "
            f"server={b!r}"
        )


def test_fuzz_program_is_deterministic():
    """The program generator is a pure function of its seed — the whole
    differential argument rests on every backend replaying ONE sequence."""
    assert _gen_ops(SEED, N_OPS) == _gen_ops(SEED, N_OPS)
    assert _gen_ops(SEED + 1, N_OPS) != _gen_ops(SEED, N_OPS)
