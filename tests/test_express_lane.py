"""Express result lane: inline result announces end to end, event-driven
intake, multiplexed + streaming waits, and the safety-poll fallback.

Covers the four planes the lane spans:

- **store**: the ``!r1:`` announce codec (oversized/NUL fallback), the
  finish paths carrying ``inline_max`` (memory, RESP pipelined, sharded
  routing), and the subscription readability fds behind event-driven
  serve loops;
- **gateway**: parked long-polls served from the inline forward with the
  delivery-source counter proving it, the wait=0 immediate-reply contract
  untouched, ``POST /results/wait`` and ``GET /events`` contracts
  (early-terminal tasks, unknown ids, oversized fallback);
- **SDK**: ``wait_many`` (sync + aio) and the pacing fix (a server-parked
  round must not be followed by a client-side sleep);
- **chaos**: the announce bus dropping every inline forward mid-burst
  under the race monitor — every parked wait still resolves via the
  safety poll, zero admitted-task loss, zero protocol violations.
"""

from __future__ import annotations

import asyncio
import select
import threading
import time

import pytest
import requests

from tpu_faas.client import FaaSClient
from tpu_faas.client.aio import AsyncFaaSClient
from tpu_faas.core.serialize import serialize
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store.base import (
    RESULT_INLINE_MAX_BYTES,
    RESULT_INLINE_PREFIX,
    RESULTS_CHANNEL,
    decode_result_announce,
    encode_result_announce,
)
from tpu_faas.store.memory import MemoryStore
from tpu_faas.store.racecheck import RaceCheckStore, RaceMonitor
from tpu_faas.workloads import arithmetic


# -- announce codec ----------------------------------------------------------


def test_result_announce_codec_roundtrip():
    payload = encode_result_announce("t1", "COMPLETED", "abc", 4096)
    assert payload.startswith(RESULT_INLINE_PREFIX)
    assert decode_result_announce(payload) == ("t1", "COMPLETED", "abc")


def test_result_announce_oversized_falls_back_to_id():
    big = "x" * (RESULT_INLINE_MAX_BYTES + 1)
    assert encode_result_announce("t1", "COMPLETED", big, RESULT_INLINE_MAX_BYTES) == "t1"
    # inline disabled (the default everywhere): always id-only
    assert encode_result_announce("t1", "COMPLETED", "small") == "t1"


def test_result_announce_nul_collision_falls_back():
    # a result containing the frame separator must not produce a frame
    # that decodes to the wrong payload — fall back to id-only instead
    assert encode_result_announce("t1", "COMPLETED", "a\x00b", 4096) == "t1"


def test_result_announce_malformed_frames_degrade_to_opaque_id():
    # classic form: passthrough
    assert decode_result_announce("plain-id") == ("plain-id", None, None)
    # truncated inline frame: whole payload treated as an opaque id (the
    # consumer's record probe then finds nothing and skips, like any
    # garbage announce)
    bad = RESULT_INLINE_PREFIX + "only-id-no-seps"
    assert decode_result_announce(bad) == (bad, None, None)


# -- store layer -------------------------------------------------------------


def test_memory_finish_inline_announce_and_fileno_wake():
    s = MemoryStore()
    sub = s.subscribe(RESULTS_CHANNEL)
    fd = sub.fileno()
    assert fd is not None and sub.pollable_fds() == [fd]
    s.create_task("t1", "F", "P")
    s.finish_task("t1", "COMPLETED", "RES", inline_max=4096)
    ready, _, _ = select.select([fd], [], [], 2.0)
    assert ready, "publish did not signal the subscription self-pipe"
    assert decode_result_announce(sub.get_message()) == (
        "t1", "COMPLETED", "RES",
    )
    # drained: fd no longer readable, queue empty
    assert sub.get_message() is None
    ready, _, _ = select.select([fd], [], [], 0)
    assert not ready
    # default (inline off): the classic bare-id payload
    s.create_task("t2", "F", "P")
    s.finish_task("t2", "COMPLETED", "RES")
    assert sub.get_message(timeout=1.0) == "t2"
    sub.close()


def test_resp_finish_many_inline_pipelined_and_fileno():
    from tpu_faas.store.launch import make_store, start_store_thread

    handle = start_store_thread()
    try:
        s = make_store(handle.url)
        sub = s.subscribe(RESULTS_CHANNEL)
        assert sub.fileno() is not None
        for tid in ("a", "b", "c"):
            s.create_task(tid, "F", "P")
        rt0 = s.n_round_trips
        s.finish_task_many(
            [
                ("a", "COMPLETED", "RA", False),
                ("b", "FAILED", "RB", False),
                # oversized: id-only announce, record still authoritative
                ("c", "COMPLETED", "x" * 5000, False),
            ],
            inline_max=4096,
        )
        # the batched write + inline announces stay ONE pipelined round
        assert s.n_round_trips - rt0 == 1
        got = {}
        deadline = time.monotonic() + 5
        while len(got) < 3 and time.monotonic() < deadline:
            msg = sub.get_message(timeout=0.5)
            if msg is not None:
                tid, status, result = decode_result_announce(msg)
                got[tid] = (status, result)
        assert got["a"] == ("COMPLETED", "RA")
        assert got["b"] == ("FAILED", "RB")
        assert got["c"] == (None, None)  # oversized fell back to id-only
        # the store write is the authority either way
        assert s.get_result("c") == ("COMPLETED", "x" * 5000)
        sub.close()
        s.close()
    finally:
        handle.stop()


def test_inline_announce_replicates_verbatim_to_replica_subscribers():
    """Replication passthrough: a replicated PUBLISH forwards the payload
    verbatim, so inline result frames reach subscribers attached to the
    REPLICA's bus intact — a promoted replica's gateways keep getting the
    express forwards without re-negotiating anything."""
    from tpu_faas.store.client import RespStore
    from tpu_faas.store.launch import start_store_thread

    p = start_store_thread()
    r = None
    try:
        pc = RespStore(port=p.port)
        r = start_store_thread(replica_of=("127.0.0.1", p.port))
        rc = RespStore(port=r.port)
        deadline = time.monotonic() + 10
        while (
            rc.info().get("role") != "replica"
            and time.monotonic() < deadline
        ):
            time.sleep(0.1)
        sub = rc.subscribe(RESULTS_CHANNEL)
        pc.create_task("t-repl", "F", "P")
        pc.finish_task("t-repl", "COMPLETED", "RREPL", inline_max=4096)
        msg = None
        deadline = time.monotonic() + 10
        while msg is None and time.monotonic() < deadline:
            msg = sub.get_message(timeout=0.5)
        assert msg is not None, "replica subscriber never saw the announce"
        assert decode_result_announce(msg) == (
            "t-repl", "COMPLETED", "RREPL",
        )
        sub.close()
        pc.close()
        rc.close()
    finally:
        if r is not None:
            r.stop()
        p.stop()


def test_sharded_inline_announce_routes_by_embedded_task_id():
    from tpu_faas.store.launch import make_store

    s = make_store("memory://fresh;fresh")
    sub = s.subscribe(RESULTS_CHANNEL)  # fan over both shards
    s.create_task("t-route", "F", "P")
    s.finish_task("t-route", "COMPLETED", "R", inline_max=4096)
    msg = None
    deadline = time.monotonic() + 2
    while msg is None and time.monotonic() < deadline:
        msg = sub.get_message(timeout=0.2)
    assert msg is not None
    assert decode_result_announce(msg) == ("t-route", "COMPLETED", "R")
    # fan subscription exposes one pollable fd per shard once asked
    assert len(sub.pollable_fds()) == 2
    sub.close()
    s.close()


def test_racecheck_passthrough_observes_inline_finish():
    monitor = RaceMonitor()
    s = RaceCheckStore(MemoryStore(), monitor, actor="test")
    sub = s.subscribe(RESULTS_CHANNEL)
    s.create_task("t1", "F", "P")
    s.set_status("t1", "RUNNING")
    s.finish_task("t1", "COMPLETED", "R", inline_max=4096)
    assert decode_result_announce(sub.get_message(timeout=1.0)) == (
        "t1", "COMPLETED", "R",
    )
    assert monitor.errors == []
    sub.close()


# -- gateway contract --------------------------------------------------------


@pytest.fixture()
def gw():
    store = MemoryStore()
    handle = start_gateway_thread(store)
    yield handle, store
    handle.stop()


def _submit(handle, store) -> str:
    fid = requests.post(
        f"{handle.url}/register_function",
        json={"name": "arithmetic", "payload": serialize(arithmetic)},
    ).json()["function_id"]
    return requests.post(
        f"{handle.url}/execute_function",
        json={"function_id": fid, "payload": serialize(((1,), {}))},
    ).json()["task_id"]


def _served_counts(handle) -> dict[str, int]:
    out = {"inline": 0, "store": 0}
    for line in requests.get(f"{handle.url}/metrics").text.splitlines():
        if line.startswith("tpu_faas_gateway_result_served_total{"):
            for src in out:
                if f'source="{src}"' in line:
                    out[src] = int(float(line.rsplit(" ", 1)[1]))
    return out


def test_long_poll_served_from_inline_forward(gw):
    handle, store = gw
    tid = _submit(handle, store)
    out: dict = {}

    def poll():
        out["body"] = requests.get(
            f"{handle.url}/result/{tid}", params={"wait": 10}, timeout=30
        ).json()

    t = threading.Thread(target=poll)
    t.start()
    time.sleep(0.4)  # parks (waiter armed) before the result lands
    store.finish_task(tid, "COMPLETED", "RES", inline_max=4096)
    t.join(timeout=10)
    assert out["body"]["status"] == "COMPLETED"
    assert out["body"]["result"] == "RES"
    counts = _served_counts(handle)
    assert counts["inline"] == 1 and counts["store"] == 0, counts


def test_early_terminal_and_oversized_serve_from_store(gw):
    handle, store = gw
    # early-terminal: the record is terminal before the wait request
    # arrives — the first store read answers (no announce involved)
    tid = _submit(handle, store)
    store.finish_task(tid, "COMPLETED", "EARLY", inline_max=4096)
    body = requests.get(
        f"{handle.url}/result/{tid}", params={"wait": 5}, timeout=30
    ).json()
    assert body["result"] == "EARLY"
    assert _served_counts(handle)["store"] == 1

    # oversized result: the announce fell back to id-only, so the woken
    # poll re-reads the store — correct result, source=store
    tid2 = _submit(handle, store)
    big = "y" * 5000
    out: dict = {}

    def poll():
        out["body"] = requests.get(
            f"{handle.url}/result/{tid2}", params={"wait": 10}, timeout=30
        ).json()

    t = threading.Thread(target=poll)
    t.start()
    time.sleep(0.4)
    store.finish_task(tid2, "COMPLETED", big, inline_max=4096)
    t.join(timeout=10)
    assert out["body"]["result"] == big
    counts = _served_counts(handle)
    assert counts["inline"] == 0 and counts["store"] == 2, counts


def test_wait0_immediate_reply_contract_unchanged(gw):
    handle, store = gw
    tid = _submit(handle, store)
    t0 = time.monotonic()
    body = requests.get(f"{handle.url}/result/{tid}", timeout=10).json()
    assert body["status"] == "QUEUED" and time.monotonic() - t0 < 5.0
    # unknown id still 404s
    r = requests.get(f"{handle.url}/result/nope", timeout=10)
    assert r.status_code == 404


def test_results_wait_contract(gw):
    handle, store = gw
    done_id = _submit(handle, store)
    live_id = _submit(handle, store)
    store.finish_task(done_id, "COMPLETED", "D", inline_max=4096)

    # early-terminal answered immediately; live + unknown ids reported
    r = requests.post(
        f"{handle.url}/results/wait",
        json={"task_ids": [done_id, live_id, "ghost"], "wait": 5},
        timeout=30,
    ).json()
    assert r["results"][done_id] == {"status": "COMPLETED", "result": "D"}
    assert r["pending"] == [live_id]
    assert r["unknown"] == ["ghost"]

    # a parked multi-wait wakes on the inline forward of ANY watched id
    out: dict = {}

    def wait():
        out["r"] = requests.post(
            f"{handle.url}/results/wait",
            json={"task_ids": [live_id], "wait": 10},
            timeout=30,
        ).json()

    t = threading.Thread(target=wait)
    t.start()
    time.sleep(0.4)
    t0 = time.monotonic()
    store.finish_task(live_id, "COMPLETED", "L", inline_max=4096)
    t.join(timeout=10)
    assert time.monotonic() - t0 < 1.5  # woken, not safety-polled
    assert out["r"]["results"][live_id]["result"] == "L"

    # duplicate ids collapse; validation errors are 400s
    assert requests.post(
        f"{handle.url}/results/wait", json={"task_ids": []}, timeout=10
    ).status_code == 400
    assert requests.post(
        f"{handle.url}/results/wait",
        json={"task_ids": [done_id], "wait": -1},
        timeout=10,
    ).status_code == 400
    assert requests.post(
        f"{handle.url}/results/wait", json={"wrong": 1}, timeout=10
    ).status_code == 400


def test_results_wait_unknown_then_delivered_not_double_reported(gw):
    """Review regression: an id the probe found no record for, whose
    create + inline-forwarded result land while the wait is parked, must
    come back in ``results`` and NOT in ``unknown`` — a client treating
    unknown as 'give up' would discard a completed task."""
    handle, store = gw
    fid = requests.post(
        f"{handle.url}/register_function",
        json={"name": "arithmetic", "payload": serialize(arithmetic)},
    ).json()["function_id"]
    late_id = "late-task-id"
    out: dict = {}

    def wait():
        out["r"] = requests.post(
            f"{handle.url}/results/wait",
            json={"task_ids": [late_id], "wait": 10},
            timeout=30,
        ).json()

    t = threading.Thread(target=wait)
    t.start()
    time.sleep(0.4)  # parked; first probe already marked the id unknown
    store.create_task(late_id, serialize(arithmetic), serialize(((1,), {})))
    store.finish_task(late_id, "COMPLETED", "LATE", inline_max=4096)
    t.join(timeout=15)
    r = out["r"]
    assert r["results"].get(late_id, {}).get("result") == "LATE", r
    assert late_id not in r["unknown"], r
    assert late_id not in r["pending"], r


def test_events_sse_stream_contract(gw):
    handle, store = gw
    early = _submit(handle, store)
    late = _submit(handle, store)
    store.finish_task(early, "COMPLETED", "E", inline_max=4096)

    def finish_late():
        time.sleep(0.5)
        store.finish_task(late, "COMPLETED", "L", inline_max=4096)

    threading.Thread(target=finish_late).start()
    with requests.get(
        f"{handle.url}/events",
        params={"task_ids": f"{early},{late},ghost", "wait": 10},
        stream=True,
        timeout=30,
    ) as resp:
        assert resp.status_code == 200
        assert resp.headers["Content-Type"].startswith("text/event-stream")
        body = b"".join(resp.iter_content(None)).decode()
    # one result frame per terminal task, closed by done with the unknowns
    assert body.count("event: result") == 2
    assert '"result": "E"' in body and '"result": "L"' in body
    assert "event: done" in body
    assert '"ghost"' in body.split("event: done")[1]
    # validation: no ids = 400 (before any stream starts)
    assert requests.get(f"{handle.url}/events", timeout=10).status_code == 400


# -- SDK ---------------------------------------------------------------------


def test_sdk_wait_many_sync(gw):
    handle, store = gw
    client = FaaSClient(handle.url)
    a = _submit(handle, store)
    b = _submit(handle, store)
    store.finish_task(a, "COMPLETED", serialize(1), inline_max=4096)
    results, pending, unknown = client.wait_many([a, b, "ghost"], wait=2.0)
    assert a in results and results[a][0] == "COMPLETED"
    assert pending == [b] and unknown == ["ghost"]


def test_sdk_wait_many_async(gw):
    handle, store = gw
    a = _submit(handle, store)
    store.finish_task(a, "COMPLETED", serialize(2), inline_max=4096)

    async def go():
        async with AsyncFaaSClient(handle.url) as client:
            return await client.wait_many([a], wait=2.0)

    results, pending, unknown = asyncio.run(go())
    assert results[a][0] == "COMPLETED" and not pending and not unknown


def test_result_skips_pacing_sleep_when_server_parked(gw, monkeypatch):
    """The satellite fix: Handle.result() used to sleep poll_interval
    between long-poll rounds even when the server parked the request —
    with the server parking, any client-side sleep is a pure latency
    floor. Proven by making the pacing sleep explode."""
    handle, store = gw
    import types

    import tpu_faas.client.sdk as sdk_mod

    def boom(_s):
        raise AssertionError("client-side pacing sleep on a parked round")

    # scope the patch to the SDK module's view of ``time`` (patching the
    # real time module would detonate every other thread in the process)
    monkeypatch.setattr(
        sdk_mod,
        "time",
        types.SimpleNamespace(
            monotonic=time.monotonic, time=time.time, sleep=boom
        ),
    )
    client = FaaSClient(handle.url)
    tid = _submit(handle, store)

    def finish():
        time.sleep(0.5)
        store.finish_task(tid, "COMPLETED", serialize(7), inline_max=4096)

    threading.Thread(target=finish).start()
    from tpu_faas.client.sdk import TaskHandle

    assert TaskHandle(client, tid).result(timeout=30.0) == 7


# -- chaos: announce loss mid-burst ------------------------------------------


class _LossyResultsStore:
    """Wraps a store, DROPPING every RESULTS_CHANNEL publish — the
    fire-and-forget bus losing the express lane's inline forwards. The
    terminal record writes go through untouched (durability unchanged)."""

    def __init__(self, inner):
        self._inner = inner
        self.dropped = 0

    def publish(self, channel, payload):
        if channel == RESULTS_CHANNEL:
            self.dropped += 1
            return
        self._inner.publish(channel, payload)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def test_lossy_inline_forward_resolves_via_safety_poll():
    """Chaos leg: the announce bus drops/loses EVERY inline forward
    mid-burst under the race monitor. Every parked wait must still
    resolve via the gateway's safety poll (armed waiter => poll starts at
    _WAIT_POLL_MAX_S, the announce-loss insurance), with zero
    admitted-task loss and zero protocol violations."""
    monitor = RaceMonitor()
    mem = MemoryStore()
    gateway_store = RaceCheckStore(mem, monitor, actor="gateway")
    # the "dispatcher" writes through the SAME backing store, monitored,
    # with its results channel severed BELOW the monitor (the monitored
    # finish path calls self.publish, so the loss must sit underneath)
    lossy = _LossyResultsStore(mem)
    finisher_store = RaceCheckStore(lossy, monitor, actor="dispatcher")
    handle = start_gateway_thread(gateway_store)
    try:
        fid = requests.post(
            f"{handle.url}/register_function",
            json={"name": "arithmetic", "payload": serialize(arithmetic)},
        ).json()["function_id"]
        tids = [
            requests.post(
                f"{handle.url}/execute_function",
                json={"function_id": fid, "payload": serialize(((i,), {}))},
            ).json()["task_id"]
            for i in range(6)
        ]
        results: dict[str, dict] = {}
        errors: list = []

        def wait(tid):
            try:
                results[tid] = requests.get(
                    f"{handle.url}/result/{tid}",
                    params={"wait": 20},
                    timeout=40,
                ).json()
            except Exception as exc:  # pragma: no cover - diagnostic
                errors.append(exc)

        threads = [threading.Thread(target=wait, args=(t,)) for t in tids]
        for t in threads:
            t.start()
        time.sleep(0.5)  # all parked, waiters armed
        # mid-burst: the dispatcher finishes every task, inline announces
        # requested — and every single one is LOST on the bus
        for i, tid in enumerate(tids):
            finisher_store.set_status(tid, "RUNNING")
            finisher_store.finish_task(
                tid, "COMPLETED", f"R{i}", inline_max=4096
            )
        for t in threads:
            t.join(timeout=40)
        assert not errors, errors
        assert lossy.dropped == len(tids)  # the chaos actually hit
        # zero admitted-task loss: every parked wait resolved with the
        # task's real terminal result, via the safety poll
        assert set(results) == set(tids)
        for i, tid in enumerate(tids):
            assert results[tid]["status"] == "COMPLETED"
            assert results[tid]["result"] == f"R{i}"
        counts = _served_counts(handle)
        assert counts["inline"] == 0 and counts["store"] == len(tids)
        assert monitor.errors == [], "\n".join(
            str(v) for v in monitor.errors
        )
    finally:
        handle.stop()


# -- tpu-push express e2e ----------------------------------------------------


def test_tpu_push_express_e2e_inline_delivery():
    """The whole lane against a real stack: RESP store server, gateway,
    tpu-push --express, subprocess push worker. Results must be served
    from the inline forward and the dispatcher must report express mode;
    the announce_wait span proves intake ran."""
    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
    from tpu_faas.store.launch import make_store, start_store_thread
    from tests.test_workers_e2e import _spawn_worker
    from tpu_faas.workloads import no_op

    store_handle = start_store_thread()
    gw_handle = start_gateway_thread(
        make_store(store_handle.url), trace=True
    )
    disp = TpuPushDispatcher(
        ip="127.0.0.1",
        port=0,
        store=make_store(store_handle.url),
        max_workers=16,
        max_pending=128,
        max_slots=2,
        tick_period=0.05,
        express=True,
    )
    assert disp.inline_result_max == RESULT_INLINE_MAX_BYTES
    t = threading.Thread(target=disp.start, daemon=True)
    t.start()
    worker = _spawn_worker(
        "push_worker", 2, f"tcp://127.0.0.1:{disp.port}",
        "--hb", "--hb-period", "0.5",
    )
    try:
        time.sleep(1.5)
        client = FaaSClient(gw_handle.url, trace=True)
        fid = client.register(no_op)
        for _ in range(5):
            h = client.submit(fid)
            assert h.result(timeout=60.0) == "DONE"
        counts = _served_counts(gw_handle)
        assert counts["inline"] >= 4, counts  # ~all express-served
        # the tick(50 ms)-independent proof: with event-driven intake and
        # push delivery, a no-op round trip beats one tick period
        t0 = time.perf_counter()
        h = client.submit(fid)
        h.result(timeout=60.0)
        assert time.perf_counter() - t0 < 10 * 0.05  # loaded-box headroom
    finally:
        if worker.poll() is None:
            worker.kill()
            worker.wait()
        disp.stop()
        t.join(timeout=10)
        gw_handle.stop()
        store_handle.stop()
