"""Auction kernel: invariants + optimality vs the scipy Hungarian oracle."""

import numpy as np
import pytest

from tpu_faas.sched.auction import auction_placement
from tpu_faas.sched.oracle import optimal_assignment
from tpu_faas.sched.problem import PlacementProblem, check_assignment


def _run(sizes, speeds, free, live, max_slots=4, eps=1e-4):
    p = PlacementProblem.build(sizes, speeds, free, live, T=len(sizes) and None)
    res = auction_placement(
        p.task_size, p.task_valid, p.worker_speed, p.worker_free,
        p.worker_live, max_slots=max_slots, eps=eps,
    )
    return p, np.asarray(res.assignment), int(res.n_rounds)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_auction_invariants_random(seed):
    rng = np.random.default_rng(seed)
    sizes = rng.uniform(0.5, 5.0, 60).astype(np.float32)
    speeds = rng.uniform(0.5, 4.0, 16).astype(np.float32)
    free = rng.integers(0, 5, 16).astype(np.int32)
    live = rng.random(16) > 0.2
    p, a, rounds = _run(sizes, speeds, free, live)
    check_assignment(
        a, np.asarray(p.task_valid), np.asarray(p.worker_free),
        np.asarray(p.worker_live),
    )
    cap = int(np.minimum(free, 4)[live].sum())
    assert (a >= 0).sum() == min(len(sizes), cap)
    assert rounds > 0


def test_auction_matches_hungarian_total_cost():
    """Near-optimality: total cost within n*eps of the exact assignment."""
    rng = np.random.default_rng(7)
    n_tasks, n_workers, max_slots = 40, 12, 4
    sizes = rng.uniform(0.5, 8.0, n_tasks).astype(np.float32)
    speeds = rng.uniform(0.5, 4.0, n_workers).astype(np.float32)
    free = np.full(n_workers, max_slots, dtype=np.int32)
    live = np.ones(n_workers, dtype=bool)
    eps = 1e-4

    _, a, _ = _run(sizes, speeds, free, live, max_slots=max_slots, eps=eps)
    placed = a[: n_tasks] >= 0
    assert placed.all()
    cost_auction = float(np.sum(sizes[placed] / speeds[a[:n_tasks][placed]]))

    _, cost_opt = optimal_assignment(sizes, speeds, free, live, max_slots)
    assert cost_auction <= cost_opt + n_tasks * eps * 10 + 1e-3


def test_auction_single_best_worker():
    # one fast worker with capacity for everything -> all tasks land there
    _, a, _ = _run([1.0, 2.0, 3.0], [10.0, 0.1], [4, 4], [True, True],
                   max_slots=4)
    assert (a[:3] == 0).all()


def test_auction_excess_tasks_admitted_by_arrival():
    # 2 slots, 4 tasks: the two earliest-arrival tasks get placed
    _, a, _ = _run([5.0, 4.0, 3.0, 2.0], [1.0], [2], [True], max_slots=2)
    assert (a[:2] >= 0).all()
    assert (a[2:4] == -1).all()


def test_auction_no_capacity():
    _, a, _ = _run([1.0, 1.0], [1.0, 1.0], [0, 0], [True, True])
    assert (a == -1).all()
